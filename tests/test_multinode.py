"""Two-host end-to-end test: master + 2 agents + jax.distributed workers.

The full distributed stack on one machine (SURVEY.md §4's
multi-node-without-a-cluster tier): a standalone master process, two
launcher/agent processes that rendezvous through it, and two worker
processes forming a real 2-process jax.distributed cluster over CPU.
"""

import os
import re
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(run_id, extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",  # workers: 1 local CPU device each
            "DLROVER_TPU_RUN_ID": run_id,
            "DLROVER_TPU_HOST_ADDR": "localhost",
        }
    )
    if extra:
        env.update(extra)
    return env


def test_two_node_elastic_training(tmp_path):
    run_id = f"mn{os.getpid()}"
    master = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--port",
            "0",
            "--num-workers",
            "2",
        ],
        cwd=REPO,
        env=_env(run_id),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    addr = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = master.stdout.readline()
        if not line:
            time.sleep(0.1)
            continue
        m = re.match(r"DLROVER_TPU_MASTER_ADDR=(.+)", line.strip())
        if m:
            addr = m.group(1)
            break
    assert addr, "master did not print its address"

    ckpt_dir = str(tmp_path / "ckpt")
    agents = []
    for node_id in range(2):
        agents.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "dlrover_tpu.agent.launcher",
                    "--nnodes",
                    "2",
                    "--node-id",
                    str(node_id),
                    "--nproc",
                    "1",
                    "--master-addr",
                    addr,
                    "--",
                    sys.executable,
                    "examples/train_gpt_elastic.py",
                    "--steps",
                    "4",
                    "--batch",
                    "4",
                    "--seq",
                    "32",
                    "--ckpt-dir",
                    ckpt_dir,
                    "--ckpt-every",
                    "2",
                ],
                cwd=REPO,
                env=_env(
                    f"{run_id}_n{node_id}",
                    {"DLROVER_TPU_COORDINATOR_PORT": "0"},
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outs = []
    try:
        for agent in agents:
            out, _ = agent.communicate(timeout=420)
            outs.append(out)
        for i, agent in enumerate(agents):
            assert agent.returncode == 0, f"agent {i} failed:\n{outs[i][-4000:]}"
        assert any("done at step 4" in o for o in outs), outs[0][-2000:]
        # both workers joined one jax.distributed cluster of 2 processes
        assert any("2 global devices" in o for o in outs), outs[0][-2000:]
    finally:
        for agent in agents:
            if agent.poll() is None:
                agent.kill()
        master.kill()
        master.wait()
