"""Two-host end-to-end test: master + 2 agents + jax.distributed workers.

The full distributed stack on one machine (SURVEY.md §4's
multi-node-without-a-cluster tier): a standalone master process, two
launcher/agent processes that rendezvous through it, and two worker
processes forming a real 2-process jax.distributed cluster over CPU.
"""

import os
import re
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(run_id, extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",  # workers: 1 local CPU device each
            "DLROVER_TPU_RUN_ID": run_id,
            "DLROVER_TPU_HOST_ADDR": "localhost",
        }
    )
    if extra:
        env.update(extra)
    return env


def _drain(proc):
    """Pump a process's merged stdout into a queue from a daemon thread:
    keeps the ~64KB pipe from backpressure-blocking the producer while
    the test waits on OTHER processes, and lets readers enforce real
    deadlines (a blocking readline would only re-check its deadline
    between lines)."""
    import queue as queue_mod
    import threading

    q = queue_mod.Queue()

    def run():
        for line in proc.stdout:
            q.put(line)
        q.put(None)

    threading.Thread(target=run, daemon=True).start()
    return q


def _collect(q, lines, until, deadline, on_line=None):
    """Consume queued lines until ``until(line)`` or EOF/deadline.
    Returns the matching line or None."""
    import queue as queue_mod

    while time.time() < deadline:
        try:
            line = q.get(timeout=0.2)
        except queue_mod.Empty:
            continue
        if line is None:
            return None
        lines.append(line)
        if on_line:
            on_line(line)
        if until(line):
            return line
    return None


def test_world_shrink_resharded_recovery(tmp_path):
    """The composed elasticity path (SURVEY §7 hard part #1): 2-node
    training checkpoints to memory, both workers die, one node leaves
    permanently, the master re-seals at world=1, and the survivor
    restores the 2-host checkpoint onto the 1-process mesh (resharded
    read of both emergency-persisted host packs) and finishes. Recovery
    wall-clock (crash → resumed) is printed."""
    run_id = f"ws{os.getpid()}"
    master = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--port",
            "0",
            # min_nodes=1 lets the post-crash rendezvous seal a
            # 1-node world after the extra-nodes grace
            "--num-workers",
            "1",
            "--max-workers",
            "2",
        ],
        cwd=REPO,
        # shrink grace tuned down (default 30s): the post-crash re-seal
        # waits this long for the lost node to come back before going
        # ahead at world=1 — the dominant term in recovery wall-clock
        env=_env(
            run_id, {"DLROVER_TPU_CTX_RDZV_WAIT_EXTRA_NODES_S": "3"}
        ),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    survivor = casualty = None
    try:
        master_q = _drain(master)  # drained for the whole test
        master_lines = []
        addr_line = _collect(
            master_q,
            master_lines,
            until=lambda l: l.startswith("DLROVER_TPU_MASTER_ADDR="),
            deadline=time.time() + 60,
        )
        assert addr_line, "master did not print its address"
        addr = re.match(
            r"DLROVER_TPU_MASTER_ADDR=(.+)", addr_line.strip()
        ).group(1)

        ckpt_dir = str(tmp_path / "ckpt")

        def launch_agent(node_id, max_restarts):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "dlrover_tpu.agent.launcher",
                    "--nnodes",
                    "1:2",
                    "--node-id",
                    str(node_id),
                    "--nproc",
                    "1",
                    "--max-restarts",
                    str(max_restarts),
                    "--master-addr",
                    addr,
                    "--",
                    sys.executable,
                    "examples/train_gpt_elastic.py",
                    "--steps",
                    "6",
                    "--batch",
                    "4",
                    "--seq",
                    "32",
                    "--ckpt-dir",
                    ckpt_dir,
                    "--ckpt-every",
                    "2",
                    "--crash-at",
                    "3",
                ],
                cwd=REPO,
                env=_env(
                    f"{run_id}_n{node_id}",
                    {"DLROVER_TPU_COORDINATOR_PORT": "0"},
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )

        # node 1 has no restart budget: after the synchronized crash at
        # step 3 it leaves the job for good (the "lost host")
        survivor = launch_agent(0, max_restarts=2)
        casualty = launch_agent(1, max_restarts=0)
        sur_q, cas_q = _drain(survivor), _drain(casualty)
        sur_lines, cas_lines = [], []

        assert (
            _collect(
                cas_q,
                cas_lines,
                until=lambda l: "simulating crash at step 3" in l,
                deadline=time.time() + 300,
            )
            is not None
        ), "".join(cas_lines)[-2000:]
        t_crash = time.time()
        casualty.wait(timeout=120)
        assert casualty.returncode != 0

        stamps = {}

        def stamp(line):
            if "resumed from step" in line and "resumed" not in stamps:
                stamps["resumed"] = time.time()

        _collect(
            sur_q,
            sur_lines,
            until=lambda l: False,  # run to EOF or deadline
            deadline=time.time() + 360,
            on_line=stamp,
        )
        survivor.wait(timeout=60)
        sur_out = "".join(sur_lines)

        assert survivor.returncode == 0, sur_out[-4000:]
        # phase 1 ran as a real 2-process cluster
        assert "2 global devices" in sur_out, sur_out[-3000:]
        # the survivor crashed too, restarted, and resumed from the
        # emergency-persisted step-2 checkpoint on the SHRUNK world
        assert "simulating crash at step 3" in sur_out
        assert "resumed from step 2" in sur_out, sur_out[-3000:]
        assert "worker succeeded" in sur_out
        assert "resumed" in stamps
        print(
            f"\n[elastic-recovery] world 2→1 recovery wall-clock: "
            f"{stamps['resumed'] - t_crash:.1f}s (crash → resumed-from-ckpt)"
        )
    finally:
        for proc in (survivor, casualty):
            if proc is not None and proc.poll() is None:
                proc.kill()
        master.kill()
        master.wait()


def test_two_node_elastic_training(tmp_path):
    run_id = f"mn{os.getpid()}"
    master = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--port",
            "0",
            "--num-workers",
            "2",
        ],
        cwd=REPO,
        env=_env(run_id),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    addr = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = master.stdout.readline()
        if not line:
            time.sleep(0.1)
            continue
        m = re.match(r"DLROVER_TPU_MASTER_ADDR=(.+)", line.strip())
        if m:
            addr = m.group(1)
            break
    assert addr, "master did not print its address"

    ckpt_dir = str(tmp_path / "ckpt")
    agents = []
    for node_id in range(2):
        agents.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "dlrover_tpu.agent.launcher",
                    "--nnodes",
                    "2",
                    "--node-id",
                    str(node_id),
                    "--nproc",
                    "1",
                    "--master-addr",
                    addr,
                    "--",
                    sys.executable,
                    "examples/train_gpt_elastic.py",
                    "--steps",
                    "4",
                    "--batch",
                    "4",
                    "--seq",
                    "32",
                    "--ckpt-dir",
                    ckpt_dir,
                    "--ckpt-every",
                    "2",
                ],
                cwd=REPO,
                env=_env(
                    f"{run_id}_n{node_id}",
                    {"DLROVER_TPU_COORDINATOR_PORT": "0"},
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outs = []
    try:
        for agent in agents:
            out, _ = agent.communicate(timeout=420)
            outs.append(out)
        for i, agent in enumerate(agents):
            assert agent.returncode == 0, f"agent {i} failed:\n{outs[i][-4000:]}"
        assert any("done at step 4" in o for o in outs), outs[0][-2000:]
        # both workers joined one jax.distributed cluster of 2 processes
        assert any("2 global devices" in o for o in outs), outs[0][-2000:]
    finally:
        for agent in agents:
            if agent.poll() is None:
                agent.kill()
        master.kill()
        master.wait()
