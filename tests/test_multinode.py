"""Two-host end-to-end test: master + 2 agents + jax.distributed workers.

The full distributed stack on one machine (SURVEY.md §4's
multi-node-without-a-cluster tier): a standalone master process, two
launcher/agent processes that rendezvous through it, and two worker
processes forming a real 2-process jax.distributed cluster over CPU.
Process plumbing lives in elastic_harness.py (shared with the
slice-grain elasticity drill).
"""

import os
import time

import pytest

from elastic_harness import (
    collect as _collect,
    drain as _drain,
    drain_now as _drain_now,
    kill_tree as _kill_tree,
    launch_agent as _launch_agent,
    start_master as _start_master,
)

# multi-process elastic drills take minutes; excluded from the tier-1 budget
pytestmark = pytest.mark.slow

def test_world_shrink_resharded_recovery(tmp_path):
    """The composed elasticity path (SURVEY §7 hard part #1): 2-node
    training checkpoints to memory, both workers die, one node leaves
    permanently, the master re-seals at world=1, and the survivor
    restores the 2-host checkpoint onto the 1-process mesh (resharded
    read of both emergency-persisted host packs) and finishes. Recovery
    wall-clock (crash → resumed) is printed."""
    run_id = f"ws{os.getpid()}"
    # shrink grace tuned down (default 30s): the post-crash re-seal
    # waits this long for the lost node to come back before going
    # ahead at world=1 — the dominant term in recovery wall-clock
    # (min_nodes=1 lets it seal a 1-node world at all)
    master, master_q, master_lines, addr = _start_master(
        run_id,
        argv_extra=("--num-workers", "1", "--max-workers", "2"),
        env_extra={"DLROVER_TPU_CTX_RDZV_WAIT_EXTRA_NODES_S": "3"},
    )
    survivor = casualty = None
    try:
        train_args = (
            "--steps", "6", "--batch", "4", "--seq", "32",
            "--ckpt-dir", str(tmp_path / "ckpt"),
            "--ckpt-every", "2", "--crash-at", "3",
        )
        # node 1 has no restart budget: after the synchronized crash at
        # step 3 it leaves the job for good (the "lost host")
        survivor = _launch_agent(
            run_id, 0, addr, train_args, ("--max-restarts", "2")
        )
        casualty = _launch_agent(
            run_id, 1, addr, train_args, ("--max-restarts", "0")
        )
        sur_q, cas_q = _drain(survivor), _drain(casualty)
        sur_lines, cas_lines = [], []

        assert (
            _collect(
                cas_q,
                cas_lines,
                until=lambda l: "simulating crash at step 3" in l,
                deadline=time.time() + 300,
            )
            is not None
        ), "".join(cas_lines)[-2000:]
        t_crash = time.time()
        casualty.wait(timeout=120)
        assert casualty.returncode != 0

        stamps = {}

        def stamp(line):
            if "resumed from step" in line and "resumed" not in stamps:
                stamps["resumed"] = time.time()

        _collect(
            sur_q,
            sur_lines,
            until=lambda l: False,  # run to EOF or deadline
            deadline=time.time() + 360,
            on_line=stamp,
        )
        survivor.wait(timeout=60)
        sur_out = "".join(sur_lines)

        assert survivor.returncode == 0, sur_out[-4000:]
        # phase 1 ran as a real 2-process cluster
        assert "2 global devices" in sur_out, sur_out[-3000:]
        # the survivor crashed too, restarted, and resumed from the
        # emergency-persisted step-2 checkpoint on the SHRUNK world
        assert "simulating crash at step 3" in sur_out
        assert "resumed from step 2" in sur_out, sur_out[-3000:]
        assert "worker succeeded" in sur_out
        assert "resumed" in stamps
        print(
            f"\n[elastic-recovery] world 2→1 recovery wall-clock: "
            f"{stamps['resumed'] - t_crash:.1f}s (crash → resumed-from-ckpt)"
        )
    finally:
        for proc in (survivor, casualty):
            _kill_tree(proc)
        master.kill()
        master.wait()


def test_world_grow_joins_mid_run(tmp_path):
    """Scale-UP elasticity: a 1-node job is joined by a second host
    mid-run. The running agent notices the waiting node (membership
    poll), checkpoints, restarts its worker, and both re-seal a 2-node
    world that resumes from the checkpoint — the grow half of the
    composed elasticity path (the shrink half is the test above)."""
    run_id = f"wg{os.getpid()}"
    # grace must outlive the running agent's checkpoint+restart cycle:
    # with a too-small value the joiner seals a 1-node world alone and
    # the two agents ping-pong restarts
    master, master_q, master_lines, addr = _start_master(
        run_id,
        argv_extra=("--num-workers", "1", "--max-workers", "2"),
        env_extra={"DLROVER_TPU_CTX_RDZV_WAIT_EXTRA_NODES_S": "10"},
    )
    a0 = a1 = None
    try:
        # --steps 400 is pure runway: the test tears down after the
        # joint checkpoint; it must never finish before the joiner
        # arrives (node 1's process startup can take minutes under load)
        train_args = (
            "--steps", "400", "--batch", "4", "--seq", "32",
            "--ckpt-dir", str(tmp_path / "ckpt"), "--ckpt-every", "2",
        )
        a0 = _launch_agent(run_id, 0, addr, train_args)
        q0 = _drain(a0)
        lines0 = []
        # wait until node 0 is genuinely TRAINING alone (a few steps in)
        assert _collect(
            q0,
            lines0,
            until=lambda l: "step=4" in l,
            deadline=time.time() + 240,
        ), "".join(lines0)[-3000:]

        # second host joins mid-run
        a1 = _launch_agent(run_id, 1, addr, train_args)
        q1 = _drain(a1)
        lines1 = []
        # the composed path is proven once the restarted world RESUMES
        # and then commits a joint checkpoint ("(2 hosts)") — running
        # to completion is other tests' job and makes this one
        # timing-fragile under CI contention
        saw_resume = {}

        def watch(line):
            if "resumed from step" in line:
                saw_resume["yes"] = True

        joint_ckpt = _collect(
            q0,
            lines0,
            until=lambda l: "(2 hosts)" in l and "yes" in saw_resume,
            deadline=time.time() + 420,
            on_line=watch,
        )
        out0 = "".join(lines0)
        if joint_ckpt is None:
            _drain_now(q1, lines1)  # the joiner may hold the real error
            raise AssertionError(
                "no joint checkpoint after resume:\n--- node 0 ---\n"
                + out0[-3000:]
                + "\n--- node 1 ---\n"
                + "".join(lines1)[-2000:]
            )
        # the running agent restarted for the membership change...
        assert "membership changed" in out0, out0[-3000:]
        # ...and the re-sealed world is a real 2-process cluster that
        # resumed from the checkpoint instead of starting over
        assert "2 global devices" in out0, out0[-3000:]
        assert "resumed from step" in out0, out0[-3000:]
    finally:
        for proc in (a0, a1):
            _kill_tree(proc)
        master.kill()
        master.wait()


def test_launcher_network_check_gates_training(tmp_path):
    """--network-check end to end through the launcher CLI: the agent
    runs the paired MXU/collective pre-check through its own rendezvous,
    reports to the master, and only THEN spawns the worker (the
    dlrover-run network-check semantic)."""
    run_id = f"nc{os.getpid()}"
    master, _mq, _ml, addr = _start_master(
        run_id, argv_extra=("--num-workers", "1")
    )
    agent = None
    try:
        agent = _launch_agent(
            run_id,
            0,
            addr,
            (
                "--steps", "3", "--batch", "4", "--seq", "32",
                "--ckpt-dir", str(tmp_path / "ckpt"),
            ),
            agent_args=("--network-check",),
            nnodes="1",
        )
        q = _drain(agent)
        lines = []
        _collect(
            q, lines, until=lambda l: False, deadline=time.time() + 300
        )
        agent.wait(timeout=60)
        out = "".join(lines)
        assert agent.returncode == 0, out[-4000:]
        # the check ran before training and passed
        assert "node check" in out, out[-3000:]
        assert "done at step 3" in out, out[-3000:]
        assert out.index("node check") < out.index("done at step 3")
        assert "worker succeeded" in out
    finally:
        _kill_tree(agent)
        master.kill()
        master.wait()


def test_two_node_elastic_training(tmp_path):
    run_id = f"mn{os.getpid()}"
    master, _mq, _mlines, addr = _start_master(
        run_id, argv_extra=("--num-workers", "2")
    )
    train_args = (
        "--steps", "4", "--batch", "4", "--seq", "32",
        "--ckpt-dir", str(tmp_path / "ckpt"), "--ckpt-every", "2",
    )
    agents = [
        _launch_agent(run_id, node_id, addr, train_args, nnodes="2")
        for node_id in range(2)
    ]
    queues = [_drain(a) for a in agents]
    outs = []
    try:
        deadline = time.time() + 420
        for agent, q in zip(agents, queues):
            lines = []
            _collect(q, lines, until=lambda l: False, deadline=deadline)
            agent.wait(timeout=60)
            outs.append("".join(lines))
        for i, agent in enumerate(agents):
            assert agent.returncode == 0, f"agent {i} failed:\n{outs[i][-4000:]}"
        assert any("done at step 4" in o for o in outs), outs[0][-2000:]
        # both workers joined one jax.distributed cluster of 2 processes
        assert any("2 global devices" in o for o in outs), outs[0][-2000:]
    finally:
        for agent in agents:
            _kill_tree(agent)
        master.kill()
        master.wait()
