"""Marker lint: every ``pytest.mark.X`` in tests/ must be declared.

Tier-1 excludes ``-m 'not slow'`` work to stay under its time budget —
but a typo'd marker (``@pytest.mark.slw``) silently keeps an expensive
test IN tier-1, and an undeclared one only warns. This AST scan turns
both into a hard failure: the set of markers used across the test tree
must be a subset of pyproject's declared markers plus pytest builtins.
"""

import ast
import pathlib

_TESTS = pathlib.Path(__file__).parent
_PYPROJECT = _TESTS.parent / "pyproject.toml"

# markers pytest itself defines; always legal
_BUILTIN = {
    "parametrize",
    "skip",
    "skipif",
    "xfail",
    "usefixtures",
    "filterwarnings",
}


def declared_markers():
    try:
        import tomllib
    except ImportError:  # py<3.11
        import tomli as tomllib  # type: ignore[no-redef]
    with open(_PYPROJECT, "rb") as f:
        data = tomllib.load(f)
    lines = data["tool"]["pytest"]["ini_options"].get("markers", [])
    return {line.split(":", 1)[0].strip() for line in lines}


def used_markers():
    """(marker, file, lineno) for every pytest.mark.<name> attribute."""
    used = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            val = node.value
            if (
                isinstance(val, ast.Attribute)
                and val.attr == "mark"
                and isinstance(val.value, ast.Name)
                and val.value.id == "pytest"
            ):
                used.append((node.attr, path.name, node.lineno))
    return used


def test_all_markers_declared():
    legal = declared_markers() | _BUILTIN
    rogue = [
        f"{fn}:{ln}: pytest.mark.{m}"
        for m, fn, ln in used_markers()
        if m not in legal
    ]
    assert not rogue, (
        "undeclared pytest markers (declare in pyproject.toml "
        "[tool.pytest.ini_options] markers, or fix the typo):\n"
        + "\n".join(rogue)
    )


def test_slow_marker_still_declared():
    """Tier-1's ``-m 'not slow'`` filter depends on this declaration."""
    assert "slow" in declared_markers()


def _module_slow_marked(tree) -> bool:
    """True when the module sets a top-level ``pytestmark`` that
    includes ``pytest.mark.slow`` (whole file excluded from tier-1)."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                return True
    return False


def test_bench_imports_are_slow_or_local():
    """Module-level ``import bench`` is reserved for slow-marked files.

    ``bench`` is the benchmark ENTRY SCRIPT, not a library: importing
    it at module scope runs its argv/env setup and heavyweight imports
    during tier-1 COLLECTION, for every test in the file — even when
    the only consumer is one HLO-guard test. Files whose whole module
    is ``pytestmark = pytest.mark.slow`` may import it at top level
    (they never collect into tier-1's budget); everyone else imports
    it inside the test function that needs it.
    """
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        if _module_slow_marked(tree):
            continue
        for node in tree.body:  # module level only, by design
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            if any(n == "bench" or n.startswith("bench.") for n in names):
                rogue.append(f"{path.name}:{node.lineno}")
    assert not rogue, (
        "module-level bench import in non-slow test files (move the "
        "import inside the test, or mark the whole module slow):\n"
        + "\n".join(rogue)
    )


def _test_functions(tree):
    """Top-level (incl. class-nested) test functions with their decorator
    lists."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("test_"):
                out.append(node)
    return out


def _fn_slow_marked(fn) -> bool:
    for dec in fn.decorator_list:
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                return True
    return False


_MESH_AXES = ("dp", "fsdp", "tp", "pp", "ep", "sp")


def _multi_axis_mesh_devices(fn) -> int:
    """Largest statically-known device count among MULTI-AXIS
    ``MeshConfig(...)`` calls in a function; 0 when there is none.
    ``-1`` (fill the remaining devices) counts as reaching the suite's
    8 virtual devices."""
    best = 0
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "MeshConfig"
        ):
            continue
        sizes = [
            kw.value.value
            for kw in node.keywords
            if kw.arg in _MESH_AXES
            and isinstance(kw.value, ast.Constant)
            and isinstance(kw.value.value, int)
        ]
        explicit = [s for s in sizes if s > 1]
        fills = any(s == -1 for s in sizes)
        if len(explicit) + (1 if fills else 0) < 2:
            continue
        total = 1
        for s in explicit:
            total *= s
        if fills:
            total = max(total, 8)
        best = max(best, total)
    return best


def _compiles_train_step(fn) -> bool:
    return any(
        isinstance(node, ast.Name) and node.id == "TrainStepBuilder"
        for node in ast.walk(fn)
    )


def test_mesh_zoo_step_compiles_are_slow():
    """A test that builds a multi-axis mesh over all 8 virtual devices
    AND compiles a train step through it is a mesh-zoo matrix entry —
    each one costs multiple multi-device SPMD compiles (~10s each on
    this backend), and the update-sharding matrix keeps growing. Those
    tests must carry ``slow`` (per-function mark or module
    ``pytestmark``) so tier-1 stays inside its 870s budget. Cheap
    multi-axis uses — plan resolution, eval_shape, checkpoint layout
    math — stay fast; the lint keys on the mesh build AND the
    ``TrainStepBuilder`` reference together."""
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        if _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if _fn_slow_marked(fn):
                continue
            if _multi_axis_mesh_devices(fn) >= 8 and _compiles_train_step(fn):
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "multi-axis mesh (≥8 devices) train-step compiles must be "
        "marked slow (add @pytest.mark.slow or a module pytestmark):\n"
        + "\n".join(rogue)
    )


def test_process_spawning_fault_tests_are_slow():
    """Files importing ``elastic_harness`` at module level spawn real
    master/agent/worker PROCESSES — the fault-injection drills. Every
    test in such a file must carry ``slow`` (module ``pytestmark`` or a
    per-function mark): a process-spawning eviction/kill drill that
    slips into tier-1 blows its time budget and flakes under load.
    In-process injectors (elastic/faults.py used directly) stay fast
    and belong in tier-1.
    """
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        imports_harness = False
        for node in tree.body:  # module level only, by design
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            if any(
                n == "elastic_harness" or n.startswith("elastic_harness.")
                for n in names
            ):
                imports_harness = True
                break
        if not imports_harness or _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if not _fn_slow_marked(fn):
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "process-spawning fault-injection tests not marked slow (add "
        "@pytest.mark.slow, or a module-level pytestmark):\n"
        + "\n".join(rogue)
    )


def _imports_pallas_paged(tree) -> bool:
    """Module-level import of the paged-attention kernel module."""
    mod_name = "dlrover_tpu.ops.pallas_paged"
    for node in tree.body:  # module level only, by design
        if isinstance(node, ast.Import):
            if any(
                a.name == mod_name or a.name.startswith(mod_name + ".")
                for a in node.names
            ):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == mod_name or mod.startswith(mod_name + "."):
                return True
            if mod == "dlrover_tpu.ops" and any(
                a.name == "pallas_paged" for a in node.names
            ):
                return True
    return False


def test_pallas_paged_importers_are_interpret_units_or_slow():
    """Direct ``ops.pallas_paged`` consumers outside the interpret-mode
    kernel unit files (``test_pallas*``) are serving integration tests:
    they drive jitted decode loops over page pools, which belongs in
    the slow tier. The interpret-mode unit files stay in tier-1 — they
    are the cheap CPU-executable coverage of the kernel bodies."""
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        if path.name.startswith("test_pallas"):
            continue  # interpret-mode kernel unit files
        tree = ast.parse(path.read_text(), filename=str(path))
        if not _imports_pallas_paged(tree) or _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if not _fn_slow_marked(fn):
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "ops.pallas_paged importers outside interpret-mode unit files "
        "must be slow-marked (add @pytest.mark.slow or a module "
        "pytestmark):\n" + "\n".join(rogue)
    )


# ---------------------------------------------------------------------------
# tier-1 duration ledger
# ---------------------------------------------------------------------------
# Tests measured >= ~9s on the tier-1 backend whose property is already
# covered by a faster sibling were moved to the slow tier to keep the
# suite inside its 870s budget (measured: the pre-rebalance fast tier
# ran ~1077s). This ledger pins that decision: each entry must exist
# AND must not collect under ``-m 'not slow'``. Removing a mark without
# updating the ledger is a hard failure; deleting/renaming the test
# fails the existence check so the ledger can't rot silently.
_SLOW_LEDGER = [
    "test_bench_smoke.py::test_bench_single_tiny_emits_schema",
    "test_bench_smoke.py::test_bench_single_block_k_mode",
    "test_bench_smoke.py::test_bench_single_save_qkv_offload_recipe",
    "test_fused_block.py::test_blockwise_cadences_match_stepwise[5]",
    "test_fused_block.py::test_blockwise_cadences_match_stepwise[8]",
    "test_fused_block.py::test_blockwise_cadences_match_stepwise[13]",
    "test_fused_block.py::test_blockwise_cadences_match_stepwise[64]",
    "test_fused_block.py::test_blockwise_eval_cadence_and_final_partial_block",
    "test_fused_block.py::test_blockwise_data_exhaustion_runs_partial_block",
    "test_estimator.py::test_train_and_evaluate_exports_best",
    "test_estimator.py::test_estimator_resume_from_latest",
    "test_estimator.py::test_estimator_incremental_restore",
    "test_estimator.py::test_evaluator_role_watches_checkpoints",
    "test_estimator.py::test_estimator_executor_env_cluster_and_resume",
    "test_sentinels.py::test_sentinels_add_no_device_to_host_transfers",
    "test_watchdog.py::test_nan_drill_end_to_end",
    "test_trainer.py::test_elastic_remesh_resume",
    "test_trainer.py::test_prefetch_to_device_preserves_stream",
    "test_model.py::test_sharded_init_and_step",
    "test_moe.py::test_train_step_threads_jitter_rng",
    "test_moe.py::test_ragged_no_truncation_under_imbalance",
    "test_elastic.py::test_restart_hits_persistent_compile_cache",
    "test_rl.py::test_dpo_trainer_shifts_preference",
    "test_sparse_serving.py::test_server_crash_failover_without_migration",
    # serving migration drills: two replica servers (four jit compiles)
    # plus a mid-stream kill each — far past the tier-1 budget
    "test_serving_migration.py::test_migration_drill_zero_reprefill_bitwise",
    "test_serving_migration.py::"
    "test_faulted_migration_degrades_to_reprefill[torn]",
    "test_serving_migration.py::"
    "test_faulted_migration_degrades_to_reprefill[stall]",
    "test_serving_migration.py::test_wait_all_backoff_with_slow_straggler",
    # serving observability drills: replica pairs with tracing on and
    # an injected stall — same two-compiles-plus-kill cost profile
    "test_serving_observability.py::"
    "test_tracing_drill_merged_trace_has_rid_span_chain",
    "test_serving_observability.py::"
    "test_slo_breach_drill_capture_and_healthcheck_naming",
    # prefix-sharing migration drill: a replica pair with two slots
    # sharing refcounted pages, killed mid-decode — same cost profile
    "test_serving_prefix.py::test_migration_drill_with_shared_pages_in_flight",
    # prefix-sharing engine drills: each stands up one-or-two engines
    # (a jit compile apiece) and streams a donor to completion. The
    # hit-path property they share is pinned fast by
    # test_prefix_hit_fast_pin (one compile, bf16/paged/spec-off);
    # the full {mode} x {kernel} x {spec} parity matrix, byte-identity
    # under sharer eviction, COW isolation, and lookahead admission run
    # on the slow tier.
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[0-True-bf16]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[0-True-int8]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[0-False-bf16]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[0-False-int8]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[3-True-bf16]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[3-True-int8]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[3-False-bf16]",
    "test_serving_prefix.py::"
    "test_prefix_hit_stream_bitwise_equals_cold[3-False-int8]",
    "test_serving_prefix.py::test_int8_hit_equals_int8_cold_stream[True]",
    "test_serving_prefix.py::test_int8_hit_equals_int8_cold_stream[False]",
    "test_serving_prefix.py::test_sharer_eviction_never_perturbs_sharee",
    "test_serving_prefix.py::test_cow_tail_page_isolates_writes",
    "test_serving_prefix.py::"
    "test_hit_aware_lookahead_admits_past_blocked_cold_head",
    "test_serving_prefix.py::test_lookahead_zero_preserves_head_of_line",
    "test_serving_prefix.py::"
    "test_sharing_off_engine_reports_inert_prefix_stats",
    # second budget rebalance (PR 16): the fast tier had crept back to
    # ~1220s wall on the 1-cpu box (870s budget) as PRs 13-15 grew the
    # suite. Coarse e2e drills whose core properties keep a faster
    # tier-1 sibling (or a cheaper representative parametrization)
    # moved to the slow tier; every one still runs under -m slow.
    "test_observability.py::test_runtime_timer_samples_real_op_breakdown",
    "test_fused_ce.py::test_loss_fn_fused_matches_unfused",
    "test_fused_ce.py::test_fused_ce_under_tp_mesh_falls_back",
    "test_sentinels.py::test_replicated_sentinels_detect_injected_nan",
    "test_trainer.py::test_trainer_resumes_from_checkpoint",
    "test_trainer.py::test_trainer_drives_auto_accelerate_plan",
    "test_trainer.py::test_trainer_early_stopping_and_control_flags",
    "test_trainer.py::test_trainer_callbacks_fire_and_log_lr",
    "test_trainer.py::test_trainer_data_exhaustion_stops_cleanly",
    "test_pallas_norm.py::test_decoder_fused_norm_matches_unfused",
    "test_rl.py::test_model_engine_roles_and_update",
    "test_rl.py::test_prompt_lens_bound_the_bidirectional_prefix",
    "test_rl.py::test_prefix_lm_cached_matches_full",
    "test_rl.py::test_decode_step_logits_match_forward",
    "test_rl.py::test_cached_generation_matches_uncached_greedy",
    "test_rl.py::test_cached_rollout_speedup",
    "test_rl.py::test_rollout_reads_training_actor_buffers",
    "test_elastic.py::test_prewarm_produces_the_exact_step_executable",
    "test_model_families.py::test_window_forward_on_sequence_parallel_mesh",
    "test_model_families.py::test_glm_forward_on_sequence_parallel_mesh",
    "test_model_families.py::test_glm_sample_runs_uncached",
    "test_model_families.py::test_parallel_residual_forward_and_grads",
    "test_estimator.py::test_estimator_trains_checkpoints_and_prunes",
    "test_serving_spec.py::test_greedy_spec_on_bitwise_equal_greedy[False]",
    "test_serving_spec.py::test_int8_spec_on_equals_spec_off[True]",
    "test_serving_spec.py::test_int8_spec_on_equals_spec_off[False]",
    "test_serving_spec.py::test_oracle_draft_accepts_everything",
    "test_serving_spec.py::test_wrong_draft_rejects_everything_same_output",
    "test_serving_spec.py::test_rejected_draft_rows_never_reach_pools",
    "test_serving_spec.py::test_spec_counters_flow_to_serving_record",
    "test_serving_sampling.py::"
    "test_sampled_engine_matches_offline_bitwise[True-0]",
    "test_serving_sampling.py::"
    "test_sampled_engine_matches_offline_bitwise[False-3]",
    "test_serving_sampling.py::"
    "test_sampled_engine_matches_offline_bitwise[True-3]",
    "test_serving_sampling.py::test_seed_stable_across_slot_reordering",
    "test_serving_sampling.py::"
    "test_poisoned_request_fails_future_and_loop_survives",
    "test_moe.py::test_alltoall_matches_dense_dispatch",
    "test_moe.py::test_ragged_sharded_matches_local",
    "test_model.py::test_streamed_offload_serializes_leaf_transfers",
    "test_model.py::test_offload_attn_remat_matches_no_remat",
    "test_model.py::test_remat_dtype_cast_close_to_full_precision",
    "test_generate_cache.py::test_external_cache_rollout_bitwise_identical",
    "test_mup.py::test_zip_infshapes_on_decoder_params",
    "test_fused_block.py::test_mid_block_stop_flag_stops_at_boundary",
    "test_fused_block.py::test_mid_block_save_flag_honored_at_next_boundary",
    "test_kube_http.py::test_pod_watcher_survives_410_by_relisting",
    "test_kube_http.py::test_reconcile_loop_over_real_http_client",
    "test_operator.py::test_operator_entrypoint_main_loop_over_http",
    # third budget rebalance (PR 17): the new fast additions are tiny,
    # but the full fast tier measured 915s wall against the 870s budget
    # on the 1-cpu box. The four heaviest remaining fast tests (58s +
    # 35s + 23s + 22s, each a coarse double-compile or full-Trainer
    # composition with a faster tier-1 sibling) moved to the slow tier.
    "test_model.py::test_logical_axes_match_params",
    "test_model.py::test_save_qkv_offload_matches_save_qkv",
    "test_model.py::test_remat_matches_no_remat",
    "test_observability.py::test_runtime_timer_in_trainer",
    "test_model.py::test_moe_forward",
    "test_model_families.py::test_glm_loss_and_grads_with_prefix_batch",
    "test_model_families.py::test_flash_kernel_window_matches_reference",
    "test_trainer.py::test_trainer_loss_decreases",
    "test_sentinels.py::test_fused_block_sentinels_are_stacked",
    "test_estimator.py::test_estimator_survives_master_outage",
    # disaggregated prefill/decode drills (PR 17): every entry stands up
    # a role-typed fleet (two-plus jit compiles) against a unified
    # reference replica; the affinity-gate property keeps fast units in
    # the same file.
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[0-True-bf16]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[0-True-int8]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[0-False-bf16]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[0-False-int8]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[3-True-bf16]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[3-True-int8]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[3-False-bf16]",
    "test_serving_disagg.py::"
    "test_disagg_bitwise_parity_matrix[3-False-int8]",
    "test_serving_disagg.py::test_one_shot_handoff_parity",
    "test_serving_disagg.py::test_torn_fragment_retries_and_stays_bitwise",
    "test_serving_disagg.py::test_torn_beyond_retries_degrades_to_reprefill",
    "test_serving_disagg.py::"
    "test_mid_stream_prefill_kill_cancels_or_repoints_exactly_once",
    "test_serving_disagg.py::test_mid_stream_decode_kill_collapses_to_unified",
    "test_serving_disagg.py::"
    "test_prefix_affinity_skips_prefill_and_stale_plan_bounces",
    # SLO-driven autoscaling drills (PR 18): live fleets (two-plus jit
    # compiles apiece) driven through scale-out, live-drain scale-in,
    # and oscillating load; the decision logic keeps fast pure units in
    # the same file (synthetic signals + fake clock, no replicas).
    "test_serving_autoscale.py::test_burst_scale_out_restores_p99_bitwise",
    "test_serving_autoscale.py::"
    "test_scale_in_drains_live_zero_loss_and_detached_is_not_dead",
    "test_serving_autoscale.py::"
    "test_live_oscillating_load_one_decision_per_cooldown",
    # brain auto-tuner drills (PR 19): each compiles real jitted steps
    # (a TrainStepBuilder rebuild, or an engine pair for retune parity)
    # and drives versioned revisions through them; the planner math and
    # ladder units (synthetic records, injected clock, no jit) stay
    # tier-1 in the same file.
    "test_brain_tuner.py::test_tuning_replan_drill_loss_continuity",
    "test_brain_tuner.py::test_serving_retune_bitwise_parity",
    # tiered sparse-serving drills (PR 20): each stands up the
    # recommendation serving loop (serving.sparse_engine) and, for the
    # reshard drill, three KvServer processes; the tiered-table,
    # prefetcher, cold-store and partition-property units in the same
    # files stay tier-1.
    "test_sparse_serving.py::test_ps_reshard_drill_mid_traffic",
    "test_bench_smoke.py::test_bench_sparse_serve_mode_emits_schema",
]


def _collected_ids(extra_args):
    import subprocess
    import sys

    out = subprocess.run(
        [
            sys.executable, "-m", "pytest", str(_TESTS), "-q",
            "--collect-only", "-p", "no:cacheprovider",
            "--continue-on-collection-errors", *extra_args,
        ],
        capture_output=True, text=True, cwd=str(_TESTS.parent),
        timeout=300,
    )
    return {
        line.strip().split("::", 1)[0].rsplit("/", 1)[-1]
        + "::" + line.strip().split("::", 1)[1]
        for line in out.stdout.splitlines()
        if "::" in line and not line.startswith(" ")
    }


def test_slow_ledger_entries_exist_and_stay_out_of_tier1():
    everything = _collected_ids([])
    fast = _collected_ids(["-m", "not slow"])
    missing = [t for t in _SLOW_LEDGER if t not in everything]
    assert not missing, (
        "slow-ledger entries no longer exist (renamed/deleted test? "
        "update _SLOW_LEDGER):\n" + "\n".join(missing)
    )
    leaked = [t for t in _SLOW_LEDGER if t in fast]
    assert not leaked, (
        "tier-1 budget regression: these heavyweight tests lost their "
        "slow mark and collect into the fast tier again:\n"
        + "\n".join(leaked)
    )


def _imports_serving_migration(tree) -> bool:
    """Module-level import of the live KV-page migration layer."""
    mod_name = "dlrover_tpu.serving.migration"
    for node in tree.body:  # module level only, by design
        if isinstance(node, ast.Import):
            if any(
                a.name == mod_name or a.name.startswith(mod_name + ".")
                for a in node.names
            ):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == mod_name or mod.startswith(mod_name + "."):
                return True
            if mod == "dlrover_tpu.serving" and any(
                a.name == "migration" for a in node.names
            ):
                return True
    return False


def test_serving_migration_importers_are_unit_file_or_slow():
    """``serving.migration`` consumers outside its own unit-test file
    (``test_serving_migration.py``) are failover drills: they stand up
    replica pairs, kill one mid-stream, and migrate live pages — slow
    tier by construction. The unit file keeps the cheap wire-format
    coverage in tier-1; everyone else must be slow-marked so a new
    drill can't silently blow the 870s budget."""
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        if path.name == "test_serving_migration.py":
            continue  # the unit-test file: fast wire coverage lives here
        tree = ast.parse(path.read_text(), filename=str(path))
        if not _imports_serving_migration(tree) or _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if not _fn_slow_marked(fn):
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "serving.migration importers outside its unit-test file must be "
        "slow-marked (add @pytest.mark.slow or a module pytestmark):\n"
        + "\n".join(rogue)
    )


def _imports_serving_e2e(tree) -> bool:
    """Module-level import of the serving SERVER or REPLICA layer —
    both spin background serve threads and jit-compile the decode
    engine. ``sparse_engine`` counts too: its server runs the same
    background loop and its drills add multiprocess KvServers on top.
    Engine/scheduler/kv_cache unit imports stay fast."""
    e2e = (
        "dlrover_tpu.serving.server",
        "dlrover_tpu.serving.replica",
        "dlrover_tpu.serving.disagg",
        "dlrover_tpu.serving.sparse_engine",
    )
    for node in tree.body:  # module level only, by design
        if isinstance(node, ast.Import):
            if any(
                a.name == m or a.name.startswith(m + ".")
                for a in node.names
                for m in e2e
            ):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if any(mod == m or mod.startswith(m + ".") for m in e2e):
                return True
            if mod == "dlrover_tpu.serving" and any(
                a.name in ("server", "replica", "disagg", "sparse_engine")
                for a in node.names
            ):
                return True
    return False


def _fn_imports_serving_e2e(fn) -> bool:
    """Function-BODY import of serving.server/replica/sparse_engine
    (the drill idiom: import inside the test so tier-1 collection stays
    light)."""
    e2e = (
        "dlrover_tpu.serving.server",
        "dlrover_tpu.serving.replica",
        "dlrover_tpu.serving.disagg",
        "dlrover_tpu.serving.sparse_engine",
    )
    for node in ast.walk(fn):
        if isinstance(node, ast.Import):
            if any(
                a.name == m or a.name.startswith(m + ".")
                for a in node.names
                for m in e2e
            ):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if any(mod == m or mod.startswith(m + ".") for m in e2e):
                return True
            if mod == "dlrover_tpu.serving" and any(
                a.name in ("server", "replica", "disagg", "sparse_engine")
                for a in node.names
            ):
                return True
    return False


def test_serving_e2e_function_imports_are_slow():
    """A test that imports serving.server/replica INSIDE its body is
    still an e2e serving drill — the function-level import dodges the
    module-level rule below but pays the same background-thread +
    two-jit-compiles cost at run time. Such tests must carry ``slow``
    themselves (helpers shared by several drills are exempt; the drills
    calling them are what collect)."""
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        if _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if _fn_slow_marked(fn):
                continue
            if _fn_imports_serving_e2e(fn):
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "function-level serving server/replica imports in non-slow "
        "tests (add @pytest.mark.slow, or a module-level pytestmark):\n"
        + "\n".join(rogue)
    )


def _fn_references(fn, names):
    """Subset of ``names`` referenced anywhere in a function body —
    bare names and attribute accesses both count."""
    found = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in names:
            found.add(node.id)
        elif isinstance(node, ast.Attribute) and node.attr in names:
            found.add(node.attr)
    return found


def test_autoscaler_fleet_drills_are_slow():
    """A test referencing BOTH ``ServingAutoScaler`` and
    ``ServingReplica`` is an autoscaling FLEET drill: it stands up live
    replicas (a jit compile plus a background loop apiece) and drives
    the scale loop against them — slow tier by construction. The scale
    loop's pure decision units (synthetic signal dicts + a fake clock,
    ``evaluate()`` only) reference no replica class and stay in tier-1,
    which is the whole point of keeping ``evaluate`` pure."""
    targets = {"ServingAutoScaler", "ServingReplica"}
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        if _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if _fn_slow_marked(fn):
                continue
            if _fn_references(fn, targets) == targets:
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "autoscaler fleet drills (ServingAutoScaler + ServingReplica) "
        "must be slow-marked (add @pytest.mark.slow or a module "
        "pytestmark):\n" + "\n".join(rogue)
    )


def test_brain_tuner_e2e_drills_are_slow():
    """A test referencing ``BrainTuner`` together with a step-building
    layer (``TrainStepBuilder``) or a live engine (``ServingEngine``)
    is a telemetry→config loop drill: it compiles real jitted steps
    and drives versioned revisions through them — slow tier by
    construction. The tuner's pure ladder units (synthetic records +
    an injected clock, no jit anywhere) reference neither class and
    stay in tier-1, which is the whole point of keeping the ladders
    pure."""
    engines = {"TrainStepBuilder", "ServingEngine"}
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        if _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if _fn_slow_marked(fn):
                continue
            refs = _fn_references(fn, engines | {"BrainTuner"})
            if "BrainTuner" in refs and refs & engines:
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "brain tuner e2e drills (BrainTuner + TrainStepBuilder/"
        "ServingEngine) must be slow-marked (add @pytest.mark.slow or "
        "a module pytestmark):\n" + "\n".join(rogue)
    )


def test_serving_e2e_tests_are_slow():
    """Files importing the serving server/replica layer at module level
    run end-to-end serving loops: background threads driving jitted
    prefill+decode over the paged KV cache, and (replica) failover
    drills. Every test in such a file must carry ``slow`` — an e2e
    serving run that slips into tier-1 pays two jit compiles per config
    and flakes under load. Allocator/scheduler/engine-math unit tests
    import those modules directly and stay in tier-1.
    """
    rogue = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        if not _imports_serving_e2e(tree) or _module_slow_marked(tree):
            continue
        for fn in _test_functions(tree):
            if not _fn_slow_marked(fn):
                rogue.append(f"{path.name}:{fn.lineno}: {fn.name}")
    assert not rogue, (
        "serving e2e tests not marked slow (add @pytest.mark.slow, or "
        "a module-level pytestmark):\n" + "\n".join(rogue)
    )
