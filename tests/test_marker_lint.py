"""Marker lint: every ``pytest.mark.X`` in tests/ must be declared.

Tier-1 excludes ``-m 'not slow'`` work to stay under its time budget —
but a typo'd marker (``@pytest.mark.slw``) silently keeps an expensive
test IN tier-1, and an undeclared one only warns. This AST scan turns
both into a hard failure: the set of markers used across the test tree
must be a subset of pyproject's declared markers plus pytest builtins.
"""

import ast
import pathlib

_TESTS = pathlib.Path(__file__).parent
_PYPROJECT = _TESTS.parent / "pyproject.toml"

# markers pytest itself defines; always legal
_BUILTIN = {
    "parametrize",
    "skip",
    "skipif",
    "xfail",
    "usefixtures",
    "filterwarnings",
}


def declared_markers():
    try:
        import tomllib
    except ImportError:  # py<3.11
        import tomli as tomllib  # type: ignore[no-redef]
    with open(_PYPROJECT, "rb") as f:
        data = tomllib.load(f)
    lines = data["tool"]["pytest"]["ini_options"].get("markers", [])
    return {line.split(":", 1)[0].strip() for line in lines}


def used_markers():
    """(marker, file, lineno) for every pytest.mark.<name> attribute."""
    used = []
    for path in sorted(_TESTS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            val = node.value
            if (
                isinstance(val, ast.Attribute)
                and val.attr == "mark"
                and isinstance(val.value, ast.Name)
                and val.value.id == "pytest"
            ):
                used.append((node.attr, path.name, node.lineno))
    return used


def test_all_markers_declared():
    legal = declared_markers() | _BUILTIN
    rogue = [
        f"{fn}:{ln}: pytest.mark.{m}"
        for m, fn, ln in used_markers()
        if m not in legal
    ]
    assert not rogue, (
        "undeclared pytest markers (declare in pyproject.toml "
        "[tool.pytest.ini_options] markers, or fix the typo):\n"
        + "\n".join(rogue)
    )


def test_slow_marker_still_declared():
    """Tier-1's ``-m 'not slow'`` filter depends on this declaration."""
    assert "slow" in declared_markers()
