"""MoE gating and expert-parallel dispatch tests.

Reference behaviors: atorch moe/topk_gating.py, switch_gating.py (jitter),
moe_layer.py _AllToAll dispatch, ST-MoE router z-loss.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel.moe import (
    init_moe_params,
    load_balancing_loss,
    moe_block,
    router_z_loss,
    switch_gating,
    top_k_gating,
)


@pytest.fixture
def ep_mesh():
    return build_mesh(MeshConfig(dp=2, ep=4))


def _moe_cfg(**kw):
    return get_config(
        "tiny-moe",
        n_layer=2,
        d_model=32,
        d_ff=64,
        n_head=4,
        vocab_size=128,
        max_seq=32,
        **kw,
    )


def test_switch_gating_is_top1():
    logits = jax.random.normal(jax.random.key(0), (2, 16, 4))
    dispatch, combine, probs = switch_gating(logits, capacity=8)
    # each token routed to at most one expert slot
    per_token = np.asarray(dispatch.sum(axis=(2, 3)))
    assert (per_token <= 1.0 + 1e-6).all()
    # kept tokens carry the RAW router probability (Switch: y = p_i·E_i),
    # not a renormalized 1.0 — that constant would zero the router grad
    w = np.asarray(combine.sum(axis=(2, 3)))
    p_top = np.asarray(probs.max(-1))
    np.testing.assert_allclose(
        w[per_token > 0.5], p_top[per_token > 0.5], atol=1e-5
    )
    assert (w[per_token > 0.5] < 1.0).all()


def test_switch_router_receives_gradient():
    """The combine path must be differentiable w.r.t. router logits."""

    def f(logits):
        _, combine, _ = switch_gating(logits, capacity=8)
        return jnp.sum(combine * 1.7)

    g = jax.grad(f)(jax.random.normal(jax.random.key(0), (2, 16, 4)))
    assert float(jnp.abs(g).max()) > 1e-3


def test_switch_gating_jitter_changes_assignment():
    logits = jax.random.normal(jax.random.key(1), (2, 64, 8)) * 0.01
    d0, _, _ = switch_gating(logits, capacity=16)
    d1, _, _ = switch_gating(
        logits, capacity=16, jitter_eps=0.5, rng=jax.random.key(2)
    )
    assert not np.allclose(np.asarray(d0), np.asarray(d1))
    # no rng → jitter disabled even with eps set
    d2, _, _ = switch_gating(logits, capacity=16, jitter_eps=0.5)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d2))


def test_router_z_loss_penalizes_large_logits():
    small = router_z_loss(jnp.ones((2, 8, 4)) * 0.1)
    large = router_z_loss(jnp.ones((2, 8, 4)) * 10.0)
    assert float(large) > float(small)


def test_balanced_router_minimizes_lb_loss():
    # uniform router → lb loss ≈ 1 (its minimum); collapsed router → ~E
    e = 4
    uniform = jnp.zeros((2, 32, e))
    du, _, pu = top_k_gating(uniform, k=1, capacity=32)
    collapsed = jnp.zeros((2, 32, e)).at[..., 0].set(20.0)
    dc, _, pc = top_k_gating(collapsed, k=1, capacity=32)
    lu = float(load_balancing_loss(pu, du))
    lc = float(load_balancing_loss(pc, dc))
    assert abs(lu - 1.0) < 0.1
    assert lc > 2.0


def test_loss_fn_adds_router_losses():
    cfg = _moe_cfg(moe_aux_coef=0.0, moe_z_coef=0.0)
    cfg_aux = _moe_cfg(moe_aux_coef=0.01, moe_z_coef=0.001)
    params = decoder.init(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (4, 32), 0, 128)
    batch = {"tokens": toks, "targets": toks}
    loss0, m0 = decoder.loss_fn(params, batch, cfg)
    loss1, m1 = decoder.loss_fn(params, batch, cfg_aux)
    assert "moe_lb_loss" in m1 and "moe_lb_loss" not in m0
    assert float(loss1) > float(loss0)
    # aux terms are exactly the difference
    np.testing.assert_allclose(
        float(loss1 - loss0),
        float(m1["moe_lb_loss"] + m1["moe_z_loss"]),
        rtol=1e-4,
    )


def test_switch_decoder_forward_finite():
    cfg = _moe_cfg(moe_gating="switch", moe_jitter=0.1)
    params = decoder.init(jax.random.key(0), cfg)
    toks = jnp.zeros((2, 16), jnp.int32)
    logits = decoder.forward(
        params, toks, cfg, rng=jax.random.key(3)
    )
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_alltoall_matches_dense_dispatch(ep_mesh):
    """The explicit shard_map all-to-all path must compute the same output
    as the dense-einsum path (same gating, same experts)."""
    cfg = _moe_cfg(n_experts=4)
    rng = jax.random.key(0)
    moe = jax.tree.map(
        lambda x: x[0],  # layer 0 slice
        init_moe_params(rng, cfg),
    )
    x = jax.random.normal(jax.random.key(1), (8, 32, cfg.d_model)).astype(
        jnp.bfloat16
    )
    dense = moe_block(x, moe, cfg, ep_mesh)
    cfg_a2a = dataclasses.replace(cfg, moe_alltoall=True)
    a2a, aux = moe_block(x, moe, cfg_a2a, ep_mesh, return_aux=True)
    np.testing.assert_allclose(
        np.asarray(dense, dtype=np.float32),
        np.asarray(a2a, dtype=np.float32),
        rtol=5e-2,
        atol=5e-2,
    )
    assert np.isfinite(float(aux["moe_lb_loss"]))


def test_alltoall_grads_flow(ep_mesh):
    cfg = _moe_cfg(n_experts=4, moe_alltoall=True)
    moe = jax.tree.map(lambda x: x[0], init_moe_params(jax.random.key(0), cfg))
    x = jax.random.normal(jax.random.key(1), (8, 32, cfg.d_model))

    def f(m):
        return jnp.sum(moe_block(x, m, cfg, ep_mesh) ** 2)

    g = jax.jit(jax.grad(f))(moe)
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    assert float(jnp.abs(g["w_up"]).sum()) > 0.0


def test_ragged_matches_dense_at_high_capacity():
    """With capacity high enough that the dense path drops nothing, the
    dropless ragged grouped-GEMM path must produce the same output."""
    cfg = _moe_cfg(n_experts=4, capacity_factor=64.0)
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    x = jax.random.normal(jax.random.key(1), (4, 32, cfg.d_model))
    dense = moe_block(x, moe, cfg, None)
    cfg_r = dataclasses.replace(cfg, moe_impl="ragged")
    ragged, aux = moe_block(x, moe, cfg_r, None, return_aux=True)
    np.testing.assert_allclose(
        np.asarray(dense, np.float32),
        np.asarray(ragged, np.float32),
        rtol=2e-5,
        atol=2e-5,
    )
    assert np.isfinite(float(aux["moe_lb_loss"]))
    assert np.isfinite(float(aux["moe_z_loss"]))


@pytest.mark.slow  # tier-1 budget: core routing/dispatch moe pins stay fast
def test_ragged_no_truncation_under_imbalance():
    """All tokens routed to ONE expert: the capacity path drops most of
    them; the ragged path must process every token (the grouped-GEMM
    FLOPs-follow-load property the reference gets from grouped_gemm_moe)."""
    cfg = _moe_cfg(n_experts=4, capacity_factor=1.0, moe_impl="ragged")
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    # bias the router so expert 2 wins for every token
    moe["w_gate"] = jnp.zeros_like(moe["w_gate"]).at[:, 2].set(10.0)
    x = jax.random.normal(jax.random.key(1), (2, 32, cfg.d_model))
    out = moe_block(x, moe, cfg, None)

    # reference: every token through expert 2's FFN with combined weight
    # = its (renormalized) top-k routing weight ≈ 1 on expert 2... use
    # the dense path with huge capacity as the no-drop oracle instead
    cfg_oracle = dataclasses.replace(
        cfg, moe_impl="dense", capacity_factor=1e4
    )
    oracle = moe_block(x, moe, cfg_oracle, None)
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(oracle, np.float32),
        rtol=2e-5,
        atol=2e-5,
    )
    # and the capacity path at 1.0 demonstrably differs (tokens dropped)
    capped = moe_block(
        x, moe, dataclasses.replace(cfg, moe_impl="dense"), None
    )
    assert not np.allclose(
        np.asarray(capped, np.float32), np.asarray(oracle, np.float32)
    )


@pytest.mark.slow
def test_ragged_sharded_matches_local():
    """shard_map'd ragged path (dp×tp token/width sharding) ≡ unsharded."""
    mesh = build_mesh(MeshConfig(dp=2, tp=2, fsdp=2))
    cfg = _moe_cfg(n_experts=4, moe_impl="ragged")
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    x = jax.random.normal(jax.random.key(1), (8, 32, cfg.d_model))
    local, aux_l = moe_block(x, moe, cfg, None, return_aux=True)
    sharded, aux_s = moe_block(x, moe, cfg, mesh, return_aux=True)
    np.testing.assert_allclose(
        np.asarray(local, np.float32),
        np.asarray(sharded, np.float32),
        rtol=2e-5,
        atol=2e-5,
    )
    np.testing.assert_allclose(
        float(aux_l["moe_lb_loss"]), float(aux_s["moe_lb_loss"]), rtol=1e-5
    )


def test_ragged_grads_flow_and_router_trains():
    cfg = _moe_cfg(n_experts=4, moe_impl="ragged")
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    x = jax.random.normal(jax.random.key(1), (4, 32, cfg.d_model))

    def f(m):
        out, aux = moe_block(x, m, cfg, None, return_aux=True)
        return jnp.sum(out**2) + 0.01 * aux["moe_lb_loss"]

    g = jax.jit(jax.grad(f))(moe)
    for name, leaf in g.items():
        assert np.isfinite(np.asarray(leaf)).all(), name
    # the router must receive gradient through the combine weights
    assert float(jnp.abs(g["w_gate"]).sum()) > 0.0
    assert float(jnp.abs(g["w_down"]).sum()) > 0.0


@pytest.mark.slow
def test_ragged_ep_matches_dense_oracle(ep_mesh):
    """Dropless EP: bounded all-to-all + ragged compute over an
    ep=4 mesh must match the no-drop dense oracle."""
    cfg = _moe_cfg(n_experts=4, moe_impl="ragged", moe_a2a_bound=4.0)
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    x = jax.random.normal(jax.random.key(1), (8, 32, cfg.d_model))
    out, aux = moe_block(x, moe, cfg, ep_mesh, return_aux=True)
    cfg_oracle = _moe_cfg(n_experts=4, capacity_factor=1e4)
    oracle = moe_block(x, moe, cfg_oracle, None)
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(oracle, np.float32),
        rtol=2e-4,
        atol=2e-4,
    )
    assert float(aux["moe_dropped_frac"]) == 0.0
    assert np.isfinite(float(aux["moe_lb_loss"]))


@pytest.mark.slow
def test_ragged_ep_dropless_under_total_imbalance(ep_mesh):
    """Every token to ONE expert on one rank: bound=ep guarantees no
    drops (the worst case the bound is sized for) and the output still
    matches the oracle; a tight bound reports the dropped fraction."""
    cfg = _moe_cfg(
        n_experts=4, moe_impl="ragged", moe_a2a_bound=float(4)
    )
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    moe["w_gate"] = jnp.zeros_like(moe["w_gate"]).at[:, 2].set(10.0)
    x = jax.random.normal(jax.random.key(1), (8, 32, cfg.d_model))
    out, aux = moe_block(x, moe, cfg, ep_mesh, return_aux=True)
    assert float(aux["moe_dropped_frac"]) == 0.0
    oracle = moe_block(
        x, moe, _moe_cfg(n_experts=4, capacity_factor=1e4), None
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(oracle, np.float32),
        rtol=2e-4,
        atol=2e-4,
    )
    # tight bound: drops happen and are COUNTED, never silent
    cfg_tight = _moe_cfg(
        n_experts=4, moe_impl="ragged", moe_a2a_bound=1.0
    )
    _, aux_t = moe_block(x, moe, cfg_tight, ep_mesh, return_aux=True)
    # top-2 routing splits load over two experts; the overloaded ranks
    # truncate at the bound and the drop is reported
    assert float(aux_t["moe_dropped_frac"]) > 0.2


def test_ragged_ep_grads_flow(ep_mesh):
    cfg = _moe_cfg(n_experts=4, moe_impl="ragged", moe_a2a_bound=2.0)
    moe = jax.tree.map(
        lambda x: x[0], init_moe_params(jax.random.key(0), cfg)
    )
    x = jax.random.normal(jax.random.key(1), (8, 32, cfg.d_model))

    def f(m):
        out, aux = moe_block(x, m, cfg, ep_mesh, return_aux=True)
        return jnp.sum(out**2) + 0.01 * aux["moe_lb_loss"]

    g = jax.jit(jax.grad(f))(moe)
    for name, leaf in g.items():
        assert np.isfinite(np.asarray(leaf)).all(), name
    assert float(jnp.abs(g["w_gate"]).sum()) > 0.0
    assert float(jnp.abs(g["w_up"]).sum()) > 0.0


def test_pipeline_rejects_moe_aux_and_alltoall():
    from dlrover_tpu.parallel.pipeline import validate_pipeline_config

    mesh_cfg = MeshConfig(pp=2, ep=2, dp=2)
    with pytest.raises(ValueError, match="moe_alltoall"):
        validate_pipeline_config(
            _moe_cfg(n_experts=4, moe_alltoall=True), mesh_cfg
        )
    with pytest.raises(ValueError, match="aux"):
        validate_pipeline_config(
            _moe_cfg(n_experts=4, moe_aux_coef=0.01), mesh_cfg
        )


@pytest.mark.slow  # tier-1 budget: core routing/dispatch moe pins stay fast
def test_train_step_threads_jitter_rng(ep_mesh):
    """Two identical steps at different step counts must see different
    jitter noise (the rng is folded with the step counter)."""
    import optax

    from dlrover_tpu.train import (
        TrainStepBuilder,
        batch_sharding,
        init_train_state,
    )

    cfg = _moe_cfg(
        n_experts=4, moe_gating="switch", moe_jitter=0.9, moe_aux_coef=0.01
    )
    opt = optax.sgd(0.0)  # no param movement: isolate the rng effect
    state = init_train_state(jax.random.key(0), cfg, ep_mesh, opt)
    builder = TrainStepBuilder(cfg, ep_mesh, opt)
    assert builder._needs_rng
    step = builder.build()
    toks = jax.random.randint(jax.random.key(5), (8, 32), 0, 128)
    batch = jax.device_put(
        {"tokens": toks, "targets": toks}, batch_sharding(ep_mesh)
    )
    s1, m1 = step(state, batch)
    s2, m2 = step(s1, batch)  # same params (lr=0), different step counter
    # with 90% jitter the router losses differ between steps
    assert float(m1["moe_lb_loss"]) != float(m2["moe_lb_loss"])
