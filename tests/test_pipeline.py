"""Pipeline parallelism: parity with the scan path and schedule math.

Reference behavior target: atorch pipeline_parallel_optimization.py:56 —
here realised as collective-permute microbatching (SURVEY.md §7), so the
test is *numerical parity* of the pipelined forward/backward with the
plain layer-scan on the same weights.
"""

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel.pipeline import (
    pipeline_bubble_fraction,
    validate_pipeline_config,
)
from dlrover_tpu.parallel.sharding import shardings_for_tree
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)

CFG = get_config(
    "tiny", n_layer=4, max_seq=64, param_dtype="float32", dtype="float32"
)


def _tokens(batch=8, seq=64):
    return jax.random.randint(jax.random.key(1), (batch, seq), 0, 1000)


def _ref_logits(params, tokens):
    mesh = build_mesh(MeshConfig(dp=8))
    return jax.jit(
        lambda p, t: decoder.forward(p, t, CFG, mesh=mesh)
    )(params, tokens)


@pytest.mark.parametrize(
    "axes",
    [
        {"dp": 2, "pp": 4},
        {"pp": 2, "tp": 2, "fsdp": 2},
    ],
)
def test_pipeline_forward_matches_scan(axes):
    tokens = _tokens()
    params = jax.jit(lambda r: decoder.init(r, CFG))(jax.random.key(0))
    ref = _ref_logits(params, tokens)

    mesh = build_mesh(MeshConfig(**axes))
    sharded = jax.device_put(
        params, shardings_for_tree(mesh, decoder.logical_axes(CFG))
    )
    out = jax.jit(
        lambda p, t: decoder.forward(p, t, CFG, mesh=mesh)
    )(sharded, tokens)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-3


def test_pipeline_train_step_loss_matches_dp():
    tokens = _tokens()
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2, decay_steps=10)

    losses = []
    for axes in ({"dp": 8}, {"dp": 2, "pp": 4}):
        mesh = build_mesh(MeshConfig(**axes))
        state = init_train_state(jax.random.key(0), CFG, mesh, opt)
        step = TrainStepBuilder(CFG, mesh, opt).build()
        b = jax.device_put(batch, batch_sharding(mesh))
        for _ in range(2):
            state, metrics = step(state, b)
        losses.append(float(metrics["loss"]))
    assert abs(losses[0] - losses[1]) < 1e-3, losses


def test_pipeline_more_microbatches_than_stages():
    import dataclasses

    cfg = dataclasses.replace(CFG, pp_microbatches=4)
    tokens = _tokens()
    params = jax.jit(lambda r: decoder.init(r, cfg))(jax.random.key(0))
    ref = _ref_logits(params, tokens)

    mesh = build_mesh(MeshConfig(dp=4, pp=2))
    sharded = jax.device_put(
        params, shardings_for_tree(mesh, decoder.logical_axes(cfg))
    )
    out = jax.jit(
        lambda p, t: decoder.forward(p, t, cfg, mesh=mesh)
    )(sharded, tokens)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-3


def test_bubble_fraction():
    assert pipeline_bubble_fraction(1, 4) == 0.0
    assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert pipeline_bubble_fraction(4, 12) == pytest.approx(3 / 15)


def test_validate_rejects_bad_configs():
    with pytest.raises(ValueError, match="not divisible"):
        validate_pipeline_config(
            get_config("tiny", n_layer=3), MeshConfig(pp=2)
        )
    with pytest.raises(ValueError, match="sp"):
        validate_pipeline_config(
            get_config("tiny", n_layer=4), MeshConfig(pp=2, sp=2)
        )
    validate_pipeline_config(get_config("tiny", n_layer=4), MeshConfig(pp=2))
