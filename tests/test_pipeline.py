"""Pipeline parallelism: parity with the scan path and schedule math.

Reference behavior target: atorch pipeline_parallel_optimization.py:56 —
here realised as collective-permute microbatching (SURVEY.md §7), so the
test is *numerical parity* of the pipelined forward/backward with the
plain layer-scan on the same weights.
"""

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel.pipeline import (
    pipeline_bubble_fraction,
    validate_pipeline_config,
)
from dlrover_tpu.parallel.sharding import shardings_for_tree
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)

# pipeline compiles are heavy on the CPU mesh; excluded from the tier-1 budget
pytestmark = pytest.mark.slow

CFG = get_config(
    "tiny", n_layer=4, max_seq=64, param_dtype="float32", dtype="float32"
)


def _tokens(batch=8, seq=64):
    return jax.random.randint(jax.random.key(1), (batch, seq), 0, 1000)


def _ref_logits(params, tokens):
    mesh = build_mesh(MeshConfig(dp=8))
    return jax.jit(
        lambda p, t: decoder.forward(p, t, CFG, mesh=mesh)
    )(params, tokens)


@pytest.mark.parametrize(
    "axes",
    [
        {"dp": 2, "pp": 4},
        {"pp": 2, "tp": 2, "fsdp": 2},
    ],
)
def test_pipeline_forward_matches_scan(axes):
    tokens = _tokens()
    params = jax.jit(lambda r: decoder.init(r, CFG))(jax.random.key(0))
    ref = _ref_logits(params, tokens)

    mesh = build_mesh(MeshConfig(**axes))
    sharded = jax.device_put(
        params, shardings_for_tree(mesh, decoder.logical_axes(CFG))
    )
    out = jax.jit(
        lambda p, t: decoder.forward(p, t, CFG, mesh=mesh)
    )(sharded, tokens)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-3


def test_pipeline_train_step_loss_matches_dp():
    tokens = _tokens()
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2, decay_steps=10)

    losses = []
    for axes in ({"dp": 8}, {"dp": 2, "pp": 4}):
        mesh = build_mesh(MeshConfig(**axes))
        state = init_train_state(jax.random.key(0), CFG, mesh, opt)
        step = TrainStepBuilder(CFG, mesh, opt).build()
        b = jax.device_put(batch, batch_sharding(mesh))
        for _ in range(2):
            state, metrics = step(state, b)
        losses.append(float(metrics["loss"]))
    assert abs(losses[0] - losses[1]) < 1e-3, losses


def test_pipeline_more_microbatches_than_stages():
    import dataclasses

    cfg = dataclasses.replace(CFG, pp_microbatches=4)
    tokens = _tokens()
    params = jax.jit(lambda r: decoder.init(r, cfg))(jax.random.key(0))
    ref = _ref_logits(params, tokens)

    mesh = build_mesh(MeshConfig(dp=4, pp=2))
    sharded = jax.device_put(
        params, shardings_for_tree(mesh, decoder.logical_axes(cfg))
    )
    out = jax.jit(
        lambda p, t: decoder.forward(p, t, cfg, mesh=mesh)
    )(sharded, tokens)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-3


def test_interleaved_forward_matches_scan():
    """v=2 circular schedule ≡ the same network on a plain scan: the
    non-pp path applies semantic_layer_perm, so both meshes compute the
    SAME function from the same storage-ordered params."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, pp_interleave=2, pp_stages=2, pp_microbatches=4
    )
    tokens = _tokens()
    params = jax.jit(lambda r: decoder.init(r, cfg))(jax.random.key(0))
    mesh_ref = build_mesh(MeshConfig(dp=8))
    ref = jax.jit(
        lambda p, t: decoder.forward(p, t, cfg, mesh=mesh_ref)
    )(params, tokens)

    mesh = build_mesh(MeshConfig(dp=4, pp=2))
    sharded = jax.device_put(
        params, shardings_for_tree(mesh, decoder.logical_axes(cfg))
    )
    out = jax.jit(
        lambda p, t: decoder.forward(p, t, cfg, mesh=mesh)
    )(sharded, tokens)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-3


def test_interleaved_grads_match_scan():
    import dataclasses

    cfg = dataclasses.replace(
        CFG, pp_interleave=2, pp_stages=2, pp_microbatches=2
    )
    tokens = _tokens(batch=4)
    params = jax.jit(lambda r: decoder.init(r, cfg))(jax.random.key(0))

    def loss_on(mesh, p):
        logits = decoder.forward(p, tokens, cfg, mesh=mesh)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    mesh_ref = build_mesh(MeshConfig(dp=8))
    g_ref = jax.jit(jax.grad(lambda p: loss_on(mesh_ref, p)))(params)
    mesh_pp = build_mesh(MeshConfig(dp=4, pp=2))
    sharded = jax.device_put(
        params, shardings_for_tree(mesh_pp, decoder.logical_axes(cfg))
    )
    g_pp = jax.jit(jax.grad(lambda p: loss_on(mesh_pp, p)))(sharded)
    for ref_leaf, pp_leaf, path in zip(
        jax.tree.leaves(g_ref),
        jax.tree.leaves(g_pp),
        jax.tree.leaves(
            jax.tree.map_with_path(lambda p, _: str(p), g_ref)
        ),
    ):
        assert (
            float(jnp.max(jnp.abs(ref_leaf - pp_leaf))) < 2e-3
        ), path


def test_bf16_boundary_matches_f32():
    """bits-ppermute bf16 stage hops ≡ f32 hops (fwd and grads) on a
    pipeline body — half the ICI bytes when enabled."""
    from dlrover_tpu.parallel.pipeline import pipeline_apply

    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    L, B, S, D = 4, 8, 16, 32
    w = (
        jax.random.normal(jax.random.key(0), (L, D, D)) * 0.1
    ).astype(jnp.bfloat16)
    x = jax.random.normal(jax.random.key(1), (B, S, D)).astype(
        jnp.bfloat16
    )
    pos = jnp.zeros((B, S), jnp.int32)

    def body(c, layer, p):
        return jnp.tanh(c @ layer)

    def loss(w, x, bdt):
        out = pipeline_apply(
            body, w, x, pos, mesh, boundary_dtype=bdt
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    ref = float(jax.jit(lambda w, x: loss(w, x, None))(w, x))
    bf = float(jax.jit(lambda w, x: loss(w, x, "bfloat16"))(w, x))
    assert abs(ref - bf) / max(abs(ref), 1) < 2e-2

    g_ref = jax.jit(jax.grad(lambda w: loss(w, x, None)))(w)
    g_bf = jax.jit(jax.grad(lambda w: loss(w, x, "bfloat16")))(w)
    err = float(
        jnp.max(jnp.abs(g_ref.astype(jnp.float32) - g_bf.astype(jnp.float32)))
    )
    scale = float(jnp.max(jnp.abs(g_ref.astype(jnp.float32))))
    assert err / max(scale, 1e-6) < 5e-2


def test_bf16_boundary_grad_through_input_feed():
    """Regression: cotangents through the microbatch FEED with bf16
    boundaries used to hit XLA:SPMD's "Invalid binary instruction
    opcode copy" CHECK crash (the where-select/dynamic_index transpose
    over a sub-32-bit xs). The fix keeps the feed path f32; this test
    differentiates w.r.t. the pipeline INPUT — the exact crash shape —
    and checks the grads against f32 hops."""
    from dlrover_tpu.parallel.pipeline import pipeline_apply

    mesh = build_mesh(MeshConfig(dp=4, pp=2))
    L, B, S, D = 2, 8, 16, 32
    w = (
        jax.random.normal(jax.random.key(0), (L, D, D)) * 0.1
    ).astype(jnp.bfloat16)
    x = jax.random.normal(jax.random.key(1), (B, S, D)).astype(
        jnp.bfloat16
    )
    pos = jnp.zeros((B, S), jnp.int32)

    def body(c, layer, p):
        return jnp.tanh(c @ layer)

    def loss(w, x, bdt):
        out = pipeline_apply(
            body, w, x, pos, mesh, boundary_dtype=bdt
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    gw_bf, gx_bf = jax.jit(
        jax.grad(lambda w, x: loss(w, x, "bfloat16"), argnums=(0, 1))
    )(w, x)
    gw_f32, gx_f32 = jax.jit(
        jax.grad(lambda w, x: loss(w, x, "float32"), argnums=(0, 1))
    )(w, x)
    for a, b in ((gw_bf, gw_f32), (gx_bf, gx_f32)):
        err = float(
            jnp.max(
                jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))
            )
        )
        scale = float(jnp.max(jnp.abs(b.astype(jnp.float32))))
        assert err / max(scale, 1e-6) < 5e-2


def test_bf16_decoder_train_step_on_pp_mesh():
    """Full bf16 decoder train step over bf16 stage hops (the default) —
    the op-combination category where the partitioner crash actually
    lived: isolated bodies always passed while the full decoder died.
    Pins the feed-path-f32 workaround at decoder level, not just on a
    toy body."""
    cfg = get_config(
        "tiny",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=256,
        max_seq=64,
    )
    assert cfg.dtype == "bfloat16"  # the default the fix protects
    mesh = build_mesh(MeshConfig(dp=4, pp=2))
    opt = make_optimizer(
        learning_rate=1e-3, warmup_steps=2, decay_steps=10
    )
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    tokens = jax.random.randint(jax.random.key(1), (8, 64), 0, 256)
    batch = jax.device_put(
        {"tokens": tokens, "targets": tokens}, batch_sharding(mesh)
    )
    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"])


def test_semantic_layer_perm_roundtrip():
    from dlrover_tpu.parallel.pipeline import (
        interleaved_chunk_order,
        semantic_layer_perm,
    )
    import numpy as np

    # P=2, v=2, L=8 (cl=2): virtual stages run storage chunks
    # [0, 2, 1, 3] → layers [0,1, 4,5, 2,3, 6,7]
    assert interleaved_chunk_order(2, 2).tolist() == [0, 2, 1, 3]
    assert semantic_layer_perm(8, 2, 2).tolist() == [0, 1, 4, 5, 2, 3, 6, 7]
    # v=1 is the identity (GPipe)
    assert semantic_layer_perm(8, 4, 1).tolist() == list(range(8))
    # every storage layer appears exactly once
    assert sorted(semantic_layer_perm(12, 3, 2).tolist()) == list(range(12))
    np.testing.assert_array_equal(
        np.sort(semantic_layer_perm(16, 4, 2)), np.arange(16)
    )


def test_bubble_fraction():
    assert pipeline_bubble_fraction(1, 4) == 0.0
    assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert pipeline_bubble_fraction(4, 12) == pytest.approx(3 / 15)
    # interleaving divides the bubble: (P−1)/(M·v+P−1)
    assert pipeline_bubble_fraction(4, 4, interleave=2) == pytest.approx(
        3 / 11
    )
    assert pipeline_bubble_fraction(4, 8, interleave=4) == pytest.approx(
        3 / 35
    )


def test_validate_rejects_bad_configs():
    with pytest.raises(ValueError, match="not divisible"):
        validate_pipeline_config(
            get_config("tiny", n_layer=3), MeshConfig(pp=2)
        )
    with pytest.raises(ValueError, match="sp"):
        validate_pipeline_config(
            get_config("tiny", n_layer=4), MeshConfig(pp=2, sp=2)
        )
    validate_pipeline_config(get_config("tiny", n_layer=4), MeshConfig(pp=2))
    # interleave: layer count must divide by pp·v; stage count must match
    with pytest.raises(ValueError, match="pp·interleave"):
        validate_pipeline_config(
            get_config("tiny", n_layer=4, pp_interleave=4, pp_stages=2),
            MeshConfig(pp=2),
        )
    with pytest.raises(ValueError, match="pp_stages"):
        validate_pipeline_config(
            get_config("tiny", n_layer=8, pp_interleave=2, pp_stages=4),
            MeshConfig(pp=2),
        )
    validate_pipeline_config(
        get_config("tiny", n_layer=8, pp_interleave=2, pp_stages=2),
        MeshConfig(pp=2),
    )
