"""Live KV-page migration (serving/migration.py).

Fast tier: the wire blob round-trips every pool dtype bitwise and any
truncation/corruption raises ``TornPageTransfer`` (never a silent
partial import).

Slow tier — the acceptance drills:

- KILL 1 of 2 replicas mid-stream with sampled requests in flight on
  both: the survivor adopts the victim's pages and resumes mid-decode
  with ZERO re-prefilled prompt tokens (``stats()["prefill_tokens"]``
  does not move) and every output bitwise equal to the never-evicted
  ``generate.sample`` stream. No lost, no duplicated request.
- Injected ``drop_page`` / ``stall_migration`` faults degrade to the
  re-prefill tier: ``reshard_recovery path=fallback`` on the telemetry
  hub, outputs still bitwise (position-indexed sampling), nothing lost
  or duplicated.
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.elastic import faults  # noqa: E402
from dlrover_tpu.elastic.resharding import PhaseBudgets  # noqa: E402
from dlrover_tpu.observability import telemetry  # noqa: E402
from dlrover_tpu.serving import migration as mig  # noqa: E402
from dlrover_tpu.serving.scheduler import SamplingParams  # noqa: E402

# ------------------------------------------------------------------ wire


def _snap(mode="int8"):
    rng = np.random.default_rng(7)
    if mode == "int8":
        pages = {
            "k_q": rng.integers(-127, 128, (2, 3, 4, 4, 8)).astype(np.int8),
            "k_scale": rng.random((2, 3, 4, 4)).astype(np.float32),
            "v_q": rng.integers(-127, 128, (2, 3, 4, 4, 8)).astype(np.int8),
            "v_scale": rng.random((2, 3, 4, 4)).astype(np.float32),
        }
    else:
        arr = jnp.asarray(
            rng.standard_normal((2, 3, 4, 4, 8)), jnp.bfloat16
        )
        pages = {"k": np.asarray(arr), "v": np.asarray(arr) * 0 + 1}
    return mig.RequestSnapshot(
        rid="rep-1/r3", prompt=[5, 6, 7], generated=[8, 9],
        n_prefilled=3, phase="decode", max_new_tokens=6, seed=11,
        mode=mode, page_size=4, n_layers=2, kv_heads=4, head_dim=8,
        kv_block=8, pages=pages,
    )


@pytest.mark.parametrize("mode", ["int8", "bf16"])
def test_wire_roundtrip_bitwise(mode):
    snap = _snap(mode)
    out = mig.decode_snapshot(mig.encode_snapshot(snap))
    assert out.rid == snap.rid
    assert out.prompt == snap.prompt and out.generated == snap.generated
    assert out.n_prefilled == 3 and out.phase == "decode"
    assert out.seed == snap.seed and out.mode == mode
    assert out.n_pages == 3
    assert out.tokens_resident == 5  # prefill + generated compute saved
    assert set(out.pages) == set(snap.pages)
    for k in snap.pages:
        assert out.pages[k].dtype == snap.pages[k].dtype
        np.testing.assert_array_equal(
            np.asarray(out.pages[k], np.float32),
            np.asarray(snap.pages[k], np.float32),
        )


def test_page_start_rides_the_wire_and_defaults_to_zero():
    # disaggregated prefill→decode streaming ships PARTIAL snapshots:
    # page_start addresses where this fragment's pages land in the
    # target's reservation. One-shot blobs (and every pre-PR recording)
    # decode to the default 0 — the old wire is a prefix of the new.
    snap = _snap("bf16")
    assert mig.decode_snapshot(mig.encode_snapshot(snap)).page_start == 0

    frag = mig.RequestSnapshot(
        rid=snap.rid, prompt=snap.prompt, generated=[],
        n_prefilled=0, phase="prefill",
        max_new_tokens=snap.max_new_tokens, seed=snap.seed,
        mode=snap.mode, page_size=snap.page_size,
        n_layers=snap.n_layers, kv_heads=snap.kv_heads,
        head_dim=snap.head_dim, kv_block=snap.kv_block,
        page_start=2, pages={k: v[:, :1] for k, v in snap.pages.items()},
    )
    out = mig.decode_snapshot(mig.encode_snapshot(frag))
    assert out.page_start == 2 and out.phase == "prefill"
    assert out.n_pages == 1


def test_torn_blobs_raise_not_partial_import():
    blob = mig.encode_snapshot(_snap())
    cases = {
        "truncated payload": blob[:-7],
        "truncated header": blob[: len(b"DTKV1\n") + 10],
        "bad magic": b"XX" + blob,
        "garbage": b"\x00" * 64,
    }
    for name, bad in cases.items():
        with pytest.raises(mig.TornPageTransfer):
            mig.decode_snapshot(bad)


def test_bit_flip_in_payload_fails_checksum():
    blob = bytearray(mig.encode_snapshot(_snap()))
    blob[-3] ^= 0x40  # flip one payload bit
    with pytest.raises(mig.TornPageTransfer, match="checksum"):
        mig.decode_snapshot(bytes(blob))


def test_dropped_page_is_retryable_by_the_ladder():
    # both torn-transfer signals sit under TornDonation, the resharder's
    # default retryable — a transient tear retries before falling back
    assert issubclass(mig.TornPageTransfer, faults.TornDonation)
    assert issubclass(faults.DroppedPage, faults.TornDonation)


# ------------------------------------------------------------ acceptance


_SERVER_KW = dict(
    n_slots=4, max_len=32, page_size=4, mode="bf16", prefill_chunk=4,
    idle_sleep=0.001,
)


@pytest.fixture(scope="module")
def drill():
    from dlrover_tpu.models import decoder, generate
    from dlrover_tpu.models.config import get_config

    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    prompts = [[2, 3, 4, 2, 3], [9, 10, 9, 10], [5, 6, 7], [11, 3, 7, 1]]
    max_new = [14, 14, 14, 14]
    sps = [
        SamplingParams(temperature=0.9, top_k=5, top_p=0.9, seed=i + 1)
        for i in range(4)
    ]
    refs = [
        [
            int(t)
            for t in np.asarray(
                generate.sample(
                    params, cfg, jnp.asarray([p], jnp.int32), m,
                    rng=jax.random.key(sp.seed),
                    temperature=sp.temperature, top_k=sp.top_k,
                    top_p=sp.top_p,
                )[0]
            )
        ]
        for p, m, sp in zip(prompts, max_new, sps)
    ]
    return cfg, params, prompts, max_new, sps, refs


@pytest.fixture
def hub_events():
    telemetry.reset_hub()
    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append)
    yield events
    telemetry.reset_hub()


def _mid_stream(rep, want):
    """Every slot decoding with ≥1 generated token and unresolved."""
    eng = rep.server.engine
    slots = [s for s in eng.slots if s is not None]
    return len(slots) == want and all(
        s.phase == "decode"
        and len(s.generated) >= 1
        and not s.req.future.done()
        for s in slots
    )


def _run_kill_drill(drill, migrator):
    """Shared body: 2 replicas, 4 sampled requests (2 each), kill one
    mid-stream, fail over through ``migrator``, gather everything.

    The victim's loop is parked from the start and its engine stepped
    BY HAND to a pinned mid-decode state before the kill — the jitted
    decode rate (ms per token once warm) is far too fast to catch a
    2-slot mid-stream window by polling wall clock."""
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, prompts, max_new, sps, refs = drill
    r0 = ServingReplica("mig-0", params, cfg, node_id=0, **_SERVER_KW)
    r1 = ServingReplica("mig-1", params, cfg, node_id=1, **_SERVER_KW)
    r0.start()
    r1.start()
    try:
        router = ReplicaRouter([r0, r1], migrator=migrator)
        with r1.server.paused() as eng1:
            reqs = [
                router.submit(p, m, sampling=sp)
                for p, m, sp in zip(prompts, max_new, sps)
            ]
            # round-robin put requests 1 and 3 on the victim r1
            assert [e.replica.name for e in router._entries] == [
                "mig-0", "mig-1", "mig-0", "mig-1",
            ]
            # drive the parked victim to mid-stream: both slots in
            # decode with >= 1 sampled token and unresolved futures
            for _ in range(50):
                if _mid_stream(r1, 2):
                    break
                eng1.step()
            assert _mid_stream(r1, 2), "victim never reached mid-stream"
            r1.kill()
        assert not r1.alive and r0.alive
        # the survivor finishes its own two requests first, so its
        # prefill counter is final before the failover lands on it
        for r in (reqs[0], reqs[2]):
            r.future.result(timeout=300)
        base_prefill = r0.server.engine.stats()["prefill_tokens"]
        moved = router.poll()
        report = router.reports[-1]
        outs = router.wait_all(timeout=600)
        return r0, r1, reqs, outs, report, base_prefill, moved, refs
    finally:
        r0.stop()
        r1.kill()


@pytest.mark.slow
def test_migration_drill_zero_reprefill_bitwise(drill, hub_events):
    migrator = mig.ServingMigrator()
    r0, r1, reqs, outs, report, base_prefill, moved, refs = _run_kill_drill(
        drill, migrator
    )
    # the live path carried both victim requests; nothing degraded
    assert report.path == "live"
    assert len(report.placements) == 2 and moved == 2
    assert report.re_prefilled == {} and report.re_routed == {}
    assert report.directive_version >= 1
    assert report.bytes_moved > 0
    assert report.tokens_saved >= 2 * (3 + 1)  # ≥ prompt+1 per request

    s0, s1 = r0.server.engine.stats(), r1.server.engine.stats()
    # ZERO re-prefilled prompt tokens: the survivor's prefill counter
    # did not move across the failover
    assert s0["prefill_tokens"] == base_prefill
    assert s0["migrated_in"] == 2 and s1["migrated_out"] == 2

    # bitwise equal to the never-evicted stream, every request
    assert outs == refs
    # no lost request, no duplicate: 4 completions, all on the survivor,
    # none through the re-admit (re-prefill) path
    assert r0.server.scheduler.completed == 4
    assert r1.server.scheduler.completed == 0
    assert r0.server.scheduler.re_admitted == 0
    assert all(r.future.done() for r in reqs)
    # telemetry: the ladder closed on the live path
    recovery = [e for e in hub_events if e.kind == "reshard_recovery"]
    assert recovery and "path=live" in recovery[-1].detail


def _fault_migrator(kind):
    inj = faults.FaultInjector()
    if kind == "torn":
        # every transfer attempt tears: retries exhaust, then fallback
        inj.install(
            faults.FaultSpec("drop_page", point="serving.transfer")
        )
        budgets = PhaseBudgets()
    else:
        # stall the transfer past its (tiny) budget: deadline exceeded
        inj.install(
            faults.FaultSpec(
                "stall_migration", point="serving.transfer", delay_s=0.3
            )
        )
        budgets = PhaseBudgets(transfer_s=0.05)
    return mig.ServingMigrator(budgets=budgets, faults=inj, retries=1)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["torn", "stall"])
def test_faulted_migration_degrades_to_reprefill(drill, hub_events, kind):
    migrator = _fault_migrator(kind)
    r0, r1, reqs, outs, report, base_prefill, moved, refs = _run_kill_drill(
        drill, migrator
    )
    # the ladder degraded: no live placement, both requests re-prefilled
    assert report.path == "fallback"
    assert report.placements == {}
    assert len(report.re_prefilled) == 2 and moved == 2

    s0 = r0.server.engine.stats()
    assert s0["migrated_in"] == 0
    assert s0["prefill_tokens"] > base_prefill  # prompts were redone

    # degradation is invisible in the output: position-indexed sampling
    # makes the re-prefilled continuation bitwise too
    assert outs == refs
    # no lost, no duplicated request
    assert r0.server.scheduler.completed == 4
    assert r0.server.scheduler.re_admitted == 2
    assert all(r.future.done() for r in reqs)
    # the survivor holds no leaked reservation pages
    assert r0.server.engine.alloc.reserved_pages == 0
    recovery = [e for e in hub_events if e.kind == "reshard_recovery"]
    assert recovery and "path=fallback" in recovery[-1].detail


@pytest.mark.slow
def test_wait_all_backoff_with_slow_straggler(drill):
    """Regression for the router's 50 ms busy-spin: with one straggler
    still decoding, ``wait_all`` polls with jittered backoff — the poll
    count stays far below the old spin's (duration / 50 ms) — and a
    per-request ``deadline_s`` tighter than the straggler's runtime
    raises instead of waiting forever."""
    import concurrent.futures

    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, prompts, max_new, sps, refs = drill
    rep = ServingReplica("strag-0", params, cfg, node_id=0, **_SERVER_KW)
    rep.start()
    try:
        router = ReplicaRouter([rep])
        quick = router.submit(prompts[0], 2)
        slow = router.submit(prompts[1], 14)
        polls = {"n": 0}
        orig = router.poll

        def counting_poll():
            polls["n"] += 1
            return orig()

        router.poll = counting_poll
        t0 = time.monotonic()
        outs = router.wait_all(timeout=600)
        waited = time.monotonic() - t0
        assert len(outs) == 2
        assert quick.future.done() and slow.future.done()
        # jittered backoff, not a 50 ms spin: the old loop would have
        # polled ~ waited/0.05 times; the backoff loop stays well under
        spin_polls = max(waited / 0.05, 1.0)
        assert polls["n"] < spin_polls / 2, (polls["n"], waited)

        # per-request deadline: tighter than the work, raises promptly.
        # The server is parked so the (now jit-warm, ms-fast) request
        # cannot win the race against its own 1 ms deadline.
        with rep.server.paused():
            doomed = router.submit(prompts[2], 14, deadline_s=0.001)
            with pytest.raises(concurrent.futures.TimeoutError):
                router.wait_all(timeout=600)
            assert not doomed.future.done()
    finally:
        rep.stop()
