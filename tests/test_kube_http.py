"""RealKubeApi (raw-HTTP k8s client) against a wire-level API server.

The server below speaks the actual Kubernetes REST protocol — JSON
bodies, labelSelector queries, 404/409 statuses, and chunked
``?watch=1`` event streams with resourceVersions — backed by the same
FakeKubeApi object store the rest of the suite uses. The point
(VERDICT r2 #2): PodWatcher + JobReconciler run UNMODIFIED over
RealKubeApi + HTTP, proving the protocol boundary holds off the
in-process fake. Reference parity: scheduler/kubernetes.py:122 +
watcher/k8s_watcher.py:194.
"""

import json
import re
import threading
import time
import urllib.error
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from dlrover_tpu.cluster.crd import (
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    TPUSliceSpec,
)
from dlrover_tpu.cluster.kube import (
    JOB_LABEL,
    FakeKubeApi,
    PodWatcher,
)
from dlrover_tpu.cluster.kube_http import RealKubeApi, WatchExpired
from dlrover_tpu.cluster.scaler import SliceScaler
from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.master.node_manager import JobManager, ScalePlan

_PLURALS = {
    "pods": "Pod",
    "services": "Service",
    "configmaps": "ConfigMap",
    "secrets": "Secret",
    "events": "Event",
    "elasticjobs": "ElasticJob",
    "scaleplans": "ScalePlan",
}
_PATH_RE = re.compile(
    r"^/(?:api/v1|apis/[^/]+/[^/]+)/namespaces/(?P<ns>[^/]+)/"
    r"(?P<plural>[^/?]+)(?:/(?P<name>[^/?]+?))?(?P<sub>/status)?$"
)


class _KubeHandler(BaseHTTPRequestHandler):
    """Wire protocol over the backing FakeKubeApi store."""

    fake: FakeKubeApi = None  # set by server factory
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code, obj):
        raw = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _auth_ok(self):
        if self.headers.get("Authorization") != "Bearer test-token":
            self._send(401, {"kind": "Status", "code": 401})
            return False
        return True

    def _route(self):
        parsed = urlparse(self.path)
        m = _PATH_RE.match(parsed.path)
        if not m or m.group("plural") not in _PLURALS:
            self._send(404, {"kind": "Status", "code": 404})
            return None
        q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        sel = None
        if "labelSelector" in q:
            sel = dict(
                pair.split("=", 1)
                for pair in q["labelSelector"].split(",")
            )
        return (
            _PLURALS[m.group("plural")],
            m.group("ns"),
            m.group("name"),
            q,
            sel,
            bool(m.group("sub")),  # the /status subresource
        )

    def do_GET(self):  # noqa: N802
        if not self._auth_ok():
            return
        route = self._route()
        if route is None:
            return
        kind, ns, name, q, sel, _sub = route
        if name:
            obj = self.fake.get(kind, name, ns)
            if obj is None:
                self._send(404, {"kind": "Status", "code": 404})
            else:
                self._send(200, obj)
            return
        if q.get("watch") == "1":
            self._stream_watch(kind, ns, sel, q.get("resourceVersion", "0"))
            return
        items = self.fake.list(kind, ns, label_selector=sel)
        # real list items omit kind (clients re-add it)
        for it in items:
            it.pop("kind", None)
        self._send(
            200,
            {
                "kind": f"{kind}List",
                "items": items,
                "metadata": {
                    "resourceVersion": self._rv_out(self.fake.latest_rv())
                },
            },
        )

    # rv_prefix (opaque-rv mode): rvs go on the wire as "<prefix><n>"
    # strings — non-numeric, like the documented k8s contract allows
    def _rv_out(self, rv: int) -> str:
        return f"{getattr(self.server, 'rv_prefix', '')}{rv}"

    def _rv_in(self, raw: str) -> int:
        prefix = getattr(self.server, "rv_prefix", "")
        if prefix and raw.startswith(prefix):
            raw = raw[len(prefix):]
        try:
            return int(raw)
        except ValueError:
            return 0

    def _chunk(self, payload: dict):
        raw = (json.dumps(payload) + "\n").encode()
        self.wfile.write(f"{len(raw):x}\r\n".encode())
        self.wfile.write(raw + b"\r\n")
        self.wfile.flush()

    def _stream_watch(self, kind, ns, sel, raw_rv):
        getattr(self.server, "seen_watch_rvs", []).append(raw_rv)
        getattr(self.server, "seen_watch_kind_rvs", []).append(
            (kind, raw_rv)
        )
        since_rv = self._rv_in(raw_rv)
        # the 410 Gone contract: honor an artificially expired window
        if getattr(self.server, "expire_below_rv", 0) > since_rv > 0:
            self._send(410, {"kind": "Status", "code": 410})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        if getattr(self.server, "drop_streams", False):
            # terminate the chunked body immediately: the client sees a
            # clean end-of-stream and reconnects with its resume token
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
            return
        stop = threading.Event()
        try:
            for ev in self.fake.watch(
                kind=kind,
                namespace=ns,
                label_selector=sel,
                since_rv=since_rv,
                stop=stop,
                poll_s=0.05,
            ):
                obj = dict(ev.obj)
                obj.setdefault("metadata", {})["resourceVersion"] = (
                    self._rv_out(ev.resource_version)
                )
                obj.pop("kind", None)  # like the real stream for core kinds
                self._chunk({"type": ev.type, "object": obj})
                if getattr(self.server, "send_bookmarks", False):
                    self._chunk(
                        {
                            "type": "BOOKMARK",
                            "object": {
                                "metadata": {
                                    "resourceVersion": self._rv_out(
                                        ev.resource_version
                                    )
                                }
                            },
                        }
                    )
                if getattr(self.server, "drop_after_each", False):
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                    return
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            stop.set()

    def do_POST(self):  # noqa: N802
        if not self._auth_ok():
            return
        route = self._route()
        if route is None:
            return
        kind, ns, _, _, _, _sub = route
        n = int(self.headers.get("Content-Length", 0))
        manifest = json.loads(self.rfile.read(n))
        manifest["kind"] = kind
        try:
            out = self.fake.create(manifest)
        except ValueError:
            self._send(409, {"kind": "Status", "code": 409})
            return
        self._send(201, out)

    def do_PUT(self):  # noqa: N802
        if not self._auth_ok():
            return
        route = self._route()
        if route is None:
            return
        kind, ns, name, _, _, sub = route
        n = int(self.headers.get("Content-Length", 0))
        manifest = json.loads(self.rfile.read(n))
        manifest["kind"] = kind
        if sub:
            # /status subresource: persist ONLY .status (the main
            # resource's spec/metadata in the body are ignored, like a
            # real server)
            out = self.fake.update_status(
                kind, name, manifest.get("status") or {}, ns
            )
            if out is None:
                self._send(404, {"kind": "Status", "code": 404})
            else:
                self._send(200, out)
            return
        try:
            out = self.fake.update(manifest)
        except KeyError:
            self._send(404, {"kind": "Status", "code": 404})
            return
        self._send(200, out)

    def do_DELETE(self):  # noqa: N802
        if not self._auth_ok():
            return
        route = self._route()
        if route is None:
            return
        kind, ns, name, _, _, _sub = route
        self.fake.delete(kind, name, ns)
        self._send(200, {"kind": "Status", "status": "Success"})


@pytest.fixture()
def api_server():
    fake = FakeKubeApi()
    handler = type("H", (_KubeHandler,), {"fake": fake})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    server.daemon_threads = True
    server.seen_watch_rvs = []
    server.seen_watch_kind_rvs = []
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield fake, f"http://127.0.0.1:{server.server_address[1]}", server
    server.shutdown()
    server.server_close()


def _client(url) -> RealKubeApi:
    return RealKubeApi(url, token="test-token")


def _job(replicas=2, max_hosts=4, hosts_per_slice=1):
    return ElasticJob(
        "demo",
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=replicas,
                    slice=TPUSliceSpec(hosts_per_slice=hosts_per_slice),
                )
            },
            min_hosts=1,
            max_hosts=max_hosts,
        ),
    )


def _wait(cond, timeout=8.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_crud_and_selectors_over_http(api_server):
    fake, url, _ = api_server
    api = _client(url)
    pod = {
        "kind": "Pod",
        "metadata": {"name": "p0", "labels": {JOB_LABEL: "demo"}},
    }
    created = api.create(pod)
    assert created["metadata"]["name"] == "p0"
    with pytest.raises(urllib.error.HTTPError):  # 409 duplicate
        api.create(pod)
    assert api.get("Pod", "p0")["metadata"]["name"] == "p0"
    assert api.get("Pod", "nope") is None
    api.create({"kind": "Pod", "metadata": {"name": "p1", "labels": {}}})
    sel = api.list("Pod", label_selector={JOB_LABEL: "demo"})
    assert [p["metadata"]["name"] for p in sel] == ["p0"]
    assert all(p["kind"] == "Pod" for p in sel)  # client re-adds kind
    api.delete("Pod", "p0")
    assert api.get("Pod", "p0") is None
    api.delete("Pod", "p0")  # idempotent (404 swallowed)


def test_unauthenticated_requests_rejected(api_server):
    _, url, _ = api_server
    api = RealKubeApi(url, token="wrong")
    with pytest.raises(urllib.error.HTTPError) as ei:
        api.list("Pod")
    assert ei.value.code == 401


def test_watch_streams_resume_and_410(api_server):
    fake, url, server = api_server
    api = _client(url)
    api.create({"kind": "Pod", "metadata": {"name": "w0", "labels": {}}})
    stop = threading.Event()
    seen = []

    def consume():
        for ev in api.watch(kind="Pod", since_rv=0, stop=stop):
            seen.append((ev.type, ev.name, ev.resource_version))
            if len(seen) >= 3:
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    fake.set_pod_phase("w0", "Running")
    fake.set_pod_phase("w0", "Failed", reason="OOMKilled")
    t.join(timeout=8)
    assert not t.is_alive()
    assert [s[0] for s in seen] == ["ADDED", "MODIFIED", "MODIFIED"]
    # rvs strictly increase — the resume contract
    rvs = [s[2] for s in seen]
    assert rvs == sorted(rvs) and len(set(rvs)) == 3
    stop.set()

    # 410 Gone surfaces as WatchExpired for the caller to relist
    server.expire_below_rv = rvs[-1] + 100
    with pytest.raises(WatchExpired):
        next(iter(api.watch(kind="Pod", since_rv=1)))


@pytest.mark.slow
def test_reconcile_loop_over_real_http_client(api_server):
    """The keystone swap: the SAME PodWatcher + JobManager + SliceScaler
    wiring as test_kube.py's end-to-end loop, with every API call going
    through RealKubeApi over the wire instead of the in-process fake."""
    fake, url, _ = api_server
    api = _client(url)
    job = _job(replicas=2)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
        master_addr="10.0.0.1:8000",
    )
    jm = JobManager(num_workers=2, relaunch_budget=2, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)

    plan = ScalePlan()
    plan.worker_num = 2
    scaler.scale(plan)
    pods = api.list("Pod", label_selector={JOB_LABEL: "demo"})
    assert [p["metadata"]["name"] for p in pods] == [
        "demo-worker-0",
        "demo-worker-1",
    ]

    watcher.start()
    fake.set_pod_phase("demo-worker-0", "Running")
    fake.set_pod_phase("demo-worker-1", "Running")
    _wait(
        lambda: all(
            jm.get_node(i).status == NodeStatus.RUNNING for i in (0, 1)
        ),
        msg="both nodes running over HTTP watch",
    )

    # kubelet reports OOM → HTTP watch stream → NodeEvent → relaunch →
    # replacement pod created through the HTTP client
    fake.set_pod_phase("demo-worker-0", "Failed", reason="OOMKilled")
    _wait(
        lambda: api.get("Pod", "demo-worker-0-r1") is not None,
        msg="relaunched pod via HTTP",
    )
    assert jm.get_node(0).relaunch_count == 1
    fake.set_pod_phase("demo-worker-0-r1", "Running")
    _wait(
        lambda: jm.get_node(0).status == NodeStatus.RUNNING,
        msg="node 0 running after relaunch",
    )
    # stale-event guard still holds across the wire
    time.sleep(0.3)
    assert jm.get_node(0).relaunch_count == 1
    assert api.get("Pod", "demo-worker-0-r2") is None
    watcher.stop()
    jm.stop()


def test_job_reconciler_over_real_http_client(api_server):
    """JobReconciler (kind=None merged watch) drives CRD events -> pods
    through the HTTP client: ElasticJob ADDED scales up; a ScalePlan
    with removePods scales back down."""
    from dlrover_tpu.cluster.kube import JobReconciler

    fake, url, _ = api_server
    api = _client(url)
    rec = JobReconciler(api, _job(replicas=0), master_addr="10.0.0.1:8000")
    rec.start()
    api.create(
        {
            "kind": "ElasticJob",
            "metadata": {"name": "demo"},
            "spec": {"replicaSpecs": {"worker": {"replicas": 2}}},
        }
    )
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 2,
        msg="reconciler created 2 pods over HTTP",
    )
    api.create(
        {
            "kind": "ScalePlan",
            "metadata": {"name": "sp-1"},
            "spec": {
                "ownerJob": "demo",
                "replicaCounts": {"worker": 1},
                "removePods": ["demo-worker-1"],
            },
        }
    )
    _wait(
        lambda: [
            p["metadata"]["name"]
            for p in api.list("Pod", label_selector={JOB_LABEL: "demo"})
        ]
        == ["demo-worker-0"],
        msg="scale plan removed worker-1 over HTTP",
    )
    rec.stop()


def test_job_reconciler_survives_410_by_relisting(api_server):
    """The reconciler's merged (kind=None) watch must survive a 410 the
    same way PodWatcher does: relist the ElasticJob, re-assert desired
    state, and keep reconciling — the watch-expired path must not kill
    the operator thread (its pumps use an internal stop event, so the
    WatchExpired is recoverable)."""
    from dlrover_tpu.cluster.kube import JobReconciler

    fake, url, server = api_server
    api = _client(url)
    rec = JobReconciler(api, _job(replicas=0), master_addr="10.0.0.1:8000")
    rec.start()
    api.create(
        {
            "kind": "ElasticJob",
            "metadata": {"name": "demo"},
            "spec": {"replicaSpecs": {"worker": {"replicas": 1}}},
        }
    )
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 1,
        msg="reconciler created the first pod",
    )
    # expire the history window; the merged watch reconnects into 410s
    server.drop_streams = True
    server.expire_below_rv = fake.latest_rv() + 1
    time.sleep(0.5)
    # desired state changes while the watch is expired — only a live
    # (relisting) reconciler can pick it up
    server.drop_streams = False
    ej = api.get("ElasticJob", "demo")
    ej["spec"]["replicaSpecs"]["worker"]["replicas"] = 3
    api.update(ej)
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 3,
        timeout=10.0,
        msg="reconciler scaled to 3 after the watch expired",
    )
    rec.stop()


def test_status_subresource_over_http(api_server):
    """With the status subresource enabled, .status only persists via
    the /status PUT path — a main-resource PUT silently drops it (real
    API-server semantics, which the operator's status sync relies on);
    and a status write never clobbers a concurrent spec change."""
    fake, url, _ = api_server
    api = _client(url)
    api.create(
        {
            "kind": "ElasticJob",
            "metadata": {"name": "sj"},
            "spec": {"minHosts": 1},
        }
    )
    # main-resource PUT cannot smuggle a status in
    obj = api.get("ElasticJob", "sj")
    obj["status"] = {"phase": "Hacked"}
    api.update(obj)
    assert (api.get("ElasticJob", "sj") or {}).get("status") is None
    # the subresource write persists
    api.update_status("ElasticJob", "sj", {"phase": "Running"})
    assert api.get("ElasticJob", "sj")["status"]["phase"] == "Running"
    # spec change + status write interleave without clobbering either
    obj = api.get("ElasticJob", "sj")
    obj["spec"]["minHosts"] = 3
    api.update(obj)
    api.update_status("ElasticJob", "sj", {"phase": "Failed"})
    got = api.get("ElasticJob", "sj")
    assert got["spec"]["minHosts"] == 3
    assert got["status"]["phase"] == "Failed"


def test_watch_passes_opaque_rvs_through_and_skips_bookmarks(api_server):
    """k8s documents resourceVersions as opaque strings: the client must
    hand the last seen token back verbatim on reconnect (not parse it)
    and swallow BOOKMARK progress events (which carry a fresh rv but no
    object change). The server here emits rvs as non-numeric 'op-<n>'
    strings, drops the stream after every event, and bookmarks after
    each one — the watch must still deliver every event exactly once."""
    fake, url, server = api_server
    server.rv_prefix = "op-"
    server.send_bookmarks = True
    server.drop_after_each = True
    api = _client(url)
    stop = threading.Event()
    seen = []

    def consume():
        for ev in api.watch(kind="Pod", since_rv=0, stop=stop):
            seen.append((ev.type, ev.name, ev.resource_version))
            if len(seen) >= 3:
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    api.create({"kind": "Pod", "metadata": {"name": "q0", "labels": {}}})
    fake.set_pod_phase("q0", "Running")
    fake.set_pod_phase("q0", "Failed", reason="OOMKilled")
    t.join(timeout=8)
    stop.set()
    assert not t.is_alive()
    # every event delivered once, in order, despite per-event reconnects
    assert [s[0] for s in seen] == ["ADDED", "MODIFIED", "MODIFIED"]
    # opaque rvs surface as 0 in the int field (documented best-effort)
    assert [s[2] for s in seen] == [0, 0, 0]
    # and the resume tokens the server received were the verbatim opaque
    # strings it emitted, not re-parsed integers
    opaque = [rv for rv in server.seen_watch_rvs if rv.startswith("op-")]
    assert opaque, f"no opaque resume tokens seen: {server.seen_watch_rvs}"


@pytest.mark.slow
def test_pod_watcher_survives_410_by_relisting(api_server):
    """The full resume-by-relist loop: a watch whose rv fell out of the
    server's history window (410 Gone) must not kill the PodWatcher —
    it relists, re-delivers current state, and keeps following events
    (reference contract: k8s_watcher.py:219)."""
    fake, url, server = api_server
    api = _client(url)
    job = _job(replicas=1)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
        master_addr="10.0.0.1:8000",
    )
    jm = JobManager(num_workers=1, relaunch_budget=2, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)
    plan = ScalePlan()
    plan.worker_num = 1
    scaler.scale(plan)
    watcher.start()
    fake.set_pod_phase("demo-worker-0", "Running")
    _wait(
        lambda: jm.get_node(0).status == NodeStatus.RUNNING,
        msg="node running before the 410",
    )
    # expire the whole current history: the next reconnect 410s until
    # the relist loop picks up a fresh-enough rv
    server.drop_streams = True
    server.expire_below_rv = fake.latest_rv() + 1
    time.sleep(0.5)  # let the watcher hit the 410/relist path
    # state advances past the expiry window; only a live (relisted)
    # watcher can see the failure and relaunch
    server.drop_streams = False
    fake.set_pod_phase("demo-worker-0", "Failed", reason="OOMKilled")
    _wait(
        lambda: api.get("Pod", "demo-worker-0-r1") is not None,
        timeout=10.0,
        msg="relaunch after the watch expired and relisted",
    )
    assert jm.get_node(0).relaunch_count == 1
    watcher.stop()
    jm.stop()


def test_merged_watch_resumes_each_kind_from_its_own_rv(api_server):
    """k8s resourceVersions are opaque PER-COLLECTION tokens: after a
    relist, the multiplexed (kind=None) watch must hand the ElasticJob
    pump the ElasticJob collection's rv and the ScalePlan pump the
    ScalePlan collection's rv — never one collection's token to the
    other's watch (ADVICE r4: a real API server may 410-loop or
    mis-position a cross-kind token)."""
    fake, url, server = api_server
    api = _client(url)
    stop = threading.Event()
    tokens = {"ElasticJob": "ej-token-7", "ScalePlan": "sp-token-42"}

    def consume():
        for _ in api.watch(kind=None, since_rv=tokens, stop=stop):
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    _wait(
        lambda: len(server.seen_watch_kind_rvs) >= 2,
        msg="both pumps opened their watch",
    )
    stop.set()
    opened = dict(server.seen_watch_kind_rvs[:2])
    assert opened == tokens, server.seen_watch_kind_rvs
