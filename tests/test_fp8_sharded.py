"""fp8 delayed scaling THROUGH the ZeRO-1 sharded update (ISSUE 6).

The contract under test (train/train_step.py + parallel/sharding.py +
ops/fp8.py):

- The ``cfg.fp8`` gate on ``resolve_update_sharding`` is LIFTED for
  pure-dp meshes: the delayed-scaling state threads the shard_map
  manual region as an explicit argument, per-rank updated histories
  merge with ``lax.pmax`` over dp — the same all-reduce-max the
  replicated program runs, so the sharded rollout's fp8 state is
  BITWISE identical to the replicated one.
- Once-per-step semantics: every microbatch of a grad-accum step
  quantizes against the SAME step-start scales; the per-microbatch
  updated histories max-merge in the scan carry; each optimizer step
  advances every history by exactly ONE slot. Consequences pinned
  below: forward-operand histories (amax_x/amax_w) are bitwise
  IDENTICAL across grad_accum settings, and the gradient history's
  new slot scales exactly linearly with accum (the per-microbatch
  loss denominator is the microbatch token count, so cotangents are
  a× larger — the history tracks the actually-quantized magnitudes).
- HLO shape: gradients still leave the backward as bucketed
  reduce-scatters (never a full-gradient all-reduce), the module
  really quantizes (f8e4m3/f8e5m2 converts), and on pre-fp8 backends
  no DOT consumes f8 operands — the recipe runs through bf16 upcasts
  of the already-quantized values (identical numerics, ops/fp8.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models.config import get_config
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.train import train_step as ts
from dlrover_tpu.train.train_step import (
    TrainStepBuilder,
    init_train_state,
    resolve_update_sharding,
)

DP = 8


def fp8_cfg(**kw):
    kw.setdefault("dtype", "float32")
    kw.setdefault("fp8", True)
    return get_config(
        "tiny",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=128,
        max_seq=32,
        **kw,
    )


def dp_mesh():
    return build_mesh(MeshConfig(dp=-1))


def comm_cfg(**kw):
    kw.setdefault("bucket_mb", 0.05)
    return shd.CommConfig(update_sharding=True, **kw)


def batches(n, batch=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        base = rng.randint(0, vocab, size=(batch, 33))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }


def assert_trees_equal(a, b, msg=""):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), msg)


# ---------------------------------------------------------------------------
# Gate: fp8 composes with the sharded update on pure-dp meshes
# ---------------------------------------------------------------------------


def test_gate_lifted_on_pure_dp():
    active, reason, plan = resolve_update_sharding(
        fp8_cfg(), dp_mesh(), optax.adamw(1e-3), comm_cfg()
    )
    assert active and reason is None and plan is not None


def test_fallback_logged_once_per_config(monkeypatch):
    """A fallback reason warns ONCE per (reason, config) — the trainer
    rebuilds steps every cadence change, and re-warning buries real
    warnings; repeats ride update_sharding_reason instead. (Handler
    attached by hand: common.log loggers set propagate=False, so
    caplog's root-logger hook never sees them.)"""
    import logging

    monkeypatch.setattr(ts, "_LOGGED_FALLBACKS", set())
    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    grab = Grab()
    ts.logger.addHandler(grab)
    try:
        cfg = fp8_cfg(n_experts=2)  # MoE gate still refuses
        for _ in range(3):
            active, reason, _ = resolve_update_sharding(
                cfg, dp_mesh(), optax.adamw(1e-3), comm_cfg()
            )
    finally:
        ts.logger.removeHandler(grab)
    assert not active and "MoE" in reason
    hits = [m for m in records if "falling back" in m]
    assert len(hits) == 1, hits


# ---------------------------------------------------------------------------
# HLO guards (one compile, several assertions)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def compiled_fp8_sharded():
    cfg = fp8_cfg()
    mesh = dp_mesh()
    b = TrainStepBuilder(cfg, mesh, optax.adamw(1e-3), comm=comm_cfg())
    assert b.update_sharding, b.update_sharding_reason
    state = init_train_state(
        jax.random.key(0), cfg, mesh, b.optimizer, comm=b.comm_resolved
    )
    batch = next(batches(1))
    lowered = jax.jit(b.step_fn).lower(state, batch)
    return b, state, batch, lowered.as_text(), lowered.compile()


def test_hlo_quantizes_and_reduce_scatters(compiled_fp8_sharded):
    # function-local: bench is the benchmark entry script (see
    # test_marker_lint's bench-import rule)
    from bench import collective_stats

    _, _, _, lowered_text, compiled = compiled_fp8_sharded
    low = lowered_text.lower()
    assert "f8e4m3" in low, "forward operands never quantize to e4m3"
    assert "f8e5m2" in low, "gradients never quantize to e5m2"
    counts = collective_stats(compiled.as_text())["counts"]
    assert (
        counts.get("reduce-scatter", 0) + counts.get("all-to-all", 0) > 0
    ), counts
    assert counts.get("all-gather", 0) > 0, counts


def test_hlo_no_full_gradient_all_reduce(compiled_fp8_sharded):
    """Same guard as the bf16 suite, now with fp8 state in the carry:
    any surviving all-reduce must be scalar-ish (loss, denom) or
    amax-history-sized (the pmax merge) — never gradient-sized."""
    import re

    b, _, _, _, compiled = compiled_fp8_sharded
    n_params = b._plan.total
    for line in compiled.as_text().splitlines():
        if " = " not in line:
            continue
        rhs = line.split(" = ", 1)[1]
        if "all-reduce(" not in rhs:
            continue
        head = rhs.split("all-reduce(", 1)[0]
        elems = sum(
            int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
            for _, dims in re.findall(r"(f32|bf16)\[([0-9,]*)\]", head)
        )
        assert elems < n_params // 2, (
            f"full-gradient-sized all-reduce survived: {line.strip()[:160]}"
        )


def test_cpu_dots_never_consume_f8(compiled_fp8_sharded):
    """On a pre-fp8 backend the OPTIMIZED module must upcast the
    quantized values before every dot — an f8-operand dot here means
    the bf16 fallback broke (XLA:CPU would either reject it or run a
    slow emulation)."""
    _, _, _, _, compiled = compiled_fp8_sharded
    for line in compiled.as_text().splitlines():
        low = line.lower()
        if "dot(" not in low and "dot-general" not in low:
            continue
        assert "f8e4m3" not in low and "f8e5m2" not in low, (
            f"f8-operand dot on a pre-fp8 backend: {line.strip()[:160]}"
        )


def test_native_lowering_feeds_f8_dots():
    """``native=True`` (what the capability table resolves on v6e+)
    lowers to dots whose OPERANDS are f8 — the MXU consumes the
    quantized values directly. Lower-only: pre-fp8 backends need not
    compile it."""
    from dlrover_tpu.ops import fp8

    x = jnp.ones((16, 32), jnp.bfloat16)
    w = jnp.ones((32, 8), jnp.bfloat16)
    st = fp8.init_fp8_state()
    text = (
        jax.jit(lambda x, w, st: fp8.fp8_dot(x, w, st, native=True))
        .lower(x, w, st)
        .as_text()
        .lower()
    )
    hit = False
    for line in text.splitlines():
        if "dot_general" in line or "dot(" in line:
            hit = hit or ("f8e4m3" in line)
    assert hit, "native=True never lowered an f8-operand dot"


# ---------------------------------------------------------------------------
# Once-per-step amax semantics (pinned against the unfused/unaccumulated
# paths) and parity rollouts
# ---------------------------------------------------------------------------


def _run(cfg, mesh, steps=1, accum=1, comm=None, seed=0, batch=16):
    b = TrainStepBuilder(
        cfg, mesh, optax.adamw(1e-3), grad_accum=accum, comm=comm
    )
    if comm is not None:
        assert b.update_sharding, b.update_sharding_reason
    state = init_train_state(
        jax.random.key(0), cfg, mesh, b.optimizer, comm=b.comm_resolved
    )
    step = jax.jit(b.step_fn)
    m = None
    for bt in batches(steps, batch=batch, seed=seed):
        state, m = step(state, bt)
    return state, m


@pytest.mark.slow
def test_amax_advances_once_per_step_under_accum():
    """grad_accum must NOT multiply history pushes. Pins: (a) one slot
    per optimizer step regardless of accum — the init-ones prefix
    shifts out one slot per step; (b) forward-operand histories are
    BITWISE independent of accum (same params, same step-start scales,
    same data ⇒ same amax, regardless of how the batch is split);
    (c) the gradient history's new slot is EXACTLY accum× the
    unaccumulated one (per-microbatch denom ⇒ a× cotangents; ×2 is
    exact in f32)."""
    cfg, mesh = fp8_cfg(), dp_mesh()
    s1, _ = _run(cfg, mesh, steps=1, accum=1)
    s2, _ = _run(cfg, mesh, steps=1, accum=2)
    for k in s1["fp8"]:
        h1, h2 = s1["fp8"][k], s2["fp8"][k]
        # (a) exactly one push: every slot but the last is still the
        # init value (ones), for both runs
        for h in (h1, h2):
            assert np.allclose(np.asarray(h["amax_x"])[..., :-1], 1.0)
            assert np.allclose(np.asarray(h["amax_g"])[..., :-1], 1.0)
        # (b) forward-operand amax is accum-invariant, bitwise
        np.testing.assert_array_equal(
            np.asarray(h1["amax_x"]), np.asarray(h2["amax_x"]), k
        )
        np.testing.assert_array_equal(
            np.asarray(h1["amax_w"]), np.asarray(h2["amax_w"]), k
        )
        # (c) gradient amax scales exactly with accum
        np.testing.assert_array_equal(
            2.0 * np.asarray(h1["amax_g"])[..., -1],
            np.asarray(h2["amax_g"])[..., -1],
            k,
        )


@pytest.mark.slow
def test_fused_block_matches_sequential_fp8():
    """The fused K-step block threads the fp8 state through its scan
    carry: a K=2 block walks the same trajectory as two separate
    step_fn dispatches. Pinned at ulp-scale tolerance, not bitwise —
    the scan body and the standalone step compile as different modules,
    so fusion boundaries differ by 1 ulp from step 2 on (same artifact
    class as test_update_sharding's documented ones); a state-threading
    BUG would show as a whole missing/doubled amax push, orders of
    magnitude above this bar."""
    cfg, mesh = fp8_cfg(), dp_mesh()
    b = TrainStepBuilder(cfg, mesh, optax.adamw(1e-3))
    seq_state = init_train_state(jax.random.key(0), cfg, mesh, b.optimizer)
    blk_state = init_train_state(jax.random.key(0), cfg, mesh, b.optimizer)
    bts = list(batches(2))
    step = jax.jit(b.step_fn)
    seq_losses = []
    for bt in bts:
        seq_state, m = step(seq_state, bt)
        seq_losses.append(float(m["loss"]))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *bts)
    blk_state, bm = b.build_block()(blk_state, stacked)
    np.testing.assert_allclose(
        np.asarray(jnp.ravel(bm["loss"]), np.float32),
        np.asarray(seq_losses, np.float32),
        rtol=1e-6,
    )
    for k in seq_state["fp8"]:
        for h in ("amax_x", "amax_w", "amax_g"):
            a = np.asarray(seq_state["fp8"][k][h])
            bb = np.asarray(blk_state["fp8"][k][h])
            # one push per step: exactly K slots moved off the init ones
            assert np.allclose(a[..., :-2], 1.0) and np.allclose(
                bb[..., :-2], 1.0
            ), (k, h)
            np.testing.assert_allclose(a, bb, rtol=1e-5, err_msg=f"{k}/{h}")


@pytest.mark.slow
def test_sharded_rollout_matches_replicated():
    """The acceptance bar: a 3-step fp8 rollout under ZeRO-1 update
    sharding reproduces the replicated update — losses agree, and the
    delayed-scaling state is BITWISE identical (the pmax merge is the
    replicated program's all-reduce-max). Params carry only the known
    tied-embedding 1-ulp fusion artifact (test_update_sharding's
    docstring: worst rel grows to ~2.5e-3 by step 6; ~3e-5 at step 3
    here), pinned at 1e-3."""
    cfg, mesh = fp8_cfg(), dp_mesh()
    sr = mr = ss = ms = None
    sr, mr = _run(cfg, mesh, steps=3)
    ss, ms = _run(cfg, mesh, steps=3, comm=comm_cfg())
    assert abs(float(mr["loss"]) - float(ms["loss"])) < 1e-6
    assert_trees_equal(sr["fp8"], ss["fp8"], "fp8 state diverged")
    for x, y in zip(
        jax.tree.leaves(sr["params"]), jax.tree.leaves(ss["params"])
    ):
        x, y = np.asarray(x), np.asarray(y)
        rel = np.max(np.abs(x - y) / np.maximum(np.abs(x), 1e-6))
        assert rel < 1e-3, rel


@pytest.mark.slow
def test_sharded_accum_matches_replicated():
    """fp8 + grad_accum + ZeRO-1 all at once: the scan carry's
    max-merge composes with the manual region's pmax merge."""
    cfg, mesh = fp8_cfg(), dp_mesh()
    sr, mr = _run(cfg, mesh, steps=2, accum=2)
    ss, ms = _run(cfg, mesh, steps=2, accum=2, comm=comm_cfg())
    assert abs(float(mr["loss"]) - float(ms["loss"])) < 2e-6
    assert_trees_equal(sr["fp8"], ss["fp8"], "fp8 state diverged")
