"""Continuous-batching engine parity (serving/engine.py).

The acceptance pin: N mixed-length concurrent requests through the
continuous-batching engine produce sequences BITWISE equal to running
each request alone through ``generate.greedy`` (bf16 page mode), and
length-equal within quantization tolerance in int8 mode. The engine's
chunked prefill, per-slot positions, paged gather/scatter and fixed
decode batch must all be invisible to the math.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.serving.engine import ServingEngine  # noqa: E402
from dlrover_tpu.serving.scheduler import Scheduler  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, 32, size=n)) for n in (3, 7, 5, 11, 2)]
    max_new = [6, 4, 8, 5, 7]
    refs = [
        [
            int(t)
            for t in np.asarray(
                generate.greedy(
                    params, cfg, jnp.asarray([p], jnp.int32), m
                )[0]
            )
        ]
        for p, m in zip(prompts, max_new)
    ]
    return cfg, params, prompts, max_new, refs


def _serve_all(cfg, params, prompts, max_new, mode, paged=True):
    sched = Scheduler(replica="t")
    eng = ServingEngine(
        params, cfg, sched, n_slots=3, max_len=32, page_size=4,
        mode=mode, prefill_chunk=4, paged=paged,
    )
    reqs = [sched.submit(p, m) for p, m in zip(prompts, max_new)]
    eng.drain(timeout=600)
    outs = [r.future.result(timeout=5) for r in reqs]
    return eng, outs


@pytest.mark.parametrize("paged", [True, False])
def test_bf16_concurrent_mixed_lengths_bitwise_equal_greedy(setup, paged):
    cfg, params, prompts, max_new, refs = setup
    eng, outs = _serve_all(cfg, params, prompts, max_new, "bf16", paged)
    assert outs == refs
    # everything drained: slots empty, all pages back on the free list
    assert eng.active_slots() == 0
    assert eng.alloc.free_pages == eng.geom.n_pages - 1
    assert eng.stats()["tokens_generated"] == sum(max_new)
    assert eng.stats()["decode_kernel"] == ("paged" if paged else "gather")


@pytest.mark.parametrize("paged", [True, False])
def test_int8_concurrent_mixed_lengths_within_tolerance(setup, paged):
    cfg, params, prompts, max_new, refs = setup
    _, outs = _serve_all(cfg, params, prompts, max_new, "int8", paged)
    for out, ref, p in zip(outs, refs, prompts):
        assert len(out) == len(ref)
        assert out[: len(p)] == ref[: len(p)]  # prompt echoed verbatim
    # int8 KV is lossy per token but must not derail generation wholesale:
    # the vast majority of greedy tokens survive quantization
    total = sum(m for m in max_new)
    agree = sum(
        o == r
        for out, ref in zip(outs, refs)
        for o, r in zip(out, ref)
    ) - sum(len(p) for p in prompts)
    assert agree >= int(0.75 * total), (agree, total)


def test_eos_stops_early_and_frees_slot(setup):
    cfg, params, prompts, max_new, refs = setup
    p, ref = prompts[0], refs[0]
    eos = ref[len(p) + 2]  # the third generated token of the reference
    sched = Scheduler(replica="t2")
    eng = ServingEngine(
        params, cfg, sched, n_slots=1, max_len=32, page_size=4,
        mode="bf16", prefill_chunk=4,
    )
    r = sched.submit(p, max_new[0], eos_id=eos)
    eng.drain(timeout=600)
    assert r.future.result(timeout=5) == ref[: len(p) + 3]
    assert eng.alloc.free_pages == eng.geom.n_pages - 1


def test_oversize_request_fails_fast(setup):
    cfg, params, *_ = setup
    sched = Scheduler(replica="t3")
    eng = ServingEngine(
        params, cfg, sched, n_slots=1, max_len=16, page_size=4,
        mode="bf16", prefill_chunk=4,
    )
    r = sched.submit(list(range(1, 15)), 10)  # 24 tokens > 16 capacity
    eng.step()
    with pytest.raises(ValueError):
        r.future.result(timeout=5)
    assert eng.active_slots() == 0


def test_unaligned_prefill_chunk_rejected(setup):
    cfg, params, *_ = setup
    sched = Scheduler(replica="t4")
    with pytest.raises(ValueError, match="multiple of prefill_chunk"):
        ServingEngine(
            params, cfg, sched, n_slots=1, max_len=16, page_size=4,
            mode="bf16", prefill_chunk=3,
        )


def _decode_hlo(eng, max_pages):
    """Lowered HLO text of the engine's jitted decode step at its own
    input structure (3 slots, bucketed page walk)."""
    import jax.numpy as jnp

    b = eng.n_slots
    tables = jnp.asarray(eng.alloc.block_tables())
    return eng._decode_fn.lower(
        eng.params, eng.pools, tables,
        jnp.zeros(b, jnp.int32), jnp.zeros(b, jnp.int32),
        jnp.zeros(b, bool), jnp.zeros((b, 2), jnp.uint32),
        jnp.zeros(b, jnp.float32), jnp.zeros(b, jnp.int32),
        jnp.ones(b, jnp.float32), max_pages,
    ).as_text()


def test_paged_decode_hlo_has_no_contiguous_cache(setup):
    """The structural guarantee behind the traffic model: the traced
    paged decode step contains NO tensor shaped like the dense
    ``[L, B, S_max, Hkv, D]`` cache the gather engine materializes.
    The gather engine's trace is the positive control — the guard
    string does catch that tensor when it exists."""
    cfg, params, *_ = setup
    kw = dict(n_slots=3, max_len=48, page_size=4, mode="bf16",
              prefill_chunk=4)
    paged_eng = ServingEngine(
        params, cfg, Scheduler(replica="h1"), paged=True, **kw
    )
    gather_eng = ServingEngine(
        params, cfg, Scheduler(replica="h2"), paged=False, **kw
    )
    geom = paged_eng.geom
    # StableHLO prints shapes as tensor<2x3x48x4x8xbf16>: any
    # ...x S_max x Hkv x D x... dims are a dense-cache-width tensor
    dense = f"x{geom.max_len}x{geom.kv_heads}x{geom.head_dim}x"
    # the L-leading [L, B, S_max, Hkv, D] cache the gather step scans
    lb_dense = (
        f"{geom.n_layers}x3x{geom.max_len}"
        f"x{geom.kv_heads}x{geom.head_dim}"
    )
    # at the engine's bucketed walk (4 of 12 pages held): nothing
    # S_max wide exists in the trace at all
    assert dense not in _decode_hlo(paged_eng, 4)
    # even at the full table width the paged step never concatenates
    # layers into the dense cache (its per-layer views live inside the
    # scan and are W·page_size wide, not L-leading)
    assert lb_dense not in _decode_hlo(paged_eng, geom.max_pages_per_slot)
    gather_text = _decode_hlo(gather_eng, geom.max_pages_per_slot)
    assert dense in gather_text and lb_dense in gather_text


def test_device_tables_reship_only_on_dirty(setup):
    """The block-table device array is cached across steps and
    re-shipped only when the allocator mutates (admit/grow/evict)."""
    cfg, params, *_ = setup
    eng = ServingEngine(
        params, cfg, Scheduler(replica="t5"), n_slots=2, max_len=16,
        page_size=4, mode="bf16", prefill_chunk=4,
    )
    t1 = eng._device_tables()
    t2 = eng._device_tables()
    assert t2 is t1 and eng.stats()["table_ships"] == 1
    eng.alloc.admit(0, 5)
    t3 = eng._device_tables()
    assert t3 is not t1 and eng.stats()["table_ships"] == 2
    assert eng._device_tables() is t3
    eng.alloc.ensure(0, 9)  # grows by a page → dirty
    eng._device_tables()
    eng.alloc.evict(0)
    eng._device_tables()
    assert eng.stats()["table_ships"] == 4
