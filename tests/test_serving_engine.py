"""Continuous-batching engine parity (serving/engine.py).

The acceptance pin: N mixed-length concurrent requests through the
continuous-batching engine produce sequences BITWISE equal to running
each request alone through ``generate.greedy`` (bf16 page mode), and
length-equal within quantization tolerance in int8 mode. The engine's
chunked prefill, per-slot positions, paged gather/scatter and fixed
decode batch must all be invisible to the math.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.serving.engine import ServingEngine  # noqa: E402
from dlrover_tpu.serving.scheduler import Scheduler  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, 32, size=n)) for n in (3, 7, 5, 11, 2)]
    max_new = [6, 4, 8, 5, 7]
    refs = [
        [
            int(t)
            for t in np.asarray(
                generate.greedy(
                    params, cfg, jnp.asarray([p], jnp.int32), m
                )[0]
            )
        ]
        for p, m in zip(prompts, max_new)
    ]
    return cfg, params, prompts, max_new, refs


def _serve_all(cfg, params, prompts, max_new, mode):
    sched = Scheduler(replica="t")
    eng = ServingEngine(
        params, cfg, sched, n_slots=3, max_len=32, page_size=4,
        mode=mode, prefill_chunk=4,
    )
    reqs = [sched.submit(p, m) for p, m in zip(prompts, max_new)]
    eng.drain(timeout=600)
    outs = [r.future.result(timeout=5) for r in reqs]
    return eng, outs


def test_bf16_concurrent_mixed_lengths_bitwise_equal_greedy(setup):
    cfg, params, prompts, max_new, refs = setup
    eng, outs = _serve_all(cfg, params, prompts, max_new, "bf16")
    assert outs == refs
    # everything drained: slots empty, all pages back on the free list
    assert eng.active_slots() == 0
    assert eng.alloc.free_pages == eng.geom.n_pages - 1
    assert eng.stats()["tokens_generated"] == sum(max_new)


def test_int8_concurrent_mixed_lengths_within_tolerance(setup):
    cfg, params, prompts, max_new, refs = setup
    _, outs = _serve_all(cfg, params, prompts, max_new, "int8")
    for out, ref, p in zip(outs, refs, prompts):
        assert len(out) == len(ref)
        assert out[: len(p)] == ref[: len(p)]  # prompt echoed verbatim
    # int8 KV is lossy per token but must not derail generation wholesale:
    # the vast majority of greedy tokens survive quantization
    total = sum(m for m in max_new)
    agree = sum(
        o == r
        for out, ref in zip(outs, refs)
        for o, r in zip(out, ref)
    ) - sum(len(p) for p in prompts)
    assert agree >= int(0.75 * total), (agree, total)


def test_eos_stops_early_and_frees_slot(setup):
    cfg, params, prompts, max_new, refs = setup
    p, ref = prompts[0], refs[0]
    eos = ref[len(p) + 2]  # the third generated token of the reference
    sched = Scheduler(replica="t2")
    eng = ServingEngine(
        params, cfg, sched, n_slots=1, max_len=32, page_size=4,
        mode="bf16", prefill_chunk=4,
    )
    r = sched.submit(p, max_new[0], eos_id=eos)
    eng.drain(timeout=600)
    assert r.future.result(timeout=5) == ref[: len(p) + 3]
    assert eng.alloc.free_pages == eng.geom.n_pages - 1


def test_oversize_request_fails_fast(setup):
    cfg, params, *_ = setup
    sched = Scheduler(replica="t3")
    eng = ServingEngine(
        params, cfg, sched, n_slots=1, max_len=16, page_size=4,
        mode="bf16", prefill_chunk=4,
    )
    r = sched.submit(list(range(1, 15)), 10)  # 24 tokens > 16 capacity
    eng.step()
    with pytest.raises(ValueError):
        r.future.result(timeout=5)
    assert eng.active_slots() == 0


def test_unaligned_prefill_chunk_rejected(setup):
    cfg, params, *_ = setup
    sched = Scheduler(replica="t4")
    with pytest.raises(ValueError, match="multiple of prefill_chunk"):
        ServingEngine(
            params, cfg, sched, n_slots=1, max_len=16, page_size=4,
            mode="bf16", prefill_chunk=3,
        )
