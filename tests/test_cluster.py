"""Cluster scheduler analog tests: CRDs, slice scaler, brain service.

Reference behaviors: go/operator ElasticJob/ScalePlan CRDs, PodScaler,
go/brain optimize algorithms.
"""

import pytest
import yaml

from dlrover_tpu.cluster import (
    BrainService,
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    SliceScaler,
    TPUSliceSpec,
)
from dlrover_tpu.cluster.brain import JobMetrics, MetricsStore
from dlrover_tpu.cluster.scaler import snap_to_slices
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.node_manager import ScalePlan


def _job(hosts_per_slice=4, min_hosts=4, max_hosts=16):
    return ElasticJob(
        name="gpt-train",
        spec=ElasticJobSpec(
            min_hosts=min_hosts,
            max_hosts=max_hosts,
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=min_hosts,
                    command=["python", "train.py"],
                    slice=TPUSliceSpec(
                        accelerator="tpu-v5p-slice",
                        topology="2x2x1",
                        chips_per_host=4,
                        hosts_per_slice=hosts_per_slice,
                    ),
                )
            },
        ),
    )


def test_elasticjob_manifest_shape():
    m = _job().to_manifest()
    assert m["kind"] == "ElasticJob"
    tpl = m["spec"]["replicaSpecs"]["worker"]["template"]
    sel = tpl["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5p-slice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x2x1"
    req = tpl["spec"]["containers"][0]["resources"]["requests"]
    assert req["google.com/tpu"] == "4"
    # yaml renders round-trip
    assert yaml.safe_load(_job().render_yaml())["kind"] == "ElasticJob"


def test_snap_to_slices():
    assert snap_to_slices(5, 4) == 8
    assert snap_to_slices(8, 4) == 8
    assert snap_to_slices(0, 4, minimum=4) == 4
    assert snap_to_slices(3, 1) == 3


def test_scaler_creates_slice_aligned_pods():
    created, deleted = [], []
    scaler = SliceScaler(
        _job(),
        submit_fn=created.append,
        delete_fn=deleted.append,
        master_addr="10.0.0.2:5001",
    )
    plan = ScalePlan()
    plan.worker_num = 5  # snaps up to 8 (2 slices)
    scaler.scale(plan)
    assert len(created) == 8
    assert scaler.live_hosts == list(range(8))
    # slice index annotated for ICI-aware rendezvous
    labels = created[5]["metadata"]["labels"]
    assert labels["elasticjob.dlrover/slice-index"] == "1"
    env = {
        e["name"]: e["value"]
        for e in created[0]["spec"]["containers"][0]["env"]
    }
    assert env["DLROVER_TPU_MASTER_ADDR"] == "10.0.0.2:5001"
    assert env["DLROVER_TPU_HOSTS_PER_SLICE"] == "4"

    # scale in to one slice: drops the highest-indexed hosts
    plan2 = ScalePlan()
    plan2.worker_num = 4
    scaler.scale(plan2)
    assert len(deleted) == 4
    assert scaler.live_hosts == [0, 1, 2, 3]


def test_scaler_respects_max_hosts():
    created = []
    scaler = SliceScaler(_job(max_hosts=8), submit_fn=created.append)
    plan = ScalePlan()
    plan.worker_num = 100
    scaler.scale(plan)
    assert len(created) == 8


def test_scaler_remove_specific_node():
    created, deleted = [], []
    scaler = SliceScaler(
        _job(hosts_per_slice=1, min_hosts=1),
        submit_fn=created.append,
        delete_fn=deleted.append,
    )
    plan = ScalePlan()
    plan.worker_num = 3
    scaler.scale(plan)
    plan2 = ScalePlan()
    plan2.remove_nodes = [Node(node_type="worker", node_id=1, name="w-1")]
    scaler.scale(plan2)
    assert deleted == ["gpt-train-worker-1"]
    assert scaler.live_hosts == [0, 2]


def test_scale_plan_crd_render():
    scaler = SliceScaler(_job())
    plan = ScalePlan()
    plan.worker_num = 6
    crd = scaler.to_scale_plan_crd(plan)
    m = crd.to_manifest()
    assert m["kind"] == "ScalePlan"
    assert m["spec"]["replicaCounts"]["worker"] == 8  # snapped
    assert m["spec"]["ownerJob"] == "gpt-train"


def test_brain_algorithm_registry_and_chains():
    """optalgorithm analog: named algorithms, per-stage chains, plan
    merging (later algorithms fill unset fields / add hints)."""
    from dlrover_tpu.cluster.brain import (
        get_algorithm,
        register_algorithm,
    )
    from dlrover_tpu.master.resource_optimizer import ResourcePlan

    with pytest.raises(ValueError, match="unknown brain algorithm"):
        get_algorithm("nope")

    @register_algorithm("test_fixed_three")
    def fixed(svc, stats):
        p = ResourcePlan()
        p.worker_num = 3
        return p

    brain = BrainService(
        stage_chains={"running": ["test_fixed_three", "job_ps_oom_resource"]}
    )
    plan = brain.generate_plan(
        "running",
        {
            "ps_mem_used_bytes": 9.0e9,
            "ps_mem_cap_bytes": 10.0e9,
            "ps_num": 2,
        },
    )
    # both algorithms contributed: count from the first, ps hint merged
    assert plan.worker_num == 3
    assert plan.node_resources["ps"]["num"] == 3


def test_brain_create_oom_memory_hint():
    store = MetricsStore()
    for i in range(4):
        store.append(
            JobMetrics(
                job_name=f"j{i}",
                job_kind="dlrm",
                worker_num=4,
                samples_per_sec=100.0,
                finished=True,
                oom=(i < 2),  # half the history OOMed
            )
        )
    brain = BrainService(store)
    brain.bind_job("new", "dlrm")
    plan = brain.generate_plan("create", {})
    assert plan.node_resources["worker"]["memory_scale"] == 1.5


def test_brain_hot_ps_rebalance_weights():
    brain = BrainService()
    brain.bind_job("j", "dlrm")
    plan = brain.generate_plan(
        "running",
        {"ps_shard_qps": {"ps0": 1000.0, "ps1": 100.0, "ps2": 100.0}},
    )
    w = plan.node_resources["ps"]["weights"]
    # the hot shard gets the smallest weight
    assert w["ps0"] < w["ps1"] and w["ps0"] < w["ps2"]
    # balanced traffic → no rebalance plan
    plan2 = brain.generate_plan(
        "running", {"ps_shard_qps": {"ps0": 100.0, "ps1": 110.0}}
    )
    assert "ps" not in plan2.node_resources


def test_weighted_hrw_shifts_load_boundedly():
    """Weighted rendezvous hashing: lowering one server's weight only
    moves keys OFF that server (bounded migration), and the moved
    fraction tracks the weight change."""
    from dlrover_tpu.sparse.partition import (
        assign_servers,
        migration_plan,
        partition_keys,
    )

    keys = list(range(30000))
    servers = ["ps0", "ps1", "ps2"]
    eq = {s: 1.0 for s in servers}
    base = partition_keys(keys, servers, eq)
    sizes = {s: len(v) for s, v in base.items()}
    # roughly balanced at equal weights
    assert max(sizes.values()) < 1.3 * min(sizes.values())

    cooled = dict(eq, ps0=0.5)
    moved = migration_plan(keys, servers, servers, eq, cooled)
    # every move originates from the cooled server
    assert moved and all(src == "ps0" for _, src, _ in moved)
    after = partition_keys(keys, servers, cooled)
    assert len(after["ps0"]) < 0.7 * sizes["ps0"]


def test_brain_first_allocation_from_history(tmp_path):
    store = MetricsStore(str(tmp_path / "metrics.jsonl"))
    # historical finished jobs of the same kind at different sizes:
    # 8 workers had the best per-worker throughput
    for n, sps in ((4, 40.0), (8, 96.0), (16, 128.0)):
        store.append(
            JobMetrics(
                job_name=f"old-{n}",
                job_kind="gpt-pretrain",
                worker_num=n,
                samples_per_sec=sps,
                finished=True,
            )
        )
    brain = BrainService(store, min_workers=1, max_workers=64)
    brain.bind_job("new-job", "gpt-pretrain")
    plan = brain.generate_plan("create", {})
    assert plan.worker_num == 8
    # persists across restarts (jsonl reload)
    store2 = MetricsStore(str(tmp_path / "metrics.jsonl"))
    assert len(store2.kind_rows("gpt-pretrain")) == 3


def test_brain_oom_bumps_memory_not_count():
    brain = BrainService()
    brain.bind_job("j", "k")
    plan = brain.generate_plan("running", {"oom": True, "worker_num": 4})
    assert plan.worker_num is None
    assert plan.node_resources["worker"]["memory_scale"] == 1.5


def test_brain_grows_then_shrinks_on_poor_scaling():
    brain = BrainService(node_unit=2, max_workers=16, min_workers=2)
    brain.bind_job("j", "k")
    # healthy: no smaller config observed → grow by node_unit
    brain.persist_metrics(
        JobMetrics(job_name="j", worker_num=4, steps_per_sec=10.0)
    )
    plan = brain.generate_plan(
        "running", {"worker_num": 4, "steps_per_sec": 10.0}
    )
    assert plan.worker_num == 6
    # poor scaling: 8 workers barely faster than 4 → shrink
    brain.persist_metrics(
        JobMetrics(job_name="j", worker_num=8, steps_per_sec=11.0)
    )
    plan2 = brain.generate_plan(
        "running", {"worker_num": 8, "steps_per_sec": 11.0}
    )
    assert plan2.worker_num == 6  # 8 − node_unit


def test_scaler_max_cap_rounds_down_to_slices():
    # max_hosts=6 with 4 hosts/slice: cap is 4 (one whole slice), never 8
    created = []
    scaler = SliceScaler(
        _job(max_hosts=6, min_hosts=4), submit_fn=created.append
    )
    plan = ScalePlan()
    plan.worker_num = 100
    scaler.scale(plan)
    assert len(created) == 4
    crd = scaler.to_scale_plan_crd(plan)
    assert crd.to_manifest()["spec"]["replicaCounts"]["worker"] == 4


def test_brain_clamp_respects_min_after_unit_snap():
    brain = BrainService(min_workers=3, node_unit=2, max_workers=16)
    assert brain._clamp(3) == 4  # not 2


def test_brain_does_not_regrow_into_known_bad_size():
    brain = BrainService(node_unit=2, max_workers=16, min_workers=2)
    brain.bind_job("j", "k")
    brain.persist_metrics(
        JobMetrics(job_name="j", worker_num=4, steps_per_sec=10.0)
    )
    brain.persist_metrics(
        JobMetrics(job_name="j", worker_num=8, steps_per_sec=9.0)
    )
    # currently at 6 (after a shrink): 8 workers was SLOWER than 6
    # (eff (9/10.5)·(6/8) ≈ 0.64 < 0.7) → hold, don't thrash back up
    plan = brain.generate_plan(
        "running", {"worker_num": 6, "steps_per_sec": 10.5}
    )
    assert plan.worker_num is None


# ---------------------------------------------------------------------------
# Brain over the wire (VERDICT r3 #4): a standalone brain process shared
# across jobs, reached through BrainClient — the reference's go/brain
# gRPC deployment (proto/brain.proto:196-199) + brain_optimizer.py.
# ---------------------------------------------------------------------------


def _brain_proc(q, store_path):
    from dlrover_tpu.cluster.brain import (
        BrainService,
        BrainWireServer,
        MetricsStore,
    )

    server = BrainWireServer(
        BrainService(
            store=MetricsStore(store_path), min_workers=1, max_workers=8
        ),
        port=0,
    )
    q.put(server.port)
    import time as _t

    while True:
        _t.sleep(0.5)


@pytest.fixture()
def brain_process(tmp_path):
    import multiprocessing as mp
    import os
    import signal

    # spawn, NOT fork: a fork child inherits pytest's signal handlers
    # (its SIGTERM handler swallows terminate()), leaving an immortal
    # child that multiprocessing's atexit join then waits on FOREVER —
    # the suite hangs at shutdown. A spawned interpreter has default
    # handlers and dies on terminate like it should.
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    proc = ctx.Process(
        target=_brain_proc, args=(q, str(tmp_path / "brain.jsonl")),
        daemon=True,
    )
    proc.start()
    port = q.get(timeout=30)
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.join(timeout=5)
    if proc.is_alive():  # belt and braces: never leave it joinable
        os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=5)


def test_brain_wire_roundtrip_separate_process(brain_process):
    """persist_metrics / get_job_metrics / optimize against a brain
    living in ANOTHER process: cold-start first-allocation comes back
    from same-kind history over the wire."""
    from dlrover_tpu.cluster.brain import BrainClient

    client = BrainClient(brain_process)
    # two finished runs of kind "deepfm": 4 workers scaled best
    for n, sps in ((2, 100.0), (4, 360.0)):
        assert client.persist_metrics(
            JobMetrics(
                job_name=f"old-{n}",
                job_kind="deepfm",
                worker_num=n,
                samples_per_sec=sps,
                finished=True,
            )
        )
    rows = client.get_job_metrics("old-4")
    assert len(rows) == 1 and rows[0].worker_num == 4
    client.bind_job("fresh-job", "deepfm")
    plan = client.generate_plan("create", {})
    assert plan.worker_num == 4  # 360/4 > 100/2 per-worker
    client.close()


def test_brain_client_degrades_to_empty_plan_when_unreachable():
    from dlrover_tpu.cluster.brain import BrainClient

    client = BrainClient("127.0.0.1:1", timeout_s=0.5)
    client._t._retries = 1  # keep the failure fast
    plan = client.generate_plan("running", {"worker_num": 2})
    assert plan.empty()
    client.close()


def test_master_optimize_mode_cluster_uses_brain(brain_process):
    """optimize_mode=cluster wires the auto-scaler's optimizer to the
    remote brain (reference: resource/brain_optimizer.py); plans flow
    over the wire from the shared store."""
    from dlrover_tpu.cluster.brain import BrainClient
    from dlrover_tpu.master.master import DistributedJobMaster

    # seed history through a second client (another "job"'s master)
    seeder = BrainClient(brain_process)
    seeder.persist_metrics(
        JobMetrics(
            job_name="prev",
            job_kind="gpt",
            worker_num=2,
            samples_per_sec=500.0,
            finished=True,
        )
    )
    seeder.close()
    master = DistributedJobMaster(
        num_workers=1,
        max_workers=4,
        optimize_mode="cluster",
        brain_addr=brain_process,
        job_name="this-job",
        job_kind="gpt",
    )
    try:
        assert isinstance(master.auto_scaler.optimizer, BrainClient)
        plan = master.auto_scaler.optimizer.generate_plan("create", {})
        assert plan.worker_num == 2
    finally:
        master.server.stop()
        master.metrics_server.stop()

    with pytest.raises(ValueError, match="brain_addr"):
        DistributedJobMaster(
            num_workers=1, max_workers=4, optimize_mode="cluster"
        )
