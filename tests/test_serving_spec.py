"""Speculative decoding pins (serving/engine.py spec path).

The tentpole guarantee: with ``spec_k > 0`` the engine's OUTPUT STREAM
is bitwise the spec-off stream — greedy spec-on equals offline
``generate.greedy`` on both kernel paths, and int8 spec-on equals int8
spec-off (commit-timing independence: the verify step shows a query its
own chunk row raw and earlier rows as-committed, exactly like the
sequential loop). Acceptance only moves throughput, never the math:
an oracle draft accepts everything, an always-wrong draft accepts
nothing, and in both cases the emitted tokens are identical. Rejected
draft rows NEVER reach the KV pools — pool cells beyond the committed
length stay byte-identical across a verify step.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.serving.engine import (  # noqa: E402
    DraftModel,
    PromptLookupDraft,
    ServingEngine,
)
from dlrover_tpu.serving.scheduler import Scheduler  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    # repetitive prompts: prompt-lookup finds trailing n-grams, so the
    # accept-rate is non-trivially exercised (not just all-reject)
    prompts = [
        [1, 2, 3, 1, 2, 3, 1],
        [5, 6, 5, 6, 5, 6, 5, 6, 5],
        [7, 8, 9, 7, 8],
    ]
    max_new = [8, 6, 7]
    refs = [
        [
            int(t)
            for t in np.asarray(
                generate.greedy(
                    params, cfg, jnp.asarray([p], jnp.int32), m
                )[0]
            )
        ]
        for p, m in zip(prompts, max_new)
    ]
    return cfg, params, prompts, max_new, refs


def _serve_all(cfg, params, prompts, max_new, mode, paged, spec_k,
               draft=None):
    sched = Scheduler(replica="spec")
    eng = ServingEngine(
        params, cfg, sched, n_slots=2, max_len=32, page_size=4,
        mode=mode, prefill_chunk=4, paged=paged, spec_k=spec_k,
        draft=draft,
    )
    reqs = [sched.submit(p, m) for p, m in zip(prompts, max_new)]
    eng.drain(timeout=600)
    outs = [r.future.result(timeout=5) for r in reqs]
    return eng, outs


# paged stays fast as the tier-1 pin; the gather path covers the same
# property and runs on the slow tier (870s budget — see _SLOW_LEDGER)
@pytest.mark.parametrize("paged", [
    pytest.param(True),
    pytest.param(False, marks=pytest.mark.slow),
])
def test_greedy_spec_on_bitwise_equal_greedy(setup, paged):
    """Spec-on greedy == offline per-request greedy, bitwise, with
    mixed-length concurrent requests on both kernel paths."""
    cfg, params, prompts, max_new, refs = setup
    eng, outs = _serve_all(
        cfg, params, prompts, max_new, "bf16", paged, spec_k=3
    )
    assert outs == refs
    st = eng.stats()
    # drafting actually happened (repetitive prompts guarantee
    # proposals) and the bookkeeping is coherent
    assert st["spec_k"] == 3 and st["draft_tokens"] > 0
    assert 0 <= st["accepted_tokens"] <= st["draft_tokens"]
    assert st["tokens_generated"] == sum(max_new)
    # drained clean: no slot leaks pages regardless of accept pattern
    assert eng.active_slots() == 0
    assert eng.alloc.free_pages == eng.geom.n_pages - 1


@pytest.mark.slow
@pytest.mark.parametrize("paged", [True, False])
def test_int8_spec_on_equals_spec_off(setup, paged):
    """Quantized mode: spec-on must still equal spec-off BITWISE —
    the verify step reproduces the sequential loop's commit timing
    (earlier chunk rows seen post-codec, own row raw)."""
    cfg, params, prompts, max_new, _ = setup
    _, off = _serve_all(
        cfg, params, prompts, max_new, "int8", paged, spec_k=0
    )
    _, on = _serve_all(
        cfg, params, prompts, max_new, "int8", paged, spec_k=3
    )
    assert on == off


class _OracleDraft(DraftModel):
    """Proposes the true greedy continuation (looked up from the
    reference sequences) — every draft token must be accepted."""

    def __init__(self, refs):
        self.refs = [list(r) for r in refs]

    def propose(self, history, k):
        hist = [int(t) for t in history]
        for ref in self.refs:
            if ref[: len(hist)] == hist:
                return ref[len(hist): len(hist) + k]
        return []


class _WrongDraft(DraftModel):
    """Proposes a constant token chosen OUTSIDE the reference
    continuations — every draft token must be rejected."""

    def __init__(self, token):
        self.token = int(token)

    def propose(self, history, k):
        return [self.token] * k


def _unused_token(refs, prompts, vocab):
    used = {t for r in refs for t in r}
    for t in range(vocab - 1, 0, -1):
        if t not in used:
            return t
    raise AssertionError("tiny vocab saturated; enlarge it")


@pytest.mark.slow
def test_oracle_draft_accepts_everything(setup):
    cfg, params, prompts, max_new, refs = setup
    eng, outs = _serve_all(
        cfg, params, prompts, max_new, "bf16", True, spec_k=3,
        draft=_OracleDraft(refs),
    )
    assert outs == refs
    st = eng.stats()
    assert st["draft_tokens"] > 0
    assert st["accepted_tokens"] == st["draft_tokens"]
    assert st["spec_accept_rate"] == 1.0


@pytest.mark.slow
def test_wrong_draft_rejects_everything_same_output(setup):
    cfg, params, prompts, max_new, refs = setup
    bad = _unused_token(refs, prompts, cfg.vocab_size)
    eng, outs = _serve_all(
        cfg, params, prompts, max_new, "bf16", True, spec_k=3,
        draft=_WrongDraft(bad),
    )
    assert outs == refs  # guaranteed >= 1 token of progress per step
    st = eng.stats()
    assert st["draft_tokens"] > 0 and st["accepted_tokens"] == 0
    assert st["spec_accept_rate"] == 0.0


@pytest.mark.slow
def test_rejected_draft_rows_never_reach_pools(setup):
    """The deferred-write invariant, observed directly: across a verify
    step with all drafts rejected, every pool cell of the slot BEYOND
    the newly committed row is byte-identical to before the step, and
    the slot's page reservation never grows."""
    cfg, params, prompts, max_new, refs = setup
    prompt, m, ref = prompts[0], max_new[0], refs[0]
    bad = _unused_token([ref], [prompt], cfg.vocab_size)
    sched = Scheduler(replica="spec-inv")
    eng = ServingEngine(
        params, cfg, sched, n_slots=1, max_len=32, page_size=4,
        mode="bf16", prefill_chunk=4, paged=True, spec_k=3,
        draft=_WrongDraft(bad),
    )
    r = sched.submit(prompt, m)
    # admit + prefill, then stop at the first decode boundary
    while eng.slots[0] is None or eng.slots[0].phase != "decode":
        assert eng.step()
    ps = eng.geom.page_size
    total = len(prompt) + m
    pages0 = eng.alloc.slot_pages(0)

    def cell(pools, pos):
        table = eng.alloc.block_tables()[0]
        return {
            n: np.asarray(a[:, table[pos // ps], pos % ps])
            for n, a in pools.items()
        }

    while eng.slots[0] is not None:
        n_before = len(eng.slots[0].generated)
        if n_before >= m:
            eng.step()  # final eviction only, no token progress
            break
        frontier = len(prompt) + n_before  # first not-yet-written row
        pre = [cell(eng.pools, p) for p in range(frontier, total)]
        assert eng.step()
        s = eng.slots[0]
        n_after = len(s.generated) if s is not None else m
        # all-wrong drafts: exactly one token of progress per step,
        # so rows past the single committed one were verify scratch
        assert n_after == n_before + 1
        assert eng.alloc.slot_pages(0) == pages0
        post = [cell(eng.pools, p) for p in range(frontier, total)]
        for pos, (a, b) in enumerate(zip(pre[1:], post[1:])):
            for name in a:
                np.testing.assert_array_equal(
                    a[name], b[name],
                    err_msg=f"rejected draft leaked into pool row "
                            f"{frontier + 1 + pos} ({name})",
                )
    assert r.future.result(timeout=5) == ref


def test_prompt_lookup_draft_unit():
    d = PromptLookupDraft(max_ngram=3, min_ngram=1)
    # trailing [1,2,3] recurs earlier; propose what followed it
    assert d.propose([1, 2, 3, 9, 8, 1, 2, 3], 2) == [9, 8]
    # longest n-gram wins over shorter, more recent matches
    assert d.propose([5, 1, 2, 3, 7, 2, 3, 1, 2, 3], 1) == [7]
    # most recent earlier occurrence preferred within one n
    assert d.propose([4, 6, 4, 5, 4], 1) == [5]
    # no recurrence → no proposal; k caps the continuation
    assert d.propose([1, 2, 3, 4, 5], 3) == []
    assert d.propose([1, 2, 1, 2, 1], 8) == [2, 1]
    assert d.propose([1, 2, 3], 0) == []
    assert d.propose([], 4) == []
    with pytest.raises(ValueError):
        PromptLookupDraft(max_ngram=0)


@pytest.mark.slow
def test_spec_counters_flow_to_serving_record(setup):
    cfg, params, prompts, max_new, _ = setup
    eng, _ = _serve_all(
        cfg, params, prompts, max_new, "bf16", True, spec_k=3
    )
    sched = Scheduler(replica="spec-rec")
    rec = sched.publish(eng.stats())
    assert rec.draft_tokens == eng.stats()["draft_tokens"] > 0
    assert rec.accepted_tokens == eng.stats()["accepted_tokens"]
    assert rec.spec_accept_rate == pytest.approx(
        eng.stats()["spec_accept_rate"]
    )


def test_spec_with_max_new_one_falls_back_to_decode(setup):
    """k_eff = min(spec_k, remaining - 1): a 1-token request never
    drafts (nothing to speculate past the last token) and still matches
    the reference."""
    cfg, params, prompts, _, _ = setup
    p = prompts[0]
    ref = [
        int(t) for t in np.asarray(
            generate.greedy(params, cfg, jnp.asarray([p], jnp.int32), 1)[0]
        )
    ]
    eng, outs = _serve_all(cfg, params, [p], [1], "bf16", True, spec_k=3)
    assert outs == [ref]
    assert eng.stats()["draft_tokens"] == 0
