"""Interpret-mode parity for the fused norm kernels (ops/pallas_norm.py).

The kernels only compile on TPU; ``interpret=True`` runs the same
kernel bodies through the pallas interpreter on the CPU mesh, so the
grid/BlockSpec plumbing, the in-kernel f32 statistics, the fused
residual add, and both custom_vjp backward kernels are exercised here
— against the jnp reference that IS the production fallback (and the
decoder's ``_norm`` math).

Tolerances: f32 cases compare at a few ulp (the kernel reduces by
sum/d where the reference uses mean — same value, different op order);
bf16 cases at 1-2 bf16 ulp. The fused-residual summed stream is pinned
BITWISE: it is an input-dtype add in both implementations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops import pallas_norm


def _ref(x, scale, bias, kind, residual=None):
    eps = pallas_norm.RMS_EPS if kind == "rmsnorm" else pallas_norm.LN_EPS
    return pallas_norm._reference(
        x, scale, bias if kind == "layernorm" else None, kind, eps, residual
    )


def _make(kind, dt, d, with_res, seed=0):
    ks = jax.random.split(jax.random.key(seed), 4)
    x = jax.random.normal(ks[0], (2, 16, d), dt)
    s = (1.0 + 0.1 * jax.random.normal(ks[1], (d,))).astype(dt)
    b = (
        (0.1 * jax.random.normal(ks[2], (d,))).astype(dt)
        if kind == "layernorm"
        else None
    )
    res = jax.random.normal(ks[3], (2, 16, d), dt) if with_res else None
    return x, s, b, res


@pytest.mark.parametrize("kind", ["rmsnorm", "layernorm"])
@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
# 256 = clean lanes; 192/100 exercise the zero-pad-to-128 path (100 is
# the odd last-dim case: pad 28 lanes, slice them back off)
@pytest.mark.parametrize("d", [256, 192, 100])
@pytest.mark.parametrize("with_res", [False, True])
def test_forward_parity(kind, dt, d, with_res):
    tol = 2e-5 if dt == jnp.float32 else 2e-2
    x, s, b, res = _make(kind, dt, d, with_res)
    out_k = pallas_norm.norm(x, s, b, kind, residual=res, interpret=True)
    out_r = _ref(x, s, b, kind, residual=res)
    if with_res:
        np.testing.assert_allclose(
            np.asarray(out_k[0], np.float32),
            np.asarray(out_r[0], np.float32),
            rtol=tol, atol=tol,
        )
        # the summed stream is an input-dtype add in both paths: bitwise
        np.testing.assert_array_equal(
            np.asarray(out_k[1]), np.asarray(out_r[1])
        )
    else:
        np.testing.assert_allclose(
            np.asarray(out_k, np.float32),
            np.asarray(out_r, np.float32),
            rtol=tol, atol=tol,
        )


@pytest.mark.parametrize("kind", ["rmsnorm", "layernorm"])
@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("d", [256, 100])
@pytest.mark.parametrize("with_res", [False, True])
def test_grad_parity(kind, dt, d, with_res):
    """Backward kernels vs jnp autodiff: dx, dscale, dbias, dres —
    with distinct cotangents on the normed output and the summed
    stream, so the in-kernel gh fold is actually exercised."""
    x, s, b, res = _make(kind, dt, d, with_res, seed=3)

    def loss(fn):
        def go(x, s, b, res):
            o = fn(x, s, b, res)
            if with_res:
                return (o[0] * 1.3).sum() + (o[1] * 0.7).sum()
            return (o * 1.3).sum()

        return go

    k_fn = loss(
        lambda x, s, b, res: pallas_norm.norm(
            x, s, b, kind, residual=res, interpret=True
        )
    )
    r_fn = loss(lambda x, s, b, res: _ref(x, s, b, kind, residual=res))
    argn = [0, 1]
    if kind == "layernorm":
        argn.append(2)
    if with_res:
        argn.append(3)
    gk = jax.grad(k_fn, argnums=tuple(argn))(x, s, b, res)
    gr = jax.grad(r_fn, argnums=tuple(argn))(x, s, b, res)
    tol = 5e-5 if dt == jnp.float32 else 6e-2
    for a, (u, v) in zip(argn, zip(gk, gr)):
        np.testing.assert_allclose(
            np.asarray(u, np.float32),
            np.asarray(v, np.float32),
            rtol=tol, atol=tol,
            err_msg=f"grad argnum {a}",
        )


def test_untileable_rows_fall_back():
    """Row counts below the dtype's min sublane tile can't grid — the
    public entry must return the jnp reference, not crash."""
    x = jax.random.normal(jax.random.key(0), (1, 3, 128), jnp.bfloat16)
    s = jnp.ones((128,), jnp.bfloat16)
    out = pallas_norm.norm(x, s, None, "rmsnorm", interpret=True)
    ref = _ref(x, s, None, "rmsnorm")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_cpu_default_is_reference():
    """Without interpret and off-TPU, norm() must be the exact jnp
    reference — the gate that keeps untouched configs bitwise stable."""
    assert not pallas_norm.kernels_available(interpret=False)
    x = jax.random.normal(jax.random.key(1), (2, 8, 64), jnp.float32)
    s = jnp.ones((64,), jnp.float32)
    out = pallas_norm.norm(x, s, None, "rmsnorm", interpret=False)
    ref = _ref(x, s, None, "rmsnorm")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_unknown_kind_raises():
    x = jnp.ones((2, 2, 8))
    with pytest.raises(ValueError, match="unknown norm kind"):
        pallas_norm.norm(x, jnp.ones((8,)), None, "batchnorm")


@pytest.mark.slow
def test_decoder_fused_norm_matches_unfused():
    """End-to-end: a tiny decoder forward+grad with cfg.fused_norm=True
    (kernels in interpret mode) matches the default jnp build within
    f32 tolerance — the wiring in _layer_body/_norm_block, including
    the fused ln2 residual add, agrees with the reference program."""
    from dlrover_tpu.models import decoder, get_config

    prev = pallas_norm.INTERPRET
    pallas_norm.INTERPRET = True
    try:
        cfg_f = get_config("tiny", fused_norm=True, dtype="float32",
                           param_dtype="float32")
        cfg_r = get_config("tiny", fused_norm=False, dtype="float32",
                           param_dtype="float32")
        params = decoder.init(jax.random.key(0), cfg_f)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                    cfg_f.vocab_size)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

        def loss(cfg):
            def f(p):
                return decoder.loss_fn(p, batch, cfg)[0]

            return f

        lf, gf = jax.value_and_grad(loss(cfg_f))(params)
        lr, gr = jax.value_and_grad(loss(cfg_r))(params)
        np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gr)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )
    finally:
        pallas_norm.INTERPRET = prev
