"""Sparse embedding tier tests (C++ KvTable + group optimizers + JAX glue).

Mirrors the reference's gtest coverage for KvVariable
(tfplus/tfplus/kv_variable/kernels/kv_variable_test.cc) and the python op
tests in tfplus/py_ut, on the TPU-native surface.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.sparse import (
    EmbeddingCollection,
    EmbeddingSpec,
    GroupAdagrad,
    GroupAdam,
    KvTable,
    ScatterOp,
    SparseGroupFtrl,
    SparseMomentum,
    SparseSGD,
)
from dlrover_tpu.sparse.embedding import lookup_callback, take_rows


@pytest.fixture
def table():
    t = KvTable("t", 4, n_slots=2, initializer="zeros")
    yield t
    t.close()


class TestKvTable:
    def test_gather_or_zeros_missing(self, table):
        out = table.gather_or_zeros([1, 2, 3])
        assert out.shape == (3, 4)
        np.testing.assert_array_equal(out, 0.0)
        assert len(table) == 0  # gather_or_zeros must not insert

    def test_gather_or_insert_creates_and_counts(self, table):
        table.gather_or_insert([7, 8])
        assert len(table) == 2
        table.gather_or_insert([7])
        np.testing.assert_array_equal(table.frequency([7, 8, 99]), [2, 1, 0])

    def test_random_init_deterministic(self):
        a = KvTable("a", 8, n_slots=0, initializer="uniform", seed=42)
        b = KvTable("b", 8, n_slots=0, initializer="uniform", seed=42)
        ra = a.gather_or_insert([3, 5])
        rb = b.gather_or_insert([3, 5])
        np.testing.assert_array_equal(ra, rb)
        assert np.abs(ra).max() <= 0.05
        assert np.abs(ra).max() > 0  # actually random
        # different keys → different rows
        assert not np.array_equal(ra[0], ra[1])
        a.close(); b.close()

    def test_insert_and_scatter_ops(self, table):
        table.insert([1], np.full((1, 4), 2.0))
        table.scatter([1], np.full((1, 4), 3.0), ScatterOp.ADD)
        np.testing.assert_allclose(table.gather_or_zeros([1]), 5.0)
        table.scatter([1], np.full((1, 4), 2.0), ScatterOp.DIV)
        np.testing.assert_allclose(table.gather_or_zeros([1]), 2.5)
        table.scatter([1], np.full((1, 4), 1.0), ScatterOp.MIN)
        np.testing.assert_allclose(table.gather_or_zeros([1]), 1.0)
        table.scatter([1], np.full((1, 4), 9.0), ScatterOp.UPDATE)
        np.testing.assert_allclose(table.gather_or_zeros([1]), 9.0)

    def test_delete_and_ttl(self, table):
        table.gather_or_insert([1, 2], now_ts=100)
        table.gather_or_insert([3], now_ts=200)
        assert table.delete([1]) == 1
        assert len(table) == 2
        # TTL: evict keys last touched before ts=150
        assert table.delete_before_timestamp(150) == 1
        assert len(table) == 1
        assert table.gather_or_zeros([3]).shape == (1, 4)

    def test_slot_reuse_after_delete(self, table):
        table.insert([1], np.full((1, 4), 7.0))
        table.delete([1])
        table.gather_or_insert([2])  # should reuse slot, zero-initialized
        np.testing.assert_array_equal(table.gather_or_zeros([2]), 0.0)

    def test_export_import_full(self, table, tmp_path):
        keys = np.arange(10, dtype=np.int64)
        table.insert(keys, np.arange(40, dtype=np.float32).reshape(10, 4))
        path = str(tmp_path / "snap.npz")
        assert table.save(path) == 10
        other = KvTable("o", 4, n_slots=2, initializer="zeros")
        assert other.restore(path) == 10
        np.testing.assert_array_equal(
            other.gather_or_zeros(keys), table.gather_or_zeros(keys)
        )
        np.testing.assert_array_equal(other.timestamp(keys), table.timestamp(keys))
        other.close()

    def test_delta_export_incremental(self, table, tmp_path):
        """full-or-delta export parity (ops/kv_variable_ops.cc:576-680):
        delta contains only rows touched since the last export."""
        table.insert([1, 2, 3], np.ones((3, 4)))
        full = str(tmp_path / "full.npz")
        table.save(full)  # clears dirty bits
        table.insert([3], np.full((1, 4), 5.0))  # touch one row
        table.insert([9], np.full((1, 4), 9.0))  # new row
        delta = str(tmp_path / "delta.npz")
        assert table.save(delta, delta_only=True) == 2
        # restore full then delta elsewhere
        other = KvTable("o2", 4, n_slots=2, initializer="zeros")
        other.restore(full)
        other.restore(delta, clear_table=False)
        np.testing.assert_allclose(other.gather_or_zeros([3])[0], 5.0)
        np.testing.assert_allclose(other.gather_or_zeros([9])[0], 9.0)
        np.testing.assert_allclose(other.gather_or_zeros([1])[0], 1.0)
        assert len(other) == 4
        other.close()

    def test_delta_is_cumulative_since_full(self, table, tmp_path):
        """Overwriting the delta file between saves must lose nothing:
        each delta carries ALL changes since the last full snapshot."""
        table.insert([1, 2], np.ones((2, 4)))
        full = str(tmp_path / "full.npz")
        table.save(full)
        delta = str(tmp_path / "delta.npz")
        table.insert([3], np.full((1, 4), 3.0))
        assert table.save(delta, delta_only=True) == 1
        table.insert([4], np.full((1, 4), 4.0))
        # second delta OVERWRITES the first; key 3 must still be in it
        assert table.save(delta, delta_only=True) == 2
        other = KvTable("cum", 4, n_slots=2, initializer="zeros")
        other.restore(full)
        other.restore(delta, clear_table=False)
        np.testing.assert_allclose(other.gather_or_zeros([3])[0], 3.0)
        np.testing.assert_allclose(other.gather_or_zeros([4])[0], 4.0)
        assert len(other) == 4
        other.close()

    def test_delta_carries_deletions(self, table, tmp_path):
        """TTL eviction / deletes must survive a full+delta restore
        (the reference's full-or-delta export tracks deleted keys)."""
        table.insert([1, 2, 3], np.ones((3, 4)), now_ts=100)
        full = str(tmp_path / "full.npz")
        table.save(full)
        table.insert([9], np.full((1, 4), 9.0), now_ts=300)
        assert table.delete_before_timestamp(200) == 3  # evict 1,2,3
        delta = str(tmp_path / "delta.npz")
        table.save(delta, delta_only=True)
        other = KvTable("tomb", 4, n_slots=2, initializer="zeros")
        other.restore(full)
        other.restore(delta, clear_table=False)
        assert len(other) == 1  # 1,2,3 stay dead
        np.testing.assert_allclose(other.gather_or_zeros([1])[0], 0.0)
        np.testing.assert_allclose(other.gather_or_zeros([9])[0], 9.0)
        other.close()
        # a re-inserted key is not resurrection-deleted by the tombstone
        table.insert([2], np.full((1, 4), 2.0), now_ts=400)
        delta2 = str(tmp_path / "delta2.npz")
        table.save(delta2, delta_only=True)
        other2 = KvTable("tomb2", 4, n_slots=2, initializer="zeros")
        other2.restore(full)
        other2.restore(delta2, clear_table=False)
        np.testing.assert_allclose(other2.gather_or_zeros([2])[0], 2.0)
        assert len(other2) == 2  # keys 2 and 9
        other2.close()

    def test_delta_survives_restart_cycle(self, table, tmp_path):
        """Restored delta rows must stay dirty: after a crash+restore,
        the next cumulative delta still carries them."""
        table.insert([1], np.ones((1, 4)))
        full = str(tmp_path / "full.npz")
        table.save(full)
        table.insert([2], np.full((1, 4), 2.0))
        delta = str(tmp_path / "delta.npz")
        table.save(delta, delta_only=True)
        # "restart": fresh table restores full + delta
        t2 = KvTable("restart", 4, n_slots=2, initializer="zeros")
        t2.restore(full)
        t2.restore(delta, clear_table=False)
        # train on, touching only key 3; overwrite the delta file
        t2.insert([3], np.full((1, 4), 3.0))
        t2.save(delta, delta_only=True)
        # second restart: key 2 must still be recoverable from full+delta
        t3 = KvTable("restart2", 4, n_slots=2, initializer="zeros")
        t3.restore(full)
        t3.restore(delta, clear_table=False)
        np.testing.assert_allclose(t3.gather_or_zeros([2])[0], 2.0)
        np.testing.assert_allclose(t3.gather_or_zeros([3])[0], 3.0)
        t2.close(); t3.close()

    def test_gather_or_insert_rows_reach_delta(self, table, tmp_path):
        """Rows created by gather_or_insert (the train-path insert) must
        be dirty, else delta checkpoints silently drop new features."""
        table.save(str(tmp_path / "full.npz"))  # clears dirty
        table.gather_or_insert([7, 8])
        keys, _, _, _ = table.export(delta_only=True)
        assert set(keys.tolist()) == {7, 8}

    def test_export_capacity_bound(self, table):
        """kv_export never writes past the caller's buffer size."""
        import ctypes

        table.insert(np.arange(10, dtype=np.int64), np.ones((10, 4)))
        cap = 4
        keys = np.empty(cap, dtype=np.int64)
        values = np.empty((cap, table.width), dtype=np.float32)
        freqs = np.empty(cap, dtype=np.uint32)
        ts = np.empty(cap, dtype=np.uint32)
        written = int(table._lib.kv_export(
            table._h, 0, 0,
            table._ptr(keys, ctypes.c_int64),
            table._ptr(values, ctypes.c_float),
            table._ptr(freqs, ctypes.c_uint32),
            table._ptr(ts, ctypes.c_uint32),
            cap,
        ))
        assert written == cap

    def test_import_layout_mismatch_raises(self, table, tmp_path):
        table.insert([1], np.ones((1, 4)))
        path = str(tmp_path / "snap.npz")
        table.save(path)
        other = KvTable("o3", 8, n_slots=2)
        with pytest.raises(ValueError):
            other.restore(path)
        other.close()


class TestSparseOptimizers:
    def _numpy_adam(self, w, g, steps, lr=0.1, b1=0.9, b2=0.999, eps=1e-8):
        m = np.zeros_like(w); v = np.zeros_like(w)
        for t in range(1, steps + 1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            w = w - lr * mhat / (np.sqrt(vhat) + eps)
        return w

    def test_adam_matches_numpy(self):
        t = KvTable("adam", 6, n_slots=2, initializer="zeros")
        opt = GroupAdam(lr=0.1)
        g = np.linspace(-1, 1, 6, dtype=np.float32).reshape(1, 6)
        for _ in range(5):
            opt.apply(t, [42], g)
        expected = self._numpy_adam(np.zeros((1, 6), np.float32), g, 5)
        np.testing.assert_allclose(t.gather_or_zeros([42]), expected, atol=1e-5)
        t.close()

    def test_adagrad_matches_numpy(self):
        t = KvTable("ag", 4, n_slots=1, initializer="zeros")
        opt = GroupAdagrad(lr=0.5)
        g = np.full((1, 4), 2.0, dtype=np.float32)
        acc = np.zeros((1, 4)); w = np.zeros((1, 4))
        for _ in range(3):
            opt.apply(t, [1], g)
            acc += g * g
            w -= 0.5 * g / (np.sqrt(acc) + 1e-10)
        np.testing.assert_allclose(t.gather_or_zeros([1]), w, atol=1e-6)
        t.close()

    def test_sgd_and_momentum(self):
        t = KvTable("sgd", 4, n_slots=1, initializer="zeros")
        SparseSGD(lr=1.0).apply(t, [1], np.ones((1, 4)))
        np.testing.assert_allclose(t.gather_or_zeros([1]), -1.0)
        t2 = KvTable("mom", 4, n_slots=1, initializer="zeros")
        opt = SparseMomentum(lr=1.0, momentum=0.5)
        opt.apply(t2, [1], np.ones((1, 4)))
        opt.apply(t2, [1], np.ones((1, 4)))
        # buf: 1 then 1.5 → w = -(1 + 1.5) = -2.5
        np.testing.assert_allclose(t2.gather_or_zeros([1]), -2.5)
        t.close(); t2.close()

    def test_ftrl_l1_gives_exact_zeros(self):
        t = KvTable("ftrl", 4, n_slots=2, initializer="zeros")
        opt = SparseGroupFtrl(lr=0.5, l1=10.0)  # huge l1 → everything clips
        opt.apply(t, [1], np.full((1, 4), 0.1, dtype=np.float32))
        np.testing.assert_array_equal(t.gather_or_zeros([1]), 0.0)
        t.close()

    def test_group_lasso_zeroes_whole_row(self):
        t = KvTable("gl", 4, n_slots=2, initializer="zeros")
        opt = GroupAdam(lr=0.01, l21=100.0)  # brutal group penalty
        opt.apply(t, [1], np.full((1, 4), 0.5, dtype=np.float32))
        np.testing.assert_array_equal(t.gather_or_zeros([1]), 0.0)
        t.close()

    def test_enter_threshold_gates_updates(self):
        """Low-frequency admission: keys below enter_threshold keep their
        value under optimizer updates (reference freq filtering)."""
        t = KvTable("thr", 4, n_slots=2, initializer="zeros",
                    enter_threshold=3)
        opt = SparseSGD(lr=1.0)
        applied = opt.apply(t, [5], np.ones((1, 4)))
        assert applied == 0
        np.testing.assert_array_equal(t.gather_or_zeros([5]), 0.0)
        # bump frequency past the threshold → updates apply
        t.increase_count([5], 5)
        assert opt.apply(t, [5], np.ones((1, 4))) == 1
        np.testing.assert_allclose(t.gather_or_zeros([5]), -1.0)
        t.close()

    def test_slot_mismatch_raises(self):
        t = KvTable("sm", 4, n_slots=1)
        with pytest.raises(ValueError):
            GroupAdam().apply(t, [1], np.ones((1, 4)))
        t.close()


class TestEmbeddingCollection:
    def test_pull_step_push_learns(self):
        """End-to-end: jitted regression step over host-pulled rows; the
        host-side GroupAdam must drive the loss down."""
        coll = EmbeddingCollection(
            [EmbeddingSpec("feat", dim=4, initializer="zeros")],
            optimizer=GroupAdam(lr=0.05),
        )
        ids = np.array([[3, 7], [3, 11]], dtype=np.int64)  # dup key 3
        target = jnp.ones((2,), dtype=jnp.float32)

        @jax.jit
        def step(rows, inverse, target):
            def loss_fn(rows):
                emb = take_rows(rows, inverse)   # [2, 2, 4]
                pred = emb.sum(axis=(1, 2))
                return jnp.mean((pred - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(rows)
            return loss, grads

        losses = []
        for _ in range(60):
            dev, host = coll.pull({"feat": ids})
            rows, inverse = dev["feat"]
            loss, gr = step(rows, inverse, target)
            coll.push(host, {"feat": gr})
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.05
        coll.close()

    def test_per_table_optimizer_steps(self):
        """One optimizer over two tables: each table's bias correction
        must see its own step count, not the interleaved total."""
        from dlrover_tpu.sparse.kv_table import GroupAdam, KvTable

        shared = GroupAdam(lr=0.1)
        solo = GroupAdam(lr=0.1)
        ta = KvTable("ta", 4, n_slots=2, initializer="zeros")
        tb = KvTable("tb", 4, n_slots=2, initializer="zeros")
        tc = KvTable("tc", 4, n_slots=2, initializer="zeros")
        g = np.full((1, 4), 0.5, dtype=np.float32)
        for _ in range(3):
            shared.apply(ta, [1], g)   # interleaved: ta, tb, ta, tb, ...
            shared.apply(tb, [1], g)
            solo.apply(tc, [1], g)     # tc sees steps 1,2,3
        np.testing.assert_allclose(
            ta.gather_or_zeros([1]), tc.gather_or_zeros([1]), rtol=1e-6
        )
        np.testing.assert_allclose(
            tb.gather_or_zeros([1]), tc.gather_or_zeros([1]), rtol=1e-6
        )
        assert shared.state_dict()["steps"] == {"ta": 3, "tb": 3}
        for t in (ta, tb, tc):
            t.close()

    def test_pull_frozen_does_not_mutate(self):
        coll = EmbeddingCollection([EmbeddingSpec("f", dim=4)])
        coll.pull({"f": np.array([1, 2])})
        n0 = len(coll.tables["f"])
        f0 = coll.tables["f"].frequency([1, 2]).copy()
        dev = coll.pull_frozen({"f": np.array([1, 2, 777])})
        rows, inv = dev["f"]
        assert len(coll.tables["f"]) == n0          # no insert of 777
        np.testing.assert_array_equal(
            coll.tables["f"].frequency([1, 2]), f0  # no freq bump
        )
        # unseen id gets the cold-start zero row
        np.testing.assert_allclose(np.asarray(rows)[int(inv[2])], 0.0)
        coll.close()

    def test_save_restore_roundtrip(self, tmp_path):
        coll = EmbeddingCollection([EmbeddingSpec("f", dim=4)])
        coll.pull({"f": np.array([1, 2, 3])})
        coll.save(str(tmp_path))
        vals = coll.tables["f"].gather_or_zeros([1, 2, 3])
        coll2 = EmbeddingCollection([EmbeddingSpec("f", dim=4)])
        coll2.restore(str(tmp_path))
        np.testing.assert_array_equal(
            coll2.tables["f"].gather_or_zeros([1, 2, 3]), vals
        )
        coll.close(); coll2.close()

    def test_lookup_callback_in_jit(self):
        t = KvTable("cb", 4, n_slots=0, initializer="zeros")
        t.insert([5], np.full((1, 4), 2.0))

        @jax.jit
        def f(ids):
            return lookup_callback(t, ids).sum(axis=-1)

        out = f(jnp.array([[5, 6]], dtype=jnp.int64))
        np.testing.assert_allclose(np.asarray(out), [[8.0, 0.0]])
        t.close()


class TestTieredTable:
    """Hybrid storage: hot KvTable + cold file tier.

    Reference behaviors: hybrid_embedding TableManager + StorageTable."""

    def _tiered(self, tmp_path, dim=4):
        from dlrover_tpu.sparse.kv_table import KvTable
        from dlrover_tpu.sparse.tiered import FileColdStore, TieredTable

        table = KvTable("tier_t", dim=dim, n_slots=0)
        cold = FileColdStore(str(tmp_path / "cold"), width=dim)
        return TieredTable(table, cold), table, cold

    def test_demote_then_fault_back(self, tmp_path):
        import numpy as np

        tiered, hot, cold = self._tiered(tmp_path)
        keys = np.array([1, 2, 3], dtype=np.int64)
        rows = tiered.gather_or_insert(keys, now_ts=100)
        assert tiered.hot_size == 3 and tiered.cold_size == 0

        # keys 1,2 go stale; key 3 stays warm
        hot.insert([3], rows[2:3], now_ts=500)
        moved = tiered.demote_before_timestamp(400)
        assert moved == 2
        assert tiered.hot_size == 1 and tiered.cold_size == 2
        assert len(tiered) == 3

        # lookup faults the cold rows back with identical values
        back = tiered.gather_or_insert(keys, now_ts=600)
        np.testing.assert_allclose(back, rows, rtol=1e-6)
        assert tiered.cold_size == 0 and tiered.hot_size == 3

    def test_cold_store_survives_restart(self, tmp_path):
        import numpy as np

        from dlrover_tpu.sparse.tiered import FileColdStore

        cold = FileColdStore(str(tmp_path / "c"), width=2)
        cold.put(
            np.array([7, 9]),
            np.array([[1.0, 2.0], [3.0, 4.0]], np.float32),
            np.array([5, 6], np.uint32),
            np.array([10, 11], np.uint32),
        )
        cold2 = FileColdStore(str(tmp_path / "c"), width=2)
        found, values, freqs, ts = cold2.get(np.array([9, 8]))
        assert found.tolist() == [True, False]
        np.testing.assert_allclose(values[0], [3.0, 4.0])
        assert freqs[0] == 6 and ts[0] == 11

    def test_new_keys_skip_cold_lookup(self, tmp_path):
        import numpy as np

        tiered, _, cold = self._tiered(tmp_path)
        out = tiered.gather_or_zeros(np.array([42], dtype=np.int64))
        np.testing.assert_array_equal(out, np.zeros((1, 4), np.float32))
        assert tiered.cold_size == 0

    def test_width_mismatch_rejected_and_slots_roundtrip(self, tmp_path):
        import numpy as np

        from dlrover_tpu.sparse.kv_table import GroupAdam, KvTable
        from dlrover_tpu.sparse.tiered import FileColdStore, TieredTable

        table = KvTable("tier_slots", dim=4, n_slots=2)  # Adam m+v slots
        with pytest.raises(ValueError, match="width"):
            TieredTable(table, FileColdStore(str(tmp_path / "bad"), width=4))
        tiered = TieredTable(
            table, FileColdStore(str(tmp_path / "ok"), width=table.width)
        )
        keys = np.array([11, 12], dtype=np.int64)
        tiered.gather_or_insert(keys, now_ts=10)
        opt = GroupAdam(lr=0.1)
        opt.apply(table, keys, np.ones((2, 4), np.float32), now_ts=20)
        rows_before = table.gather_full(keys)
        assert tiered.demote_before_timestamp(100) == 2
        back = tiered.gather_or_insert(keys, now_ts=200)
        # full rows (values + optimizer slots) survive the round-trip
        np.testing.assert_allclose(
            np.asarray(table.gather_full(keys)),
            np.asarray(rows_before),
            rtol=1e-6,
        )
        assert back.shape == (2, 4)

    def test_demotion_sweep_touches_o_stale_rows(self, tmp_path):
        """Regression pin for the incremental sweep: row I/O is bounded
        by the STALE candidate count — a big warm working set costs the
        sweep nothing, and the hot table is never exported."""
        import numpy as np

        tiered, hot, _ = self._tiered(tmp_path)
        stale_keys = np.arange(1000, 1005, dtype=np.int64)
        warm_keys = np.arange(100, dtype=np.int64)
        tiered.gather_or_insert(stale_keys, now_ts=10)
        tiered.gather_or_insert(warm_keys, now_ts=1000)

        counts = {"gather_full": 0, "timestamp": 0, "frequency": 0}
        orig = {m: getattr(hot, m) for m in counts}

        def _wrap(m):
            def inner(keys):
                counts[m] += int(np.asarray(keys).size)
                return orig[m](keys)
            return inner

        for m in counts:
            setattr(hot, m, _wrap(m))

        def _no_export(*a, **kw):
            raise AssertionError("sweep must not export the hot table")

        hot.export = _no_export
        try:
            moved = tiered.demote_before_timestamp(500)
        finally:
            for m in counts:
                setattr(hot, m, orig[m])
            del hot.export
        assert moved == 5
        # O(stale), not O(hot): only the 5 stale candidates were read
        assert counts["gather_full"] == 5
        assert counts["timestamp"] == 5
        assert counts["frequency"] == 5
        assert tiered.hot_size == 100 and tiered.cold_size == 5

    def test_frozen_gather_promotions_stay_demotable(self, tmp_path):
        """Rows promoted by a FROZEN gather (the serve path — it never
        records touches itself) must re-enter the touch ring at
        promotion time, or they could never be demoted again."""
        import numpy as np

        tiered, _, _ = self._tiered(tmp_path)
        keys = np.array([1, 2, 3], dtype=np.int64)
        rows = tiered.gather_or_insert(keys, now_ts=100)
        assert tiered.demote_before_timestamp(200) == 3
        # frozen fault-back (gather_or_zeros = pull_frozen path); the
        # promotion stamps wall-clock time, so sweep with a max threshold
        back = tiered.gather_or_zeros(keys)
        np.testing.assert_allclose(back, rows, rtol=1e-6)
        assert tiered.cold_size == 0
        # the promotion recorded the touch: a later sweep spills again
        assert tiered.demote_before_timestamp(2**60) == 3
        assert tiered.cold_size == 3

    def test_frozen_gather_retries_past_racing_demotion(self, tmp_path):
        """Read/demote race regression: a sweep running cold.put →
        hot.delete between the residency check and the lock-free hot
        read must not turn a trained row into zeros — the reader sees
        the demotion epoch moved and retries the fault path."""
        import numpy as np

        tiered, hot, _ = self._tiered(tmp_path)
        keys = np.array([1, 2, 3], dtype=np.int64)
        rows = tiered.gather_or_insert(keys, now_ts=100)

        orig = hot.gather_or_zeros
        fired = []

        def racing_gather(k):
            # the sweep lands exactly in the race window (first read
            # only): after _fault_in saw the keys resident, before the
            # hot gather runs
            if not fired:
                fired.append(True)
                assert tiered.demote_before_timestamp(2**60) == 3
            return orig(k)

        hot.gather_or_zeros = racing_gather
        try:
            out = tiered.gather_or_zeros(keys)
        finally:
            hot.gather_or_zeros = orig
        np.testing.assert_allclose(out, rows, rtol=1e-6)
        assert tiered.cold_size == 0  # retried fault promoted them back

    def test_train_gather_fences_out_racing_demotion(self, tmp_path):
        """gather_or_insert's insert side effect can't be fixed by a
        retry, so it takes the begin_update fence: the touch lands
        before the hot read, and a sweep racing in re-reads the ring
        post-claim, sees the keys fresh, and backs off — no fresh init
        row is inserted over (and later demoted over) the real row."""
        import numpy as np

        tiered, hot, _ = self._tiered(tmp_path)
        keys = np.array([1, 2, 3], dtype=np.int64)
        rows = tiered.gather_or_insert(keys, now_ts=100)

        orig = hot.gather_or_insert
        moved = []

        def racing_gather(k, now_ts=None):
            # sweep cutoff beats the keys' OLD touches (100) but not the
            # in-flight read's touch (300): pre-fence it spilled the
            # rows and the gather re-inserted fresh init rows over them
            if not moved:
                moved.append(tiered.demote_before_timestamp(200))
            return orig(k, now_ts=now_ts)

        hot.gather_or_insert = racing_gather
        try:
            out = tiered.gather_or_insert(keys, now_ts=300)
        finally:
            hot.gather_or_insert = orig
        assert moved == [0]  # the sweep saw fresh touches and backed off
        np.testing.assert_allclose(out, rows, rtol=1e-6)
        assert tiered.cold_size == 0

    def test_concurrent_faults_promote_each_key_once(self, tmp_path):
        """Promotion-epoch concurrency: N threads faulting the same cold
        keys cost ONE cold read per key — the first fault claims, racers
        wait on the claimant's event — and every thread sees the exact
        row values."""
        import threading

        import numpy as np

        tiered, _, cold = self._tiered(tmp_path)
        keys = np.arange(20, dtype=np.int64)
        rows = tiered.gather_or_insert(keys, now_ts=10)
        assert tiered.demote_before_timestamp(100) == 20

        hit_keys = []
        orig_get = cold.get

        def counting_get(k):
            res = orig_get(k)
            # a racer whose residency check lost to a finished promotion
            # may re-read an already-moved key and find nothing; the
            # invariant is one SUCCESSFUL cold row fetch per key
            hit_keys.extend(np.asarray(k)[res[0]].tolist())
            return res

        cold.get = counting_get
        results, errors = [None] * 8, []
        barrier = threading.Barrier(8)

        def fault(i):
            try:
                barrier.wait()
                results[i] = tiered.gather_or_zeros(keys)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=fault, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        cold.get = orig_get
        assert not errors
        # each key's row left the cold tier exactly once across threads
        assert sorted(hit_keys) == keys.tolist()
        assert tiered.stats.snapshot()["cold_faults"] == 20
        for r in results:
            np.testing.assert_allclose(r, rows, rtol=1e-6)

    def test_int8_codec_roundtrip_and_resident_bytes(self, tmp_path):
        """codec="int8" cuts resident payload bytes ~4x vs f32 with
        block-scaled quantization error, survives restart (the on-disk
        base stays f32), and the default f32 codec stays exact."""
        import numpy as np

        from dlrover_tpu.sparse.tiered import FileColdStore

        width = 32
        rng = np.random.default_rng(0)
        keys = np.arange(64, dtype=np.int64)
        rows = rng.normal(size=(64, width)).astype(np.float32)
        freqs = np.arange(64, dtype=np.uint32)
        ts = np.arange(100, 164, dtype=np.uint32)

        f32 = FileColdStore(str(tmp_path / "f32"), width=width)
        f32.put(keys, rows, freqs, ts)
        _, exact, _, _ = f32.get(keys)
        np.testing.assert_array_equal(exact, rows)  # f32 path is exact

        q8 = FileColdStore(
            str(tmp_path / "q8"), width=width, codec="int8"
        )
        q8.put(keys, rows, freqs, ts)
        found, deq, gfr, gts = q8.get(keys)
        assert found.all()
        np.testing.assert_array_equal(gfr, freqs)
        np.testing.assert_array_equal(gts, ts)
        # block-scaled error bound: one scale step per element
        step = np.abs(rows).max() / 127.0
        assert np.abs(deq - rows).max() <= step + 1e-7
        # the measurable win: int8 payloads hold ~1 byte/elem + scales
        assert q8.resident_bytes < f32.resident_bytes / 2
        # restart replays the f32 WAL/base into the SAME quantized form
        q8.flush()
        q8b = FileColdStore(
            str(tmp_path / "q8"), width=width, codec="int8"
        )
        _, deq2, _, _ = q8b.get(keys)
        np.testing.assert_array_equal(deq2, deq)
        # and an f32 reader loads the int8-written base unchanged
        # (the on-disk format is codec-independent)
        f32b = FileColdStore(str(tmp_path / "q8"), width=width)
        _, deq3, _, _ = f32b.get(keys)
        np.testing.assert_allclose(deq3, deq, atol=step + 1e-7)

    def test_wal_torn_tail_and_compaction(self, tmp_path):
        """Crash-shaped durability: a torn tail record is dropped on
        replay (everything before it applies); hitting ``flush_every``
        compacts the WAL into an atomically-replaced base npz."""
        import os

        import numpy as np

        from dlrover_tpu.sparse.tiered import FileColdStore

        path = str(tmp_path / "c")
        cold = FileColdStore(path, width=2, flush_every=1000)
        k = np.arange(6, dtype=np.int64)
        rows = np.arange(12, dtype=np.float32).reshape(6, 2)
        cold.put(k, rows, np.ones(6, np.uint32), np.ones(6, np.uint32))
        cold.delete(np.array([5], np.int64))
        # no compaction yet: everything lives in the WAL only
        assert not os.path.exists(os.path.join(path, "cold.npz"))
        # simulate a crash mid-append: torn put record (header, no row)
        cold._wal.close()
        with open(os.path.join(path, "wal.log"), "ab") as fh:
            from dlrover_tpu.sparse.tiered import _WAL_HEADER

            fh.write(_WAL_HEADER.pack(b"P", 99, 1, 1) + b"\x00\x00")
        cold2 = FileColdStore(path, width=2, flush_every=2)
        found, vals, _, _ = cold2.get(np.arange(7, dtype=np.int64))
        assert found.tolist() == [True] * 5 + [False, False]  # no 99
        np.testing.assert_array_equal(vals[:5], rows[:5])
        # two mutation batches trigger compaction: base written, WAL cut
        cold2.put(
            np.array([7], np.int64),
            np.full((1, 2), 7.0, np.float32),
            np.array([1], np.uint32),
            np.array([1], np.uint32),
        )
        cold2.put(
            np.array([8], np.int64),
            np.full((1, 2), 8.0, np.float32),
            np.array([1], np.uint32),
            np.array([1], np.uint32),
        )
        assert os.path.exists(os.path.join(path, "cold.npz"))
        assert not os.path.exists(os.path.join(path, "cold_tmp.npz"))
        assert os.path.getsize(os.path.join(path, "wal.log")) == 0
        cold3 = FileColdStore(path, width=2)
        assert len(cold3) == 7
        f3, v3, _, _ = cold3.get(np.array([0, 7, 8], np.int64))
        assert f3.all()
        np.testing.assert_array_equal(v3[1], [7.0, 7.0])

    def test_wal_torn_tail_truncated_before_reappend(self, tmp_path):
        """Double-crash regression: replay must TRUNCATE a torn tail,
        not just skip it — __init__ reopens the log for append, so
        without the truncate new records land after the partial bytes
        and the NEXT replay misparses them (the torn put's row bytes
        swallow the following record: garbage row, silent drops)."""
        import os

        import numpy as np

        from dlrover_tpu.sparse.tiered import FileColdStore, _WAL_HEADER

        path = str(tmp_path / "c")
        cold = FileColdStore(path, width=2, flush_every=1000)
        k = np.arange(4, dtype=np.int64)
        rows = np.arange(8, dtype=np.float32).reshape(4, 2)
        cold.put(k, rows, np.ones(4, np.uint32), np.ones(4, np.uint32))
        cold._wal.close()
        wal = os.path.join(path, "wal.log")
        good_size = os.path.getsize(wal)
        # crash mid-append: torn put record (header + half a row)
        with open(wal, "ab") as fh:
            fh.write(_WAL_HEADER.pack(b"P", 99, 1, 1) + b"\x00\x00")
        # unclean restart 1: good records replay, torn tail cut from disk
        cold2 = FileColdStore(path, width=2, flush_every=1000)
        assert os.path.getsize(wal) == good_size
        cold2.put(
            np.array([7], np.int64),
            np.full((1, 2), 7.0, np.float32),
            np.array([1], np.uint32),
            np.array([1], np.uint32),
        )
        cold2._wal.close()
        # unclean restart 2: the record appended after the crash parses —
        # no garbage row for key 99, nothing silently dropped
        cold3 = FileColdStore(path, width=2, flush_every=1000)
        assert len(cold3) == 5
        found, vals, _, _ = cold3.get(
            np.array([0, 1, 2, 3, 7, 99], np.int64)
        )
        assert found.tolist() == [True] * 5 + [False]
        np.testing.assert_array_equal(vals[:4], rows)
        np.testing.assert_array_equal(vals[4], [7.0, 7.0])
        # corrupt-record tails (bad opcode) truncate the same way
        cold3._wal.close()
        with open(wal, "ab") as fh:
            fh.write(b"XXXX")
        pre = os.path.getsize(wal) - 4
        cold4 = FileColdStore(path, width=2, flush_every=1000)
        assert os.path.getsize(wal) == pre
        assert len(cold4) == 5

    def test_wal_fsync_interval(self, tmp_path):
        """fsync_every syncs the log to disk every N append batches and
        the synced records replay on restart (smoke for the opt-in
        power-loss durability knob)."""
        import numpy as np

        from dlrover_tpu.sparse.tiered import FileColdStore

        cold = FileColdStore(
            str(tmp_path / "c"), width=2, flush_every=1000, fsync_every=1
        )
        cold.put(
            np.array([1], np.int64),
            np.array([[1.0, 2.0]], np.float32),
            np.array([1], np.uint32),
            np.array([1], np.uint32),
        )
        assert cold._unsynced == 0  # batch was synced, counter reset
        cold2 = FileColdStore(str(tmp_path / "c"), width=2)
        found, vals, _, _ = cold2.get(np.array([1], np.int64))
        assert found.all()
        np.testing.assert_array_equal(vals[0], [1.0, 2.0])


class TestLookaheadPrefetcher:
    """sparse/prefetch.py: queue-peeking promotion off the request path."""

    class _Req:
        def __init__(self, keys):
            self.keys = np.asarray(keys, np.int64)

    def _tiered(self, tmp_path, dim=4):
        from dlrover_tpu.sparse.kv_table import KvTable
        from dlrover_tpu.sparse.tiered import FileColdStore, TieredTable

        table = KvTable("pf_t", dim=dim, n_slots=0)
        cold = FileColdStore(str(tmp_path / "cold"), width=dim)
        return TieredTable(table, cold)

    def test_prefetch_promotes_queued_keys(self, tmp_path):
        tiered = self._tiered(tmp_path)
        keys = np.arange(40, dtype=np.int64)
        rows = tiered.gather_or_insert(keys, now_ts=10)
        assert tiered.demote_before_timestamp(100) == 40

        from dlrover_tpu.sparse.prefetch import LookaheadPrefetcher

        queue = [self._Req(keys[i:i + 8]) for i in range(0, 40, 8)]
        pf = LookaheadPrefetcher(
            tiered, lambda n=1: queue[:n], lambda r: r.keys,
            lookahead=8,
        )
        pf.start()
        try:
            pf.notify()
            assert pf.drain(timeout=30.0)
        finally:
            pf.stop()
        snap = tiered.stats.snapshot()
        # everything the peek window exposed was promoted OFF the
        # gather path...
        assert snap["prefetched"] == 40
        assert snap["prefetch_coverage"] == 1.0
        st = pf.stats()
        assert st["keys_promoted"] == 40
        assert st["batches"] >= 1
        # ...so the serve-time gather is all hot hits (fresh gauges to
        # isolate the serve window, as the engine does per publish arm)
        from dlrover_tpu.sparse.tiered import TierStats

        tiered.stats = TierStats()
        back = tiered.gather_or_zeros(keys)
        np.testing.assert_allclose(back, rows, rtol=1e-6)
        snap = tiered.stats.snapshot()
        assert snap["cold_faults"] == 0
        assert snap["hot_hit_rate"] == 1.0

    def test_prefetch_dedups_recent_keys(self, tmp_path):
        tiered = self._tiered(tmp_path)
        keys = np.arange(10, dtype=np.int64)
        tiered.gather_or_insert(keys, now_ts=10)
        assert tiered.demote_before_timestamp(100) == 10

        from dlrover_tpu.sparse.prefetch import LookaheadPrefetcher

        staged = []
        orig_prefetch = tiered.prefetch

        def counting_prefetch(k, now_ts=None):
            staged.extend(np.asarray(k).tolist())
            return orig_prefetch(k, now_ts)

        tiered.prefetch = counting_prefetch
        queue = [self._Req(keys)]
        pf = LookaheadPrefetcher(
            tiered, lambda n=1: queue[:n], lambda r: r.keys,
            lookahead=4,
        )
        pf.start()
        try:
            for _ in range(5):  # the same head peeked repeatedly
                pf.notify()
                assert pf.drain(timeout=30.0)
        finally:
            pf.stop()
            tiered.prefetch = orig_prefetch
        # recent-key dedup: repeated peeks of the same head stage each
        # key once, not once per wakeup
        assert sorted(staged) == keys.tolist()
