"""Test bootstrap: force an 8-device virtual CPU platform.

Mirrors the reference's keystone test trick (SURVEY.md §4): everything
distributed is testable on one host — the master runs in-process and the
device mesh comes from XLA's forced host platform.

The container's sitecustomize imports jax at interpreter startup (to
register the TPU PJRT plugin), which latches ``JAX_PLATFORMS`` from the
environment before this file runs — so we must override through
``jax.config`` rather than ``os.environ``. ``XLA_FLAGS`` is still read
lazily at first backend creation, which has not happened yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, (
    "tests need the 8-device virtual CPU platform, got: " + str(jax.devices())
)

import glob  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _cleanup_shm():
    """Remove checkpoint shm segments staged during tests."""
    yield
    for path in glob.glob("/dev/shm/dlrover_tpu_ckpt_*"):
        try:
            os.unlink(path)
        except OSError:
            pass
