"""Master control-plane tests with an in-process master.

Replicates the reference's keystone fixture (SURVEY.md §4): a real gRPC
master in-process, real clients, no cluster.
"""

import time

import pytest

from dlrover_tpu.common.constants import (
    NodeStatus,
    NodeType,
    RendezvousName,
)
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.master.master import LocalJobMaster
from dlrover_tpu.master.rdzv_manager import NetworkCheckRendezvousManager
from dlrover_tpu.master.status_flow import transition
from dlrover_tpu.master.task_manager import TaskManager


@pytest.fixture()
def master():
    m = LocalJobMaster(port=0, num_workers=2)
    m.prepare()
    yield m
    m.stop()


def _client(master, node_id):
    c = MasterClient(master.addr, node_id=node_id)
    c.register_node(local_chips=4, tpu_type="v5e")
    return c


def test_event_callback_registry_fires_hooks(master):
    """Pluggable NodeEventCallback observers (event_callback.py:42
    analog) see started/failed/succeeded with the cluster context, and
    an observer exception never breaks lifecycle handling."""
    from dlrover_tpu.master.event_callback import NodeEventCallback

    seen = []

    class Recorder(NodeEventCallback):
        def on_node_started(self, node, ctx):
            seen.append(("started", node.id, ctx is not None))

        def on_node_failed(self, node, ctx):
            seen.append(("failed", node.id, ctx.task_manager is not None))

    class Broken(NodeEventCallback):
        def on_node_started(self, node, ctx):
            raise RuntimeError("observer bug")

    master.job_manager.event_callbacks.extend([Recorder(), Broken()])
    c0 = _client(master, 0)
    c0.report_node_status(NodeStatus.FAILED, exit_reason="fatal_error")
    assert ("started", 0, True) in seen
    assert ("failed", 0, True) in seen
    # Broken raised on started, yet the node still registered + failed
    assert master.job_manager.get_node(0).status == NodeStatus.FAILED


def test_task_reschedule_callback_requeues_shards(master):
    """A dead node's in-flight shard goes back to the queue through the
    registry's TaskRescheduleCallback (no inline master plumbing)."""
    master.task_manager.new_dataset(
        "ds", dataset_size=8, shard_size=4
    )
    c0, c1 = _client(master, 0), _client(master, 1)
    t0 = c0.get_task("ds")
    assert t0.task_id >= 0
    c0.report_node_status(NodeStatus.FAILED, exit_reason="killed")
    # the shard node 0 held is available again (for node 1)
    t1 = c1.get_task("ds")
    t2 = c1.get_task("ds")
    got = {t1.shard_start, t2.shard_start}
    assert t0.shard_start in got


def test_chief_and_evaluator_roles(master):
    """Role-aware accounting: workers succeeding does not complete the
    job while an evaluator still runs; chief visibility is queryable."""
    from dlrover_tpu.common.constants import NodeType

    c0, c1 = _client(master, 0), _client(master, 1)
    ev = MasterClient(master.addr, node_id=7)
    ev.register_node(node_type=NodeType.EVALUATOR)
    chief = MasterClient(master.addr, node_id=8)
    chief.register_node(node_type=NodeType.CHIEF)

    jm = master.job_manager
    assert jm.is_chief_running()
    assert len(jm.nodes_of_type(NodeType.EVALUATOR)) == 1
    c0.report_node_status(NodeStatus.SUCCEEDED)
    c1.report_node_status(NodeStatus.SUCCEEDED)
    chief.report_node_status(NodeStatus.SUCCEEDED)
    assert jm.all_workers_succeeded()
    assert not jm.all_evaluators_exited()  # evaluator still running
    ev.report_node_status(NodeStatus.SUCCEEDED)
    assert jm.all_evaluators_exited()


def test_chief_exhaustion_fails_job_and_evaluator_gates_exit(master):
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.master.event_callback import ChiefFailureCallback

    failures = []
    master.job_manager.event_callbacks.append(
        ChiefFailureCallback(failures.append)
    )
    chief = MasterClient(master.addr, node_id=9)
    chief.register_node(node_type=NodeType.CHIEF)
    # non-relaunchable exit → the job-failed hook fires (DELETED path
    # covered by the alias)
    chief.report_node_status(NodeStatus.FAILED, exit_reason="fatal_error")
    assert failures and "chief" in failures[0]

    # evaluator gating: workers done but evaluator alive → master's exit
    # condition must hold off
    ev = MasterClient(master.addr, node_id=7)
    ev.register_node(node_type=NodeType.EVALUATOR)
    c0, c1 = _client(master, 0), _client(master, 1)
    c0.report_node_status(NodeStatus.SUCCEEDED)
    c1.report_node_status(NodeStatus.SUCCEEDED)
    jm = master.job_manager
    assert jm.all_workers_succeeded() is False  # chief FAILED counts
    assert not jm.all_evaluators_exited()
    ev.report_node_status(NodeStatus.SUCCEEDED)
    assert jm.all_evaluators_exited()


def test_brain_ps_weights_flow_to_sparse_tier(master):
    """Brain hot-shard plan → auto-scaler → ElasticPsService weights +
    version bump (the rebalance consumer path)."""
    from dlrover_tpu.master.auto_scaler import JobAutoScaler
    from dlrover_tpu.master.node_manager import NoopScaler
    from dlrover_tpu.master.resource_optimizer import ResourcePlan

    scaler = JobAutoScaler(
        master.job_manager,
        master.speed_monitor,
        NoopScaler(),
        ps_service=master.ps_service,
    )
    v0 = master.ps_service.get_global_version()
    plan = ResourcePlan()
    plan.node_resources["ps"] = {"weights": {"ps0": 0.5, "ps1": 1.0}}
    scaler.execute_plan(plan)
    assert master.ps_service.get_weights() == {"ps0": 0.5, "ps1": 1.0}
    assert master.ps_service.get_global_version() == v0 + 1
    # idempotent: same weights do not churn the version
    scaler.execute_plan(plan)
    assert master.ps_service.get_global_version() == v0 + 1

    # ps-oom count hints reach the platform hook
    targets = []
    scaler.ps_scale_fn = targets.append
    plan2 = ResourcePlan()
    plan2.node_resources["ps"] = {"num": 3}
    scaler.execute_plan(plan2)
    assert targets == [3]


def test_node_unit_rendezvous_seals_whole_slices():
    """node_unit=2 (hosts per slice): 3 waiting nodes seal a 2-node
    world — a partial slice has no ICI and must never join; the odd
    node stays waiting for the next round."""
    from dlrover_tpu.master.rdzv_manager import RendezvousManager

    mgr = RendezvousManager()
    mgr.update_rdzv_params(
        min_nodes=2, max_nodes=4, node_unit=2, waiting_timeout=0.0
    )
    for rank in (0, 1, 2):
        mgr.join_rendezvous(
            node_id=rank, node_rank=rank, local_world_size=4
        )
    _, _, world, _ = mgr.get_comm_world(0)
    # floor(3, unit=2) = 2, deterministically the lowest ranks
    assert set(world) == {0, 1}, world
    # the left-out node is still waiting for the next seal
    assert mgr.num_nodes_waiting() == 1


def test_node_unit_rejects_below_minimum():
    """2 waiting with unit 4 (min 4): nothing usable, no seal."""
    from dlrover_tpu.master.rdzv_manager import RendezvousManager

    mgr = RendezvousManager()
    mgr.update_rdzv_params(
        min_nodes=4, max_nodes=8, node_unit=4, waiting_timeout=0.0
    )
    mgr.join_rendezvous(node_id=0, node_rank=0, local_world_size=4)
    mgr.join_rendezvous(node_id=1, node_rank=1, local_world_size=4)
    _, _, world, _ = mgr.get_comm_world(0)
    assert world == {}


def test_pending_node_timeout_fails_job():
    """A node stuck INITIAL/PENDING past the deadline trips
    pending_timeout() — the master exits PENDING_TIMEOUT on it."""
    from dlrover_tpu.master.node_manager import JobManager

    jm = JobManager(num_workers=2, pending_timeout_s=0.2)
    assert not jm.pending_timeout()  # fresh nodes, inside the window
    time.sleep(0.3)
    assert jm.pending_timeout()  # neither ever registered
    # one registers: the OTHER still pending → still timed out
    from dlrover_tpu.common.messages import NodeMeta

    jm.register_node(NodeMeta(node_id=0))
    assert jm.pending_timeout()


def test_register_and_heartbeat(master):
    c = _client(master, 0)
    assert c.node_rank == 0
    assert c.report_heartbeat()
    node = master.job_manager.get_node(0)
    assert node.status == NodeStatus.RUNNING
    assert node.config_resource.tpu_chips == 4


def test_rendezvous_two_nodes(master):
    c0, c1 = _client(master, 0), _client(master, 1)
    assert c0.join_rendezvous(local_world_size=4) >= 1
    # world not sealed until min nodes joined
    _, _, world, _ = c0.get_comm_world()
    assert world == {}
    c1.join_rendezvous(local_world_size=4)
    _, _, world, coord = c0.get_comm_world()
    assert world == {0: 4, 1: 4}
    assert coord
    # both nodes see the same sealed world
    _, _, world1, coord1 = c1.get_comm_world()
    assert world1 == world and coord1 == coord


def test_rendezvous_restart_bumps_round(master):
    c0, c1 = _client(master, 0), _client(master, 1)
    r1 = c0.join_rendezvous(4)
    c1.join_rendezvous(4)
    _, _, world, _ = c0.get_comm_world()
    assert len(world) == 2
    # node 1 dies: master event callback removes it from the world
    c1.report_node_status(NodeStatus.FAILED, exit_reason="killed")
    time.sleep(0.1)
    # both nodes re-join (the agent restarts its worker) → new round seals
    r2 = c0.join_rendezvous(4)
    c1.join_rendezvous(4)
    assert r2 > r1
    _, _, world, _ = c0.get_comm_world()
    assert world == {0: 4, 1: 4}


def test_model_info_and_running_nodes(master):
    """report_model_info lands in the metrics collector's JobMeta (the
    Brain optimizer's input); get_running_nodes lists the live world
    (reference: master_client.py report_model_info/get_running_nodes)."""
    c0 = _client(master, 0)
    c1 = _client(master, 1)
    assert c0.report_model_info(
        model_name="llama-1.4b",
        num_params=1_360_000_000,
        flops_per_token=8.2e9,
        global_batch_size=8,
        seq_len=1024,
    )
    meta = master.metric_collector.meta
    assert meta.model_name == "llama-1.4b"
    assert meta.num_params == 1_360_000_000
    assert meta.seq_len == 1024

    nodes = c1.get_running_nodes()
    assert {n.id for n in nodes} == {0, 1}
    assert all(n.status == "running" for n in nodes)
    assert {n.rank_index for n in nodes} == {0, 1}


def test_rendezvous_concurrent_join_storm():
    """Stress: many threads join/poll/crash/rejoin concurrently. The
    sealed world must always be internally consistent — contiguous rank
    set from the waiting pool, node_unit multiple, one coordinator —
    and a post-storm rendezvous must still seal (no wedged state)."""
    import threading

    import numpy as np

    from dlrover_tpu.master.rdzv_manager import (
        ElasticTrainingRendezvousManager,
    )

    mgr = ElasticTrainingRendezvousManager()
    mgr.update_rdzv_params(
        min_nodes=4, max_nodes=8, waiting_timeout=0.05, node_unit=2
    )
    stop = time.time() + 2.0
    errors = []

    def node(rank):
        rng = np.random.RandomState(rank)
        try:
            while time.time() < stop:
                mgr.join_rendezvous(rank, rank, 4, f"h{rank}")
                for _ in range(rng.randint(1, 20)):
                    rnd, _, world, coord = mgr.get_comm_world(rank)
                    if world:
                        # invariants on any observed sealed world
                        if len(world) % 2:
                            errors.append(f"odd world {world}")
                        if not (4 <= len(world) <= 8):
                            errors.append(f"size {len(world)}")
                        if rank in world and not coord:
                            errors.append("sealed without coordinator")
                        break
                    time.sleep(0.001)
                if rng.rand() < 0.3:
                    mgr.remove_alive_node(rank)  # simulated crash
                time.sleep(rng.rand() * 0.01)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [
        threading.Thread(target=node, args=(r,)) for r in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]

    # post-storm: clear every storm leftover (waiting stragglers AND a
    # possibly still-sealed world), then a clean rendezvous must seal —
    # proving the storm cannot wedge the manager's internal state.
    for r in range(8):
        mgr.remove_alive_node(r)
    for r in range(4):
        mgr.join_rendezvous(r, r, 4, f"h{r}")
    deadline = time.time() + 2
    world = {}
    while time.time() < deadline and not world:
        _, _, world, coord = mgr.get_comm_world(0)
        time.sleep(0.01)
    assert sorted(world) == [0, 1, 2, 3], world
    assert coord


def test_data_sharding_dispatch_and_requeue(master):
    c0, c1 = _client(master, 0), _client(master, 1)
    c0.report_dataset_shard_params(
        "train", dataset_size=100, shard_size=10, num_epochs=1
    )
    t0 = c0.get_task("train")
    t1 = c1.get_task("train")
    assert t0.shard_end - t0.shard_start == 10
    assert (t0.shard_start, t0.shard_end) != (t1.shard_start, t1.shard_end)
    assert c0.report_task_result("train", t0.task_id, success=True)

    # worker 1 dies with a task in flight → its shard is re-dispatched
    c1.report_node_status(NodeStatus.FAILED, exit_reason="killed")
    time.sleep(0.1)
    seen = set()
    while True:
        t = c0.get_task("train")
        if t.task_id < 0:
            break
        seen.add((t.shard_start, t.shard_end))
        c0.report_task_result("train", t.task_id, success=True)
    assert (t1.shard_start, t1.shard_end) in seen


def test_shard_checkpoint_roundtrip(master):
    c0 = _client(master, 0)
    c0.report_dataset_shard_params(
        "ds", dataset_size=40, shard_size=10, num_epochs=1
    )
    got = c0.get_task("ds")
    assert got.task_id >= 0
    ckpt = c0.get_shard_checkpoint("ds")
    assert ckpt
    # restore re-queues the in-flight shard
    assert c0.report_shard_checkpoint("ds", ckpt)
    ranges = []
    while True:
        t = c0.get_task("ds")
        if t.task_id < 0:
            break
        ranges.append((t.shard_start, t.shard_end))
        c0.report_task_result("ds", t.task_id)
    assert (got.shard_start, got.shard_end) in ranges
    assert len(ranges) == 4


def test_kv_and_sync(master):
    c0, c1 = _client(master, 0), _client(master, 1)
    assert c0.kv_store_set("coord", "h0:1234")
    assert c1.kv_store_get("coord") == "h0:1234"
    assert not c0.sync_finished("step-sync")
    c0.join_sync("step-sync")
    c1.join_sync("step-sync")
    assert c0.sync_finished("step-sync")


def test_speed_monitor_and_ckpt_sync(master):
    c0 = _client(master, 0)
    # rate math uses the master-side monotonic arrival clock (injected
    # here); the wall timestamp is watermark metadata only
    master.speed_monitor.collect_global_step(0, now=90.0)
    master.speed_monitor.collect_global_step(100, now=100.0)
    assert master.speed_monitor.running_speed == pytest.approx(10.0, rel=0.1)
    c0.report_ckpt_step(120)
    assert c0.get_min_ckpt_step() == 120


def test_status_flow():
    assert transition(NodeStatus.PENDING, NodeStatus.RUNNING).allowed
    assert not transition(NodeStatus.FAILED, NodeStatus.RUNNING).allowed
    assert not transition(NodeStatus.RUNNING, NodeStatus.RUNNING).allowed


def test_network_check_grouping_and_fault():
    mgr = NetworkCheckRendezvousManager()
    groups = mgr._group_nodes([0, 1, 2, 3])
    assert groups == [[0, 1], [2, 3]]
    mgr._check_round = 1
    groups2 = mgr._group_nodes([0, 1, 2, 3])
    assert groups2 != groups

    # node 2 fails both rounds → fault; node 3 only once → not fault
    mgr._check_round = 0
    for rank in (0, 1, 3):
        mgr.report_network_check_result(rank, True, 1.0)
    mgr.report_network_check_result(2, False, 0.0)
    mgr.next_check_round()
    for rank in (0, 1):
        mgr.report_network_check_result(rank, True, 1.0)
    mgr.report_network_check_result(2, False, 0.0)
    mgr.report_network_check_result(3, False, 0.0)
    fault, _ = mgr.check_fault_node()
    assert fault == [2]


def test_straggler_detection():
    mgr = NetworkCheckRendezvousManager()
    for rank in range(3):
        mgr.report_network_check_result(rank, True, 1.0)
    mgr.report_network_check_result(3, True, 5.0)
    stragglers, _ = mgr.get_stragglers(ratio=1.6)
    assert stragglers == [3]


def test_task_manager_timeout_requeue():
    tm = TaskManager(shard_timeout_s=0.05)
    tm.new_dataset("d", 20, 10)
    t = tm.get_task("d", worker_id=0)
    assert t.task_id >= 0
    time.sleep(0.1)
    n = tm._datasets["d"].recover_timeout_tasks(0.05)
    assert n == 1


def test_abort_fans_out_to_all_nodes(master):
    """An OOM (abort-classified) failure on one node must stop every node."""
    c0 = _client(master, 0)
    c1 = _client(master, 1)
    c0.report_heartbeat()
    c1.report_heartbeat()
    c0.report_failure(
        "Traceback ...\nRESOURCE_EXHAUSTED: out of memory allocating ...",
        level="process_error",
    )
    assert "abort_job" in c0.heartbeat_with_actions()
    assert "abort_job" in c1.heartbeat_with_actions()
    # actions drain: second heartbeat is clean
    assert c1.heartbeat_with_actions() == []


def test_unknown_failure_does_not_restart_dead_worker(master):
    """Plain exit-code reports must not queue a duplicate restart (the
    agent already restarts a dead worker itself)."""
    c0 = _client(master, 0)
    c0.report_failure("worker exit code 1", level="process_error")
    assert c0.heartbeat_with_actions() == []


def test_reregistration_clears_stale_prescriptions():
    """A replacement agent must never be handed a prescription queued
    against its dead predecessor: the slice drill's joiner was told
    relaunch_node (diagnosed from the ORIGINAL node's crash) and obeyed
    by exiting — looping the recovery it was the recovery for. A fresh
    registration drains the node's pending action queue."""
    from dlrover_tpu.common import messages as msgs
    from dlrover_tpu.diagnosis.manager import DiagnosisManager
    from dlrover_tpu.master.node_manager import JobManager
    from dlrover_tpu.master.servicer import MasterServicer

    jm = JobManager(num_workers=2)
    dm = DiagnosisManager()
    servicer = MasterServicer(job_manager=jm, diagnosis_manager=dm)

    # node 1 dies; the failure is diagnosed as needing a node relaunch
    dm.collect_failure(
        msgs.NodeFailureReport(
            node_id=1, error_data="killed: preempted", level="node_error"
        )
    )
    assert dm._pending_actions.get(1), "precondition: action queued"

    # the replacement registers (fresh incarnation)
    resp = servicer.get(
        msgs.NodeRegisterRequest(
            meta=msgs.NodeMeta(node_id=1, node_rank=1, host_addr="h1"),
            restart_count=0,
        )
    )
    assert resp.success
    # ...and the stale prescription is gone: its next heartbeat carries
    # no relaunch order
    hb = servicer.get(msgs.HeartbeatReport(node_id=1))
    assert not hb.actions, hb.actions


def test_worker_restart_requeues_inflight_shards():
    """A PLANNED worker restart (membership change / restart
    prescription) must re-queue the node's leased shard immediately:
    only node FAILURES re-queued before, so a voluntary restart leaked
    the lease and the dataset tail deadlocked until the 1800 s shard
    timeout (found by the slice-elasticity drill's grow phase)."""
    from dlrover_tpu.common import messages as msgs
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.task_manager import TaskManager

    tm = TaskManager()
    tm.new_dataset("train", dataset_size=16, shard_size=8)
    servicer = MasterServicer(task_manager=tm)

    t1 = tm.get_task("train", worker_id=0)
    assert t1.task_id >= 0
    # the second shard goes out too — nothing left in todo
    t2 = tm.get_task("train", worker_id=0)
    assert t2.task_id >= 0
    assert tm.get_task("train", worker_id=0).task_type == "wait"

    # agent kills + respawns its worker: both leases come back
    servicer.report(msgs.WorkerRestartReport(node_id=0, reason="test"))
    t3 = tm.get_task("train", worker_id=0)
    assert t3.task_id >= 0, "lease was not re-queued"


def test_agent_registration_carries_slice_placement(monkeypatch):
    """The operator injects DLROVER_TPU_SLICE_INDEX per pod and GKE
    multislice exposes MEGASCALE_SLICE_ID; the agent must forward the
    real placement so the master's SliceTopology (whole-slice scaling,
    rdzv node_unit) isn't a cosmetic all-zeros map."""
    from dlrover_tpu.agent.agent import (
        ElasticLaunchConfig,
        ElasticTrainingAgent,
    )

    seen = {}

    class _T:
        addr = "localhost:1"

    class _Client:
        _t = _T()
        node_rank = 0

        def register_node(self, **kw):
            seen.update(kw)
            raise RuntimeError("stop after register")  # end run() early

    monkeypatch.setenv("DLROVER_TPU_SLICE_INDEX", "3")
    monkeypatch.setenv("DLROVER_TPU_SLICE_ID", "slice-3")
    agent = ElasticTrainingAgent(ElasticLaunchConfig(), _Client())
    with pytest.raises(RuntimeError):
        agent.run()
    assert seen["slice_index"] == 3
    assert seen["slice_id"] == "slice-3"

    # GKE multislice fallback
    seen.clear()
    monkeypatch.delenv("DLROVER_TPU_SLICE_INDEX")
    monkeypatch.delenv("DLROVER_TPU_SLICE_ID")
    monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
    agent2 = ElasticTrainingAgent(ElasticLaunchConfig(), _Client())
    with pytest.raises(RuntimeError):
        agent2.run()
    assert seen["slice_index"] == 1


# ---------------------------------------------------------------------------
# Live-reshard directive (eviction → survivors migrate instead of restart)
# ---------------------------------------------------------------------------


def test_reshard_plan_versioning_and_world_excision():
    from dlrover_tpu.master.rdzv_manager import RendezvousManager

    mgr = RendezvousManager()
    mgr.update_rdzv_params(min_nodes=1, max_nodes=8, waiting_timeout=0.0)
    for r in range(4):
        mgr.join_rendezvous(node_id=r, node_rank=r, local_world_size=1)
    _, _, world, _ = mgr.get_comm_world(0)
    assert set(world) == {0, 1, 2, 3}
    assert mgr.get_reshard_plan() == {"version": 0}

    v = mgr.plan_reshard([2, 3], dp_size=4, deadline_s=10.0, reason="drill")
    assert v == 1
    plan = mgr.get_reshard_plan()
    assert plan["dp_old"] == 4 and plan["dp_new"] == 2
    assert plan["lost_ranks"] == [2, 3]
    # lost ranks are excised but the round stays sealed for survivors
    _, _, world, _ = mgr.get_comm_world(0)
    assert set(world) == {0, 1}
    # the prune callback firing for a directive-listed rank is a no-op
    mgr.remove_alive_node(3)
    _, _, world, _ = mgr.get_comm_world(0)
    assert set(world) == {0, 1}
    # a SURVIVOR dying is a real failure: the world tears down
    mgr.remove_alive_node(0)
    _, _, world, _ = mgr.get_comm_world(0)
    assert world == {}

    # evicting everyone is rejected; versions stay monotonic
    with pytest.raises(ValueError):
        mgr.plan_reshard([0, 1], dp_size=2)
    assert mgr.plan_reshard([1], dp_size=2) == 2


def test_eviction_notice_issues_reshard_directive(master):
    c0, c1 = _client(master, 0), _client(master, 1)
    c0.join_rendezvous(4)
    c1.join_rendezvous(4)
    _, _, world, _ = c0.get_comm_world()
    assert len(world) == 2
    assert c0.get_reshard_plan().version == 0

    assert c0.report_eviction(
        [1], dp_size=2, deadline_s=5.0, reason="maintenance"
    )
    plan = c1.get_reshard_plan()
    assert plan.version == 1
    assert plan.dp_old == 2 and plan.dp_new == 1
    assert plan.lost_ranks == [1]
    assert plan.deadline_s == 5.0
    # survivor keeps the sealed round with rank 1 excised
    _, _, world, _ = c0.get_comm_world()
    assert world == {0: 4}
    # the evicted node failing afterwards must not tear the round down
    c1.report_node_status(NodeStatus.FAILED, exit_reason="evicted")
    time.sleep(0.1)
    _, _, world, _ = c0.get_comm_world()
    assert world == {0: 4}

    # an eviction that would leave no survivors is refused
    assert not c0.report_eviction([0, 1], dp_size=2)


def test_serving_eviction_issues_page_migration_directive(master):
    """The serving twin of the eviction flow: a replica's departure is
    reported over the wire and the master answers subsequent polls with
    a versioned page-migration directive naming victim + survivors."""
    clients = []
    for nid in (10, 11, 12):
        c = MasterClient(master.addr, node_id=nid)
        c.register_node(node_type=NodeType.SERVING)
        clients.append(c)
    c10, c11, c12 = clients

    assert c10.get_serving_reshard().version == 0  # none pending

    assert c10.report_serving_eviction(
        "serving-11", in_flight=2, deadline_s=3.0, reason="evict"
    )
    d = c12.get_serving_reshard()
    assert d.version == 1
    assert d.victim == "serving-11"
    # survivors default to every OTHER registered serving replica
    assert d.survivors == ["serving-10", "serving-12"]
    assert d.deadline_s == 3.0 and d.reason == "evict"

    # directives version monotonically, latest wins
    assert c10.report_serving_eviction("serving-12", reason="drain")
    d2 = c10.get_serving_reshard()
    assert d2.version == 2 and d2.victim == "serving-12"
    for c in clients:
        c.close()
