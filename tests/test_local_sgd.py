"""Local SGD / HSDP reducer tests.

Reference behaviors: atorch/local_sgd reduce_methods (linear, GTA sign
consensus, sparsify) and the HSDP outer-optimizer sync cadence.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.parallel.local_sgd import (
    InProcessTransport,
    LocalSGDConfig,
    LocalSGDSynchronizer,
    OuterOptimizer,
    SocketTransport,
    consensus_mask,
    gta_merge,
    linear_merge,
    socket_exchange,
    sparsify_magnitude,
    sparsify_random,
)


def test_linear_merge_is_weighted_mean():
    stacked = jnp.stack([jnp.ones((4,)), 3 * jnp.ones((4,))])
    np.testing.assert_allclose(np.asarray(linear_merge(stacked)), 2.0)
    out = linear_merge(stacked, weights=[3.0, 1.0])
    np.testing.assert_allclose(np.asarray(out), 1.5)


def test_consensus_mask_sum_vs_count():
    # replica deltas: [+10, -1, -1] → sum majority +, count majority −
    stacked = jnp.array([[10.0], [-1.0], [-1.0]])
    m_sum = consensus_mask(stacked, "sum")
    m_cnt = consensus_mask(stacked, "count")
    np.testing.assert_array_equal(np.asarray(m_sum[:, 0]), [1.0, 0.0, 0.0])
    np.testing.assert_array_equal(np.asarray(m_cnt[:, 0]), [0.0, 1.0, 1.0])


def test_gta_merge_drops_minority_sign():
    # 2 replicas agree (+1), 1 disagrees (−1): merged = mean of agreeing
    stacked = jnp.array([[1.0], [1.0], [-1.0]])
    out = gta_merge(stacked, consensus="count")
    np.testing.assert_allclose(np.asarray(out), [1.0])


def test_gta_merge_no_consensus_is_mean():
    stacked = jnp.stack([jnp.full((8,), 2.0), jnp.full((8,), 4.0)])
    out = gta_merge(stacked, consensus=None)
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_sparsify_magnitude_keeps_topk():
    x = jnp.array([0.1, -5.0, 0.2, 3.0])
    out = sparsify_magnitude(x, density=0.5)
    np.testing.assert_allclose(np.asarray(out), [0.0, -5.0, 0.0, 3.0])


def test_sparsify_random_unbiased():
    x = jnp.ones((10000,))
    out = sparsify_random(x, 0.25, jax.random.key(0), rescale=True)
    assert abs(float(out.mean()) - 1.0) < 0.1
    kept = float((out != 0).mean())
    assert abs(kept - 0.25) < 0.05


def test_outer_optimizer_momentum_accumulates():
    opt = OuterOptimizer(lr=1.0, momentum=0.9)
    base = {"w": jnp.zeros((2,))}
    delta = {"w": jnp.ones((2,))}
    p1 = opt.apply(base, delta)
    p2 = opt.apply(p1, delta)
    # second step: velocity = 0.9*1 + 1 = 1.9
    np.testing.assert_allclose(np.asarray(p2["w"]), 1.0 + 1.9)


def _run_slices(world, cfg, steps, lr=0.1, target=2.0):
    """N threads, each descending sum((w−target)²) locally with different
    data noise, syncing through an InProcessTransport."""
    transport = InProcessTransport(world)
    results = [None] * world

    def slice_main(rank):
        rng = jax.random.key(rank)
        params = {"w": jnp.zeros((16,))}
        sync = LocalSGDSynchronizer(cfg, transport.make_exchange(rank))
        sync.maybe_sync(0, params)  # records initial synced point
        for step in range(1, steps + 1):
            noise = jax.random.normal(
                jax.random.fold_in(rng, step), (16,)
            ) * 0.1
            g = 2 * (params["w"] - target) + noise
            params = {"w": params["w"] - lr * g}
            params = sync.maybe_sync(step, params)
        results[rank] = params

    threads = [
        threading.Thread(target=slice_main, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


@pytest.mark.parametrize("reducer", ["mean", "gta"])
def test_local_sgd_converges_and_stays_in_sync(reducer):
    cfg = LocalSGDConfig(sync_interval=4, reducer=reducer)
    results = _run_slices(world=3, cfg=cfg, steps=24)
    # all slices hold identical params after a sync boundary
    for r in results[1:]:
        np.testing.assert_allclose(
            np.asarray(r["w"]), np.asarray(results[0]["w"]), rtol=1e-5
        )
    # and they converged near the target
    np.testing.assert_allclose(np.asarray(results[0]["w"]), 2.0, atol=0.3)


def _run_big_slices(world, cfg, steps, lr=0.1, target=2.0, dim=8192):
    """Like _run_slices but with a leaf large enough to be quantized
    (>= ops.quant.MIN_QUANT_SIZE)."""
    transport = InProcessTransport(world)
    results = [None] * world

    def slice_main(rank):
        rng = jax.random.key(rank)
        params = {"w": jnp.zeros((dim,))}
        sync = LocalSGDSynchronizer(cfg, transport.make_exchange(rank))
        sync.maybe_sync(0, params)
        for step in range(1, steps + 1):
            noise = jax.random.normal(
                jax.random.fold_in(rng, step), (dim,)
            ) * 0.1
            g = 2 * (params["w"] - target) + noise
            params = {"w": params["w"] - lr * g}
            params = sync.maybe_sync(step, params)
        results[rank] = params

    threads = [
        threading.Thread(target=slice_main, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


@pytest.mark.parametrize("compress", ["int8", "int4"])
def test_compressed_sync_converges_and_stays_in_sync(compress):
    """int8/int4 outer reduce (quant_reduce.cu capability): slices stay
    bit-identical after syncs and converge within tolerance of the
    uncompressed trajectory."""
    cfg_c = LocalSGDConfig(sync_interval=4, compress=compress)
    cfg_f = LocalSGDConfig(sync_interval=4)
    res_c = _run_big_slices(world=3, cfg=cfg_c, steps=24)
    res_f = _run_big_slices(world=3, cfg=cfg_f, steps=24)
    for r in res_c[1:]:
        np.testing.assert_allclose(
            np.asarray(r["w"]), np.asarray(res_c[0]["w"]), rtol=1e-5
        )
    np.testing.assert_allclose(np.asarray(res_c[0]["w"]), 2.0, atol=0.3)
    # compressed endpoint within a small band of the exact one
    err = np.abs(
        np.asarray(res_c[0]["w"]) - np.asarray(res_f[0]["w"])
    ).max()
    assert err < 0.05, err


def test_compressed_wire_bytes_shrink_4x():
    from dlrover_tpu.parallel.local_sgd import _pack_tree
    from dlrover_tpu.ops.quant import quantize_tree

    delta = {"w": jnp.asarray(np.random.randn(512 * 1024), jnp.float32)}
    raw = len(_pack_tree(delta))
    q8 = len(_pack_tree(quantize_tree(delta, bits=8)))
    q4 = len(_pack_tree(quantize_tree(delta, bits=4)))
    assert raw / q8 > 3.5, (raw, q8)
    assert raw / q4 > 6.5, (raw, q4)


def test_error_feedback_conserves_delta():
    """sent + residual must equal the intended delta exactly, and the
    residual is re-injected into the next round's send."""
    sent_trees = []

    def exchange(t):
        sent_trees.append(t)
        return [t]

    cfg = LocalSGDConfig(sync_interval=1, compress="int8")
    sync = LocalSGDSynchronizer(cfg, exchange)
    sync.maybe_sync(0, {"w": jnp.zeros((8192,))})
    # mixed magnitudes INSIDE each 256-wide quantization block: the big
    # values force a coarse blockwise scale, so the small ones suffer
    # real quantization error
    delta = jnp.where(jnp.arange(8192) % 2 == 0, 3.0, 1e-3)
    sync.maybe_sync(1, {"w": delta})
    from dlrover_tpu.ops.quant import wire_decode_tree

    sent = wire_decode_tree(sent_trees[0], {"w": delta})["w"]
    resid = sync._error["w"]
    np.testing.assert_allclose(
        np.asarray(sent + resid), np.asarray(delta), rtol=1e-6
    )
    assert float(jnp.abs(resid).max()) > 0.0


def test_local_sgd_interval_respected():
    calls = []

    def exchange(delta):
        calls.append(1)
        return [delta]

    cfg = LocalSGDConfig(sync_interval=5)
    sync = LocalSGDSynchronizer(cfg, exchange)
    params = {"w": jnp.zeros((2,))}
    sync.maybe_sync(0, params)
    for step in range(1, 21):
        params = sync.maybe_sync(step, {"w": jnp.full((2,), float(step))})
    assert len(calls) == 4  # steps 5, 10, 15, 20


def test_local_sgd_warmup_syncs_every_step():
    calls = []

    def exchange(delta):
        calls.append(1)
        return [delta]

    cfg = LocalSGDConfig(sync_interval=5, warmup_steps=3)
    sync = LocalSGDSynchronizer(cfg, exchange)
    sync.maybe_sync(0, {"w": jnp.zeros((2,))})
    for step in range(1, 4):
        sync.maybe_sync(step, {"w": jnp.ones((2,))})
    assert len(calls) == 3


def _socket_pair_exchange(payloads):
    """Two SocketTransports exchange the given pytrees over real TCP.
    Joins the worker threads and re-raises any captured exception (a
    bare thread would swallow it and fail later as a cryptic None)."""
    t0 = SocketTransport(0, {}, bind_host="127.0.0.1", token="t")
    t1 = SocketTransport(1, {}, bind_host="127.0.0.1", token="t")
    peers = {0: f"127.0.0.1:{t0.port}", 1: f"127.0.0.1:{t1.port}"}
    t0.peers = dict(peers)
    t1.peers = dict(peers)
    out = [None, None]
    errs = [None, None]
    try:

        def run(rank, t):
            try:
                out[rank] = socket_exchange(t)(payloads[rank])
            except Exception as e:  # noqa: BLE001
                errs[rank] = e

        th = [
            threading.Thread(target=run, args=(r, t))
            for r, t in ((0, t0), (1, t1))
        ]
        for t in th:
            t.start()
        for t in th:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        return out
    finally:
        t0.close()
        t1.close()


def test_socket_transport_allgather():
    out = _socket_pair_exchange(
        [{"w": jnp.full((4,), float(rank + 1))} for rank in (0, 1)]
    )
    for rank in (0, 1):
        np.testing.assert_allclose(np.asarray(out[rank][0]["w"]), 1.0)
        np.testing.assert_allclose(np.asarray(out[rank][1]["w"]), 2.0)


def test_compressed_exchange_over_socket_wire():
    """QuantizedArray delta trees survive the real TCP wire: the packed
    npz carries the int8 payload + scales (a registered pytree, so
    _pack_tree/_unpack_tree need no special casing), and both peers
    dequantize to identical trees."""
    from dlrover_tpu.ops.quant import (
        QuantizedArray,
        dequantize_tree,
        quantize_tree,
    )

    deltas = [
        {"w": jnp.full((8192,), 0.5), "small": jnp.ones((4,))},
        {"w": jnp.linspace(-1.0, 1.0, 8192), "small": jnp.zeros((4,))},
    ]
    out = _socket_pair_exchange(
        [quantize_tree(d, bits=8) for d in deltas]
    )
    for rank in (0, 1):
        got = [dequantize_tree(t) for t in out[rank]]
        # large leaf arrived quantized; small leaf exact
        assert isinstance(out[rank][0]["w"], QuantizedArray)
        np.testing.assert_allclose(np.asarray(got[0]["w"]), 0.5, atol=0.01)
        np.testing.assert_allclose(
            np.asarray(got[1]["w"]), np.asarray(deltas[1]["w"]), atol=0.01
        )
        np.testing.assert_array_equal(
            np.asarray(got[rank]["small"]),
            np.asarray(deltas[rank]["small"]),
        )
