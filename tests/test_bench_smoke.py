"""bench.py bit-rot guard: the driver runs bench.py on real hardware at
round end, where an import error or schema regression would surface too
late to fix. Run the cheap pieces here on the CPU mesh."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PALLAS_AXON_POOL_IPS="",
    XLA_FLAGS="--xla_force_host_platform_device_count=1",
    # the sentinel cost probe compiles a SECOND train step per --single
    # run — too expensive for the CPU smoke tier; the schema test turns
    # it back on for exactly one run
    DLROVER_TPU_SENTINEL_PROBE="0",
)


def _run(args, timeout, env_extra=None):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=dict(_ENV, **(env_extra or {})),
        cwd=_REPO,
    )


@pytest.mark.slow  # tier-1 budget: full subprocess bench run; schema readers stay fast
def test_bench_single_tiny_emits_schema():
    out = _run(
        ["--single", "tiny", "2", "64", "none"],
        timeout=240,
        env_extra={"DLROVER_TPU_SENTINEL_PROBE": "1"},
    )
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    for key in ("metric", "value", "unit", "vs_baseline",
                "tokens_per_sec", "flop_expansion_est"):
        assert key in rec, key
    assert rec["unit"] == "fraction_of_peak"
    assert rec["value"] > 0
    # the sentinel cost probe ran and recorded a real on-vs-off delta
    # (the <1% acceptance number is a TPU claim; on CPU just require
    # the probe to have produced a measurement, not fallen to None)
    assert rec["sentinel_overhead_frac"] is not None


@pytest.mark.slow  # tier-1 budget: full subprocess bench run; schema readers stay fast
def test_bench_single_block_k_mode():
    """Fused-block bench (block_k>1): same schema as block_k=1, plus the
    block fields, so the k=8-vs-k=1 host-overhead comparison stays
    runnable on real hardware."""
    out = _run(
        ["--single", "tiny", "2", "64", "none", "bfloat16", "4"],
        timeout=240,
    )
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["block_k"] == 4
    assert ",k4," in rec["metric"]
    assert rec["value"] > 0
    assert rec["host_dispatch_us_per_step"] >= 0


def test_bench_aux_modes_cpu_safe():
    # kernel check short-circuits true off-TPU; ceiling returns {}
    out = _run(["--check"], timeout=120)
    assert out.returncode == 0
    assert json.loads(out.stdout.strip().splitlines()[-1]) == {
        "kernels_ok": True
    }
    out = _run(["--ceiling"], timeout=120)
    assert out.returncode == 0
    assert json.loads(out.stdout.strip().splitlines()[-1]) == {}


@pytest.mark.slow  # tier-1 budget: full subprocess bench run; schema readers stay fast
def test_bench_single_save_qkv_offload_recipe():
    """The promoted gpt2 remat policy runs end-to-end on CPU (offload
    residency is a no-op there; the policy/plumbing is what's smoked)."""
    out = _run(
        ["--single", "tiny", "2", "64", "save_qkv_offload"], timeout=240
    )
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert rec["flop_expansion_est"] == pytest.approx(1.233, abs=1e-3)


def test_attempt_budgets_fit_deadline():
    """The documented `timeout 900 python bench.py` must always reach
    the tiny config: per-attempt budgets may not exceed the deadline."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    assert sum(a[4] for a in bench._ATTEMPTS) <= bench._DEADLINE_S
    # the seq-matched companion must stay locked to the ladder
    assert bench._BASELINE_SEQ_COMPANION == bench._ATTEMPTS[1][:4]


def test_gpt2_attempt_promoted_off_full_remat():
    """ISSUE 3 acceptance: the gpt2-1.5b attempt (and thus the fallback
    block, which derives from it) runs an offload remat policy, not
    full; the on-device kernel gate covers the narrow d=64 head shape."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    assert bench._GPT2_FALLBACK[0] == "gpt2-1.5b"
    assert bench._GPT2_FALLBACK[3] == "save_qkv_offload"
    assert "save_qkv_offload" in bench._FLOP_EXPANSION
    assert any(d == 64 for _h, d in bench._KERNEL_CHECK_SHAPES)
    # the narrow shape must exercise auto head-packing incl. odd heads
    assert (25, 64) in bench._KERNEL_CHECK_SHAPES


def test_failure_classifier_buckets():
    """Failed attempts now emit a machine-readable `failure` field so
    the round-end driver can tell an OOM (retry smaller batch) from a
    compile error (fix the kernel) from a deadline kill."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    cf = bench._classify_failure
    assert cf(1, "RESOURCE_EXHAUSTED: out of HBM") == "oom"
    assert cf(1, "jaxlib ... ResourceExhausted while allocating") == "oom"
    assert cf(1, "Allocation failure on device") == "oom"
    assert cf(1, "Mosaic lowering failed for fused kernel") == \
        "compile_error"
    assert cf(1, "XlaCompile: Compilation failure in backend") == \
        "compile_error"
    assert cf(None, "") == "timeout"
    assert cf(None, "anything at all") == "timeout"
    assert cf(2, "Traceback (most recent call last): ValueError") == \
        "error"
    # OOM wins over compile wording when both appear (an OOM during
    # compilation is still actionable as an OOM)
    assert cf(1, "Compilation failure: RESOURCE_EXHAUSTED") == "oom"


def test_overlap_and_bucket_models_scale_with_zero2():
    """zero2 pays the gradient exchange once per microbatch: the
    overlap estimate scales the reduce-scatter wire by grad_accum, and
    the suggested bucket grows so the recurring launch cost stays
    amortized. zero1 (one deferred exchange) passes through unscaled."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    stats = {"bytes_by_op": {"reduce-scatter": 1e8, "all-gather": 1e8}}
    base = bench.overlap_report(stats, step_us=10_000.0)
    z1 = bench.overlap_report(
        stats, step_us=10_000.0, grad_accum=4, update_mode="zero1"
    )
    z2 = bench.overlap_report(
        stats, step_us=10_000.0, grad_accum=4, update_mode="zero2"
    )
    rs = lambda r: r["per_op"]["reduce-scatter"]["wire_us"]  # noqa: E731
    assert rs(z1) == rs(base)
    assert rs(z2) == pytest.approx(4 * rs(base))
    # the all-gather param return happens once per step either way
    assert z2["per_op"]["all-gather"]["wire_us"] == \
        pytest.approx(base["per_op"]["all-gather"]["wire_us"])

    grad_bytes = 4e9
    mb1 = bench.suggest_bucket_mb(grad_bytes, launch_us=100.0)
    mb2 = bench.suggest_bucket_mb(
        grad_bytes, launch_us=100.0, grad_accum=4, update_mode="zero2"
    )
    assert mb2 >= mb1
    # zero1 with accum is a single exchange: same answer as accum=1
    assert bench.suggest_bucket_mb(
        grad_bytes, launch_us=100.0, grad_accum=4, update_mode="zero1"
    ) == mb1


def test_drill_recovery_metric_reads_artifact(tmp_path, monkeypatch):
    """The bench record embeds the eviction drill's recovery_s so the
    BENCH and DRILL artifacts share one comparable trajectory number."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    p = tmp_path / "DRILL_test.json"
    p.write_text(json.dumps({
        "recovery_budget_s": 30,
        "failures": [
            {"kind": "slice_loss", "recovery_s": 4.2},
            {
                "kind": "host_eviction_live_reshard",
                "recovery_s": 1.7,
                "restore_tier": "live",
            },
        ],
    }))
    got = bench.drill_recovery_metric(str(p))
    assert got["recovery_s"] == pytest.approx(4.2)
    assert got["kind"] == "slice_loss"
    assert got["live_reshard_recovery_s"] == pytest.approx(1.7)
    assert got["budget_s"] == 30
    assert got["n_failures"] == 2
    # env override wins; missing/corrupt artifacts degrade to None
    monkeypatch.setenv("DLROVER_TPU_DRILL_ARTIFACT", str(p))
    assert bench.drill_recovery_metric()["recovery_s"] == \
        pytest.approx(4.2)
    assert bench.drill_recovery_metric(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench.drill_recovery_metric(str(bad)) is None
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"failures": []}))
    assert bench.drill_recovery_metric(str(empty)) is None


def test_nonmatmul_residue_derivation():
    """`nonmatmul_us_per_step` = step time minus the matmuls-only
    counterfactual (executed flops at the shape's measured chained-
    matmul rate), clamped at 0, absent without a measured ceiling."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    rec = {
        "tokens_per_sec": 100_000.0,
        "mxu_tflops": 150.0,
        "mxu_ceiling_frac": 0.75,
        "model_tflops_per_sec": 100.0,
    }
    # step = 8192/1e5 s = 81920us; shape_rate = (150/0.75)*0.75 = 150;
    # residue = 81920 * (1 - 100/150)
    got = bench._nonmatmul_us_per_step(rec, "llama-1.4b", 1, 8192, "none")
    assert got == pytest.approx(81920 * (1 - 100 / 150), abs=0.1)
    # faster-than-ceiling (long-seq flash) clamps to 0, never negative
    fast = dict(rec, model_tflops_per_sec=200.0)
    assert bench._nonmatmul_us_per_step(
        fast, "llama-1.4b", 1, 8192, "none"
    ) == 0.0
    # CPU smoke runs carry no ceiling -> no field
    assert bench._nonmatmul_us_per_step(
        {"tokens_per_sec": 1.0}, "llama-1.4b", 1, 8192, "none"
    ) is None
    # gpt2 family is judged against its own shape-set ceiling
    g = dict(rec, mxu_ceiling_frac_gpt2_shapes=0.5)
    got_g = bench._nonmatmul_us_per_step(g, "gpt2-1.5b", 1, 8192, "none")
    # shape_rate = (150/0.75)*0.5 = 100 -> executed == rate -> 0 residue
    assert got_g == 0.0
    # remat expansion raises executed flops and shrinks the residue
    assert bench._nonmatmul_us_per_step(
        rec, "llama-1.4b", 1, 8192, "full"
    ) < bench._nonmatmul_us_per_step(rec, "llama-1.4b", 1, 8192, "none")


@pytest.mark.slow  # a full threaded serve run (two jit compiles) in a
# subprocess — the one bench smoke too heavy for the tier-1 budget
def test_bench_serve_mode_emits_schema():
    """`bench.py serve` is the serving half of the trajectory: decode
    tokens/sec at a fixed p99 target plus the paged-KV memory story.
    The headline fields must be present AND measured (non-None), and
    the int8 geometry must beat bf16 residency by >= 1.7x."""
    out = _run(["serve", "int8", "4"], timeout=540)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["unit"] == "new_tokens_per_sec"
    assert rec["serve_tokens_per_s"] is not None
    assert rec["serve_tokens_per_s"] > 0
    assert rec["serve_p99_ms"] is not None
    assert rec["serve_p99_ms"] >= rec["serve_p50_ms"] > 0
    assert rec["p99_target_ms"] > 0
    # per-phase latency axes, measured from the scheduler's log-bucketed
    # histograms: TTFT/TPOT resolve the interactive SLO story that the
    # e2e percentile alone can't
    assert rec["ttft_p99_ms"] >= rec["ttft_p50_ms"] > 0
    assert rec["tpot_p99_ms"] >= rec["tpot_p50_ms"] > 0
    assert rec["queue_wait_p99_ms"] >= 0
    assert rec["ttft_p99_ms"] <= rec["serve_p99_ms"]
    assert rec["kv_cache"]["mode"] == "int8"
    assert rec["kv_cache"]["reduction_vs_bf16"] >= 1.7
    assert (
        rec["kv_cache"]["resident_bytes_int8"]
        < rec["kv_cache"]["resident_bytes_bf16"]
    )
    # the speculative arm rode along: spec-on throughput at the same
    # p99 target plus the measured acceptance rate (reported honestly —
    # no assertion that spec wins on the CPU test backend)
    spec = rec["speculative"]
    assert spec["spec_k"] > 0
    assert spec["tokens_per_s"] > 0
    assert spec["draft_tokens"] > 0
    assert 0.0 <= spec["accept_rate"] <= 1.0
    assert spec["accepted_tokens"] <= spec["draft_tokens"]
    assert spec["speedup_vs_specoff"] > 0
    # the migration drill rode along: kill → first post-migration token
    # on the survivor via the live page-migration path, with the token
    # savings over the re-prefill failover it replaced
    migr = rec["migration"]
    assert migr is not None, "migration drill never reached mid-stream"
    assert migr["path"] == "live"
    assert migr["migrated"] == 2 and migr["re_prefilled"] == 0
    assert migr["bytes_moved"] > 0
    assert migr["tokens_saved_vs_reprefill"] > 0
    assert rec["migration_recovery_s"] is not None
    assert rec["migration_recovery_s"] > 0


@pytest.mark.slow  # tier-1 budget: full subprocess bench run; schema readers stay fast
def test_bench_sparse_serve_mode_emits_schema():
    """`bench.py sparse_serve` is the recommender half of the serving
    trajectory: request QPS at a fixed p99 over the tiered embedding
    stack, prefetch-on vs prefetch-off at the same seed. The acceptance
    bar rides in the artifact: the lookahead prefetcher must be worth
    >= 2x QPS at the calibrated cold-tier profile, and the f32 served
    outputs must be exactly equal between the arms."""
    out = _run(["sparse_serve", "80", "8"], timeout=540)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["unit"] == "requests_per_sec"
    assert rec["sparse_qps"] > 0
    assert rec["sparse_qps_prefetch_off"] > 0
    assert rec["sparse_prefetch_speedup"] >= 2.0
    assert rec["sparse_p99_ms"] > 0
    assert rec["sparse_p99_target_ms"] > 0
    assert rec["sparse_p99_met"] is True
    # correctness half: prefetch moves rows between tiers, never values
    assert rec["sparse_outputs_exact_equal"] is True
    tiers = rec["tiers"]
    on, off = tiers["prefetch_on"], tiers["prefetch_off"]
    # calibrated profile: the off arm faulted essentially everything in
    # the request path; the on arm's prefetcher absorbed most of it
    assert off["cold_faults"] > 0 and off["prefetch_coverage"] == 0.0
    assert on["prefetched"] > 0
    assert on["prefetch_coverage"] > 0.5
    assert on["hot_hit_rate"] > off["hot_hit_rate"]
    assert 0.0 <= on["hot_hit_rate"] <= 1.0
    assert on["promote_latency_avg_ms"] >= 0
    # both arms served the whole trace out of the same row population
    assert rec["demoted_rows"] > 0
    assert on["hot_rows"] == off["hot_rows"]


def test_sparse_serving_trajectory_metric_reads_artifact(
    tmp_path, monkeypatch
):
    """The train record embeds the last sparse-serving bench's
    QPS-at-p99 + tier gauges from its own SPARSE_SERVE_*.json artifact
    family — old SERVE_*.json artifacts replay byte-for-byte unchanged
    (pinned in test_serving_trajectory_metric_reads_artifact)."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    p = tmp_path / "SPARSE_SERVE_test.json"
    p.write_text(json.dumps({
        "sparse_qps": 310.5,
        "sparse_p99_ms": 240.0,
        "sparse_p99_target_ms": 10000.0,
        "sparse_p99_met": True,
        "sparse_prefetch_speedup": 7.1,
        "sparse_outputs_exact_equal": True,
        "tiers": {"prefetch_on": {
            "hot_hit_rate": 0.97, "prefetch_coverage": 0.98,
            "promote_latency_avg_ms": 9.2,
        }},
    }))
    got = bench.sparse_serving_trajectory_metric(str(p))
    assert got == {
        "sparse_qps": 310.5,
        "sparse_p99_ms": 240.0,
        "sparse_p99_target_ms": 10000.0,
        "sparse_p99_met": True,
        "sparse_prefetch_speedup": 7.1,
        "sparse_outputs_exact_equal": True,
        "sparse_hot_hit_rate": 0.97,
        "sparse_prefetch_coverage": 0.98,
        "sparse_promote_latency_avg_ms": 9.2,
    }
    monkeypatch.setenv("DLROVER_TPU_SPARSE_SERVE_ARTIFACT", str(p))
    assert bench.sparse_serving_trajectory_metric()["sparse_qps"] == \
        pytest.approx(310.5)
    # a tiers-less artifact projects only the headline block
    bare = tmp_path / "SPARSE_SERVE_bare.json"
    bare.write_text(json.dumps({"sparse_qps": 100.0}))
    got_bare = bench.sparse_serving_trajectory_metric(str(bare))
    assert got_bare["sparse_qps"] == pytest.approx(100.0)
    assert "sparse_hot_hit_rate" not in got_bare
    # missing/corrupt/unmeasured artifacts degrade to None
    assert bench.sparse_serving_trajectory_metric(
        str(tmp_path / "nope.json")
    ) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench.sparse_serving_trajectory_metric(str(bad)) is None
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"sparse_qps": None}))
    assert bench.sparse_serving_trajectory_metric(str(empty)) is None
    # an old SERVE artifact is NOT a sparse artifact: the reader wants
    # the sparse headline and degrades to None rather than projecting
    old_serve = tmp_path / "SERVE_old.json"
    old_serve.write_text(json.dumps({
        "serve_tokens_per_s": 123.4, "serve_p99_ms": 80.5,
    }))
    assert bench.sparse_serving_trajectory_metric(str(old_serve)) is None


def test_serving_trajectory_metric_reads_artifact(tmp_path, monkeypatch):
    """The train bench record embeds the last serving bench's
    tokens/s-at-p99 (same cross-artifact pattern as the drill metric)."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    p = tmp_path / "SERVE_test.json"
    p.write_text(json.dumps({
        "serve_tokens_per_s": 123.4,
        "serve_p99_ms": 80.5,
        "p99_target_ms": 200.0,
        "p99_met": True,
    }))
    got = bench.serving_trajectory_metric(str(p))
    assert got == {
        "serve_tokens_per_s": 123.4,
        "serve_p99_ms": 80.5,
        "p99_target_ms": 200.0,
        "p99_met": True,
    }
    monkeypatch.setenv("DLROVER_TPU_SERVE_ARTIFACT", str(p))
    assert bench.serving_trajectory_metric()["serve_tokens_per_s"] == \
        pytest.approx(123.4)
    # a spec-bearing artifact projects the speculative headline too
    pspec = tmp_path / "SERVE_spec.json"
    pspec.write_text(json.dumps({
        "serve_tokens_per_s": 123.4,
        "serve_p99_ms": 80.5,
        "p99_target_ms": 200.0,
        "p99_met": True,
        "speculative": {
            "spec_k": 3, "tokens_per_s": 150.0, "accept_rate": 0.62,
            "speedup_vs_specoff": 1.21, "draft_tokens": 90,
            "accepted_tokens": 56, "p99_ms": 70.0, "p99_met": True,
        },
    }))
    got_spec = bench.serving_trajectory_metric(str(pspec))
    assert got_spec["spec_tokens_per_s"] == pytest.approx(150.0)
    assert got_spec["spec_accept_rate"] == pytest.approx(0.62)
    assert got_spec["spec_speedup_vs_specoff"] == pytest.approx(1.21)
    # a phase-latency-bearing artifact projects the ttft/tpot axes;
    # older artifacts (the minimal one above) simply omit them
    pphase = tmp_path / "SERVE_phase.json"
    pphase.write_text(json.dumps({
        "serve_tokens_per_s": 123.4,
        "serve_p99_ms": 80.5,
        "ttft_p50_ms": 12.0, "ttft_p99_ms": 30.0,
        "tpot_p50_ms": 2.5, "tpot_p99_ms": 4.0,
        "queue_wait_p99_ms": 1.5,
    }))
    got_phase = bench.serving_trajectory_metric(str(pphase))
    assert got_phase["ttft_p99_ms"] == pytest.approx(30.0)
    assert got_phase["tpot_p50_ms"] == pytest.approx(2.5)
    assert got_phase["queue_wait_p99_ms"] == pytest.approx(1.5)
    assert "ttft_p99_ms" not in got  # old artifacts stay exact-shape
    # a migration-bearing artifact projects the recovery headline too
    pmig = tmp_path / "SERVE_mig.json"
    pmig.write_text(json.dumps({
        "serve_tokens_per_s": 99.0,
        "serve_p99_ms": 70.0,
        "migration_recovery_s": 0.42,
        "migration": {
            "path": "live", "migrated": 2, "re_prefilled": 0,
            "bytes_moved": 4096, "tokens_saved_vs_reprefill": 17,
        },
    }))
    got_mig = bench.serving_trajectory_metric(str(pmig))
    assert got_mig["migration_recovery_s"] == pytest.approx(0.42)
    assert got_mig["migration_path"] == "live"
    assert got_mig["migration_tokens_saved"] == 17
    # an autoscale-bearing artifact projects the SLO-goodput headline;
    # pre-autoscaler artifacts simply lack the block and replay with
    # the exact shape pinned above
    pasc = tmp_path / "SERVE_asc.json"
    pasc.write_text(json.dumps({
        "serve_tokens_per_s": 99.0,
        "serve_p99_ms": 70.0,
        "autoscale": {
            "p99_target_ms": 120.0,
            "fleet_tokens_per_s_at_p99": 150.0,
            "autoscale_reaction_s": 0.31,
            "scale_decisions": 1,
            "goodput_win_vs_pinned1": 2.1,
            "bitwise_equal_vs_static2": True,
        },
    }))
    got_asc = bench.serving_trajectory_metric(str(pasc))
    assert got_asc["fleet_tokens_per_s_at_p99"] == pytest.approx(150.0)
    assert got_asc["autoscale_reaction_s"] == pytest.approx(0.31)
    assert got_asc["scale_decisions"] == 1
    assert got_asc["autoscale_goodput_win"] == pytest.approx(2.1)
    assert "fleet_tokens_per_s_at_p99" not in got  # old-artifact replay
    assert "scale_decisions" not in got
    # missing/corrupt/unmeasured artifacts degrade to None
    assert bench.serving_trajectory_metric(
        str(tmp_path / "nope.json")
    ) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench.serving_trajectory_metric(str(bad)) is None
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"serve_tokens_per_s": None}))
    assert bench.serving_trajectory_metric(str(empty)) is None
    # the tuned arm lives in the TRAIN record, not the serve artifact:
    # old SERVE_*.json files replay with the exact shapes pinned above
    # and never grow a "tuned" key
    assert "tuned" not in got and "tuned" not in got_asc
    # the sparse arm has its OWN artifact family (SPARSE_SERVE_*.json):
    # old SERVE artifacts replay unchanged and never grow sparse keys
    for g in (got, got_spec, got_phase, got_mig, got_asc):
        assert not any(k.startswith("sparse_") for k in g)


def test_tuned_arm_metric_schema():
    """The ``tuned`` block of the train record: cold-start plan vs the
    hand-tuned row (CPU-modeled MFU fraction) plus the live-refinement
    reaction drill. In-process and cheap — no subprocess bench run."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.remove(_REPO)
    got = bench.tuned_arm_metric("tiny", 2, 64, "none")
    assert "error" not in got, got
    for key in ("planned", "hand", "match", "cold_start_mfu_frac",
                "modeled_chip", "reaction_s", "reaction_knob",
                "reaction_version"):
        assert key in got, key
    for key in ("batch", "remat", "block_k", "comm_bucket_mb",
                "update_sharding", "comm_wire_dtype"):
        assert key in got["planned"], key
    assert got["hand"] == {"batch": 2, "remat": "none"}
    # acceptance bar: the zero-config plan models >= 95% of the
    # hand-tuned row's MFU
    assert got["cold_start_mfu_frac"] >= 0.95
    # off-TPU the plan is modeled against the reference chip the
    # flagship ladder was hand-tuned for
    assert got["modeled_chip"] == "v5e"
    # the synthetic overlap-drift regression produced a versioned
    # revision, and doing so took real (non-negative) wall time
    assert got["reaction_knob"] == "comm_bucket_mb"
    assert got["reaction_version"] >= 1
    assert got["reaction_s"] >= 0
    # the flagship shape reproduces the hand recipe exactly
    flagship = bench.tuned_arm_metric("llama-1.4b", 1, 8192, "save_qkv")
    assert "error" not in flagship, flagship
    assert flagship["match"] is True
    assert flagship["cold_start_mfu_frac"] == pytest.approx(1.0)
    # a brain regression degrades to an error record, never a raise
    assert "error" in bench.tuned_arm_metric("no-such-model", 1, 64, "none")
