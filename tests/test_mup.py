"""muP: infshape classification, lr multipliers, width-transfer coord check."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.models.config import mup_base_config
from dlrover_tpu.train.mup import (
    InfShape,
    coord_check_stats,
    get_shapes,
    mu_adam,
    mu_sgd,
    rescale_init,
    scale_by_infshape,
    zip_infshapes,
)


def test_infshape_classification():
    assert InfShape((256, 1024), (256, 64)).kind == "input"     # embed [v,d]
    assert InfShape((1024, 1024), (64, 64)).kind == "hidden"
    assert InfShape((1024, 256), (64, 256)).kind == "output"    # head [d,v]
    assert InfShape((1024,), (64,)).kind == "vector"
    assert InfShape((256, 256), (256, 256)).kind == "vector"    # no inf dims
    assert InfShape((4, 1024, 1024), (4, 64, 64)).kind == "hidden"  # stacked
    assert InfShape((1024, 1024), (64, 64)).fan_in_mult == 16.0


@pytest.mark.slow
def test_zip_infshapes_on_decoder_params():
    cfg = get_config("tiny", d_model=256, d_ff=1024, mup_base_width=64,
                     n_layer=2)
    base_cfg = mup_base_config(cfg)
    params = decoder.init(jax.random.key(0), cfg)
    base_shapes = get_shapes(decoder.init(jax.random.key(0), base_cfg))
    infs = zip_infshapes(base_shapes, params)
    assert infs["embed"]["tokens"].kind == "input"
    assert infs["layers"]["attn"]["wq"].kind == "hidden"
    assert infs["layers"]["mlp"]["w_down"].kind == "hidden"
    # stacked norm scales [L, d] classify as input (indistinguishable from
    # an embedding by shape alone) — harmless: input and vector get the
    # same lr multiplier under both the adam and sgd rules
    assert infs["layers"]["ln1"]["scale"].kind in ("input", "vector")
    assert infs["layers"]["attn"]["wq"].fan_in_mult == 4.0


def test_scale_by_infshape_multipliers():
    infs = {
        "hidden": InfShape((128, 128), (32, 32)),   # mult 4
        "embed": InfShape((10, 128), (10, 32)),
        "bias": InfShape((128,), (32,)),
    }
    tx = scale_by_infshape(infs, "adam")
    updates = {k: jnp.ones(s.shape) for k, s in infs.items()}
    out, _ = tx.update(updates, tx.init(updates))
    assert float(out["hidden"][0, 0]) == pytest.approx(0.25)
    assert float(out["embed"][0, 0]) == 1.0
    assert float(out["bias"][0]) == 1.0
    # SGD rule: input/vector scale UP with fan_out growth
    tx = scale_by_infshape(infs, "sgd")
    out, _ = tx.update(updates, tx.init(updates))
    assert float(out["hidden"][0, 0]) == 1.0
    assert float(out["embed"][0, 0]) == 4.0
    assert float(out["bias"][0]) == 4.0


def _mlp_init(key, d_in, d, d_out):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w_in": jax.random.normal(k1, (d_in, d)) / np.sqrt(d_in),
        "w_h": jax.random.normal(k2, (d, d)) / np.sqrt(d),
        "w_out": jax.random.normal(k3, (d, d_out)) / np.sqrt(d),
    }


def _mlp_fwd(p, x, mult=1.0):
    h = jax.nn.relu(x @ p["w_in"])
    h = jax.nn.relu(h @ p["w_h"])
    return h @ p["w_out"] * mult, h


def _train_and_measure(width, base_width, mup: bool, steps=3, lr=0.01):
    # few steps at small lr: the overparametrized SP model must not
    # converge (vanishing gradients would mask its width blowup)
    d_in, d_out = 16, 4
    key = jax.random.key(0)
    params = _mlp_init(key, d_in, width, d_out)
    base_shapes = get_shapes(_mlp_init(key, d_in, base_width, d_out))
    infs = zip_infshapes(base_shapes, params)
    # w_out is an untied output-class weight: muP handles it entirely via
    # rescale_init + mu_adam (no logit multiplier)
    mult = 1.0
    if mup:
        params = rescale_init(params, infs)
        tx = mu_adam(lr, infs)
    else:
        tx = optax.adam(lr)
    x = jax.random.normal(jax.random.key(1), (32, d_in))
    y = jax.random.normal(jax.random.key(2), (32, d_out))
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        def loss(p):
            out, _ = _mlp_fwd(p, x, mult)
            return jnp.mean((out - y) ** 2)

        g = jax.grad(loss)(params)
        upd, state2 = tx.update(g, state, params)
        return optax.apply_updates(params, upd), state2

    _, h0 = _mlp_fwd(params, x, mult)
    for _ in range(steps):
        params, state = step(params, state)
    _, h = _mlp_fwd(params, x, mult)
    # the muP coordinate-check quantity: how much training MOVED the
    # features (the init contribution is O(1) in any parametrization)
    return coord_check_stats({"dh": h - h0})["['dh']"]


def test_coord_check_width_transfer():
    """muP: the training-induced feature change stays O(1) as width grows
    16x; standard parametrization + Adam grows it with width."""
    base = 64
    mup_small = _train_and_measure(base, base, mup=True)
    mup_big = _train_and_measure(base * 16, base, mup=True)
    sp_small = _train_and_measure(base, base, mup=False)
    sp_big = _train_and_measure(base * 16, base, mup=False)
    mup_ratio = mup_big / mup_small
    sp_ratio = sp_big / sp_small
    assert 1 / 3 < mup_ratio < 3, f"muP coord check failed: {mup_ratio}"
    assert sp_ratio > mup_ratio * 2, (
        f"SP should blow up vs muP: sp={sp_ratio} mup={mup_ratio}"
    )


def test_mup_decoder_forward_runs():
    cfg = get_config("tiny", d_model=128, mup_base_width=32, n_layer=2,
                     max_seq=64)
    params = decoder.init(jax.random.key(0), cfg)
    toks = jnp.zeros((2, 64), jnp.int32)
    logits = decoder.forward(params, toks, cfg)
    assert logits.shape == (2, 64, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_mu_sgd_runs():
    params = {"w": jnp.ones((8, 8))}
    infs = {"w": InfShape((8, 8), (4, 4))}
    tx = mu_sgd(0.1, infs, momentum=0.9)
    state = tx.init(params)
    upd, _ = tx.update({"w": jnp.ones((8, 8))}, state, params)
    assert upd["w"].shape == (8, 8)
