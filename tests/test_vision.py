"""ViT + CLIP model family tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import vision
from dlrover_tpu.models.vision import (
    VIT_CONFIGS,
    clip_loss,
    clip_logical_axes,
    clip_tiny_test,
    encode_image,
    encode_text,
    forward_vit,
    init_clip,
    init_vit,
    patchify,
    vit_logical_axes,
)
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel import sharding as shd

# CLIP training runs are heavy on the CPU mesh; excluded from the tier-1 budget
pytestmark = pytest.mark.slow


def test_patchify_layout():
    # pixel (y, x) of patch (gy, gx) must land at patch index gy*gw+gx
    img = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
    p = patchify(img, 4)
    assert p.shape == (2, 4, 4 * 4 * 3)
    np.testing.assert_array_equal(
        np.asarray(p[0, 1]),  # patch (0,1): rows 0..3, cols 4..7
        np.asarray(img[0, 0:4, 4:8, :].reshape(-1)),
    )


def test_vit_forward_shapes():
    cfg = VIT_CONFIGS["vit-tiny-test"]
    params = init_vit(jax.random.key(0), cfg)
    imgs = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    feats = forward_vit(params, imgs, cfg)
    assert feats.shape == (2, cfg.trunk.d_model)
    toks = forward_vit(params, imgs, cfg, features_only=True)
    assert toks.shape == (2, cfg.seq_len, cfg.trunk.d_model)


def test_vit_mean_pool():
    import dataclasses

    cfg = dataclasses.replace(VIT_CONFIGS["vit-tiny-test"], pool="mean")
    params = init_vit(jax.random.key(0), cfg)
    assert "cls_token" not in params
    imgs = jnp.ones((2, 32, 32, 3))
    assert forward_vit(params, imgs, cfg).shape == (2, cfg.trunk.d_model)


def test_vit_logical_axes_match_params():
    cfg = VIT_CONFIGS["vit-tiny-test"]
    params = init_vit(jax.random.key(0), cfg)
    axes = vit_logical_axes(cfg)
    is_leaf = lambda x: x is None or isinstance(x, tuple)  # noqa: E731
    assert jax.tree.structure(params) == jax.tree.structure(
        axes, is_leaf=is_leaf
    )
    for p, a in zip(
        jax.tree.leaves(params), jax.tree.leaves(axes, is_leaf=is_leaf)
    ):
        if a is not None:
            assert len(a) == p.ndim


def _toy_batch(rng, b=8):
    """Correlated (image, text) pairs: class c colors the image and is
    the text token sequence."""
    cls = jax.random.randint(rng, (b,), 0, 8)
    shades = jax.random.normal(jax.random.key(7), (8, 3))
    imgs = jnp.broadcast_to(
        shades[cls][:, None, None, :], (b, 32, 32, 3)
    )
    tokens = jnp.broadcast_to((cls + 1)[:, None], (b, 8)).astype(jnp.int32)
    return {"images": imgs, "tokens": tokens}


def test_clip_loss_decreases():
    cfg = clip_tiny_test()
    params = init_clip(jax.random.key(0), cfg)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(
            clip_loss, has_aux=True
        )(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, metrics

    losses = []
    for i in range(30):
        batch = _toy_batch(jax.random.key(i % 4))
        params, opt_state, m = step(params, opt_state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_clip_encoders_normalized():
    cfg = clip_tiny_test()
    params = init_clip(jax.random.key(0), cfg)
    batch = _toy_batch(jax.random.key(1))
    img = encode_image(params, batch["images"], cfg)
    txt = encode_text(params, batch["tokens"], cfg)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(img), axis=-1), 1.0, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(txt), axis=-1), 1.0, rtol=1e-5
    )


def test_clip_sharded_matches_single():
    cfg = clip_tiny_test()
    params = init_clip(jax.random.key(0), cfg)
    batch = _toy_batch(jax.random.key(2))
    loss_ref, _ = jax.jit(
        lambda p, b: clip_loss(p, b, cfg)
    )(params, batch)

    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    axes = clip_logical_axes(cfg)
    shardings = shd.shardings_for_tree(mesh, axes)
    params_s = jax.device_put(params, shardings)
    bs = shd.shardings_for_tree(
        mesh,
        {
            "images": ("batch", None, None, None),
            "tokens": ("batch", None),
        },
    )
    batch_s = jax.device_put(batch, bs)
    loss_sharded, _ = jax.jit(
        lambda p, b: clip_loss(p, b, cfg, mesh=mesh)
    )(params_s, batch_s)
    np.testing.assert_allclose(
        float(loss_ref), float(loss_sharded), rtol=2e-3
    )
