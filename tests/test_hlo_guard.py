"""HLO regression guard for the non-matmul byte budget.

Lowers the lead bench shape (llama-1.4b, b1 x s8192, save_qkv remat,
bf16 moments) on CPU and counts ``convert`` ops that materialize a
full ``[B, S, d_model]`` activation in f32. Every such convert is an
extra HBM round-trip at 4 bytes/elem, so an unexplained increase is
exactly the regression class this PR closes (norms that upcast and
write back, optimizer passes that re-expand activations, etc.).

The pin is an upper bound over the *declared* f32 sites in the current
program (located by lowering and grouping converts per HLO function):

  forward scan body:  ln1 + ln2 norm upcasts (2)
  remat replay body:  the same two norms recomputed for bwd (2)
  backward scan body: stream/cotangent upcasts in the norm bwds (4)
  top level:          final-norm upcast, fused-CE hidden upcast, and
                      the embed-grad accumulation upcast (3)

Anything beyond these 11 means a new full-activation f32 tensor crept
into the step program. Lowering only (no compile), so this stays in
tier-1 time budget (<2s).
"""

import re

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.models import get_config
from dlrover_tpu.parallel.mesh import single_device_mesh
from dlrover_tpu.train import TrainStepBuilder, make_optimizer
from dlrover_tpu.train.train_step import abstract_train_state

_B, _S = 1, 8192
_MAX_FULL_F32_CONVERTS = 11


@pytest.fixture(scope="module")
def lead_step_hlo():
    cfg = get_config(
        "llama-1.4b", max_seq=_S, remat="save_qkv", param_dtype="bfloat16"
    )
    mesh = single_device_mesh()
    opt = make_optimizer(
        learning_rate=1e-4,
        warmup_steps=10,
        decay_steps=1000,
        state_dtype="bfloat16",
    )
    state_abs = abstract_train_state(cfg, mesh, opt)
    batch_abs = {
        "tokens": jax.ShapeDtypeStruct((_B, _S), jnp.int32),
        "targets": jax.ShapeDtypeStruct((_B, _S), jnp.int32),
    }
    builder = TrainStepBuilder(cfg, mesh, opt)
    lowered = jax.jit(builder.step_fn, donate_argnums=(0,)).lower(
        state_abs, batch_abs
    )
    return cfg, lowered.as_text()


def test_no_new_full_activation_f32_converts(lead_step_hlo):
    cfg, txt = lead_step_hlo
    full = rf"stablehlo\.convert.*->\s*tensor<{_B}x{_S}x{cfg.d_model}xf32>"
    n = len(re.findall(full, txt))
    assert 0 < n <= _MAX_FULL_F32_CONVERTS, (
        f"{n} full-activation f32 converts in the lead-shape step "
        f"(budget {_MAX_FULL_F32_CONVERTS}). A new [B,S,d_model] f32 "
        "tensor entered the program — check norm/loss/optimizer edits "
        "for stray upcasts that round-trip the whole activation."
    )


def test_no_f32_residual_stream_carries(lead_step_hlo):
    """The scan carry (residual stream between layers) must stay in the
    compute dtype — an f32 carry would double the inter-layer HBM
    traffic for every one of the 24 layers."""
    cfg, txt = lead_step_hlo
    # while-loop carries show up as iota-indexed dynamic-update-slices
    # over a stacked [L, B, S, d] buffer; an f32 stacked stream buffer
    # would read tensor<24x1x8192x2048xf32>.
    stacked = rf"tensor<{cfg.n_layer}x{_B}x{_S}x{cfg.d_model}xf32>"
    assert not re.search(stacked, txt), (
        "found a stacked f32 residual-stream buffer in the lowered "
        "step — the layer scan carry was upcast to f32"
    )
