"""DeepFM over the sparse tier: learning + checkpoint round-trip."""

import numpy as np
import pytest

from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.sparse import GroupAdam


def synthetic_ctr(rng, n, cfg):
    """Clicks driven by a hidden affinity of (field, id) pairs so the
    embeddings have something real to learn."""
    cat = rng.integers(0, 50, size=(n, cfg.n_fields))
    dense = rng.normal(size=(n, cfg.n_dense)).astype(np.float32)
    # ground truth: some ids are "hot"
    hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
    p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
    labels = (rng.random(n) < p).astype(np.float32)
    return cat.astype(np.int64), dense, labels


@pytest.fixture(scope="module")
def cfg():
    return DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))


def test_deepfm_learns(cfg):
    rng = np.random.default_rng(0)
    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    cat, dense, labels = synthetic_ctr(rng, 512, cfg)
    first = model.train_step(cat, dense, labels)
    losses = [model.train_step(cat, dense, labels) for _ in range(40)]
    assert losses[-1] < first * 0.8, (first, losses[-1])
    # predictions correlate with labels
    p = model.predict(cat, dense)
    assert p.shape == (512,)
    auc_proxy = np.mean(p[labels == 1]) - np.mean(p[labels == 0])
    assert auc_proxy > 0.05
    model.close()


def test_deepfm_checkpoint_roundtrip(cfg, tmp_path):
    rng = np.random.default_rng(1)
    model = DeepFM(cfg)
    cat, dense, labels = synthetic_ctr(rng, 128, cfg)
    for _ in range(3):
        model.train_step(cat, dense, labels)
    before = model.predict(cat, dense)
    model.save(str(tmp_path))

    model2 = DeepFM(cfg)
    model2.restore(str(tmp_path))
    after = model2.predict(cat, dense)
    np.testing.assert_allclose(before, after, atol=1e-6)
    model.close(); model2.close()


def test_deepfm_delta_checkpoint(cfg, tmp_path):
    """Incremental export: full snapshot + delta restores to same state."""
    rng = np.random.default_rng(2)
    model = DeepFM(cfg)
    cat, dense, labels = synthetic_ctr(rng, 64, cfg)
    model.train_step(cat, dense, labels)
    model.save(str(tmp_path))                       # full, clears dirty
    model.train_step(cat, dense, labels)            # touches rows again
    model.save(str(tmp_path), delta_only=True)      # delta on top
    import pickle, os
    with open(os.path.join(str(tmp_path), "dense.pkl"), "wb") as f:
        import jax, numpy as _np
        pickle.dump(jax.tree.map(_np.asarray,
                                 (model.dense_params, model.dense_opt_state)), f)
    before = model.predict(cat, dense)

    model2 = DeepFM(cfg)
    model2.restore(str(tmp_path))
    np.testing.assert_allclose(model2.predict(cat, dense), before, atol=1e-6)
    model.close(); model2.close()
