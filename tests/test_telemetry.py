"""Telemetry bus + trace spans: schema lint, zero-cost-off, sinks,
tracer clock/correlation, cross-process merge, HBM aggregation."""

import dataclasses
import json
import time
import tracemalloc

import pytest

import dlrover_tpu.cluster.brain  # noqa: F401 — registers TuningPlan/JobMetrics for the schema lint
from dlrover_tpu.common.constants import GraftEnv
from dlrover_tpu.observability import telemetry, tracing


@pytest.fixture(autouse=True)
def _fresh_bus():
    telemetry.reset_hub()
    tracing.reset_tracer()
    yield
    telemetry.reset_hub()
    tracing.reset_tracer()


# ---------------------------------------------------------------------------
# schema lint (tier-1): every registered record survives the wire
# ---------------------------------------------------------------------------


def _non_default(cls):
    """Instantiate with every field moved off its default, typed from
    the default's own type so new fields are linted automatically."""
    kwargs = {}
    for j, f in enumerate(dataclasses.fields(cls)):
        d = f.default
        if isinstance(d, bool):  # before int: bool is an int subclass
            kwargs[f.name] = not d
        elif isinstance(d, int):
            kwargs[f.name] = d + 13 + j
        elif isinstance(d, float):
            kwargs[f.name] = d + 2.25 + j  # exact binary fraction
        elif isinstance(d, str):
            kwargs[f.name] = f"{f.name}_x{j}"
        else:
            pytest.fail(
                f"{cls.__name__}.{f.name}: non-scalar default {d!r} "
                "breaks the lossless-JSON contract"
            )
    return cls(**kwargs)


def test_every_record_round_trips_losslessly():
    types = telemetry.record_types()
    assert len(types) >= 10  # the bus is not accidentally empty
    for name, cls in types.items():
        rec = _non_default(cls)
        line = rec.to_json()
        back = telemetry.from_json(line)
        assert type(back) is cls, name
        assert back == rec, name
        # and the envelope is one JSON object per line (JsonlSink shape)
        assert "\n" not in line and json.loads(line)["r"] == name


def test_from_json_rejects_unknown_record():
    with pytest.raises(KeyError):
        telemetry.from_json('{"r": "NoSuchRecord", "d": {}}')


def test_old_serving_recordings_replay_with_defaults():
    """Recordings taken BEFORE the speculative-decoding fields existed
    must still replay: ``from_json`` fills absent fields from dataclass
    defaults, so healthcheck replay of an old JSONL never KeyErrors."""
    old_line = json.dumps({
        "r": "ServingRecord",
        "d": {
            "replica": "replica-0", "active_slots": 2, "queue_depth": 1,
            "admitted": 9, "completed": 7, "re_admitted": 0,
            "tokens_per_s": 123.5, "p50_ms": 10.0, "p99_ms": 40.0,
            "ts": 1700000000.0,
        },
    })
    rec = telemetry.from_json(old_line)
    assert isinstance(rec, telemetry.ServingRecord)
    assert rec.completed == 7 and rec.tokens_per_s == 123.5
    # spec fields default cleanly
    assert rec.draft_tokens == 0
    assert rec.accepted_tokens == 0
    assert rec.spec_accept_rate == 0.0
    # phase-latency / drop-counter / histogram-envelope fields (the
    # serving-observability additions) default cleanly too
    assert rec.ttft_p99_ms == 0.0 and rec.tpot_p50_ms == 0.0
    assert rec.queue_wait_p99_ms == 0.0
    assert rec.rejected == 0 and rec.timed_out == 0 and rec.poisoned == 0
    assert rec.hists == ""
    # and a new-style line round-trips the new fields losslessly
    new = telemetry.ServingRecord(
        replica="r", draft_tokens=12, accepted_tokens=8,
        spec_accept_rate=8 / 12, ttft_p50_ms=5.0, ttft_p99_ms=11.0,
        tpot_p50_ms=1.5, tpot_p99_ms=2.0, queue_wait_p99_ms=0.3,
        rejected=2, timed_out=1, poisoned=1,
        hists='{"e2e": {"v": 1}}',
    )
    assert telemetry.from_json(new.to_json()) == new


# ---------------------------------------------------------------------------
# zero-cost when off (tier-1 overhead guard)
# ---------------------------------------------------------------------------


def test_disabled_hub_is_pinned_noop(monkeypatch):
    monkeypatch.delenv(GraftEnv.TELEMETRY_DIR, raising=False)
    hub = telemetry.get_hub()
    assert hub is telemetry.get_hub()  # pinned singleton, not per-call
    assert hub.enabled is False
    # publish resolves to the module no-op function — no bound-method
    # allocation, no record ever reaches it behind the enabled guard
    assert hub.publish is telemetry._noop
    tracemalloc.start()
    base = tracemalloc.get_traced_memory()[0]
    for _ in range(2000):
        h = telemetry.get_hub()
        if h.enabled:  # the producer-side guard from trainer/saver/bench
            pytest.fail("hub must stay disabled without configuration")
    grown = tracemalloc.get_traced_memory()[0] - base
    tracemalloc.stop()
    assert grown < 4096, f"disabled-hub hot path retained {grown}B"


def test_null_tracer_shared_span_discards_writes(monkeypatch):
    monkeypatch.delenv(GraftEnv.TRACE_DIR, raising=False)
    tr = tracing.get_tracer()
    assert tr is tracing.get_tracer() and not tr.enabled
    sp = tr.span("a", k=1)
    assert sp is tr.span("b")  # one shared no-op span, no allocation
    sp.args["pollute"] = 1  # annotating callers must not accumulate
    assert sp.args == {}
    assert sp.end(more=2) == 0.0
    with tr.span("c"):
        pass
    assert tr.events() == []


# ---------------------------------------------------------------------------
# hub fan-out + sinks
# ---------------------------------------------------------------------------


class _FakeCollector:
    def __init__(self):
        self.gauges = {}
        self.counters = {}

    def set_gauge(self, name, value):
        self.gauges[name] = value

    def inc(self, name):
        self.counters[name] = self.counters.get(name, 0) + 1


def test_hub_fanout_stamps_ts_and_detaches_failing_sink(tmp_path):
    class BadSink:
        def emit(self, record):
            raise RuntimeError("boom")

    path = tmp_path / "telemetry.jsonl"
    hub = telemetry.configure_hub(
        sinks=[BadSink()], jsonl_path=str(path)
    )
    assert telemetry.get_hub() is hub and hub.enabled
    got = []
    hub.subscribe(got.append, types=("StepRecord",))

    rec = telemetry.StepRecord(step=3, loss=1.5)
    assert rec.ts == 0.0
    hub.publish(rec)
    assert rec.ts > 0  # stamped at publish
    assert got == [rec]
    hub.publish(telemetry.NumericEvent(kind="nan"))  # type-filtered
    assert got == [rec]
    hub.publish(telemetry.StepRecord(step=4))  # bad sink already detached
    lines = path.read_text().splitlines()
    assert len(lines) == 3
    assert telemetry.from_json(lines[0]) == rec


def test_metrics_sink_projects_gauges_and_counters():
    c = _FakeCollector()
    sink = telemetry.MetricsSink(c)
    sink.emit(telemetry.StepRecord(step=1, loss=2.0, step_time_s=0.1,
                                   tokens_per_s=10.0))
    assert c.gauges["telemetry_loss"] == 2.0
    assert c.gauges["telemetry_tokens_per_s"] == 10.0
    sink.emit(telemetry.ElasticEvent(kind="rendezvous", seconds=1.25))
    assert c.counters["elastic_events_total"] == 1
    assert c.gauges["failover_rendezvous_s"] == 1.25
    sink.emit(telemetry.OverlapDriftRecord(
        step=2, planned_exposed_us=100.0, measured_collective_us=130.0,
        drift_us=30.0, drift_frac=0.3,
    ))
    assert c.gauges["overlap_drift_us"] == 30.0
    assert c.gauges["overlap_drift_frac"] == pytest.approx(0.3)
    sink.emit(telemetry.ResourceRecord(hbm_mb=100.0, hbm_peak_mb=140.0))
    assert c.gauges["hbm_peak_mb"] == 140.0


def test_master_sink_never_forwards_per_step_records():
    class FakeClient:
        def __init__(self):
            self.sent = []

        def report_telemetry(self, line):
            self.sent.append(line)

    cl = FakeClient()
    sink = telemetry.MasterSink(cl)
    sink.emit(telemetry.StepRecord(step=1))  # hot path: no RPC per step
    sink.emit(telemetry.KernelSample(step=1, op="fusion"))
    assert cl.sent == []
    sink.emit(telemetry.ElasticEvent(kind="node_down"))
    sink.emit(telemetry.OverlapDriftRecord(step=2))
    assert len(cl.sent) == 2
    assert isinstance(
        telemetry.from_json(cl.sent[0]), telemetry.ElasticEvent
    )


def test_plan_and_overlap_drift_helpers():
    rec = telemetry.plan_record_from_overlap(
        "gpt2,b8x512",
        {"exposed_us_total": 120.0, "hidden_us_total": 900.0,
         "assumed_ici_gbps": 45.0},
        suggested_bucket_mb=16.0,
        update_sharding_reason="params>=fsdp threshold",
    )
    assert rec.config == "gpt2,b8x512"
    assert rec.planned_exposed_us == 120.0
    assert rec.planned_hidden_us == 900.0
    assert rec.suggested_bucket_mb == 16.0

    class Op:
        def __init__(self, name, us):
            self.name = name
            self.total_us = us

    bd = [Op("fusion.1", 500.0), Op("all-reduce.3", 80.0),
          Op("all-gather-start", 40.0)]
    assert telemetry.measured_collective_us(bd) == 120.0
    d = telemetry.overlap_drift(7, 100.0, bd)
    assert d.measured_collective_us == 120.0
    assert d.drift_us == pytest.approx(20.0)
    assert d.drift_frac == pytest.approx(0.2)
    # pure-measurement mode: nothing planned → frac pinned at 0
    d0 = telemetry.overlap_drift(7, 0.0, bd)
    assert d0.drift_frac == 0.0 and d0.drift_us == 120.0


# ---------------------------------------------------------------------------
# tracer: clock, correlation, span semantics, merge
# ---------------------------------------------------------------------------


def test_tracer_span_carries_correlation_and_streams(monkeypatch, tmp_path):
    monkeypatch.setenv(GraftEnv.RUN_ID, "r1")
    monkeypatch.setenv(GraftEnv.NODE_ID, "1")
    monkeypatch.setenv(GraftEnv.RESTART_COUNT, "2")
    t = tracing.Tracer(role="worker", trace_dir=str(tmp_path))
    with t.span("failover.restore", step=5) as sp:
        time.sleep(0.01)
        sp.args["tier"] = "memory"
    t.instant("failover.first_step", step=6)
    t.counter("hbm", used_mb=3.0)
    t.close()

    evs = t.events()
    x = next(e for e in evs if e["ph"] == "X")
    assert x["name"] == "failover.restore"
    assert x["dur"] >= 5_000  # ≥5ms of the 10ms sleep, µs units
    args = x["args"]
    # identity stamped from env + explicit kwargs + live annotation
    assert args["role"] == "worker" and args["run"] == "r1"
    assert args["node"] == 1 and args["restart"] == 2
    assert args["step"] == 5 and args["tier"] == "memory"
    # wall-anchored monotonic clock lands near real epoch time
    assert abs(x["ts"] / 1e6 - time.time()) < 60
    inst = next(e for e in evs if e["ph"] == "i")
    assert inst["s"] == "p" and inst["args"]["step"] == 6
    ctr = next(e for e in evs if e["ph"] == "C")
    assert ctr["args"]["used_mb"] == 3.0
    # the per-process JSONL stream carries the same three events
    assert len(tracing.merge_trace_dir(str(tmp_path))) == 3


def test_span_end_semantics():
    t = tracing.Tracer(role="agent")
    sp = t.begin("phase")
    time.sleep(0.005)
    s1 = sp.end(k=1)
    s2 = sp.end()  # double-end: no-op returning the recorded duration
    assert s1 == s2 and s1 > 0
    assert len([e for e in t.events() if e["ph"] == "X"]) == 1

    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    ev = next(e for e in t.events() if e["name"] == "boom")
    assert ev["args"]["error"] == "ValueError"

    # an un-ended span records nothing (exception paths opt out)
    t.begin("never.closed")
    assert not any(e["name"] == "never.closed" for e in t.events())


def test_tracer_ring_is_bounded():
    t = tracing.Tracer(role="m", capacity=8)
    for i in range(20):
        t.instant(f"e{i}")
    evs = t.events()
    assert len(evs) == 8 and evs[0]["name"] == "e12"


def test_merge_trace_dir_sorts_and_tolerates_torn_lines(tmp_path):
    (tmp_path / "trace-worker-11.jsonl").write_text(
        json.dumps({"name": "late", "ph": "i", "ts": 2e6,
                    "args": {"role": "worker"}})
        + "\n" + '{"name": "torn tail'
    )
    (tmp_path / "trace-agent-22.jsonl").write_text(
        json.dumps({"name": "failover.x", "ph": "X", "ts": 1e6,
                    "dur": 5e5, "args": {"role": "agent"}}) + "\n"
    )
    out = tmp_path / "merged.jsonl"
    evs = tracing.merge_trace_dir(str(tmp_path), out_path=str(out))
    assert [e["name"] for e in evs] == ["failover.x", "late"]
    assert len(out.read_text().splitlines()) == 2

    iv = tracing.span_intervals(evs, prefix="failover.")
    assert iv == [{
        "name": "failover.x", "start_s": 1.0, "dur_s": 0.5,
        "role": "agent", "args": {"role": "agent"},
    }]


# ---------------------------------------------------------------------------
# agent monitor: HBM aggregation over all local devices
# ---------------------------------------------------------------------------


def test_get_tpu_stats_aggregates_all_local_devices(monkeypatch):
    import jax

    from dlrover_tpu.agent.monitor import get_tpu_stats

    class Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    monkeypatch.setattr(jax, "local_devices", lambda: [
        Dev({"bytes_in_use": 2_000_000, "peak_bytes_in_use": 4_000_000}),
        Dev({"bytes_in_use": 3_000_000, "peak_bytes_in_use": 3_000_000}),
        Dev(None),  # backends without memory_stats report nothing
    ])
    s = get_tpu_stats()
    assert s["hbm_used_mb"] == pytest.approx(5.0)
    assert s["hbm_peak_mb"] == pytest.approx(7.0)  # sum of watermarks
