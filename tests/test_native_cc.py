"""Run the native C++ test binary (reference test-strategy parity:
SURVEY.md §4 lists gtest coverage of the KvVariable C++ kernel —
tfplus kv_variable_test.cc; ours is assert-based, same coverage areas:
CRUD, deterministic init, scatter family, TTL eviction, full/delta
export-import, shard concurrency)."""

from dlrover_tpu.native.build import build_and_run_cc_tests


def test_native_kv_store_cc_suite():
    out = build_and_run_cc_tests()
    assert "all OK" in out
