"""In-graph health sentinels (observability/sentinels.py).

Pins the sentinel contract end to end:

- the count/fraction lanes are exact on synthetic inputs;
- the replicated step surfaces every sentinel key and detects an
  injected NaN-grad fault in-graph;
- replicated vs zero1/zero2 sharded steps agree (counts bitwise where
  the underlying grads agree, norm-order-sensitive lanes by tolerance —
  see the module docstring's parity contract);
- the fused K-step block returns [K]-stacked sentinel streams;
- sentinels add ZERO device-to-host transfers per step (the dispatch
  guard: same jax.device_get call count with sentinels on and off);
- the sanitize_grads plumbing (optimizer wrap, _flat_factory
  re-advertising, TrainerArgs passthrough + external-builder fallback).
"""

import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.observability import sentinels as snt
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel.sharding import CommConfig
from dlrover_tpu.train import (
    Trainer,
    TrainerArgs,
    TrainStepBuilder,
    init_train_state,
    make_optimizer,
)
from dlrover_tpu.train.optimizer import with_grad_sanitizer


@pytest.fixture(autouse=True)
def _run_id(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_RUN_ID", f"snt{os.getpid()}_{time.time_ns()}"
    )


# ---------------------------------------------------------------------------
# unit lanes
# ---------------------------------------------------------------------------


def test_leaf_counts_lanes():
    g = jnp.asarray(
        [
            jnp.nan,          # nonfinite
            jnp.inf,          # nonfinite
            1e5,              # f16 overflow (finite)
            1e-6,             # f16 underflow (nonzero)
            0.0,              # exact zero: excluded from underflow lanes
            1.0,              # plain
            3.4e38,           # bf16 AND f16 overflow (finite in f32)
            2e-38,            # f16 underflow, still a NORMAL f32
        ],
        jnp.float32,
    )
    counts = {
        k: float(v) for k, v in zip(snt.COUNT_KEYS, snt._leaf_counts(g))
    }
    assert counts["sent_nonfinite"] == 2.0
    assert counts["sent_ovf_f16"] == 2.0
    assert counts["sent_und_f16"] == 2.0
    assert counts["sent_ovf_bf16"] == 1.0
    # bf16's min normal IS f32's min normal, so this lane can only
    # count f32 subnormals — which flush to zero on FTZ backends
    # (XLA:CPU included). Nothing here is subnormal, so exactly 0.
    assert counts["sent_und_bf16"] == 0.0


def test_grad_counts_tree_and_padding_invariance():
    """The ZeRO flat stream pads buckets with zeros; zeros must not
    shift any lane, so a padded flat view counts like the leaf tree."""
    tree = {"a": jnp.asarray([1e-6, jnp.nan]), "b": jnp.asarray([2.0])}
    flat_padded = jnp.asarray([1e-6, jnp.nan, 2.0, 0.0, 0.0, 0.0])
    np.testing.assert_array_equal(
        np.asarray(snt.grad_counts(tree)),
        np.asarray(snt.grad_counts(flat_padded)),
    )


def test_counts_to_metrics_static_denominator():
    tree = {"a": jnp.zeros((3, 4)), "b": jnp.zeros(8)}
    assert snt.static_size(tree) == 20
    counts = jnp.asarray([3.0, 2.0, 10.0, 0.0, 1.0])
    m = snt.counts_to_metrics(counts, snt.static_size(tree))
    # nonfinite stays a raw count; range lanes become fractions
    assert float(m["sent_nonfinite"]) == 3.0
    assert float(m["sent_ovf_f16"]) == pytest.approx(2.0 / 20.0)
    assert float(m["sent_und_f16"]) == pytest.approx(10.0 / 20.0)
    assert float(m["sent_und_bf16"]) == pytest.approx(1.0 / 20.0)


def test_update_ratio_and_loss_nonfinite():
    params = {"w": jnp.asarray([3.0, 4.0])}        # ‖p‖ = 5
    updates = {"w": jnp.asarray([0.3, 0.4])}       # ‖u‖ = 0.5
    assert float(snt.update_ratio(updates, params)) == pytest.approx(0.1)
    # zero params: the 1e-12 floor keeps the ratio finite
    zero = {"w": jnp.zeros(2)}
    assert math.isfinite(float(snt.update_ratio(updates, zero)))
    assert float(snt.loss_nonfinite(jnp.float32(1.0))) == 0.0
    assert float(snt.loss_nonfinite(jnp.float32(jnp.nan))) == 1.0
    assert float(snt.loss_nonfinite(jnp.float32(jnp.inf))) == 1.0


def test_fp8_saturation_fraction():
    # history layout [..., H]: newest slot last. One of two histories
    # has newest > max(window) → 0.5
    state = {
        "x": jnp.asarray([[1.0, 2.0, 3.0, 4.0]]),   # 4 > 3: saturating
        "y": jnp.asarray([[5.0, 2.0, 3.0, 4.0]]),   # 4 < 5: fine
    }
    assert float(snt.fp8_saturation(state)) == pytest.approx(0.5)


def test_sanitizer_count_threading():
    params = {"w": jnp.asarray([1.0, 2.0])}
    nan_grads = {"w": jnp.asarray([jnp.nan, 1.0])}
    ok_grads = {"w": jnp.asarray([0.1, 0.1])}

    plain = optax.sgd(0.1)
    assert snt.sanitizer_count(plain.init(params)) is None

    tx = with_grad_sanitizer(optax.sgd(0.1), "skip")
    s = tx.init(params)
    assert float(snt.sanitizer_count(s)) == 0.0
    _, s = tx.update(ok_grads, s, params)
    assert float(snt.sanitizer_count(s)) == 0.0
    upd, s = tx.update(nan_grads, s, params)
    assert float(snt.sanitizer_count(s)) == 1.0
    # the skipped step's update is a no-op, not a NaN write
    assert np.isfinite(np.asarray(jax.tree.leaves(upd)[0])).all()


# ---------------------------------------------------------------------------
# in-step wiring
# ---------------------------------------------------------------------------


def _cfg():
    return get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32,
    )


def _batch(rows=8, seq=32, poison=False, seed=0):
    rng = np.random.RandomState(seed)
    base = rng.randint(0, 8, size=(rows, seq + 1))
    return {
        "tokens": jnp.asarray(base[:, :-1], jnp.int32),
        "targets": jnp.asarray(base[:, 1:], jnp.int32),
        "poison": jnp.full(
            (rows, seq), 1 if poison else 0, jnp.int32
        ),
    }


def _poison_loss(cfg, mesh):
    """Multiplicative NaN injection: grads (not just the loss) go NaN
    when any ``poison`` flag is set, mirroring a corrupt-sample fault."""

    def loss_fn(params, batch, **kw):
        clean = {k: v for k, v in batch.items() if k != "poison"}
        loss, metrics = decoder.loss_fn(params, clean, cfg=cfg, mesh=mesh)
        bad = jnp.max(batch["poison"]) > 0
        return loss * jnp.where(bad, jnp.float32(jnp.nan), 1.0), metrics

    return loss_fn


@pytest.mark.slow
def test_replicated_sentinels_detect_injected_nan():
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=8))
    # constant schedule: the default warmup would zero the first
    # update and with it the ratio sentinel this test asserts on
    opt = with_grad_sanitizer(
        make_optimizer(learning_rate=1e-3, schedule="constant"), "skip"
    )
    b = TrainStepBuilder(
        cfg, mesh, opt, loss_fn=_poison_loss(cfg, mesh),
        health_sentinels=True,
    )
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = b.build()

    state, clean = step(state, _batch())
    clean = {k: float(v) for k, v in clean.items()}
    for key in snt.COUNT_KEYS:
        assert key in clean, key
    assert clean["sent_nonfinite"] == 0.0
    assert clean["sent_loss_nonfinite"] == 0.0
    assert clean["sent_sanitizer_skips"] == 0.0
    assert 0.0 < clean["sent_update_ratio"] < 1.0

    state, bad = step(state, _batch(poison=True))
    bad = {k: float(v) for k, v in bad.items()}
    assert bad["sent_nonfinite"] > 0.0
    assert bad["sent_loss_nonfinite"] == 1.0
    assert bad["sent_sanitizer_skips"] == 1.0
    # the guard skipped the poisoned update: params stay finite
    assert all(
        np.isfinite(np.asarray(x)).all()
        for x in jax.tree.leaves(state["params"])
    )


def test_sentinels_off_adds_no_keys():
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=8))
    opt = make_optimizer(learning_rate=1e-3)
    b = TrainStepBuilder(cfg, mesh, opt)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    _, m = b.build()(
        state, {k: v for k, v in _batch().items() if k != "poison"}
    )
    assert not any(k.startswith("sent_") for k in m)


@pytest.mark.slow  # tier-1 budget: fused-block compile (~10s);
# sentinel keys are pinned fast by the per-step sentinel tests
def test_fused_block_sentinels_are_stacked():
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=8))
    opt = make_optimizer(learning_rate=1e-3)
    b = TrainStepBuilder(cfg, mesh, opt, health_sentinels=True)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    k = 3
    rng = np.random.RandomState(0)
    base = rng.randint(0, 8, size=(k, 8, 33))
    blocks = {
        "tokens": jnp.asarray(base[..., :-1], jnp.int32),
        "targets": jnp.asarray(base[..., 1:], jnp.int32),
    }
    _, m = b.build_block()(state, blocks)
    for key in snt.COUNT_KEYS + (
        "sent_update_ratio", "sent_loss_nonfinite",
    ):
        assert np.asarray(m[key]).shape == (k,), key
    assert np.all(np.asarray(m["sent_nonfinite"]) == 0.0)


# ---------------------------------------------------------------------------
# replicated vs sharded parity (the acceptance pin)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sentinel_parity_replicated_vs_zero1_zero2():
    """Counts agree bitwise across paths wherever the gradient values
    are away from the lane thresholds (nonfinite / overflow lanes here);
    threshold-adjacent lanes and norm-order-sensitive lanes agree to
    1e-3 (the underlying grads differ in the last ulp between reduction
    orders, so entries sitting exactly at a threshold may flip)."""
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=8))
    raw = _batch(rows=16)
    batch = {k: v for k, v in raw.items() if k != "poison"}

    results = {}
    for mode in ("rep", "zero1", "zero2"):
        opt = make_optimizer(learning_rate=1e-3)
        comm = None if mode == "rep" else CommConfig(update_sharding=mode)
        b = TrainStepBuilder(
            cfg, mesh, opt, comm=comm, grad_accum=2,
            health_sentinels=True,
        )
        if mode != "rep":
            assert b.update_sharding, mode
        state = init_train_state(
            jax.random.key(0), cfg, mesh, opt, comm=b.comm_resolved
        )
        _, m = b.build()(state, batch)
        results[mode] = {k: float(v) for k, v in m.items()}

    rep = results["rep"]
    for mode in ("zero1", "zero2"):
        got = results[mode]
        assert set(got) == set(rep), mode
        # clean data: incident lanes exactly zero on every path
        for key in ("sent_nonfinite", "sent_ovf_f16", "sent_ovf_bf16",
                    "sent_loss_nonfinite"):
            assert got[key] == rep[key] == 0.0, (mode, key)
        for key in ("sent_update_ratio", "loss", "grad_norm"):
            assert got[key] == pytest.approx(
                rep[key], rel=1e-3, abs=1e-6
            ), (mode, key)
        # underflow lanes sit ON a threshold: with this low-entropy
        # batch a visible share of grad entries lands within an ulp of
        # f16-tiny, so last-ulp grad differences between reduction
        # orders flip O(100) entries — pin the fraction to 1% absolute
        for key in ("sent_und_f16", "sent_und_bf16"):
            assert got[key] == pytest.approx(
                rep[key], abs=1e-2
            ), (mode, key)


# ---------------------------------------------------------------------------
# dispatch guard: zero extra host syncs
# ---------------------------------------------------------------------------


def _data_iter(seed=0):
    rng = np.random.RandomState(seed)
    while True:
        base = rng.randint(0, 8, size=(8, 33))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }


@pytest.mark.slow  # tier-1 budget: HLO transfer audit; detection pins stay fast
def test_sentinels_add_no_device_to_host_transfers(
    tmp_path, monkeypatch
):
    """The acceptance pin for "zero host syncs": the stepwise loop does
    exactly one jax.device_get per step whether sentinels are on or off
    — the sentinel scalars ride that same transfer."""
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    def run(on):
        args = TrainerArgs(
            output_dir=str(tmp_path / f"s{on}"), max_steps=3,
            save_interval=0, log_interval=0, report_to_master=False,
            detect_loss_spikes=False, health_sentinels=on, resume=False,
        )
        t = Trainer(
            _cfg(), args, _data_iter(),
            make_optimizer(learning_rate=1e-3),
            mesh=build_mesh(MeshConfig(dp=8)),
        )
        t._init_state()
        calls["n"] = 0
        monkeypatch.setattr(jax, "device_get", counting)
        try:
            t.train()
        finally:
            monkeypatch.setattr(jax, "device_get", real)
        return calls["n"]

    assert run(False) == run(True)


# ---------------------------------------------------------------------------
# sanitize_grads plumbing
# ---------------------------------------------------------------------------


def test_make_optimizer_sanitize_grads_wraps():
    params = {"w": jnp.asarray([1.0, 2.0])}
    opt = make_optimizer(learning_rate=1e-3, sanitize_grads="zero")
    assert snt.sanitizer_count(opt.init(params)) is not None


def test_with_grad_sanitizer_readvertises_flat_factory():
    base = make_optimizer(learning_rate=1e-3, state_dtype="factored")
    assert getattr(base.init, "_flat_factory", None) is not None
    wrapped = with_grad_sanitizer(base, "skip")
    assert getattr(wrapped.init, "_flat_factory", None) is not None
    # a plain optimizer stays flat-factory-less after wrapping
    plain = with_grad_sanitizer(optax.sgd(0.1), "skip")
    assert getattr(plain.init, "_flat_factory", None) is None


def test_trainer_external_builder_ignores_sanitize(tmp_path):
    """An external step_builder already baked its optimizer; wrapping
    the trainer's copy would desync init_state from the step — the
    incompatibility is logged, not silently applied. (Handler attached
    by hand: common.log loggers set propagate=False, so caplog's
    root-logger hook never sees them.)"""
    import logging

    from dlrover_tpu.train import trainer as trainer_mod

    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=8))
    opt = make_optimizer(learning_rate=1e-3)
    builder = TrainStepBuilder(cfg, mesh, opt)
    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=1, save_interval=0,
        log_interval=0, report_to_master=False, sanitize_grads="skip",
    )
    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    grab = Grab()
    trainer_mod.logger.addHandler(grab)
    try:
        t = Trainer(
            cfg, args, _data_iter(), opt, mesh=mesh,
            step_builder=builder,
        )
    finally:
        trainer_mod.logger.removeHandler(grab)
    assert t.optimizer is opt  # not wrapped
    assert any("sanitize_grads" in m for m in records)
