"""Watchdog classification, capture budget, cross-host correlation,
and the offline healthcheck CLI (observability/watchdog.py +
observability/healthcheck.py)."""

import json
import os
import time

import pytest

from dlrover_tpu.observability import healthcheck, telemetry
from dlrover_tpu.observability.telemetry import configure_hub, reset_hub
from dlrover_tpu.observability.watchdog import (
    HealthAggregator,
    Watchdog,
    WatchdogConfig,
    verdict_for,
)


@pytest.fixture(autouse=True)
def _fresh_hub():
    reset_hub()
    yield
    reset_hub()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Op:
    """Duck-typed OpTime for write_capture."""

    def __init__(self, name, us=100.0):
        self.name = name
        self.total_us = us
        self.count = 4
        self.fraction = 0.5


def _watchdog(tmp_path=None, **kw):
    clock = kw.pop("clock", None) or FakeClock()
    cfg = WatchdogConfig(
        node_id=kw.pop("node_id", 0),
        capture_dir=str(tmp_path / "caps") if tmp_path else "",
        **kw,
    )
    return Watchdog(cfg, clock=clock)


# ---------------------------------------------------------------------------
# classification (table-driven over every anomaly kind)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "metrics,kw,kind",
    [
        ({"sent_nonfinite": 3.0}, {}, "nan_grads"),
        ({"sent_loss_nonfinite": 1.0}, {}, "nan_grads"),
        ({"sent_fp8_sat": 0.9}, {}, "fp8_saturation"),
        (
            {"loss": 1.0},
            {"step_time_s": 2.0, "planned_step_time_s": 1.0},
            "step_time_regression",
        ),
    ],
)
def test_classifies_kind(metrics, kw, kind):
    wd = _watchdog()
    out = wd.observe(10, metrics, **kw)
    assert [r.kind for r in out] == [kind]
    rec = out[0]
    assert rec.step == 10 and rec.node_id == 0
    assert rec.capture == ""  # no capture_dir → classification only


def test_no_anomaly_on_healthy_step():
    wd = _watchdog()
    assert wd.observe(
        5,
        {"loss": 2.0, "sent_nonfinite": 0.0, "sent_fp8_sat": 0.1},
        step_time_s=1.0,
        planned_step_time_s=1.0,
    ) == []
    assert wd.anomalies == []


def test_loss_spike_classified():
    wd = _watchdog(
        spike_min_iter=5, spike_min_loss=0.0, spike_zscore=3.0,
        spike_window=50,
    )
    out = []
    for s in range(1, 40):
        # slight jitter: a perfectly flat baseline has zero std and the
        # z-score gate (sd > 0) deliberately stays quiet on it
        out += wd.observe(s, {"loss": 2.0 + 0.001 * (s % 5)})
    out += wd.observe(40, {"loss": 50.0})
    assert [r.kind for r in out] == ["loss_spike"]
    assert out[0].value == 50.0


def test_step_time_regression_gates():
    wd = _watchdog(step_time_factor=1.5, min_step_for_drift=3)
    # no plan → never fires, however slow
    assert wd.observe(10, {}, step_time_s=99.0) == []
    # warmup steps skipped (recompiles)
    assert wd.observe(
        2, {}, step_time_s=99.0, planned_step_time_s=1.0
    ) == []
    # within factor → quiet
    assert wd.observe(
        10, {}, step_time_s=1.4, planned_step_time_s=1.0
    ) == []
    out = wd.observe(11, {}, step_time_s=1.6, planned_step_time_s=1.0)
    assert [r.kind for r in out] == ["step_time_regression"]


def test_observe_straggler():
    wd = _watchdog(node_id=3)
    rec = wd.observe_straggler(20, lag_steps=15, ratio=0.4)
    assert rec.kind == "straggler" and rec.node_id == 3
    assert "lag_steps=15" in rec.detail


def test_nan_grads_detail_carries_sanitizer_skips():
    wd = _watchdog()
    (rec,) = wd.observe(
        7,
        {
            "sent_nonfinite": 12.0,
            "sent_loss_nonfinite": 1.0,
            "sent_sanitizer_skips": 2.0,
        },
    )
    assert "sanitizer_skips=2" in rec.detail
    assert "nonfinite_grad_entries=12" in rec.detail


# ---------------------------------------------------------------------------
# capture reservation: rate limit + budget under an anomaly storm
# ---------------------------------------------------------------------------


def test_capture_storm_rate_limit_and_budget(tmp_path):
    clock = FakeClock()
    cfg = WatchdogConfig(
        node_id=0,
        capture_dir=str(tmp_path / "caps"),
        min_capture_interval_s=60.0,
        max_captures=2,
    )
    wd = Watchdog(cfg, clock=clock)

    # a NaN storm: every step anomalous, but only ONE capture reserved
    reserved = []
    for s in range(100):
        clock.t = float(s)
        for r in wd.observe(s, {"sent_nonfinite": 1.0}):
            if r.capture:
                reserved.append(r.capture)
    assert len(reserved) == 1
    assert reserved[0].endswith("capture_step0_nan_grads.json")
    assert wd.capture_pending == reserved[0]

    # writing frees the in-flight slot, but the rate limit still holds
    wd.write_capture(1, [_Op("fusion")])
    assert wd.capture_pending == ""
    clock.t = 130.0
    (r2,) = wd.observe(130, {"sent_nonfinite": 1.0})
    assert r2.capture  # interval elapsed → second capture (budget: 2)
    wd.write_capture(131, [_Op("fusion")])

    # budget exhausted: no further captures no matter how much time
    clock.t = 10_000.0
    (r3,) = wd.observe(10_000, {"sent_nonfinite": 1.0})
    assert r3.capture == ""


def test_write_capture_artifact_content(tmp_path):
    wd = _watchdog(tmp_path, min_capture_interval_s=0.0)
    (rec,) = wd.observe(4, {"sent_nonfinite": 2.0})
    assert rec.capture and wd.capture_pending == rec.capture
    path = wd.write_capture(
        5,
        [_Op("fusion", 300.0), _Op("all-reduce", 100.0)],
        planned_exposed_us=50.0,
        block=3,
        plan={"config": "tiny"},
    )
    assert path == rec.capture and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["anomaly"] == {"kind": "nan_grads", "step": 4, "node_id": 0}
    assert doc["captured_step"] == 5
    assert doc["block"] == 3  # fused K-step capture is labeled, not hidden
    assert [o["op"] for o in doc["ops"]] == ["fusion", "all-reduce"]
    assert doc["plan_diff"]["planned_exposed_us"] == 50.0
    assert doc["plan"] == {"config": "tiny"}
    # nothing pending anymore → a second write is a no-op
    assert wd.write_capture(6, [_Op("x")]) == ""


def test_anomalies_publish_to_hub(tmp_path):
    path = tmp_path / "flight.jsonl"
    configure_hub(jsonl_path=str(path))
    wd = _watchdog()
    wd.observe(3, {"sent_nonfinite": 1.0})
    lines = path.read_text().strip().splitlines()
    recs = [telemetry.from_json(line) for line in lines]
    assert any(
        isinstance(r, telemetry.AnomalyRecord) and r.kind == "nan_grads"
        for r in recs
    )


def test_master_sink_forwards_anomaly_records():
    class FakeClient:
        def __init__(self):
            self.sent = []

        def report_telemetry(self, line):
            self.sent.append(line)

    cl = FakeClient()
    sink = telemetry.MasterSink(cl)
    sink.emit(telemetry.StepRecord(step=1))  # hot path: stays local
    sink.emit(telemetry.AnomalyRecord(kind="nan_grads", step=4, node_id=1))
    assert len(cl.sent) == 1
    back = telemetry.from_json(cl.sent[0])
    assert isinstance(back, telemetry.AnomalyRecord)
    assert back.kind == "nan_grads" and back.node_id == 1


# ---------------------------------------------------------------------------
# cross-host correlation
# ---------------------------------------------------------------------------


def test_verdict_for_attribution_rule():
    assert verdict_for(1, 4) == "suspect_data_or_hardware"
    assert verdict_for(2, 4) == "suspect_partial"
    assert verdict_for(4, 4) == "suspect_model_or_config"
    assert verdict_for(5, 4) == "suspect_model_or_config"
    # unknown world: a single rank still points at the host
    assert verdict_for(1, 0) == "suspect_data_or_hardware"
    assert verdict_for(3, 0) == "suspect_partial"


def test_aggregator_refines_verdict_as_ranks_join():
    hub = configure_hub()
    seen = []
    hub.subscribe(lambda r: seen.append(r), types=("HealthSummary",))
    agg = HealthAggregator(hub=hub, world=4)

    hub.publish(telemetry.AnomalyRecord(kind="nan_grads", step=9, node_id=2))
    assert agg.summaries["nan_grads"].verdict == "suspect_data_or_hardware"
    assert agg.summaries["nan_grads"].ranks == "2"

    # same rank again: no rank-set growth → no re-publish
    hub.publish(telemetry.AnomalyRecord(kind="nan_grads", step=11, node_id=2))
    assert len(seen) == 1
    # an EARLIER step from the same rank is folded in silently; the
    # refreshed first_step surfaces with the next rank-set growth
    hub.publish(telemetry.AnomalyRecord(kind="nan_grads", step=5, node_id=2))
    assert len(seen) == 1

    for nid in (0, 1, 3):
        hub.publish(
            telemetry.AnomalyRecord(kind="nan_grads", step=12, node_id=nid)
        )
    s = agg.summaries["nan_grads"]
    assert s.verdict == "suspect_model_or_config"
    assert s.ranks == "0,1,2,3" and s.n_ranks == 4 and s.world == 4
    assert s.first_step == 5
    assert "2:5" in s.detail  # per-rank first bad step
    assert len(seen) == 4  # one publish per rank-set growth


def test_aggregator_folds_in_straggler_records():
    hub = configure_hub()
    agg = HealthAggregator(hub=hub, world=3)
    hub.publish(
        telemetry.StragglerRecord(
            node_id=1, step=40, max_step=55, lag_steps=15, ratio=0.4
        )
    )
    s = agg.summaries["straggler"]
    assert s.verdict == "suspect_data_or_hardware" and s.ranks == "1"


# ---------------------------------------------------------------------------
# offline healthcheck CLI
# ---------------------------------------------------------------------------


def _write_flight(path, world=2):
    hub = configure_hub(jsonl_path=str(path))
    for s in range(1, 6):
        hub.publish(telemetry.StepRecord(step=s, loss=3.0 - 0.1 * s))
    hub.publish(
        telemetry.AnomalyRecord(
            kind="nan_grads", step=4, node_id=1, value=12.0,
            detail="nonfinite_grad_entries=12",
            capture="/caps/capture_step4_nan_grads.json",
        )
    )
    hub.publish(
        telemetry.NumericEvent(kind="loss_spike", step=3, value=9.0,
                               detail="samples=[7]")
    )
    reset_hub()


def test_healthcheck_replay_names_rank_and_step(tmp_path):
    path = tmp_path / "flight.jsonl"
    _write_flight(path)
    # torn tail + foreign line: the replay must skip, not crash
    with open(path, "a") as f:
        f.write('{"not": "ours"}\n{"r": "StepRecord", "d": {"st')

    records = healthcheck.load_records(str(path))
    diag = healthcheck.diagnose(records, world=2)
    assert not diag["healthy"]
    info = diag["anomalies"]["nan_grads"]
    assert info["first_step"] == 4
    assert info["failing_ranks"] == [1]
    assert info["verdict"] == "suspect_data_or_hardware"
    assert info["captures"] == ["/caps/capture_step4_nan_grads.json"]
    assert diag["steps"]["last_step"] == 5

    report = healthcheck.format_report(diag)
    assert "failing rank(s) 1" in report
    assert "first bad step 4" in report
    assert "suspect_data_or_hardware" in report
    assert "loss_spike" in report  # numeric events section


def test_healthcheck_recorded_summary_takes_precedence(tmp_path):
    path = tmp_path / "flight.jsonl"
    hub = configure_hub(jsonl_path=str(path))
    hub.publish(telemetry.AnomalyRecord(kind="nan_grads", step=4, node_id=1))
    # the live master saw MORE ranks than this worker's file shows
    hub.publish(
        telemetry.HealthSummary(
            kind="nan_grads", first_step=4, ranks="0,1", n_ranks=2,
            world=2, verdict="suspect_model_or_config",
        )
    )
    reset_hub()
    diag = healthcheck.diagnose(
        healthcheck.load_records(str(path)), world=2
    )
    assert diag["anomalies"]["nan_grads"]["verdict"] == (
        "suspect_model_or_config"
    )


def test_healthcheck_main_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.jsonl"
    _write_flight(bad)
    assert healthcheck.main([str(bad), "--world", "2"]) == 1
    out = capsys.readouterr().out
    assert "failing rank(s) 1" in out

    ok = tmp_path / "ok.jsonl"
    hub = configure_hub(jsonl_path=str(ok))
    hub.publish(telemetry.StepRecord(step=1, loss=2.0))
    reset_hub()
    assert healthcheck.main([str(ok)]) == 0
    assert "healthy" in capsys.readouterr().out

    # --json mode emits the machine-readable diagnosis
    assert healthcheck.main([str(bad), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["anomalies"]["nan_grads"]["first_step"] == 4


def test_anomaly_records_reach_diagnosis_manager():
    from dlrover_tpu.diagnosis.manager import DiagnosisManager

    hub = configure_hub()
    dm = DiagnosisManager()
    dm.attach(hub)
    hub.publish(
        telemetry.AnomalyRecord(
            kind="nan_grads", step=4, node_id=1, value=12.0,
            capture="/caps/c.json",
        )
    )
    hub.publish(
        telemetry.HealthSummary(
            kind="nan_grads", first_step=4, ranks="1", n_ranks=1,
            world=2, verdict="suspect_data_or_hardware",
        )
    )
    ev1 = [d["content"] for d in dm.diagnosis_data[1]]
    assert any("anomaly nan_grads at step 4" in c for c in ev1)
    assert any("capture=/caps/c.json" in c for c in ev1)
    # the correlated verdict files job-wide AND under the named rank
    assert any("suspect_data_or_hardware" in c for c in ev1)
    evj = [d["content"] for d in dm.diagnosis_data[-1]]
    assert any("suspect_data_or_hardware" in c for c in evj)


# ---------------------------------------------------------------------------
# end-to-end NaN drill: poisoned batch → sentinel → AnomalyRecord →
# capture artifact → HealthSummary → healthcheck report
# ---------------------------------------------------------------------------


def _drill_pieces(monkeypatch, tmp_path, node_id=1):
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.models import decoder, get_config
    from dlrover_tpu.parallel import MeshConfig, build_mesh

    monkeypatch.setenv(
        "DLROVER_TPU_RUN_ID", f"wd{os.getpid()}_{time.time_ns()}"
    )
    monkeypatch.setenv("DLROVER_TPU_NODE_ID", str(node_id))
    cfg = get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32,
    )
    mesh = build_mesh(MeshConfig(dp=8))

    def poison_loss(params, batch, **kw):
        clean = {k: v for k, v in batch.items() if k != "poison"}
        loss, metrics = decoder.loss_fn(params, clean, cfg=cfg, mesh=mesh)
        bad = jnp.max(batch["poison"]) > 0
        # multiplicative: the GRADIENTS go NaN, not just the loss
        return loss * jnp.where(bad, jnp.float32(jnp.nan), 1.0), metrics

    def data(poison_step):
        rng = np.random.RandomState(0)
        step = 0
        while True:
            step += 1
            base = rng.randint(0, 8, size=(8, 33))
            yield {
                "tokens": jnp.asarray(base[:, :-1], jnp.int32),
                "targets": jnp.asarray(base[:, 1:], jnp.int32),
                "poison": jnp.full(
                    (8, 32), 1 if step == poison_step else 0, jnp.int32
                ),
            }

    return cfg, mesh, poison_loss, data


@pytest.mark.slow  # tier-1 budget: e2e drill; unit NaN paths stay fast
def test_nan_drill_end_to_end(monkeypatch, tmp_path):
    """The acceptance drill: one rank hits NaN grads at step 4 → the
    sentinel trips in-graph, the watchdog classifies an AnomalyRecord
    with a reserved capture, the next step is force-profiled into the
    capture artifact, the master-side aggregator attributes the fault
    to the failing host, and the offline healthcheck replay names the
    rank and the first bad step."""
    from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer

    flight = tmp_path / "flight.jsonl"
    hub = configure_hub(jsonl_path=str(flight))
    agg = HealthAggregator(hub=hub, world=2)
    cfg, mesh, poison_loss, data = _drill_pieces(monkeypatch, tmp_path)

    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=6, save_interval=0,
        log_interval=0, report_to_master=False, detect_loss_spikes=False,
        resume=False, health_sentinels=True, sanitize_grads="skip",
    )
    t = Trainer(
        cfg, args, data(poison_step=4),
        make_optimizer(learning_rate=1e-3), mesh=mesh,
        loss_fn=poison_loss,
    )
    state = t.train()
    assert int(state["step"]) == 6

    # classified on the failing worker, capture attached
    kinds = {(r.kind, r.step) for r in t.watchdog.anomalies}
    assert ("nan_grads", 4) in kinds
    (rec,) = [r for r in t.watchdog.anomalies if r.kind == "nan_grads"]
    assert rec.node_id == 1 and rec.capture
    assert os.path.exists(rec.capture)
    doc = json.load(open(rec.capture))
    assert doc["anomaly"]["step"] == 4
    assert doc["captured_step"] == 5  # the next (force-profiled) step
    assert doc["ops"], "capture carries a runtime breakdown"

    # the sanitizer skipped the poisoned update: weights stayed finite
    import jax
    import numpy as np

    assert all(
        np.isfinite(np.asarray(x)).all()
        for x in jax.tree.leaves(state["params"])
    )

    # master-side correlation: 1 of 2 ranks → data/hardware suspicion
    s = agg.summaries["nan_grads"]
    assert s.verdict == "suspect_data_or_hardware"
    assert s.ranks == "1" and s.first_step == 4

    # offline replay reaches the same diagnosis from the jsonl alone
    diag = healthcheck.diagnose(
        healthcheck.load_records(str(flight)), world=2
    )
    report = healthcheck.format_report(diag)
    assert "failing rank(s) 1" in report
    assert "first bad step 4" in report
    assert "suspect_data_or_hardware" in report
    assert rec.capture in report


@pytest.mark.slow
def test_nan_drill_fused_block_capture_labeled(monkeypatch, tmp_path):
    """block_k > 1: the anomaly is detected in the block drain, the
    NEXT block is force-profiled, and the capture (and its
    KernelSamples) are labeled with the block size — a K-step trace is
    never passed off as one step's budget (the profile_interval ×
    block_k contract)."""
    from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer

    flight = tmp_path / "flight.jsonl"
    configure_hub(jsonl_path=str(flight))
    cfg, mesh, poison_loss, data = _drill_pieces(monkeypatch, tmp_path)

    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=8, block_k=2,
        save_interval=0, log_interval=0, report_to_master=False,
        detect_loss_spikes=False, resume=False, health_sentinels=True,
        sanitize_grads="skip",
    )
    t = Trainer(
        cfg, args, data(poison_step=3),
        make_optimizer(learning_rate=1e-3), mesh=mesh,
        loss_fn=poison_loss,
    )
    t.train()

    (rec,) = [r for r in t.watchdog.anomalies if r.kind == "nan_grads"]
    assert rec.step == 3 and rec.capture
    assert os.path.exists(rec.capture)
    doc = json.load(open(rec.capture))
    assert doc["block"] == 2
    assert doc["captured_step"] > 3  # a later block carried the trace
    assert doc["ops"]

    samples = [
        r
        for r in healthcheck.load_records(str(flight))
        if isinstance(r, telemetry.KernelSample)
    ]
    assert samples and all(r.block == 2 for r in samples)


def test_schema_roundtrip_new_records():
    """AnomalyRecord / HealthSummary survive the wire losslessly (the
    generic lint in test_telemetry covers defaults; this pins a fully
    populated instance)."""
    rec = telemetry.AnomalyRecord(
        kind="fp8_saturation", step=123, node_id=7, value=0.75,
        detail="threshold=0.5", capture="/x/y.json", ts=111.5,
    )
    back = telemetry.from_json(rec.to_json())
    assert back == rec
    s = telemetry.HealthSummary(
        kind="straggler", first_step=9, ranks="0,3", n_ranks=2, world=8,
        verdict="suspect_partial", detail="first bad step per rank: 0:9 3:11",
        ts=222.25,
    )
    assert telemetry.from_json(s.to_json()) == s
