"""Estimator-style executor over the sparse tier — the reference's TF
estimator trainer row (estimator_executor.py:52, tensorflow_failover.py:33,
failover_client.py:21, reader/file_reader.py, hooks/).

The TPU-native re-design under test: planned PS membership changes are
adopted LIVE (HRW re-route + bounded key migration) instead of a
session rebuild; unplanned PS loss (crash) is detected when migration
export hits a dead socket and recovers by checkpoint restore routed at
the new ring; TF_CONFIG becomes ClusterSpec synthesized from the master
or injected via DLROVER_TPU_CLUSTER_SPEC.
"""

import json
import os

import numpy as np
import pytest

from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.sparse import GroupAdam
from dlrover_tpu.sparse.embedding import EmbeddingCollection, EmbeddingSpec
from dlrover_tpu.sparse.server import (
    _ADDR_KV_PREFIX,
    DistributedEmbedding,
    KvServer,
)
from dlrover_tpu.train.estimator import (
    CLUSTER_SPEC_ENV,
    ClusterSpec,
    ColumnInfo,
    ElasticDataShardReportHook,
    Estimator,
    EvalSpec,
    FileReader,
    ModeKeys,
    PsFailover,
    RunConfig,
    TrainSpec,
    set_cluster_spec,
    synthesize_cluster_spec,
    train_and_evaluate,
    wait_for_cluster_spec,
)


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------


class FakePsMaster:
    """The master surface PsFailover + cluster-spec synthesis consume:
    get_ps_version / kv_store_get / kv_store_set / report_global_step."""

    def __init__(self):
        self.version = 0
        self.servers = []
        self.kv = {}
        self.steps = []
        self.node_rank = 0

    def set_ring(self, servers, addrs):
        self.servers = list(servers)
        self.version += 1
        for name, addr in addrs.items():
            self.kv[_ADDR_KV_PREFIX + name] = json.dumps(list(addr))

    def get_ps_version(self):
        class R:
            pass

        r = R()
        r.version = self.version
        r.servers = list(self.servers)
        return r

    def kv_store_get(self, key):
        return self.kv.get(key, "")

    def kv_store_set(self, key, value):
        self.kv[key] = value
        return True

    def report_global_step(self, step, worker_num=0):
        self.steps.append(step)
        return True

    def report_model_info(self, **kw):
        self.model_info = dict(kw)
        return True


class FakeShardMaster:
    """get_task/report_task_result surface for ShardingClient: serves
    fixed-size shards over [0, size)."""

    def __init__(self, size, shard_size):
        self.size = size
        self.shard_size = shard_size
        self.next = 0
        self.done = []

    def report_dataset_shard_params(self, *a, **k):
        return True

    def get_task(self, dataset_name):
        class T:
            pass

        t = T()
        if self.next >= self.size:
            t.task_type = "none"
            t.task_id = -1
            t.shard_start = t.shard_end = 0
            t.record_indices = []
            return t
        t.task_type = "train"
        t.task_id = self.next // self.shard_size
        t.shard_start = self.next
        t.shard_end = min(self.next + self.shard_size, self.size)
        t.record_indices = list(range(t.shard_start, t.shard_end))
        self.next = t.shard_end
        return t

    def report_task_result(self, dataset_name, task_id, success=True):
        self.done.append((task_id, success))
        return True

    def get_shard_checkpoint(self, dataset_name):
        return json.dumps({"next": self.next})

    def report_shard_checkpoint(self, dataset_name, content):
        return True


# ---------------------------------------------------------------------------
# model plumbing
# ---------------------------------------------------------------------------

CFG = DeepFMConfig(n_fields=4, n_dense=3, emb_dim=4, mlp_dims=(16,))


def _specs():
    return [
        EmbeddingSpec("emb", CFG.emb_dim, initializer="normal",
                      init_scale=0.01, seed=3),
        EmbeddingSpec("wide", 1, initializer="zeros"),
    ]


def _start_server():
    return KvServer(_specs(), optimizer=GroupAdam(lr=1e-2))


class DeepFMAdapter:
    """Two-line shim from the estimator's (features, labels) contract to
    DeepFM's positional one — the analog of the user's estimator class."""

    def __init__(self, model):
        self.model = model
        self.coll = model.coll

    def train_step(self, features, labels):
        return self.model.train_step(
            features["cat"], features["dense"], labels
        )

    def eval_metrics(self, features, labels):
        p = self.model.predict(features["cat"], features["dense"])
        eps = 1e-6
        loss = -np.mean(
            labels * np.log(p + eps) + (1 - labels) * np.log(1 - p + eps)
        )
        return {"loss": float(loss),
                "accuracy": float(np.mean((p > 0.5) == (labels > 0.5)))}

    def predict(self, features):
        return self.model.predict(features["cat"], features["dense"])

    def save(self, dir_path, delta_only=False):
        self.model.save(dir_path, delta_only=delta_only)

    def restore(self, dir_path):
        self.model.restore(dir_path)

    def close(self):
        self.model.close()


def make_model_fn(addrs):
    def model_fn(mode, params, cluster):
        assert mode == ModeKeys.TRAIN
        model = DeepFM(CFG, optimizer=GroupAdam(lr=1e-2), dense_lr=1e-2)
        model.coll.close()
        model.coll = DistributedEmbedding(_specs(), addrs)
        return DeepFMAdapter(model)

    return model_fn


def synthetic_ctr(rng, n):
    cat = rng.integers(0, 50, size=(n, CFG.n_fields)).astype(np.int64)
    dense = rng.normal(size=(n, CFG.n_dense)).astype(np.float32)
    hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
    p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
    labels = (rng.random(n) < p).astype(np.float32)
    return cat, dense, labels


def batch_input_fn(seed=0, batch=128, repeat=10_000):
    def input_fn():
        rng = np.random.default_rng(seed)
        for _ in range(repeat):
            cat, dense, labels = synthetic_ctr(rng, batch)
            yield {"cat": cat, "dense": dense}, labels

    return input_fn


# ---------------------------------------------------------------------------
# FileReader + ColumnInfo
# ---------------------------------------------------------------------------


def _write_csv(path, n=64):
    rng = np.random.default_rng(5)
    with open(path, "w", encoding="utf-8") as f:
        f.write("a,b,label\n")
        for _ in range(n):
            f.write(
                f"{rng.integers(0, 9)},{rng.random():.4f},"
                f"{rng.integers(0, 2)}\n"
            )


def test_file_reader_schema_and_batches(tmp_path):
    path = str(tmp_path / "data.csv")
    _write_csv(path, n=64)
    reader = FileReader(
        path,
        [
            ColumnInfo("a", "int64"),
            ColumnInfo("b", "float32"),
            ColumnInfo("label", "float32", is_label=True),
        ],
        batch_size=16,
        skip_header=True,
    )
    assert reader.num_records == 64
    batches = list(reader)
    assert len(batches) == 4
    feats, labels = batches[0]
    assert feats["a"].dtype == np.int64 and feats["a"].shape == (16,)
    assert feats["b"].dtype == np.float32
    assert labels.shape == (16,) and "label" not in feats


def test_file_reader_rejects_bad_rows(tmp_path):
    path = str(tmp_path / "bad.csv")
    with open(path, "w", encoding="utf-8") as f:
        f.write("1,2\n1\n")
    reader = FileReader(
        path,
        [ColumnInfo("a", "int64"), ColumnInfo("b", "int64")],
        batch_size=4,
    )
    with pytest.raises(ValueError, match="schema"):
        list(reader)


def test_file_reader_sharded_auto_report(tmp_path):
    """Shard-fed reading closes each master shard exactly once."""
    from dlrover_tpu.agent.sharding_client import ShardingClient

    path = str(tmp_path / "data.csv")
    _write_csv(path, n=40)
    master = FakeShardMaster(size=40, shard_size=10)
    sc = ShardingClient.__new__(ShardingClient)  # skip RPC-registering init
    import threading

    sc._client = master
    sc.dataset_name = "d"
    sc._lock = threading.Lock()
    sc._current_task = None
    sc._consumed = 0
    reader = FileReader(
        path,
        [
            ColumnInfo("a", "int64"),
            ColumnInfo("b", "float32"),
            ColumnInfo("label", "float32", is_label=True),
        ],
        batch_size=4,
        skip_header=True,
        shard_client=sc,
        auto_report=True,
    )
    batches = list(reader)
    # 4 shards x 10 records at batch 4 → 3 batches per shard (4+4+2)
    assert len(batches) == 12
    assert [tid for tid, ok in master.done] == [0, 1, 2, 3]
    assert all(ok for _, ok in master.done)


def test_report_batch_done_closes_shard_incrementally():
    from dlrover_tpu.agent.sharding_client import ShardingClient
    import threading

    master = FakeShardMaster(size=10, shard_size=10)
    sc = ShardingClient.__new__(ShardingClient)
    sc._client = master
    sc.dataset_name = "d"
    sc._lock = threading.Lock()
    sc._current_task = None
    sc._consumed = 0
    assert sc.fetch_shard() == (0, 10, list(range(10)))
    assert sc.report_batch_done(4) is False
    assert sc.report_batch_done(4) is False
    assert sc.report_batch_done(2) is True
    assert master.done == [(0, True)]
    # no current shard: counting is a no-op
    assert sc.report_batch_done(4) is False


# ---------------------------------------------------------------------------
# ClusterSpec (TF_CONFIG analog)
# ---------------------------------------------------------------------------


def test_cluster_spec_roundtrip_and_chief():
    spec = ClusterSpec(
        cluster={"ps": ["ps-0", "ps-1"], "worker": ["w-0", "w-1"]},
        task_type="worker",
        task_index=0,
    )
    back = ClusterSpec.from_json(spec.to_json())
    assert back.cluster == spec.cluster
    assert back.is_chief  # worker 0 with no chief declared
    assert not ClusterSpec(
        cluster=spec.cluster, task_type="worker", task_index=1
    ).is_chief
    chief = ClusterSpec(
        cluster={"chief": ["c-0"], "worker": ["w-0"]},
        task_type="chief", task_index=0,
    )
    assert chief.is_chief
    w0_with_chief = ClusterSpec(
        cluster={"chief": ["c-0"], "worker": ["w-0"]},
        task_type="worker", task_index=0,
    )
    assert not w0_with_chief.is_chief


def test_cluster_spec_env_inject_and_wait(monkeypatch):
    monkeypatch.delenv(CLUSTER_SPEC_ENV, raising=False)
    with pytest.raises(TimeoutError):
        wait_for_cluster_spec(timeout_s=0.05, poll_s=0.01)
    set_cluster_spec(
        {"cluster": {"ps": ["p0"]}, "task": {"type": "worker", "index": 2}}
    )
    spec = wait_for_cluster_spec(timeout_s=1)
    assert spec.cluster["ps"] == ["p0"]
    assert spec.task_index == 2
    monkeypatch.delenv(CLUSTER_SPEC_ENV, raising=False)


def test_synthesize_cluster_spec_from_master():
    master = FakePsMaster()
    master.set_ring(["s0", "s1"], {"s0": ("h", 1), "s1": ("h", 2)})
    master.node_rank = 3
    spec = synthesize_cluster_spec(master)
    assert spec.cluster["ps"] == ["s0", "s1"]
    assert spec.task_index == 3 and not spec.is_chief


# ---------------------------------------------------------------------------
# ring-wide sparse checkpoint (DistributedEmbedding.save/restore)
# ---------------------------------------------------------------------------


def test_ring_snapshot_interchanges_with_local(tmp_path):
    s0, s1 = _start_server(), _start_server()
    try:
        demb = DistributedEmbedding(
            _specs(), {"s0": s0.address, "s1": s1.address}
        )
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 10_000, 256).astype(np.int64)
        dev, host = demb.pull({"emb": keys, "wide": keys})
        demb.push(host, {
            "emb": np.ones((len(host["emb"]), CFG.emb_dim), np.float32),
            "wide": np.ones((len(host["wide"]), 1), np.float32),
        })
        written = demb.save(str(tmp_path))
        assert written["emb"] == len(host["emb"])
        # the full save just cleared the dirty epoch: an immediate
        # delta is empty (cumulative-since-full contract)
        assert demb.save(str(tmp_path), delta_only=True)["emb"] == 0

        # a LOCAL collection restores the ring snapshot byte-for-byte
        local = EmbeddingCollection(_specs(), optimizer=GroupAdam(lr=1e-2))
        local.restore(str(tmp_path))
        ring_rows = demb.pull_frozen({"emb": keys})["emb"][0]
        local_rows = local.pull_frozen({"emb": keys})["emb"][0]
        np.testing.assert_allclose(
            np.asarray(ring_rows), np.asarray(local_rows), atol=1e-6
        )
        local.close()

        # restore onto a DIFFERENT ring (resharded restore)
        s2 = _start_server()
        try:
            demb2 = DistributedEmbedding(_specs(), {"s2": s2.address})
            demb2.restore(str(tmp_path))
            rows2 = demb2.pull_frozen({"emb": keys})["emb"][0]
            np.testing.assert_allclose(
                np.asarray(ring_rows), np.asarray(rows2), atol=1e-6
            )
            demb2.close()
        finally:
            s2.stop()
        demb.close()
    finally:
        s0.stop()
        s1.stop()


# ---------------------------------------------------------------------------
# Estimator train / evaluate / export
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_estimator_trains_checkpoints_and_prunes(tmp_path):
    s0, s1 = _start_server(), _start_server()
    try:
        addrs = {"s0": s0.address, "s1": s1.address}
        est = Estimator(
            make_model_fn(addrs),
            config=RunConfig(
                model_dir=str(tmp_path), save_steps=5,
                keep_checkpoint_max=2, log_steps=50,
            ),
        )
        loss = est.train(batch_input_fn(), max_steps=12)
        assert np.isfinite(loss)
        assert est.global_step == 12
        ckpts = sorted(
            d for d in os.listdir(str(tmp_path)) if d.startswith("ckpt-")
        )
        # saved at 5, 10, 12(end) — pruned to keep_checkpoint_max=2
        assert ckpts == ["ckpt-10", "ckpt-12"], ckpts
        assert est.latest_checkpoint() == 12
        est.model.close()
    finally:
        s0.stop()
        s1.stop()


@pytest.mark.slow  # tier-1 budget: prunes/outage keep fast estimator coverage
def test_train_and_evaluate_exports_best(tmp_path):
    s0 = _start_server()
    try:
        addrs = {"s0": s0.address}
        est = Estimator(
            make_model_fn(addrs),
            config=RunConfig(
                model_dir=str(tmp_path), save_steps=10, log_steps=50
            ),
        )
        metrics = train_and_evaluate(
            est,
            TrainSpec(batch_input_fn(), max_steps=30),
            EvalSpec(batch_input_fn(seed=9), steps=4, every_steps=10),
        )
        assert "loss" in metrics and np.isfinite(metrics["loss"])
        meta = json.loads(
            open(
                os.path.join(str(tmp_path), "export", "best",
                             "metadata.json"),
                encoding="utf-8",
            ).read()
        )
        assert np.isfinite(meta["loss"]) and meta["step"] <= 30
        # learning happened: the best export beats a fresh model's loss
        assert metrics["accuracy"] >= 0.5
        est.model.close()
    finally:
        s0.stop()


@pytest.mark.slow  # tier-1 budget: prunes/outage keep fast estimator coverage
def test_estimator_resume_from_latest(tmp_path):
    s0 = _start_server()
    try:
        addrs = {"s0": s0.address}
        cfg = RunConfig(model_dir=str(tmp_path), save_steps=5, log_steps=50)
        est = Estimator(make_model_fn(addrs), config=cfg)
        est.train(batch_input_fn(), max_steps=10)
        before = est.model.predict(
            {"cat": np.zeros((4, CFG.n_fields), np.int64),
             "dense": np.zeros((4, CFG.n_dense), np.float32)}
        )
        est.model.close()

        # a restarted worker: fresh Estimator, same model_dir
        est2 = Estimator(make_model_fn(addrs), config=cfg)
        restored = est2.restore_latest()
        assert restored == 10
        after = est2.model.predict(
            {"cat": np.zeros((4, CFG.n_fields), np.int64),
             "dense": np.zeros((4, CFG.n_dense), np.float32)}
        )
        np.testing.assert_allclose(before, after, atol=1e-6)
        est2.model.close()
    finally:
        s0.stop()


# ---------------------------------------------------------------------------
# PS failover: live adoption + crash restore
# ---------------------------------------------------------------------------


def test_ps_failover_scaling_adopts_live():
    s0, s1, s2 = _start_server(), _start_server(), _start_server()
    try:
        master = FakePsMaster()
        master.set_ring(
            ["s0", "s1"], {"s0": s0.address, "s1": s1.address}
        )
        demb = DistributedEmbedding(
            _specs(), {"s0": s0.address, "s1": s1.address}
        )
        demb.version = master.version
        keys = np.arange(512, dtype=np.int64)
        dev, host = demb.pull({"emb": keys})
        before = np.asarray(demb.pull_frozen({"emb": keys})["emb"][0])

        changes = []
        fo = PsFailover(master, demb, on_change=changes.append)
        # scale-out: s2 joins — adopted live, keys migrate, rows intact
        master.set_ring(
            ["s0", "s1", "s2"],
            {"s0": s0.address, "s1": s1.address, "s2": s2.address},
        )
        assert fo.poll_once() == "scaling"
        assert changes == ["scaling"]
        assert demb.server_names == ["s0", "s1", "s2"]
        after = np.asarray(demb.pull_frozen({"emb": keys})["emb"][0])
        np.testing.assert_allclose(before, after, atol=1e-6)
        assert int(demb.stats()["s2"]["emb"]) > 0  # really rebalanced
        # same version again: no-op
        assert fo.poll_once() is None
        demb.close()
    finally:
        s0.stop()
        s1.stop()
        s2.stop()


@pytest.mark.slow
def test_ps_failure_detected_and_restored(tmp_path):
    """Kill a server (rows gone), replace it: migration export hits the
    dead socket → 'ps_failure' → estimator restores the ring from the
    latest checkpoint and training continues (the reference reaches the
    same restore via worker exit + agent restart)."""
    s0, s1, s2 = _start_server(), _start_server(), _start_server()
    try:
        master = FakePsMaster()
        master.set_ring(
            ["s0", "s1"], {"s0": s0.address, "s1": s1.address}
        )
        addrs = {"s0": s0.address, "s1": s1.address}
        est = Estimator(
            make_model_fn(addrs),
            config=RunConfig(
                model_dir=str(tmp_path), save_steps=5, log_steps=50
            ),
            master_client=master,
        )
        est.train(batch_input_fn(), max_steps=10)
        assert est.latest_checkpoint() == 10
        assert est.failover is not None  # wired from model.coll + master
        probe = {"cat": np.zeros((4, CFG.n_fields), np.int64),
                 "dense": np.zeros((4, CFG.n_dense), np.float32)}
        before = est.model.predict(probe)

        # crash s1 (its shard is unrecoverable), replace with s2
        s1.stop()
        master.set_ring(
            ["s0", "s2"], {"s0": s0.address, "s2": s2.address}
        )
        assert est.failover.poll_once() == "ps_failure"
        assert est._needs_sparse_restore
        assert est.model.coll.server_names == ["s0", "s2"]

        # next train call restores from ckpt-10 then keeps training
        loss = est.train(batch_input_fn(seed=1), max_steps=14)
        assert np.isfinite(loss) and est.global_step == 14
        assert not est._needs_sparse_restore
        # s2 now serves restored rows
        assert int(est.model.coll.stats()["s2"]["emb"]) > 0
        after = est.model.predict(probe)
        assert np.all(np.isfinite(after)) and after.shape == before.shape
        est.model.close()
    finally:
        s0.stop()
        s2.stop()


def test_ps_failure_without_checkpoint_raises(tmp_path):
    from dlrover_tpu.train.estimator import PsFailureError

    s0, s1 = _start_server(), _start_server()
    try:
        master = FakePsMaster()
        master.set_ring(["s0"], {"s0": s0.address})
        est = Estimator(
            make_model_fn({"s0": s0.address}),
            config=RunConfig(model_dir=str(tmp_path), save_steps=1000),
            master_client=master,
        )
        est.model  # build + wire failover
        est._needs_sparse_restore = True  # simulated failure, no ckpt
        with pytest.raises(PsFailureError):
            est.train(batch_input_fn(), max_steps=2)
        est.model.close()
    finally:
        s0.stop()
        s1.stop()


@pytest.mark.slow
def test_global_step_hook_reports(tmp_path):
    master = FakePsMaster()
    s0 = _start_server()
    try:
        est = Estimator(
            make_model_fn({"s0": s0.address}),
            config=RunConfig(
                model_dir=str(tmp_path), save_steps=1000, log_steps=50,
            ),
            master_client=master,
        )
        est.train(batch_input_fn(), max_steps=20)
        assert 10 in master.steps and 20 in master.steps
        # model statistics reported once at begin (ReportModelInfoHook
        # analog): the Brain's plans key off these job metrics
        assert master.model_info["model_name"] == "DeepFMAdapter"
        est.model.close()
    finally:
        s0.stop()


# ---------------------------------------------------------------------------
# real-wire composition: LocalJobMaster + MasterClient + PS ring
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_estimator_over_real_master_wire(tmp_path):
    """The full registration story over the real wire: KvServers join
    the master as PS nodes (PsClusterCallback builds the versioned
    ring), the estimator synthesizes its ClusterSpec from the master
    (the TF_CONFIG-from-cluster-info path), and a PLANNED scale-out
    mid-run is adopted live by the inline failover poll."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.master.master import LocalJobMaster
    from dlrover_tpu.sparse.server import register_server, resolve_ring

    master = LocalJobMaster(port=0, num_workers=1)
    master.prepare()
    s0, s1, s2 = _start_server(), _start_server(), _start_server()
    try:
        def join_ps(node_id, server):
            c = MasterClient(master.addr, node_id=node_id)
            c.register_node(node_type=NodeType.PS)
            register_server(c, f"{NodeType.PS}-{node_id}", server.address)
            return c

        join_ps(100, s0)
        join_ps(101, s1)
        worker = MasterClient(master.addr, node_id=0)
        worker.register_node()
        spec = synthesize_cluster_spec(worker)
        assert spec.cluster["ps"] == ["ps-100", "ps-101"]
        assert spec.is_chief  # worker 0, no explicit chief

        addrs = resolve_ring(worker, spec.cluster["ps"])
        assert addrs is not None
        est = Estimator(
            make_model_fn(addrs),
            config=RunConfig(
                model_dir=str(tmp_path), save_steps=5, log_steps=50
            ),
            cluster=spec,
            master_client=worker,
        )
        # the ring the model adopted at build time IS the master's
        # current version — align so the first poll is a no-op
        est.model.coll.version = worker.get_ps_version().version
        est.train(batch_input_fn(), max_steps=6)
        assert est.failover is not None and est.failover.changes == []

        # planned scale-out: a third PS registers; the next train's
        # inline poll adopts it live (no restore, keys migrate)
        join_ps(102, s2)
        est.failover._poll = 0.0  # poll every step
        est.train(batch_input_fn(seed=2), max_steps=12)
        assert est.model.coll.server_names == [
            "ps-100", "ps-101", "ps-102"
        ]
        assert int(est.model.coll.stats()["ps-102"]["emb"]) > 0
        assert est.failover.changes == ["scaling"]
        assert est.global_step == 12
        est.model.close()
    finally:
        master.stop()
        s0.stop()
        s1.stop()
        s2.stop()


# ---------------------------------------------------------------------------
# incremental (delta) checkpoints: ring-wide full-or-delta export
# ---------------------------------------------------------------------------


def test_ring_delta_snapshot_roundtrip(tmp_path):
    """Full save clears the dirty epoch; a later delta carries only
    rows changed since (plus deletion tombstones); full+delta restores
    the exact live state onto a DIFFERENT ring (tfplus full-or-delta
    export capability, ops/kv_variable_ops.cc, at the serving tier)."""
    s0, s1 = _start_server(), _start_server()
    try:
        demb = DistributedEmbedding(
            _specs(), {"s0": s0.address, "s1": s1.address}
        )
        keys_a = np.arange(0, 200, dtype=np.int64)
        dev, host = demb.pull({"emb": keys_a})
        demb.push(host, {
            "emb": np.ones((len(host["emb"]), CFG.emb_dim), np.float32)
        })
        full_written = demb.save(str(tmp_path))
        assert full_written["emb"] == 200

        # epoch cleared: an immediate delta is empty
        assert demb.save(str(tmp_path), delta_only=True)["emb"] == 0

        # mutate a subset, insert new keys, delete a few
        keys_b = np.arange(100, 250, dtype=np.int64)  # 100-199 old, 200-249 new
        dev, host = demb.pull({"emb": keys_b})
        demb.push(host, {
            "emb": np.full((len(host["emb"]), CFG.emb_dim), 2.0, np.float32)
        })
        gone = np.array([0, 1, 2], dtype=np.int64)
        demb._route_delete("emb", gone)
        delta_written = demb.save(str(tmp_path), delta_only=True)
        # only the touched rows travel (bounded by 150 + admission noise)
        assert 0 < delta_written["emb"] <= 160, delta_written

        live = np.asarray(
            demb.pull_frozen({"emb": np.arange(250, dtype=np.int64)})[
                "emb"
            ][0]
        )

        # restore full+delta onto a fresh single-server ring
        s2 = _start_server()
        try:
            demb2 = DistributedEmbedding(_specs(), {"s2": s2.address})
            demb2.restore(str(tmp_path))
            got = np.asarray(
                demb2.pull_frozen(
                    {"emb": np.arange(250, dtype=np.int64)}
                )["emb"][0]
            )
            np.testing.assert_allclose(got, live, atol=1e-6)
            # tombstoned keys really are absent (zeros on frozen pull)
            dead = np.asarray(
                demb2.pull_frozen({"emb": gone})["emb"][0]
            )
            np.testing.assert_allclose(dead, 0.0)
            demb2.close()
        finally:
            s2.stop()
        demb.close()
    finally:
        s0.stop()
        s1.stop()


def test_checkpoint_saver_hook_incremental_cadence():
    calls = []

    class FakeEst:
        def save_checkpoint(self, step):
            calls.append(("full", step))

        def save_incremental(self, step):
            calls.append(("delta", step))

    from dlrover_tpu.train.estimator import CheckpointSaverHook

    est = FakeEst()
    hook = CheckpointSaverHook(est, save_steps=6, incremental_steps=2)
    for step in range(1, 13):
        hook.after_run(est, step, 0.0)
    assert calls == [
        ("delta", 2), ("delta", 4), ("full", 6),
        ("delta", 8), ("delta", 10), ("full", 12),
    ]


@pytest.mark.slow  # tier-1 budget: prunes/outage keep fast estimator coverage
def test_estimator_incremental_restore(tmp_path):
    """A delta saved after the last full checkpoint restores forward to
    the delta step: fresh estimator resumes at step 10 from dir ckpt-8
    (full base) + its delta overlay, predictions matching the live
    model."""
    s0 = _start_server()
    try:
        addrs = {"s0": s0.address}
        cfg = RunConfig(
            model_dir=str(tmp_path), save_steps=1000, log_steps=50
        )
        est = Estimator(make_model_fn(addrs), config=cfg)
        est.train(batch_input_fn(), max_steps=8)  # end-save: full ckpt-8
        assert est._read_tracker() == {"latest_step": 8, "full_step": 8}

        # two more "steps" past the full checkpoint, then a delta
        rng = np.random.default_rng(3)
        for _ in range(2):
            cat, dense, labels = synthetic_ctr(rng, 128)
            est.model.train_step(
                {"cat": cat, "dense": dense}, labels
            )
        est.save_incremental(10)
        assert est._read_tracker() == {"latest_step": 10, "full_step": 8}
        probe = {"cat": np.arange(4 * CFG.n_fields).reshape(
            4, CFG.n_fields).astype(np.int64),
            "dense": np.zeros((4, CFG.n_dense), np.float32)}
        want = est.model.predict(probe)
        est.model.close()

        est2 = Estimator(make_model_fn(addrs), config=cfg)
        assert est2.restore_latest() == 10
        got = est2.model.predict(probe)
        np.testing.assert_allclose(got, want, atol=1e-6)
        est2.model.close()
    finally:
        s0.stop()


def test_full_save_invalidates_stale_delta(tmp_path):
    """A new full snapshot starts a fresh delta epoch: the previous
    delta file is removed (restore must never overlay an older-baseline
    delta onto a newer full)."""
    s0 = _start_server()
    try:
        demb = DistributedEmbedding(_specs(), {"s0": s0.address})
        keys = np.arange(50, dtype=np.int64)
        dev, host = demb.pull({"emb": keys})
        demb.save(str(tmp_path))
        demb.push(host, {
            "emb": np.ones((len(host["emb"]), CFG.emb_dim), np.float32)
        })
        demb.save(str(tmp_path), delta_only=True)
        assert os.path.exists(str(tmp_path / "emb.delta.npz"))
        demb.save(str(tmp_path))  # new baseline
        assert not os.path.exists(str(tmp_path / "emb.delta.npz"))
        demb.close()
    finally:
        s0.stop()


def test_restore_rejects_orphan_delta(tmp_path):
    s0 = _start_server()
    try:
        demb = DistributedEmbedding(_specs(), {"s0": s0.address})
        demb.pull({"emb": np.arange(10, dtype=np.int64)})
        demb.save(str(tmp_path))
        demb.save(str(tmp_path), delta_only=True)
        os.remove(str(tmp_path / "emb.full.npz"))
        with pytest.raises(ValueError, match="full baseline"):
            demb.restore(str(tmp_path))
        demb.close()
    finally:
        s0.stop()


@pytest.mark.slow
def test_wire_error_waits_for_reseal_and_restores(tmp_path):
    """A PS dies UNDER a train step (worker sees the wire error before
    the master does): the step waits for the master's ring version to
    move, adopts through the normal failover path, restores from the
    checkpoint, and training rides through — the reference exits the
    worker here (tensorflow_failover.py:133)."""
    s0, s1, s2 = _start_server(), _start_server(), _start_server()
    try:
        master = FakePsMaster()
        master.set_ring(
            ["s0", "s1"], {"s0": s0.address, "s1": s1.address}
        )
        est = Estimator(
            make_model_fn({"s0": s0.address, "s1": s1.address}),
            config=RunConfig(
                model_dir=str(tmp_path), save_steps=5, log_steps=50,
                ps_failure_grace_s=30,
            ),
            master_client=master,
        )
        est.model.coll.version = master.version
        est.train(batch_input_fn(), max_steps=5)  # full ckpt-5

        # kill s1; the master only announces the re-sealed ring on the
        # SECOND version query after the kill — the pre-step poll sees
        # the stale ring, the step hits the dead socket, and
        # _await_reseal has to wait the master out
        s1.stop()
        state = {"calls": 0}
        orig = master.get_ps_version

        def delayed():
            state["calls"] += 1
            if state["calls"] == 2:
                master.set_ring(
                    ["s0", "s2"],
                    {"s0": s0.address, "s2": s2.address},
                )
            return orig()

        master.get_ps_version = delayed

        loss = est.train(batch_input_fn(seed=4), max_steps=10)
        assert np.isfinite(loss) and est.global_step == 10
        assert est.failover.changes == ["ps_failure"]
        assert est.model.coll.server_names == ["s0", "s2"]
        assert int(est.model.coll.stats()["s2"]["emb"]) > 0
        est.model.close()
    finally:
        s0.stop()
        s2.stop()


def test_cold_table_snapshot_keeps_slot_width(tmp_path):
    """A table with no admitted rows still snapshots at the ring's full
    row width (dim × (1 + optimizer slots)) — probed over the wire — so
    a local KvTable under the same optimizer can restore the file."""
    s0 = _start_server()
    try:
        demb = DistributedEmbedding(_specs(), {"s0": s0.address})
        # touch ONLY "emb"; "wide" stays cold
        demb.pull({"emb": np.arange(20, dtype=np.int64)})
        written = demb.save(str(tmp_path))
        assert written["wide"] == 0
        with np.load(str(tmp_path / "wide.full.npz")) as z:
            n_slots = int(z["n_slots"])
        from dlrover_tpu.sparse import GroupAdam as GA

        assert n_slots == GA(lr=1e-2).required_slots
        # a local collection under the same optimizer restores it
        local = EmbeddingCollection(_specs(), optimizer=GroupAdam(lr=1e-2))
        local.restore(str(tmp_path))
        local.close()
        demb.close()
    finally:
        s0.stop()


def test_brain_weights_reach_trainers_over_the_wire():
    """A Brain hot-shard rebalance (ElasticPsService.set_weights) must
    actually move keys on the trainers: the weights ride the
    PsVersionResponse over the real wire and sync_with_master
    re-partitions with them.  Before this field existed the version
    bumped but workers re-routed with their OLD weights — the rebalance
    silently no-opped."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.master.master import LocalJobMaster
    from dlrover_tpu.sparse.server import (
        register_server,
        resolve_ring,
        sync_with_master,
    )

    master = LocalJobMaster(port=0, num_workers=1)
    master.prepare()
    s0, s1 = _start_server(), _start_server()
    try:
        for node_id, server in ((100, s0), (101, s1)):
            c = MasterClient(master.addr, node_id=node_id)
            c.register_node(node_type=NodeType.PS)
            register_server(c, f"ps-{node_id}", server.address)
        worker = MasterClient(master.addr, node_id=0)
        worker.register_node()
        addrs = resolve_ring(worker, ["ps-100", "ps-101"])
        demb = DistributedEmbedding(_specs(), addrs)
        demb.version = worker.get_ps_version().version

        keys = np.arange(4000, dtype=np.int64)
        demb.pull({"emb": keys})
        before = {k: v["emb"] for k, v in demb.stats().items()}
        # unweighted HRW: roughly balanced
        assert abs(before["ps-100"] - before["ps-101"]) < 1200, before

        # the Brain decides ps-100 should carry 3x the keys
        master.ps_service.set_weights({"ps-100": 3.0, "ps-101": 1.0})
        resp = worker.get_ps_version()
        assert resp.weights == {"ps-100": 3.0, "ps-101": 1.0}
        assert sync_with_master(demb, worker) is True
        assert demb._weights == {"ps-100": 3.0, "ps-101": 1.0}
        after = {k: v["emb"] for k, v in demb.stats().items()}
        # weighted HRW: ~75/25 split, and no rows lost
        assert after["ps-100"] > 1.5 * after["ps-101"], after
        assert after["ps-100"] + after["ps-101"] == len(keys)
        demb.close()
    finally:
        master.stop()
        s0.stop()
        s1.stop()


def test_brain_weight_clear_reaches_trainers():
    """set_weights({}) — a rebalance reset — must also reach trainers:
    the wire value is authoritative INCLUDING the empty dict (returning
    None would silently keep the old 3:1 routing on long-running
    workers while fresh workers route unweighted — split-brain key
    ownership)."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.master.master import LocalJobMaster
    from dlrover_tpu.sparse.server import (
        register_server,
        resolve_ring,
        sync_with_master,
    )

    master = LocalJobMaster(port=0, num_workers=1)
    master.prepare()
    s0, s1 = _start_server(), _start_server()
    try:
        for node_id, server in ((100, s0), (101, s1)):
            c = MasterClient(master.addr, node_id=node_id)
            c.register_node(node_type=NodeType.PS)
            register_server(c, f"ps-{node_id}", server.address)
        worker = MasterClient(master.addr, node_id=0)
        addrs = resolve_ring(worker, ["ps-100", "ps-101"])
        demb = DistributedEmbedding(_specs(), addrs)
        demb.version = worker.get_ps_version().version
        keys = np.arange(3000, dtype=np.int64)
        demb.pull({"emb": keys})

        master.ps_service.set_weights({"ps-100": 4.0, "ps-101": 1.0})
        assert sync_with_master(demb, worker) is True
        skewed = {k: v["emb"] for k, v in demb.stats().items()}
        assert skewed["ps-100"] > 2 * skewed["ps-101"], skewed

        master.ps_service.set_weights({})  # rebalance reset
        assert sync_with_master(demb, worker) is True
        assert demb._weights == {}
        flat = {k: v["emb"] for k, v in demb.stats().items()}
        assert abs(flat["ps-100"] - flat["ps-101"]) < 900, flat
        assert flat["ps-100"] + flat["ps-101"] == len(keys)
        demb.close()
    finally:
        master.stop()
        s0.stop()
        s1.stop()


@pytest.mark.slow  # tier-1 budget: prunes/outage keep fast estimator coverage
def test_evaluator_role_watches_checkpoints(tmp_path):
    """A separate evaluator-role estimator (task_type='evaluator', not
    chief) watches the model_dir, evaluates each new checkpoint, and
    owns the best export — the reference's evaluator task in
    train_and_evaluate."""
    from dlrover_tpu.train.estimator import run_evaluator

    s0 = _start_server()
    try:
        addrs = {"s0": s0.address}
        cfg = RunConfig(model_dir=str(tmp_path), save_steps=5,
                        log_steps=50)
        trainer = Estimator(make_model_fn(addrs), config=cfg)
        trainer.train(batch_input_fn(), max_steps=10)
        trainer.model.close()

        evaluator = Estimator(
            make_model_fn(addrs),
            config=cfg,
            cluster=ClusterSpec(
                cluster={"worker": ["w-0"], "evaluator": ["e-0"]},
                task_type="evaluator", task_index=0,
            ),
        )
        assert not evaluator.cluster.is_chief
        # the trainer has stopped, so restoring into the shared ring is
        # safe here — opt in past the live-ring guard
        metrics = run_evaluator(
            evaluator,
            EvalSpec(batch_input_fn(seed=9), steps=4),
            poll_interval_s=0.1,
            stop_at_step=10,
            allow_ring_restore=True,
        )
        assert np.isfinite(metrics["loss"])
        assert evaluator.global_step == 10
        meta = json.loads(
            open(os.path.join(str(tmp_path), "export", "best",
                              "metadata.json"), encoding="utf-8").read()
        )
        assert meta["step"] == 10
        evaluator.model.close()
    finally:
        s0.stop()


def test_file_reader_string_columns(tmp_path):
    path = str(tmp_path / "s.csv")
    with open(path, "w", encoding="utf-8") as f:
        f.write("u1,3,1\nu2,4,0\n")
    reader = FileReader(
        path,
        [
            ColumnInfo("uid", "string"),
            ColumnInfo("n", "int64"),
            ColumnInfo("label", "float32", is_label=True),
        ],
        batch_size=2,
    )
    feats, labels = next(iter(reader))
    assert feats["uid"].tolist() == ["u1", "u2"]
    assert feats["n"].dtype == np.int64
    with pytest.raises(ValueError, match="dtype"):
        FileReader(
            path, [ColumnInfo("a", "complex")], batch_size=1
        )._batch(["x"])


@pytest.mark.slow  # tier-1 budget: prunes/outage keep fast estimator coverage
def test_estimator_executor_env_cluster_and_resume(tmp_path, monkeypatch):
    """EstimatorExecutor end to end: cluster spec injected via env (the
    set_tf_config path), train_and_evaluate, then a RESTARTED executor
    resumes from the latest checkpoint instead of step 0."""
    from dlrover_tpu.train.estimator import EstimatorExecutor

    s0 = _start_server()
    try:
        addrs = {"s0": s0.address}
        monkeypatch.setenv(
            CLUSTER_SPEC_ENV,
            json.dumps({
                "cluster": {"ps": ["s0"], "worker": ["w-0"]},
                "task": {"type": "worker", "index": 0},
            }),
        )
        cfg = RunConfig(model_dir=str(tmp_path), save_steps=4,
                        log_steps=50)
        ex = EstimatorExecutor(make_model_fn(addrs), cfg)
        assert ex.estimator.cluster.cluster["ps"] == ["s0"]
        assert ex.estimator.cluster.is_chief
        metrics = ex.train_and_evaluate(
            TrainSpec(batch_input_fn(), max_steps=8),
            EvalSpec(batch_input_fn(seed=9), steps=2, every_steps=4),
        )
        assert np.isfinite(metrics["loss"])
        assert ex.estimator.global_step == 8
        ex.estimator.model.close()

        ex2 = EstimatorExecutor(make_model_fn(addrs), cfg)
        ex2.train_and_evaluate(
            TrainSpec(batch_input_fn(), max_steps=8),
            EvalSpec(batch_input_fn(seed=9), steps=2, every_steps=4),
        )
        # resumed at the completed step: no retraining happened
        assert ex2.estimator.global_step == 8
        ex2.estimator.model.close()
    finally:
        s0.stop()


@pytest.mark.slow  # tier-1 budget: spawns a live master (~7s);
# outage handling also rides the slow estimator e2e drills
def test_estimator_survives_master_outage(tmp_path):
    """Every master touchpoint (global-step report, model info, the
    failover poll) degrades to a warning when the master dies mid-run —
    training and checkpointing continue without it."""
    s0 = _start_server()
    try:
        master = FakePsMaster()
        master.set_ring(["s0"], {"s0": s0.address})
        calls = {"n": 0}

        def outage(*a, **k):
            calls["n"] += 1
            raise ConnectionRefusedError("master is down")

        est = Estimator(
            make_model_fn({"s0": s0.address}),
            config=RunConfig(model_dir=str(tmp_path), save_steps=4,
                             log_steps=50),
            master_client=master,
        )
        est.model.coll.version = master.version
        est.failover._poll = 0.0  # poll every step so the outage is hit
        # the master dies before training starts
        master.get_ps_version = outage
        master.report_global_step = outage
        master.report_model_info = outage

        loss = est.train(batch_input_fn(), max_steps=8)
        assert np.isfinite(loss) and est.global_step == 8
        assert calls["n"] > 0  # the outage was really exercised
        assert est.latest_checkpoint() == 8  # checkpoints kept flowing
        est.model.close()
    finally:
        s0.stop()


def test_failover_defers_until_replacement_registers():
    """A ring announcement naming a server with no registered address
    adopts NOTHING (half-routing would strand keys at an unreachable
    host); adoption happens on the poll after the address appears."""
    s0, s1 = _start_server(), _start_server()
    try:
        master = FakePsMaster()
        master.set_ring(["s0"], {"s0": s0.address})
        demb = DistributedEmbedding(_specs(), {"s0": s0.address})
        demb.version = master.version
        fo = PsFailover(master, demb)

        # announce s1 WITHOUT registering its address
        master.servers = ["s0", "s1"]
        master.version += 1
        assert fo.poll_once() is None
        assert demb.server_names == ["s0"]

        master.kv[_ADDR_KV_PREFIX + "s1"] = json.dumps(list(s1.address))
        assert fo.poll_once() == "scaling"
        assert demb.server_names == ["s0", "s1"]
        demb.close()
    finally:
        s0.stop()
        s1.stop()


def test_incremental_before_any_full_widens_to_full(tmp_path):
    s0 = _start_server()
    try:
        est = Estimator(
            make_model_fn({"s0": s0.address}),
            config=RunConfig(model_dir=str(tmp_path), save_steps=1000),
        )
        est.model  # build
        est.save_incremental(3)  # nothing to be incremental against
        assert est._read_tracker() == {"latest_step": 3, "full_step": 3}
        assert os.path.exists(str(tmp_path / "ckpt-3" / "emb.full.npz"))
        est.model.close()
    finally:
        s0.stop()


# ---------------------------------------------------------------------------
# end-of-run save semantics, restore rewind, best-export side effects
# ---------------------------------------------------------------------------


class _RecordingModel:
    """Dense-only fake: records saves, optionally fails mid-run."""

    def __init__(self, fail_after=None):
        self.save_calls = []
        self.fail_after = fail_after
        self.steps_run = 0

    def train_step(self, features, labels):
        self.steps_run += 1
        if self.fail_after is not None and self.steps_run > self.fail_after:
            raise RuntimeError("boom")
        return 0.5

    def eval_metrics(self, features, labels):
        return {"loss": 0.1}

    def save(self, dir_path, delta_only=False, clear_dirty=None):
        self.save_calls.append((dir_path, delta_only, clear_dirty))

    def restore(self, dir_path):
        pass

    def close(self):
        pass


def _dense_input_fn():
    def input_fn():
        while True:
            yield {"x": np.zeros(2, np.float32)}, np.zeros(2, np.float32)

    return input_fn


def test_exceptional_exit_skips_end_of_run_save(tmp_path):
    """A crash must propagate unmasked and must NOT checkpoint the
    post-failure state over the last good one (ADVICE r5)."""
    model = _RecordingModel(fail_after=3)
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(
            model_dir=str(tmp_path), save_steps=1000, log_steps=1000
        ),
    )
    with pytest.raises(RuntimeError, match="boom"):
        est.train(_dense_input_fn(), max_steps=10)
    assert model.save_calls == []
    assert est._train_failed


def test_clean_exit_still_saves_end_of_run(tmp_path):
    model = _RecordingModel()
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(
            model_dir=str(tmp_path), save_steps=1000, log_steps=1000
        ),
    )
    est.train(_dense_input_fn(), max_steps=4)
    assert len(model.save_calls) == 1  # CheckpointSaverHook.end
    assert not est._train_failed


def test_mid_run_restore_rewinds_global_step(tmp_path, monkeypatch):
    """After an unplanned PS restore, step accounting resumes FROM the
    restored step (reference worker-restart semantics)."""
    model = _RecordingModel()
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(
            model_dir=str(tmp_path), save_steps=1000, log_steps=1000
        ),
    )
    est.model  # build
    est.global_step = 7
    est._needs_sparse_restore = True
    monkeypatch.setattr(est, "restore_latest", lambda: 5)
    est.train(_dense_input_fn(), max_steps=9)
    assert est.global_step == 9
    assert model.steps_run == 4  # steps 6..9, not 8..9


def test_export_best_is_side_effect_free(tmp_path):
    """Best export passes clear_dirty=False when the model supports it,
    so it cannot consume the sparse tier's dirty epoch (ADVICE r5)."""
    model = _RecordingModel()
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(model_dir=str(tmp_path)),
    )
    est.model
    assert est.export_best({"loss": 0.5}, "loss") is True
    assert len(model.save_calls) == 1
    assert model.save_calls[0][2] is False  # clear_dirty=False
    # a worse metric does not export
    assert est.export_best({"loss": 0.9}, "loss") is False
    assert len(model.save_calls) == 1


def test_run_evaluator_rejects_ring_backed_model(tmp_path):
    """An evaluator restoring into the SHARED PS ring would clobber the
    rows trainers are updating; the guard demands a local collection."""
    from dlrover_tpu.train.estimator import run_evaluator

    model = _RecordingModel()
    model.coll = DistributedEmbedding(_specs(), {"s0": ("localhost", 1)})
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(model_dir=str(tmp_path)),
    )
    with pytest.raises(ValueError, match="ring-backed"):
        run_evaluator(
            est, EvalSpec(input_fn=_dense_input_fn()), stop_at_step=1
        )


def test_ring_full_export_with_clear_dirty_false_keeps_delta_epoch(tmp_path):
    """A clear_dirty=False full export (best export) must leave the
    dirty epoch intact: the next delta still carries every row dirtied
    since the last CADENCED full save."""
    s0 = _start_server()
    try:
        demb = DistributedEmbedding(_specs(), {"s0": s0.address})
        keys = np.arange(6, dtype=np.int64)
        _dev, host = demb.pull({"emb": keys})
        demb.push(host, {
            "emb": np.ones((len(host["emb"]), CFG.emb_dim), np.float32)
        })
        # cadenced full save starts the delta epoch
        demb.save(str(tmp_path / "ckpt"))
        _dev, host = demb.pull({"emb": keys})
        demb.push(host, {
            "emb": np.ones((len(host["emb"]), CFG.emb_dim), np.float32)
        })
        # side-effect-free best export between cadenced saves
        demb.save(str(tmp_path / "best"), clear_dirty=False)
        # the 6 re-dirtied rows still land in the next delta
        written = demb.save(str(tmp_path / "ckpt"), delta_only=True)
        assert written["emb"] == 6
        demb.close()
    finally:
        s0.stop()


# ---------------------------------------------------------------------------
# ISSUE 3 satellites: node-listing resilience + best-export race/atomicity
# ---------------------------------------------------------------------------


def test_synthesize_cluster_spec_survives_malformed_node():
    """One node object missing BOTH name and id must fall back to its
    enumerate index — not raise inside the loop and drop the whole
    running-node listing (ADVICE r5 low #1)."""

    class _Node:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    master = FakePsMaster()
    master.set_ring(["s0"], {"s0": ("h", 1)})
    master.get_running_nodes = lambda: [
        _Node(type="worker", name="w-a"),
        _Node(type="worker"),            # no name, no id → index fallback
        _Node(type="evaluator", id=7),   # no name → role-id
    ]
    spec = synthesize_cluster_spec(master)
    assert spec.cluster["worker"] == ["w-a", "worker-1"]
    assert spec.cluster["evaluator"] == ["evaluator-7"]
    assert spec.cluster["ps"] == ["s0"]


def test_export_best_atomic_replace(tmp_path):
    """Best export lands via temp-dir + rename: after each export the
    ``best`` tree is complete (model + metadata agree) and no ``.best-``
    temp dirs linger (ADVICE r5 low #2)."""
    model = _RecordingModel()
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(model_dir=str(tmp_path)),
    )
    est.model
    assert est.export_best({"loss": 0.5}, "loss") is True
    export_root = os.path.join(str(tmp_path), "export")
    best = os.path.join(export_root, "best")
    with open(os.path.join(best, "metadata.json")) as f:
        assert json.load(f)["loss"] == 0.5
    # the model saved into the TEMP dir, which became best by rename
    assert model.save_calls[-1][0] != best
    est.global_step = 5
    assert est.export_best({"loss": 0.3}, "loss") is True
    with open(os.path.join(best, "metadata.json")) as f:
        assert json.load(f) == {"loss": 0.3, "step": 5}
    leftovers = [d for d in os.listdir(export_root) if d != "best"]
    assert leftovers == []


def test_export_best_failed_save_keeps_previous(tmp_path):
    """A save() crash mid-export must leave the previous best intact
    (the swap only happens after a complete temp tree) and clean up its
    temp dir."""
    model = _RecordingModel()
    est = Estimator(
        lambda mode, params, cluster: model,
        config=RunConfig(model_dir=str(tmp_path)),
    )
    est.model
    assert est.export_best({"loss": 0.5}, "loss") is True

    def _boom(dir_path, delta_only=False, clear_dirty=None):
        raise RuntimeError("save died")

    model.save = _boom
    with pytest.raises(RuntimeError, match="save died"):
        est.export_best({"loss": 0.2}, "loss")
    export_root = os.path.join(str(tmp_path), "export")
    best = os.path.join(export_root, "best")
    with open(os.path.join(best, "metadata.json")) as f:
        assert json.load(f)["loss"] == 0.5  # previous best survives
    assert [d for d in os.listdir(export_root) if d != "best"] == []


def test_train_and_evaluate_chief_defers_export_to_evaluator(tmp_path):
    """With an evaluator role in the ClusterSpec the chief must NOT race
    it on export/best: run_evaluator owns the export (ADVICE r5 low #2);
    without one the chief exports as before."""
    for evaluator, expect_saves in ((["e-0"], 0), ([], 1)):
        model = _RecordingModel()
        cluster = {"worker": ["w-0"]}
        if evaluator:
            cluster["evaluator"] = evaluator
        est = Estimator(
            lambda mode, params, cluster: model,
            config=RunConfig(
                model_dir=str(tmp_path / ("ev" if evaluator else "noev")),
                save_steps=10_000, log_steps=10_000,
            ),
            cluster=ClusterSpec(
                cluster=cluster, task_type="worker", task_index=0
            ),
        )
        assert est.cluster.is_chief
        train_and_evaluate(
            est,
            TrainSpec(input_fn=_dense_input_fn(), max_steps=2),
            EvalSpec(input_fn=_dense_input_fn(), steps=1, every_steps=2),
        )
        best_saves = [
            c for c in model.save_calls if ".best-" in str(c[0])
        ]
        assert len(best_saves) == expect_saves, (evaluator, model.save_calls)
