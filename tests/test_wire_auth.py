"""Connection auth on the TCP data planes (VERDICT r3 #5).

The replica ring already authenticated; these tests pin the lifted
shared preamble (common/sockets.py) onto the other three planes —
KvServer (carries model weights), BatchFeedServer (accepts training
data), local_sgd.SocketTransport (exchanges gradient deltas) — and the
run-id default plumbing. An unauthenticated connect must be closed
without a single protocol byte answered.
"""

import socket
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.common import sockets as shared
from dlrover_tpu.data.coworker import (
    BatchRing,
    BatchFeedServer,
    RemoteBatchWriter,
)
from dlrover_tpu.sparse.embedding import EmbeddingSpec
from dlrover_tpu.sparse.server import KvClient, KvServer

TOKEN = "s3cret-run"


def _raw_probe(addr, payload: bytes, timeout=3.0) -> bytes:
    """Connect without the preamble, send ``payload``, read the reply
    (b'' = server closed on us)."""
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(payload)
        try:
            return s.recv(4096)
        except (ConnectionError, TimeoutError):
            return b""


def test_default_token_comes_from_run_id(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", "run-42")
    assert shared.default_token() == "run-42"
    # the job-wide credential wins over the (possibly node-scoped) run
    # id — cross-host planes need ONE token per job
    monkeypatch.setenv("DLROVER_TPU_WIRE_TOKEN", "job-secret")
    assert shared.default_token() == "job-secret"
    monkeypatch.delenv("DLROVER_TPU_WIRE_TOKEN")
    monkeypatch.delenv("DLROVER_TPU_RUN_ID")
    assert shared.default_token() == ""


def test_kv_server_rejects_unauthenticated(monkeypatch):
    server = KvServer([EmbeddingSpec("emb", dim=4)], token=TOKEN)
    try:
        # authenticated client round-trips
        client = KvClient(server.address, token=TOKEN)
        keys = np.array([1, 2, 3], dtype=np.int64)
        rows = client.pull("emb", keys, train=True)
        assert rows.shape == (3, 4)
        client.close()
        # a valid protocol frame WITHOUT the preamble: closed, no reply
        import struct

        frame = struct.Struct("<cqq").pack(b"S", 2, 0) + b"{}"
        assert _raw_probe(server.address, frame) == b""
        # wrong token: same silence
        client_bad_alive = True
        try:
            bad = KvClient(server.address, token="wrong")
            bad.stats()
        except Exception:
            client_bad_alive = False
        assert not client_bad_alive
    finally:
        server.stop()


def test_batch_feed_server_rejects_unauthenticated(tmp_path):
    ring = BatchRing(
        f"auth-{time.time_ns()}", slots=2, slot_bytes=1 << 16, create=True
    )
    server = BatchFeedServer(ring, host="127.0.0.1", token=TOKEN)
    try:
        # authenticated producer delivers a batch
        w = RemoteBatchWriter(server.address, token=TOKEN)
        batch = {"x": np.arange(6, dtype=np.float32).reshape(2, 3)}
        w.put(batch)
        out = ring.get(timeout=5.0)
        assert np.allclose(out["x"], batch["x"])
        w.done()
        # a forged PUT without the preamble: closed, nothing deposited,
        # and crucially no done-marker accounting from the stray
        import struct

        forged = struct.Struct("<cq").pack(b"P", 4) + b"evil"
        assert _raw_probe(server.address, forged) == b""
        assert ring.get(timeout=2.0) is None  # the legit done marker
        with pytest.raises(TimeoutError):
            ring.get(timeout=0.3)  # nothing deposited, no stray marker
    finally:
        server.stop()
        ring.close()


def test_wrong_token_batch_writer_fails():
    ring = BatchRing(
        f"auth2-{time.time_ns()}", slots=2, slot_bytes=1 << 16, create=True
    )
    server = BatchFeedServer(ring, host="127.0.0.1", token=TOKEN)
    try:
        w = RemoteBatchWriter(server.address, token="wrong")
        with pytest.raises((RuntimeError, ConnectionError, OSError)):
            w.put({"x": np.zeros((1, 1), np.float32)})
    finally:
        server.stop()
        ring.close()


def test_socket_transport_token_on_by_default(monkeypatch):
    """SocketTransport picks up the run token by default and sits behind
    the SAME shared preamble as every other plane (VERDICT r4 weak #5):
    a protocol frame without the preamble — even a well-formed one — is
    closed before any header is parsed."""
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", TOKEN)
    from dlrover_tpu.checkpoint import replica as wire
    from dlrover_tpu.parallel.local_sgd import SocketTransport

    t = SocketTransport(rank=0, peers={}, bind_host="127.0.0.1")
    assert t.token == TOKEN
    try:
        # stray without the preamble: ignored (closed, never an ack —
        # the send itself may die with BrokenPipeError mid-frame, which
        # is the reject working)
        for preamble_token in (None, "wrong"):
            with socket.create_connection(
                ("127.0.0.1", t.port), timeout=3.0
            ) as s:
                try:
                    if preamble_token is not None:
                        shared.send_auth(s, preamble_token)
                    wire._send_frame(
                        s, {"src": 1, "round": 0, "size": 3}, b"bad"
                    )
                    s.settimeout(2.0)
                    reply = s.recv(16)
                except (TimeoutError, ConnectionError, OSError):
                    reply = b""
                assert reply == b""
        with t._cv:
            assert t._inbox == {}
        # peer with the preamble + token: accepted
        with socket.create_connection(
            ("127.0.0.1", t.port), timeout=3.0
        ) as s:
            shared.send_auth(s, TOKEN)
            wire._send_frame(
                s, {"src": 1, "round": 0, "size": 2}, b"ok"
            )
            wire._recv_frame(s)
        with t._cv:
            assert t._inbox[0][1] == b"ok"
    finally:
        t.close()


def test_socket_transport_allgather_authenticated(monkeypatch):
    """End-to-end: two transports with the run token complete an
    allgather (pins the CLIENT side of the preamble too)."""
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", TOKEN)
    from dlrover_tpu.parallel.local_sgd import SocketTransport

    # short timeout: on any failure the helper thread must not sit in
    # allgather's wait loop for the 600 s default at interpreter exit
    a = SocketTransport(
        rank=0, peers={}, bind_host="127.0.0.1", timeout=15.0
    )
    b = SocketTransport(
        rank=1, peers={}, bind_host="127.0.0.1", timeout=15.0
    )
    a.peers = {0: f"127.0.0.1:{a.port}", 1: f"127.0.0.1:{b.port}"}
    b.peers = dict(a.peers)
    try:
        out = {}
        th = threading.Thread(
            target=lambda: out.setdefault("b", b.allgather(b"from-b")),
            daemon=True,
        )
        th.start()
        got_a = a.allgather(b"from-a")
        th.join(timeout=10.0)
        assert got_a == [b"from-a", b"from-b"]
        assert out["b"] == [b"from-a", b"from-b"]
    finally:
        a.close()
        b.close()
