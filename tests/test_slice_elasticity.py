"""Slice-grain elasticity drill (VERDICT r4 ask #1b).

The production TPU topology is DCN-connected slices, and the elastic
unit is a WHOLE slice (a partial slice has no ICI to the rest —
SURVEY.md §7, cluster/scaler.py, rdzv node_unit). This drill runs the
real process stack — a master plus one launcher/agent group per
emulated slice, each worker process driving TWO local devices (a TPU VM
with locally-attached chips) — and proves:

1. kill the whole of slice 1 (its agent process group) → the master
   re-seals a 1-slice world and the survivor re-meshes from
   num_slices=2 (dp across DCN, fsdp intra-slice) to num_slices=1,
   restoring the 2-slice checkpoint RESHARDED onto the 1-slice mesh
   from the emergency-persisted host packs;
2. recovery (crash → resumed-from-ckpt) fits the <60 s budget;
3. a replacement slice joining mid-run re-meshes BACK to num_slices=2
   and resumes from the shrunk world's progress — the grow half;
4. loss continuity: every resume starts at-or-past the prior
   checkpointed step (never from scratch) and the loss improves across
   the whole shrink/grow.

The worker (examples/train_gpt_elastic.py --hosts-per-slice 1) rebuilds
its hybrid multi-slice mesh (parallel/mesh.py num_slices) from the
CURRENT world on every restart. Whole-slice sealing at the rendezvous
level (a partial slice is never sealed, node_unit truncation) is pinned
separately in test_master.py::test_node_unit_rendezvous_seals_whole_slices,
and the scaler's whole-slice snap in test_kube.py / test_cluster.py —
this drill is the training-side re-mesh those guarantees feed.
"""

import os
import re
import time

import pytest

from elastic_harness import (
    collect,
    drain,
    drain_now,
    kill_tree,
    launch_agent,
    start_master,
)

# each host (= agent = emulated slice) drives 2 local CPU devices
CHIPS_PER_HOST = 2
HOST_ENV = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}


@pytest.mark.slow
def test_slice_shrink_grow_elasticity(tmp_path):
    run_id = f"se{os.getpid()}"
    master, master_q, master_lines, addr = start_master(
        run_id,
        argv_extra=("--num-workers", "1", "--max-workers", "2"),
        # short grace: the post-crash re-seal (the recovery critical
        # path) waits this long for the lost slice before shrinking
        env_extra={"DLROVER_TPU_CTX_RDZV_WAIT_EXTRA_NODES_S": "3"},
    )
    # --steps 60 is pure runway: the test tears down after the grown
    # world commits a joint checkpoint (running to dataset completion
    # would race the joiner's cold start against the shrunk world's
    # cached ~1 s steps — timing-fragile under CI contention, same
    # reasoning as test_world_grow_joins_mid_run)
    train_args = (
        "--steps", "60", "--batch", "4", "--seq", "32",
        "--hosts-per-slice", "1",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--ckpt-every", "2",
    )
    agents = {}
    queues = {}
    lines = {}

    def spawn(node_id, max_restarts, crash_at=None):
        extra = ("--crash-at", str(crash_at)) if crash_at else ()
        agents[node_id] = launch_agent(
            run_id, node_id, addr, train_args + extra,
            agent_args=("--max-restarts", str(max_restarts)),
            nnodes="1:2",
            env_extra=HOST_ENV,
        )
        queues[node_id] = drain(agents[node_id])
        lines.setdefault(node_id, [])

    def dump(nid):
        drain_now(queues[nid], lines[nid])
        return "".join(lines[nid])

    try:
        # slice 0 = host 0 (survivor, restart budget); slice 1 = host 1
        # (the doomed slice: after the synchronized crash at step 3 it
        # leaves the job for good)
        spawn(0, max_restarts=2, crash_at=3)
        spawn(1, max_restarts=0, crash_at=3)

        # ---- phase 1: 2 slices × 2 chips training ----------------------
        assert collect(
            queues[0], lines[0],
            until=lambda l: "slice mesh: num_slices=2" in l,
            deadline=time.time() + 300,
        ), dump(0)[-3000:]
        assert collect(
            queues[1], lines[1],
            until=lambda l: "simulating crash at step 3" in l,
            deadline=time.time() + 300,
        ), dump(1)[-3000:]
        t_crash = time.time()
        agents[1].wait(timeout=180)
        assert agents[1].returncode != 0

        # ---- phase 2: shrink to 1 slice, resharded restore -------------
        stamps = {}

        def watch_resume(line):
            if "resumed from step" in line and "resumed" not in stamps:
                stamps["resumed"] = time.time()

        shrunk = collect(
            queues[0], lines[0],
            until=lambda l: "slice mesh: num_slices=1" in l,
            deadline=time.time() + 300,
            on_line=watch_resume,
        )
        assert shrunk, dump(0)[-4000:]
        resumed = collect(
            queues[0], lines[0],
            until=lambda l: "resumed from step" in l,
            deadline=time.time() + 180,
            on_line=watch_resume,
        )
        assert resumed, dump(0)[-4000:]
        # continuity: resumed from the step-2 checkpoint, not step 0
        assert "resumed from step 2" in resumed, resumed
        recovery_s = stamps["resumed"] - t_crash
        assert recovery_s < 60.0, f"recovery took {recovery_s:.1f}s"

        # let the shrunk world make real progress before growing
        assert collect(
            queues[0], lines[0],
            until=lambda l: re.search(r"step=[4-9] ", l),
            deadline=time.time() + 240,
        ), dump(0)[-4000:]

        # ---- phase 3: a replacement slice joins — grow back ------------
        # (no --crash-at: the replacement is a healthy fresh host)
        spawn(1, max_restarts=2)

        # the grow is proven once the re-meshed 2-slice world RESUMES
        # from a checkpoint and then commits a joint one ("(2 hosts)").
        # Generous deadline: on a loaded 1-core box the joiner's cold
        # process start alone can take many minutes.
        saw = {}

        def watch_grow(line):
            if "slice mesh: num_slices=1" in line:
                saw["shrunk_mesh"] = True
            elif "slice mesh: num_slices=2" in line and saw.get(
                "shrunk_mesh"
            ):
                saw["regrown_mesh"] = True
            elif "resumed from step" in line and saw.get("regrown_mesh"):
                saw["regrown_resume"] = True

        for line in lines[0]:
            watch_grow(line)
        joint = collect(
            queues[0], lines[0],
            until=lambda l: "(2 hosts)" in l and "regrown_resume" in saw,
            deadline=time.time() + 900,
            on_line=watch_grow,
        )
        if joint is None:
            drain_now(master_q, master_lines)
            raise AssertionError(
                "no joint checkpoint after grow "
                f"(agent0 rc={agents[0].poll()} "
                f"agent1 rc={agents[1].poll()} saw={saw}):\n"
                "--- host 0 ---\n"
                + dump(0)[-4000:]
                + "\n--- host 1 (joiner) ---\n"
                + dump(1)[-2000:]
                + "\n--- master ---\n"
                + "".join(master_lines)[-2000:]
            )
        out0 = dump(0)

        # phase 1 really ran 2 slices × 2 chips as one SPMD job
        assert "4 global devices" in out0, out0[-4000:]
        # the shrunk world re-meshed to one slice over 2 local chips
        # (a single surviving host runs without jax.distributed, so the
        # mesh line is the evidence: dp collapsed to 1, fsdp kept the
        # intra-slice pair)
        assert "slice mesh: num_slices=1 dp=1 fsdp=2" in out0, (
            out0[-4000:]
        )
        # the grown world re-meshed BACK to two slices
        assert out0.rindex("slice mesh: num_slices=2") > out0.index(
            "slice mesh: num_slices=1"
        ), out0[-4000:]
        # continuity across the grow too: every resume is at-or-past the
        # first checkpoint, never from scratch
        resumes = [
            int(m) for m in re.findall(r"resumed from step (\d+)", out0)
        ]
        assert resumes and resumes[0] == 2, resumes
        assert all(r >= 2 for r in resumes), resumes
        # loss improves across the whole drill
        losses = [float(x) for x in re.findall(r"loss=([\d.]+)", out0)]
        assert len(losses) >= 10, out0[-3000:]
        assert losses[-1] < losses[0], (losses[0], losses[-1])
        print(
            f"\n[slice-elasticity] 2-slice→1-slice recovery: "
            f"{recovery_s:.1f}s (crash → resumed, resharded "
            f"dp2·fsdp2→dp1·fsdp2); grow re-meshed back to 2 slices; "
            f"final loss {losses[-1]:.3f} < first {losses[0]:.3f}"
        )
    finally:
        for proc in agents.values():
            kill_tree(proc)
        master.kill()
        master.wait()
