"""HLO-cleanliness of the sharded train step.

The SPMD partitioner can make a config numerically correct while falling
back to replicate-then-repartition ("involuntary full rematerialization")
on a reshard it cannot do efficiently — on real [B,S,D] activations that
is a full all-gather every step. Round 3 shipped exactly this on the
fsdp·dp·tp mesh: the embedding gather's output inherited the table's
fsdp-sharded embed dim, unreachable from the batch-sharded activation
layout (VERDICT r3 weak #1). These tests pin the fix (gather-on-use
constraint in models/decoder.py forward) and the driver gate
(__graft_entry__.check_hlo_clean).

Reference never pays this class of cost (NCCL groups reshard nothing):
atorch/atorch/distributed/distributed.py:323.
"""

import jax
import jax.numpy as jnp
import pytest

import __graft_entry__ as graft
from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)

# full train-step compile inspection is heavy; excluded from the tier-1 budget
pytestmark = pytest.mark.slow

MARKER = "Involuntary full rematerialization"


def test_check_hlo_clean_passes_on_clean_output():
    graft.check_hlo_clean("")
    graft.check_hlo_clean("compiled fine\nok\n")


def test_check_hlo_clean_raises_on_involuntary_remat():
    stderr = (
        "W0731 spmd_partitioner.cc:652 [SPMD] Involuntary full "
        "rematerialization. The compiler cannot go from sharding X to Y\n"
    )
    with pytest.raises(RuntimeError, match="involuntary"):
        graft.check_hlo_clean(stderr)


def test_fsdp_dp_tp_train_step_has_no_involuntary_remat(capfd):
    """Compile the r3-offending config (fsdp2·dp2·tp2, grad-accum scan,
    full remat) and assert the partitioner stays silent. ``capfd``
    captures at the fd level, so the C++ absl warning stream is seen."""
    mesh = build_mesh(
        MeshConfig(dp=2, fsdp=2, tp=2), devices=jax.devices()[:8]
    )
    cfg = get_config(
        "tiny-moe",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=256,
        max_seq=64,
        remat="full",
    )
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2, decay_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt, grad_accum=2).build()
    tokens = jnp.zeros((8, 64), dtype=jnp.int32)
    batch = jax.device_put(
        {"tokens": tokens, "targets": tokens}, batch_sharding(mesh)
    )
    capfd.readouterr()  # drop anything staged before compile
    compiled = step.lower(state, batch).compile()
    out, err = capfd.readouterr()
    assert MARKER not in out and MARKER not in err, (
        "SPMD partitioner fell back to replicate-then-repartition:\n"
        + "\n".join(
            line for line in (out + err).splitlines() if MARKER in line
        )
    )
    # and the step still runs + learns the same thing it did in r3
    state, metrics = compiled(state, batch)
    assert metrics["loss"].shape == ()
    assert bool(jnp.isfinite(metrics["loss"]))
