"""Serving-tier observability (PR: lifecycle tracing + SLO watchdog).

Fast tier — pure host-side units, no serving.server/replica import at
module scope:

- ``ServingWatchdog`` gate classification (slo_breach, ttft_regression,
  spec_accept_collapse, shed_storm, migration_fallback), edge-trigger /
  re-arm semantics, and the immediately-written capture artifact
  (engine phase split + allocator occupancy via ``snapshot_fn``).
- ``healthcheck`` replay of a serving flight recorder: per-replica
  window + fleet percentiles merged from the recorded histogram
  envelopes, exit 1 on an SLO breach naming the breaching replica,
  torn-line tolerance.
- Zero-cost-when-off tracing: the NullTracer's ``complete_span`` is a
  pinned no-op (tracemalloc-guarded), and the scheduler emits spans
  only when a real tracer is installed.

Slow tier — the acceptance drills:

- 2 replicas with tracing on, kill one mid-decode: the merged Chrome
  trace contains the victim request's span chain (queue wait → prefill
  chunks → decode → migration transfer → resume on the survivor)
  correlated by ``rid``; the router's fleet histogram merge equals the
  by-hand merge of per-replica histograms.
- An injected stall on one replica breaches the p99 SLO: the watchdog
  fires a serving AnomalyRecord, writes a capture with phase split +
  allocator occupancy, and the offline healthcheck replay names the
  breaching replica with exit code 1.
"""

import json
import time
import tracemalloc

import pytest

from dlrover_tpu.common.constants import GraftEnv
from dlrover_tpu.observability import healthcheck, telemetry, tracing
from dlrover_tpu.observability.histogram import LatencyHistogram
from dlrover_tpu.observability.telemetry import configure_hub, reset_hub
from dlrover_tpu.observability.watchdog import (
    SERVING_ANOMALY_KINDS,
    ServingWatchdog,
    ServingWatchdogConfig,
)
from dlrover_tpu.serving.scheduler import Scheduler


@pytest.fixture(autouse=True)
def _fresh_bus():
    reset_hub()
    tracing.reset_tracer()
    yield
    reset_hub()
    tracing.reset_tracer()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _rec(**kw):
    base = dict(replica="rep-0", completed=20, p99_ms=10.0)
    base.update(kw)
    return telemetry.ServingRecord(**base)


def _watchdog(tmp_path=None, clock=None, **cfg_kw):
    if tmp_path is not None:
        cfg_kw.setdefault("capture_dir", str(tmp_path / "caps"))
    cfg = ServingWatchdogConfig(node_id=3, **cfg_kw)
    return ServingWatchdog(cfg, clock=clock or FakeClock())


# ---------------------------------------------------------------------------
# gate classification + edge-trigger semantics
# ---------------------------------------------------------------------------


def test_serving_kinds_disjoint_from_training_kinds():
    from dlrover_tpu.observability.watchdog import ANOMALY_KINDS

    assert not set(SERVING_ANOMALY_KINDS) & set(ANOMALY_KINDS)


def test_slo_breach_edge_triggers_and_rearms():
    wd = _watchdog(p99_target_ms=100.0, min_completed=8)
    # breach fires exactly once while sustained
    assert [a.kind for a in wd.observe(_rec(p99_ms=150.0))] == ["slo_breach"]
    assert wd.observe(_rec(p99_ms=180.0)) == []
    # clearing re-arms the gate; the next breach fires again
    assert wd.observe(_rec(p99_ms=50.0)) == []
    fired = wd.observe(_rec(p99_ms=140.0))
    assert [a.kind for a in fired] == ["slo_breach"]
    assert len(wd.anomalies) == 2
    a = wd.anomalies[0]
    assert a.replica == "rep-0" and a.node_id == 3
    assert a.value == 150.0 and "target=100" in a.detail


def test_min_completed_suppresses_noise_and_zero_target_disables():
    wd = _watchdog(p99_target_ms=100.0, min_completed=8)
    # 3 completions is noise, however bad the percentile looks
    assert wd.observe(_rec(completed=3, p99_ms=9999.0)) == []
    # target 0 disables the gate entirely
    off = _watchdog()  # all latency targets default 0
    assert off.observe(_rec(p99_ms=1e9, ttft_p99_ms=1e9)) == []


def test_ttft_regression_gate():
    wd = _watchdog(ttft_target_ms=50.0, min_completed=4)
    out = wd.observe(_rec(completed=5, ttft_p99_ms=80.0))
    assert [a.kind for a in out] == ["ttft_regression"]
    assert "ttft_p99=80" in out[0].detail


def test_spec_accept_collapse_needs_enough_drafts():
    wd = _watchdog(min_accept_rate=0.2, min_draft_tokens=64)
    # too few drafts to judge
    assert wd.observe(_rec(draft_tokens=10, spec_accept_rate=0.01)) == []
    out = wd.observe(_rec(draft_tokens=200, spec_accept_rate=0.05))
    assert [a.kind for a in out] == ["spec_accept_collapse"]
    # healthy accept rate never fires
    assert wd.observe(_rec(draft_tokens=500, spec_accept_rate=0.8)) == []


def test_shed_storm_fires_on_drop_delta_not_lifetime_total():
    wd = _watchdog(shed_storm_drops=8)
    # first observation only sets the baseline — a replica restarted
    # with a big lifetime counter must not instantly alarm
    assert wd.observe(_rec(shed=100, rejected=50)) == []
    # +3 new drops: under the storm threshold
    assert wd.observe(_rec(shed=102, rejected=51)) == []
    # +10 new drops in one window: storm
    out = wd.observe(_rec(shed=110, rejected=53))
    assert [a.kind for a in out] == ["shed_storm"]
    assert "new_drops=10" in out[0].detail
    # flat counters re-arm; the next burst fires again
    assert wd.observe(_rec(shed=110, rejected=53)) == []
    out = wd.observe(_rec(shed=110, rejected=53, timed_out=9))
    assert [a.kind for a in out] == ["shed_storm"]


def test_migration_fallback_fires_on_streak_and_live_resets():
    wd = _watchdog(fallback_storm=2)

    def rep(path):
        return type("R", (), {"path": path, "re_prefilled": {"x": "s"}})()

    assert wd.observe_migration(rep("fallback"), replica="rep-1") is None
    a = wd.observe_migration(rep("fallback"), replica="rep-1")
    assert a is not None and a.kind == "migration_fallback"
    assert a.replica == "rep-1" and "consecutive_fallbacks=2" in a.detail
    # a live migration resets the streak AND re-arms the gate
    assert wd.observe_migration(rep("live"), replica="rep-1") is None
    assert wd.observe_migration(rep("fallback"), replica="rep-1") is None
    assert (
        wd.observe_migration(rep("fallback"), replica="rep-1").kind
        == "migration_fallback"
    )


def test_anomalies_publish_on_the_hub():
    hub = configure_hub()
    seen = []
    hub.subscribe(seen.append, types=("AnomalyRecord",))
    wd = _watchdog(p99_target_ms=100.0)
    wd.observe(_rec(p99_ms=500.0, replica="rep-9"))
    assert len(seen) == 1
    assert seen[0].kind == "slo_breach" and seen[0].replica == "rep-9"
    # survives the wire like every other record
    back = telemetry.from_json(seen[0].to_json())
    assert back.replica == "rep-9"


# ---------------------------------------------------------------------------
# triggered capture: immediate write, engine snapshot, storm budget
# ---------------------------------------------------------------------------


def test_capture_written_immediately_with_engine_snapshot(tmp_path):
    snap = {
        "phase_split": {"step_time_s": 1.2, "host_time_s": 0.3,
                        "table_ships": 4},
        "allocator": {"free_pages": 2, "reserved_pages": 1, "n_pages": 16},
        "scheduler": {"queue_depth": 7},
    }
    wd = _watchdog(tmp_path, p99_target_ms=100.0)
    wd.snapshot_fn = lambda: snap
    (a,) = wd.observe(_rec(p99_ms=250.0, replica="rep-2/x"))
    assert a.capture and "rep-2_x" in a.capture and "slo_breach" in a.capture
    with open(a.capture) as f:
        doc = json.load(f)
    assert doc["anomaly"]["kind"] == "slo_breach"
    assert doc["anomaly"]["replica"] == "rep-2/x"
    assert doc["engine"]["phase_split"]["step_time_s"] == 1.2
    assert doc["engine"]["allocator"]["free_pages"] == 2
    assert doc["record"]["p99_ms"] == 250.0  # the breaching window rides


def test_capture_survives_snapshot_failure(tmp_path):
    wd = _watchdog(tmp_path, p99_target_ms=100.0)
    wd.snapshot_fn = lambda: 1 / 0
    (a,) = wd.observe(_rec(p99_ms=250.0))
    with open(a.capture) as f:
        doc = json.load(f)
    assert "error" in doc["engine"]  # capture landed anyway


def test_capture_rate_limit_and_budget(tmp_path):
    clock = FakeClock()
    wd = _watchdog(
        tmp_path, clock=clock, p99_target_ms=100.0, ttft_target_ms=10.0,
        min_capture_interval_s=60.0, max_captures=2,
    )
    (a1,) = wd.observe(_rec(p99_ms=200.0))
    assert a1.capture  # first breach captures
    clock.t = 1.0
    (a2,) = wd.observe(_rec(p99_ms=200.0, ttft_p99_ms=50.0))
    assert a2.kind == "ttft_regression"
    assert a2.capture == ""  # classified, but capture rate-limited
    clock.t = 100.0
    wd.observe(_rec(p99_ms=50.0, ttft_p99_ms=1.0))  # clear both gates
    (a3,) = wd.observe(_rec(p99_ms=200.0))
    assert a3.capture  # budget slot 2 of 2
    clock.t = 300.0
    wd.observe(_rec(p99_ms=50.0))
    (a4,) = wd.observe(_rec(p99_ms=200.0))
    assert a4.capture == ""  # lifetime budget exhausted
    assert wd._captures_used == 2


def test_no_capture_dir_means_classify_only(tmp_path):
    wd = _watchdog(p99_target_ms=100.0)  # no capture_dir
    (a,) = wd.observe(_rec(p99_ms=200.0))
    assert a.capture == ""
    assert not list(tmp_path.iterdir())


# ---------------------------------------------------------------------------
# healthcheck replay of a serving flight recorder
# ---------------------------------------------------------------------------


def _serving_hists(ms_samples):
    hists = {k: LatencyHistogram() for k in
             ("e2e", "ttft", "tpot", "queue_wait")}
    for v in ms_samples:
        hists["e2e"].record(v)
        hists["ttft"].record(v / 4)
    return json.dumps({k: h.to_dict() for k, h in hists.items()},
                      sort_keys=True)


def _write_serving_flight(path, breach=True):
    hub = configure_hub(jsonl_path=str(path))
    hub.publish(telemetry.ServingRecord(
        replica="srv-0", completed=20, admitted=22, shed=1, rejected=1,
        p99_ms=40.0, ttft_p99_ms=10.0,
        hists=_serving_hists([10.0] * 19 + [40.0]),
    ))
    hub.publish(telemetry.ServingRecord(
        replica="srv-1", completed=20, admitted=20,
        p99_ms=900.0, ttft_p99_ms=200.0,
        hists=_serving_hists([20.0] * 15 + [900.0] * 5),
    ))
    if breach:
        hub.publish(telemetry.AnomalyRecord(
            kind="slo_breach", step=2, node_id=1, value=900.0,
            detail="p99=900ms target=250ms n=20", replica="srv-1",
            capture="/caps/capture_serving2_srv-1_slo_breach.json",
        ))
    reset_hub()


def test_healthcheck_serving_replay_names_breaching_replica(tmp_path):
    path = tmp_path / "serving.jsonl"
    _write_serving_flight(path)
    # torn tail + foreign line: replay must skip, not crash
    with open(path, "a") as f:
        f.write('{"not": "ours"}\n{"r": "ServingRecord", "d": {"re')

    diag = healthcheck.diagnose(healthcheck.load_records(str(path)))
    assert not diag["healthy"]
    info = diag["anomalies"]["slo_breach"]
    assert info["replicas"] == ["srv-1"]
    assert info["captures"] == [
        "/caps/capture_serving2_srv-1_slo_breach.json"
    ]
    srv = diag["serving"]
    assert set(srv["replicas"]) == {"srv-0", "srv-1"}
    assert srv["replicas"]["srv-1"]["p99_ms"] == 900.0
    assert srv["replicas"]["srv-0"]["dropped"] == 2
    # fleet percentiles come from the MERGED envelopes: 40 samples,
    # 5 of them at ~900ms → fleet p99 sits in the slow mass
    assert srv["fleet"]["e2e"]["n"] == 40
    assert srv["fleet"]["e2e"]["p99"] > 800.0

    report = healthcheck.format_report(diag)
    assert "breaching replica(s): srv-1" in report
    assert "serving replicas:" in report
    assert "fleet e2e:" in report


def test_healthcheck_serving_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.jsonl"
    _write_serving_flight(bad)
    assert healthcheck.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "srv-1" in out and "slo_breach" in out

    ok = tmp_path / "ok.jsonl"
    _write_serving_flight(ok, breach=False)
    assert healthcheck.main([str(ok)]) == 0
    out = capsys.readouterr().out
    assert "healthy" in out and "serving replicas:" in out

    # --json mode stays serializable with the serving section attached
    assert healthcheck.main([str(bad), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["serving"]["fleet"]["e2e"]["n"] == 40
    assert doc["anomalies"]["slo_breach"]["replicas"] == ["srv-1"]


def test_healthcheck_tolerates_torn_hists_envelope(tmp_path):
    path = tmp_path / "torn.jsonl"
    hub = configure_hub(jsonl_path=str(path))
    hub.publish(telemetry.ServingRecord(
        replica="srv-0", completed=5, p99_ms=10.0, hists='{"e2e": {"bro'
    ))
    reset_hub()
    diag = healthcheck.diagnose(healthcheck.load_records(str(path)))
    # the per-replica view stands even when the envelope is torn
    assert diag["serving"]["replicas"]["srv-0"]["p99_ms"] == 10.0
    assert diag["serving"]["fleet"] == {}


# ---------------------------------------------------------------------------
# tracing: zero-cost when off, scheduler spans when on
# ---------------------------------------------------------------------------


def test_null_tracer_complete_span_is_pinned_noop(monkeypatch):
    monkeypatch.delenv(GraftEnv.TRACE_DIR, raising=False)
    tr = tracing.get_tracer()
    assert not tr.enabled
    assert tr.complete_span("serving.queue_wait", time.monotonic()) == 0.0
    t0 = time.monotonic()
    tracemalloc.start()
    base = tracemalloc.get_traced_memory()[0]
    for _ in range(2000):
        t = tracing.get_tracer()
        if t.enabled:  # the guard every serving call site uses
            pytest.fail("tracer must stay disabled without configuration")
        t.complete_span("serving.queue_wait", t0)
    grown = tracemalloc.get_traced_memory()[0] - base
    tracemalloc.stop()
    assert grown < 4096, f"disabled-tracer hot path retained {grown}B"
    assert tr.events() == []


def test_scheduler_emits_no_spans_with_tracing_off(monkeypatch):
    monkeypatch.delenv(GraftEnv.TRACE_DIR, raising=False)
    s = Scheduler(replica="quiet")
    r = s.submit([1, 2], 2)
    s.record_admitted(s.pop_next())
    s.re_admit(r)
    assert tracing.get_tracer().events() == []


def test_scheduler_emits_rid_correlated_spans_with_tracing_on():
    tr = tracing.configure_tracer("serving-test", force=True)
    assert tr.enabled
    s = Scheduler(replica="loud")
    r = s.submit([1, 2], 2)
    s.record_admitted(s.pop_next())
    s.re_admit(r)
    events = tr.events()
    qw = [e for e in events if e["name"] == "serving.queue_wait"]
    assert len(qw) == 1 and qw[0]["ph"] == "X"
    assert qw[0]["args"]["rid"] == r.rid
    assert qw[0]["args"]["replica"] == "loud"
    ra = [e for e in events if e["name"] == "serving.re_admit"]
    assert len(ra) == 1 and ra[0]["ph"] == "i"
    assert ra[0]["args"]["rid"] == r.rid


def test_complete_span_backdates_to_interval_start():
    tr = tracing.configure_tracer("serving-test", force=True)
    t0 = time.monotonic() - 0.05  # interval started 50 ms ago
    dur = tr.complete_span("serving.queue_wait", t0, rid="x/r0")
    assert 0.04 < dur < 5.0
    (ev,) = [e for e in tr.events() if e["name"] == "serving.queue_wait"]
    assert ev["dur"] == pytest.approx(dur * 1e6)
    # the event's start sits ~dur before its emission time
    assert ev["ts"] + ev["dur"] <= tr._now_us() + 1e3


# ---------------------------------------------------------------------------
# slow acceptance drills
# ---------------------------------------------------------------------------


_SERVER_KW = dict(
    n_slots=4, max_len=32, page_size=4, mode="bf16", prefill_chunk=4,
    idle_sleep=0.001,
)


def _mid_stream(rep, want):
    eng = rep.server.engine
    slots = [s for s in eng.slots if s is not None]
    return len(slots) == want and all(
        s.phase == "decode"
        and len(s.generated) >= 1
        and not s.req.future.done()
        for s in slots
    )


def _tiny_setup():
    jax = pytest.importorskip("jax")
    from dlrover_tpu.models import decoder
    from dlrover_tpu.models.config import get_config

    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    return cfg, params


@pytest.mark.slow
def test_tracing_drill_merged_trace_has_rid_span_chain(tmp_path):
    """Kill one of two replicas mid-decode with tracing on: the merged
    trace holds the victim request's whole life, correlated by rid —
    queue wait → prefill chunks → decode occupancy on the victim →
    migration transfer → live resume → decode on the survivor."""
    from dlrover_tpu.serving import migration as mig
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica
    from dlrover_tpu.serving.scheduler import SamplingParams

    cfg, params = _tiny_setup()
    trace_dir = tmp_path / "traces"
    tracing.configure_tracer(
        "serving-drill", trace_dir=str(trace_dir), force=True
    )
    prompts = [[2, 3, 4, 2, 3], [9, 10, 9, 10], [5, 6, 7], [11, 3, 7, 1]]
    sps = [
        SamplingParams(temperature=0.9, top_k=5, top_p=0.9, seed=i + 1)
        for i in range(4)
    ]
    r0 = ServingReplica("obs-0", params, cfg, node_id=0, **_SERVER_KW)
    r1 = ServingReplica("obs-1", params, cfg, node_id=1, **_SERVER_KW)
    r0.start()
    r1.start()
    try:
        router = ReplicaRouter([r0, r1], migrator=mig.ServingMigrator())
        with r1.server.paused() as eng1:
            reqs = [
                router.submit(p, 14, sampling=sp)
                for p, sp in zip(prompts, sps)
            ]
            victim_rids = sorted(
                e.req.rid for e in router._entries if e.replica is r1
            )
            assert len(victim_rids) == 2
            for _ in range(50):
                if _mid_stream(r1, 2):
                    break
                eng1.step()
            assert _mid_stream(r1, 2), "victim never reached mid-stream"
            r1.kill()
        moved = router.poll()
        assert moved == 2
        outs = router.wait_all(timeout=600)
        assert len(outs) == 4 and all(r.future.done() for r in reqs)

        # fleet rollup: the router's merged histograms ARE the by-hand
        # merge of per-replica histograms — same counts, same p99
        from dlrover_tpu.observability.histogram import merge_histograms

        fleet = router.fleet_histograms()
        manual = merge_histograms(
            s.histograms()["e2e"]
            for s in (r0.server.scheduler, r1.server.scheduler)
        )
        assert fleet["e2e"].counts == manual.counts
        assert fleet["e2e"].n == 4  # every request exactly once
        assert router.fleet_latency_ms() == manual.summary()
    finally:
        r0.stop()
        r1.kill()
        tracing.reset_tracer()  # close the trace file before merging

    events = tracing.merge_trace_dir(str(trace_dir))
    rid = victim_rids[0]

    def spans(name):
        return sorted(
            (
                e for e in events
                if e.get("name") == name
                and e.get("args", {}).get("rid") == rid
            ),
            key=lambda e: e["ts"],
        )

    qw = spans("serving.queue_wait")
    assert len(qw) == 1 and qw[0]["args"]["replica"] == "obs-1"
    pf = spans("serving.prefill_chunk")
    assert pf and all(e["args"]["replica"] == "obs-1" for e in pf)
    dec = spans("serving.decode")
    victim_dec = [e for e in dec if e["args"]["replica"] == "obs-1"]
    survivor_dec = [e for e in dec if e["args"]["replica"] == "obs-0"]
    assert len(victim_dec) == 1
    assert victim_dec[0]["args"]["reason"] == "migrated_out"
    assert len(survivor_dec) == 1
    assert survivor_dec[0]["args"]["resumed"] is True
    assert survivor_dec[0]["args"]["reason"] == "completed"
    xfer = spans("serving.migrate_transfer")
    assert len(xfer) == 1
    assert xfer[0]["args"]["victim"] == "obs-1"
    assert xfer[0]["args"]["survivor"] == "obs-0"
    assert xfer[0]["args"]["bytes"] > 0
    res = spans("serving.migrate_resume")
    assert len(res) == 1 and res[0]["args"]["path"] == "live"

    # contiguous chain: each stage starts no earlier than the previous
    assert qw[0]["ts"] <= pf[0]["ts"] <= victim_dec[0]["ts"]
    assert victim_dec[0]["ts"] <= xfer[0]["ts"] <= res[0]["ts"]
    assert res[0]["ts"] <= survivor_dec[0]["ts"] + survivor_dec[0]["dur"]

    # admit markers correlate the same rid on BOTH replicas (admitted
    # on the victim, re-imported on the survivor is a decode span, so
    # exactly one admit instant)
    admits = [
        e for e in events
        if e.get("name") == "serving.admit"
        and e.get("args", {}).get("rid") == rid
    ]
    assert len(admits) == 1 and admits[0]["args"]["replica"] == "obs-1"
    # occupancy counters flowed from the publish loop
    assert any(
        e.get("name", "").startswith("serving.occupancy.") for e in events
    )


@pytest.mark.slow
def test_slo_breach_drill_capture_and_healthcheck_naming(tmp_path):
    """Stall one of two replicas so its p99 breaches the SLO: the
    watchdog fires ONE serving AnomalyRecord for the stalled replica,
    writes a capture carrying the engine phase split + allocator
    occupancy, and the offline healthcheck replay names the breaching
    replica with exit code 1."""
    from dlrover_tpu.serving.replica import ServingReplica

    cfg, params = _tiny_setup()
    flight = tmp_path / "flight.jsonl"
    hub = configure_hub(jsonl_path=str(flight))
    wds = {
        name: ServingWatchdog(ServingWatchdogConfig(
            node_id=i, capture_dir=str(tmp_path / "caps"),
            p99_target_ms=500.0, min_completed=2,
            min_capture_interval_s=0.0,
        ))
        for i, name in enumerate(["slo-0", "slo-1"])
    }
    reps = {
        name: ServingReplica(
            name, params, cfg, node_id=i, hub=hub,
            watchdog=wds[name], publish_every=1000.0, **_SERVER_KW,
        ).start()
        for i, name in enumerate(["slo-0", "slo-1"])
    }
    try:
        # warm the jit caches so compile time doesn't skew either p99
        for rep in reps.values():
            rep.generate([2, 3, 4], 4, timeout=600.0)
            rep.server.scheduler.reset_latencies()
        # inject the stall: every engine step on slo-1 drags 150 ms
        eng1 = reps["slo-1"].server.engine
        orig_step = eng1.step

        def stalled_step():
            time.sleep(0.15)
            return orig_step()

        eng1.step = stalled_step
        futs = []
        for rep in reps.values():
            for seed in (1, 2, 3):
                futs.append(rep.submit([2, 3, 4, seed], 6).future)
        for f in futs:
            f.result(timeout=600.0)
    finally:
        for rep in reps.values():
            rep.stop()  # final publish → watchdog observes the window
        reset_hub()

    assert [a.kind for a in wds["slo-1"].anomalies] == ["slo_breach"]
    assert wds["slo-0"].anomalies == []
    a = wds["slo-1"].anomalies[0]
    assert a.replica == "slo-1" and a.value > 500.0
    with open(a.capture) as f:
        doc = json.load(f)
    # the capture freezes WHY: phase split + allocator occupancy
    assert doc["engine"]["phase_split"]["step_time_s"] >= 0.0
    assert doc["engine"]["allocator"]["n_pages"] > 0
    assert doc["engine"]["allocator"]["free_pages"] >= 0
    assert doc["record"]["replica"] == "slo-1"

    assert healthcheck.main([str(flight)]) == 1
    diag = healthcheck.diagnose(healthcheck.load_records(str(flight)))
    assert diag["anomalies"]["slo_breach"]["replicas"] == ["slo-1"]
    assert diag["serving"]["replicas"]["slo-1"]["p99_ms"] > 500.0
    assert diag["serving"]["replicas"]["slo-0"]["p99_ms"] < 500.0
