"""Copy-on-write prefix sharing (serving/prefix.py + refcounted pages).

Fast tier: radix-index units (intern/lookup/drop, keep-first
collisions, subtree drop on free), admission-plan math (chunk-aligned
resume, single COW tail, whole-prompt clamp), and the tentpole parity
bar — a request admitted after a prefix hit emits the BITWISE token
stream of the same request cold, across {bf16, int8} × {paged, gather}
× {spec on, off}; eviction of the sharer never perturbs the sharee
(pool-cell byte identity under refcounts). Hit-aware admission: a
cheap hot-prefix request is admitted past a cold head blocked on
pages (``admission_lookahead``), and the head is never starved.

Telemetry: engine stats → ServingRecord carries prefix_hit_rate /
prefill_tokens_saved / trie_pages / dedup_ratio, and recordings from
builds that predate those fields replay via dataclass defaults (the
same forward-compat pin speculative decoding shipped with).

Slow tier: the migration drill with shared pages in flight — donor and
sharer migrate off a killed replica, the survivor re-interns, and the
allocator invariants (refcount conservation, partition, no double-free)
hold on both sides at drill end.
"""

import json
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.observability import telemetry  # noqa: E402
from dlrover_tpu.serving.engine import ServingEngine  # noqa: E402
from dlrover_tpu.serving.prefix import (  # noqa: E402
    PrefixIndex, PrefixMatch, plan_admission,
)
from dlrover_tpu.serving.scheduler import Scheduler  # noqa: E402


# ------------------------------------------------------------- trie units


def test_trie_intern_lookup_partial_tail():
    trie = PrefixIndex(4)
    toks = list(range(1, 13))
    assert trie.intern(toks, 3, np.array([5, 6, 7])) == 3
    m = trie.lookup(toks)
    assert m.pages == (5, 6, 7) and m.tail_tokens == 0
    assert m.matched_tokens(4) == 12
    # diverge inside page 1: full match on page 0, 2-token tail on 6
    m2 = trie.lookup(toks[:6] + [99, 99, 98])
    assert m2.pages == (5,)
    assert m2.tail_page == 6 and m2.tail_tokens == 2
    # no shared prefix at all
    miss = trie.lookup([31, 30, 29, 28])
    assert miss.pages == () and miss.tail_page is None
    # prompt shorter than one page can still tail-match
    m3 = trie.lookup(toks[:3])
    assert m3.pages == () and m3.tail_page == 5 and m3.tail_tokens == 3


def test_trie_keep_first_on_collision():
    trie = PrefixIndex(2)
    trie.intern([1, 2, 3, 4], 2, np.array([5, 6]))
    # a second slot committing the same runs does NOT rebind the nodes
    assert trie.intern([1, 2, 3, 4], 2, np.array([9, 10])) == 0
    assert trie.lookup([1, 2, 3, 4]).pages == (5, 6)
    # ...but a divergent second page forks a new node under the shared
    # first page
    assert trie.intern([1, 2, 7, 7], 2, np.array([9, 10])) == 1
    assert trie.lookup([1, 2, 7, 7]).pages == (5, 10)
    assert trie.n_pages == 3


def test_trie_drop_removes_subtree():
    trie = PrefixIndex(2)
    trie.intern([1, 2, 3, 4, 5, 6], 3, np.array([5, 6, 7]))
    trie.intern([1, 2, 8, 8], 2, np.array([5, 9]))
    assert trie.n_pages == 4
    # dropping a leaf leaves the rest reachable
    assert trie.drop_pages([7]) == 1
    assert trie.lookup([1, 2, 3, 4, 5, 6]).pages == (5, 6)
    # dropping the shared root page takes every deeper prefix with it
    assert trie.drop_pages([5]) == 3
    assert trie.n_pages == 0
    assert trie.lookup([1, 2, 3, 4]).pages == ()
    # dropping an unindexed page is a no-op
    assert trie.drop_pages([5, 42]) == 0
    assert trie.stats()["dropped_total"] == 4


# ------------------------------------------------------------ plan math


def test_plan_full_match_shares_aligned_prefix():
    # 12 matched of a 16-token prompt, chunk 4: resume at 12, three
    # pages shared read-only, no COW (resume page-aligned)
    m = PrefixMatch((5, 6, 7), None, 0)
    plan = plan_admission(m, 16, 4, 4)
    assert plan.shared == (5, 6, 7) and plan.cow == ()
    assert plan.resume == 12 and plan.matched_tokens == 12
    assert plan.prefix_pages == (5, 6, 7)


def test_plan_partial_tail_cows_one_page():
    # 6 matched of an 8-token prompt, chunk 2: resume at 6, page 0
    # shared, page 1 (half-committed) COW'd
    m = PrefixMatch((5,), 6, 2)
    plan = plan_admission(m, 8, 4, 2)
    assert plan.shared == (5,)
    assert plan.cow == ((1, 6),)
    assert plan.resume == 6
    assert plan.prefix_pages == (5, 6)


def test_plan_whole_prompt_match_clamps_resume():
    # the ENTIRE prompt is committed: resume must land strictly inside
    # the prompt (the last token re-runs for the first-token logits).
    # chunk 4: resume 8→7→4, page-aligned, so page 1 is discarded —
    # recomputing it whole beats copying it
    m = PrefixMatch((5, 6), None, 0)
    plan = plan_admission(m, 8, 4, 4)
    assert plan.resume == 4
    assert plan.shared == (5,) and plan.cow == ()
    # chunk 2: resume 8→7→6 lands INSIDE page 1, turning the fully
    # matched page into the COW page
    plan2 = plan_admission(m, 8, 4, 2)
    assert plan2.resume == 6
    assert plan2.shared == (5,) and plan2.cow == ((1, 6),)
    # chunk wider than the whole usable prefix: resume 0 → no plan
    assert plan_admission(m, 8, 4, 8) is None


def test_plan_miss_and_tiny_matches_return_none():
    assert plan_admission(PrefixMatch((), None, 0), 8, 4, 4) is None
    # a 2-token tail match floors to resume 0 under chunk 4
    assert plan_admission(PrefixMatch((), 6, 2), 8, 4, 4) is None


# ------------------------------------------------------- engine parity


@pytest.fixture(scope="module")
def setup():
    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    prefix = list(rng.integers(1, 32, size=12))
    donor_p = prefix + [3, 4]
    foll_p = prefix + [9, 8, 7]
    refs = {
        "donor": [int(t) for t in np.asarray(generate.greedy(
            params, cfg, jnp.asarray([donor_p], jnp.int32), 16)[0])],
        "foll": [int(t) for t in np.asarray(generate.greedy(
            params, cfg, jnp.asarray([foll_p], jnp.int32), 5)[0])],
    }
    return cfg, params, donor_p, foll_p, refs


def _engine(cfg, params, *, sharing=True, lookahead=0, replica="px",
            **kw):
    sched = Scheduler(replica=replica)
    base = dict(
        n_slots=2, max_len=32, page_size=4, mode="bf16",
        prefill_chunk=4, prefix_sharing=sharing,
        admission_lookahead=lookahead,
    )
    base.update(kw)
    return sched, ServingEngine(params, cfg, sched, **base)


def _commit_donor(eng, sched, donor_p, max_new=16):
    """Admit the donor alone and step until its prompt is fully
    committed (decode phase) so its pages are interned and shareable."""
    rd = sched.submit(donor_p, max_new)
    for _ in range(40):
        eng.step()
        s = next((s for s in eng.slots if s is not None), None)
        if s is not None and s.phase == "decode":
            return rd
    raise AssertionError("donor never reached decode")


def _check_alloc(alloc, geom):
    """Refcount conservation + partition (mirrors the kv_cache property
    checker) — the no-leak/no-double-free bar at drill end."""
    cells = Counter(
        int(p) for row in alloc._tables for p in row if p >= 0
    )
    for page in range(geom.n_pages):
        assert alloc.refcount(page) == cells.get(page, 0), page
    reserved = [int(p) for ps in alloc._reserved.values() for p in ps]
    free = set(alloc._free)
    assert len(alloc._free) == len(free)
    assert set(cells) | set(reserved) | free == set(
        range(1, geom.n_pages)
    )
    assert not free & set(cells) and not free & set(reserved)


def _parity_case(setup, mode, paged, spec_k):
    """The tentpole parity bar: the follower admitted after a prefix hit
    (12 of its 15 prompt tokens mapped from the donor's pages) emits
    the exact cold stream — and spends ONE prefill chunk where cold
    spends four."""
    cfg, params, donor_p, foll_p, refs = setup
    sched, eng = _engine(cfg, params, mode=mode, paged=paged,
                         spec_k=spec_k, replica=f"px-{mode}")
    rd = _commit_donor(eng, sched, donor_p)
    chunks_before = eng.stats()["prefill_chunks"]
    rf = sched.submit(foll_p, 5)
    eng.drain(timeout=600)
    out_d, out_f = rd.future.result(5), rf.future.result(5)
    st = eng.stats()
    if mode == "bf16":
        assert out_d == refs["donor"] and out_f == refs["foll"]
    else:
        # int8 is lossy vs the bf16 offline reference; its hit-vs-cold
        # parity is pinned in test_int8_hit_equals_int8_cold_stream
        assert len(out_f) == len(refs["foll"])
    assert st["prefix_hits"] == 1 and st["prefill_tokens_saved"] == 12
    assert st["prefill_chunks"] - chunks_before == 1  # cold pays 4
    assert st["prefix_hit_rate"] == 0.5  # the donor was the one miss
    # drained: every page freed, every trie entry dropped with it
    assert eng.alloc.free_pages == eng.geom.n_pages - 1
    assert st["trie_pages"] == 0
    _check_alloc(eng.alloc, eng.geom)
    return sched, eng


def test_prefix_hit_fast_pin(setup):
    """Tier-1 pin of the core hit path (bf16/paged/spec-off — one jit
    compile) plus the telemetry flow; the full {mode} × {kernel} ×
    {spec} matrix and the byte-identity/COW/lookahead drills run on the
    slow tier (one engine compile each — see _SLOW_LEDGER)."""
    sched, eng = _parity_case(setup, "bf16", True, 0)
    rec = sched.publish(eng.stats())
    assert rec.prefix_hit_rate == 0.5
    assert rec.prefill_tokens_saved == 12
    assert rec.trie_pages == 0 and rec.dedup_ratio == 1.0
    assert telemetry.from_json(rec.to_json()).prefill_tokens_saved == 12
    snap = eng.observability_snapshot()
    assert snap["prefix"]["sharing"] is True
    assert snap["prefix"]["hit_rate"] == 0.5
    assert snap["prefix"]["prefill_tokens_saved"] == 12
    assert "interned_total" in snap["prefix"]["trie"]


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["bf16", "int8"])
@pytest.mark.parametrize("paged", [True, False])
@pytest.mark.parametrize("spec_k", [0, 3])
def test_prefix_hit_stream_bitwise_equals_cold(setup, mode, paged, spec_k):
    _parity_case(setup, mode, paged, spec_k)


@pytest.mark.slow
@pytest.mark.parametrize("paged", [True, False])
def test_int8_hit_equals_int8_cold_stream(setup, paged):
    """int8 parity is pinned hit-vs-cold (both through the quantized
    engine): the shared pages hold the SAME int8 payloads + scales a
    cold prefill would commit, so the streams are bitwise equal."""
    cfg, params, donor_p, foll_p, _ = setup
    outs = {}
    for sharing in (True, False):
        sched, eng = _engine(cfg, params, sharing=sharing, mode="int8",
                             paged=paged, replica=f"i8-{sharing}")
        rd = _commit_donor(eng, sched, donor_p)
        rf = sched.submit(foll_p, 5)
        eng.drain(timeout=600)
        outs[sharing] = (rd.future.result(5), rf.future.result(5))
        assert eng.stats()["prefix_hits"] == (1 if sharing else 0)
    assert outs[True] == outs[False]


@pytest.mark.slow
def test_sharer_eviction_never_perturbs_sharee(setup):
    """Donor completes and evicts while the sharee is mid-decode: the
    shared pool cells must stay byte-identical (rc holds them live) and
    the sharee's stream stays the cold stream."""
    cfg, params, donor_p, foll_p, refs = setup
    sched, eng = _engine(cfg, params)
    rd = _commit_donor(eng, sched, donor_p, max_new=4)
    # donor is freshly decoding on a 4-token budget; admit the sharee
    rf = sched.submit(foll_p, 5)
    while not any(
        s is not None and s.req is rf for s in eng.slots
    ):
        eng.step()
    slot_f = next(
        i for i, s in enumerate(eng.slots) if s is not None and s.req is rf
    )
    shared_phys = [
        int(p) for p in eng.alloc.block_tables()[slot_f, :3]
    ]
    assert all(eng.alloc.refcount(p) == 2 for p in shared_phys)
    before = {
        k: np.asarray(v[:, shared_phys]) for k, v in eng.pools.items()
    }
    # run the donor to completion + eviction; the sharee keeps decoding
    while any(
        s is not None and s.req is rd for s in eng.slots
    ) or not rd.future.done():
        eng.step()
    assert rd.future.result(5) == refs["donor"][:len(donor_p) + 4]
    # donor gone, sharee still maps the pages — now rc 1, bytes intact
    assert all(eng.alloc.refcount(p) == 1 for p in shared_phys)
    after = {
        k: np.asarray(v[:, shared_phys]) for k, v in eng.pools.items()
    }
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])
    eng.drain(timeout=600)
    assert rf.future.result(5) == refs["foll"]
    assert eng.alloc.free_pages == eng.geom.n_pages - 1


@pytest.mark.slow
def test_cow_tail_page_isolates_writes(setup):
    """A follower whose prompt EQUALS the donor's (whole-prompt match,
    chunk 2 → resume 14 strides into page 3) COWs that page before
    re-running its last chunk — the donor's copy must not move a byte."""
    cfg, params, donor_p, _, _ = setup
    prompt = donor_p + [1, 2]  # 16 tokens = 4 full committed pages
    refs = {
        m: [int(t) for t in np.asarray(generate.greedy(
            params, cfg, jnp.asarray([prompt], jnp.int32), m)[0])]
        for m in (12, 5)
    }
    sched, eng = _engine(cfg, params, prefill_chunk=2)
    rd = _commit_donor(eng, sched, prompt, max_new=12)
    donor_slot = next(
        i for i, s in enumerate(eng.slots) if s is not None
    )
    donor_phys = [
        int(p) for p in eng.alloc.block_tables()[donor_slot, :4]
    ]
    donor_bytes = {
        k: np.asarray(v[:, donor_phys]) for k, v in eng.pools.items()
    }
    rf = sched.submit(prompt, 5)
    eng.drain(timeout=600)
    st = eng.stats()
    assert rf.future.result(5) == refs[5]
    assert rd.future.result(5) == refs[12]
    assert st["cow_pages"] == 1 and st["prefix_hits"] == 1
    assert st["prefill_tokens_saved"] == 14  # resume 14 of 16
    # the donor's page bytes never moved (writes went to the COW copy);
    # eviction doesn't scrub pools, so post-drain bytes still tell
    for k, v in eng.pools.items():
        np.testing.assert_array_equal(
            donor_bytes[k], np.asarray(v[:, donor_phys])
        )


@pytest.mark.slow
def test_hit_aware_lookahead_admits_past_blocked_cold_head(setup):
    """A cold request blocked on pages must not idle the slot when a
    hot-prefix request behind it fits via its shared-page discount —
    and the cold head still runs (keeps its ticket) once pages free."""
    cfg, params, donor_p, foll_p, refs = setup
    sched, eng = _engine(cfg, params, lookahead=2)
    rd = _commit_donor(eng, sched, donor_p)  # holds 8 of 16 pages
    # squeeze the free list to 3 pages so a cold 20-token request (5
    # pages) blocks while the hot one (5 pages, 3 shared) fits
    assert eng.alloc.reserve_for_migration("squeeze", 20)
    cold = sched.submit(list(np.arange(1, 18) % 31 + 1), 3)
    hot = sched.submit(foll_p, 5)
    for _ in range(12):
        eng.step()
    # the hot request jumped the blocked head and finished; cold waits
    assert hot.future.done() and hot.future.result(5) == refs["foll"]
    assert not cold.future.done()
    assert sched.queue_depth() == 1
    assert not rd.future.done()  # donor still decoding throughout
    # pages return → the head is admitted (never starved)
    eng.alloc.abort_migration("squeeze")
    eng.drain(timeout=600)
    assert len(cold.future.result(5)) == 20
    st = eng.stats()
    assert st["prefix_hits"] == 1
    assert eng.alloc.free_pages == eng.geom.n_pages - 1


@pytest.mark.slow
def test_lookahead_zero_preserves_head_of_line(setup):
    """Default admission (lookahead 0) stays strict head-of-line even
    with sharing on: the hot request waits behind the blocked head."""
    cfg, params, donor_p, foll_p, refs = setup
    sched, eng = _engine(cfg, params, lookahead=0)
    _commit_donor(eng, sched, donor_p)
    assert eng.alloc.reserve_for_migration("squeeze", 20)
    cold = sched.submit(list(np.arange(1, 18) % 31 + 1), 3)
    hot = sched.submit(foll_p, 5)
    for _ in range(8):
        eng.step()
    assert not hot.future.done() and not cold.future.done()
    assert sched.queue_depth() == 2
    eng.alloc.abort_migration("squeeze")
    eng.drain(timeout=600)
    assert hot.future.result(5) == refs["foll"]


# ------------------------------------------------------------ telemetry


def test_pre_sharing_recordings_replay_via_defaults():
    """A ServingRecord serialized BEFORE prefix sharing existed (no
    prefix fields in its JSON) must rehydrate with the dataclass
    defaults — the same forward-compat pin speculative decoding set."""
    rec = telemetry.ServingRecord(replica="old", completed=3)
    obj = json.loads(rec.to_json())
    for f in ("prefix_hit_rate", "prefill_tokens_saved", "trie_pages",
              "dedup_ratio"):
        del obj["d"][f]
    back = telemetry.from_json(json.dumps(obj))
    assert back.completed == 3
    assert back.prefix_hit_rate == 0.0
    assert back.prefill_tokens_saved == 0
    assert back.trie_pages == 0
    assert back.dedup_ratio == 1.0


@pytest.mark.slow
def test_sharing_off_engine_reports_inert_prefix_stats(setup):
    cfg, params, donor_p, foll_p, _ = setup
    sched, eng = _engine(cfg, params, sharing=False)
    _commit_donor(eng, sched, donor_p)
    rf = sched.submit(foll_p, 5)
    eng.drain(timeout=600)
    rf.future.result(5)
    st = eng.stats()
    assert st["prefix_hits"] == 0 and st["prefix_misses"] == 0
    assert st["prefix_hit_rate"] == 0.0 and st["trie_pages"] == 0
    assert eng.observability_snapshot()["prefix"]["sharing"] is False


# ------------------------------------------------------ migration drill


@pytest.mark.slow
def test_migration_drill_with_shared_pages_in_flight(setup):
    """Kill a replica whose two slots SHARE prefix pages mid-decode;
    the survivor (sharing on) adopts both via live migration, outputs
    stay bitwise, and the allocator invariants hold on both replicas —
    no refcount leak, no double-free."""
    from dlrover_tpu.serving import migration as mig
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, donor_p, foll_p, refs = setup
    kw = dict(
        n_slots=4, max_len=32, page_size=4, mode="bf16",
        prefill_chunk=4, prefix_sharing=True, idle_sleep=0.001,
    )
    r0 = ServingReplica("px-0", params, cfg, node_id=0, **kw)
    r1 = ServingReplica("px-1", params, cfg, node_id=1, **kw)
    r0.start()
    r1.start()
    try:
        router = ReplicaRouter([r0, r1], migrator=mig.ServingMigrator())
        with r1.server.paused() as eng1:
            # round-robin lands the pads on r0, donor + sharer on the
            # parked victim r1
            pad = router.submit(donor_p, 1)
            rd = router.submit(donor_p, 16)
            pad2 = router.submit(donor_p, 1)
            assert [e.replica.name for e in router._entries] == [
                "px-0", "px-1", "px-0",
            ]
            # hand-step the victim: the donor commits its prompt pages,
            # then the sharer is submitted and hits
            for _ in range(40):
                eng1.step()
                s = next(
                    (s for s in eng1.slots if s is not None), None
                )
                if s is not None and s.phase == "decode":
                    break
            rf = router.submit(foll_p, 5)
            for _ in range(40):
                eng1.step()
                live = [s for s in eng1.slots if s is not None]
                if len(live) == 2 and all(
                    s.phase == "decode" and len(s.generated) >= 1
                    and not s.req.future.done()
                    for s in live
                ):
                    break
            st1 = eng1.stats()
            assert st1["prefix_hits"] == 1, "sharer never hit"
            assert st1["dedup_ratio"] > 1.0, "no pages shared in flight"
            r1.kill()
        assert not r1.alive and r0.alive
        pad.future.result(timeout=300)
        pad2.future.result(timeout=300)
        moved = router.poll()
        outs = [
            rd.future.result(timeout=600),
            rf.future.result(timeout=600),
        ]
        assert moved == 2
        assert outs[0] == refs["donor"] and outs[1] == refs["foll"]
        eng0 = r0.server.engine
        assert eng0.stats()["migrated_in"] == 2
        with r0.server.paused():
            assert eng0.stats()["trie_pages"] == 0  # all drained
            _check_alloc(eng0.alloc, eng0.geom)
            assert eng0.alloc.free_pages == eng0.geom.n_pages - 1
        # the victim's allocator balances too: the migrator's
        # release_slot of two sharers double-frees nothing
        _check_alloc(eng1.alloc, eng1.geom)
        assert eng1.alloc.free_pages == eng1.geom.n_pages - 1
    finally:
        r0.stop()
        r1.kill()
