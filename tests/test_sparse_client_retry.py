"""KvClient transport retry (sparse/server.py satellite).

The client's ``_call`` must survive a dropped connection by
reconnecting under the job-wide full-jitter backoff policy
(``common.comm._backoff_delay`` — the master client's curve), and must
NOT retry server-reported (``!``) errors: the server answered, the
request is wrong.
"""

import numpy as np
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.sparse.embedding import EmbeddingSpec
from dlrover_tpu.sparse.server import KvClient, KvServer


@pytest.fixture()
def server():
    srv = KvServer(
        [EmbeddingSpec("emb", 4, initializer="normal",
                       init_scale=0.01, seed=0)]
    )
    yield srv
    srv.stop()


def test_call_reconnects_after_dropped_connection(server, monkeypatch):
    delays = []
    monkeypatch.setattr(
        comm, "_backoff_delay", lambda a: delays.append(a) or 0.0
    )
    client = KvClient(server.address, timeout=10.0)
    keys = np.arange(3, dtype=np.int64)
    rows = client.pull("emb", keys, train=True)
    assert rows.shape == (3, 4)
    # sever the live connection underneath the client (server restart /
    # repartition); the next call must transparently reconnect
    client._sock.close()
    again = client.pull("emb", keys, train=False)
    np.testing.assert_allclose(again, rows)
    assert delays == [0], "exactly one retry, on the shared backoff curve"
    client.close()


def test_retries_exhaust_when_server_is_gone(monkeypatch):
    monkeypatch.setattr(comm, "_backoff_delay", lambda a: 0.0)
    srv = KvServer(
        [EmbeddingSpec("emb", 4, initializer="zeros")]
    )
    client = KvClient(srv.address, timeout=2.0, retries=2)
    srv.stop()
    with pytest.raises((ConnectionError, OSError, EOFError)):
        client.pull("emb", np.arange(2, dtype=np.int64), train=True)
    client.close()


def test_server_reported_errors_are_not_retried(server, monkeypatch):
    attempts = []
    monkeypatch.setattr(
        comm, "_backoff_delay",
        lambda a: attempts.append(a) or 0.0,
    )
    client = KvClient(server.address, timeout=10.0)
    with pytest.raises(RuntimeError, match="kv server error"):
        client.keys("no_such_table")
    assert attempts == [], "a '!' frame is an answer, not a failure"
    client.close()