"""Update sharding across the mesh zoo: dp×fsdp / dp×tp, zero1/zero2.

The contract under test (train/train_step.py resolve_update_sharding on
hybrid meshes + parallel/sharding.py partial-manual exchange):

- On a dp×fsdp or dp×tp mesh the gradient exchange is manual over dp
  ONLY — fsdp/tp stay with the auto partitioner. The flat optimizer
  state is sharded over dp and replicated over the model axes, so the
  bucket collectives must be reduce-scatter/all-gather with replica
  groups of size dp, never spanning the model axis, and no
  full-gradient all-reduce may survive.
- ``zero2`` reduce-scatters every microbatch and accumulates the 1/dp
  shard — the per-microbatch scatter count in the HLO is the
  structural witness that no full-gradient accumulator crosses the
  grad-accum loop. ``zero1`` defers to one scatter per step.
- SGD one-step parity is the scaling guard: SGD is linear in the
  gradients, so a uniform wrong factor (the class of bug Adam's
  normalizer hides) shows up at full size.

Tolerances are pinned from measured runs on this backend: hybrid-mesh
rollouts are NOT bitwise (the auto partitioner fuses the model-axis
collectives differently than the replicated program — 1-ulp origins
that compound through Adam's low-bit amplification), but losses track
to ~1e-5 and one SGD step to ~1e-6.

Everything here builds multi-axis meshes over the 8 virtual devices
and compiles multiple SPMD programs — the whole module is slow-marked
(see test_marker_lint's mesh-zoo rule).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bench import collective_stats
from dlrover_tpu.common import jax_compat
from dlrover_tpu.models.config import get_config
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.train.optimizer import (
    make_optimizer,
    opt_state_bytes_per_replica,
)
from dlrover_tpu.train.train_step import TrainStepBuilder, init_train_state

P = jax.sharding.PartitionSpec

pytestmark = pytest.mark.slow


def tiny_cfg(**kw):
    kw.setdefault("dtype", "float32")
    return get_config(
        "tiny",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=128,
        max_seq=32,
        **kw,
    )


def zoo_mesh(axis, size=2):
    return build_mesh(MeshConfig(dp=-1, **{axis: size}))


def batches(n, batch=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        base = rng.randint(0, vocab, size=(batch, 33))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }


def build_pair(cfg, mesh, opt_fn, mode, accum=1, **comm_kw):
    comm_kw.setdefault("bucket_mb", 0.05)
    comm = shd.CommConfig(update_sharding=mode, **comm_kw)
    bu = TrainStepBuilder(cfg, mesh, opt_fn(), grad_accum=accum)
    bs = TrainStepBuilder(cfg, mesh, opt_fn(), grad_accum=accum, comm=comm)
    assert bs.update_sharding, bs.update_sharding_reason
    su = init_train_state(jax.random.key(0), cfg, mesh, bu.optimizer)
    ss = init_train_state(
        jax.random.key(0), cfg, mesh, bs.optimizer, comm=bs.comm_resolved
    )
    return bu, bs, su, ss


# ---------------------------------------------------------------------------
# Numerics: SGD one-step scaling guard + adamw loss tracking
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "axis,mode,accum",
    [
        ("tp", "zero2", 2),
        ("tp", "zero1", 2),
        ("fsdp", "zero2", 2),
        ("fsdp", "zero1", 1),
    ],
)
def test_sgd_one_step_parity(axis, mode, accum):
    """One SGD step matches the replicated update to float rounding.

    SGD is linear in the gradient: a wrong uniform factor on the
    exchanged gradients (the bug class Adam's 1/sqrt(nu) normalizer
    conceals) would shift every parameter proportionally. Measured
    worst abs diff ~1.2e-7 on this backend."""
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = zoo_mesh(axis)
    bu, bs, su, ss = build_pair(
        cfg, mesh, lambda: optax.sgd(1e-2), mode, accum=accum
    )
    batch = next(batches(1, batch=16 * accum))
    su, mu = jax.jit(bu.step_fn)(su, batch)
    ss, ms = jax.jit(bs.step_fn)(ss, batch)
    worst = max(
        float(jnp.max(jnp.abs(x - y)))
        for x, y in zip(
            jax.tree.leaves(su["params"]), jax.tree.leaves(ss["params"])
        )
    )
    assert worst < 1e-5, worst
    assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-6


@pytest.mark.parametrize("axis", ["tp", "fsdp"])
def test_adamw_rollout_losses_track(axis):
    """3-step adamw rollout: per-step losses agree with the replicated
    update. Params drift by low-bit amplification (Adam divides 1-ulp
    nu differences into the update), so the pin is on the losses."""
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = zoo_mesh(axis)
    bu, bs, su, ss = build_pair(
        cfg, mesh, lambda: optax.adamw(1e-3), "zero2"
    )
    fu, fs = jax.jit(bu.step_fn), jax.jit(bs.step_fn)
    for b in batches(3):
        su, mu = fu(su, b)
        ss, ms = fs(ss, b)
        assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-5


@pytest.mark.parametrize(
    "state_dtype,tol",
    [("bfloat16", 5e-2), ("factored", 5e-2)],
)
def test_low_precision_state_shards(state_dtype, tol):
    """bf16 and row/col-factored optimizer state thread the flat view
    on a hybrid mesh: the builder must activate (not fall back) and the
    rollout must track the same-optimizer replicated run."""
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = zoo_mesh("tp")
    opt_fn = lambda: make_optimizer(  # noqa: E731
        learning_rate=1e-3, warmup_steps=2, decay_steps=10,
        grad_clip=0.0, fused=True, state_dtype=state_dtype,
    )
    bu, bs, su, ss = build_pair(cfg, mesh, opt_fn, "zero1")
    fu, fs = jax.jit(bu.step_fn), jax.jit(bs.step_fn)
    for b in batches(3):
        su, mu = fu(su, b)
        ss, ms = fs(ss, b)
    assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-3
    worst = 0.0
    for x, y in zip(
        jax.tree.leaves(su["params"]), jax.tree.leaves(ss["params"])
    ):
        x, y = np.asarray(x), np.asarray(y)
        worst = max(
            worst,
            float(np.sqrt(np.mean((x - y) ** 2) / (np.mean(x**2) + 1e-30))),
        )
    assert worst < tol, worst


# ---------------------------------------------------------------------------
# HLO guards: dp-only collectives, no full-grad all-reduce, zero2 scatters
# ---------------------------------------------------------------------------


_COLL_RE = re.compile(
    r"(f32|bf16|s8|u8)\[([0-9,]*)\][^=]*"
    r"(reduce-scatter|all-gather|all-reduce|all-to-all|collective-permute)"
    r"\(.*?replica_groups=\{?\{([0-9,]+)\}"
)


def hlo_collectives(text):
    """(op, out_elems, group_size) for each collective in the HLO."""
    out = []
    for line in text.splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        dims = [int(d) for d in m.group(2).split(",") if d]
        elems = int(np.prod(dims)) if dims else 1
        group = len(m.group(4).split(","))
        out.append((m.group(3), elems, group))
    return out


@pytest.fixture(scope="module")
def compiled_dpxfsdp():
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = zoo_mesh("fsdp")
    bu, bs, su, ss = build_pair(
        cfg, mesh, lambda: optax.adamw(1e-3), "zero1"
    )
    batch = next(batches(1))
    compiled = jax.jit(bs.step_fn).lower(ss, batch).compile()
    return mesh, bs, ss, compiled


def test_dpxfsdp_exchange_is_dp_only(compiled_dpxfsdp):
    """The bucket exchange lowers to reduce-scatter/all-gather with
    replica groups of exactly dp ranks — never the model axis, never
    the whole mesh — and no all-to-all sneaks in."""
    mesh, bs, _, compiled = compiled_dpxfsdp
    dp = mesh.shape["dp"]
    plan = bs._plan
    colls = hlo_collectives(compiled.as_text())
    assert colls, "no collectives parsed from HLO"
    # all-to-alls with fsdp-sized groups are the auto partitioner
    # resharding activations — fine. Over dp-sized groups they would
    # mean a quantized wire leaked into the hybrid region.
    assert not [c for c in colls if c[0] == "all-to-all" and c[2] == dp]
    shard_elems = plan.bucket_elems // dp
    rs_buckets = [
        c for c in colls if c[0] == "reduce-scatter"
        and c[1] % shard_elems == 0
    ]
    assert len(rs_buckets) >= plan.n_buckets, colls
    for op, elems, group in rs_buckets:
        assert group == dp, (op, elems, group)
    # the updated flat params come home through dp-group all-gathers of
    # bucket-stream shapes (fsdp-group gathers are the model's own
    # param gathers, not the exchange)
    ag_buckets = [
        c for c in colls if c[0] == "all-gather" and c[2] == dp
    ]
    assert ag_buckets, colls
    assert all(e % shard_elems == 0 for _, e, _ in ag_buckets), ag_buckets
    # and the ONLY dp-group traffic is the flat bucket stream: every
    # dp-group collective is stream-shaped, so no per-leaf gradient or
    # param payload crosses dp outside the exchange
    for op, elems, group in colls:
        if group == dp and op in ("reduce-scatter", "all-gather"):
            assert elems % shard_elems == 0, (op, elems, group)


def test_dpxfsdp_no_cross_axis_optimizer_collectives(compiled_dpxfsdp):
    """Optimizer state is elementwise on the flat dp shard: nothing
    moment-sized may cross the mesh at all, and no gradient-sized
    all-reduce may survive (scalars — loss, denom, grad-norm — are
    fine)."""
    _, bs, ss, compiled = compiled_dpxfsdp
    n_params = bs._plan.total
    moment_elems = {
        int(np.prod(np.shape(l)))
        for l in jax.tree.leaves(ss["opt_state"])
        if np.ndim(l) > 0 and int(np.prod(np.shape(l))) > 1
    }
    for op, elems, group in hlo_collectives(compiled.as_text()):
        if op == "all-reduce":
            assert elems < n_params // 2, (op, elems, group)
        assert elems not in moment_elems or op in (
            "reduce-scatter",
            "all-gather",
        ), ("optimizer-state-sized collective", op, elems, group)


def test_dpxfsdp_opt_state_bytes(compiled_dpxfsdp):
    mesh, bs, ss, _ = compiled_dpxfsdp
    cfg = tiny_cfg(tie_embeddings=False)
    dp = mesh.shape["dp"]
    full_state = init_train_state(
        jax.random.key(0), cfg, mesh, optax.adamw(1e-3)
    )
    full = opt_state_bytes_per_replica(full_state["opt_state"])
    rep = opt_state_bytes_per_replica(ss["opt_state"])
    assert rep <= full / dp + 3 * bs.comm_resolved.bucket_bytes, (rep, full)


def test_zero2_scatters_every_microbatch():
    """zero2's accumulator is the 1/dp shard: each microbatch pays its
    own bucket reduce-scatters (accum × n_buckets in the HLO), where
    zero1 defers to one exchange per step. The scatter-before-
    accumulate structure is what removes the full-gradient buffer from
    the accum loop."""
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = zoo_mesh("tp")
    accum = 2

    def rs_count(mode):
        comm = shd.CommConfig(update_sharding=mode, bucket_mb=0.05)
        b = TrainStepBuilder(
            cfg, mesh, optax.adamw(1e-3), grad_accum=accum, comm=comm
        )
        assert b.update_sharding, b.update_sharding_reason
        state = init_train_state(
            jax.random.key(0), cfg, mesh, b.optimizer, comm=b.comm_resolved
        )
        batch = next(batches(1, batch=32))
        compiled = jax.jit(b.step_fn).lower(state, batch).compile()
        stats = collective_stats(compiled.as_text())
        return b._plan, stats["counts"].get("reduce-scatter", 0)

    plan1, n1 = rs_count("zero1")
    plan2, n2 = rs_count("zero2")
    assert n2 >= accum * plan2.n_buckets, (n2, plan2.n_buckets)
    assert n1 < n2
    assert n1 >= plan1.n_buckets


# ---------------------------------------------------------------------------
# PackPlan property: pack → exchange → unpack over model-sharded leaves
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_packplan_roundtrip_sharded_leaves(seed):
    """pack_flat → exchange_buckets → unpack_flat over fsdp-sharded
    leaf views reconstructs the dp-sum, for random shapes.

    Each dp rank holds a different local partial (leading ``[dp]``
    axis, sharded over dp); leaves also carry fsdp shardings so the
    pack runs over auto-axis-sharded views inside the partial-manual
    region — the exact provenance where a concatenate-based pack
    miscompiles on jax 0.4.x (values scaled by an unrelated mesh-axis
    size). The reference sum is computed in numpy from the replicated
    host values, never through the pack itself."""
    mesh = zoo_mesh("fsdp")
    dp, fsdp = mesh.shape["dp"], mesh.shape["fsdp"]
    rng = np.random.RandomState(seed)
    n_leaves = rng.randint(2, 6)
    tree = {}
    specs = {}
    for i in range(n_leaves):
        if rng.rand() < 0.5:
            shape = (int(rng.randint(1, 5)) * fsdp, int(rng.randint(1, 40)))
            spec = P(None, "fsdp") if rng.rand() < 0.5 else P("fsdp", None)
            if spec == P(None, "fsdp"):
                shape = (shape[0], int(rng.randint(1, 5)) * fsdp)
        else:
            shape = (int(rng.randint(1, 120)),)
            spec = P(None)
        tree[f"leaf{i}"] = np.asarray(
            rng.randn(dp, *shape), np.float32
        )
        specs[f"leaf{i}"] = P(*(("dp",) + tuple(spec)))

    abs_tree = {
        k: jax.ShapeDtypeStruct(v.shape[1:], jnp.float32)
        for k, v in tree.items()
    }
    plan = shd.build_pack_plan(abs_tree, dp, bucket_bytes=512, mesh_axes=("dp", "fsdp"))
    sharded = {
        k: jax.device_put(
            v, jax.sharding.NamedSharding(mesh, specs[k])
        )
        for k, v in tree.items()
    }

    def region(t):
        local = {k: v[0] for k, v in t.items()}  # this rank's partial
        flat = shd.pack_flat(local, plan)
        return shd.exchange_buckets(flat, plan, "float32")

    # in_specs may only name the manual axes ({"dp"}); the fsdp
    # shardings ride along on the values through the auto partitioner
    f = jax.jit(
        jax_compat.shard_map(
            region,
            mesh=mesh,
            in_specs=({k: P("dp") for k in tree},),
            out_specs=P(None, "dp"),
            axis_names={"dp"},
        )
    )
    flat_sum = f(sharded)
    assert flat_sum.shape == (plan.n_buckets, plan.bucket_elems)
    got = shd.unpack_flat(flat_sum, abs_tree, plan)
    for k in tree:
        want = tree[k].sum(axis=0)
        np.testing.assert_allclose(
            np.asarray(got[k]), want, rtol=1e-5, atol=1e-5,
            err_msg=f"{k} seed={seed}",
        )
