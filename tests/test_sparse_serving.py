"""Multi-host sparse serving e2e (VERDICT r2 #7).

Two real KvServer PROCESSES serve the embedding tier over TCP while a
DeepFM trains against them through DistributedEmbedding; mid-run the
server set changes (scale-out, then scale-in) and the HRW rebalance
migrates only the owner-changed keys — values, optimizer slots and
admission state included — without interrupting convergence.

Reference capability: dlrover's elastic TF PS jobs keep training while
PS instances migrate (trainer/tensorflow/failover/tensorflow_failover.py:33);
here the PS role is the sparse tier's KvServer ring.
"""

import multiprocessing as mp
import os
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.sparse import GroupAdam
from dlrover_tpu.sparse.embedding import EmbeddingSpec
from dlrover_tpu.sparse.server import (
    DistributedEmbedding,
    KvClient,
    KvServer,
)


def _specs(emb_dim=8):
    return [
        EmbeddingSpec("emb", emb_dim, initializer="normal",
                      init_scale=0.01, seed=3),
        EmbeddingSpec("wide", 1, initializer="zeros"),
    ]


def _server_main(port_q, emb_dim, lr):
    server = KvServer(_specs(emb_dim), optimizer=GroupAdam(lr=lr))
    port_q.put(server.address[1])
    threading.Event().wait()  # park; the parent terminates us


def _spawn_server(ctx, emb_dim=8, lr=5e-3):
    q = ctx.Queue()
    p = ctx.Process(target=_server_main, args=(q, emb_dim, lr), daemon=True)
    p.start()
    port = q.get(timeout=60)
    return p, ("127.0.0.1", port)


@pytest.fixture()
def two_servers():
    ctx = mp.get_context("spawn")
    procs, addrs = [], {}
    for name in ("s0", "s1"):
        p, addr = _spawn_server(ctx)
        procs.append(p)
        addrs[name] = addr
    yield ctx, procs, addrs
    for p in procs:
        if p.is_alive():
            p.terminate()
        p.join(timeout=10)


def _synthetic_ctr(rng, n, cfg):
    cat = rng.integers(0, 50, size=(n, cfg.n_fields))
    dense = rng.normal(size=(n, cfg.n_dense)).astype(np.float32)
    hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
    p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
    labels = (rng.random(n) < p).astype(np.float32)
    return cat.astype(np.int64), dense, labels


def test_lookup_update_over_wire(two_servers):
    """Basic wire ops: pull inserts rows on the OWNING server; push
    updates move the values; routing is disjoint and complete."""
    _, _, addrs = two_servers
    demb = DistributedEmbedding(_specs(), addrs)
    ids = np.arange(100, dtype=np.int64).reshape(10, 10)
    dev, host = demb.pull({"emb": ids})
    rows0 = np.asarray(dev["emb"][0])
    assert rows0.shape == (100, 8)
    # rows landed on both servers, partitioned disjointly
    stats = demb.stats()
    counts = [s["emb"] for s in stats.values()]
    assert sum(counts) == 100 and all(c > 0 for c in counts)
    # a push changes what the next pull returns
    demb.push(host, {"emb": np.ones((100, 8), np.float32)})
    dev2, _ = demb.pull({"emb": ids})
    assert not np.allclose(rows0, np.asarray(dev2["emb"][0]))
    demb.close()


@pytest.mark.slow
def test_deepfm_trains_and_survives_rebalance(two_servers):
    """The headline drive: train -> scale OUT (migrate) -> train ->
    scale IN (migrate back) -> train; convergence must continue and
    migration stay bounded to the HRW-moved share."""
    ctx, procs, addrs = two_servers
    cfg = DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))
    rng = np.random.default_rng(0)
    cat, dense, labels = _synthetic_ctr(rng, 512, cfg)

    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    model.coll.close()
    demb = DistributedEmbedding(_specs(cfg.emb_dim), addrs)
    model.coll = demb

    first = model.train_step(cat, dense, labels)
    for _ in range(20):
        mid = model.train_step(cat, dense, labels)
    assert mid < first * 0.9, (first, mid)

    total_before = sum(s["emb"] for s in demb.stats().values())

    # ---- scale OUT: add s2; only ~1/3 of keys may move --------------
    p2, addr2 = _spawn_server(ctx)
    procs.append(p2)
    moved = demb.set_servers(dict(addrs, s2=addr2))
    stats = demb.stats()
    assert "s2" in stats and stats["s2"]["emb"] > 0
    assert sum(s["emb"] for s in stats.values()) == total_before
    # bounded migration: HRW moves ~1/3 on 2->3 growth, never most keys
    assert 0 < moved < total_before * 2 * 0.6  # emb + wide tables

    for _ in range(10):
        after_grow = model.train_step(cat, dense, labels)
    # optimizer slots moved with the rows: convergence continues, no
    # re-warmup spike
    assert after_grow < first * 0.9

    # ---- scale IN: drop s0; its keys must migrate before routing ----
    new_set = {"s1": addrs["s1"], "s2": addr2}
    moved_in = demb.set_servers(new_set)
    stats = demb.stats()
    assert sorted(stats) == ["s1", "s2"]
    assert sum(s["emb"] for s in stats.values()) == total_before
    assert moved_in > 0

    for _ in range(10):
        final = model.train_step(cat, dense, labels)
    assert final < first * 0.9

    # inference path over the wire (frozen: no inserts)
    preds = model.predict(cat, dense)
    assert preds.shape == (512,)
    total_after = sum(s["emb"] for s in demb.stats().values())
    assert total_after == total_before
    demb.close()
    model.dense_params = None  # model.close() would close demb twice


@pytest.mark.slow  # tier-1 budget: crash drills live on the slow tier
def test_server_crash_failover_without_migration(two_servers):
    """Unplanned PS death: the dead server cannot export, so workers
    adopt the survivor ring with migrate=False — lookups keep working,
    keys the dead server owned re-initialize on demand
    (gather-or-insert), and training continues. Availability over
    durability for rows not yet checkpointed, matching the elastic-PS
    failover story (TTL'd rows re-learn)."""
    ctx, procs, addrs = two_servers
    cfg = DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))
    rng = np.random.default_rng(1)
    cat, dense, labels = _synthetic_ctr(rng, 256, cfg)

    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    model.coll.close()
    demb = DistributedEmbedding(_specs(cfg.emb_dim), addrs)
    model.coll = demb

    first = model.train_step(cat, dense, labels)
    for _ in range(10):
        model.train_step(cat, dense, labels)
    s0_rows = demb.stats()["s0"]["emb"]
    assert s0_rows > 0

    # hard-kill s0 (no drain, no export possible)
    procs[0].kill()
    procs[0].join(timeout=10)

    demb.set_servers({"s1": addrs["s1"]}, migrate=False)
    # the survivor still holds its share; the dead server's rows are
    # gone and will re-initialize on first touch
    stats = demb.stats()
    assert sorted(stats) == ["s1"]
    dev, _ = demb.pull({"emb": np.arange(300, dtype=np.int64)})
    assert np.asarray(dev["emb"][0]).shape == (300, cfg.emb_dim)

    # training continues through the loss bump from the lost rows
    for _ in range(15):
        after = model.train_step(cat, dense, labels)
    assert np.isfinite(after)
    assert after < first, (first, after)
    demb.close()
    model.dense_params = None


def test_migration_preserves_row_values(two_servers):
    """Row-level proof: a migrated key's value/freq round-trips exactly
    (the optimizer slab rides along in gather_full width)."""
    _, _, addrs = two_servers
    demb = DistributedEmbedding(_specs(), addrs)
    ids = np.arange(40, dtype=np.int64)
    demb.pull({"emb": ids})  # insert
    demb.push(
        {"emb": ids}, {"emb": np.full((40, 8), 0.25, np.float32)}
    )
    dev, _ = demb.pull({"emb": ids})
    before = np.asarray(dev["emb"][0]).copy()

    # force migration by renaming the ring (new server NAMES re-hash
    # every key even on the same processes)
    moved = demb.set_servers(
        {"a0": addrs["s0"], "a1": addrs["s1"]}
    )
    assert moved > 0
    dev2, _ = demb.pull({"emb": ids})
    np.testing.assert_allclose(
        before, np.asarray(dev2["emb"][0]), atol=1e-6
    )
    demb.close()


def test_sync_with_master_reroutes(two_servers):
    """Trainer-side version poll: when the master's ElasticPsService
    bumps the sparse-tier version, the client resolves addresses from
    the KV store and reroutes (tensorflow_failover.py:33 capability)."""
    from dlrover_tpu.common import messages as msgs
    from dlrover_tpu.sparse.server import register_server, sync_with_master

    ctx, procs, addrs = two_servers

    class FakeClient:
        def __init__(self):
            self.kv = {}
            self.version = 0
            self.servers = []

        def kv_store_set(self, k, v):
            self.kv[k] = v
            return True

        def kv_store_get(self, k):
            return self.kv.get(k, "")

        def get_ps_version(self, version_type="global"):
            return msgs.PsVersionResponse(
                version=self.version, servers=self.servers
            )

    client = FakeClient()
    for name, addr in addrs.items():
        register_server(client, name, addr)
    demb = DistributedEmbedding(_specs(), {"s0": addrs["s0"]})
    demb.pull({"emb": np.arange(30, dtype=np.int64)})
    base_version = demb.version

    # no version change -> no reroute
    assert sync_with_master(demb, client) is False

    # master announces the 2-server set
    client.version = base_version + 1
    client.servers = ["s0", "s1"]
    assert sync_with_master(demb, client) is True
    assert demb.version == base_version + 1
    assert demb.server_names == ["s0", "s1"]
    # rows redistributed across both processes, none lost
    stats = demb.stats()
    assert sum(s["emb"] for s in stats.values()) == 30

    # unknown address defers adoption instead of half-routing
    client.version += 1
    client.servers = ["s0", "s1", "ghost"]
    assert sync_with_master(demb, client) is False
    assert demb.server_names == ["s0", "s1"]
    demb.close()


# ---------------------------------------------------------------------------
# Elastic PS resharding: migration_plan property + mid-traffic drill
# ---------------------------------------------------------------------------


class _MasterPsClient:
    """Master-side surface the trainer/server polls, backed by the REAL
    ElasticPsService: kv-store for addresses (register_server /
    resolve_ring) and get_ps_version for the versioned server set."""

    def __init__(self, svc):
        self.svc = svc
        self.kv = {}

    def kv_store_set(self, k, v):
        self.kv[k] = v
        return True

    def kv_store_get(self, k):
        return self.kv.get(k, "")

    def get_ps_version(self, version_type="global"):
        from dlrover_tpu.common import messages as msgs

        return msgs.PsVersionResponse(
            version=self.svc.get_global_version(),
            servers=self.svc.get_servers(),
        )


def test_migration_plan_elastic_ps_property():
    """Property test over random key sets: for every ElasticPsService
    membership step (the 2→3 scale-out among them), applying
    ``migration_plan`` two-phase (copy all, then delete sources) leaves
    every key routable before AND after with no row lost or duplicated,
    values intact, and unchanged owners untouched."""
    from dlrover_tpu.master.elastic_ps import ElasticPsService
    from dlrover_tpu.sparse.partition import migration_plan, partition_keys

    rng = np.random.default_rng(123)
    svc = ElasticPsService()
    svc.set_servers(["s0", "s1"])
    memberships = [
        ["s0", "s1", "s2"],        # the drill's 2→3 scale-out
        ["s1", "s2"],              # scale-in
        ["s1", "s2", "s3", "s4"],  # double join
        ["s0", "s4"],              # churn: one back, most gone
    ]
    for new_set in memberships:
        keys = np.unique(
            rng.integers(0, 2**62, size=int(rng.integers(50, 400)))
        )
        old_set = svc.get_servers()
        before = partition_keys(keys, old_set)
        # routable BEFORE: the old partition covers every key once
        assert sum(v.size for v in before.values()) == keys.size
        stores = {
            s: {int(k): float(int(k) % 97) for k in ks}
            for s, ks in before.items()
        }

        v0 = svc.get_global_version()
        assert svc.set_servers(new_set) > v0  # membership change bumps
        assert svc.set_servers(new_set) == v0 + 1  # idempotent re-set

        plan = migration_plan(keys, old_set, new_set)
        # two-phase: every copy lands before any source delete (the
        # torn-transfer-atomic shape sparse/server.py migrates with)
        for key, src, dst in plan:
            stores.setdefault(dst, {})[key] = stores[src][key]
        for key, src, dst in plan:
            del stores[src][key]

        after = partition_keys(keys, new_set)
        for s, ks in after.items():
            held = stores.get(s, {})
            # routable AFTER, nothing lost, nothing duplicated
            assert set(held) == {int(k) for k in ks}
            # migrated values rode along exactly
            assert all(held[k] == float(k % 97) for k in held)
        assert (
            sum(len(stores.get(s, {})) for s in new_set) == keys.size
        )
        # servers that left the ring drained completely
        for s in set(old_set) - set(new_set):
            assert not stores[s]
        # bounded migration: HRW never reshuffles most of the keyspace
        # on a grow step (pure adds move ~added/total of the keys)
        if set(old_set) <= set(new_set):
            assert len(plan) < 0.7 * keys.size


@pytest.mark.slow  # serving loop + 3 KvServer processes: slow tier
def test_ps_reshard_drill_mid_traffic(two_servers):
    """Acceptance drill: scale the PS ring 2→3 WHILE a recommendation
    replica serves traffic against it. ``resync_ps`` adopts the
    master's bumped version at a step boundary; afterwards every
    submitted request resolved exactly once (futures), every row is
    still routable with per-table totals conserved (no loss, no
    duplication), and the reshard path + recovery seconds landed in
    the published SparseServingRecord."""
    from dlrover_tpu.master.elastic_ps import ElasticPsService
    from dlrover_tpu.serving.sparse_engine import SparseServingServer
    from dlrover_tpu.sparse.server import register_server

    ctx, procs, addrs = two_servers
    cfg = DeepFMConfig(n_fields=6, n_dense=4, emb_dim=8, mlp_dims=(32,))
    rng = np.random.default_rng(7)
    cat, dense, labels = _synthetic_ctr(rng, 256, cfg)

    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    model.coll.close()
    demb = DistributedEmbedding(_specs(cfg.emb_dim), addrs)
    model.coll = demb
    for _ in range(3):  # warm rows onto the 2-server ring
        model.train_step(cat, dense, labels)
    totals_before = {}
    for tname in ("emb", "wide"):
        totals_before[tname] = sum(
            s[tname] for s in demb.stats().values()
        )
    assert totals_before["emb"] > 0

    svc = ElasticPsService()
    client = _MasterPsClient(svc)
    for name, addr in addrs.items():
        register_server(client, name, addr)
    svc.set_servers(sorted(addrs))

    srv = SparseServingServer(
        model, cfg, replica="rec-0", max_queue=4096
    ).start()
    futures = []
    stop_feed = threading.Event()

    def feed():
        frng = np.random.default_rng(11)
        while not stop_feed.is_set() and len(futures) < 400:
            i = int(frng.integers(0, cat.shape[0]))
            futures.append(srv.submit(cat[i], dense[i]).future)
            time.sleep(0.001)

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()
    time.sleep(0.05)  # requests genuinely in flight before the reshard
    assert futures

    # ---- scale OUT mid-traffic: s2 joins, master bumps the version --
    p2, addr2 = _spawn_server(ctx)
    procs.append(p2)
    register_server(client, "s2", addr2)
    svc.add_server("s2")
    while svc.get_global_version() <= demb.version:
        svc.bump_global_version()
    assert srv.resync_ps(client) is True
    assert demb.server_names == ["s0", "s1", "s2"]

    stop_feed.set()
    feeder.join(timeout=30)
    n_submitted = len(futures)

    # zero lost/duplicated requests: every future resolves exactly once
    scores = [f.result(timeout=60)[0] for f in futures]
    assert len(scores) == n_submitted > 0
    assert all(np.isfinite(s) and 0.0 <= s <= 1.0 for s in scores)

    # zero lost/duplicated rows: per-table totals conserved across the
    # move and the new server owns its HRW share (serving traffic is
    # pull_frozen — it inserts nothing)
    stats = demb.stats()
    assert sorted(stats) == ["s0", "s1", "s2"]
    for tname in ("emb", "wide"):
        assert (
            sum(s[tname] for s in stats.values())
            == totals_before[tname]
        )
    assert stats["s2"]["emb"] > 0

    # reshard path + recovery seconds in telemetry
    rec = srv._publish()
    assert rec.ps_reshards == 1
    assert rec.last_reshard_s > 0.0
    assert rec.ps_version == demb.version
    assert rec.completed == n_submitted
    srv.stop()
    demb.close()
    model.dense_params = None  # model.close() would close demb twice
