"""Coworker data-plane tests: shm batch ring + producer pool.

Reference behaviors: atorch data/shm_context.py + shm_dataloader.py —
preprocessing processes ship batches to the trainer through shared
memory.
"""

import os
import time

import numpy as np
import pytest

from dlrover_tpu.data import BatchRing, CoworkerPool


@pytest.fixture(autouse=True)
def _run_id(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_RUN_ID", f"cw{os.getpid()}_{time.time_ns()}"
    )


def test_ring_roundtrip_single_process():
    ring = BatchRing("t1", slots=2, slot_bytes=1 << 20, create=True)
    try:
        batch = {
            "tokens": np.arange(64, dtype=np.int32).reshape(8, 8),
            "weight": np.ones((8,), np.float32),
        }
        ring.put(batch)
        out = ring.get()
        np.testing.assert_array_equal(out["tokens"], batch["tokens"])
        assert out["weight"].dtype == np.float32
    finally:
        ring.close()


def test_ring_slot_recycling():
    ring = BatchRing("t2", slots=2, slot_bytes=1 << 20, create=True)
    try:
        for i in range(6):  # 3× the slot count: slots must recycle
            ring.put({"x": np.full((4,), i)})
            out = ring.get()
            np.testing.assert_array_equal(out["x"], np.full((4,), i))
    finally:
        ring.close()


def test_ring_rejects_oversize_batch():
    ring = BatchRing("t3", slots=1, slot_bytes=1024, create=True)
    try:
        with pytest.raises(ValueError, match="slot_bytes"):
            ring.put({"x": np.zeros((1 << 16,), np.float32)})
    finally:
        ring.close()


def _producer(worker_id, num_workers):
    # module-level (picklable): each worker yields its own shard
    for i in range(worker_id, 12, num_workers):
        yield {"idx": np.array([i]), "data": np.full((16,), float(i))}


def test_coworker_pool_multiprocess():
    pool = CoworkerPool(
        _producer, num_workers=3, slots=4, slot_bytes=1 << 20, name="t4"
    ).start()
    try:
        seen = sorted(
            int(b["idx"][0]) for b in pool.batches(timeout=60)
        )
        assert seen == list(range(12))
    finally:
        pool.stop()


def test_coworker_pool_backpressure():
    """Producers block on free slots; a slow consumer still gets every
    batch exactly once."""
    pool = CoworkerPool(
        _producer, num_workers=2, slots=2, slot_bytes=1 << 20, name="t5"
    ).start()
    try:
        seen = []
        for b in pool.batches(timeout=60):
            time.sleep(0.02)  # slow consumer
            seen.append(int(b["idx"][0]))
        assert sorted(seen) == list(range(12))
    finally:
        pool.stop()
