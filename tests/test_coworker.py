"""Coworker data-plane tests: shm batch ring + producer pool.

Reference behaviors: atorch data/shm_context.py + shm_dataloader.py —
preprocessing processes ship batches to the trainer through shared
memory.
"""

import os
import time

import numpy as np
import pytest

from dlrover_tpu.data import BatchRing, CoworkerPool


@pytest.fixture(autouse=True)
def _run_id(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_RUN_ID", f"cw{os.getpid()}_{time.time_ns()}"
    )


def test_ring_roundtrip_single_process():
    ring = BatchRing("t1", slots=2, slot_bytes=1 << 20, create=True)
    try:
        batch = {
            "tokens": np.arange(64, dtype=np.int32).reshape(8, 8),
            "weight": np.ones((8,), np.float32),
        }
        ring.put(batch)
        out = ring.get()
        np.testing.assert_array_equal(out["tokens"], batch["tokens"])
        assert out["weight"].dtype == np.float32
    finally:
        ring.close()


def test_ring_slot_recycling():
    ring = BatchRing("t2", slots=2, slot_bytes=1 << 20, create=True)
    try:
        for i in range(6):  # 3× the slot count: slots must recycle
            ring.put({"x": np.full((4,), i)})
            out = ring.get()
            np.testing.assert_array_equal(out["x"], np.full((4,), i))
    finally:
        ring.close()


def test_ring_rejects_oversize_batch():
    ring = BatchRing("t3", slots=1, slot_bytes=1024, create=True)
    try:
        with pytest.raises(ValueError, match="slot_bytes"):
            ring.put({"x": np.zeros((1 << 16,), np.float32)})
    finally:
        ring.close()


def _producer(worker_id, num_workers):
    # module-level (picklable): each worker yields its own shard
    for i in range(worker_id, 12, num_workers):
        yield {"idx": np.array([i]), "data": np.full((16,), float(i))}


def test_coworker_pool_multiprocess():
    pool = CoworkerPool(
        _producer, num_workers=3, slots=4, slot_bytes=1 << 20, name="t4"
    ).start()
    try:
        seen = sorted(
            int(b["idx"][0]) for b in pool.batches(timeout=60)
        )
        assert seen == list(range(12))
    finally:
        pool.stop()


def test_coworker_pool_backpressure():
    """Producers block on free slots; a slow consumer still gets every
    batch exactly once."""
    pool = CoworkerPool(
        _producer, num_workers=2, slots=2, slot_bytes=1 << 20, name="t5"
    ).start()
    try:
        seen = []
        for b in pool.batches(timeout=60):
            time.sleep(0.02)  # slow consumer
            seen.append(int(b["idx"][0]))
        assert sorted(seen) == list(range(12))
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# cross-pod TCP data plane (reference: coworker_data_service.py:43 —
# CPU pods feeding trainer pods over the network)
# ---------------------------------------------------------------------------


def test_network_fed_pool_two_process():
    """Remote producer processes push over TCP into the consumer ring."""
    from dlrover_tpu.data.coworker import RemoteProducerPool

    pool = CoworkerPool(
        None, slots=4, slot_bytes=1 << 20, name="t6",
        remote_producers=2, listen=True, listen_host="127.0.0.1",
    )
    port = pool.feed_server.address[1]
    remote = RemoteProducerPool(
        ("127.0.0.1", port), _producer, num_workers=2
    ).start()
    try:
        seen = sorted(int(b["idx"][0]) for b in pool.batches(timeout=60))
        assert seen == list(range(12))
        remote.join(timeout=30)
    finally:
        remote.stop()
        pool.stop()


def test_mixed_local_and_network_producers():
    """shm fast path and TCP ingress feed the SAME ring concurrently;
    every batch arrives exactly once and done-marker accounting closes."""
    from dlrover_tpu.data.coworker import RemoteProducerPool

    pool = CoworkerPool(
        _producer, num_workers=1, slots=4, slot_bytes=1 << 20, name="t7",
        remote_producers=1, listen=True, listen_host="127.0.0.1",
    ).start()
    port = pool.feed_server.address[1]
    remote = RemoteProducerPool(
        ("127.0.0.1", port), _remote_shard, num_workers=1
    ).start()
    try:
        seen = sorted(int(b["idx"][0]) for b in pool.batches(timeout=60))
        # local producer: 0..11 (1 worker); remote shard: 100..105
        assert seen == list(range(0, 12)) + list(range(100, 106))
    finally:
        remote.stop()
        pool.stop()


def _remote_shard(worker_id, num_workers):
    for i in range(100 + worker_id, 106, num_workers):
        yield {"idx": np.array([i]), "data": np.zeros((8,))}


def test_network_backpressure_bounded_by_ring():
    """A fast remote producer must not run ahead of the ring: acks are
    slot claims, so at most `slots` batches are in flight."""
    from dlrover_tpu.data.coworker import BatchFeedServer, RemoteBatchWriter

    ring = BatchRing("t8", slots=2, slot_bytes=1 << 20, create=True)
    server = BatchFeedServer(ring, host="127.0.0.1")
    writer = RemoteBatchWriter(("127.0.0.1", server.address[1]))
    import threading

    sent = []

    def blast():
        for i in range(8):
            writer.put({"x": np.array([i])})
            sent.append(i)
        writer.done()

    t = threading.Thread(target=blast, daemon=True)
    t.start()
    time.sleep(1.0)
    # ring has 2 slots: the writer can be at most slots+1 ahead
    # (one batch may sit in the server thread waiting for a slot)
    assert len(sent) <= 3, sent
    got = []
    while True:
        b = ring.get(timeout=30)
        if b is None:
            break
        got.append(int(b["x"][0]))
    t.join(timeout=30)
    assert got == list(range(8))
    server.stop()
    ring.close()
