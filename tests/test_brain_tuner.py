"""Brain auto-tuner: the telemetry→config loop (cluster/brain.py
ColdStartPlanner + BrainTuner, the master's plan_tuning directive path,
the ParalConfigTuner poll doc, and step-boundary application).

Tier split: the planner math, the revision ladders (synthetic records,
injected clock), the master plumbing, and the MetricsStore durability
pins are pure and fast; the end-to-end drills (a real TrainStepBuilder
rebuild, a ServingEngine retune parity run) compile jitted steps and
live on the slow tier (see test_marker_lint _SLOW_LEDGER +
test_brain_tuner_e2e_drills_are_slow).
"""

import json
import threading

import pytest

from dlrover_tpu.cluster import brain
from dlrover_tpu.common import messages as msgs
from dlrover_tpu.models.config import get_config
from dlrover_tpu.observability import telemetry


@pytest.fixture(autouse=True)
def _fresh_bus():
    telemetry.reset_hub()
    yield
    telemetry.reset_hub()


def _drift(frac=1.0):
    return telemetry.OverlapDriftRecord(
        planned_exposed_us=100.0,
        measured_collective_us=100.0 * (1 + frac),
        drift_us=100.0 * frac,
        drift_frac=frac,
    )


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# cold-start planner
# ---------------------------------------------------------------------------


def test_cold_start_plan_reproduces_hand_tuned_flagship():
    """The acceptance bar: from ONLY the model shape + a 16 GiB chip,
    the planner lands on the hand-tuned bench recipe for the flagship
    long-context row (llama-1.4b, b1 x s8192, save_qkv — bench.py
    _ATTEMPTS[0]), i.e. cold_start_mfu_frac == 1.0 by construction."""
    cfg = get_config("llama-1.4b", max_seq=8192)
    plan = brain.ColdStartPlanner().plan(
        cfg, n_devices=1, seq=8192, hbm_bytes=16e9
    )
    assert plan.origin == "cold_start"
    assert plan.batch_size == 1
    assert plan.remat == "save_qkv"
    assert plan.comm_bucket_mb > 0
    # single chip, no dp: no ZeRO, bitwise-safe f32 wire, no DCN
    assert plan.update_sharding == ""
    assert plan.comm_wire_dtype == "float32"
    assert plan.comm_wire_dtype_dcn == ""


def test_cold_start_plan_small_model_dp_mesh():
    """Small shape on a dp mesh: batch fills the token target, remat
    stays off, dispatch-bound small steps get the fused block, dp>1
    without accumulation picks zero1, and a multi-slice mesh narrows
    the DCN wire only."""
    cfg = get_config("tiny")
    plan = brain.ColdStartPlanner().plan(
        cfg,
        mesh={"dp": 4, "num_slices": 2},
        seq=128,
        hbm_bytes=16e9,
    )
    assert plan.remat == "none"
    assert plan.batch_size >= 8
    assert plan.block_k > 1
    assert plan.update_sharding == "zero1"
    assert plan.comm_wire_dtype == "float32"
    assert plan.comm_wire_dtype_dcn == "int8"


def test_cold_start_plan_nothing_fits_degrades_to_floor():
    """A shape no remat can fit on the budget still yields a plan —
    batch 1 at full remat (the caller sees the warning, never a
    crash)."""
    cfg = get_config("llama-1.4b", max_seq=8192)
    plan = brain.ColdStartPlanner().plan(
        cfg, n_devices=1, seq=8192, hbm_bytes=6e9
    )
    assert plan.batch_size == 1
    assert plan.remat == "full"


def test_estimate_hbm_is_calibrated_to_the_attempt_ladder():
    """The memory model's load-bearing property: at the flagship shape
    save_qkv fits a 16 GiB chip and the next-cheaper tier does not —
    exactly the boundary the hand-tuned ladder sits on."""
    cfg = get_config("llama-1.4b", max_seq=8192)
    budget = 16e9 * 0.92
    assert brain.estimate_hbm_bytes(cfg, 1, 8192, "save_qkv") <= budget
    assert brain.estimate_hbm_bytes(cfg, 1, 8192, "save_qkv_gate") > budget


def test_tuning_plan_round_trips_and_replays_old_lines():
    plan = brain.TuningPlan(
        version=3, origin="revision", knob="spec_k", signal="accept",
        spec_k=4,
    )
    assert telemetry.from_json(plan.to_json()) == plan
    # a pre-tuner recording has no TuningPlan lines; a FUTURE recording
    # missing fields fills from defaults (sentinel = leave alone)
    old = json.dumps({"r": "TuningPlan", "d": {"version": 1}})
    back = telemetry.from_json(old)
    assert back.spec_k == -1 and back.page_bucketing == -1
    assert back.remat == "" and back.batch_size == 0


# ---------------------------------------------------------------------------
# revision ladders (synthetic records, injected clock — pure + fast)
# ---------------------------------------------------------------------------


def test_drift_ladder_doubles_bucket_after_patience():
    tuner = brain.BrainTuner(
        brain.TuningPlan(comm_bucket_mb=4.0), cooldown_s=0.0
    )
    for _ in range(2):
        tuner.on_record(_drift())
    assert not tuner.revisions  # patience not yet met
    tuner.on_record(_drift())
    rev = tuner.revisions[-1]
    assert rev.knob == "comm_bucket_mb" and rev.signal == "overlap_drift"
    assert tuner.plan.comm_bucket_mb == 8.0
    # a healthy sample resets the streak
    tuner.on_record(_drift(frac=0.0))
    tuner.on_record(_drift())
    tuner.on_record(_drift())
    assert len(tuner.revisions) == 1


def test_fp8_saturation_widens_dcn_wire_first():
    tuner = brain.BrainTuner(
        brain.TuningPlan(
            comm_wire_dtype="float32", comm_wire_dtype_dcn="int8"
        ),
        cooldown_s=0.0,
    )
    tuner.on_record(telemetry.AnomalyRecord(kind="fp8_saturation"))
    assert tuner.plan.comm_wire_dtype_dcn == "bfloat16"
    assert tuner.plan.comm_wire_dtype == "float32"  # ICI untouched
    tuner.on_record(telemetry.AnomalyRecord(kind="fp8_saturation"))
    assert tuner.plan.comm_wire_dtype_dcn == "float32"
    # ladder top: no further revision
    n = len(tuner.revisions)
    tuner.on_record(telemetry.AnomalyRecord(kind="fp8_saturation"))
    assert len(tuner.revisions) == n


def test_oom_ladder_descends_remat_then_halves_batch():
    tuner = brain.BrainTuner(
        brain.TuningPlan(remat="save_qkv", batch_size=4), cooldown_s=0.0
    )
    assert tuner.on_failure("oom").remat == "save_attn"
    assert tuner.on_failure("oom").remat == "full"
    assert tuner.on_failure("oom").batch_size == 2
    assert tuner.on_failure("oom").batch_size == 1
    assert tuner.on_failure("oom") is None  # ladder exhausted, no crash
    assert tuner.on_failure("timeout") is None  # only oom ladders


def test_serving_ladders_spec_k_chunk_slots_bucketing():
    tuner = brain.BrainTuner(
        brain.TuningPlan(
            spec_k=2, prefill_chunk=128, n_slots=4, page_bucketing=0
        ),
        cooldown_s=0.0,
        ttft_target_ms=50.0,
        occupancy_patience=2,
    )
    # high accept EWMA → spec_k up (one step per revision window; the
    # zero cooldown here means one step per record)
    tuner.on_record(
        telemetry.ServingRecord(
            replica="r", draft_tokens=10, spec_accept_rate=0.95,
            active_slots=3, queue_depth=1,  # occupancy-neutral sample
        )
    )
    assert tuner.plan.spec_k == 3
    # TTFT breach → chunk halves (never below the floor)
    tuner.on_record(
        telemetry.ServingRecord(
            replica="r", ttft_p99_ms=120.0, active_slots=3, queue_depth=1
        )
    )
    assert tuner.plan.prefill_chunk == 64
    # saturated slots with queued work → grow
    for _ in range(2):
        tuner.on_record(
            telemetry.ServingRecord(
                replica="r", active_slots=4, queue_depth=3
            )
        )
    assert tuner.plan.n_slots == 5
    # table-ship burst across stats snapshots → enable bucketing
    tuner.observe_serving_stats({"table_ships": 0})
    tuner.observe_serving_stats({"table_ships": 20})
    assert tuner.plan.page_bucketing == 1
    knobs = [r.knob for r in tuner.revisions]
    assert knobs == ["spec_k", "prefill_chunk", "n_slots", "page_bucketing"]


def test_cooldown_suppresses_per_knob_thrash():
    clk = FakeClock()
    tuner = brain.BrainTuner(
        brain.TuningPlan(comm_bucket_mb=4.0), cooldown_s=30.0, clock=clk
    )
    for _ in range(3):
        tuner.on_record(_drift())
    assert tuner.plan.comm_bucket_mb == 8.0
    for _ in range(3):
        tuner.on_record(_drift())  # inside the cooldown: suppressed
    assert tuner.plan.comm_bucket_mb == 8.0
    clk.t = 31.0
    for _ in range(3):
        tuner.on_record(_drift())
    assert tuner.plan.comm_bucket_mb == 16.0


def test_revisions_version_through_report_and_publish_to_hub(tmp_path):
    hub = telemetry.configure_hub()
    seen = []
    hub.subscribe(seen.append, types=("TuningPlan",))
    tuner = brain.BrainTuner(
        brain.TuningPlan(version=7, comm_bucket_mb=4.0),
        report=lambda rev: 41,  # the master's directive counter
        cooldown_s=0.0,
    )
    for _ in range(3):
        tuner.on_record(_drift())
    assert tuner.plan.version == 41
    assert seen and seen[-1].version == 41
    # a failing report falls back to local monotonic versioning
    def boom(rev):
        raise OSError("master unreachable")

    tuner._report = boom
    for _ in range(3):
        tuner.on_record(_drift())
    assert tuner.plan.version == 42


def test_apply_revision_maps_fields_onto_acceleration_plan():
    from dlrover_tpu.accelerate.strategy import AccelerationPlan

    ap = AccelerationPlan(remat="save_qkv", comm_bucket_mb=4.0)
    out = brain.apply_revision(
        ap,
        brain.TuningPlan(
            remat="full", comm_bucket_mb=8.0, comm_wire_dtype_dcn="bfloat16",
            update_sharding="zero2", grad_accum_steps=2,
        ),
    )
    assert out.remat == "full" and out.comm_bucket_mb == 8.0
    assert out.comm_wire_dtype_dcn == "bfloat16"
    assert out.update_sharding == "zero2" and out.grad_accum == 2
    assert ap.remat == "save_qkv"  # pure: input untouched
    # sentinels leave knobs alone; "off" disables
    out2 = brain.apply_revision(out, brain.TuningPlan(update_sharding="off"))
    assert out2.remat == "full" and out2.update_sharding is False


# ---------------------------------------------------------------------------
# master plumbing: versioned directive → ParallelConfig poll
# ---------------------------------------------------------------------------


def test_job_manager_plan_tuning_is_monotonic():
    from dlrover_tpu.master.node_manager import JobManager

    jm = JobManager(num_workers=1)
    assert jm.get_tuning() == {"version": 0}
    v1 = jm.plan_tuning('{"remat": "full"}', reason="oom")
    v2 = jm.plan_tuning('{"spec_k": 3}', reason="accept")
    assert (v1, v2) == (1, 2)
    got = jm.get_tuning()
    assert got["version"] == 2 and got["plan_json"] == '{"spec_k": 3}'


def test_servicer_folds_tuning_directive_into_parallel_config():
    from dlrover_tpu.master.node_manager import JobManager
    from dlrover_tpu.master.servicer import MasterServicer

    jm = JobManager(num_workers=1)
    jm.register_node(msgs.NodeMeta(node_id=0, node_rank=0))
    servicer = MasterServicer(job_manager=jm)
    # before any plan: plain config, version pair (0, 0)
    cfg = servicer.get(msgs.ParallelConfigRequest(node_id=0))
    assert cfg.tuning_version == 0 and cfg.tuning_json == ""
    plan_json = json.dumps({"version": 0, "remat": "save_attn"})
    assert servicer.report(
        msgs.TuningPlanNotice(node_id=0, plan_json=plan_json, signal="oom")
    )
    cfg = servicer.get(msgs.ParallelConfigRequest(node_id=0))
    assert cfg.tuning_version == 1
    assert json.loads(cfg.tuning_json)["remat"] == "save_attn"
    # the dedicated getter carries the same directive
    d = servicer.get(msgs.TuningPlanRequest(node_id=0))
    assert d.version == 1 and d.plan_json == plan_json


def test_config_tuner_doc_carries_tuning_and_gates_on_version_pair(
    tmp_path,
):
    from dlrover_tpu.agent.config_tuner import ParalConfigTuner

    class FakeClient:
        tuning_json = ""
        tuning_version = 0

        def get_parallel_config(self):
            return msgs.ParallelConfig(
                batch_size=32, version=2,
                tuning_json=self.tuning_json,
                tuning_version=self.tuning_version,
            )

    client = FakeClient()
    path = tmp_path / "cfg.json"
    tuner = ParalConfigTuner(client, config_path=str(path))
    assert tuner.poll_once()
    assert "tuning" not in json.loads(path.read_text())
    # same dataloader version, NEW tuning version → rewrite (the pair
    # gates, not either version alone)
    client.tuning_json = json.dumps({"version": 5, "spec_k": 3})
    client.tuning_version = 5
    assert tuner.poll_once()
    doc = json.loads(path.read_text())
    assert doc["version"] == 2 and doc["tuning_version"] == 5
    assert doc["tuning"]["spec_k"] == 3
    assert not tuner.poll_once()  # both versions unchanged → no rewrite
    # malformed directive: dropped with a warning, doc still written
    client.tuning_json = "{not json"
    client.tuning_version = 6
    assert tuner.poll_once()
    assert "tuning" not in json.loads(path.read_text())


def test_config_tuner_rate_limits_tracebacks_and_backs_off(monkeypatch):
    from dlrover_tpu.agent import config_tuner as ct

    class FlakyClient:
        def __init__(self):
            self.fail_with = OSError("master down")

        def get_parallel_config(self):
            raise self.fail_with

    warned = []
    monkeypatch.setattr(
        ct.logger, "warning", lambda msg, *a, **kw: warned.append(msg % a)
    )
    client = FlakyClient()
    tuner = ct.ParalConfigTuner(client, config_path="/tmp/unused_cfg.json")
    for _ in range(4):
        assert not tuner.poll_once()
    # a DISTINCT failure reason warns again
    client.fail_with = ValueError("bad frame")
    assert not tuner.poll_once()
    assert len(warned) == 2  # once per distinct reason, not per poll
    assert "OSError" in warned[0] and "ValueError" in warned[1]
    assert tuner._fail_streak == 5
    # the loop delay grows with the streak (jittered exponential on top
    # of the base cadence) and a success resets it
    from dlrover_tpu.common.comm import _backoff_delay

    assert _backoff_delay(tuner._fail_streak - 1) > 0
    client.fail_with = None

    class OkClient:
        def get_parallel_config(self):
            return msgs.ParallelConfig(batch_size=8, version=1)

    tuner._client = OkClient()
    assert tuner.poll_once()
    assert tuner._fail_streak == 0


# ---------------------------------------------------------------------------
# MetricsStore durability (the jsonl store behind the brain's history)
# ---------------------------------------------------------------------------


def test_metrics_store_tolerates_torn_and_foreign_lines(tmp_path):
    """A crash mid-append leaves a torn last line; a foreign writer
    leaves junk. Reload must keep every intact row and skip the rest —
    same tolerance contract as healthcheck's flight-recorder replay."""
    path = tmp_path / "metrics.jsonl"
    store = brain.MetricsStore(str(path))
    for i in range(3):
        store.append(
            brain.JobMetrics(
                job_name="j", job_kind="llm", worker_num=i + 1,
                samples_per_sec=10.0 * (i + 1), finished=True,
            )
        )
    with open(path, "a") as f:
        f.write("not json at all\n")
        f.write('{"job_name": "j", "unknown_field": 1}\n')  # foreign schema
        f.write('{"job_name": "j", "worker_num": 9')  # torn mid-write
    reloaded = brain.MetricsStore(str(path))
    rows = reloaded.job_rows("j")
    assert [r.worker_num for r in rows] == [1, 2, 3]
    assert all(r.timestamp > 0 for r in rows)  # stamped at append time


def test_jsonl_store_first_allocation_matches_in_process(tmp_path):
    """Cold-start worker allocation from history must not depend on
    WHERE the history lives: the same rows through an in-process store
    and through a jsonl round-trip (write, reload from disk) produce
    the identical plan."""
    rows = [
        brain.JobMetrics(
            job_name=f"old-{i}", job_kind="llm", worker_num=n,
            samples_per_sec=s, finished=True, timestamp=1000.0 + i,
        )
        for i, (n, s) in enumerate([(2, 40.0), (4, 100.0), (8, 120.0)])
    ]
    mem = brain.BrainService(store=brain.MetricsStore())
    for r in rows:
        mem.persist_metrics(r)
    path = tmp_path / "metrics.jsonl"
    disk = brain.MetricsStore(str(path))
    for r in rows:
        disk.append(
            brain.JobMetrics(**{
                f: getattr(r, f)
                for f in ("job_name", "job_kind", "worker_num",
                          "samples_per_sec", "finished", "timestamp")
            })
        )
    jsonl = brain.BrainService(store=brain.MetricsStore(str(path)))
    mem.bind_job("new", "llm")
    jsonl.bind_job("new", "llm")
    a = mem._first_allocation()
    b = jsonl._first_allocation()
    assert a.worker_num == b.worker_num == 4  # best samples/sec/worker


# ---------------------------------------------------------------------------
# healthcheck replay of the decision trail
# ---------------------------------------------------------------------------


def test_healthcheck_replays_tuning_decision_trail(tmp_path):
    from dlrover_tpu.observability import healthcheck as hc

    path = tmp_path / "flight.jsonl"
    with open(path, "w") as f:
        f.write(
            brain.TuningPlan(
                version=1, origin="cold_start", reason="llama-1.4b b1"
            ).to_json() + "\n"
        )
        f.write(
            brain.TuningPlan(
                version=2, origin="revision", knob="comm_bucket_mb",
                signal="overlap_drift", comm_bucket_mb=16.0,
            ).to_json() + "\n"
        )
        f.write('{"torn')
    diag = hc.diagnose(hc.load_records(str(path)))
    t = diag["tuning"]
    assert t["n_revisions"] == 1
    assert t["knobs_moved"] == {"comm_bucket_mb": 1}
    assert [d["version"] for d in t["decisions"]] == [1, 2]
    report = hc.format_report(diag)
    assert "brain tuning: 1 revision(s)" in report
    assert "v2 comm_bucket_mb: overlap_drift" in report
    # pre-tuner recordings replay with NO tuning section, not an error
    empty = tmp_path / "old.jsonl"
    empty.write_text(
        telemetry.StepRecord(step=1, loss=2.0).to_json() + "\n"
    )
    assert hc.diagnose(hc.load_records(str(empty)))["tuning"] == {}


# ---------------------------------------------------------------------------
# step-boundary application (fast: fake build_step, no jit)
# ---------------------------------------------------------------------------


def test_elastic_trainer_apply_tuning_rebuilds_at_boundary():
    from dlrover_tpu.elastic.trainer import ElasticTrainer

    built = []

    def build_step(ga):
        built.append(ga)
        return lambda state, batch: (state, {"ga": ga})

    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append, types=("ElasticEvent",))
    tr = ElasticTrainer(
        global_batch_size=32, micro_batch_size=4,
        build_step=build_step, data_replicas_fn=lambda: 2,
    )
    assert tr.grad_accum == 4 and built == [4]
    # an unversioned no-op plan does nothing
    assert not tr.apply_tuning(brain.TuningPlan())
    # a versioned batch revision re-derives accumulation + rebuilds
    assert tr.apply_tuning(brain.TuningPlan(version=3, batch_size=8))
    assert tr.micro_batch_size == 8 and tr.grad_accum == 2
    assert built == [4, 2]
    kinds = [e.kind for e in events]
    assert "tuning_replan" in kinds and "mesh_replan" not in kinds[1:]
    # a version bump alone (builder-side knob changed) still rebuilds
    assert tr.apply_tuning({"version": 4})
    assert built == [4, 2, 2]
    _, metrics = tr.step(None, None)
    assert metrics["ga"] == 2


# ---------------------------------------------------------------------------
# end-to-end drills (slow tier: real jit compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_tuning_replan_drill_loss_continuity(tmp_path):
    """Injected mid-run regression → versioned revision through the
    master → step-boundary rebuild, NO restart: the drilled run's loss
    trajectory is bitwise the undisturbed run's (same state object
    carries across the rebuild), the revision event lands on the hub,
    and the changed knob is the one the signal maps to."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from dlrover_tpu.elastic.trainer import ElasticTrainer
    from dlrover_tpu.master.node_manager import JobManager
    from dlrover_tpu.parallel.mesh import single_device_mesh
    from dlrover_tpu.train import (
        TrainStepBuilder,
        init_train_state,
        make_optimizer,
    )

    cfg = get_config("tiny", max_seq=64)
    mesh = single_device_mesh()
    opt = make_optimizer(
        learning_rate=1e-3, warmup_steps=2, decay_steps=100
    )
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, 100)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def build_step(ga):
        return TrainStepBuilder(cfg, mesh, opt, grad_accum=ga).build()

    def run(n_steps, mid=None):
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        tr = ElasticTrainer(
            global_batch_size=2, micro_batch_size=2,
            build_step=build_step, data_replicas_fn=lambda: 1,
        )
        losses = []
        for i in range(n_steps):
            if mid is not None and i == n_steps // 2:
                mid(tr)
            state, metrics = tr.step(state, batch)
            losses.append(float(jnp.ravel(metrics["loss"])[-1]))
        return losses

    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append, types=("ElasticEvent", "TuningPlan"))
    jm = JobManager(num_workers=1)
    tuner = brain.BrainTuner(
        brain.TuningPlan(version=1, comm_bucket_mb=4.0),
        report=lambda rev: jm.plan_tuning(
            json.dumps({"knob": rev.knob}), reason=rev.signal
        ),
        cooldown_s=0.0,
    )
    tuner.attach(hub)

    def inject(tr):
        # the regression: sustained overlap drift over the threshold
        for _ in range(3):
            hub.publish(_drift())
        assert tuner.revisions, "drift did not produce a revision"
        assert tr.apply_tuning(tuner.plan)

    baseline = run(6)
    drilled = run(6, mid=inject)
    # loss continuity: bitwise the undisturbed trajectory — the rebuild
    # changed the executable, never the state or the math
    assert drilled == baseline
    rev = tuner.revisions[-1]
    assert rev.knob == "comm_bucket_mb"
    # the master minted the version (its counter starts at 1)
    assert rev.version == jm.get_tuning()["version"] == 1
    kinds = [type(e).__name__ + ":" + getattr(e, "kind", "") for e in events]
    assert "ElasticEvent:tuning_replan" in kinds
    assert any(isinstance(e, brain.TuningPlan) for e in events)


@pytest.mark.slow
def test_serving_retune_bitwise_parity():
    """Retuning spec_k + prefill_chunk on a LIVE engine keeps the
    output stream bitwise equal to the offline reference at the same
    seeds (spec-on == spec-off == offline; chunk-width independence),
    and an idle n_slots retune rebuilds geometry without perturbing a
    subsequent wave."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.models import decoder, generate
    from dlrover_tpu.serving.engine import ServingEngine
    from dlrover_tpu.serving.scheduler import Scheduler

    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    prompts = [[1, 2, 3, 1, 2, 3, 1], [5, 6, 5, 6, 5, 6, 5, 6, 5]]
    max_new = [8, 6]
    refs = [
        [
            int(t)
            for t in np.asarray(
                generate.greedy(
                    params, cfg, jnp.asarray([p], jnp.int32), m
                )[0]
            )
        ]
        for p, m in zip(prompts, max_new)
    ]

    sched = Scheduler(replica="retune")
    eng = ServingEngine(
        params, cfg, sched, n_slots=2, max_len=32, page_size=4,
        mode="bf16", prefill_chunk=8, paged=True, spec_k=0,
    )
    reqs = [sched.submit(p, m) for p, m in zip(prompts, max_new)]
    for _ in range(2):
        eng.step()
    # mid-stream retune: spec on, chunk halved (halving keeps every
    # in-flight resume point aligned by construction)
    out = eng.retune(spec_k=2, prefill_chunk=4)
    assert out["applied"] == {"spec_k": 2, "prefill_chunk": 4}
    eng.drain(timeout=600)
    assert [r.future.result(timeout=5) for r in reqs] == refs
    assert eng.stats()["spec_k"] == 2

    # growing n_slots while busy defers; once idle it applies and the
    # next wave still matches the offline reference bitwise
    out = eng.retune(n_slots=3)
    assert out["applied"].get("n_slots") == 3  # drained → idle → applies
    reqs = [sched.submit(p, m) for p, m in zip(prompts, max_new)]
    eng.drain(timeout=600)
    assert [r.future.result(timeout=5) for r in reqs] == refs

    # invalid widths are rejected loudly, not deferred
    with pytest.raises(ValueError):
        eng.retune(prefill_chunk=5)  # 32 % 5 != 0
    with pytest.raises(ValueError):
        eng.retune(spec_k=-2)
