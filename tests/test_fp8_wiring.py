"""fp8 model-graph wiring (VERDICT r2 #4).

ops/fp8.py's delayed-scaling GEMM threaded through the decoder MLPs and
the train step: the fp8 state lives in ``state["fp8"]``, updates ride
the gradient of the fp8 inputs (state-on-cotangent), and — because
pre-fp8 backends upcast the ALREADY-QUANTIZED values — CPU runs the
same numerics v6e+ would, so the wiring + convergence are testable
here; only the speed claim needs hardware. Reference:
atorch/auto/opt_lib/amp_optimization.py:197 (TE fp8 autocast).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import (
    TrainStepBuilder,
    init_train_state,
    make_optimizer,
)
from dlrover_tpu.train.train_step import batch_sharding


def _cfg(fp8: bool):
    return get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32, fp8=fp8,
    )


def _batch(key, batch=8, seq=32):
    base = jax.random.randint(key, (batch, seq + 1), 0, 8)
    return {
        "tokens": base[:, :-1].astype(jnp.int32),
        "targets": base[:, 1:].astype(jnp.int32),
    }


def test_fp8_state_updates_and_loss_tracks_bf16():
    """Training the tiny flagship with fp8 on: the delayed-scaling
    histories roll every step, and the loss curve tracks the bf16 run
    within tolerance (same quantized numerics the v6e MXU would see)."""
    mesh = build_mesh(MeshConfig(dp=-1))
    batch = jax.device_put(_batch(jax.random.key(1)), batch_sharding(mesh))
    losses = {}
    for fp8 in (False, True):
        cfg = _cfg(fp8)
        opt = make_optimizer(
            learning_rate=3e-3, warmup_steps=2, decay_steps=200
        )
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        if fp8:
            assert "fp8" in state
            before = np.asarray(
                jax.tree.leaves(state["fp8"])[0]
            ).copy()
        step = TrainStepBuilder(cfg, mesh, opt).build()
        curve = []
        for _ in range(25):
            state, metrics = step(state, batch)
            curve.append(float(metrics["loss"]))
        losses[fp8] = curve
        if fp8:
            after = np.asarray(jax.tree.leaves(state["fp8"])[0])
            assert not np.allclose(before, after), (
                "fp8 amax histories never updated"
            )
    # both train; fp8 tracks bf16 (quantization noise bounded)
    assert losses[True][-1] < losses[True][0] * 0.7
    np.testing.assert_allclose(
        losses[True][-1], losses[False][-1], rtol=0.15
    )


def test_fp8_with_grad_accum_threads_state():
    """The microbatch scan must roll the fp8 state across microbatches
    (amax from micro i visible to micro i+1's scales next step)."""
    mesh = build_mesh(MeshConfig(dp=-1))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt, grad_accum=2).build()
    batch = jax.device_put(
        _batch(jax.random.key(2), batch=8), batch_sharding(mesh)
    )
    before = np.asarray(jax.tree.leaves(state["fp8"])[0]).copy()
    state, metrics = step(state, batch)
    after = np.asarray(jax.tree.leaves(state["fp8"])[0])
    assert np.isfinite(float(metrics["loss"]))
    assert not np.allclose(before, after)


def test_fp8_composes_with_remat():
    cfg = dataclasses.replace(_cfg(True), remat="full")
    mesh = build_mesh(MeshConfig(dp=-1))
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(3)), batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fp8_rejects_unsupported_combos():
    with pytest.raises(ValueError, match="MoE"):
        decoder.init_fp8_states(
            get_config("tiny-moe", n_layer=2, d_model=64, d_ff=128,
                       n_head=4, vocab_size=128, max_seq=32)
        )
    mesh = build_mesh(MeshConfig(dp=-1))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3)
    with pytest.raises(ValueError, match="custom loss_fn"):
        TrainStepBuilder(
            cfg, mesh, opt, loss_fn=lambda p, b: (0.0, {})
        )


def test_fp8_covers_attention_projections():
    """VERDICT r3 #2: fp8 is no longer MLP-only — the q/k/v/o projection
    GEMMs carry their own delayed-scaling states and those histories
    roll during training (their amax observations differ from the MLP
    ones, so a shared state would be wrong)."""
    cfg = _cfg(True)
    states = decoder.init_fp8_states(cfg)
    assert {"wq", "wk", "wv", "wo"} <= set(states)
    mesh = build_mesh(MeshConfig(dp=-1))
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(4)), batch_sharding(mesh))
    before = {
        k: np.asarray(state["fp8"][k]["amax_x"]).copy()
        for k in ("wq", "wk", "wv", "wo")
    }
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    for k, b in before.items():
        a = np.asarray(state["fp8"][k]["amax_x"])
        assert not np.allclose(b, a), f"attention fp8 state {k} frozen"


def test_fp8_under_pipeline_mesh_uses_current_scaling():
    """VERDICT r3 #2: fp8 + pp no longer raises. Pipeline meshes run
    stateless current scaling (delayed-scaling state cannot thread a
    pipeline schedule — the cotangent would sum m microbatch updates),
    so the train state carries no fp8 entry, and the loss tracks the
    bf16 pipeline run within quantization tolerance."""
    mesh = build_mesh(MeshConfig(pp=2, dp=-1))
    losses = {}
    for fp8 in (False, True):
        cfg = _cfg(fp8)
        opt = make_optimizer(
            learning_rate=3e-3, warmup_steps=2, decay_steps=200
        )
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        assert "fp8" not in state  # stateless under pp
        step = TrainStepBuilder(cfg, mesh, opt).build()
        batch = jax.device_put(
            _batch(jax.random.key(5)), batch_sharding(mesh)
        )
        curve = []
        for _ in range(15):
            state, metrics = step(state, batch)
            curve.append(float(metrics["loss"]))
        losses[fp8] = curve
    assert losses[True][-1] < losses[True][0] * 0.85
    np.testing.assert_allclose(
        losses[True][-1], losses[False][-1], rtol=0.15
    )


def test_fp8_pipeline_composes_with_remat():
    """fp8 + pp + remat: the 'current' sentinel must ride inside the
    checkpoint-wrapped body partial — passed as a call-time argument,
    jax.checkpoint would reject the str as a non-JAX type (and this is
    a combination the engine auto-generates on fp8 hardware)."""
    mesh = build_mesh(MeshConfig(pp=2, dp=-1))
    cfg = dataclasses.replace(_cfg(True), remat="full")
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(7)), batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fp8_pipeline_with_grad_accum():
    """current-scaling fp8 composes with the microbatch scan (no state
    in the carry)."""
    mesh = build_mesh(MeshConfig(pp=2, dp=-1))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt, grad_accum=2).build()
    batch = jax.device_put(
        _batch(jax.random.key(6), batch=8), batch_sharding(mesh)
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state["step"]) == 1


def test_fp8_auto_enabled_on_fp8_hardware(monkeypatch):
    """VERDICT r3 #2: on fp8-native hardware the engine's candidate
    strategies carry fp8 by default (reference auto-applies TE fp8 the
    same way); MoE models stay bf16; pre-fp8 hardware is unchanged."""
    from dlrover_tpu.accelerate import device_context, engine

    cfg = _cfg(False)
    monkeypatch.setattr(device_context, "fp8_supported", lambda: True)
    cands = engine.generate_candidates(cfg, n_devices=2, seq=32)
    assert cands, "no candidates generated"
    assert all(
        any(name == "fp8" for name, _ in c) for c in cands
    ), "fp8 not default-enabled on fp8-capable hardware"
    moe_cfg = get_config(
        "tiny-moe", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32,
    )
    moe_cands = engine.generate_candidates(moe_cfg, n_devices=2, seq=32)
    assert all(
        all(name != "fp8" for name, _ in c) for c in moe_cands
    ), "fp8 must not auto-enable for MoE models"
    monkeypatch.setattr(device_context, "fp8_supported", lambda: False)
    cands_off = engine.generate_candidates(cfg, n_devices=2, seq=32)
    assert all(
        all(name != "fp8" for name, _ in c) for c in cands_off
    ), "fp8 must stay off on pre-fp8 hardware"


def test_fp8_strategy_force_applies_to_config():
    """auto_accelerate path: the fp8 strategy entry (forced off-v6e)
    lands in the built model config."""
    from dlrover_tpu.accelerate.dry_runner import build_from_plan
    from dlrover_tpu.accelerate.strategy import apply_strategy

    plan = apply_strategy(
        [
            ("mixed_parallel",
             {"dp": 1, "fsdp": 1, "tp": 1, "sp": 1, "pp": 1}),
            ("fp8", {"force": True}),
        ]
    )
    cfg = _cfg(False)
    _, builder, _, _, cfg2 = build_from_plan(
        cfg, plan, devices=jax.devices()[:1]
    )
    assert cfg2.fp8 is True
