"""fp8 model-graph wiring (VERDICT r2 #4).

ops/fp8.py's delayed-scaling GEMM threaded through the decoder MLPs and
the train step: the fp8 state lives in ``state["fp8"]``, updates ride
the gradient of the fp8 inputs (state-on-cotangent), and — because
pre-fp8 backends upcast the ALREADY-QUANTIZED values — CPU runs the
same numerics v6e+ would, so the wiring + convergence are testable
here; only the speed claim needs hardware. Reference:
atorch/auto/opt_lib/amp_optimization.py:197 (TE fp8 autocast).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import (
    TrainStepBuilder,
    init_train_state,
    make_optimizer,
)
from dlrover_tpu.train.train_step import batch_sharding

# fp8 wiring compiles are heavy on the CPU mesh; excluded from the tier-1 budget
pytestmark = pytest.mark.slow


def _cfg(fp8: bool):
    return get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32, fp8=fp8,
    )


def _batch(key, batch=8, seq=32):
    base = jax.random.randint(key, (batch, seq + 1), 0, 8)
    return {
        "tokens": base[:, :-1].astype(jnp.int32),
        "targets": base[:, 1:].astype(jnp.int32),
    }


def test_fp8_state_updates_and_loss_tracks_bf16():
    """Training the tiny flagship with fp8 on: the delayed-scaling
    histories roll every step, and the loss curve tracks the bf16 run
    within tolerance (same quantized numerics the v6e MXU would see)."""
    mesh = build_mesh(MeshConfig(dp=-1))
    batch = jax.device_put(_batch(jax.random.key(1)), batch_sharding(mesh))
    losses = {}
    for fp8 in (False, True):
        cfg = _cfg(fp8)
        opt = make_optimizer(
            learning_rate=3e-3, warmup_steps=2, decay_steps=200
        )
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        if fp8:
            assert "fp8" in state
            before = np.asarray(
                jax.tree.leaves(state["fp8"])[0]
            ).copy()
        step = TrainStepBuilder(cfg, mesh, opt).build()
        curve = []
        for _ in range(25):
            state, metrics = step(state, batch)
            curve.append(float(metrics["loss"]))
        losses[fp8] = curve
        if fp8:
            after = np.asarray(jax.tree.leaves(state["fp8"])[0])
            assert not np.allclose(before, after), (
                "fp8 amax histories never updated"
            )
    # both train; fp8 tracks bf16 (quantization noise bounded)
    assert losses[True][-1] < losses[True][0] * 0.7
    np.testing.assert_allclose(
        losses[True][-1], losses[False][-1], rtol=0.15
    )


def test_fp8_with_grad_accum_threads_state():
    """The microbatch scan must merge the fp8 state across microbatches:
    every microbatch quantizes against the SAME step-start scales, the
    per-microbatch updated histories max-merge in the scan carry, and
    the history advances exactly ONE slot per optimizer step (the
    once-per-step semantics test_fp8_sharded pins numerically)."""
    mesh = build_mesh(MeshConfig(dp=-1))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt, grad_accum=2).build()
    batch = jax.device_put(
        _batch(jax.random.key(2), batch=8), batch_sharding(mesh)
    )
    before = np.asarray(jax.tree.leaves(state["fp8"])[0]).copy()
    state, metrics = step(state, batch)
    after = np.asarray(jax.tree.leaves(state["fp8"])[0])
    assert np.isfinite(float(metrics["loss"]))
    assert not np.allclose(before, after)


def test_fp8_composes_with_remat():
    cfg = dataclasses.replace(_cfg(True), remat="full")
    mesh = build_mesh(MeshConfig(dp=-1))
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(3)), batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fp8_rejects_unsupported_combos():
    # MoE configs get attention-projection states only (experts run
    # stateless current scaling — VERDICT r4 ask #4 lifted the raise)
    states = decoder.init_fp8_states(
        get_config("tiny-moe", n_layer=2, d_model=64, d_ff=128,
                   n_head=4, vocab_size=128, max_seq=32)
    )
    assert set(states) == {"wq", "wk", "wv", "wo"}
    mesh = build_mesh(MeshConfig(dp=-1))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3)
    with pytest.raises(ValueError, match="custom loss_fn"):
        TrainStepBuilder(
            cfg, mesh, opt, loss_fn=lambda p, b: (0.0, {})
        )


def test_fp8_covers_attention_projections():
    """VERDICT r3 #2: fp8 is no longer MLP-only — the q/k/v/o projection
    GEMMs carry their own delayed-scaling states and those histories
    roll during training (their amax observations differ from the MLP
    ones, so a shared state would be wrong)."""
    cfg = _cfg(True)
    states = decoder.init_fp8_states(cfg)
    assert {"wq", "wk", "wv", "wo"} <= set(states)
    mesh = build_mesh(MeshConfig(dp=-1))
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(4)), batch_sharding(mesh))
    before = {
        k: np.asarray(state["fp8"][k]["amax_x"]).copy()
        for k in ("wq", "wk", "wv", "wo")
    }
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    for k, b in before.items():
        a = np.asarray(state["fp8"][k]["amax_x"])
        assert not np.allclose(b, a), f"attention fp8 state {k} frozen"


def test_fp8_under_pipeline_mesh_uses_current_scaling():
    """VERDICT r3 #2: fp8 + pp no longer raises. Pipeline meshes run
    stateless current scaling (delayed-scaling state cannot thread a
    pipeline schedule — the cotangent would sum m microbatch updates),
    so the train state carries no fp8 entry, and the loss tracks the
    bf16 pipeline run within quantization tolerance."""
    mesh = build_mesh(MeshConfig(pp=2, dp=-1))
    losses = {}
    for fp8 in (False, True):
        cfg = _cfg(fp8)
        opt = make_optimizer(
            learning_rate=3e-3, warmup_steps=2, decay_steps=200
        )
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        assert "fp8" not in state  # stateless under pp
        step = TrainStepBuilder(cfg, mesh, opt).build()
        batch = jax.device_put(
            _batch(jax.random.key(5)), batch_sharding(mesh)
        )
        curve = []
        for _ in range(15):
            state, metrics = step(state, batch)
            curve.append(float(metrics["loss"]))
        losses[fp8] = curve
    assert losses[True][-1] < losses[True][0] * 0.85
    np.testing.assert_allclose(
        losses[True][-1], losses[False][-1], rtol=0.15
    )


def test_fp8_pipeline_composes_with_remat():
    """fp8 + pp + remat: the 'current' sentinel must ride inside the
    checkpoint-wrapped body partial — passed as a call-time argument,
    jax.checkpoint would reject the str as a non-JAX type (and this is
    a combination the engine auto-generates on fp8 hardware)."""
    mesh = build_mesh(MeshConfig(pp=2, dp=-1))
    cfg = dataclasses.replace(_cfg(True), remat="full")
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(7)), batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fp8_pipeline_with_grad_accum():
    """current-scaling fp8 composes with the microbatch scan (no state
    in the carry)."""
    mesh = build_mesh(MeshConfig(pp=2, dp=-1))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt, grad_accum=2).build()
    batch = jax.device_put(
        _batch(jax.random.key(6), batch=8), batch_sharding(mesh)
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state["step"]) == 1


def test_fp8_auto_enabled_on_fp8_hardware(monkeypatch):
    """VERDICT r3 #2: on fp8-native hardware the engine's candidate
    strategies carry fp8 by default (reference auto-applies TE fp8 the
    same way); MoE models stay bf16; pre-fp8 hardware is unchanged."""
    from dlrover_tpu.accelerate import device_context, engine

    cfg = _cfg(False)
    monkeypatch.setattr(device_context, "fp8_supported", lambda: True)
    cands = engine.generate_candidates(cfg, n_devices=2, seq=32)
    assert cands, "no candidates generated"
    assert all(
        any(name == "fp8" for name, _ in c) for c in cands
    ), "fp8 not default-enabled on fp8-capable hardware"
    moe_cfg = get_config(
        "tiny-moe", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32,
    )
    moe_cands = engine.generate_candidates(moe_cfg, n_devices=2, seq=32)
    assert all(
        all(name != "fp8" for name, _ in c) for c in moe_cands
    ), "fp8 must not auto-enable for MoE models"
    monkeypatch.setattr(device_context, "fp8_supported", lambda: False)
    cands_off = engine.generate_candidates(cfg, n_devices=2, seq=32)
    assert all(
        all(name != "fp8" for name, _ in c) for c in cands_off
    ), "fp8 must stay off on pre-fp8 hardware"


def test_fp8_strategy_force_applies_to_config():
    """auto_accelerate path: the fp8 strategy entry (forced off-v6e)
    lands in the built model config."""
    from dlrover_tpu.accelerate.dry_runner import build_from_plan
    from dlrover_tpu.accelerate.strategy import apply_strategy

    plan = apply_strategy(
        [
            ("mixed_parallel",
             {"dp": 1, "fsdp": 1, "tp": 1, "sp": 1, "pp": 1}),
            ("fp8", {"force": True}),
        ]
    )
    cfg = _cfg(False)
    _, builder, _, _, cfg2 = build_from_plan(
        cfg, plan, devices=jax.devices()[:1]
    )
    assert cfg2.fp8 is True


def test_delayed_scaling_cotangent_sum_divergence():
    """Pins the WHY behind the pipeline refusal (decoder.loss path
    raises on pp meshes with delayed-scaling state; VERDICT r4 weak #3):
    when one fp8 state feeds m microbatches inside a single
    differentiated computation — exactly what a pipeline schedule does,
    every microbatch passing through the same stage weights — the
    state's cotangent is the elementwise SUM of m updated amax
    histories, which is not a valid state. Sequential threading (what
    the grad-accum scan does, and what a pipeline cannot do) rolls the
    history correctly. This turns the docstring argument at
    models/decoder.py (pp>1 + delayed fp8 → ValueError) into a
    verified numeric constraint.
    """
    from dlrover_tpu.ops.fp8 import AMAX_HISTORY, fp8_dot, init_fp8_state

    k1, k2, kw = jax.random.split(jax.random.key(0), 3)
    # distinct, known amaxes per microbatch so the sum is detectable
    x1 = jax.random.normal(k1, (4, 8), jnp.float32)
    x1 = 3.0 * x1 / jnp.max(jnp.abs(x1))          # amax(x1) == 3
    x2 = jax.random.normal(k2, (4, 8), jnp.float32)
    x2 = 5.0 * x2 / jnp.max(jnp.abs(x2))          # amax(x2) == 5
    w = jax.random.normal(kw, (8, 8), jnp.float32)
    state0 = init_fp8_state()

    # pipeline-shaped use: ONE state, m=2 microbatches, one backward
    def loss_shared(state):
        return (
            fp8_dot(x1, w, state).sum() + fp8_dot(x2, w, state).sum()
        )

    shared_out = jax.grad(loss_shared)(state0)

    # sequential threading (the grad-accum convention): each
    # microbatch's backward consumes the PREVIOUS updated state
    s = state0
    for x in (x1, x2):
        s = jax.grad(lambda st: fp8_dot(x, w, st).sum())(s)
    sequential = s

    # sequential is a real rolled history: ones shifted out, the two
    # microbatch amaxes appended in order
    np.testing.assert_allclose(
        np.asarray(sequential["amax_x"][-2:]), [3.0, 5.0], rtol=1e-6
    )
    assert np.allclose(np.asarray(sequential["amax_x"][:-2]), 1.0)

    # the pipeline-shaped cotangent is the SUM of the two per-microbatch
    # updated histories: prefix 1+1=2 (not 1), tails 3 and 5 summed into
    # overlapping slots — NOT a state, and NOT the sequential result
    shared_hist = np.asarray(shared_out["amax_x"])
    assert np.allclose(shared_hist[: AMAX_HISTORY - 1], 2.0), shared_hist
    np.testing.assert_allclose(shared_hist[-1], 3.0 + 5.0, rtol=1e-6)
    assert not np.allclose(shared_hist, np.asarray(sequential["amax_x"]))

    # consequence: a scale derived from the summed "state" misquantizes
    # (8/448 vs the true 5/448 — a 1.6x dynamic-range error)
    from dlrover_tpu.ops.fp8 import E4M3_MAX, _scale_from_history

    bad = float(_scale_from_history(shared_out["amax_x"], E4M3_MAX))
    good = float(_scale_from_history(sequential["amax_x"], E4M3_MAX))
    assert bad == pytest.approx(8.0 / E4M3_MAX, rel=1e-6)
    assert good == pytest.approx(5.0 / E4M3_MAX, rel=1e-6)


def test_fp8_moe_loss_tracks_bf16():
    """fp8 through a MoE model (VERDICT r4 ask #4): attention
    projections on delayed scaling, expert FFN GEMMs on stateless
    current scaling (fp8_batched_dot_current, per-expert weight
    scales) — the fp8 loss curve tracks the bf16 run."""
    mesh = build_mesh(MeshConfig(dp=-1, ep=2))
    batch = jax.device_put(_batch(jax.random.key(3)), batch_sharding(mesh))
    losses = {}
    for fp8 in (False, True):
        cfg = get_config(
            "tiny-moe", n_layer=2, d_model=64, d_ff=128, n_head=4,
            vocab_size=128, max_seq=32, fp8=fp8,
        )
        opt = make_optimizer(
            learning_rate=3e-3, warmup_steps=2, decay_steps=200
        )
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        if fp8:
            # attention-projection states only; expert GEMMs stateless
            assert set(state["fp8"]) == {"wq", "wk", "wv", "wo"}
        step = TrainStepBuilder(cfg, mesh, opt).build()
        curve = []
        for _ in range(25):
            state, metrics = step(state, batch)
            curve.append(float(metrics["loss"]))
        losses[fp8] = curve
        if fp8:
            rolled = np.asarray(jax.tree.leaves(state["fp8"])[0])
            assert rolled.shape[0] == cfg.n_layer
    assert losses[True][-1] < losses[True][0] * 0.7
    np.testing.assert_allclose(
        losses[True][-1], losses[False][-1], rtol=0.15
    )


def test_fp8_moe_under_pipeline_current_scaling():
    """MoE + fp8 + pp: everything (attention AND experts) runs the
    stateless current-scaling path — one step compiles and trains."""
    mesh = build_mesh(MeshConfig(dp=-1, pp=2))
    cfg = get_config(
        "tiny-moe", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32, fp8=True,
        pp_stages=2,
    )
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    assert "fp8" not in state  # pp meshes are stateless ("current")
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(
        _batch(jax.random.key(4), batch=8), batch_sharding(mesh)
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fp8_moe_alltoall_dispatch():
    """fp8 expert GEMMs inside the explicit all-to-all lowering: the
    current-scaling custom VJP must compose with shard_map over ep
    (per-rank token slices, lax.all_to_all exchanges) — one step
    compiles and trains with finite loss."""
    mesh = build_mesh(MeshConfig(dp=-1, ep=2))
    cfg = get_config(
        "tiny-moe", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32, fp8=True, moe_alltoall=True,
    )
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(
        _batch(jax.random.key(6), batch=8), batch_sharding(mesh)
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fp8_with_ring_attention():
    """fp8 projection GEMMs (delayed scaling) feeding ring attention
    on an sp mesh: the q/k/v produced by fp8_dot enter the ppermute
    ring's shard_map — one step compiles and trains."""
    mesh = build_mesh(MeshConfig(dp=-1, sp=2))
    cfg = _cfg(True)
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=100)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt, attn_impl="ring").build()
    batch = jax.device_put(
        _batch(jax.random.key(7), batch=8), batch_sharding(mesh)
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
