"""SLO-driven serving autoscaling (master/serving_autoscaler.py).

Fast tier: the scale loop's PURE decision logic — ``evaluate()`` driven
by synthetic signal dicts and a fake clock (breach detection priority,
role attribution, hysteresis latch + clear, cooldown, min/max bounds,
shrink ladder), the watchdog ``subscribe`` gate-edge hook, the
histogram delta-window arithmetic, and the master-plane versioning
plumbing. None of it stands up a replica.

Slow tier: the fleet drills. A seeded burst against a 1-replica fleet
breaches, the scaler attaches a warm spare at runtime, p99 restores,
and the outputs are bitwise equal to an always-2 fleet; a planned
scale-in drains the least-loaded victim over the live-migration wire
with zero lost, zero duplicated, zero re-prefilled requests — and the
detached victim is never re-counted dead by the failover sweep; an
oscillating load makes at most one actionable decision per cooldown
window even through repeated breach/clear episodes.
"""

import time

import pytest

jax = pytest.importorskip("jax")

from dlrover_tpu.master.serving_autoscaler import (  # noqa: E402
    SCALE_SIGNALS,
    ServingAutoScaler,
    ServingScalerConfig,
)
from dlrover_tpu.observability import telemetry  # noqa: E402
from dlrover_tpu.observability.histogram import (  # noqa: E402
    LatencyHistogram,
    histogram_delta,
)
from dlrover_tpu.observability.watchdog import (  # noqa: E402
    ServingWatchdog,
    ServingWatchdogConfig,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class FakeRouter:
    """Just enough router for the pure decision paths: ``evaluate``
    with synthetic signals never touches it, and ``apply`` without a
    provision_fn only records."""

    disaggregated = False

    def live_replicas(self, role=None):
        return []


def _scaler(clock=None, **cfg_kw):
    cfg_kw.setdefault("p99_target_ms", 100.0)
    cfg_kw.setdefault("min_window_n", 4)
    cfg_kw.setdefault("cooldown_s", 10.0)
    return ServingAutoScaler(
        FakeRouter(), ServingScalerConfig(**cfg_kw),
        clock=clock or FakeClock(),
    )


def _sig(role="unified", n=16, p99=50.0, ttft=0.0, tpot=0.0, queue=0,
         occ=0.0, n_replicas=1):
    return {"roles": {role: {
        "n": n, "p99_ms": p99, "ttft_p99_ms": ttft, "tpot_p99_ms": tpot,
        "queue_depth": queue, "new_drops": 0, "occupancy": occ,
        "n_replicas": n_replicas,
    }}}


# ---------------------------------------------------------------------------
# breach detection, bounds, cooldown
# ---------------------------------------------------------------------------


def test_slo_breach_scales_out_with_reaction_clock():
    clock = FakeClock()
    sc = _scaler(clock)
    clock.t = 5.0
    d = sc.evaluate(_sig(p99=150.0))
    assert d is not None
    assert (d["direction"], d["role"], d["signal"]) == (
        "out", "unified", "slo_breach"
    )
    assert (d["n_before"], d["n_after"]) == (1, 2)
    # breach first seen at this evaluation → reaction clock starts here
    assert d["reaction_s"] == 0.0
    rec = sc.apply(d)
    assert rec.direction == "out" and rec.n_after == 2
    assert sc.decisions == [rec]


def test_at_max_replicas_breach_latches_but_no_decision():
    sc = _scaler(max_replicas=2)
    assert sc.evaluate(_sig(p99=150.0, n_replicas=2)) is None
    assert sc._latched == {"unified": "slo_breach"}


def test_cooldown_suppresses_second_scale_out():
    clock = FakeClock()
    sc = _scaler(clock, max_replicas=4, cooldown_s=10.0)
    d = sc.evaluate(_sig(p99=150.0))
    sc.apply(d)
    clock.t = 5.0  # inside the cooldown window: breach persists, no act
    assert sc.evaluate(_sig(p99=150.0, n_replicas=2)) is None
    clock.t = 11.0  # window over: the sustained breach may act again
    d2 = sc.evaluate(_sig(p99=150.0, n_replicas=2))
    assert d2 is not None and d2["n_after"] == 3


def test_signal_priority_pages_over_latency():
    sc = _scaler()
    d = sc.evaluate(_sig(p99=150.0, occ=0.99))
    assert d["signal"] == "out_of_pages"
    assert SCALE_SIGNALS[0] == "out_of_pages"


def test_thin_window_cannot_judge_latency_percentiles():
    sc = _scaler(min_window_n=8)
    assert sc.evaluate(_sig(p99=150.0, n=3)) is None


# ---------------------------------------------------------------------------
# hysteresis latch, clear, shrink ladder
# ---------------------------------------------------------------------------


def test_hysteresis_band_stays_latched_then_clears():
    clock = FakeClock()
    sc = _scaler(clock, max_replicas=2)
    clock.t = 1.0
    sc.apply(sc.evaluate(_sig(p99=150.0)))
    # 90 > 80 = target × clear_frac: inside the band, still latched
    clock.t = 2.0
    assert sc.evaluate(_sig(p99=90.0, n_replicas=2)) is None
    assert sc._latched == {"unified": "slo_breach"}
    # 70 ≤ 80: the latch clears and the restore clock stops
    clock.t = 4.5
    d = sc.evaluate(_sig(p99=70.0, n_replicas=2))
    assert d["signal"] == "clear" and d["direction"] == ""
    assert sc.last_restore_s == pytest.approx(3.5)  # breach@1.0 → 4.5
    assert not sc._latched


def test_shrink_after_consecutive_clear_windows():
    clock = FakeClock()
    sc = _scaler(clock, shrink_after_clear=3, cooldown_s=1.0)
    for i in range(2):
        clock.t = 10.0 + i
        assert sc.evaluate(_sig(p99=20.0, n_replicas=2)) is None
    clock.t = 14.0  # third consecutive clear window: shrink fires
    d = sc.evaluate(_sig(p99=20.0, n_replicas=2))
    assert (d["direction"], d["signal"]) == ("in", "planned")
    assert (d["n_before"], d["n_after"]) == (2, 1)


def test_never_shrinks_below_min_or_while_gate_open():
    clock = FakeClock()
    sc = _scaler(clock, shrink_after_clear=1, cooldown_s=0.0)
    clock.t = 100.0
    # at the floor: clear windows accumulate but never go below min
    for i in range(5):
        clock.t += 1.0
        assert sc.evaluate(_sig(p99=20.0, n_replicas=1)) is None
    # an open watchdog gate vetoes the shrink even above the floor
    sc._on_gate("slo_breach", True, None)
    clock.t += 1.0
    assert sc.evaluate(_sig(p99=20.0, n_replicas=2)) is None


def test_oscillating_signals_one_decision_per_cooldown_window():
    """Hysteresis + cooldown: a trace that flaps around the target
    every tick produces at most ONE actionable decision per cooldown
    window, not one per oscillation."""
    clock = FakeClock()
    sc = _scaler(clock, max_replicas=8, cooldown_s=10.0,
                 shrink_after_clear=2)
    n_dec = 0
    for i in range(100):  # 25s of 0.25s ticks, p99 flapping 150 ↔ 70
        clock.t = i * 0.25
        p99 = 150.0 if i % 2 == 0 else 70.0
        d = sc.evaluate(_sig(p99=p99, n_replicas=1 + n_dec))
        if d is not None and d["direction"]:
            n_dec += 1
            sc._last_decision_t[d["role"]] = clock.t
    # 25s / 10s cooldown → at most 3 windows can act
    assert n_dec <= 3


# ---------------------------------------------------------------------------
# role attribution (disaggregated fleets)
# ---------------------------------------------------------------------------


def _two_roles(**over):
    roles = {
        "prefill": dict(n=16, p99_ms=40.0, ttft_p99_ms=30.0,
                        tpot_p99_ms=0.0, queue_depth=1, new_drops=0,
                        occupancy=0.3, n_replicas=1),
        "decode": dict(n=16, p99_ms=40.0, ttft_p99_ms=0.0,
                       tpot_p99_ms=5.0, queue_depth=1, new_drops=0,
                       occupancy=0.3, n_replicas=1),
    }
    for role, kv in over.items():
        roles[role].update(kv)
    return {"roles": roles}


def test_ttft_breach_attributes_to_prefill_pool():
    sc = _scaler(p99_target_ms=0.0, ttft_target_ms=20.0)
    d = sc.evaluate(_two_roles(prefill={"ttft_p99_ms": 80.0}))
    assert (d["role"], d["signal"]) == ("prefill", "ttft_regression")


def test_tpot_breach_attributes_to_decode_pool():
    sc = _scaler(p99_target_ms=0.0, tpot_target_ms=4.0)
    d = sc.evaluate(_two_roles(decode={"tpot_p99_ms": 9.0}))
    assert (d["role"], d["signal"]) == ("decode", "tpot_breach")


def test_out_of_pages_attributes_to_most_occupied_pool():
    sc = _scaler(p99_target_ms=0.0)
    d = sc.evaluate(_two_roles(decode={"occupancy": 0.97}))
    assert (d["role"], d["signal"]) == ("decode", "out_of_pages")


def test_queue_depth_attributes_to_deepest_pool():
    sc = _scaler(p99_target_ms=0.0, queue_depth_high=4)
    d = sc.evaluate(_two_roles(prefill={"queue_depth": 9}))
    assert (d["role"], d["signal"]) == ("prefill", "queue_depth")


def test_per_role_bounds_override_scalars():
    sc = _scaler(p99_target_ms=0.0, queue_depth_high=4,
                 max_replicas=4, role_max={"prefill": 1})
    d = sc.evaluate(_two_roles(prefill={"queue_depth": 9}))
    assert d is None  # prefill pinned at 1 despite the fleet-wide 4
    assert sc._latched == {"prefill": "queue_depth"}


# ---------------------------------------------------------------------------
# watchdog gate-edge subscription (satellite: ServingWatchdog.subscribe)
# ---------------------------------------------------------------------------


def _rec(**kw):
    base = dict(replica="rep-0", completed=20, p99_ms=10.0)
    base.update(kw)
    return telemetry.ServingRecord(**base)


def test_watchdog_subscribe_delivers_both_edges():
    wd = ServingWatchdog(ServingWatchdogConfig(p99_target_ms=100.0))
    seen = []
    wd.subscribe(lambda kind, breaching, rec: seen.append(
        (kind, breaching, rec.replica if rec is not None else None)
    ))
    wd.observe(_rec(p99_ms=150.0))  # breach edge
    wd.observe(_rec(p99_ms=150.0))  # sustained: NOT an edge
    wd.observe(_rec(p99_ms=50.0))   # clear edge
    assert seen == [
        ("slo_breach", True, "rep-0"),
        ("slo_breach", False, "rep-0"),
    ]


def test_watchdog_without_subscribers_still_classifies():
    wd = ServingWatchdog(ServingWatchdogConfig(p99_target_ms=100.0))
    assert [a.kind for a in wd.observe(_rec(p99_ms=150.0))] == [
        "slo_breach"
    ]


def test_raising_subscriber_never_breaks_classification():
    wd = ServingWatchdog(ServingWatchdogConfig(p99_target_ms=100.0))

    def boom(kind, breaching, rec):
        raise RuntimeError("observer bug")

    wd.subscribe(boom)
    assert [a.kind for a in wd.observe(_rec(p99_ms=150.0))] == [
        "slo_breach"
    ]


def test_gate_edge_starts_the_reaction_clock():
    """A breach the watchdog saw FIRST is timed from its edge, not the
    scaler's next tick."""
    clock = FakeClock()
    sc = _scaler(clock)
    wd = ServingWatchdog(
        ServingWatchdogConfig(p99_target_ms=100.0), clock=clock
    )
    wd.subscribe(sc._on_gate)
    clock.t = 2.0
    wd.observe(_rec(p99_ms=150.0))  # edge at t=2
    clock.t = 3.5
    d = sc.evaluate(_sig(p99=150.0))
    assert d["reaction_s"] == pytest.approx(1.5)


# ---------------------------------------------------------------------------
# versioning + telemetry record
# ---------------------------------------------------------------------------


class FakePlanner:
    def __init__(self):
        self.calls = []
        self.v = 41

    def plan_serving_scale(self, role, target, reason=""):
        self.calls.append((role, target, reason))
        self.v += 1
        return self.v


def test_decisions_version_through_the_master_plane():
    planner = FakePlanner()
    sc = ServingAutoScaler(
        FakeRouter(), ServingScalerConfig(p99_target_ms=100.0,
                                          min_window_n=4),
        job_manager=planner, clock=FakeClock(),
    )
    rec = sc.apply(sc.evaluate(_sig(p99=150.0)))
    assert rec.version == 42
    assert planner.calls == [("unified", 2, "slo_breach 150>100")]
    # clear decisions are telemetry-only: no directive, version 0
    rec2 = sc.apply(sc.evaluate(_sig(p99=10.0, n_replicas=2)))
    assert rec2.signal == "clear" and rec2.version == 0
    assert len(planner.calls) == 1


def test_job_manager_plan_serving_scale_is_monotonic_per_role():
    from dlrover_tpu.master.node_manager import JobManager

    jm = JobManager(num_workers=1)
    v1 = jm.plan_serving_scale("prefill", 2, reason="ttft")
    v2 = jm.plan_serving_scale("decode", 3, reason="tpot")
    assert v2 == v1 + 1
    assert jm.get_serving_scale("prefill")["target"] == 2
    assert jm.get_serving_scale("decode")["version"] == v2
    # newest across roles when unspecified; unknown role is empty
    assert jm.get_serving_scale()["role"] == "decode"
    assert jm.get_serving_scale("nope") == {"version": 0}


def test_scale_decision_record_roundtrip_and_replay():
    rec = telemetry.ScaleDecisionRecord(
        role="decode", direction="out", signal="tpot_breach",
        value=9.0, target=4.0, n_before=1, n_after=2, version=7,
        reaction_s=0.31, replica="spare-0", reason="tpot 9>4", ts=1.0,
    )
    back = telemetry.from_json(rec.to_json())
    assert back == rec
    # healthcheck replay: the scale trail names why the fleet is its size
    from dlrover_tpu.observability.healthcheck import _scale_section

    sect = _scale_section({"ScaleDecisionRecord": [rec]})
    assert sect["n_scaled"] == 1
    assert sect["final_size"] == {"decode": 2}
    assert sect["worst_reaction_s"] == pytest.approx(0.31)
    assert _scale_section({}) == {}  # pre-autoscaler recordings


def test_histogram_delta_is_a_window_not_a_lifetime():
    prev = LatencyHistogram()
    for v in (10.0, 10.0, 10.0, 10.0):
        prev.record(v)
    cur = prev.copy()
    for v in (500.0, 500.0):
        cur.record(v)
    win = histogram_delta(cur, prev)
    assert win.n == 2
    assert win.percentile(99.0) > 400.0  # the fresh breach, unmasked
    assert cur.percentile(50.0) < 20.0   # ...which the lifetime hides
    assert histogram_delta(cur, None).n == cur.n
    with pytest.raises(ValueError):
        histogram_delta(cur, LatencyHistogram(sub_bits=3))


# ---------------------------------------------------------------------------
# fleet drills (slow tier): live scale-out / scale-in / oscillation
# ---------------------------------------------------------------------------


def _drill_fleet(n, cfg, params, kw, prefix="as"):
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica
    from dlrover_tpu.serving.migration import ServingMigrator

    reps = [
        ServingReplica(f"{prefix}-{i}", params, cfg, node_id=i,
                       **kw).start()
        for i in range(n)
    ]
    return reps, ReplicaRouter(reps, migrator=ServingMigrator())


def _warm(router, max_len, import_np):
    np = import_np
    n_warm = 0
    for frac in (4, 2, 1):
        router.submit(list(np.arange(max(3, (max_len - 3) // frac - 2))
                           % 4 + 1), 3)
        n_warm += 1
    router.wait_all(timeout=600.0)
    return n_warm


@pytest.mark.slow
def test_burst_scale_out_restores_p99_bitwise():
    """Drill (a): a burst against a 1-replica fleet breaches, the
    scaler attaches a pre-warmed spare at runtime, the latched breach
    clears (p99 restored), and every output is bitwise equal to the
    same trace on an always-2 fleet."""
    import numpy as np

    from dlrover_tpu.models import decoder
    from dlrover_tpu.models.config import get_config
    from dlrover_tpu.serving.replica import ServingReplica

    cfg = get_config("tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
                     vocab_size=32, max_seq=64)
    params = decoder.init(jax.random.key(0), cfg)
    # paced like a fixed-rate host so the burst actually queues (see
    # GenerationServer.step_period_s) and the breach window is real
    kw = dict(n_slots=2, max_len=32, page_size=4, mode="bf16",
              prefill_chunk=4, idle_sleep=0.001, step_period_s=0.02)
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(1, 32, size=5)) for _ in range(10)]

    def run(n_start, autoscale):
        reps, router = _drill_fleet(n_start, cfg, params, kw)
        spare = scaler = None
        try:
            n_warm = _warm(router, 32, np)
            if autoscale:
                spare = ServingReplica(
                    "as-spare", params, cfg, node_id=9, **kw
                ).start()
                spare.server.generate(list(np.arange(20) % 4 + 1), 3,
                                      timeout=600.0)
                scaler = ServingAutoScaler(
                    router,
                    ServingScalerConfig(
                        queue_depth_high=2, cooldown_s=1.0,
                        max_replicas=2, shrink_after_clear=10**6,
                        interval_s=0.02,
                    ),
                    provision_fn=lambda role: spare,
                ).start()
            reqs = [router.submit(p, 6) for p in prompts]
            outs = router.wait_all(timeout=600.0)[n_warm:]
            if scaler is not None:
                deadline = time.monotonic() + 10.0
                while (time.monotonic() < deadline
                       and scaler.last_restore_s <= 0.0):
                    time.sleep(0.02)
                scaler.stop()
            return outs, router, scaler, reqs
        finally:
            if scaler is not None:
                scaler.stop()
            router.close()
            for r in reps + ([spare] if spare is not None else []):
                r.stop()

    refs, _, _, _ = run(2, False)
    outs, router, scaler, reqs = run(1, True)
    assert outs == refs  # scaling changed WHERE, never WHAT
    out_decs = [d for d in scaler.decisions if d.direction == "out"]
    assert len(out_decs) == 1
    assert out_decs[0].signal == "queue_depth"
    assert out_decs[0].n_after == 2
    # the breach latched at the burst and cleared after the scale-out:
    # that edge pair IS "p99 restored" as the fleet measured it
    assert scaler.last_restore_s > 0.0
    assert all(r.future.done() for r in reqs)


@pytest.mark.slow
def test_scale_in_drains_live_zero_loss_and_detached_is_not_dead():
    """Drill (b) + the ``detached`` regression: a planned scale-in
    mid-decode evacuates the victim over the live-migration wire (zero
    lost, zero duplicated, zero re-prefilled), and the detached victim
    is never re-counted dead — no spurious failover migration fires."""
    import numpy as np

    import jax.numpy as jnp
    from dlrover_tpu.models import decoder, generate
    from dlrover_tpu.models.config import get_config

    cfg = get_config("tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
                     vocab_size=32, max_seq=64)
    params = decoder.init(jax.random.key(0), cfg)
    # paced steps keep the victim MID-decode at the remove_replica call
    # (an unpaced tiny engine finishes the whole trace in milliseconds);
    # 4 slots so the survivor has room to IMPORT the victim's two live
    # slots next to its own two — that is what keeps the drain on the
    # live wire instead of the re-prefill fallback
    kw = dict(n_slots=4, max_len=32, page_size=4, mode="bf16",
              prefill_chunk=4, idle_sleep=0.001, step_period_s=0.05)
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(1, 32, size=n)) for n in (3, 7, 5, 9)]
    max_new = [14, 12, 14, 12]
    refs = [
        [int(t) for t in np.asarray(generate.greedy(
            params, cfg, jnp.asarray([p], jnp.int32), m)[0])]
        for p, m in zip(prompts, max_new)
    ]

    reps, router = _drill_fleet(2, cfg, params, kw)
    try:
        n_warm = _warm(router, 32, np)
        reqs = [router.submit(p, m) for p, m in zip(prompts, max_new)]
        time.sleep(0.4)  # paced engines are now mid-decode
        victim = reps[1]
        report = router.remove_replica(victim, reason="autoscale")
        assert report is not None and report.path == "live"
        assert report.placements  # live-migrated in-flight slots
        assert report.re_prefilled == {}  # zero re-prefill on scale-in
        # detached ≠ dead: the failover sweep must not touch the victim
        assert router.is_detached(victim)
        assert not victim.server.alive  # drained and stopped
        n_reports = len(router.reports)
        assert router.poll() == 0
        assert len(router.reports) == n_reports  # no spurious migration
        assert router.live_replicas() == [reps[0]]
        outs = router.wait_all(timeout=600.0)[n_warm:]
    finally:
        router.close()
        for r in reps:
            r.stop()

    assert outs == refs  # zero lost, and bitwise through the drain
    # zero duplicated: every request completed exactly once fleet-wide
    done = sum(r.server.scheduler.completed for r in reps) - n_warm
    assert done == len(refs)
    # zero re-prefilled: the survivor never re-admitted a drained slot
    assert reps[0].server.scheduler.re_admitted == 0


@pytest.mark.slow
def test_live_oscillating_load_one_decision_per_cooldown():
    """Drill (c): repeated burst/drain episodes against a live fleet.
    With the breach latched and the cooldown window open, the scaler
    makes at most ONE actionable decision per window no matter how
    often the queue signal flaps."""
    import numpy as np

    from dlrover_tpu.models import decoder
    from dlrover_tpu.models.config import get_config
    from dlrover_tpu.serving.replica import ServingReplica

    cfg = get_config("tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
                     vocab_size=32, max_seq=64)
    params = decoder.init(jax.random.key(0), cfg)
    kw = dict(n_slots=2, max_len=32, page_size=4, mode="bf16",
              prefill_chunk=4, idle_sleep=0.001)
    rng = np.random.default_rng(11)
    reps, router = _drill_fleet(1, cfg, params, kw)
    spare = ServingReplica("as-sp", params, cfg, node_id=9, **kw).start()
    scaler = ServingAutoScaler(
        router,
        ServingScalerConfig(
            queue_depth_high=2, cooldown_s=60.0, max_replicas=2,
            shrink_after_clear=10**6,
        ),
        provision_fn=lambda role: spare,
    )
    try:
        _warm(router, 32, np)
        spare.server.generate(list(np.arange(20) % 4 + 1), 3,
                              timeout=600.0)
        for _ in range(3):  # three burst → drain oscillations
            for _ in range(6):
                router.submit(list(rng.integers(1, 32, size=4)), 4)
            for _ in range(10):
                scaler.step()
                time.sleep(0.02)
            router.wait_all(timeout=600.0)
            for _ in range(3):
                scaler.step()
        n_dec = sum(1 for d in scaler.decisions if d.direction)
        assert n_dec == 1  # one 60s cooldown window covers the drill
        assert len(router.live_replicas()) == 2
    finally:
        scaler.stop()
        router.close()
        for r in reps + [spare]:
            r.stop()
