"""dlrover-tpu-operator: deployable packaging (VERDICT r3 #3).

Reference: dlrover/go/operator — main.go (manager entrypoint + leader
election) and config/ (crd/, rbac/, manifests/). Covered here: the
manifest set under deploy/ renders and matches what the code serves,
the controller fan-out (one JobReconciler per ElasticJob, master
pod + Service first), the ConfigMap lease, and the entrypoint main loop
driven against the wire-level API server.
"""

import os
import threading
import time

import pytest
import yaml

from dlrover_tpu.cluster import crd
from dlrover_tpu.cluster.crd import (
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    TPUSliceSpec,
)
from dlrover_tpu.cluster.kube import JOB_LABEL, FakeKubeApi
from dlrover_tpu.cluster.operator import (
    LeaderElector,
    OperatorController,
    parse_operator_args,
    run_operator,
)

DEPLOY = os.path.join(os.path.dirname(__file__), "..", "deploy")


def _job(name="demo", replicas=2, max_hosts=4):
    return ElasticJob(
        name,
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=replicas,
                    slice=TPUSliceSpec(hosts_per_slice=1),
                    env={"FOO": "bar"},
                )
            },
            min_hosts=1,
            max_hosts=max_hosts,
        ),
    )


def _wait(cond, timeout=8.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _docs(path):
    with open(os.path.join(DEPLOY, path)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def test_deploy_manifests_render_and_match_the_code():
    crds = _docs("crd.yaml")
    names = {d["spec"]["names"]["kind"]: d for d in crds}
    assert set(names) == {"ElasticJob", "ScalePlan"}
    for kind, d in names.items():
        assert d["spec"]["group"] == crd.GROUP
        versions = [v["name"] for v in d["spec"]["versions"]]
        assert crd.VERSION in versions
        # the plural must match the REST path RealKubeApi uses
        from dlrover_tpu.cluster.kube_http import _BUILTIN_PATHS

        prefix, plural = _BUILTIN_PATHS[kind]
        assert d["spec"]["names"]["plural"] == plural
        assert prefix == f"/apis/{crd.GROUP}/{crd.VERSION}"

    rbac = _docs("rbac.yaml")
    kinds = {d["kind"] for d in rbac}
    assert {"ServiceAccount", "ClusterRole", "ClusterRoleBinding"} <= kinds
    role = next(d for d in rbac if d["kind"] == "ClusterRole")
    covered = {}
    for rule in role["rules"]:
        for res in rule["resources"]:
            covered.setdefault(res, set()).update(rule["verbs"])
    # everything cluster/operator.py + JobReconciler touch
    assert {"create", "delete", "list", "watch"} <= covered["pods"]
    assert {"create", "get", "update"} <= covered["configmaps"]
    assert {"list", "watch"} <= covered["elasticjobs"]
    assert "watch" in covered["scaleplans"]

    dep_docs = _docs("operator.yaml")
    dep = next(d for d in dep_docs if d["kind"] == "Deployment")
    spec = dep["spec"]["template"]["spec"]
    sa = next(d for d in rbac if d["kind"] == "ServiceAccount")
    assert spec["serviceAccountName"] == sa["metadata"]["name"]
    assert spec["containers"][0]["command"][0] == "dlrover-tpu-operator"
    binding = next(d for d in rbac if d["kind"] == "ClusterRoleBinding")
    assert binding["subjects"][0]["namespace"] == (
        sa["metadata"]["namespace"]
    )
    assert dep["metadata"]["namespace"] == sa["metadata"]["namespace"]


def test_elasticjob_manifest_roundtrip():
    job = _job(replicas=3, max_hosts=8)
    back = ElasticJob.from_manifest(job.to_manifest())
    assert back.name == job.name
    assert back.spec.max_hosts == 8
    rs = back.spec.replica_specs["worker"]
    assert rs.replicas == 3
    assert rs.env["FOO"] == "bar"
    assert rs.slice.hosts_per_slice == 1
    assert rs.slice.chips_per_host == job.spec.replica_specs[
        "worker"
    ].slice.chips_per_host


def test_operator_controller_fans_out_reconcilers():
    """One controller, many jobs: each ElasticJob gets its master
    pod + Service and its worker pods with the master addr injected;
    DELETED tears the job's pods down."""
    api = FakeKubeApi()
    ctl = OperatorController(api)
    ctl.start()
    try:
        api.create(_job("j1", replicas=2).to_manifest())
        _wait(
            lambda: api.get("Pod", "j1-worker-1") is not None,
            msg="j1 workers",
        )
        assert api.get("Pod", "j1-master") is not None
        assert api.get("Service", "j1-master") is not None
        env = {
            e["name"]: e.get("value", "")
            for e in api.get("Pod", "j1-worker-0")["spec"]["containers"][0][
                "env"
            ]
        }
        assert env["DLROVER_TPU_MASTER_ADDR"] == "j1-master.default.svc:8600"

        api.create(_job("j2", replicas=1).to_manifest())
        _wait(
            lambda: api.get("Pod", "j2-worker-0") is not None,
            msg="j2 worker",
        )
        assert ctl.jobs() == ["j1", "j2"]

        api.delete("ElasticJob", "j1")
        _wait(
            lambda: not api.list("Pod", label_selector={JOB_LABEL: "j1"}),
            msg="j1 pods torn down",
        )
        _wait(lambda: ctl.jobs() == ["j2"], msg="j1 reconciler removed")
        assert api.get("Pod", "j2-worker-0") is not None  # j2 untouched
    finally:
        ctl.stop()


def test_operator_relist_tears_down_jobs_deleted_during_watch_gap():
    """After a 410, the DELETED events inside the gap are unrecoverable:
    the relist must diff live reconcilers against the listed collection
    and tear down the vanished jobs' pods (otherwise they leak forever
    and a stale ScalePlan could scale a dead job back up)."""
    api = FakeKubeApi()
    ctl = OperatorController(api)
    api.create(_job("gap", replicas=1).to_manifest())
    since = ctl._adopt_current()
    assert ctl.jobs() == ["gap"]
    assert since > 0
    _wait(lambda: api.get("Pod", "gap-worker-0") is not None, msg="pod")
    # the job disappears while "the watch is down" (no controller loop
    # running to see the DELETED event)
    api.delete("ElasticJob", "gap")
    ctl._adopt_current()
    assert ctl.jobs() == []
    assert not api.list("Pod", label_selector={JOB_LABEL: "gap"})
    ctl.stop()


def test_master_command_carries_cluster_optimize_mode():
    """An optimizeMode=cluster job's master must actually be told to use
    the brain (VERDICT r3 #4 wiring meets the operator)."""
    from dlrover_tpu.cluster.operator import master_pod_manifest

    job = _job("br", replicas=1)
    job.spec.optimize_mode = "cluster"
    pod = master_pod_manifest(job, brain_addr="brain.svc:8600")
    cmd = pod["spec"]["containers"][0]["command"]
    assert "--optimize-mode" in cmd and "cluster" in cmd
    assert "--brain-addr" in cmd and "brain.svc:8600" in cmd
    # without a brain addr the flag is dropped (with a warning), not
    # emitted half-formed
    pod2 = master_pod_manifest(job)
    assert "--optimize-mode" not in pod2["spec"]["containers"][0]["command"]


def test_user_supplied_master_spec_gets_brain_flags():
    """A job that declares its OWN master replicaSpec must not have
    optimizeMode=cluster silently ignored (ADVICE r4): the operator
    appends the brain flags to the declared command — unless they are
    already there, which is respected verbatim."""
    from dlrover_tpu.cluster.crd import ReplicaSpec
    from dlrover_tpu.cluster.operator import master_pod_manifest

    job = _job("um", replicas=1)
    job.spec.optimize_mode = "cluster"
    job.spec.replica_specs["master"] = ReplicaSpec(
        replicas=1, command=["my-master", "--port", "8600"]
    )
    pod = master_pod_manifest(job, brain_addr="brain.svc:8600")
    cmd = pod["spec"]["containers"][0]["command"]
    assert cmd[:3] == ["my-master", "--port", "8600"]
    assert "--brain-addr" in cmd and "brain.svc:8600" in cmd
    # the caller's spec object is not mutated
    assert "--brain-addr" not in job.spec.replica_specs["master"].command
    # a command already carrying the flag is left alone
    job.spec.replica_specs["master"] = ReplicaSpec(
        replicas=1,
        command=["my-master", "--brain-addr", "other:1"],
    )
    pod2 = master_pod_manifest(job, brain_addr="brain.svc:8600")
    cmd2 = pod2["spec"]["containers"][0]["command"]
    assert cmd2.count("--brain-addr") == 1 and "other:1" in cmd2


def test_elasticjob_status_reflects_pod_phases():
    """The operator writes ElasticJob.status (phase + per-replica pod
    counts — what `kubectl get elasticjobs` shows via the CRD's printer
    columns), updating only on change so status writes can't feed back
    into the reconcile loop."""
    api = FakeKubeApi()
    ctl = OperatorController(api, status_interval_s=0.2)
    ctl.start()
    try:
        api.create(_job("st", replicas=2).to_manifest())
        _wait(
            lambda: (api.get("ElasticJob", "st") or {})
            .get("status", {})
            .get("phase")
            == "Pending",
            msg="pending status",
        )
        api.set_pod_phase("st-worker-0", "Running")
        _wait(
            lambda: api.get("ElasticJob", "st")["status"]["phase"]
            == "Running",
            msg="running status",
        )
        workers = api.get("ElasticJob", "st")["status"][
            "replicaStatuses"
        ]["worker"]
        assert workers.get("Running") == 1
        assert sum(workers.values()) == 2
        # the no-write-on-no-change guard: the stored rv stays put
        # while nothing changes (each write would bump it)
        rv1 = api.get("ElasticJob", "st")["metadata"]["resourceVersion"]
        time.sleep(0.8)
        rv2 = api.get("ElasticJob", "st")["metadata"]["resourceVersion"]
        assert rv1 == rv2, "status loop rewrites unchanged status"
        api.set_pod_phase("st-worker-0", "Failed", reason="OOMKilled")
        api.set_pod_phase("st-worker-1", "Failed", reason="OOMKilled")
        _wait(
            lambda: api.get("ElasticJob", "st")["status"]["phase"]
            == "Failed",
            msg="failed status",
        )
    finally:
        ctl.stop()


def test_operator_records_events_on_the_job():
    """`kubectl describe elasticjob` shows the reconcile trail
    (reference: the Go controller's EventRecorder): Reconciling on
    adopt, TornDown on delete, with the RBAC verb to match."""
    api = FakeKubeApi()
    ctl = OperatorController(api)
    api.create(_job("ev", replicas=1).to_manifest())
    ctl._adopt_current()
    events = api.list("Event", label_selector={JOB_LABEL: "ev"})
    assert [e["reason"] for e in events] == ["Reconciling"]
    assert events[0]["involvedObject"]["name"] == "ev"
    api.delete("ElasticJob", "ev")
    ctl._adopt_current()
    reasons = {
        e["reason"]
        for e in api.list("Event", label_selector={JOB_LABEL: "ev"})
    }
    assert reasons == {"Reconciling", "TornDown"}
    ctl.stop()
    role = next(
        d for d in _docs("rbac.yaml") if d["kind"] == "ClusterRole"
    )
    event_rules = [r for r in role["rules"] if "events" in r["resources"]]
    assert event_rules and "create" in event_rules[0]["verbs"]


def test_crd_printer_columns_point_at_real_fields():
    """kubectl's ElasticJob columns must reference fields the code
    actually writes (.status.phase) / the schema defines."""
    ej = next(
        d
        for d in _docs("crd.yaml")
        if d["spec"]["names"]["kind"] == "ElasticJob"
    )
    cols = {
        c["name"]: c["jsonPath"]
        for c in ej["spec"]["versions"][0]["additionalPrinterColumns"]
    }
    assert cols["Phase"] == ".status.phase"
    props = ej["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
        "properties"
    ]
    assert "minHosts" in props["spec"]["properties"]
    assert cols["Min"] == ".spec.minHosts"


def test_wire_token_minted_once_and_injected_into_pods():
    """Every pod of a job (workers AND master) references the SAME
    per-job wire-token Secret via secretKeyRef — never a plaintext env
    value (pods/get is granted far more broadly than secrets/get) —
    and the Secret survives operator restarts/leader failovers (a
    fresh token would partition new pods from old ones mid-job);
    teardown removes it."""

    def pod_env(api, name):
        return {
            e["name"]: e
            for e in api.get("Pod", name)["spec"]["containers"][0]["env"]
        }

    api = FakeKubeApi()
    ctl = OperatorController(api)
    api.create(_job("tok", replicas=2).to_manifest())
    ctl._adopt_current()
    _wait(lambda: api.get("Pod", "tok-worker-1") is not None, msg="pods")
    secret = api.get("Secret", "tok-wire-token")
    assert secret is not None
    token = secret["stringData"]["token"]
    assert len(token) >= 32
    for pod in ("tok-worker-0", "tok-worker-1", "tok-master"):
        env = pod_env(api, pod)
        ref = env["DLROVER_TPU_WIRE_TOKEN"]["valueFrom"]["secretKeyRef"]
        assert ref == {"name": "tok-wire-token", "key": "token"}, pod
        assert "value" not in env["DLROVER_TPU_WIRE_TOKEN"], (
            "token must never be a plaintext env value"
        )
        assert env["DLROVER_TPU_RUN_ID"]["value"] == "tok"
    ctl.stop()

    # a NEW controller (restart / failover) adopting the same job
    # reuses the minted Secret rather than partitioning the job
    ctl2 = OperatorController(api)
    ctl2._adopt_current()
    assert api.get("Secret", "tok-wire-token")["stringData"][
        "token"
    ] == token
    ctl2._teardown("tok")
    assert api.get("Secret", "tok-wire-token") is None
    ctl2.stop()


def test_leader_elector_acquire_renew_steal():
    api = FakeKubeApi()
    a = LeaderElector(api, identity="op-a", ttl_s=0.4)
    b = LeaderElector(api, identity="op-b", ttl_s=0.4)
    assert a.try_acquire()          # fresh lease
    assert not b.try_acquire()      # held and live
    assert a.try_acquire()          # renew own
    time.sleep(0.6)                 # let it go stale
    assert b.try_acquire()          # steal expired lease
    assert not a.try_acquire()      # a sees b's live lease


def test_health_endpoints_report_but_do_not_gate_on_leadership():
    """Both probes answer 200 while serving — readiness deliberately
    does NOT require leadership (a 503-ing standby would deadlock the
    2-replica Deployment's rolling updates: the surge pod can never go
    Ready while the old leader renews). The JSON body carries
    {leading} for humans."""
    import json
    import urllib.request

    from dlrover_tpu.cluster.operator import OperatorHealthServer

    api = FakeKubeApi()
    ctl = OperatorController(api)
    state = {"leading": False}
    health = OperatorHealthServer(
        ctl, lambda: state["leading"], port=0
    )
    health.start()
    try:
        base = f"http://127.0.0.1:{health.port}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read())["leading"] is False
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            assert r.status == 200  # standby is still Ready
            assert json.loads(r.read())["leading"] is False
        state["leading"] = True
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read())["leading"] is True
    finally:
        health.stop()
        ctl.stop()


def test_deployment_probes_match_health_server():
    """The Deployment's probe paths/port must match what the operator
    serves (a renamed flag or path would pass YAML validation and fail
    only in the cluster)."""
    dep = next(
        d for d in _docs("operator.yaml") if d["kind"] == "Deployment"
    )
    cont = dep["spec"]["template"]["spec"]["containers"][0]
    cmd = cont["command"]
    assert "--health-port" in cmd
    port = int(cmd[cmd.index("--health-port") + 1])
    named = {p["name"]: p["containerPort"] for p in cont["ports"]}
    assert named["health"] == port
    assert cont["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert cont["readinessProbe"]["httpGet"]["path"] == "/readyz"


@pytest.mark.slow
def test_operator_entrypoint_main_loop_over_http():
    """Drive the REAL entrypoint body (argparse → RealKubeApi →
    election → controller) against the wire-level API server from
    test_kube_http; an ElasticJob applied by a separate client turns
    into pods."""
    from test_kube_http import _KubeHandler
    from http.server import ThreadingHTTPServer

    from dlrover_tpu.cluster.kube_http import RealKubeApi

    fake = FakeKubeApi()
    handler = type("H", (_KubeHandler,), {"fake": fake})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    server.daemon_threads = True
    server.seen_watch_rvs = []
    st = threading.Thread(target=server.serve_forever, daemon=True)
    st.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        args = parse_operator_args(
            ["--kube-url", url, "--token", "test-token",
             "--lease-ttl", "2", "--health-port", "0"]
        )
        stop = threading.Event()
        op = threading.Thread(
            target=run_operator, args=(args,), kwargs={"stop": stop},
            daemon=True,
        )
        op.start()
        client = RealKubeApi(url, token="test-token")
        client.create(_job("wired", replicas=2).to_manifest())
        _wait(
            lambda: client.get("Pod", "wired-worker-1") is not None,
            timeout=12.0,
            msg="operator created workers over HTTP",
        )
        assert client.get("Pod", "wired-master") is not None
        # the lease exists and is held by this operator instance
        lease = client.get("ConfigMap", "dlrover-tpu-operator-leader")
        assert lease and lease["data"]["holder"]
        stop.set()
        op.join(timeout=10)
        assert not op.is_alive()
    finally:
        server.shutdown()
        server.server_close()
