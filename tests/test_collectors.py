"""Agent diagnosis collector tests.

Reference behaviors: elastic_agent/diagnosis/datacollector — logs,
process state, stuck-worker stack dumps.
"""

import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu.agent.collectors import (
    CollectorRunner,
    LogCollector,
    ProcStateCollector,
    StackCollector,
)


def test_log_collector_tails(tmp_path):
    log = tmp_path / "worker.log"
    log.write_text("\n".join(f"line {i}" for i in range(500)))
    c = LogCollector(str(log), max_lines=100)
    data = c.collect()
    lines = data.content.splitlines()
    assert len(lines) == 100
    assert lines[-1] == "line 499"


def test_log_collector_missing_file():
    c = LogCollector("/nonexistent/x.log")
    assert not c.is_enabled()
    assert c.collect() is None


def test_proc_state_collector_self():
    c = ProcStateCollector(os.getpid())
    data = c.collect()
    assert data is not None
    assert "State" in data.content and "Threads" in data.content


def test_proc_state_collector_dead_pid():
    assert ProcStateCollector(2**22 - 1).collect() is None


def test_stack_collector_dumps_child_stacks(tmp_path):
    """End-to-end: child installs the SIGUSR2 handler (as agent-launched
    workers do), parent collects a py-level stack while it hangs."""
    code = (
        "from dlrover_tpu.agent.collectors import StackCollector\n"
        "import time\n"
        "StackCollector.install_in_worker()\n"
        "def obvious_hang_marker():\n"
        "    time.sleep(60)\n"
        "obvious_hang_marker()\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        env={**os.environ, "PYTHONPATH": os.getcwd()},
    )
    try:
        time.sleep(2.0)  # let the handler install
        c = StackCollector(proc.pid, timeout=10.0)
        data = c.collect()
        assert data is not None
        assert "obvious_hang_marker" in data.content
    finally:
        proc.kill()
        proc.wait()


def test_runner_skips_disabled_and_collects_rest(tmp_path):
    log = tmp_path / "a.log"
    log.write_text("hello\n")
    runner = CollectorRunner()
    runner.register(LogCollector(str(log)))
    runner.register(LogCollector("/nonexistent.log"))
    runner.register(ProcStateCollector(os.getpid()))
    out = runner.collect_all()
    types = {d.data_type for d in out}
    assert types == {"training_log", "proc_state"}
