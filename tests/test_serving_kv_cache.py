"""Paged KV cache invariants (serving/kv_cache.py).

Property test over random admit/grow/evict/share/cow/reserve/commit/
abort traces: refcount conservation — every physical page's rc equals
the number of (slot, logical) table cells mapping it — the trash page
is never handed out, eviction decrements and frees only rc==0 pages,
and free + assigned-unique + migration-reserved stays a partition of
pages 1..n_pages-1 at every step. Device-side: bf16 pages round-trip
bitwise, int8 pages round-trip within the per-block scale bound, and
the int8 geometry's resident bytes beat bf16 by ≥1.7×.
"""

from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.ops import quant  # noqa: E402
from dlrover_tpu.serving import kv_cache as kvc  # noqa: E402


def _cfg(**kw):
    base = dict(
        n_layer=2, d_model=32, d_ff=64, n_head=4, vocab_size=32, max_seq=64
    )
    base.update(kw)
    return get_config("tiny", **base)


def _check_partition(alloc, geom):
    """Refcount conservation + partition: every page's rc equals the
    number of (slot, logical) cells mapping it, and free +
    assigned-unique (rc ≥ 1) + reserved partitions pages 1..n_pages-1,
    trash excluded."""
    cells = Counter(
        int(p) for row in alloc._tables for p in row if p >= 0
    )
    for page in range(geom.n_pages):
        assert alloc.refcount(page) == cells.get(page, 0), page
    reserved = [int(p) for ps in alloc._reserved.values() for p in ps]
    assigned = set(cells)
    assert kvc.TRASH_PAGE not in assigned, "trash page handed out"
    assert kvc.TRASH_PAGE not in reserved, "trash page reserved"
    assert len(reserved) == len(set(reserved)), "double-reserved page"
    assert not assigned & set(reserved), "reserved page is mapped"
    free = set(alloc._free)
    assert len(alloc._free) == len(free), "duplicate free-list entry"
    universe = set(range(1, geom.n_pages))
    assert assigned | set(reserved) | free == universe
    assert not free & assigned and not free & set(reserved)
    assert alloc.reserved_pages == len(reserved)
    assert alloc.unique_assigned_pages == len(assigned)


def test_allocator_random_trace_property():
    geom = kvc.make_geometry(
        _cfg(), n_slots=4, max_len=40, page_size=4, mode="int8"
    )
    alloc = kvc.PageAllocator(geom, 4)
    # on_free discipline: fires only for pages whose rc hit zero, and
    # those pages must be back on the free list when it fires
    def _on_free(pages):
        for p in pages:
            assert alloc.refcount(p) == 0
            assert p in alloc._free
    alloc.on_free = _on_free
    rng = np.random.default_rng(0)
    held = [0, 0, 0, 0]  # tokens covered per slot
    reservations = {}    # tag -> n_tokens reserved for migration
    tag_seq = 0
    for _ in range(400):
        slot = int(rng.integers(0, 4))
        op = rng.choice(
            ["admit", "grow", "evict", "share", "cow",
             "reserve", "commit", "abort"]
        )
        if op == "admit" and held[slot] == 0:
            n = int(rng.integers(1, geom.max_len + 5))
            before = alloc.free_pages
            ok = alloc.admit(slot, n)
            assert ok == (
                alloc.pages_needed(n) <= geom.max_pages_per_slot
                and alloc.pages_needed(n) <= before
            )
            if ok:
                held[slot] = n
        elif op == "grow" and held[slot] > 0:
            n = held[slot] + int(rng.integers(0, 8))
            before_free = alloc.free_pages
            before_pages = alloc.slot_pages(slot)
            ok = alloc.ensure(slot, n)
            if ok:
                held[slot] = max(held[slot], n)
            else:
                # failed growth must not leak or steal pages
                assert alloc.free_pages == before_free
                assert alloc.slot_pages(slot) == before_pages
        elif op == "evict":
            # with sharing live this is the RELEASE op: rc−1 per cell,
            # only rc==0 pages return to the free list — a sharer's
            # eviction must never free a sharee's pages
            n_pages = alloc.slot_pages(slot)
            shared_out = sum(
                1
                for p in alloc._tables[slot, :n_pages]
                if alloc.refcount(int(p)) > 1
            )
            before_free = alloc.free_pages
            freed = alloc.evict(slot)
            assert freed == n_pages  # cell count, sharing-invisible
            assert alloc.free_pages == before_free + n_pages - shared_out
            held[slot] = 0
            assert alloc.slot_pages(slot) == 0
        elif op == "share" and held[slot] == 0:
            donors = [
                d for d in range(4) if d != slot and alloc.slot_pages(d)
            ]
            if not donors:
                continue
            donor = donors[int(rng.integers(0, len(donors)))]
            m = int(rng.integers(1, alloc.slot_pages(donor) + 1))
            prefix = [int(p) for p in alloc.block_tables()[donor, :m]]
            n = int(rng.integers(m * geom.page_size, geom.max_len + 5))
            before = alloc.free_pages
            rc_before = [alloc.refcount(p) for p in prefix]
            need = alloc.pages_needed(n)
            ok = alloc.admit_shared(slot, n, prefix)
            assert ok == (
                need <= geom.max_pages_per_slot
                and need - m <= before
            )
            if ok:
                held[slot] = n
                assert alloc.free_pages == before - (need - m)
                for p, rc in zip(prefix, rc_before):
                    assert alloc.refcount(p) == rc + 1
            else:
                assert alloc.free_pages == before
                for p, rc in zip(prefix, rc_before):
                    assert alloc.refcount(p) == rc
        elif op == "cow" and held[slot] > 0:
            logical = int(rng.integers(0, alloc.slot_pages(slot)))
            src = int(alloc.block_tables()[slot, logical])
            if alloc.refcount(src) == 1:
                assert alloc.cow_page(slot, logical) is None
            elif alloc.free_pages == 0:
                with pytest.raises(RuntimeError):
                    alloc.cow_page(slot, logical)
            else:
                rc_src = alloc.refcount(src)
                got_src, dst = alloc.cow_page(slot, logical)
                assert got_src == src
                assert alloc.refcount(src) == rc_src - 1
                assert alloc.refcount(dst) == 1
                assert int(alloc.block_tables()[slot, logical]) == dst
        elif op == "reserve":
            tag = f"mig-{tag_seq}"
            tag_seq += 1
            n = int(rng.integers(1, geom.max_len + 5))
            before = alloc.free_pages
            ok = alloc.reserve_for_migration(tag, n)
            assert ok == (
                alloc.pages_needed(n) <= geom.max_pages_per_slot
                and alloc.pages_needed(n) <= before
            )
            if ok:
                reservations[tag] = n
                assert len(alloc.reservation(tag)) == alloc.pages_needed(n)
            else:
                # failed reservation must not leak pages or leave a tag
                assert alloc.free_pages == before
                assert alloc.reservation(tag) == ()
        elif op == "commit" and reservations and held[slot] == 0:
            tag = next(iter(reservations))
            n = reservations.pop(tag)
            pages = alloc.commit_migration(tag, slot)
            assert len(pages) == alloc.pages_needed(n)
            assert alloc.slot_pages(slot) == len(pages)
            held[slot] = n
        elif op == "abort" and reservations:
            tag = next(iter(reservations))
            n = reservations.pop(tag)
            before = alloc.free_pages
            freed = alloc.abort_migration(tag)
            assert freed == alloc.pages_needed(n)
            assert alloc.free_pages == before + freed
        _check_partition(alloc, geom)
    # drain: after aborting/evicting everything the free list is whole
    for tag in list(reservations):
        alloc.abort_migration(tag)
    for s in range(4):
        alloc.evict(s)
    assert alloc.free_pages == geom.n_pages - 1
    assert alloc.reserved_pages == 0
    _check_partition(alloc, geom)


def test_reserve_commit_abort_edges():
    geom = kvc.make_geometry(
        _cfg(), n_slots=2, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.reserve_for_migration("a", 9)
    with pytest.raises(ValueError):        # duplicate tag
        alloc.reserve_for_migration("a", 1)
    with pytest.raises(KeyError):          # unknown tag
        alloc.commit_migration("ghost", 0)
    assert alloc.admit(0, 5)
    with pytest.raises(ValueError):        # occupied slot
        alloc.commit_migration("a", 0)
    pages = alloc.commit_migration("a", 1)
    assert len(pages) == alloc.pages_needed(9) == alloc.slot_pages(1)
    assert alloc.abort_migration("ghost") == 0   # abort is idempotent
    _check_partition(alloc, geom)


def test_admit_rejects_nonempty_slot():
    geom = kvc.make_geometry(
        _cfg(), n_slots=2, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.admit(0, 5)
    with pytest.raises(ValueError):
        alloc.admit(0, 3)


def test_bf16_pages_roundtrip_bitwise():
    cfg = _cfg()
    geom = kvc.make_geometry(
        cfg, n_slots=2, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.admit(0, 9) and alloc.admit(1, 6)
    pools = kvc.init_pools(geom)
    tables = jnp.asarray(alloc.block_tables())
    L, B, C = cfg.n_layer, 2, 3
    shape = (L, B, C, cfg.kv_heads, cfg.head_dim)
    k = jax.random.normal(jax.random.key(1), shape).astype(jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), shape).astype(jnp.bfloat16)
    positions = jnp.array([[0, 4, 8], [1, 3, 5]], jnp.int32)
    valid = jnp.ones((B, C), bool)
    pools = kvc.write_rows(pools, tables, positions, valid, k, v, geom)
    got = kvc.gather(pools, tables, geom)
    for b in range(B):
        for ci in range(C):
            pos = int(positions[b, ci])
            np.testing.assert_array_equal(
                np.asarray(got["k"][:, b, pos]), np.asarray(k[:, b, ci])
            )
            np.testing.assert_array_equal(
                np.asarray(got["v"][:, b, pos]), np.asarray(v[:, b, ci])
            )


def test_int8_pages_roundtrip_within_scale_bound():
    cfg = _cfg()
    geom = kvc.make_geometry(
        cfg, n_slots=2, max_len=16, page_size=4, mode="int8"
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.admit(0, 8) and alloc.admit(1, 8)
    pools = kvc.init_pools(geom)
    tables = jnp.asarray(alloc.block_tables())
    L, B, C = cfg.n_layer, 2, 4
    shape = (L, B, C, cfg.kv_heads, cfg.head_dim)
    k = jax.random.normal(jax.random.key(3), shape).astype(jnp.float32)
    v = jax.random.normal(jax.random.key(4), shape).astype(jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (B, C))
    valid = jnp.ones((B, C), bool)
    pools = kvc.write_rows(pools, tables, positions, valid, k, v, geom)
    got = kvc.gather(pools, tables, geom)
    row = geom.row_elems
    for b in range(B):
        for ci in range(C):
            ref = np.asarray(k[:, b, ci], np.float32).reshape(L, row)
            dec = np.asarray(
                got["k"][:, b, ci], np.float32
            ).reshape(L, row)
            # per-block bound: quantization error ≤ scale/2 + bf16
            # rounding of the dequantized value
            blocks = ref.reshape(L, geom.n_blocks, geom.kv_block)
            scale = np.abs(blocks).max(-1, keepdims=True) / 127.0
            bound = np.broadcast_to(
                scale * 0.51 + 2e-2, blocks.shape
            ).reshape(L, row)
            assert (np.abs(ref - dec) <= bound).all()


def test_invalid_lanes_hit_trash_page_only():
    cfg = _cfg()
    geom = kvc.make_geometry(
        cfg, n_slots=2, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.admit(0, 8)
    pools = kvc.init_pools(geom)
    tables = jnp.asarray(alloc.block_tables())
    L, B, C = cfg.n_layer, 2, 2
    shape = (L, B, C, cfg.kv_heads, cfg.head_dim)
    k = jnp.ones(shape, jnp.bfloat16)
    v = jnp.ones(shape, jnp.bfloat16)
    positions = jnp.zeros((B, C), jnp.int32)
    # slot 1 has NO pages (table row all -1) and is fully invalid
    valid = jnp.array([[True, True], [False, False]])
    pools = kvc.write_rows(pools, tables, positions, valid, k, v, geom)
    # every allocated page except slot 0's first stays zero
    pool_k = np.asarray(pools["k"], np.float32)
    slot0_page = int(alloc.block_tables()[0, 0])
    for page in range(1, geom.n_pages):
        if page == slot0_page:
            continue
        assert (pool_k[:, page] == 0).all(), page


def test_consume_dirty_true_once_per_mutation():
    geom = kvc.make_geometry(
        _cfg(), n_slots=2, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.consume_dirty()       # fresh tables must ship once
    assert not alloc.consume_dirty()   # ...and only once
    assert alloc.admit(0, 5)
    assert alloc.consume_dirty()
    assert not alloc.consume_dirty()
    assert alloc.ensure(0, 6)          # covered already: no new page
    assert not alloc.consume_dirty()
    assert alloc.ensure(0, 9)          # grows by a page
    assert alloc.consume_dirty()
    assert alloc.evict(1) == 0         # empty slot: nothing changed
    assert not alloc.consume_dirty()
    assert alloc.evict(0) == 3
    assert alloc.consume_dirty()


def test_block_tables_snapshot_cached_until_mutation():
    """The common no-mutation step must not pay a full-array copy:
    ``block_tables()`` returns the SAME snapshot until the allocator
    mutates, and an old snapshot never aliases the live buffer."""
    geom = kvc.make_geometry(
        _cfg(), n_slots=2, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 2)
    t1 = alloc.block_tables()
    assert alloc.block_tables() is t1        # cached, no re-copy
    assert alloc.admit(0, 5)
    t2 = alloc.block_tables()
    assert t2 is not t1                      # mutation invalidates
    assert (t1 == -1).all()                  # old snapshot frozen
    assert alloc.block_tables() is t2
    # the cache is independent of consume_dirty: the engine draining
    # the dirty flag must not force the next block_tables() to copy
    assert alloc.consume_dirty()
    assert alloc.block_tables() is t2
    assert alloc.evict(0) == 2
    t3 = alloc.block_tables()
    assert t3 is not t2 and int(t2[0, 0]) >= 0
    # cow + shared admission invalidate too (table cells change)
    assert alloc.admit(0, 5)
    row = [int(p) for p in alloc.block_tables()[0, :1]]
    t4 = alloc.block_tables()
    assert alloc.admit_shared(1, 4, row)
    assert alloc.block_tables() is not t4
    t5 = alloc.block_tables()
    assert alloc.cow_page(1, 0) is not None
    assert alloc.block_tables() is not t5


def test_share_and_cow_edges():
    geom = kvc.make_geometry(
        _cfg(), n_slots=3, max_len=16, page_size=4, mode="bf16"
    )
    alloc = kvc.PageAllocator(geom, 3)
    assert alloc.admit(0, 16)
    row = [int(p) for p in alloc.block_tables()[0]]
    with pytest.raises(ValueError):   # occupied slot
        alloc.admit_shared(0, 8, row[:1])
    with pytest.raises(ValueError):   # prefix longer than footprint
        alloc.admit_shared(1, 4, row[:3])
    with pytest.raises(ValueError):   # trash page is never shareable
        alloc.admit_shared(1, 8, [kvc.TRASH_PAGE])
    free = alloc.free_pages
    with pytest.raises(ValueError):   # dead page is not shareable
        alloc.admit_shared(1, 8, [alloc._free[-1]])
    assert alloc.admit_shared(1, 16, row)   # full-row share: no fresh
    assert alloc.free_pages == free
    assert all(alloc.refcount(p) == 2 for p in row)
    with pytest.raises(ValueError):   # no such logical page
        alloc.cow_page(2, 0)
    _check_partition(alloc, geom)


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_partial_gather_bitwise_equals_sliced_full(mode):
    """gather(max_pages=W) must equal the first W·page_size positions
    of the full gather BITWISE — held pages are a table prefix, so the
    narrower gather only drops -1-clamped trash."""
    cfg = _cfg()
    geom = kvc.make_geometry(
        cfg, n_slots=2, max_len=32, page_size=4, mode=mode
    )
    alloc = kvc.PageAllocator(geom, 2)
    assert alloc.admit(0, 9) and alloc.admit(1, 14)
    pools = kvc.init_pools(geom)
    tables = jnp.asarray(alloc.block_tables())
    L, B, C = cfg.n_layer, 2, 14
    shape = (L, B, C, cfg.kv_heads, cfg.head_dim)
    k = jax.random.normal(jax.random.key(11), shape).astype(jnp.bfloat16)
    v = jax.random.normal(jax.random.key(12), shape).astype(jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (B, C))
    valid = jnp.asarray(
        np.arange(C)[None, :] < np.asarray([9, 14])[:, None]
    )
    pools = kvc.write_rows(pools, tables, positions, valid, k, v, geom)
    held = max(alloc.slot_pages(0), alloc.slot_pages(1))
    full = kvc.gather(pools, tables, geom)
    part = kvc.gather(pools, tables, geom, max_pages=held)
    width = held * geom.page_size
    for key in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(part[key]),
            np.asarray(full[key][:, :, :width]),
        )


def test_resident_bytes_reduction_vs_bf16():
    for d_model, n_head in ((32, 4), (64, 4), (128, 8)):
        cfg = _cfg(d_model=d_model, n_head=n_head)
        g8 = kvc.make_geometry(
            cfg, n_slots=2, max_len=32, page_size=8, mode="int8"
        )
        g16 = g8._replace(mode="bf16")
        ratio = kvc.resident_bytes(g16) / kvc.resident_bytes(g8)
        assert ratio >= 1.7, (d_model, ratio)


def test_decode_traffic_model_asymptotics():
    """The bench's HBM model: paged traffic scales with pages held and
    stays below the gather cost, which is O(S_max) and independent of
    what is actually resident."""
    geom = kvc.make_geometry(
        _cfg(), n_slots=4, max_len=256, page_size=8, mode="int8"
    )
    few = kvc.decode_traffic_bytes(geom, 8, 4, paged=True)
    many = kvc.decode_traffic_bytes(geom, 64, 4, paged=True)
    gather = kvc.decode_traffic_bytes(geom, 8, 4, paged=False)
    assert 0 < few < many < gather
    # gather cost ignores pages_held entirely — full table width
    assert gather == kvc.decode_traffic_bytes(geom, 64, 4, paged=False)


def test_kv_block_size_divides_rows():
    for row in (8, 32, 96, 128, 256, 320, 384, 1024):
        blk = quant.kv_block_size(row)
        assert 1 <= blk <= 256
        assert row % blk == 0


def test_geometry_validates_mode():
    with pytest.raises(ValueError):
        kvc.make_geometry(_cfg(), n_slots=1, max_len=8, mode="fp4")
