"""Elastic sampler / dataloader / trainer tests."""

import numpy as np
import pytest

from dlrover_tpu.elastic import (
    ElasticDataLoader,
    ElasticDistributedSampler,
    ElasticTrainer,
)


def test_sampler_partition_disjoint_and_complete():
    n = 100
    replicas = 4
    seen = []
    for rank in range(replicas):
        s = ElasticDistributedSampler(
            n, num_replicas=replicas, rank=rank, shuffle=True, seed=7
        )
        seen.extend(list(s))
    assert sorted(set(seen)) == list(range(n))


def test_sampler_resume_different_world_size():
    n = 64
    # 4 replicas consume 2 steps of per-replica batch 4 → 32 samples done
    s0 = ElasticDistributedSampler(n, num_replicas=4, rank=0, shuffle=True)
    s0.record_batch(4)
    s0.record_batch(4)
    state = s0.state_dict()

    # resume with 2 replicas: remaining 32 samples split between them
    remaining = []
    for rank in range(2):
        s = ElasticDistributedSampler(n, num_replicas=2, rank=rank, shuffle=True)
        s.load_state_dict(state)
        remaining.extend(list(s))
    assert len(remaining) == 32
    # completed samples are not replayed
    all_epoch = ElasticDistributedSampler(
        n, num_replicas=1, rank=0, shuffle=True
    )
    all_epoch.load_state_dict({**state, "completed": 0})
    first32 = list(all_epoch)[:32]
    assert not (set(first32) & set(remaining))


def test_sampler_resume_fuzz_covers_epoch_exactly_once():
    """Property: across RANDOM resume points and world-size changes, an
    epoch's samples are consumed exactly once — no replay, no loss.
    This is the contract a mid-epoch scale event depends on (reference:
    sampler.py state_dict/load_state_dict)."""
    import numpy as np

    rng = np.random.RandomState(3)
    for trial in range(10):
        n = int(rng.randint(40, 200))
        world = int(rng.choice([1, 2, 4, 8]))
        s0 = ElasticDistributedSampler(
            n, num_replicas=world, rank=0, shuffle=True, seed=trial
        )
        per_rank_total = len(list(s0))
        # consume a random number of whole batches
        bs = int(rng.randint(1, 8))
        steps = int(rng.randint(0, max(1, per_rank_total // bs)))
        consumed = []
        ranks = [
            ElasticDistributedSampler(
                n, num_replicas=world, rank=r, shuffle=True, seed=trial
            )
            for r in range(world)
        ]
        iters = [iter(list(r)) for r in ranks]
        for _ in range(steps):
            for r in range(world):
                for _ in range(bs):
                    consumed.append(next(iters[r]))
            ranks[0].record_batch(bs)
        state = ranks[0].state_dict()

        new_world = int(rng.choice([1, 2, 4]))
        resumed = []
        for r in range(new_world):
            s = ElasticDistributedSampler(
                n, num_replicas=new_world, rank=r, shuffle=True,
                seed=trial,
            )
            s.load_state_dict(state)
            resumed.extend(list(s))
        # padding may duplicate a few tail samples WITHIN one phase,
        # but nothing consumed before the scale event is replayed
        assert not (set(consumed) & set(resumed)), (
            f"trial {trial}: replayed "
            f"{sorted(set(consumed) & set(resumed))[:5]}"
        )
        # and together both phases cover the whole epoch exactly
        assert set(consumed) | set(resumed) == set(range(n)), (
            f"trial {trial} lost samples"
        )


def test_dataloader_with_sampler_and_reconfig(tmp_path):
    cfg_path = tmp_path / "paral.json"
    cfg_path.write_text('{"version": 1, "batch_size": 8}')
    sampler = ElasticDistributedSampler(
        64, num_replicas=1, rank=0, shuffle=False
    )
    loader = ElasticDataLoader(
        fetch_fn=lambda idx: {"x": idx},
        sampler=sampler,
        batch_size=4,
        config_path=str(cfg_path),
    )
    batches = list(loader)
    # re-config to 8 picked up at construction
    assert all(len(b["x"]) == 8 for b in batches)
    assert len(batches) == 8
    assert sampler.completed == 64


def test_elastic_trainer_grad_accum_follows_world():
    replicas = {"n": 8}
    built = []

    def build_step(accum):
        built.append(accum)
        return lambda state, batch: (state, {"accum": accum})

    t = ElasticTrainer(
        global_batch_size=64,
        micro_batch_size=2,
        build_step=build_step,
        data_replicas_fn=lambda: replicas["n"],
    )
    assert t.grad_accum == 4  # 64 / (2*8)
    _, m = t.step({}, {})
    assert m["accum"] == 4

    replicas["n"] = 4  # world shrank
    t.on_membership_change()
    assert t.grad_accum == 8  # 64 / (2*4)
    assert built == [4, 8]


def test_sampler_short_tail_pads_equally():
    """Tail shorter than the pad: every rank must still yield the same
    count (lockstep SPMD deadlocks otherwise)."""
    from dlrover_tpu.elastic.sampler import ElasticDistributedSampler

    counts = []
    for rank in range(4):
        s = ElasticDistributedSampler(
            dataset_size=10, num_replicas=4, rank=rank, shuffle=False
        )
        s.load_state_dict({"epoch": 0, "completed": 9})
        counts.append(len(list(iter(s))))
    assert len(set(counts)) == 1 and counts[0] >= 1


def test_compile_cache_dir_from_job_config(monkeypatch):
    """--compile-cache-dir (job config) overrides the per-user default
    AND an inherited operator env — the job's declared cache location
    must win everywhere (e.g. shared NFS so replacement hosts hit it)."""
    from dlrover_tpu.agent.agent import (
        ElasticLaunchConfig,
        ElasticTrainingAgent,
    )
    from dlrover_tpu.agent.launcher import parse_args
    from dlrover_tpu.agent.rendezvous import RendezvousOutcome

    args = parse_args(
        ["--compile-cache-dir", "/mnt/job-cache", "--", "python", "t.py"]
    )
    assert args.compile_cache_dir == "/mnt/job-cache"

    class _T:
        addr = "localhost:1"

    class _Client:
        _t = _T()
        node_rank = 0

    agent = ElasticTrainingAgent(
        ElasticLaunchConfig(compile_cache_dir="/mnt/job-cache"), _Client()
    )
    outcome = RendezvousOutcome(
        round=1, world={0: 1}, coordinator="localhost:7010",
        process_id=0, num_processes=1, global_chips=1,
    )
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/operator-env")
    env = agent._worker_env(outcome)
    assert env["JAX_COMPILATION_CACHE_DIR"] == "/mnt/job-cache"
    assert env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "1"


_CACHE_STEP_SCRIPT = """
import json, os, sys, time
import jax, jax.numpy as jnp
sys.path.insert(0, os.environ["DLROVER_TPU_TEST_REPO"])
from dlrover_tpu.models import get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.train import (
    TrainStepBuilder, batch_sharding, init_train_state, make_optimizer,
)

mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
cfg = get_config(
    "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
    vocab_size=256, max_seq=64,
)
opt = make_optimizer(learning_rate=1e-3, warmup_steps=2, decay_steps=10)
state = init_train_state(jax.random.key(0), cfg, mesh, opt)
step = TrainStepBuilder(cfg, mesh, opt).build()
tokens = jnp.zeros((8, 64), dtype=jnp.int32)
batch = jax.device_put(
    {"tokens": tokens, "targets": tokens}, batch_sharding(mesh)
)
t0 = time.time()
state, metrics = step(state, batch)
loss = float(metrics["loss"])
print(json.dumps({"loss": loss, "step_wall_s": time.time() - t0}))
"""


@pytest.mark.slow  # tier-1 budget: prewarm pins the executable fast
def test_restart_hits_persistent_compile_cache(tmp_path):
    """The re-mesh recovery story end-to-end (VERDICT r4 ask #2): the
    SAME sharded train step run in two fresh subprocesses against a
    shared cache dir — the first populates the cache, the second adds
    ZERO new entries (pure deserialization, i.e. a restart does not pay
    the compile again)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache = tmp_path / "jit-cache"
    cache.mkdir()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "JAX_COMPILATION_CACHE_DIR": str(cache),
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
            "DLROVER_TPU_TEST_REPO": repo,
        }
    )
    script = _CACHE_STEP_SCRIPT

    def run():
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env=env, cwd=repo, capture_output=True, text=True,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        import json as json_mod

        return json_mod.loads(proc.stdout.strip().splitlines()[-1])

    first = run()
    entries_after_first = {
        p.name for p in cache.rglob("*") if p.is_file()
    }
    assert entries_after_first, "first run populated no cache entries"
    second = run()
    entries_after_second = {
        p.name for p in cache.rglob("*") if p.is_file()
    }
    # the restart compiled NOTHING new — every executable came from the
    # shared cache
    assert entries_after_second == entries_after_first
    assert second["loss"] == pytest.approx(first["loss"], rel=1e-6)


def test_worker_env_sets_persistent_compile_cache(monkeypatch):
    """Restarted workers must share an XLA compile cache — the re-mesh
    recovery-time lever (SURVEY §7): same-shape restarts skip recompile."""
    from dlrover_tpu.agent.agent import (
        ElasticLaunchConfig,
        ElasticTrainingAgent,
    )
    from dlrover_tpu.agent.rendezvous import RendezvousOutcome

    class _T:
        addr = "localhost:1"

    class _Client:
        _t = _T()
        node_rank = 0

    agent = ElasticTrainingAgent(ElasticLaunchConfig(), _Client())
    outcome = RendezvousOutcome(
        round=1, world={0: 1}, coordinator="localhost:7010",
        process_id=0, num_processes=1, global_chips=1,
    )
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    env = agent._worker_env(outcome)
    assert env["JAX_COMPILATION_CACHE_DIR"]
    assert env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "1"
    # an operator-set cache dir wins (worker env inherits os.environ)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/custom")
    env = agent._worker_env(outcome)
    assert "JAX_COMPILATION_CACHE_DIR" not in env  # inherited, not forced


def test_comm_perf_test_reports_bandwidth():
    """--comm-perf-test sweep: positive GB/s per payload size on the
    8-device mesh, keyed by payload bytes."""
    from dlrover_tpu.agent.node_check import run_comm_perf_test

    res = run_comm_perf_test(sizes=(1 << 16, 1 << 18))
    # keys are the requested global element counts — per-device derived
    # byte sizes can collide between nearby requested sizes
    assert set(res) == {1 << 16, 1 << 18}
    assert all(v > 0 for v in res.values())
    # regression: sizes within a factor of device-count must not collide
    res2 = run_comm_perf_test(sizes=(1 << 16, 1 << 17))
    assert len(res2) == 2


@pytest.mark.slow
def test_prewarm_produces_the_exact_step_executable(tmp_path, monkeypatch):
    """Re-mesh pre-warming (SURVEY §7's 'pre-compile async where
    possible'): AOT-lowering the train step for a candidate world must
    produce the IDENTICAL persistent-cache entry the live job compiles
    — same content key — so a later re-mesh to that world deserializes
    instead of compiling. Proven by content-addressing: the largest
    entry a real run writes (the train-step executable) must already
    exist, byte-keyed, in a cache populated ONLY by prewarm."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # pin the AMBIENT env too: prewarm_worlds builds its child env from
    # os.environ, and cache keys embed XLA flags — an ambient
    # --xla_dump_to (common while debugging) would make the two
    # children's keys diverge for reasons unrelated to prewarm
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    monkeypatch.delenv("DLROVER_TPU_PREWARM_PLATFORM", raising=False)
    monkeypatch.setenv("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PYTHONPATH", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
            "DLROVER_TPU_TEST_REPO": repo,
        }
    )

    # NOTE: prewarm and the job must share ONE cache dir — this jax's
    # key embeds the cache path itself (the per-fusion autotune cache
    # dir rides in debug_options un-zeroed), so entries are only ever
    # portable within a directory. That matches production: the agent
    # points prewarm at the same dir it exports to workers.
    cache = tmp_path / "cache"
    cache.mkdir()

    # 1) prewarm ONLY (AOT — no arrays materialized) for the candidate
    #    world the job will later run at
    from dlrover_tpu.train.prewarm import prewarm_worlds

    ok = prewarm_worlds(
        "tiny",
        worlds=[{"n_devices": 8, "dp": 2, "fsdp": 2, "tp": 2}],
        batch_size=8,
        seq=64,
        model_kw=dict(n_layer=2, d_model=64, d_ff=128, n_head=4,
                      vocab_size=256, max_seq=64),
        opt_kw=dict(learning_rate=1e-3, warmup_steps=2, decay_steps=10),
        cache_dir=str(cache),
        timeout_s=600,
    )
    assert ok, "prewarm subprocess failed"
    prewarmed_steps = {
        p.name for p in cache.rglob("*jit_step_fn*") if p.is_file()
    }
    assert prewarmed_steps, "prewarm produced no train-step entry"

    # 2) the real job runs: its train step must be a pure cache HIT —
    #    no new jit_step_fn entry beyond what prewarm wrote
    env_run = dict(env, JAX_COMPILATION_CACHE_DIR=str(cache))
    proc = subprocess.run(
        [sys.executable, "-c", _CACHE_STEP_SCRIPT],
        env=env_run, cwd=repo, capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    steps_after = {
        p.name for p in cache.rglob("*jit_step_fn*") if p.is_file()
    }
    assert steps_after == prewarmed_steps, (
        "the live job compiled a train step the prewarm missed: "
        f"{sorted(steps_after - prewarmed_steps)}"
    )


def test_elastic_trainer_shrink_grow_keeps_global_batch():
    """8→6→8 hosts with global batch 48: grad_accum re-derives to 3→4→3
    and the EFFECTIVE batch — what the LR schedule sees — never moves."""
    from dlrover_tpu.observability import telemetry

    replicas = {"n": 8}
    telemetry.reset_hub()
    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append)
    try:
        t = ElasticTrainer(
            global_batch_size=48,
            micro_batch_size=2,
            build_step=lambda accum: (lambda s, b: (s, {})),
            data_replicas_fn=lambda: replicas["n"],
        )
        seen = [(t.grad_accum, t.grad_accum * 2 * replicas["n"])]
        for n in (6, 8):
            replicas["n"] = n
            t.on_membership_change()
            seen.append((t.grad_accum, t.grad_accum * 2 * n))
        assert seen == [(3, 48), (4, 48), (3, 48)]
        # no drift: the schedule's global batch was preserved throughout
        assert not [e for e in events if e.kind == "effective_batch_drift"]
    finally:
        telemetry.reset_hub()


def test_elastic_trainer_drift_published_as_numeric_event():
    """global=50 is not reachable with micro=2 × replicas=8: accum
    rounds up to 4 → effective 64. The +14 drift must surface as a
    NumericEvent, not just a log line."""
    from dlrover_tpu.observability import telemetry

    telemetry.reset_hub()
    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append)
    try:
        t = ElasticTrainer(
            global_batch_size=50,
            micro_batch_size=2,
            build_step=lambda accum: (lambda s, b: (s, {})),
            data_replicas_fn=lambda: 8,
        )
        assert t.grad_accum == 4
        drifts = [e for e in events if e.kind == "effective_batch_drift"]
        assert len(drifts) == 1
        assert isinstance(drifts[0], telemetry.NumericEvent)
        assert drifts[0].value == 14.0  # 64 - 50
        assert "effective=64" in drifts[0].detail
    finally:
        telemetry.reset_hub()


@pytest.mark.parametrize("drop_last", [False, True])
@pytest.mark.parametrize("shuffle", [False, True])
def test_sampler_mid_epoch_eviction_no_loss_no_dup(drop_last, shuffle):
    """Property: an eviction mid-epoch (num_replicas 8→6, every rank
    re-assigned) neither drops nor duplicates samples. drop_last=False
    may duplicate only pad indices (tail tiling for lockstep SPMD);
    drop_last=True may drop only a tail shorter than the new world."""
    rng = np.random.RandomState(11)
    for trial in range(12):
        n = int(rng.randint(50, 300))
        r1 = int(rng.choice([4, 6, 8]))
        r2 = int(rng.choice([2, 3, 4, 6]))
        bs = int(rng.randint(1, 5))
        steps = int(rng.randint(1, max(2, n // (bs * r1))))

        ranks1 = [
            ElasticDistributedSampler(
                n, num_replicas=r1, rank=r, shuffle=shuffle,
                seed=7, drop_last=drop_last,
            )
            for r in range(r1)
        ]
        consumed = []
        iters = [iter(s) for s in ranks1]
        for _ in range(steps):
            for it in iters:
                for _ in range(bs):
                    consumed.append(next(it))
        for s in ranks1:
            for _ in range(steps):
                s.record_batch(bs)
        state = ranks1[0].state_dict()
        assert state["completed"] == steps * bs * r1

        remaining = []
        for r in range(r2):
            s = ElasticDistributedSampler(
                n, num_replicas=r2, rank=r, shuffle=shuffle,
                seed=0, drop_last=drop_last,
            )
            s.load_state_dict(state)
            remaining.extend(list(s))

        consumed_set, remaining_set = set(consumed), set(remaining)
        # nothing consumed pre-eviction is replayed post-eviction
        assert not (consumed_set & remaining_set), (trial, drop_last)
        if drop_last:
            # only a tail shorter than the new world may be dropped
            missed = set(range(n)) - consumed_set - remaining_set
            assert len(missed) < r2, (trial, len(missed), r2)
            assert len(remaining) == len(remaining_set)
        else:
            # full coverage; duplicates are exactly the lockstep pad
            assert consumed_set | remaining_set == set(range(n)), trial
            assert len(remaining) - len(remaining_set) == (
                (-(n - state["completed"])) % r2
            ), trial
