"""LatencyHistogram unit tests (observability/histogram.py).

The load-bearing property is EXACT mergeability: because the bucket
index of a value is a pure function of (value, geometry), merging
per-replica histograms and histogramming the concatenated raw samples
yield identical counts — fleet percentiles from counts, never from
averaging per-replica percentiles. Everything here is pure host-side
Python; no jax import.
"""

import json
import math
import random

import pytest

from dlrover_tpu.observability.histogram import (
    LatencyHistogram,
    merge_histograms,
)


def _samples(seed, n, lo=0.01, hi=5000.0):
    rng = random.Random(seed)
    # log-uniform spread across the whole range plus edge values
    out = [math.exp(rng.uniform(math.log(lo), math.log(hi)))
           for _ in range(n)]
    out += [0.0, lo, hi, 1e-9]
    return out


def test_bucket_index_is_deterministic_and_monotone():
    h = LatencyHistogram()
    prev = -1
    for v in sorted(_samples(0, 500)):
        idx = h.bucket_index(v)
        assert idx == h.bucket_index(v)  # pure function of value
        assert idx >= prev               # monotone in the value
        prev = idx


def test_bucket_mid_lands_in_own_bucket():
    h = LatencyHistogram()
    for v in _samples(1, 200):
        idx = h.bucket_index(v)
        assert h.bucket_index(h.bucket_mid(idx)) == idx


def test_relative_error_bound():
    """Each value's bucket midpoint is within 2**-(sub_bits+1) relative
    error — the advertised resolution of the geometry."""
    h = LatencyHistogram(sub_bits=5)
    bound = 2.0 ** -(h.sub_bits + 1) + 1e-12
    for v in _samples(2, 500, lo=0.01):
        if v <= h.min_value:
            continue
        mid = h.bucket_mid(h.bucket_index(v))
        assert abs(mid - v) / v <= bound


@pytest.mark.parametrize("n_parts", [2, 3, 7])
def test_merge_of_parts_equals_histogram_of_concat(n_parts):
    """THE mergeability property: splitting a sample stream across
    replicas and merging their histograms gives bucket counts
    identical to one histogram over the concatenated stream."""
    samples = _samples(3, 2000)
    parts = [LatencyHistogram() for _ in range(n_parts)]
    whole = LatencyHistogram()
    for i, v in enumerate(samples):
        parts[i % n_parts].record(v)
        whole.record(v)
    merged = merge_histograms(parts)
    assert merged.counts == whole.counts
    assert merged.n == whole.n
    assert merged.vmin == whole.vmin and merged.vmax == whole.vmax
    assert merged.total == pytest.approx(whole.total)
    for q in (1, 25, 50, 90, 99, 99.9):
        assert merged.percentile(q) == whole.percentile(q)
    # inputs untouched
    assert sum(p.n for p in parts) == whole.n


def test_merge_rejects_geometry_mismatch():
    a = LatencyHistogram(sub_bits=5)
    b = LatencyHistogram(sub_bits=6)
    with pytest.raises(ValueError, match="geometry"):
        a.merge(b)
    c = LatencyHistogram(min_value=1e-6)
    with pytest.raises(ValueError, match="geometry"):
        merge_histograms([a, c])


def test_merge_of_empty_iterable_is_none():
    assert merge_histograms([]) is None
    assert merge_histograms(iter([])) is None


def test_percentiles_against_sorted_samples():
    """Histogram percentiles track exact nearest-rank percentiles of
    the raw samples within the geometry's relative error bound."""
    samples = _samples(4, 5000, lo=0.1, hi=1000.0)
    h = LatencyHistogram()
    for v in samples:
        h.record(v)
    srt = sorted(samples)
    bound = 2.0 ** -(h.sub_bits + 1) + 1e-9
    for q in (10, 50, 90, 99):
        exact = srt[max(0, math.ceil(q / 100 * len(srt)) - 1)]
        got = h.percentile(q)
        assert abs(got - exact) <= max(bound * exact, h.min_value)
    # percentiles are monotone in q
    ps = [h.percentile(q) for q in (1, 10, 50, 90, 99, 100)]
    assert ps == sorted(ps)


def test_percentile_clamped_to_observed_range():
    h = LatencyHistogram()
    h.record(7.0)
    # a single sample: every percentile IS that sample, not the bucket
    # midpoint (which could exceed it)
    for q in (0, 50, 100):
        assert h.percentile(q) == 7.0
    assert h.summary() == {"p50": 7.0, "p99": 7.0, "n": 1}


def test_empty_and_degenerate_values():
    h = LatencyHistogram()
    assert h.percentile(99) == 0.0
    assert h.summary() == {"p50": 0.0, "p99": 0.0, "n": 0}
    h.record(float("nan"))           # dropped, not poisoning the stats
    assert h.n == 0
    h.record(-5.0)                   # clamps into bucket 0
    h.record(0.0)
    assert h.n == 2
    assert h.percentile(50) == 0.0   # clamped to the observed range


def test_wire_roundtrip_is_lossless():
    h = LatencyHistogram()
    for v in _samples(5, 1000):
        h.record(v)
    back = LatencyHistogram.from_json(h.to_json())
    assert back.counts == h.counts
    assert back.n == h.n
    assert back.geometry() == h.geometry()
    assert back.vmin == h.vmin and back.vmax == h.vmax
    assert back.total == h.total
    # envelope survives a generic JSON hop (string bucket keys)
    doc = json.loads(h.to_json())
    assert all(isinstance(k, str) for k in doc["counts"])
    # empty histogram round-trips too (inf min/max encoded as None)
    e = LatencyHistogram.from_json(LatencyHistogram().to_json())
    assert e.n == 0 and e.vmin == math.inf and e.vmax == -math.inf


def test_clear_resets_to_empty():
    h = LatencyHistogram()
    for v in _samples(6, 50):
        h.record(v)
    h.clear()
    assert h.n == 0 and not h.counts
    assert h.summary() == {"p50": 0.0, "p99": 0.0, "n": 0}


def test_copy_is_independent():
    h = LatencyHistogram()
    h.record(3.0)
    c = h.copy()
    c.record(9.0)
    assert h.n == 1 and c.n == 2


def test_merged_p99_differs_from_averaged_p99():
    """Why histograms exist: the fleet p99 computed from counts is NOT
    the mean of per-replica p99s when load is skewed."""
    fast, slow = LatencyHistogram(), LatencyHistogram()
    for _ in range(990):
        fast.record(1.0)
    for _ in range(10):
        fast.record(2.0)
    for _ in range(100):
        slow.record(1000.0)
    merged = merge_histograms([fast, slow])
    averaged = (fast.percentile(99) + slow.percentile(99)) / 2.0
    true_p99 = merged.percentile(99)
    # ~9% of merged traffic is slow → true p99 is in the slow mass
    assert true_p99 > 900.0
    assert abs(averaged - true_p99) > 300.0
