"""Scheduler unit tests (serving/scheduler.py) — pure host side.

Priority-then-arrival ordering, bounded-queue admission control,
re-admission under the original ticket, head-of-line blocking, and the
ServingRecord telemetry snapshot.
"""

import pytest

from dlrover_tpu.observability.telemetry import ServingRecord, TelemetryHub
from dlrover_tpu.serving.scheduler import AdmissionError, Scheduler


def test_fifo_within_priority_class():
    s = Scheduler()
    r1 = s.submit([1], 4)
    r2 = s.submit([2], 4)
    r3 = s.submit([3], 4)
    assert [s.pop_next().rid for _ in range(3)] == [r1.rid, r2.rid, r3.rid]
    assert s.pop_next() is None


def test_priority_outranks_arrival():
    s = Scheduler()
    s.submit([1], 4, priority=5)
    hi = s.submit([2], 4, priority=0)
    assert s.pop_next().rid == hi.rid


def test_admission_control_bounds_queue():
    s = Scheduler(max_queue=2)
    s.submit([1], 4)
    s.submit([2], 4)
    with pytest.raises(AdmissionError):
        s.submit([3], 4)
    # draining reopens admission
    s.pop_next()
    s.submit([3], 4)


def test_re_admit_preserves_original_ticket():
    s = Scheduler()
    first = s.submit([1], 4)
    s.submit([2], 4)
    popped = s.pop_next()
    assert popped.rid == first.rid
    # preempted: first re-enters AHEAD of the later arrival
    s.re_admit(popped)
    assert s.pop_next().rid == first.rid
    assert s.re_admitted == 1


def test_re_admit_tolerates_foreign_ticket_collision():
    """A request re-admitted from a DEAD PEER can carry the exact same
    (priority, arrival) as a local one — the heap must not compare
    Request objects (the failover bug class)."""
    a, b = Scheduler(replica="a"), Scheduler(replica="b")
    local = a.submit([1], 4)
    foreign = b.submit([2], 4)
    assert local.arrival == foreign.arrival
    a.re_admit(foreign)
    got = {a.pop_next().rid, a.pop_next().rid}
    assert got == {local.rid, foreign.rid}


def test_head_of_line_admission():
    s = Scheduler()
    big = s.submit([1] * 10, 4)
    s.submit([2], 4)
    # can_admit rejects the head → nothing pops, later arrivals wait
    assert s.pop_next(lambda r: len(r.prompt) < 5) is None
    assert s.queue_depth() == 2
    assert s.pop_next(lambda r: True).rid == big.rid


def test_cancelled_future_is_skipped():
    s = Scheduler()
    r1 = s.submit([1], 4)
    r2 = s.submit([2], 4)
    r1.future.cancel()
    assert s.pop_next().rid == r2.rid
    assert s.pop_next() is None


def test_complete_resolves_future_once_and_records_latency():
    s = Scheduler()
    r = s.submit([1, 2], 2)
    s.complete(r, [1, 2, 3, 4])
    assert r.future.result(timeout=1) == [1, 2, 3, 4]
    # double delivery (failover race) must not blow up or re-resolve
    s.complete(r, [9, 9, 9, 9])
    assert r.future.result(timeout=1) == [1, 2, 3, 4]
    lat = s.latency_ms()
    assert lat["n"] == 2 and lat["p99"] >= lat["p50"] >= 0.0


def test_publish_emits_serving_record():
    hub = TelemetryHub()
    seen = []
    hub.add_sink(type("S", (), {"emit": lambda self, r: seen.append(r)})())
    s = Scheduler(hub=hub, replica="rep-7")
    r = s.submit([1], 1)
    s.complete(r, [1, 2])
    rec = s.publish({"active_slots": 3, "tokens_per_s": 12.5})
    assert isinstance(rec, ServingRecord)
    assert seen and seen[-1] is rec
    assert rec.replica == "rep-7"
    assert rec.active_slots == 3
    assert rec.tokens_per_s == 12.5
    assert rec.completed == 1 and rec.admitted == 1
    assert rec.ts > 0  # hub stamps publish time
    # round-trips as JSON scalars (schema lint contract)
    assert "rep-7" in rec.to_json()


def test_capacity_error_carries_retry_after_hint():
    s = Scheduler(max_queue=1)
    s.submit([1], 1)
    with pytest.raises(AdmissionError) as ei:
        s.submit([2], 1)
    assert ei.value.retry_after_s >= 0.05  # deadline-aware hint attached


def test_shed_lowest_prefers_worst_priority_and_spares_re_admits():
    s = Scheduler()
    keep_hi = s.submit([1], 1, priority=0)
    moved = s.submit([2], 1, priority=9)
    s.pop_next()  # drain so re_admit keeps its ticket shape simple
    s.pop_next()
    s.re_admit(moved)          # re-admitted: shed-exempt forever
    low_a = s.submit([3], 1, priority=5)
    low_b = s.submit([4], 1, priority=7)
    shed = s.shed_lowest(count=2)
    # worst first: priority 7 then 5; the re-admitted 9 is untouchable
    assert shed == [low_b, low_a]
    assert s.shed == 2
    for req in shed:
        with pytest.raises(AdmissionError) as ei:
            req.future.result(timeout=1)
        assert ei.value.retry_after_s > 0
        assert req.rid in str(ei.value)
    # survivors: the re-admitted request is still queued
    assert s.queue_depth() == 1
    assert s.pop_next() is moved
    assert not keep_hi.future.done() or True  # popped earlier, unaffected


def test_shed_below_priority_only_sheds_outranked_traffic():
    s = Scheduler()
    same = s.submit([1], 1, priority=3)
    worse = s.submit([2], 1, priority=8)
    shed = s.shed_lowest(count=5, below_priority=3)
    assert shed == [worse]      # equal-priority traffic is not outranked
    assert not same.future.done()


def test_publish_reports_shed_and_migration_counters():
    s = Scheduler()
    s.submit([1], 1, priority=9)
    s.shed_lowest()
    rec = s.publish({"migrated_in": 2, "migrated_out": 1})
    assert rec.shed == 1
    assert rec.migrated_in == 2 and rec.migrated_out == 1
