"""Scheduler unit tests (serving/scheduler.py) — pure host side.

Priority-then-arrival ordering, bounded-queue admission control,
re-admission under the original ticket, head-of-line blocking, and the
ServingRecord telemetry snapshot.
"""

import pytest

from dlrover_tpu.observability.telemetry import ServingRecord, TelemetryHub
from dlrover_tpu.serving.scheduler import AdmissionError, Scheduler


def test_fifo_within_priority_class():
    s = Scheduler()
    r1 = s.submit([1], 4)
    r2 = s.submit([2], 4)
    r3 = s.submit([3], 4)
    assert [s.pop_next().rid for _ in range(3)] == [r1.rid, r2.rid, r3.rid]
    assert s.pop_next() is None


def test_priority_outranks_arrival():
    s = Scheduler()
    s.submit([1], 4, priority=5)
    hi = s.submit([2], 4, priority=0)
    assert s.pop_next().rid == hi.rid


def test_admission_control_bounds_queue():
    s = Scheduler(max_queue=2)
    s.submit([1], 4)
    s.submit([2], 4)
    with pytest.raises(AdmissionError):
        s.submit([3], 4)
    # draining reopens admission
    s.pop_next()
    s.submit([3], 4)


def test_re_admit_preserves_original_ticket():
    s = Scheduler()
    first = s.submit([1], 4)
    s.submit([2], 4)
    popped = s.pop_next()
    assert popped.rid == first.rid
    # preempted: first re-enters AHEAD of the later arrival
    s.re_admit(popped)
    assert s.pop_next().rid == first.rid
    assert s.re_admitted == 1


def test_re_admit_tolerates_foreign_ticket_collision():
    """A request re-admitted from a DEAD PEER can carry the exact same
    (priority, arrival) as a local one — the heap must not compare
    Request objects (the failover bug class)."""
    a, b = Scheduler(replica="a"), Scheduler(replica="b")
    local = a.submit([1], 4)
    foreign = b.submit([2], 4)
    assert local.arrival == foreign.arrival
    a.re_admit(foreign)
    got = {a.pop_next().rid, a.pop_next().rid}
    assert got == {local.rid, foreign.rid}


def test_head_of_line_admission():
    s = Scheduler()
    big = s.submit([1] * 10, 4)
    s.submit([2], 4)
    # can_admit rejects the head → nothing pops, later arrivals wait
    assert s.pop_next(lambda r: len(r.prompt) < 5) is None
    assert s.queue_depth() == 2
    assert s.pop_next(lambda r: True).rid == big.rid


def test_cancelled_future_is_skipped():
    s = Scheduler()
    r1 = s.submit([1], 4)
    r2 = s.submit([2], 4)
    r1.future.cancel()
    assert s.pop_next().rid == r2.rid
    assert s.pop_next() is None


def test_complete_resolves_future_once_and_records_latency():
    s = Scheduler()
    r = s.submit([1, 2], 2)
    s.complete(r, [1, 2, 3, 4])
    assert r.future.result(timeout=1) == [1, 2, 3, 4]
    # double delivery (failover race) must not blow up or re-resolve
    s.complete(r, [9, 9, 9, 9])
    assert r.future.result(timeout=1) == [1, 2, 3, 4]
    lat = s.latency_ms()
    assert lat["n"] == 2 and lat["p99"] >= lat["p50"] >= 0.0


def test_publish_emits_serving_record():
    hub = TelemetryHub()
    seen = []
    hub.add_sink(type("S", (), {"emit": lambda self, r: seen.append(r)})())
    s = Scheduler(hub=hub, replica="rep-7")
    r = s.submit([1], 1)
    s.complete(r, [1, 2])
    rec = s.publish({"active_slots": 3, "tokens_per_s": 12.5})
    assert isinstance(rec, ServingRecord)
    assert seen and seen[-1] is rec
    assert rec.replica == "rep-7"
    assert rec.active_slots == 3
    assert rec.tokens_per_s == 12.5
    assert rec.completed == 1 and rec.admitted == 1
    assert rec.ts > 0  # hub stamps publish time
    # round-trips as JSON scalars (schema lint contract)
    assert "rep-7" in rec.to_json()


def test_capacity_error_carries_retry_after_hint():
    s = Scheduler(max_queue=1)
    s.submit([1], 1)
    with pytest.raises(AdmissionError) as ei:
        s.submit([2], 1)
    assert ei.value.retry_after_s >= 0.05  # deadline-aware hint attached


def test_shed_lowest_prefers_worst_priority_and_spares_re_admits():
    s = Scheduler()
    keep_hi = s.submit([1], 1, priority=0)
    moved = s.submit([2], 1, priority=9)
    s.pop_next()  # drain so re_admit keeps its ticket shape simple
    s.pop_next()
    s.re_admit(moved)          # re-admitted: shed-exempt forever
    low_a = s.submit([3], 1, priority=5)
    low_b = s.submit([4], 1, priority=7)
    shed = s.shed_lowest(count=2)
    # worst first: priority 7 then 5; the re-admitted 9 is untouchable
    assert shed == [low_b, low_a]
    assert s.shed == 2
    for req in shed:
        with pytest.raises(AdmissionError) as ei:
            req.future.result(timeout=1)
        assert ei.value.retry_after_s > 0
        assert req.rid in str(ei.value)
    # survivors: the re-admitted request is still queued
    assert s.queue_depth() == 1
    assert s.pop_next() is moved
    assert not keep_hi.future.done() or True  # popped earlier, unaffected


def test_shed_below_priority_only_sheds_outranked_traffic():
    s = Scheduler()
    same = s.submit([1], 1, priority=3)
    worse = s.submit([2], 1, priority=8)
    shed = s.shed_lowest(count=5, below_priority=3)
    assert shed == [worse]      # equal-priority traffic is not outranked
    assert not same.future.done()


def test_publish_reports_shed_and_migration_counters():
    s = Scheduler()
    s.submit([1], 1, priority=9)
    s.shed_lowest()
    rec = s.publish({"migrated_in": 2, "migrated_out": 1})
    assert rec.shed == 1
    assert rec.migrated_in == 2 and rec.migrated_out == 1


# ---- histogram-backed latency accounting (PR: serving observability) ----


def test_percentiles_stable_under_more_than_window_load():
    """Regression: the old flat list truncated to the newest 4096
    samples, so a long tail recorded early was silently forgotten. The
    histogram keeps every sample: p99 over 10k records must still see
    the early outliers."""
    s = Scheduler()
    # a 2% slow tail first, then 9800 fast ones — more than the old
    # window, which would have evicted every slow sample
    for ms in [500.0] * 200 + [1.0] * 9800:
        with s._lock:
            s._hists["e2e"].record(ms)
    lat = s.latency_ms()
    assert lat["n"] == 10000
    assert lat["p50"] < 5.0          # bulk is fast
    assert lat["p99"] > 400.0        # the early tail is NOT forgotten


def test_deadline_expired_in_queue_fails_fast_and_counts_timed_out():
    s = Scheduler()
    r = s.submit([1], 4, deadline_s=0.0)
    live = s.submit([2], 4)
    import time as _t

    _t.sleep(0.002)  # let the zero-budget deadline lapse
    got = s.pop_next()
    assert got is live               # expired head skipped, not served
    assert s.timed_out == 1
    with pytest.raises(AdmissionError, match="deadline"):
        r.future.result(timeout=1)


def test_peek_skips_done_entries_without_spending_lookahead():
    """Cancelled/resolved entries at the head must not consume the peek
    budget: with n live requests queued behind k done ones, peek(n)
    returns all n live requests, in pop order, without popping anything."""
    s = Scheduler()
    done = [s.submit([i], 4) for i in range(3)]
    live = [s.submit([10 + i], 4) for i in range(3)]
    for r in done:
        r.future.cancel()
    got = s.peek(3)
    assert [r.rid for r in got] == [r.rid for r in live]
    assert s.queue_depth() == 6  # non-destructive: nothing popped
    # partial windows and n=0 stay well-behaved
    assert [r.rid for r in s.peek(100)] == [r.rid for r in live]
    assert s.peek(0) == []


def test_drop_counters_and_publish_fields():
    s = Scheduler(max_queue=1)
    s.submit([1], 1)
    with pytest.raises(AdmissionError):
        s.submit([2], 1)             # capacity → rejected
    s.count_rejected()               # engine oversize path
    s.count_poisoned()
    s.count_timed_out()
    rec = s.publish()
    assert rec.rejected == 2
    assert rec.poisoned == 1
    assert rec.timed_out == 1


def test_record_admitted_fills_queue_wait_histogram():
    s = Scheduler()
    r = s.submit([1], 4)
    popped = s.pop_next()
    s.record_admitted(popped)
    h = s.histograms()
    assert h["queue_wait"].n == 1
    assert r.last_enqueue_t > 0


def test_latency_summary_has_per_phase_keys():
    s = Scheduler()
    r = s.submit([1, 2], 2)
    s.record_admitted(s.pop_next())
    s.record_first_token(r)
    s.complete(r, [1, 2, 3, 4])      # 2 new tokens → TPOT sample
    out = s.latency_summary()
    for key in (
        "p50", "p99", "n", "ttft_p50_ms", "ttft_p99_ms",
        "tpot_p50_ms", "tpot_p99_ms", "queue_wait_p99_ms",
    ):
        assert key in out
    assert out["n"] == 1
    h = s.histograms()
    assert h["ttft"].n == 1 and h["tpot"].n == 1


def test_ttft_survives_re_prefill_failover():
    """A re-prefilled failover must NOT reset the TTFT clock the user
    has been watching since submit — record_first_token is once-only."""
    s = Scheduler()
    r = s.submit([1], 4)
    s.record_first_token(r)
    first = r.first_token_t
    s.record_first_token(r)          # failover re-emits token 0
    assert r.first_token_t == first
    assert s.histograms()["ttft"].n == 1


def test_publish_hists_envelope_merges_back_exactly():
    from dlrover_tpu.observability.histogram import LatencyHistogram
    import json as _json

    s = Scheduler()
    for i in range(1, 40):
        r = s.submit([1], 1)
        s.complete(r, [1, 2])
    rec = s.publish()
    env = _json.loads(rec.hists)
    assert set(env) == {"e2e", "ttft", "tpot", "queue_wait", "handoff"}
    back = LatencyHistogram.from_dict(env["e2e"])
    assert back.n == s.histograms()["e2e"].n
    assert back.summary() == s.latency_ms()
