"""Wire-codec and transport tests (reference analog: tests of common/grpc.py)."""

import threading

import pytest

from dlrover_tpu.common import messages as msgs
from dlrover_tpu.common.comm import (
    MasterTransportClient,
    MasterTransportServer,
)


def test_roundtrip_simple():
    m = msgs.HeartbeatReport(node_id=3, node_type="worker", timestamp=1.5)
    out = msgs.deserialize(msgs.serialize(m))
    assert out == m


def test_roundtrip_nested():
    m = msgs.NodeRegisterRequest(
        meta=msgs.NodeMeta(node_id=7, host_addr="10.0.0.1", local_chips=4),
        restart_count=2,
    )
    out = msgs.deserialize(msgs.serialize(m))
    assert isinstance(out.meta, msgs.NodeMeta)
    assert out.meta.host_addr == "10.0.0.1"
    assert out == m


def test_roundtrip_collections():
    m = msgs.CommWorldResponse(
        rdzv_round=2, world={"0": 4, "1": 4}, coordinator="h0:1234"
    )
    out = msgs.deserialize(msgs.serialize(m))
    assert out.world == {"0": 4, "1": 4}


def test_unregistered_type_rejected():
    with pytest.raises(TypeError):
        msgs.deserialize(b'{"t": "os.system", "d": {}}')


class _EchoServicer:
    def __init__(self):
        self.reported = []

    def report(self, msg):
        self.reported.append(msg)
        return True

    def get(self, msg):
        if isinstance(msg, msgs.CommWorldRequest):
            return msgs.CommWorldResponse(rdzv_round=5, world={"0": 8})
        return None


def test_grpc_transport_roundtrip():
    servicer = _EchoServicer()
    server = MasterTransportServer(servicer, port=0)
    server.start()
    try:
        client = MasterTransportClient(f"localhost:{server.port}")
        assert client.report(msgs.HeartbeatReport(node_id=1))
        resp = client.get(msgs.CommWorldRequest(node_id=1))
        assert resp.rdzv_round == 5 and resp.world == {"0": 8}
        assert client.get(msgs.KeyRequest(key="missing")) is None
        assert servicer.reported[0].node_id == 1
    finally:
        server.stop()


def test_grpc_transport_concurrent():
    servicer = _EchoServicer()
    server = MasterTransportServer(servicer, port=0)
    server.start()
    try:
        client = MasterTransportClient(f"localhost:{server.port}")
        errs = []

        def hammer(i):
            try:
                for _ in range(20):
                    assert client.report(msgs.HeartbeatReport(node_id=i))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=hammer, args=(i,)) for i in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errs
        assert len(servicer.reported) == 160
    finally:
        server.stop()


def test_roundtrip_reshard_messages():
    m = msgs.EvictionNotice(
        node_id=2, node_rank=2, lost_dp_ranks=[4, 5], dp_size=8,
        deadline_s=12.5, reason="maintenance",
    )
    assert msgs.deserialize(msgs.serialize(m)) == m
    r = msgs.ReshardPlanResponse(
        version=3, rdzv_round=1, dp_old=8, dp_new=6, lost_ranks=[6, 7],
    )
    out = msgs.deserialize(msgs.serialize(r))
    assert out == r and out.lost_ranks == [6, 7]
    req = msgs.ReshardPlanRequest(node_id=1, node_rank=1)
    assert msgs.deserialize(msgs.serialize(req)) == req


def test_roundtrip_serving_reshard_messages():
    n = msgs.ServingEvictionNotice(
        node_id=1, replica="rep-1", in_flight=3, deadline_s=2.5,
        reason="drain",
    )
    assert msgs.deserialize(msgs.serialize(n)) == n
    d = msgs.ServingReshardDirective(
        version=2, victim="rep-1", survivors=["rep-0", "rep-2"],
        deadline_s=2.5, reason="drain",
    )
    out = msgs.deserialize(msgs.serialize(d))
    assert out == d and out.survivors == ["rep-0", "rep-2"]
    # version 0 is the none-pending sentinel the client polls against
    assert msgs.ServingReshardDirective().version == 0
    req = msgs.ServingReshardRequest(node_id=4)
    assert msgs.deserialize(msgs.serialize(req)) == req
