"""End-to-end platform-binding tests: FakeKubeApi list-watch →
NodeEvent → JobManager relaunch → SliceScaler → new pod manifest.

Reference parity: k8s_watcher.py:194 (PodWatcher list-watch),
pod_scaler.py:372 (periodic pod create), elasticjob_controller.go:47
(operator reconcile) — the full loop the reference only exercises
against a mocked k8s client is driven here against an API double with
real watch streams and resourceVersions.
"""

import time

import pytest

from dlrover_tpu.cluster.crd import (
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    ScalePlanCRD,
    TPUSliceSpec,
)
from dlrover_tpu.cluster.kube import (
    JOB_LABEL,
    FakeKubeApi,
    JobReconciler,
    PodWatcher,
    pod_to_node_event,
    WatchEvent,
)
from dlrover_tpu.cluster.scaler import SliceScaler
from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
)
from dlrover_tpu.master.node_manager import JobManager, ScalePlan


def _wait(cond, timeout=5.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _job(replicas=2, max_hosts=4, hosts_per_slice=1):
    return ElasticJob(
        "demo",
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=replicas,
                    slice=TPUSliceSpec(hosts_per_slice=hosts_per_slice),
                )
            },
            min_hosts=1,
            max_hosts=max_hosts,
        ),
    )


def test_fake_api_store_and_watch_replay():
    api = FakeKubeApi()
    api.create(
        {
            "kind": "Pod",
            "metadata": {"name": "p0", "labels": {JOB_LABEL: "demo"}},
        }
    )
    api.create({"kind": "Pod", "metadata": {"name": "p1"}})
    assert len(api.list("Pod")) == 2
    assert len(api.list("Pod", label_selector={JOB_LABEL: "demo"})) == 1

    api.set_pod_phase("p0", "Running")
    api.delete("Pod", "p1")

    import threading

    stop = threading.Event()
    seen = []
    for ev in api.watch(kind="Pod", since_rv=0, stop=stop, poll_s=0.01):
        seen.append((ev.type, ev.name))
        if len(seen) == 4:
            stop.set()
    assert seen == [
        ("ADDED", "p0"),
        ("ADDED", "p1"),
        ("MODIFIED", "p0"),
        ("DELETED", "p1"),
    ]
    # resume from a later resourceVersion: only the tail replays
    stop2 = threading.Event()
    tail = []
    for ev in api.watch(kind="Pod", since_rv=2, stop=stop2, poll_s=0.01):
        tail.append(ev.type)
        if len(tail) == 2:
            stop2.set()
    assert tail == ["MODIFIED", "DELETED"]


def test_pod_event_translation():
    def pod(phase, reason="", rank="3"):
        return WatchEvent(
            "MODIFIED",
            {
                "kind": "Pod",
                "metadata": {
                    "name": "x",
                    "labels": {
                        JOB_LABEL: "demo",
                        "elasticjob.dlrover/rank-index": rank,
                    },
                },
                "status": {"phase": phase, "reason": reason},
            },
        )

    ev = pod_to_node_event(pod("Running"))
    assert ev.node_id == 3 and ev.status == NodeStatus.RUNNING
    ev = pod_to_node_event(pod("Failed", reason="OOMKilled"))
    assert ev.status == NodeStatus.FAILED
    assert ev.exit_reason == NodeExitReason.OOM
    ev = pod_to_node_event(pod("Failed", reason="Evicted"))
    assert ev.exit_reason == NodeExitReason.KILLED
    # unlabelled pods are not ours
    assert (
        pod_to_node_event(
            WatchEvent("MODIFIED", {"kind": "Pod", "metadata": {}})
        )
        is None
    )


def test_reconcile_loop_end_to_end():
    """The VERDICT loop: pod dies → watch event → NodeEvent → relaunch
    via ScalePlan → new pod manifest, against the API double."""
    api = FakeKubeApi()
    job = _job(replicas=2)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
        master_addr="10.0.0.1:8000",
    )
    jm = JobManager(num_workers=2, relaunch_budget=2, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)

    # master-direct mode: the master itself creates the worker pods
    plan = ScalePlan()
    plan.worker_num = 2
    scaler.scale(plan)
    pods = api.list("Pod", label_selector={JOB_LABEL: "demo"})
    assert [p["metadata"]["name"] for p in pods] == [
        "demo-worker-0",
        "demo-worker-1",
    ]

    watcher.start()
    api.set_pod_phase("demo-worker-0", "Running")
    api.set_pod_phase("demo-worker-1", "Running")
    _wait(
        lambda: all(
            jm.get_node(i).status == NodeStatus.RUNNING for i in (0, 1)
        ),
        msg="both nodes running",
    )

    # kubelet reports worker-0 OOM-killed → watch → NodeEvent(FAILED,
    # oom) → JobManager relaunch → scaler creates the replacement pod
    api.set_pod_phase("demo-worker-0", "Failed", reason="OOMKilled")
    _wait(
        lambda: api.get("Pod", "demo-worker-0-r1") is not None,
        msg="relaunched pod demo-worker-0-r1",
    )
    node = jm.get_node(0)
    assert node.relaunch_count == 1
    # the replacement keeps rank 0 (same position in the ring)
    repl = api.get("Pod", "demo-worker-0-r1")
    assert (
        repl["metadata"]["labels"]["elasticjob.dlrover/rank-index"] == "0"
    )

    # replacement comes up → node 0 running again on the watch stream
    api.set_pod_phase("demo-worker-0-r1", "Running")
    _wait(
        lambda: jm.get_node(0).status == NodeStatus.RUNNING,
        msg="node 0 running after relaunch",
    )

    # the relaunch DELETED the dead predecessor; that watch event
    # carries incarnation 0 < the node's current incarnation 1 → it is
    # dropped as stale. Without the guard it would read as another
    # failure of rank 0 and cascade into relaunching the healthy -r1.
    time.sleep(0.3)
    assert jm.get_node(0).status == NodeStatus.RUNNING
    assert jm.get_node(0).relaunch_count == 1
    assert api.get("Pod", "demo-worker-0-r2") is None
    watcher.stop()
    jm.stop()


def test_relaunch_budget_exhaustion_stops_pod_churn():
    api = FakeKubeApi()
    job = _job(replicas=1)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
    )
    jm = JobManager(num_workers=1, relaunch_budget=1, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)
    plan = ScalePlan()
    plan.worker_num = 1
    scaler.scale(plan)
    watcher.start()

    api.set_pod_phase("demo-worker-0", "Running")
    _wait(lambda: jm.get_node(0).status == NodeStatus.RUNNING)
    api.set_pod_phase("demo-worker-0", "Failed", reason="Error")
    _wait(lambda: api.get("Pod", "demo-worker-0-r1") is not None)

    api.set_pod_phase("demo-worker-0-r1", "Running")
    _wait(lambda: jm.get_node(0).status == NodeStatus.RUNNING)
    api.set_pod_phase("demo-worker-0-r1", "Failed", reason="Error")
    time.sleep(0.3)  # give a (wrong) relaunch the chance to happen
    # budget exhausted: no -r2 pod, job reports fatal failure
    assert api.get("Pod", "demo-worker-0-r2") is None
    assert jm.any_node_failed_fatally()
    watcher.stop()
    jm.stop()


def test_eviction_relaunch_gets_unique_pod_name():
    """Evicted exits don't consume relaunch budget (NodeExitReason
    NO_BUDGET) but must STILL produce a uniquely-named replacement —
    pod identity rides node.incarnation, not relaunch_count."""
    api = FakeKubeApi()
    job = _job(replicas=1)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
    )
    jm = JobManager(num_workers=1, relaunch_budget=1, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)
    plan = ScalePlan()
    plan.worker_num = 1
    scaler.scale(plan)
    watcher.start()

    for attempt, name in ((1, "demo-worker-0"), (2, "demo-worker-0-r1")):
        api.set_pod_phase(name, "Running")
        _wait(lambda: jm.get_node(0).status == NodeStatus.RUNNING)
        api.set_pod_phase(name, "Failed", reason="Evicted")
        _wait(
            lambda: api.get("Pod", f"demo-worker-0-r{attempt}")
            is not None,
            msg=f"replacement r{attempt}",
        )
    # two free relaunches happened despite budget=1; budget untouched
    assert jm.get_node(0).relaunch_count == 0
    assert jm.get_node(0).incarnation == 2
    watcher.stop()
    jm.stop()


def test_scale_in_does_not_resurrect_pods():
    """set_worker_num scale-in releases the dropped nodes: their pod
    deletions must not be treated as failures to relaunch."""
    api = FakeKubeApi()
    job = _job(replicas=3, max_hosts=4)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
    )
    jm = JobManager(num_workers=3, relaunch_budget=2, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)
    plan = ScalePlan()
    plan.worker_num = 3
    scaler.scale(plan)
    watcher.start()
    for i in range(3):
        api.set_pod_phase(f"demo-worker-{i}", "Running")
    _wait(
        lambda: all(
            jm.get_node(i).status == NodeStatus.RUNNING for i in range(3)
        )
    )

    # master decides to scale in to 1 worker
    jm.set_worker_num(1)
    plan = ScalePlan()
    plan.worker_num = 1
    scaler.scale(plan)
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 1,
        msg="scale-in to 1 pod",
    )
    time.sleep(0.3)  # give wrong relaunches the chance to happen
    pods = api.list("Pod", label_selector={JOB_LABEL: "demo"})
    assert [p["metadata"]["name"] for p in pods] == ["demo-worker-0"]
    assert jm.get_node(0).status == NodeStatus.RUNNING
    watcher.stop()
    jm.stop()


def test_concurrent_failures_during_scale_in():
    """Scale-plan execution under concurrent failures (round-1 VERDICT
    weak #8): while the master scales 4 → 2, the two SURVIVING ranks
    fail simultaneously. The released ranks must stay gone (no
    resurrection) and the in-range ranks must be relaunched exactly
    once each — the final pod set is the 2-worker target."""
    api = FakeKubeApi()
    job = _job(replicas=4, max_hosts=4)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
    )
    jm = JobManager(num_workers=4, relaunch_budget=2, scaler=scaler)
    watcher = PodWatcher(api, "demo", jm.process_event)
    plan = ScalePlan()
    plan.worker_num = 4
    scaler.scale(plan)
    watcher.start()
    for i in range(4):
        api.set_pod_phase(f"demo-worker-{i}", "Running")
    _wait(
        lambda: all(
            jm.get_node(i).status == NodeStatus.RUNNING for i in range(4)
        )
    )

    # master decides to shrink to 2...
    jm.set_worker_num(2)
    plan = ScalePlan()
    plan.worker_num = 2
    scaler.scale(plan)
    # ...and IN THE SAME INSTANT ranks 0 and 1 crash while the watch
    # stream still carries the scale-in deletions of ranks 2 and 3
    api.set_pod_phase("demo-worker-0", "Failed", reason="Error")
    api.set_pod_phase("demo-worker-1", "Failed", reason="OOMKilled")

    _wait(
        lambda: api.get("Pod", "demo-worker-0-r1") is not None
        and api.get("Pod", "demo-worker-1-r1") is not None,
        msg="both in-range ranks relaunched",
    )
    api.set_pod_phase("demo-worker-0-r1", "Running")
    api.set_pod_phase("demo-worker-1-r1", "Running")
    _wait(
        lambda: jm.get_node(0).status == NodeStatus.RUNNING
        and jm.get_node(1).status == NodeStatus.RUNNING
    )
    time.sleep(0.3)  # let any wrong resurrection surface
    pods = sorted(
        p["metadata"]["name"]
        for p in api.list("Pod", label_selector={JOB_LABEL: "demo"})
        if p.get("status", {}).get("phase") != "Failed"
    )
    assert pods == ["demo-worker-0-r1", "demo-worker-1-r1"], pods
    assert jm.get_node(0).relaunch_count == 1
    assert jm.get_node(1).relaunch_count == 1
    watcher.stop()
    jm.stop()


def test_job_reconciler_plays_operator_for_crds():
    """ElasticJob CRD → pods; ScalePlan CRD → scale out and targeted
    removal (elasticjob_controller.go:47 reconcile analog)."""
    api = FakeKubeApi()
    job = _job(replicas=2, max_hosts=6)
    rec = JobReconciler(api, job)
    rec.start()

    api.create(job.to_manifest())
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 2,
        msg="operator created replica pods",
    )

    # scale out via ScalePlan CRD
    api.create(
        ScalePlanCRD(
            job_name="demo", name="sp-1", replica_counts={"worker": 4}
        ).to_manifest()
    )
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 4,
        msg="scale to 4",
    )

    # targeted removal via removePods
    api.create(
        ScalePlanCRD(
            job_name="demo", name="sp-2", remove_pods=["demo-worker-3"]
        ).to_manifest()
    )
    _wait(
        lambda: api.get("Pod", "demo-worker-3") is None,
        msg="pod removed",
    )
    rec.stop()


def test_scale_plan_lifecycle_makes_replays_safe():
    """A processed ScalePlan is marked Succeeded via the status
    subresource (reference: ScalePlanStatus, scaleplan_types.go), so a
    replay — the plan's own status MODIFIED event, or a post-410
    relist — can never undo scaling that happened after it."""
    api = FakeKubeApi()
    job = _job(replicas=2, max_hosts=6)
    rec = JobReconciler(api, job)
    rec.start()
    api.create(job.to_manifest())
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 2,
        msg="initial pods",
    )
    api.create(
        ScalePlanCRD(
            job_name="demo", name="sp-old", replica_counts={"worker": 1}
        ).to_manifest()
    )
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 1,
        msg="scaled down by the plan",
    )
    _wait(
        lambda: (api.get("ScalePlan", "sp-old") or {})
        .get("status", {})
        .get("phase")
        == "Succeeded",
        msg="plan marked Succeeded",
    )
    # the job scales UP afterwards
    ej = api.get("ElasticJob", "demo")
    ej["spec"]["replicaSpecs"]["worker"]["replicas"] = 3
    api.update(ej)
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 3,
        msg="scaled up after the plan",
    )
    # replaying the COMPLETED plan (as a relist would) must be a no-op
    rec._reconcile(WatchEvent("MODIFIED", api.get("ScalePlan", "sp-old")))
    time.sleep(0.3)
    assert (
        len(api.list("Pod", label_selector={JOB_LABEL: "demo"})) == 3
    ), "a completed ScalePlan undid later scaling"
    rec.stop()


def test_reconciler_snaps_to_whole_slices():
    api = FakeKubeApi()
    job = _job(replicas=4, max_hosts=8, hosts_per_slice=4)
    rec = JobReconciler(api, job)
    rec.start()
    # 5 hosts is not a slice multiple: snaps up to 8 (2 slices)
    api.create(
        ScalePlanCRD(
            job_name="demo", name="sp-1", replica_counts={"worker": 5}
        ).to_manifest()
    )
    _wait(
        lambda: len(api.list("Pod", label_selector={JOB_LABEL: "demo"}))
        == 8,
        msg="snap 5 → 8 hosts",
    )
    rec.stop()


def test_pod_watcher_resumes_from_resource_version():
    """List-then-watch (k8s_watcher.py:194 semantics): a watcher started
    from the post-list resourceVersion sees only NEW events — the
    backlog arrives via the initial list, not replayed twice."""
    api = FakeKubeApi()
    job = _job(replicas=2)
    scaler = SliceScaler(
        job,
        submit_fn=api.create,
        delete_fn=lambda name: api.delete("Pod", name),
    )
    plan = ScalePlan()
    plan.worker_num = 2
    scaler.scale(plan)
    api.set_pod_phase("demo-worker-0", "Running")
    rv = api.latest_rv()

    events = []
    watcher = PodWatcher(api, "demo", events.append)
    # the initial list snapshots CURRENT state (one running, one
    # pending); the watch resumes after rv so the backlog isn't doubled
    watcher.start(since_rv=rv)
    _wait(lambda: len(events) >= 2, msg="list snapshot")
    assert sorted(e.node_id for e in events[:2]) == [0, 1]
    n_list = len(events)

    api.set_pod_phase("demo-worker-1", "Running")
    _wait(lambda: len(events) > n_list, msg="fresh watch event")
    fresh = events[n_list:]
    assert all(e.node_id == 1 for e in fresh)
    assert all(e.status == NodeStatus.RUNNING for e in fresh)
    watcher.stop()
