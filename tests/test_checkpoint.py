"""Flash Checkpoint tests: shm staging, persist/commit, resharded restore."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.checkpoint import Checkpointer, StorageType
from dlrover_tpu.checkpoint import core
from dlrover_tpu.checkpoint.checkpointer import state_template
from dlrover_tpu.checkpoint.storage import (
    KeepLatestStepStrategy,
    PosixStorage,
    read_tracker,
)
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel import sharding as shd
from jax.sharding import NamedSharding, PartitionSpec as P


@pytest.fixture(autouse=True)
def _run_id(tmp_path, monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", f"test{os.getpid()}_{time.time_ns()}")


def _state(mesh=None):
    a = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    b = jnp.ones((16,), jnp.bfloat16)
    if mesh is not None:
        a = jax.device_put(a, NamedSharding(mesh, P(("dp", "fsdp"), "tp")))
        b = jax.device_put(b, NamedSharding(mesh, P("tp")))
    return {"params": {"w": a, "b": b}, "step": jnp.asarray(3)}


def test_pack_roundtrip_unsharded():
    state = _state()
    entries, payload = core.plan_pack(state)
    header = core.header_bytes(7, entries)
    buf = memoryview(bytearray(core.pack_size(header, payload)))
    used = core.write_pack(buf, 7, state, entries)
    idx = core.PackIndex()
    idx.add_pack(buf[:used])
    assert idx.step == 7
    out = core.restore_tree(state_template(state), idx)
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"])
    )
    assert out["params"]["b"].dtype == jnp.bfloat16
    assert int(out["step"]) == 3


def test_restore_casts_to_target_dtype():
    """A precision change between save and restore (bf16 run resumed in
    f32, or vice versa) must land in the TARGET dtype, sharded or not."""
    state = _state()
    entries, payload = core.plan_pack(state)
    header = core.header_bytes(1, entries)
    buf = memoryview(bytearray(core.pack_size(header, payload)))
    used = core.write_pack(buf, 1, state, entries)
    idx = core.PackIndex()
    idx.add_pack(buf[:used])
    target = {
        "params": {
            "w": jax.ShapeDtypeStruct((8, 8), jnp.bfloat16),  # was f32
            "b": jax.ShapeDtypeStruct((16,), jnp.float32),    # was bf16
        },
        "step": jax.ShapeDtypeStruct((), jnp.int32),
    }
    out = core.restore_tree(target, idx)
    assert out["params"]["w"].dtype == jnp.bfloat16
    assert out["params"]["b"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out["params"]["w"], np.float32),
        np.asarray(state["params"]["w"]),
        rtol=1e-2,
    )
    # sharded path casts too
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    sh = {
        "params": {
            "w": NamedSharding(mesh, P(("dp", "fsdp"), "tp")),
            "b": NamedSharding(mesh, P("tp")),
        },
        "step": NamedSharding(mesh, P()),
    }
    out_s = core.restore_tree(target, idx, sh)
    assert out_s["params"]["w"].dtype == jnp.bfloat16
    assert out_s["params"]["b"].dtype == jnp.float32


def test_pack_roundtrip_sharded():
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    state = _state(mesh)
    entries, payload = core.plan_pack(state)
    header = core.header_bytes(1, entries)
    buf = memoryview(bytearray(core.pack_size(header, payload)))
    used = core.write_pack(buf, 1, state, entries)
    idx = core.PackIndex()
    idx.add_pack(buf[:used])
    # restore onto a DIFFERENT sharding (resharded restore)
    new_shardings = {
        "params": {
            "w": NamedSharding(mesh, P("tp", None)),
            "b": NamedSharding(mesh, P(None)),
        },
        "step": NamedSharding(mesh, P()),
    }
    out = core.restore_tree(state_template(state), idx, new_shardings)
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"])
    )
    assert out["params"]["w"].sharding.spec == P("tp", None)


def test_pack_reshard_fuzz():
    """Randomized pack→restore across sharding layouts: random shapes,
    dtypes, and source/target PartitionSpecs. Dims are kept divisible
    by every axis combo because jax's NamedSharding device_put rejects
    uneven dims outright — unevenly-sharded leaves cannot exist in this
    framework. Any offset/slice bug in the pack format shows up as a
    value mismatch here long before a multi-host scale event would
    find it."""
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    rng = np.random.RandomState(0)
    axes_pool = [None, "dp", "fsdp", "tp", ("dp", "fsdp")]

    def rand_spec(ndim):
        picked, used = [], set()
        for _ in range(ndim):
            ax = axes_pool[rng.randint(len(axes_pool))]
            names = (
                set()
                if ax is None
                else {ax} if isinstance(ax, str) else set(ax)
            )
            if names & used:
                ax = None
            used |= names
            picked.append(ax)
        return P(*picked)

    for trial in range(8):
        state, src_sh, dst_sh = {}, {}, {}
        for i in range(rng.randint(2, 6)):
            ndim = rng.randint(1, 4)
            # dims divisible by 4 so every axis combo divides evenly
            shape = tuple(4 * rng.randint(1, 5) for _ in range(ndim))
            dtype = [jnp.float32, jnp.bfloat16, jnp.int32][
                rng.randint(3)
            ]
            arr = jnp.asarray(
                rng.randint(-100, 100, size=shape), dtype=dtype
            )
            key = f"leaf{i}"
            state[key] = jax.device_put(
                arr, NamedSharding(mesh, rand_spec(ndim))
            )
            dst_sh[key] = NamedSharding(mesh, rand_spec(ndim))
        entries, payload = core.plan_pack(state)
        header = core.header_bytes(trial, entries)
        buf = memoryview(bytearray(core.pack_size(header, payload)))
        used = core.write_pack(buf, trial, state, entries)
        idx = core.PackIndex()
        idx.add_pack(buf[:used])
        out = core.restore_tree(state_template(state), idx, dst_sh)
        for key in state:
            np.testing.assert_array_equal(
                np.asarray(out[key]),
                np.asarray(state[key]),
                err_msg=f"trial {trial} {key} "
                f"{state[key].sharding.spec}->{dst_sh[key].spec}",
            )
            assert out[key].sharding.spec == dst_sh[key].spec


def test_checkpointer_disk_roundtrip(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ckpt"), use_agent=False)
    state = _state()
    assert ckpt.save_checkpoint(10, state, StorageType.DISK)
    ckpt.wait_for_persist()
    assert ckpt.latest_committed_step() == 10
    out = ckpt.load_checkpoint(state_template(state))
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"])
    )


def test_checkpointer_memory_then_load(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ckpt"), use_agent=False)
    state = _state()
    assert ckpt.save_checkpoint(5, state, StorageType.MEMORY)
    # nothing persisted to disk
    assert ckpt.latest_committed_step() is None
    out = ckpt.load_checkpoint(state_template(state))
    assert out is not None
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"])
    )


def test_agent_saver_flow(tmp_path):
    """Worker stages via shm IPC; agent daemon persists + commits."""
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    saver = AsyncCheckpointSaver.start_async_saving_ckpt()
    try:
        ckpt = Checkpointer(str(tmp_path / "ckpt"), use_agent=True)
        state = _state()
        assert ckpt.save_checkpoint(20, state, StorageType.DISK)
        deadline = time.time() + 10
        while time.time() < deadline:
            if read_tracker(str(tmp_path / "ckpt"), PosixStorage()) == 20:
                break
            time.sleep(0.05)
        assert ckpt.latest_committed_step() == 20

        # memory-only stage + emergency persist (worker-failure path)
        state2 = jax.tree.map(lambda x: x + 1, state)
        assert ckpt.save_checkpoint(21, state2, StorageType.MEMORY)
        saver.save_shm_to_storage()
        assert ckpt.latest_committed_step() == 21
        out = ckpt.engine.load_from_storage(state_template(state))
        np.testing.assert_array_equal(
            np.asarray(out["params"]["w"]),
            np.asarray(state2["params"]["w"]),
        )
    finally:
        saver.close()


def test_deletion_strategy(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    ckpt = Checkpointer(ckpt_dir, use_agent=False)
    state = _state()
    for step in (1, 2, 3, 4):
        ckpt.save_checkpoint(step, state, StorageType.DISK)
        ckpt.wait_for_persist()
    KeepLatestStepStrategy(max_to_keep=2).clean_up(ckpt_dir, PosixStorage())
    remaining = sorted(
        d for d in os.listdir(ckpt_dir) if d.startswith("step_")
    )
    assert remaining == ["step_3", "step_4"]


def test_orbax_roundtrip(tmp_path):
    """Native pack ⇄ orbax conversion preserves values and shardings."""
    from dlrover_tpu.checkpoint.orbax_compat import (
        load_orbax,
        orbax_to_pack,
        pack_to_orbax,
        save_orbax,
    )
    from dlrover_tpu.checkpoint.engine import CheckpointEngine

    state = _state()
    # native save (committed to disk)
    engine = CheckpointEngine(str(tmp_path / "native"), use_agent=False)
    assert engine.save_to_storage(5, state)
    engine.wait_for_persist()

    # native → orbax
    out = str(tmp_path / "orbax_out")
    pack_to_orbax(
        str(tmp_path / "native"), out, state_template(state), step=5
    )
    restored = load_orbax(out)
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]), np.asarray(state["params"]["w"])
    )

    # orbax → native (fresh dir), then native restore
    orbax_to_pack(out, str(tmp_path / "native2"), step=9)
    engine2 = CheckpointEngine(str(tmp_path / "native2"), use_agent=False)
    back = engine2.load_from_storage(state_template(state))
    assert back is not None
    np.testing.assert_array_equal(
        np.asarray(back["params"]["w"]), np.asarray(state["params"]["w"])
    )
    assert int(back["step"]) == 3  # the stored scalar, not the ckpt step


def test_orbax_save_load_direct(tmp_path):
    from dlrover_tpu.checkpoint.orbax_compat import load_orbax, save_orbax

    state = {"a": jnp.arange(8.0), "b": {"c": jnp.ones((2, 2))}}
    save_orbax(str(tmp_path / "o"), state)
    out = load_orbax(str(tmp_path / "o"))
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(state["a"]))


def test_partial_restore_keeps_fresh_leaves_for_grown_tree(tmp_path):
    """State-tree upgrade path (ADVICE r4): a checkpoint saved BEFORE a
    state tree grew (e.g. fp8 gaining attention-projection amax slots)
    restores the stored leaves and keeps the live state's fresh values
    for the new ones — instead of failing the whole restore. Params
    must still restore exactly (a missing param leaf refuses even with
    partial); an abstract template with missing leaves raises; a grown
    tree without partial raises instead of reading as "no checkpoint"
    — and all of it holds on the DISK path (fresh engine, no shm
    meta), not just the shm cache."""
    from dlrover_tpu.checkpoint.core import RestoreMismatchError

    ckpt = Checkpointer(str(tmp_path / "ckpt"), use_agent=False)
    old_state = _state()
    assert ckpt.save_checkpoint(7, old_state, StorageType.DISK)
    ckpt.wait_for_persist()

    # the tree grew: a new subtree exists in the live state only
    new_state = dict(old_state)
    new_state["fp8"] = {"wq": {"amax_x": jnp.ones((16,), jnp.float32) * 3}}

    # a FRESH Checkpointer: no shm meta, restore must come from disk
    reader = Checkpointer(str(tmp_path / "ckpt"), use_agent=False)
    out = reader.load_checkpoint(new_state, partial=True)
    assert out is not None
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]),
        np.asarray(old_state["params"]["w"]),
    )
    # the new leaves kept their fresh (initialized) values
    np.testing.assert_array_equal(
        np.asarray(out["fp8"]["wq"]["amax_x"]),
        np.asarray(new_state["fp8"]["wq"]["amax_x"]),
    )
    # ...and the shm path of the ORIGINAL engine agrees
    out2 = ckpt.load_checkpoint(new_state, partial=True)
    np.testing.assert_array_equal(
        np.asarray(out2["fp8"]["wq"]["amax_x"]),
        np.asarray(new_state["fp8"]["wq"]["amax_x"]),
    )
    # an abstract template cannot provide values for missing leaves
    with pytest.raises(RestoreMismatchError):
        reader.load_checkpoint(state_template(new_state), partial=True)
    # without partial, a grown tree fails loudly (never reads as
    # "no checkpoint → fresh start")
    with pytest.raises(RestoreMismatchError):
        reader.load_checkpoint(new_state)
    # a missing PARAM leaf refuses even under partial: substituting
    # fresh weights is a rename/corruption, not an upgrade
    renamed = dict(new_state)
    renamed["params"] = dict(old_state["params"])
    renamed["params"]["w_renamed"] = renamed["params"].pop("w")
    with pytest.raises(RestoreMismatchError):
        reader.load_checkpoint(renamed, partial=True)


def test_restore_tree_returns_owned_buffers(monkeypatch):
    """Restored leaves must be jax-OWNED copies, never zero-copy
    aliases of the numpy arrays assembled from the pack: the train step
    donates the restored state, and XLA releasing a buffer that numpy's
    malloc owns corrupts the glibc heap (flakily — jax's CPU backend
    only aliases 64-byte-aligned buffers, so the elastic resume crashed
    on roughly the malloc alignment rate). Pin the ownership contract
    by forcing read_slice to hand back guaranteed-aligned arrays and
    asserting the restored jax buffers live elsewhere."""
    state = _state()
    entries, payload = core.plan_pack(state)
    header = core.header_bytes(7, entries)
    buf = memoryview(bytearray(core.pack_size(header, payload)))
    used = core.write_pack(buf, 7, state, entries)
    idx = core.PackIndex()
    idx.add_pack(buf[:used])

    def _aligned(a):
        # view into an oversized buffer at a 64-byte-aligned offset —
        # the deterministic worst case for the zero-copy alias
        raw = np.empty(a.nbytes + 64, np.uint8)
        off = (-raw.ctypes.data) % 64
        v = raw[off : off + a.nbytes].view(a.dtype).reshape(a.shape)
        v[...] = a
        assert v.ctypes.data % 64 == 0
        return v

    src_ptrs = []
    orig = core.PackIndex.read_slice

    def read_aligned(self, path, index):
        v = _aligned(orig(self, path, index))
        src_ptrs.append((v.ctypes.data, v))  # keep alive for the check
        return v

    monkeypatch.setattr(core.PackIndex, "read_slice", read_aligned)
    out = core.restore_tree(state_template(state), idx)
    restored = [
        leaf.unsafe_buffer_pointer() for leaf in jax.tree.leaves(out)
    ]
    assert not (set(restored) & {p for p, _ in src_ptrs})
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"])
    )

    # the resharding path (make_array_from_callback) must not alias its
    # callback arrays either
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    sh = {
        "params": {
            "w": NamedSharding(mesh, P(("dp", "fsdp"), "tp")),
            "b": NamedSharding(mesh, P("tp")),
        },
        "step": NamedSharding(mesh, P()),
    }
    src_ptrs.clear()
    out_s = core.restore_tree(state_template(state), idx, sh)
    shard_ptrs = {
        s.data.unsafe_buffer_pointer()
        for leaf in jax.tree.leaves(out_s)
        for s in leaf.addressable_shards
    }
    assert not (shard_ptrs & {p for p, _ in src_ptrs})
    np.testing.assert_array_equal(
        np.asarray(out_s["params"]["w"]), np.asarray(state["params"]["w"])
    )


def test_wait_for_persist_timeout_publishes_failure(tmp_path):
    """A blown persist deadline must return False and leave a failed
    ``persist_wait`` CheckpointRecord — a silent return here let callers
    tear down hosts believing the disk tier was durable."""
    import threading

    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.observability import telemetry

    telemetry.reset_hub()
    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append)
    try:
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=False)
        engine._local_step = 42
        # a persist that will not finish inside the deadline
        engine._persist_thread = threading.Thread(
            target=time.sleep, args=(1.5,), daemon=True
        )
        engine._persist_thread.start()
        assert engine.wait_for_persist(timeout=0.05) is False
        fails = [
            e
            for e in events
            if isinstance(e, telemetry.CheckpointRecord)
            and e.kind == "persist_wait"
        ]
        assert len(fails) == 1
        assert fails[0].ok is False
        assert fails[0].step == 42 and fails[0].tier == "storage"
        # once the thread finishes, the wait succeeds and stays quiet
        engine._persist_thread.join()
        assert engine.wait_for_persist(timeout=0.05) is True
        assert len([e for e in events if e.kind == "persist_wait"]) == 1
    finally:
        telemetry.reset_hub()


def test_stale_broker_socket_heals_to_standalone(tmp_path, monkeypatch):
    """A SIGKILLed agent leaves its IPC socket file behind; the next
    engine in that namespace must NOT become a client of the dead
    broker — it probes the socket, unlinks the corpse, and runs
    standalone."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.common import multi_process as mp

    monkeypatch.setenv("DLROVER_TPU_RUN_ID", f"stale{os.getpid()}")
    path = mp._socket_path("queue_ckpt")
    # the corpse: a bound-then-abandoned unix socket (no listener)
    import socket as socket_mod

    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.bind(path)
    s.close()
    assert os.path.exists(path)

    eng = CheckpointEngine(str(tmp_path))
    assert eng._use_agent is False
    assert not os.path.exists(path), "stale socket should be unlinked"

    # a LIVE broker still routes the engine into client mode
    from dlrover_tpu.common.multi_process import SharedQueue

    broker = SharedQueue("ckpt")
    try:
        assert mp.broker_alive("queue_ckpt") is True
        eng2 = CheckpointEngine(str(tmp_path))
        assert eng2._use_agent is True
    finally:
        broker.close()
