"""Live resharding: interval math, donation vs reference repack, the
LiveResharder phase machine, and the bitwise continuation pin.

Fast tier covers the pure-numpy donation path and in-process fault
injectors; the slow tier runs the end-to-end eviction: a ZeRO-1 rollout
at dp=8, drop four devices, migrate the in-HBM state onto a dp=4 mesh
via the donation machinery, and pin that continued training is bitwise
identical to the direct canonical-stream repack (f32 wire).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.elastic import faults
from dlrover_tpu.elastic.resharding import (
    LiveResharder,
    MigrationError,
    PhaseBudgets,
    PhaseDeadlineExceeded,
    donation_plan,
    migrate_flat,
    reshard_flat,
    reshard_train_state,
    shard_intervals,
)
from dlrover_tpu.models.config import get_config
from dlrover_tpu.observability import telemetry
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.train.train_step import (
    TrainStepBuilder,
    init_train_state,
    state_shardings,
)


def synth_plan(dp, n_buckets, bucket_elems, total):
    assert bucket_elems % dp == 0
    assert total <= n_buckets * bucket_elems
    return shd.PackPlan(
        shapes=(),
        sizes=(),
        offsets=(),
        total=total,
        bucket_elems=bucket_elems,
        n_buckets=n_buckets,
        dp=dp,
        tie_size=0,
        n_tie_buckets=0,
    )


def canonical_fill(plan, seed=0):
    """A flat (nb, E) leaf whose canonical region is a random stream and
    whose tail padding is zero (the invariant the optimizer maintains)."""
    rng = np.random.RandomState(seed)
    stream = rng.randn(plan.total).astype(np.float32)
    out = np.zeros(plan.padded, np.float32)
    out[: plan.total] = stream
    return out.reshape(plan.n_buckets, plan.bucket_elems)


# ------------------------------------------------------------- intervals


def test_shard_intervals_partition_canonical_stream():
    for plan in (
        synth_plan(8, 3, 32, 90),
        synth_plan(4, 2, 16, 17),
        synth_plan(6, 5, 24, 120),
        synth_plan(1, 1, 8, 5),
    ):
        got = sorted(
            iv for r in range(plan.dp) for iv in shard_intervals(plan, r)
        )
        # disjoint, sorted, and exactly covering [0, total)
        assert got[0][0] == 0
        assert got[-1][1] == plan.total
        for (a, b), (c, d) in zip(got, got[1:]):
            assert a < b and b == c


def test_shard_intervals_rank_bounds():
    plan = synth_plan(4, 2, 16, 17)
    with pytest.raises(ValueError):
        shard_intervals(plan, 4)
    with pytest.raises(ValueError):
        shard_intervals(plan, -1)


def test_donation_plan_totals_must_match():
    with pytest.raises(ValueError):
        donation_plan(synth_plan(8, 1, 32, 30), synth_plan(4, 1, 16, 16))


@pytest.mark.parametrize(
    "old,new",
    [
        (synth_plan(8, 3, 32, 90), synth_plan(4, 2, 48, 90)),
        (synth_plan(8, 2, 64, 100), synth_plan(6, 3, 36, 100)),
        (synth_plan(4, 2, 48, 90), synth_plan(8, 3, 32, 90)),
        (synth_plan(2, 1, 64, 64), synth_plan(2, 1, 64, 64)),
    ],
)
def test_migrate_matches_reference_repack(old, new):
    flat = canonical_fill(old, seed=3)
    np.testing.assert_array_equal(
        migrate_flat(flat, old, new), reshard_flat(flat, old, new)
    )


def test_donation_plan_on_real_pack_plans():
    """Same abstract tree laid out for dp=8 and dp=6 (different alignment,
    different bucket_elems): donation path == canonical repack."""
    tree = {
        "a": jax.ShapeDtypeStruct((130,), jnp.float32),
        "b": jax.ShapeDtypeStruct((7, 5), jnp.float32),
        "c": jax.ShapeDtypeStruct((3, 3, 3), jnp.float32),
    }
    old = shd.build_pack_plan(tree, dp=8, bucket_bytes=256)
    new = shd.build_pack_plan(tree, dp=6, bucket_bytes=512)
    assert old.total == new.total
    flat = canonical_fill(old, seed=5)
    np.testing.assert_array_equal(
        migrate_flat(flat, old, new), reshard_flat(flat, old, new)
    )


def test_migrate_dead_donor_raises_migration_error():
    old, new = synth_plan(8, 3, 32, 90), synth_plan(4, 2, 48, 90)
    flat = canonical_fill(old)
    with pytest.raises(MigrationError):
        migrate_flat(flat, old, new, dead_ranks=(2,))


def test_reshard_train_state_moves_flat_leaves():
    old, new = synth_plan(8, 2, 64, 100), synth_plan(4, 3, 40, 100)
    mesh4 = build_mesh(MeshConfig(dp=-1), devices=jax.devices()[:4])
    P = jax.sharding.PartitionSpec
    flat_shd = jax.sharding.NamedSharding(mesh4, P(None, "dp"))
    rep_shd = jax.sharding.NamedSharding(mesh4, P())
    state = {
        "opt": {"mu": canonical_fill(old, 1), "nu": canonical_fill(old, 2)},
        "step": np.int32(7),
    }
    shardings = {"opt": {"mu": flat_shd, "nu": flat_shd}, "step": rep_shd}
    out = reshard_train_state(state, old, new, shardings)
    assert out["opt"]["mu"].shape == (new.n_buckets, new.bucket_elems)
    np.testing.assert_array_equal(
        np.asarray(out["opt"]["nu"]),
        reshard_flat(state["opt"]["nu"], old, new),
    )
    assert int(out["step"]) == 7


def _hybrid_plan(dp=4):
    """A real PackPlan built under a dp×fsdp mesh_axes family."""
    tree = {
        "w": jax.ShapeDtypeStruct((64, 32), jnp.float32),
        "b": jax.ShapeDtypeStruct((37,), jnp.float32),
    }
    return shd.build_pack_plan(
        tree, dp, bucket_bytes=512, mesh_axes=("dp", "fsdp")
    )


def test_reshard_train_state_refuses_hybrid_mesh_plans():
    """Flat-stream coordinates are only canonical within one mesh_axes
    family: a PackPlan built under dp×fsdp must be refused for live
    donation (either side of the migration) instead of silently
    repacking a stream whose offsets mean something else."""
    hybrid = _hybrid_plan(dp=4)
    pure = synth_plan(8, 3, 768, hybrid.total)
    mesh4 = build_mesh(MeshConfig(dp=-1), devices=jax.devices()[:4])
    P = jax.sharding.PartitionSpec
    flat_shd = jax.sharding.NamedSharding(mesh4, P(None, "dp"))
    state = {"opt": {"mu": canonical_fill(hybrid)}}
    shardings = {"opt": {"mu": flat_shd}}
    with pytest.raises(MigrationError, match="pure-dp"):
        reshard_train_state(state, hybrid, pure, shardings)
    with pytest.raises(MigrationError, match="pure-dp"):
        reshard_train_state(
            {"opt": {"mu": canonical_fill(pure)}}, pure, hybrid, shardings
        )


def test_resharder_hybrid_plan_degrades_to_fallback(hub_events):
    """The zoo refusal rides the existing failover ladder: the
    resharder catches the MigrationError, runs the checkpoint fallback,
    and publishes reshard_recovery path=fallback with the reason."""
    hybrid = _hybrid_plan(dp=4)
    pure = synth_plan(8, 3, 768, hybrid.total)
    mesh4 = build_mesh(MeshConfig(dp=-1), devices=jax.devices()[:4])
    P = jax.sharding.PartitionSpec
    flat_shd = jax.sharding.NamedSharding(mesh4, P(None, "dp"))
    state = {"opt": {"mu": canonical_fill(hybrid)}}
    shardings = {"opt": {"mu": flat_shd}}
    rs = LiveResharder(retries=2, backoff_base_s=0.01)
    outcome = rs.execute(
        [
            ("replan", lambda _: (hybrid, pure)),
            (
                "migrate",
                lambda plans: reshard_train_state(
                    state, *plans, shardings
                ),
            ),
        ],
        fallback=lambda e: "restored-from-checkpoint",
    )
    assert outcome.ok and outcome.path == "fallback"
    assert outcome.result == "restored-from-checkpoint"
    assert "pure-dp" in outcome.reason
    assert "path=fallback" in hub_events[-1].detail


# ---------------------------------------------------------------- faults


def test_parse_faults():
    specs = faults.parse_faults(
        "torn_donation:point=donation:times=1;"
        "slow_peer:delay_s=0.5:rank=3;evict:rank=5"
    )
    assert [s.kind for s in specs] == ["torn_donation", "slow_peer", "evict"]
    assert specs[0].point == "donation" and specs[0].times == 1
    assert specs[1].delay_s == 0.5 and specs[1].rank == 3
    assert specs[2].rank == 5


def test_parse_faults_rejects_unknown_kind():
    with pytest.raises(ValueError):
        faults.parse_faults("meteor_strike:rank=1")


def test_parse_faults_names_the_bad_clause():
    """Strict grammar: every malformed clause raises ValueError NAMING
    the clause — a typo'd drill spec must fail loudly at startup, not
    silently run without the fault."""
    cases = [
        "meteor_strike:rank=1",               # unknown kind
        "kill:rank",                          # key with no =
        "kill:=3",                            # empty key
        "kill:rank=three",                    # non-int rank
        "slow_peer:delay_s=soon",             # non-float delay
        "kill:color=red",                     # unknown key
        "kill:times=1.5",                     # non-int times
    ]
    for bad in cases:
        with pytest.raises(ValueError) as ei:
            faults.parse_faults(f"evict:rank=1;{bad}")
        # the message names the offending clause, not just "bad input"
        assert bad in str(ei.value), bad


def test_parse_faults_serving_kinds():
    specs = faults.parse_faults(
        "drop_page:point=serving.transfer:times=1;"
        "stall_migration:point=serving.transfer:delay_s=0.3;"
        "kill:point=serving.resume:rank=1"
    )
    assert [s.kind for s in specs] == ["drop_page", "stall_migration", "kill"]
    inj = faults.FaultInjector()
    inj.install(specs[0])
    with pytest.raises(faults.DroppedPage):
        inj.at("serving.transfer", rank=0)
    inj.at("serving.transfer", rank=0)  # times=1: exhausted
    # DroppedPage is a TornDonation — the migrator's retry ladder covers it
    assert issubclass(faults.DroppedPage, faults.TornDonation)


def test_injector_times_and_scoping():
    inj = faults.FaultInjector()
    inj.install(faults.FaultSpec("torn_donation", point="donation", times=1))
    inj.at("other_point")  # scoped: does not fire
    with pytest.raises(faults.TornDonation):
        inj.at("donation")
    inj.at("donation")  # exhausted: does not fire again


def test_injector_evicted_ranks_and_kill():
    inj = faults.FaultInjector()
    inj.install(faults.FaultSpec("evict", rank=5))
    inj.install(faults.FaultSpec("evict", rank=4))
    assert inj.evicted_ranks() == (4, 5)
    inj.at("anywhere")  # evict specs never raise
    inj.install(faults.FaultSpec("kill", point="step", rank=1))
    inj.at("step", rank=0)  # wrong rank
    with pytest.raises(faults.InjectedKill):
        inj.at("step", rank=1)


def test_injector_env_seeding(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_FAULTS", "evict:rank=2;evict:rank=3")
    faults.reset_injector()
    try:
        assert faults.get_injector().evicted_ranks() == (2, 3)
    finally:
        faults.reset_injector()


# ---------------------------------------------------------- phase machine


@pytest.fixture
def hub_events():
    telemetry.reset_hub()
    hub = telemetry.configure_hub()
    events = []
    hub.subscribe(events.append)
    yield events
    telemetry.reset_hub()


def _plans():
    return synth_plan(8, 3, 32, 90), synth_plan(4, 2, 48, 90)


def test_resharder_transient_fault_retries_to_live(hub_events):
    old, new = _plans()
    flat = canonical_fill(old)
    inj = faults.FaultInjector()
    inj.install(faults.FaultSpec("torn_donation", point="donation", times=1))
    rs = LiveResharder(faults=inj, retries=2, backoff_base_s=0.01)
    outcome = rs.execute(
        [
            ("replan", lambda _: (old, new)),
            ("migrate", lambda plans: migrate_flat(flat, *plans, faults=inj)),
        ],
        fallback=lambda e: pytest.fail("must not fall back"),
    )
    assert outcome.ok and outcome.path == "live"
    np.testing.assert_array_equal(outcome.result, reshard_flat(flat, old, new))
    kinds = [e.kind for e in hub_events]
    assert kinds == ["reshard_replan", "reshard_migrate", "reshard_recovery"]
    assert "retries=1" in hub_events[1].detail
    assert "path=live" in hub_events[-1].detail


def test_resharder_persistent_fault_falls_back(hub_events):
    old, new = _plans()
    flat = canonical_fill(old)
    inj = faults.FaultInjector()
    inj.install(faults.FaultSpec("torn_donation", point="donation"))
    rs = LiveResharder(faults=inj, retries=2, backoff_base_s=0.01)
    outcome = rs.execute(
        [
            ("replan", lambda _: (old, new)),
            ("migrate", lambda plans: migrate_flat(flat, *plans, faults=inj)),
        ],
        fallback=lambda e: "restored-from-checkpoint",
    )
    assert outcome.ok and outcome.path == "fallback"
    assert outcome.result == "restored-from-checkpoint"
    assert outcome.failed_phase == "migrate"
    assert "TornDonation" in outcome.reason
    kinds = [e.kind for e in hub_events]
    assert kinds[-2:] == ["reshard_fallback", "reshard_recovery"]
    assert "path=fallback" in hub_events[-1].detail


def test_resharder_dead_donor_falls_back_without_retry():
    old, new = _plans()
    flat = canonical_fill(old)
    calls = []
    rs = LiveResharder(retries=2, backoff_base_s=0.01)
    outcome = rs.execute(
        [
            (
                "migrate",
                lambda _: (
                    calls.append(1),
                    migrate_flat(flat, old, new, dead_ranks=(6,)),
                ),
            ),
        ],
        fallback=lambda e: e,
    )
    assert outcome.path == "fallback"
    assert isinstance(outcome.result, MigrationError)
    assert len(calls) == 1  # MigrationError is not retryable


def test_resharder_deadline_exceeded_falls_back():
    old, new = _plans()
    flat = canonical_fill(old)
    inj = faults.FaultInjector()
    inj.install(
        faults.FaultSpec("slow_peer", point="donation", delay_s=0.2, times=1)
    )
    rs = LiveResharder(
        budgets=PhaseBudgets(migrate_s=0.05), faults=inj, retries=0
    )
    outcome = rs.execute(
        [("migrate", lambda _: migrate_flat(flat, old, new, faults=inj))],
        fallback=lambda e: e,
    )
    assert outcome.path == "fallback"
    assert isinstance(outcome.result, PhaseDeadlineExceeded)
    assert outcome.result.phase == "migrate"


def test_resharder_without_fallback_raises():
    rs = LiveResharder(retries=0)
    with pytest.raises(MigrationError):
        rs.execute([("migrate", lambda _: (_ for _ in ()).throw(MigrationError("x")))])


# ------------------------------------------------- end-to-end bitwise pin


def tiny_cfg(**kw):
    kw.setdefault("dtype", "float32")
    kw.setdefault("tie_embeddings", False)
    return get_config(
        "tiny",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=128,
        max_seq=32,
        **kw,
    )


def batches(n, batch=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        base = rng.randint(0, vocab, size=(batch, 33))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }


@pytest.mark.slow
def test_bitwise_continuation_across_eviction():
    """dp=8 ZeRO-1 rollout; four devices 'evicted'; the in-HBM state is
    live-resharded onto the dp=4 survivor mesh through the donation
    machinery and must continue training bitwise identically to the
    reference canonical-stream repack (f32 wire)."""
    cfg = tiny_cfg()
    comm = shd.CommConfig(update_sharding=True, bucket_mb=0.05)
    mesh8 = build_mesh(MeshConfig(dp=-1))
    b8 = TrainStepBuilder(cfg, mesh8, optax.adamw(1e-3), comm=comm)
    assert b8.update_sharding, b8.update_sharding_reason
    state = init_train_state(
        jax.random.key(0), cfg, mesh8, b8.optimizer, comm=b8.comm_resolved
    )
    f8 = jax.jit(b8.step_fn)
    pre_loss = None
    for b in batches(3):
        state, m = f8(state, b)
        pre_loss = float(m["loss"])

    survivors = jax.devices()[:4]
    mesh4 = build_mesh(MeshConfig(dp=-1), devices=survivors)
    b4 = TrainStepBuilder(cfg, mesh4, optax.adamw(1e-3), comm=comm)
    assert b4.update_sharding, b4.update_sharding_reason
    plan8, plan4 = b8._plan, b4._plan
    shd4 = state_shardings(cfg, mesh4, b4.optimizer, comm=b4.comm_resolved)

    live = reshard_train_state(state, plan8, plan4, shd4)
    flat_shape = (plan8.n_buckets, plan8.bucket_elems)
    ref = jax.tree.map(
        lambda leaf, s: jax.device_put(
            reshard_flat(np.asarray(leaf), plan8, plan4)
            if np.asarray(leaf).shape == flat_shape
            else np.asarray(leaf),
            s,
        ),
        state,
        shd4,
    )
    for x, y in zip(jax.tree.leaves(live), jax.tree.leaves(ref)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    f4 = jax.jit(b4.step_fn)
    post_first = None
    for b in batches(3, seed=1):
        live, ml = f4(live, b)
        ref, mr = f4(ref, b)
        if post_first is None:
            post_first = float(ml["loss"])
        assert float(ml["loss"]) == float(mr["loss"])
        assert np.isfinite(float(ml["loss"]))
    for x, y in zip(jax.tree.leaves(live), jax.tree.leaves(ref)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # resumed from the exact in-memory step: the first post-eviction loss
    # sits on the pre-eviction trend, not back at init (~ln(vocab)=4.85)
    assert abs(post_first - pre_loss) < 1.0
