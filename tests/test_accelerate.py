"""auto_accelerate strategy engine tests."""

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.accelerate import auto_accelerate
from dlrover_tpu.accelerate.analyser import analyse
from dlrover_tpu.accelerate.engine import generate_candidates, search_strategy
from dlrover_tpu.accelerate.strategy import (
    AccelerationPlan,
    apply_strategy,
    strategy_from_json,
    strategy_to_json,
)
from dlrover_tpu.models import get_config

# end-to-end auto_accelerate runs are heavy; excluded from the tier-1 budget
pytestmark = pytest.mark.slow


def test_apply_strategy_builds_plan():
    plan = apply_strategy(
        [
            ("amp_bf16", {}),
            ("mixed_parallel", {"dp": 2, "fsdp": 2, "tp": 2}),
            ("checkpoint", {"policy": "full"}),
            ("low_bit_optim", {}),
        ]
    )
    assert plan.mesh.tp == 2 and plan.mesh.fsdp == 2 and plan.mesh.dp == 2
    assert plan.remat == "full"
    assert plan.optimizer_state_dtype == "int8"
    # round-trip
    plan2 = AccelerationPlan.from_json(plan.to_json())
    assert plan2 == plan


def test_zero_strategy_modes_reach_comm_config():
    """The zero1/zero2 library methods set the mode string on the plan
    and the mode survives into the resolved CommConfig (the builder
    keys per-microbatch vs deferred exchange off update_mode)."""
    for name, mode in (("zero1", "zero1"), ("zero2", "zero2")):
        plan = apply_strategy(
            [
                ("mixed_parallel", {"dp": 4, "tp": 2}),
                (name, {"bucket_mb": 2.0}),
            ]
        )
        assert plan.update_sharding == mode
        comm = plan.comm_config()
        assert comm.update_mode == mode
        assert comm.bucket_mb == 2.0
        plan2 = AccelerationPlan.from_json(plan.to_json())
        assert plan2.update_sharding == mode
    off = apply_strategy([("zero1", {"enabled": False})])
    assert off.update_sharding is False
    assert off.comm_config() is None


def test_analyser_update_sharding_hybrid_mesh():
    """On a dp×fsdp mesh with update sharding the flat moments divide
    by dp (replicated over the model axes), not dp × param shards —
    and the saving still beats the per-leaf fsdp sharding it trades
    away whenever dp > fsdp."""
    cfg = get_config("gpt2-1.5b")
    base = apply_strategy([("mixed_parallel", {"dp": 4, "fsdp": 2})])
    zoo = apply_strategy(
        [("mixed_parallel", {"dp": 4, "fsdp": 2}), ("zero1", {})]
    )
    a_base = analyse(cfg, base, 8, 8, 1024, hbm_bytes=16e9)
    a_zoo = analyse(cfg, zoo, 8, 8, 1024, hbm_bytes=16e9)
    n = cfg.num_params()
    # replicated-over-dp per-leaf fsdp sharding: /2; flat dp shard: /4
    assert a_base.opt_bytes_per_chip == pytest.approx(n * 2 * 4 / 2)
    bucket = zoo.comm_bucket_mb * 2**20
    assert a_zoo.opt_bytes_per_chip == pytest.approx(
        n * 2 * 4 / 4 + 2 * bucket
    )


def test_strategy_json_roundtrip():
    s = [("fsdp", {"size": 4}), ("checkpoint", {"policy": "full"})]
    assert strategy_from_json(strategy_to_json(s)) == s


def test_candidates_respect_head_divisibility():
    cfg = get_config("tiny")  # 4 heads
    cands = generate_candidates(cfg, 8, seq=256)
    assert cands
    for strat in cands:
        plan = apply_strategy(strat)
        sizes = plan.mesh.resolved_sizes(8)
        assert cfg.n_head % sizes["tp"] == 0


def test_analyser_memory_scaling():
    cfg = get_config("gpt2-1.5b")
    plan1 = apply_strategy([("mixed_parallel", {"dp": 1, "fsdp": 1})])
    plan8 = apply_strategy([("mixed_parallel", {"dp": 1, "fsdp": 8})])
    a1 = analyse(cfg, plan1, 1, 8, 1024, hbm_bytes=16e9)
    a8 = analyse(cfg, plan8, 8, 8, 1024, hbm_bytes=16e9)
    assert a8.param_bytes_per_chip * 7 < a1.param_bytes_per_chip * 8
    assert a1.num_params == pytest.approx(1.56e9, rel=0.1)


def test_search_returns_feasible(monkeypatch):
    cfg = get_config("tiny")
    strat, plan = search_strategy(cfg, 8, global_batch=16, seq=256)
    sizes = plan.mesh.resolved_sizes(8)
    assert (
        sizes["dp"] * sizes["fsdp"] * sizes["tp"] * sizes["sp"] * sizes["pp"]
        * sizes["ep"] == 8
    )


def test_auto_accelerate_end_to_end():
    cfg = get_config("tiny")
    result = auto_accelerate(cfg, global_batch=16, seq=64)
    state = result.init_state(jax.random.key(0))
    tokens = jnp.zeros((16, 64), jnp.int32)
    batch = jax.device_put(
        {"tokens": tokens, "targets": tokens}, result.batch_sharding
    )
    state, metrics = result.train_step(state, batch)
    assert float(metrics["loss"]) > 0
    em = result.eval_step(state["params"], batch)
    assert float(em["loss"]) > 0


def test_auto_accelerate_with_explicit_strategy():
    cfg = get_config("tiny")
    result = auto_accelerate(
        cfg,
        global_batch=8,
        seq=64,
        strategy=[
            ("half", {}),
            ("mixed_parallel", {"dp": 2, "fsdp": 2, "tp": 2}),
            ("grad_accum", {"steps": 2}),
        ],
    )
    assert result.plan.param_dtype == "bfloat16"
    state = result.init_state(jax.random.key(0))
    tokens = jnp.zeros((8, 64), jnp.int32)
    batch = jax.device_put(
        {"tokens": tokens, "targets": tokens}, result.batch_sharding
    )
    state, metrics = result.train_step(state, batch)
    assert int(state["step"]) == 1


def test_candidates_axes_multiply_to_device_count():
    """tp*sp that merely fits (but does not divide) n_devices must be
    skipped — resolved sizes always multiply out to the device count."""
    from dlrover_tpu.accelerate.engine import generate_candidates
    from dlrover_tpu.accelerate.strategy import apply_strategy
    from dlrover_tpu.models import get_config

    cfg = get_config("tiny", n_head=8)
    for strat in generate_candidates(cfg, 12, seq=128, max_candidates=64):
        plan = apply_strategy(strat)
        sizes = plan.mesh.resolved_sizes(12)
        prod = 1
        for v in sizes.values():
            prod *= v
        assert prod == 12, (strat, sizes)


def test_offload_strategy_chosen_when_memory_forces_it():
    """The search picks the host-offload tier only when resident plans
    don't fit: tiny HBM → offload_opt selected; huge HBM → resident."""
    from dlrover_tpu.accelerate.analyser import analyse
    from dlrover_tpu.accelerate.engine import (
        ANALYTIC_CANDIDATE_CAP,
        _heuristic_score,
        generate_candidates,
    )
    from dlrover_tpu.accelerate.strategy import apply_strategy
    from dlrover_tpu.models import get_config

    cfg = get_config("gpt2-124m", max_seq=512)
    # same uncapped call search_strategy makes for the analytic filter
    cands = [
        (s, apply_strategy(s))
        for s in generate_candidates(
            cfg, 8, 512, max_candidates=ANALYTIC_CANDIDATE_CAP
        )
    ]
    assert any(p.offload_opt_state for _, p in cands)
    # the capped listing still reserves at least one offload variant
    capped = generate_candidates(cfg, 8, 512)
    assert any(
        any(n == "offload_opt" for n, _ in s) for s in capped
    )

    def best_for_hbm(hbm):
        feasible = []
        for strat, plan in cands:
            a = analyse(cfg, plan, 8, 2, 512, hbm)
            if a.fits:
                feasible.append(
                    (_heuristic_score(cfg, plan, 8), strat, plan)
                )
        assert feasible, f"nothing fits at {hbm/1e9:.1f} GB"
        return max(feasible, key=lambda t: t[0])[2]

    roomy = best_for_hbm(64e9)
    assert not roomy.offload_opt_state  # resident wins when it fits
    # squeeze until only the offload tier fits (bf16 moments ~0.5 GB/chip
    # resident at this sharding; offload tier needs ~5x less)
    tight = None
    for hbm in (1.2e9, 0.8e9, 0.6e9, 0.45e9, 0.35e9):
        try:
            tight = best_for_hbm(hbm)
        except AssertionError:
            break
        if tight.offload_opt_state:
            break
    assert tight is not None and tight.offload_opt_state, (
        "offload tier never became the choice under memory pressure"
    )


def test_device_context_probe():
    """Capability probe (atorch device_context.py:10 analog): coherent
    facts on the test platform, cached, and consistent with the
    analyser's HBM sizing."""
    from dlrover_tpu.accelerate.analyser import device_hbm_bytes
    from dlrover_tpu.accelerate.device_context import (
        detect_device_context,
        fp8_supported,
    )

    ctx = detect_device_context()
    assert ctx.platform == "cpu" and not ctx.on_tpu
    assert ctx.n_devices == 8  # the virtual test mesh
    assert ctx.hbm_bytes == device_hbm_bytes()  # single source of truth
    assert not ctx.supports_fp8 and not fp8_supported()
    assert detect_device_context() is ctx  # lru-cached singleton


def test_engine_service_round_trip():
    """The engine client/servicer split (reference auto/engine/
    servicer.py): a CPU-only client submits a model config over the
    typed transport and gets back the same strategy an in-process
    search would produce."""
    from dlrover_tpu.accelerate.engine import search_strategy
    from dlrover_tpu.accelerate.service import EngineClient, EngineService
    from dlrover_tpu.models import get_config

    cfg = get_config("tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
                     vocab_size=128, max_seq=64)
    service = EngineService(port=0)
    client = EngineClient(f"127.0.0.1:{service.port}")
    try:
        strategy, plan = client.search(
            cfg, n_devices=8, global_batch=16, seq=64, mode="heuristic"
        )
        local_strategy, local_plan = search_strategy(
            cfg, 8, 16, 64, mode="heuristic"
        )
        assert strategy == local_strategy
        assert plan.mesh.resolved_sizes(8) == (
            local_plan.mesh.resolved_sizes(8)
        )
        # errors propagate as typed failures, not hangs
        from dlrover_tpu.common import messages as msgs

        resp = client._t.get(
            msgs.StrategySearchRequest(
                model_config_json="{not json", n_devices=8,
                global_batch=8, seq=64,
            )
        )
        assert resp.error
    finally:
        client.close()
        service.stop()
