"""High-level Trainer tests (AtorchTrainer analog)."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import get_config
from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer
from dlrover_tpu.parallel import MeshConfig, build_mesh


@pytest.fixture(autouse=True)
def _run_id(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_RUN_ID", f"tr{os.getpid()}_{time.time_ns()}"
    )


def _cfg():
    return get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32,
    )


def _data_iter(batch=8, seq=32, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        # low-entropy data so a few steps visibly reduce loss
        base = rng.randint(0, 8, size=(batch, seq + 1))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }


def test_trainer_trains_and_checkpoints(tmp_path):
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=2, fsdp=4))
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=12,
        log_interval=4,
        save_interval=6,
        report_to_master=False,
    )
    opt = make_optimizer(learning_rate=3e-3, warmup_steps=2, decay_steps=100)
    trainer = Trainer(cfg, args, _data_iter(), opt, mesh=mesh)
    state = trainer.train()
    assert int(state["step"]) == 12
    # final checkpoint committed
    assert trainer.checkpointer.latest_committed_step() == 12
    step_dir = os.path.join(str(tmp_path), "checkpoints", "step_12")
    assert any(f.endswith(".pack") for f in os.listdir(step_dir))


@pytest.mark.slow
def test_trainer_resumes_from_checkpoint(tmp_path, monkeypatch):
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=2, fsdp=4))
    opt = make_optimizer(learning_rate=3e-3, warmup_steps=2, decay_steps=100)
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=6,
        save_interval=3,
        report_to_master=False,
    )
    t1 = Trainer(cfg, args, _data_iter(), opt, mesh=mesh)
    s1 = t1.train()
    assert int(s1["step"]) == 6
    w1 = np.asarray(jax.tree.leaves(s1["params"])[0])

    # fresh shm namespace: the "restarted worker" must restore from disk
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", f"tr2_{time.time_ns()}")
    args2 = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=9,
        save_interval=3,
        report_to_master=False,
    )
    t2 = Trainer(cfg, args2, _data_iter(seed=1), opt, mesh=mesh)
    t2._init_state()
    assert int(t2.state["step"]) == 6  # resumed, not fresh
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(t2.state["params"])[0]), w1
    )
    s2 = t2.train()
    assert int(s2["step"]) == 9


@pytest.mark.slow  # tier-1 budget: full training loop (~9s); the
# fast e2e representative is test_trainer_trains_and_checkpoints
def test_trainer_loss_decreases(tmp_path):
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=8))
    opt = make_optimizer(learning_rate=5e-3, warmup_steps=2, decay_steps=200)
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=20,
        save_interval=0,
        report_to_master=False,
        eval_interval=0,
    )
    trainer = Trainer(cfg, args, _data_iter(), opt, mesh=mesh)
    trainer._init_state()
    eval_fn = lambda: _data_iter(seed=7)  # noqa: E731
    trainer.eval_iter_fn = eval_fn
    before = trainer.evaluate()["loss"]
    trainer.train()
    after = trainer.evaluate()["loss"]
    assert after < before - 0.3, (before, after)


def test_trainer_eval_only_counts_eval_steps(tmp_path):
    cfg = _cfg()
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=1,
        eval_steps=3,
        save_interval=0,
        report_to_master=False,
    )
    opt = make_optimizer(learning_rate=1e-3)
    trainer = Trainer(
        cfg,
        args,
        _data_iter(),
        opt,
        mesh=build_mesh(MeshConfig(dp=8)),
        eval_iter_fn=lambda: _data_iter(seed=3),
    )
    trainer._init_state()
    m = trainer.evaluate()
    assert m["batches"] == 3.0


@pytest.mark.slow
def test_trainer_data_exhaustion_stops_cleanly(tmp_path):
    cfg = _cfg()
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=50,
        save_interval=0,
        report_to_master=False,
    )
    opt = make_optimizer(learning_rate=1e-3)

    def finite():
        it = _data_iter()
        for _ in range(4):
            yield next(it)

    trainer = Trainer(
        cfg, args, finite(), opt, mesh=build_mesh(MeshConfig(dp=8))
    )
    state = trainer.train()
    assert int(state["step"]) == 4


@pytest.mark.slow  # tier-1 budget: resume/elastic covered fast elsewhere
def test_elastic_remesh_resume(tmp_path, monkeypatch):
    """The elastic hard path (SURVEY §7): train on one mesh, lose the
    cluster, restore the SAME checkpoint onto a DIFFERENT mesh (new
    world shape after a scale event) and keep training — the pack
    format's resharded restore end-to-end through the Trainer."""
    cfg = _cfg()
    opt = make_optimizer(learning_rate=3e-3, warmup_steps=2, decay_steps=100)
    mesh_a = build_mesh(MeshConfig(dp=2, fsdp=4))
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=6,
        save_interval=6,
        report_to_master=False,
    )
    t1 = Trainer(cfg, args, _data_iter(), opt, mesh=mesh_a)
    s1 = t1.train()
    w1 = np.asarray(jax.tree.leaves(s1["params"])[0])

    # "scale event": the replacement job gets a different topology
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", f"remesh_{time.time_ns()}")
    mesh_b = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    args2 = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=9,
        save_interval=0,
        report_to_master=False,
    )
    t2 = Trainer(cfg, args2, _data_iter(seed=2), opt, mesh=mesh_b)
    t2._init_state()
    assert int(t2.state["step"]) == 6  # resumed across the mesh change
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(t2.state["params"])[0]), w1
    )
    # params landed with mesh-B shardings, and training continues
    leaf = jax.tree.leaves(t2.state["params"])[0]
    assert leaf.sharding.mesh.shape["tp"] == 2
    s2 = t2.train()
    assert int(s2["step"]) == 9


# ---------------------------------------------------------------------------
# callbacks (reference: atorch_trainer.py TrainerCallback/TrainerControl)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # tier-1 budget: resume/elastic covered fast elsewhere
def test_prefetch_to_device_preserves_stream(tmp_path):
    """Prefetched batches arrive in order, device-placed, value-equal;
    a prefetching Trainer computes the SAME losses as a direct one
    (reference analog: atorch data/preloader.py H2D overlap)."""
    from dlrover_tpu.train.data_utils import prefetch_to_device
    from dlrover_tpu.train.train_step import batch_sharding

    mesh = build_mesh(MeshConfig(dp=8))
    sh = batch_sharding(mesh)
    src = [
        {"tokens": np.full((8, 4), i, np.int32)} for i in range(7)
    ]
    out = list(prefetch_to_device(iter(src), size=3, sharding=sh))
    assert len(out) == 7
    for i, b in enumerate(out):
        assert int(b["tokens"][0, 0]) == i
        assert b["tokens"].sharding.is_equivalent_to(sh, 2)

    def run(prefetch):
        cfg = _cfg()
        args = TrainerArgs(
            output_dir=str(tmp_path / f"p{prefetch}"), max_steps=4,
            save_interval=0, log_interval=0, resume=False,
            report_to_master=False, prefetch=prefetch,
        )
        t = Trainer(
            cfg, args, _data_iter(), make_optimizer(learning_rate=1e-3),
            mesh=build_mesh(MeshConfig(dp=8)),
        )
        state = t.train()
        return float(state["step"]), float(
            jax.tree.leaves(state["params"])[0].sum()
        )

    direct = run(0)
    prefetched = run(2)
    assert direct == prefetched


def test_trainer_reports_model_info(tmp_path):
    """The trainer announces model statistics to the master once at
    train() start (reference: atorch report_model_info → Brain)."""

    class FakeClient:
        def __init__(self):
            self.model_info = None
            self.steps = []

        def report_model_info(self, **kw):
            self.model_info = kw
            return True

        def report_global_step(self, step, n):
            self.steps.append(step)
            return True

    cfg = _cfg()
    client = FakeClient()
    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=3, save_interval=0,
        log_interval=0, resume=False, report_to_master=True,
    )
    t = Trainer(
        cfg, args, _data_iter(), make_optimizer(learning_rate=1e-3),
        mesh=build_mesh(MeshConfig(dp=8)), master_client=client,
    )
    t.train()
    assert client.model_info is not None
    assert client.model_info["model_name"] == cfg.name
    assert client.model_info["num_params"] == cfg.num_params()
    assert client.model_info["seq_len"] == cfg.max_seq


@pytest.mark.slow
def test_trainer_drives_auto_accelerate_plan(tmp_path):
    """auto_accelerate → Trainer integration: the plan's lowering
    (step builder + state initializer) drives the high-level loop
    unchanged — no re-derivation from TrainerArgs that could drop the
    sp/offload overrides."""
    from dlrover_tpu.accelerate.api import auto_accelerate

    cfg = _cfg()
    res = auto_accelerate(cfg, global_batch=8, seq=32)
    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=3, save_interval=0,
        log_interval=0, resume=False, report_to_master=False,
        eval_at_end=True, eval_steps=2,
    )
    t = Trainer(
        res.model_config, args, _data_iter(), res.optimizer,
        mesh=res.mesh,
        eval_iter_fn=lambda: _data_iter(seed=1),
        step_builder=res.step_builder,
        init_state_fn=res.init_state,
        eval_step_fn=res.eval_step,
    )
    state = t.train()
    assert int(state["step"]) == 3
    # the trainer really used the plan's lowering, not its own — for
    # the train step AND eval (the sp/offload overrides live there)
    assert t._builder is res.step_builder
    assert t._eval_fn is res.eval_step


@pytest.mark.slow
def test_trainer_callbacks_fire_and_log_lr(tmp_path):
    import json

    from dlrover_tpu.train.callbacks import (
        Callback,
        JsonlLoggingCallback,
        LRLoggingCallback,
    )
    from dlrover_tpu.train.optimizer import warmup_cosine

    events = []

    class Recorder(Callback):
        def on_train_begin(self, trainer, control):
            events.append("begin")

        def on_step_end(self, trainer, step, metrics, control):
            events.append(("step", step, "loss" in metrics))

        def on_log(self, trainer, step, logs, control):
            events.append(("log", step))

        def on_save(self, trainer, step, control):
            events.append(("save", step))

        def on_train_end(self, trainer, control):
            events.append("end")

    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=-1))
    sched = warmup_cosine(3e-3, 2, 100)
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=6,
        log_interval=3,
        save_interval=6,
        report_to_master=False,
    )
    opt = make_optimizer(learning_rate=3e-3, warmup_steps=2, decay_steps=100)
    trainer = Trainer(
        cfg, args, _data_iter(), opt, mesh=mesh,
        callbacks=[
            Recorder(),
            LRLoggingCallback(schedule=sched),
            JsonlLoggingCallback(),
        ],
    )
    trainer.train()
    assert events[0] == "begin" and events[-1] == "end"
    assert ("step", 1, True) in events
    assert ("log", 3) in events and ("log", 6) in events
    assert ("save", 6) in events
    # jsonl log carries the schedule's learning rate
    lines = [
        json.loads(x)
        for x in open(os.path.join(str(tmp_path), "train_log.jsonl"))
    ]
    train_recs = [r for r in lines if r["kind"] == "train"]
    assert train_recs and all("learning_rate" in r for r in train_recs)
    assert train_recs[0]["learning_rate"] > 0


@pytest.mark.slow
def test_trainer_early_stopping_and_control_flags(tmp_path):
    from dlrover_tpu.train.callbacks import Callback, EarlyStoppingCallback

    class ForceEval(Callback):
        """Force an eval every step so EarlyStopping sees a stream."""

        def on_step_end(self, trainer, step, metrics, control):
            control.should_eval = True

    class ConstantEval(Callback):
        """Overwrite eval metrics is not possible — instead track calls."""

        evals = 0

        def on_eval(self, trainer, step, eval_metrics, control):
            ConstantEval.evals += 1

    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=-1))
    args = TrainerArgs(
        output_dir=str(tmp_path),
        max_steps=50,
        log_interval=0,
        save_interval=0,
        eval_interval=0,   # evals come ONLY from the control flag
        eval_steps=1,
        report_to_master=False,
        detect_loss_spikes=False,
    )
    opt = make_optimizer(learning_rate=0.0, warmup_steps=1, decay_steps=10)
    stopper = EarlyStoppingCallback(metric="loss", patience=2, min_delta=0.0)
    trainer = Trainer(
        cfg, args, _data_iter(), opt, mesh=mesh,
        eval_iter_fn=lambda: _data_iter(seed=3),
        callbacks=[ForceEval(), ConstantEval(), stopper],
    )
    state = trainer.train()
    # lr=0 -> eval loss never improves after the first -> stop after
    # patience=2 further evals; well before max_steps
    assert int(state["step"]) < 50
    assert ConstantEval.evals >= 3


def test_schedule_breadth():
    """Named LR schedules (HF lr_scheduler_type parity): shapes sane."""
    import numpy as np

    from dlrover_tpu.train.optimizer import build_schedule

    for name in ("warmup_cosine", "warmup_linear", "constant_with_warmup",
                 "polynomial", "inverse_sqrt"):
        sched = build_schedule(name, 1e-3, warmup_steps=10, decay_steps=100)
        v0, v10, v100 = (float(sched(s)) for s in (0, 10, 100))
        assert v0 <= v10 * 1.01, (name, v0, v10)
        assert abs(v10 - 1e-3) < 2e-4, (name, v10)
        assert v100 <= v10, (name, v100, v10)
    assert build_schedule("constant", 5e-4) == 5e-4
    import pytest as _pytest

    with _pytest.raises(ValueError):
        build_schedule("nope", 1e-3)
