"""BERT-family encoder tests (bidirectional attention via cfg.causal).

Reference behaviors: atorch's Megatron-style BERT TP blocks
(distributed_modules/transformer.py:45) — here the same decoder weights
with causal=False; TP/SP sharding machinery is shared.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh


def _cfg(**kw):
    return get_config(
        "tiny-bert",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=256,
        max_seq=32,
        **kw,
    )


def test_bert_configs_registered():
    cfg = get_config("bert-base")
    assert cfg.causal is False
    assert cfg.pos == "learned" and cfg.norm == "layernorm"
    assert cfg.vocab_size % 128 == 0


def test_encoder_is_bidirectional():
    """Changing a LATER token must change an EARLIER position's output
    (it cannot in a causal model)."""
    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    toks = jnp.ones((1, 8), jnp.int32)
    toks2 = toks.at[0, 7].set(5)
    out1 = decoder.forward(params, toks, cfg)
    out2 = decoder.forward(params, toks2, cfg)
    assert not np.allclose(np.asarray(out1[0, 0]), np.asarray(out2[0, 0]))

    # and the causal control: same perturbation, position 0 unchanged
    ccfg = get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=256, max_seq=32,
    )
    cparams = decoder.init(jax.random.key(0), ccfg)
    c1 = decoder.forward(cparams, toks, ccfg)
    c2 = decoder.forward(cparams, toks2, ccfg)
    np.testing.assert_allclose(
        np.asarray(c1[0, 0]), np.asarray(c2[0, 0]), rtol=1e-5
    )


def test_mlm_loss_respects_mask():
    """MLM training: loss computed only at masked positions (the existing
    loss_fn mask channel carries the MLM positions)."""
    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    rng = jax.random.key(1)
    toks = jax.random.randint(rng, (4, 32), 0, 256)
    mlm_mask = jnp.zeros((4, 32)).at[:, ::4].set(1.0)
    batch = {"tokens": toks, "targets": toks, "mask": mlm_mask}
    loss, metrics = decoder.loss_fn(params, batch, cfg)
    assert np.isfinite(float(loss))
    assert float(metrics["tokens"]) == float(mlm_mask.sum())


@pytest.mark.slow
def test_encoder_trains_on_mesh():
    from dlrover_tpu.train import (
        TrainStepBuilder,
        batch_sharding,
        init_train_state,
        make_optimizer,
    )

    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    opt = make_optimizer(learning_rate=1e-3)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    step = TrainStepBuilder(cfg, mesh, opt).build()
    toks = jax.random.randint(jax.random.key(2), (8, 32), 0, 256)
    batch = jax.device_put(
        {"tokens": toks, "targets": toks}, batch_sharding(mesh)
    )
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    assert int(state["step"]) == 1


def test_decode_step_rejects_encoder():
    cfg = _cfg()
    with pytest.raises(ValueError, match="causal"):
        decoder.decode_step(
            decoder.init(jax.random.key(0), cfg),
            jnp.ones((1,), jnp.int32),
            decoder.init_kv_cache(cfg, 1, 8),
            jnp.asarray(0),
            cfg,
        )
