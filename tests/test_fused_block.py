"""Fused multi-step train blocks: K steps per device dispatch.

Pins the two contracts the fused engine must keep:

1. NUMERICS — ``train_block(K)`` is bitwise-identical to K sequential
   ``step_fn`` calls (same params, opt state, per-step metrics), so
   turning the knob can never change training.
2. CADENCE — saves/evals/logs/max_steps land on the SAME global steps
   as the unfused loop for any K (blocks auto-shrink onto boundaries),
   control flags raised mid-block are honored at the next boundary,
   and no step is lost or double-counted.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.models.config import get_config
from dlrover_tpu.observability.loss_spike import LossSpikeDetector
from dlrover_tpu.observability.profiler import StepTimer
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.train.callbacks import Callback
from dlrover_tpu.train.optimizer import make_optimizer
from dlrover_tpu.train.train_step import TrainStepBuilder, init_train_state
from dlrover_tpu.train.trainer import Trainer, TrainerArgs


def _cfg():
    return get_config(
        "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=32,
    )


def _data_iter(batch=8, seq=32, seed=0, limit=None):
    rng = np.random.RandomState(seed)
    n = 0
    while limit is None or n < limit:
        base = rng.randint(0, 8, size=(batch, seq + 1))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }
        n += 1


# ---------------------------------------------------------------------------
# numerics: the block IS K steps
# ---------------------------------------------------------------------------


def test_train_block_bitwise_equals_sequential_steps():
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=-1))
    opt = optax.adamw(1e-3)
    builder = TrainStepBuilder(cfg, mesh, opt)
    K = 4
    it = _data_iter(seed=3)
    batches = [next(it) for _ in range(K)]

    step = jax.jit(builder.step_fn)
    state_seq = init_train_state(jax.random.key(0), cfg, mesh, opt)
    seq_losses, seq_gnorms = [], []
    for b in batches:
        state_seq, m = step(state_seq, b)
        seq_losses.append(float(m["loss"]))
        seq_gnorms.append(float(m["grad_norm"]))

    state_blk = init_train_state(jax.random.key(0), cfg, mesh, opt)
    block = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    state_blk, metrics = builder.build_block()(state_blk, block)

    # state: bitwise over every leaf (params, both Adam moments, step)
    for a, b in zip(jax.tree.leaves(state_seq), jax.tree.leaves(state_blk)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # metrics stack per step, in order, bitwise
    assert metrics["loss"].shape == (K,)
    assert np.array_equal(
        np.asarray(metrics["loss"], np.float32),
        np.asarray(seq_losses, np.float32),
    )
    assert np.array_equal(
        np.asarray(metrics["grad_norm"], np.float32),
        np.asarray(seq_gnorms, np.float32),
    )


def test_block_builder_rejects_offloaded_opt_state():
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=-1))
    builder = TrainStepBuilder(
        cfg, mesh, optax.adamw(1e-3), offload_opt_state=True
    )
    with pytest.raises(NotImplementedError):
        builder.build_block()


# ---------------------------------------------------------------------------
# cadence: fused loop == unfused loop, for awkward K
# ---------------------------------------------------------------------------


class _Recorder(Callback):
    """Record every step/save/eval/log the loop emits, in order."""

    def __init__(self):
        self.steps = []
        self.losses = {}
        self.saves = []
        self.evals = []
        self.logs = []

    def on_step_end(self, trainer, step, metrics, control):
        self.steps.append(step)
        self.losses[step] = metrics["loss"]

    def on_save(self, trainer, step, control):
        self.saves.append(step)

    def on_eval(self, trainer, step, metrics, control):
        self.evals.append(step)

    def on_log(self, trainer, step, logs, control):
        self.logs.append(step)


def _run(tmp_path, block_k, max_steps=13, save_interval=6,
         eval_interval=0, callbacks=None, limit=None, tag=""):
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=2, fsdp=4))
    rec = _Recorder()
    args = TrainerArgs(
        output_dir=str(tmp_path / f"k{block_k}{tag}"),
        max_steps=max_steps,
        log_interval=4,
        save_interval=save_interval,
        eval_interval=eval_interval,
        report_to_master=False,
        block_k=block_k,
    )
    trainer = Trainer(
        cfg, args, _data_iter(limit=limit),
        make_optimizer(learning_rate=3e-3, warmup_steps=2, decay_steps=100),
        mesh=mesh,
        eval_iter_fn=(lambda: _data_iter(seed=9)) if eval_interval else None,
        callbacks=[rec] + list(callbacks or []),
    )
    state = trainer.train()
    return trainer, rec, state


# tier-1 budget: block_k=3 exercises the auto-shrink boundary logic on
# the fast tier; the other widths re-prove the same property and ride
# the slow tier
@pytest.mark.parametrize(
    "block_k",
    [3] + [pytest.param(k, marks=pytest.mark.slow) for k in (5, 8, 13, 64)],
)
def test_blockwise_cadences_match_stepwise(tmp_path, block_k):
    # 13 steps, save every 6, log every 4: none of these divide the
    # block sizes, so every boundary requires the auto-shrink
    _, base, state1 = _run(tmp_path, 1, tag="base%d" % block_k)
    _, fused, statek = _run(tmp_path, block_k)

    assert base.steps == list(range(1, 14))
    assert fused.steps == base.steps  # no lost or double-counted steps
    assert fused.saves == base.saves == [6, 12]
    assert fused.logs == base.logs == [4, 8, 12]
    assert int(state1["step"]) == int(statek["step"]) == 13
    # identical batches + bitwise-equivalent engine ⇒ identical losses
    for s in base.steps:
        assert fused.losses[s] == base.losses[s]


@pytest.mark.slow  # tier-1 budget: trainer covers these cadence/exhaustion paths fast
def test_blockwise_eval_cadence_and_final_partial_block(tmp_path):
    _, rec, state = _run(
        tmp_path, 4, max_steps=10, save_interval=0, eval_interval=5,
    )
    assert rec.steps == list(range(1, 11))
    assert rec.evals == [5, 10]  # block shrank 4→1 to land on step 5
    assert int(state["step"]) == 10


@pytest.mark.slow  # tier-1 budget: trainer covers these cadence/exhaustion paths fast
def test_blockwise_data_exhaustion_runs_partial_block(tmp_path):
    # 10 batches with block_k=4: final block is a partial (2-step) one;
    # every consumed batch must become exactly one step
    _, rec, state = _run(
        tmp_path, 4, max_steps=100, save_interval=0, limit=10,
    )
    assert rec.steps == list(range(1, 11))
    assert int(state["step"]) == 10


class _FlagAt(Callback):
    """Raise a control flag from inside the drain, mid-block."""

    def __init__(self, step, flag):
        self._step = step
        self._flag = flag

    def on_step_end(self, trainer, step, metrics, control):
        if step == self._step:
            setattr(control, self._flag, True)


@pytest.mark.slow
def test_mid_block_save_flag_honored_at_next_boundary(tmp_path):
    # drain of block [1..5] sees step 3 raise should_save while block
    # [6..10] is in flight: the save must land at a block end (10 or
    # 15), at most ONE block after the flag, with no mid-block save
    trainer, rec, _ = _run(
        tmp_path, 5, max_steps=20, save_interval=0,
        callbacks=[_FlagAt(3, "should_save")],
    )
    assert len(rec.saves) >= 1
    assert rec.saves[0] in (10, 15)  # next boundary after the drain
    assert rec.saves[0] % 5 == 0
    # the save is real: that step's checkpoint committed
    assert trainer.checkpointer.latest_committed_step() >= rec.saves[0]


@pytest.mark.slow
def test_mid_block_stop_flag_stops_at_boundary(tmp_path):
    _, rec, state = _run(
        tmp_path, 5, max_steps=100, save_interval=0,
        callbacks=[_FlagAt(2, "should_stop")],
    )
    final = int(state["step"])
    # stopped at a block boundary, within one block of the flag
    assert final % 5 == 0 and final <= 15
    assert rec.steps == list(range(1, final + 1))


def test_next_block_k_never_overshoots_boundaries(tmp_path):
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(dp=-1))
    args = TrainerArgs(
        output_dir=str(tmp_path), max_steps=97, save_interval=7,
        eval_interval=5, memory_save_interval=3, block_k=8,
        report_to_master=False,
    )
    trainer = Trainer(
        cfg, args, _data_iter(),
        make_optimizer(learning_rate=1e-3, warmup_steps=2, decay_steps=10),
        mesh=mesh,
    )
    for step in range(0, 97):
        k = trainer._next_block_k(step)
        assert 1 <= k <= 8
        end = step + k
        assert end <= 97
        for boundary in (7, 5, 3):
            # no cadence boundary strictly inside (step, end)
            for s in range(step + 1, end):
                assert s % boundary != 0, (step, k, boundary)


# ---------------------------------------------------------------------------
# stacked-metrics ingestion (loss spikes at the exact step; timer)
# ---------------------------------------------------------------------------


def test_loss_spike_update_block_fires_at_exact_step(tmp_path):
    det = LossSpikeDetector(
        save_dir=str(tmp_path), min_iter=0, min_loss=1.0, zscore=None
    )
    # warm block, then a block whose 3rd step spikes
    assert det.update_block(0, np.asarray([0.5, 0.6, 0.5, 0.4])) == []
    spiked = det.update_block(4, np.asarray([0.5, 0.4, 7.5, 0.5]))
    assert spiked == [6]
    assert det.spikes == [(6, 7.5)]
    # jax arrays (what a drained metrics block actually holds) work too
    spiked = det.update_block(8, jnp.asarray([9.0, 0.3]))
    assert spiked == [8]


def test_step_timer_attributes_block_time_per_step():
    t = StepTimer(window=16)
    t.record(0.8, n_steps=8)
    assert t.steps == 8
    assert t.mean_s == pytest.approx(0.1)
    assert t.steps_per_s == pytest.approx(10.0)
    t.record(0.1)  # unfused records still work alongside
    assert t.steps == 9
    assert t.mean_s == pytest.approx(0.1)
