"""Ray platform adapter against a wire-level Jobs-API server.

Reference parity: scheduler/ray.py + ray_job_submitter.py:48. Same
strategy as test_kube_http.py: a stdlib HTTP server speaking Ray's
actual /api/jobs/ REST protocol, and the SAME SliceScaler the k8s path
uses driving worker lifecycle through RayJobSubmitter unmodified.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dlrover_tpu.cluster.crd import (
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    TPUSliceSpec,
)
from dlrover_tpu.cluster.ray import RayJobsApi, RayJobSubmitter
from dlrover_tpu.cluster.scaler import SliceScaler
from dlrover_tpu.master.node_manager import ScalePlan


class _RayHandler(BaseHTTPRequestHandler):
    jobs = None  # {submission_id: record}; set by fixture
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, code, obj):
        raw = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n)) if n else {}
        if self.path == "/api/jobs/":
            sid = body["submission_id"]
            if sid in self.jobs:
                self._send(400, {"error": "exists"})
                return
            self.jobs[sid] = {
                "submission_id": sid,
                "status": "RUNNING",
                "entrypoint": body["entrypoint"],
                "runtime_env": body.get("runtime_env", {}),
                "metadata": body.get("metadata", {}),
            }
            self._send(200, {"submission_id": sid})
        elif self.path.endswith("/stop"):
            sid = self.path.split("/")[-2]
            if sid not in self.jobs:
                self._send(404, {})
                return
            self.jobs[sid]["status"] = "STOPPED"
            self._send(200, {"stopped": True})
        else:
            self._send(404, {})

    def do_GET(self):  # noqa: N802
        if self.path == "/api/jobs/":
            self._send(200, list(self.jobs.values()))
            return
        sid = self.path.split("/")[-1]
        if sid in self.jobs:
            self._send(200, self.jobs[sid])
        else:
            self._send(404, {})

    def do_DELETE(self):  # noqa: N802
        sid = self.path.split("/")[-1]
        if self.jobs.pop(sid, None) is None:
            self._send(404, {})
        else:
            self._send(200, {})


@pytest.fixture()
def ray_server():
    jobs = {}
    handler = type("H", (_RayHandler,), {"jobs": jobs})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield jobs, f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    server.server_close()


def _job(replicas=2):
    return ElasticJob(
        "demo",
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=replicas, slice=TPUSliceSpec(hosts_per_slice=1)
                )
            },
            min_hosts=1,
            max_hosts=4,
        ),
    )


def test_jobs_api_roundtrip(ray_server):
    jobs, url = ray_server
    api = RayJobsApi(url)
    api.submit("w0", "python agent.py", env={"A": "1"}, metadata={"r": "0"})
    assert api.status("w0") == "RUNNING"
    assert [j["submission_id"] for j in api.list()] == ["w0"]
    assert api.stop("w0") is True
    assert api.status("w0") == "STOPPED"
    api.delete("w0")
    assert api.status("w0") is None
    assert api.stop("gone") is False


def test_slice_scaler_drives_ray_jobs(ray_server):
    """The SAME ScalePlan flow as the k8s path, submitted as Ray jobs:
    scale up, relaunch keeps rank + env, scale-in stops jobs."""
    jobs, url = ray_server
    api = RayJobsApi(url)
    sub = RayJobSubmitter(
        api, master_addr="10.0.0.1:8000", run_id="r77"
    )
    scaler = SliceScaler(
        _job(), submit_fn=sub.submit, delete_fn=sub.delete,
        master_addr="10.0.0.1:8000",
    )
    plan = ScalePlan()
    plan.worker_num = 2
    scaler.scale(plan)
    assert sorted(jobs) == ["demo-worker-0", "demo-worker-1"]
    env = jobs["demo-worker-0"]["runtime_env"]["env_vars"]
    assert env["DLROVER_MASTER_ADDR"] == "10.0.0.1:8000"
    assert env["DLROVER_TPU_RUN_ID"] == "r77"
    # rank label rides Ray job metadata
    assert (
        jobs["demo-worker-0"]["metadata"][
            "elasticjob.dlrover/rank-index"
        ]
        == "0"
    )
    assert sub.live_jobs() and set(sub.live_jobs()) == set(jobs)

    # scale in to 1: worker-1's job is stopped+removed
    plan2 = ScalePlan()
    plan2.worker_num = 1
    scaler.scale(plan2)
    assert sorted(jobs) == ["demo-worker-0"]
