"""Per-request sampling on the serving engine (serving/engine.py).

Three layers of pins:

1. ENGINE == OFFLINE, bitwise: a sampled request served by the engine
   (either kernel, spec on or off) yields the exact token stream of
   ``generate.sample`` at the same seed — the fused in-step sampler and
   the offline scan share ``warp_logits``/``draw_token`` and the
   fold-in-absolute-position key schedule.
2. DETERMINISM is positional, not temporal: the same seeded request
   produces the same stream regardless of which slot it lands in, what
   traffic surrounds it, or whether it was re-admitted after a replica
   death mid-stream (slow-tier failover drill).
3. DISTRIBUTION: ``draw_token`` empirically follows the renormalized
   truncation of softmax(logits/T) under top-k/top-p at >= 1e4 draws,
   and masked tokens are NEVER drawn.

Plus the poisoned-request regression: invalid sampling params fail the
submitting future with ``AdmissionError`` at admission — the step-loop
thread survives and neighbouring requests complete untouched.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.serving.engine import ServingEngine  # noqa: E402
from dlrover_tpu.serving.scheduler import (  # noqa: E402
    AdmissionError,
    SamplingParams,
    Scheduler,
)


@pytest.fixture(scope="module")
def setup():
    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    return cfg, params


def _offline(params, cfg, prompt, max_new, sp: SamplingParams):
    return [
        int(t)
        for t in np.asarray(
            generate.sample(
                params, cfg, jnp.asarray([prompt], jnp.int32), max_new,
                rng=jax.random.key(sp.seed),
                temperature=sp.temperature, top_k=sp.top_k,
                top_p=sp.top_p,
            )[0]
        )
    ]


def _engine(params, cfg, *, n_slots=2, spec_k=0, paged=True):
    sched = Scheduler(replica="samp")
    eng = ServingEngine(
        params, cfg, sched, n_slots=n_slots, max_len=32, page_size=4,
        mode="bf16", prefill_chunk=4, paged=paged, spec_k=spec_k,
    )
    return sched, eng


SP = SamplingParams(temperature=0.9, top_k=5, top_p=0.9, seed=3)


# one combo stays fast as the tier-1 pin; the other three cover the
# same engine==offline property on the remaining kernel/spec paths and
# run on the slow tier (870s budget — see _SLOW_LEDGER)
@pytest.mark.parametrize("paged,spec_k", [
    pytest.param(False, 0),
    pytest.param(True, 0, marks=pytest.mark.slow),
    pytest.param(False, 3, marks=pytest.mark.slow),
    pytest.param(True, 3, marks=pytest.mark.slow),
])
def test_sampled_engine_matches_offline_bitwise(setup, paged, spec_k):
    cfg, params = setup
    prompts = [[2, 3, 4, 2, 3, 4, 2], [9, 10, 9, 10, 9]]
    max_new = [8, 6]
    sps = [SP, SamplingParams(temperature=1.3, top_k=0, top_p=0.8, seed=41)]
    sched, eng = _engine(params, cfg, spec_k=spec_k, paged=paged)
    reqs = [
        sched.submit(p, m, sampling=sp)
        for p, m, sp in zip(prompts, max_new, sps)
    ]
    eng.drain(timeout=600)
    outs = [r.future.result(timeout=5) for r in reqs]
    refs = [
        _offline(params, cfg, p, m, sp)
        for p, m, sp in zip(prompts, max_new, sps)
    ]
    assert outs == refs


@pytest.mark.slow
def test_seed_stable_across_slot_reordering(setup):
    """Same seeded request, two very different traffic mixes (slot
    index, companions, admit order all differ) → identical stream.
    Draw keys fold in the absolute buffer position, never a step
    counter, so batching is invisible."""
    cfg, params = setup
    prompt, max_new = [4, 5, 6, 4, 5], 7
    ref = _offline(params, cfg, prompt, max_new, SP)

    sched_a, eng_a = _engine(params, cfg, n_slots=2)
    ra = sched_a.submit(prompt, max_new, sampling=SP)
    sched_a.submit([1, 2, 3], 4)
    eng_a.drain(timeout=600)

    sched_b, eng_b = _engine(params, cfg, n_slots=3)
    # three greedy fillers ahead of it, and a later priority bump means
    # the target is admitted last into whichever slot frees first
    for filler in ([7, 8], [11, 12, 13], [14, 15, 16, 17]):
        sched_b.submit(filler, 5)
    rb = sched_b.submit(prompt, max_new, sampling=SP, priority=1)
    eng_b.drain(timeout=600)

    assert ra.future.result(timeout=5) == ref
    assert rb.future.result(timeout=5) == ref


@pytest.mark.slow
def test_failover_readmission_reproduces_sampled_output(setup):
    """Router failover drill with sampled requests: the survivor
    re-prefills from the prompt, and position-indexed draws make the
    re-admitted continuation bitwise the original's."""
    import time

    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params = setup
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(1, 32, size=n)) for n in (3, 7, 5, 9, 4, 6)]
    max_new = [6, 5, 8, 4, 7, 5]
    sps = [
        SamplingParams(temperature=0.8 + 0.1 * i, top_k=4 + i,
                       top_p=0.9, seed=100 + i)
        for i in range(len(prompts))
    ]
    refs = [
        _offline(params, cfg, p, m, sp)
        for p, m, sp in zip(prompts, max_new, sps)
    ]
    kw = dict(n_slots=2, max_len=32, page_size=4, mode="bf16",
              prefill_chunk=4, idle_sleep=0.001)
    r0 = ServingReplica("samp-0", params, cfg, **kw).start()
    r1 = ServingReplica("samp-1", params, cfg, **kw).start()
    try:
        router = ReplicaRouter([r0, r1])
        reqs = [
            router.submit(p, m, sampling=sp)
            for p, m, sp in zip(prompts, max_new, sps)
        ]
        time.sleep(1.0)
        r1.kill()
        moved = router.poll()
        outs = router.wait_all(timeout=600)
    finally:
        r0.stop()
        r1.kill()
    assert outs == refs
    assert moved == r0.server.scheduler.re_admitted
    assert all(r.future.done() for r in reqs)


def test_draw_token_distribution_frequency(setup):
    """>= 1e4 draws of ``draw_token`` match the renormalized truncated
    softmax within 4-sigma per token, and tokens masked out by
    top-k/top-p are never drawn."""
    cfg, _ = setup
    n, v = 10_000, cfg.vocab_size
    temp, top_k, top_p = 1.3, 8, 0.9
    logits = jax.random.normal(jax.random.key(7), (v,)) * 2.0
    warped = generate.warp_logits(logits, temp, top_k, top_p)
    probs = np.asarray(jax.nn.softmax(warped))
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        jax.random.key(123), jnp.arange(n)
    )
    draws = np.asarray(
        jax.vmap(
            lambda k: generate.draw_token(logits, k, temp, top_k, top_p)
        )(keys)
    )
    counts = np.bincount(draws, minlength=v)
    # hard mask: zero-probability tokens never drawn
    assert counts[probs == 0.0].sum() == 0
    assert (probs > 0).sum() <= top_k
    # frequency within 4 sigma of the binomial expectation, per token
    exp = n * probs
    sigma = np.sqrt(n * probs * (1 - probs))
    assert np.all(np.abs(counts - exp) <= 4 * sigma + 1), (
        counts, np.round(exp, 1)
    )
    # and in aggregate: total variation distance is small
    tv = 0.5 * np.abs(counts / n - probs).sum()
    assert tv < 0.03, tv


def test_warp_logits_units():
    logits = jnp.asarray([4.0, 3.0, 2.0, 1.0, 0.0])
    # top-k keeps exactly k best, masks the rest to -inf
    w = generate.warp_logits(logits, 1.0, top_k=2)
    np.testing.assert_array_equal(
        np.asarray(w), [4.0, 3.0, -np.inf, -np.inf, -np.inf]
    )
    # disabled warps are exact no-ops of temperature scaling
    w = generate.warp_logits(logits, 2.0, top_k=0, top_p=1.0)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(logits) / 2.0)
    # top-p keeps the smallest prefix reaching the mass, at least one
    w = generate.warp_logits(logits, 1.0, top_p=1e-6)
    np.testing.assert_array_equal(
        np.asarray(w), [4.0, -np.inf, -np.inf, -np.inf, -np.inf]
    )


def test_sampling_params_validate():
    with pytest.raises(AdmissionError):
        SamplingParams(temperature=-0.5).validate()
    with pytest.raises(AdmissionError):
        SamplingParams(temperature=float("nan")).validate()
    with pytest.raises(AdmissionError):
        SamplingParams(top_k=-1).validate()
    with pytest.raises(AdmissionError):
        SamplingParams(top_p=0.0).validate()
    with pytest.raises(AdmissionError):
        SamplingParams(top_p=float("nan")).validate()
    SamplingParams().validate()  # defaults are valid
    SamplingParams(temperature=1.0, top_k=5, top_p=0.5).validate()


@pytest.mark.slow
def test_poisoned_request_fails_future_and_loop_survives(setup):
    """A request with invalid sampling params mid-stream fails ITS OWN
    future with AdmissionError; the engine keeps stepping and the
    surrounding requests complete bitwise."""
    cfg, params = setup
    good_a, good_c = [1, 2, 3, 1, 2], [6, 7, 8, 6, 7]
    refs = [
        [
            int(t) for t in np.asarray(
                generate.greedy(
                    params, cfg, jnp.asarray([p], jnp.int32), 5
                )[0]
            )
        ]
        for p in (good_a, good_c)
    ]
    sched, eng = _engine(params, cfg, n_slots=2)
    ra = sched.submit(good_a, 5)
    # frozen dataclass blocks accidental construction of bad params at
    # submit; a poisoned object can still arrive (deserialization, bad
    # client) — bypass __init__ the same way pickle would
    bad = SamplingParams.__new__(SamplingParams)
    object.__setattr__(bad, "temperature", -1.0)
    object.__setattr__(bad, "top_k", 0)
    object.__setattr__(bad, "top_p", 1.0)
    object.__setattr__(bad, "seed", 0)
    rb = sched.submit([4, 5], 4, sampling=bad)
    rc = sched.submit(good_c, 5)
    eng.drain(timeout=600)
    with pytest.raises(AdmissionError):
        rb.future.result(timeout=5)
    assert ra.future.result(timeout=5) == refs[0]
    assert rc.future.result(timeout=5) == refs[1]
    # the poisoned request never held pages or a slot
    assert eng.active_slots() == 0
    assert eng.alloc.free_pages == eng.geom.n_pages - 1
    # and the loop still works afterwards
    rd = sched.submit(good_a, 5)
    eng.drain(timeout=600)
    assert rd.future.result(timeout=5) == refs[0]
