"""Full-stack elasticity drill (VERDICT r3 #7).

The production composition in ONE job: a real master process, two
launcher/agent process groups training DeepFM-with-dense-tower, a
two-process KvServer ring carrying the sparse tier, and a remote
coworker feed (this test IS the producer pool, pushing packed CTR
batches over TCP into each worker's shm ring). Mid-run an agent AND a
sparse server are killed; recovery must complete inside 60 s each and
convergence continue to the end.

Reference story: docs/tech_report/fault_tolerance_exps.md:1-60 — the
pieces are individually proven (test_multinode, test_sparse_serving,
test_coworker); this is their composition.
"""

import json
import multiprocessing as mp
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from elastic_harness import (
    REPO,
    collect as _collect,
    drain as _drain,
    drain_now as _drain_now,
    kill_tree as _kill_tree,
    make_env as _env,
    start_master as _start_master,
)
from test_sparse_serving import _spawn_server

RECOVERY_BUDGET_S = 60.0


def _launch_drill_agent(run_id, node_id, addr, kv_json, steps, wire_token):
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.agent.launcher",
            "--nnodes",
            "1:2",
            "--node-id",
            str(node_id),
            "--nproc",
            "1",
            "--master-addr",
            addr,
            "--",
            sys.executable,
            "examples/train_deepfm_fullstack.py",
            "--steps",
            str(steps),
            "--kv-addrs",
            kv_json,
        ],
        cwd=REPO,
        env=_env(
            f"{run_id}_n{node_id}",
            {
                "DLROVER_TPU_COORDINATOR_PORT": "0",
                # the job-wide wire credential: run ids are node-scoped
                # here (shm isolation on one box), so the cross-host
                # planes authenticate with this instead
                "DLROVER_TPU_WIRE_TOKEN": wire_token,
            },
        ),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )


def _synthetic_ctr(rng, n, fields, n_dense):
    cat = rng.integers(0, 50, size=(n, fields)).astype(np.int64)
    dense = rng.normal(size=(n, n_dense)).astype(np.float32)
    hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
    p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
    labels = (rng.random(n) < p).astype(np.float32)
    return cat, dense, labels


class _Producer(threading.Thread):
    """One remote coworker: pushes the fixed dataset over TCP forever
    (until stopped or the worker's ingress goes away)."""

    def __init__(self, port, batch):
        super().__init__(daemon=True)
        self.port = port
        self.batch = batch
        self.stop_ev = threading.Event()

    def run(self):
        from dlrover_tpu.data.coworker import RemoteBatchWriter

        try:
            w = RemoteBatchWriter(("127.0.0.1", self.port), timeout=30.0)
            while not self.stop_ev.is_set():
                w.put(self.batch)
                time.sleep(0.02)
        except Exception:  # noqa: BLE001 — worker gone/done
            return


_STEP_RE = re.compile(r"\[fullstack\] step (\d+) loss ([0-9.]+)")
_METRICS_RE = re.compile(r"metrics endpoint on port (\d+)")


def _master_metrics(port: int) -> dict:
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/json", timeout=10
    ) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_fullstack_elasticity_drill(monkeypatch):
    run_id = f"drill{os.getpid()}"
    wire_token = f"{run_id}-wire"
    # the KvServer children (mp spawn) inherit this env
    monkeypatch.setenv("DLROVER_TPU_WIRE_TOKEN", wire_token)
    ctx = mp.get_context("spawn")
    kv_procs, kv_addrs = [], {}
    for name in ("s0", "s1"):
        p, addr = _spawn_server(ctx)
        kv_procs.append(p)
        kv_addrs[name] = addr
    kv_json = json.dumps({k: list(v) for k, v in kv_addrs.items()})

    master = agents = None
    producers = []
    try:
        master, mq, mlines, maddr = _start_master(
            run_id,
            argv_extra=("--num-workers", "2"),
            env_extra={
                "DLROVER_TPU_WIRE_TOKEN": wire_token,
                # detect the killed agent INSIDE the drill window (the
                # 300 s default would outlive the whole test), so the
                # goodput tracker sees the failure
                "DLROVER_TPU_CTX_HEARTBEAT_TIMEOUT_S": "35",
            },
        )
        # the metrics endpoint is logged during prepare(), before the
        # address line _start_master scraped — so it is already in mlines
        metrics_port = None
        for line in mlines:
            m = _METRICS_RE.search(line)
            if m:
                metrics_port = int(m.group(1))
        assert metrics_port, "".join(mlines)[-2000:]
        agents = [
            _launch_drill_agent(
                run_id, i, maddr, kv_json, steps=60,
                wire_token=wire_token,
            )
            for i in (0, 1)
        ]
        queues = [_drain(a) for a in agents]
        logs = [[], []]

        # discover each worker's TCP ingress and become its producers
        rng = np.random.default_rng(7)
        batch_data = _synthetic_ctr(rng, 256, fields=6, n_dense=4)
        batch = {
            "cat": batch_data[0],
            "dense": batch_data[1],
            "labels": batch_data[2],
        }
        # the port line can interleave with worker logger output on the
        # merged pipe: match the digits explicitly (the script prints
        # the line twice so one clean copy always exists)
        port_re = re.compile(r"\[fullstack\] feed port (\d+)\b")
        for i in (0, 1):
            line = _collect(
                queues[i],
                logs[i],
                until=lambda l: bool(port_re.search(l)),
                deadline=time.time() + 120,
            )
            assert line, (
                f"worker {i} never served its feed port:\n"
                + "".join(logs[i][-40:])
            )
            port = int(port_re.search(line).group(1))
            prod = _Producer(port, batch)
            prod.start()
            producers.append(prod)

        def steps_seen(log):
            out = {}
            for line in log:
                m = _STEP_RE.search(line)
                if m:
                    out[int(m.group(1))] = float(m.group(2))
            return out

        # both workers make progress against the shared sparse tier
        for i in (0, 1):
            assert _collect(
                queues[i],
                logs[i],
                until=lambda l: bool(
                    (m := _STEP_RE.search(l)) and int(m.group(1)) >= 8
                ),
                deadline=time.time() + 180,
            ), f"worker {i} stalled:\n" + "".join(logs[i][-40:])
        first_losses = steps_seen(logs[0])
        first = first_losses[min(first_losses)]
        # goodput window opens here: startup (rendezvous + first jit
        # compile) is excluded — the reference's 95% headline is a
        # steady-state number too, not a cold-start one
        gp0 = _master_metrics(metrics_port)
        t_window_open = time.time()

        # ---- failure 1: kill agent 1 (whole process group) ------------
        t_kill_agent = time.time()
        producers[1].stop_ev.set()
        _kill_tree(agents[1])
        # recovery: the surviving worker keeps stepping (PS-style
        # training has no collective coupling to the dead peer) and the
        # master stays up — within the budget
        base = max(steps_seen(logs[0]))
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: bool(
                (m := _STEP_RE.search(l)) and int(m.group(1)) > base
            ),
            deadline=t_kill_agent + RECOVERY_BUDGET_S,
        )
        assert line, (
            "worker 0 made no progress within 60s of the agent kill:\n"
            + "".join(logs[0][-40:])
        )
        recovery_agent_s = time.time() - t_kill_agent
        assert recovery_agent_s < RECOVERY_BUDGET_S
        assert master.poll() is None, "master died with the agent"

        # ---- failure 2: kill sparse server s0 -------------------------
        t_kill_kv = time.time()
        kv_procs[0].kill()
        kv_procs[0].join(timeout=10)
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: "[fullstack] sparse failover" in l,
            deadline=t_kill_kv + RECOVERY_BUDGET_S,
        )
        assert line and "'s1'" in line, (
            "worker 0 never failed over the sparse ring:\n"
            + "".join(logs[0][-40:])
        )
        base = max(steps_seen(logs[0]))
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: bool(
                (m := _STEP_RE.search(l)) and int(m.group(1)) > base
            ),
            deadline=t_kill_kv + RECOVERY_BUDGET_S,
        )
        assert line, (
            "worker 0 made no step within 60s of the KvServer kill:\n"
            + "".join(logs[0][-40:])
        )
        recovery_kv_s = time.time() - t_kill_kv
        assert recovery_kv_s < RECOVERY_BUDGET_S

        # the master must have SEEN failure 1 (heartbeat timeout) before
        # the goodput window closes — otherwise the goodput number would
        # be vacuous (no stall ever marked)
        deadline = time.time() + 60
        while time.time() < deadline:
            if _master_metrics(metrics_port)["counters"][
                "node_failures_total"
            ] >= 1:
                break
            time.sleep(2)
        else:
            raise AssertionError(
                "master never detected the killed agent"
            )

        # ---- convergence continues to the end -------------------------
        assert _collect(
            queues[0],
            logs[0],
            until=lambda l: "[fullstack] done" in l,
            deadline=time.time() + 240,
        ), "worker 0 never finished:\n" + "".join(logs[0][-40:])
        losses = steps_seen(logs[0])
        final = losses[max(losses)]
        assert np.isfinite(final)
        # through both failures (incl. re-initialized embedding rows)
        # the loss ends below where it started
        assert final < first, (first, final)

        # ---- goodput across the two failures (VERDICT r4 ask #5) ------
        # windowed: (lost-time delta) / (wall delta) between the sample
        # taken before failure 1 and now, from the LIVE master's
        # GoodputTracker — the measured analog of the reference's
        # 69%→95% headline (reference README.md:57-58)
        gp1 = _master_metrics(metrics_port)
        window_wall = time.time() - t_window_open
        lost = (
            gp1["goodput_lost_seconds"] - gp0["goodput_lost_seconds"]
        )
        goodput = max(0.0, 1.0 - lost / max(window_wall, 1e-9))
        assert goodput >= 0.90, (
            f"goodput {goodput:.3f} across the two failures "
            f"(lost {lost:.1f}s of {window_wall:.1f}s)"
        )
        artifact = {
            "drill": "test_fullstack_elasticity_drill",
            "failures": [
                {"kind": "agent_killed", "recovery_s": round(recovery_agent_s, 2)},
                {"kind": "sparse_server_killed", "recovery_s": round(recovery_kv_s, 2)},
            ],
            "recovery_budget_s": RECOVERY_BUDGET_S,
            "goodput_across_failures": round(goodput, 4),
            "goodput_lost_s": round(lost, 2),
            "goodput_window_s": round(window_wall, 2),
            "goodput_since_master_start": gp1["goodput"],
            "node_failures_seen_by_master": gp1["counters"][
                "node_failures_total"
            ],
        }
        out_path = os.environ.get(
            "DLROVER_TPU_DRILL_ARTIFACT",
            os.path.join(REPO, "DRILL_r05.json"),
        )
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"\n[drill] {json.dumps(artifact)}")
    finally:
        for prod in producers:
            prod.stop_ev.set()
        for a in agents or []:
            _kill_tree(a)
        if master is not None:
            master.kill()
        for p in kv_procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)
