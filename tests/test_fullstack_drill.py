"""Full-stack elasticity drill (VERDICT r3 #7).

The production composition in ONE job: a real master process, two
launcher/agent process groups training DeepFM-with-dense-tower, a
two-process KvServer ring carrying the sparse tier, and a remote
coworker feed (this test IS the producer pool, pushing packed CTR
batches over TCP into each worker's shm ring). Mid-run an agent AND a
sparse server are killed; recovery must complete inside 60 s each and
convergence continue to the end.

Reference story: docs/tech_report/fault_tolerance_exps.md:1-60 — the
pieces are individually proven (test_multinode, test_sparse_serving,
test_coworker); this is their composition.
"""

import json
import multiprocessing as mp
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from elastic_harness import (
    REPO,
    collect as _collect,
    drain as _drain,
    drain_now as _drain_now,
    kill_tree as _kill_tree,
    launch_agent as _launch_agent,
    make_env as _env,
    start_master as _start_master,
)
from test_sparse_serving import _spawn_server

from dlrover_tpu.observability.tracing import merge_trace_dir

RECOVERY_BUDGET_S = 60.0


def _launch_drill_agent(
    run_id, node_id, addr, kv_json, steps, wire_token, trace_dir
):
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.agent.launcher",
            "--nnodes",
            "1:2",
            "--node-id",
            str(node_id),
            "--nproc",
            "1",
            "--master-addr",
            addr,
            "--",
            sys.executable,
            "examples/train_deepfm_fullstack.py",
            "--steps",
            str(steps),
            "--kv-addrs",
            kv_json,
        ],
        cwd=REPO,
        env=_env(
            f"{run_id}_n{node_id}",
            {
                "DLROVER_TPU_COORDINATOR_PORT": "0",
                # the job-wide wire credential: run ids are node-scoped
                # here (shm isolation on one box), so the cross-host
                # planes authenticate with this instead
                "DLROVER_TPU_WIRE_TOKEN": wire_token,
                # the flight recorder: one JOB-wide trace dir (run ids
                # are node-scoped, so this is the cross-process merge
                # key); the agent streams role=agent spans, its workers
                # inherit the dir and stream role=worker
                "DLROVER_TPU_TRACE_DIR": trace_dir,
            },
        ),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )


def _find_worker_pid(agent_pid, script="train_deepfm_fullstack.py",
                     deadline_s=30.0):
    """The agent's worker child: ppid == agent AND running the drill
    script (the launcher itself also matches the script name in argv)."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for pid_dir in os.listdir("/proc"):
            if not pid_dir.isdigit():
                continue
            try:
                with open(f"/proc/{pid_dir}/stat") as f:
                    ppid = int(f.read().rsplit(")", 1)[1].split()[1])
                if ppid != agent_pid:
                    continue
                with open(f"/proc/{pid_dir}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\0", b" ").decode(
                        errors="replace"
                    )
                if script in cmd:
                    return int(pid_dir)
            except (OSError, ValueError, IndexError):
                continue
        time.sleep(0.5)
    return None


def _failover_phases(events, t0, t1):
    """Attribute the recovery inside wall window [t0, t1] to phases from
    the merged ``failover.*`` events (``ts`` is wall-anchored epoch µs).

    Returns ({phase: seconds}, window_events). Spans/instants that carry
    a ``node`` arg are pinned to node 0 — the node whose worker was
    killed; master-side events (rdzv seal) carry no node and pass."""
    lo, hi = (t0 - 2.0) * 1e6, (t1 + 5.0) * 1e6
    win = [
        e
        for e in events
        if e.get("name", "").startswith("failover.")
        and lo <= e.get("ts", 0.0) <= hi
    ]

    def first(name, ph):
        for e in win:
            if e.get("name") != name or e.get("ph") != ph:
                continue
            if (e.get("args") or {}).get("node", 0) != 0:
                continue
            return e
        return None

    phases = {}
    exit_ev = first("failover.worker_exit", "i")
    if exit_ev:
        phases["detect_s"] = round(exit_ev["ts"] / 1e6 - t0, 3)
    for span_name, key in (
        ("failover.ckpt_persist", "ckpt_persist_s"),
        ("failover.rendezvous", "rendezvous_s"),
        ("failover.restore", "restore_s"),
    ):
        ev = first(span_name, "X")
        if ev:
            phases[key] = round(ev.get("dur", 0.0) / 1e6, 3)
    fs = first("failover.first_step", "i")
    if fs:
        phases["first_step_s"] = round(fs["ts"] / 1e6 - t0, 3)
    return phases, win


def _synthetic_ctr(rng, n, fields, n_dense):
    cat = rng.integers(0, 50, size=(n, fields)).astype(np.int64)
    dense = rng.normal(size=(n, n_dense)).astype(np.float32)
    hot = (cat % 7 == 0).sum(axis=1) + dense[:, 0]
    p = 1.0 / (1.0 + np.exp(-(hot - 2.0)))
    labels = (rng.random(n) < p).astype(np.float32)
    return cat, dense, labels


class _Producer(threading.Thread):
    """One remote coworker: pushes the fixed dataset over TCP forever
    (until stopped or the worker's ingress goes away)."""

    def __init__(self, port, batch):
        super().__init__(daemon=True)
        self.port = port
        self.batch = batch
        self.stop_ev = threading.Event()

    def run(self):
        from dlrover_tpu.data.coworker import RemoteBatchWriter

        try:
            w = RemoteBatchWriter(("127.0.0.1", self.port), timeout=30.0)
            while not self.stop_ev.is_set():
                w.put(self.batch)
                time.sleep(0.02)
        except Exception:  # noqa: BLE001 — worker gone/done
            return


_STEP_RE = re.compile(r"\[fullstack\] step (\d+) loss ([0-9.]+)")
_METRICS_RE = re.compile(r"metrics endpoint on port (\d+)")


def _master_metrics(port: int) -> dict:
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/json", timeout=10
    ) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_fullstack_elasticity_drill(monkeypatch, tmp_path):
    run_id = f"drill{os.getpid()}"
    wire_token = f"{run_id}-wire"
    # job-wide flight-recorder dir: every process (master, agents,
    # workers) streams its spans here; the merge is the drill artifact
    trace_dir = str(tmp_path / "trace")
    # the KvServer children (mp spawn) inherit this env
    monkeypatch.setenv("DLROVER_TPU_WIRE_TOKEN", wire_token)
    ctx = mp.get_context("spawn")
    kv_procs, kv_addrs = [], {}
    for name in ("s0", "s1"):
        p, addr = _spawn_server(ctx)
        kv_procs.append(p)
        kv_addrs[name] = addr
    kv_json = json.dumps({k: list(v) for k, v in kv_addrs.items()})

    master = agents = None
    producers = []
    try:
        master, mq, mlines, maddr = _start_master(
            run_id,
            argv_extra=("--num-workers", "2"),
            env_extra={
                "DLROVER_TPU_WIRE_TOKEN": wire_token,
                # detect the killed agent INSIDE the drill window (the
                # 300 s default would outlive the whole test), so the
                # goodput tracker sees the failure
                "DLROVER_TPU_CTX_HEARTBEAT_TIMEOUT_S": "35",
                "DLROVER_TPU_TRACE_DIR": trace_dir,
            },
        )
        # the metrics endpoint is logged during prepare(), before the
        # address line _start_master scraped — so it is already in mlines
        metrics_port = None
        for line in mlines:
            m = _METRICS_RE.search(line)
            if m:
                metrics_port = int(m.group(1))
        assert metrics_port, "".join(mlines)[-2000:]
        agents = [
            _launch_drill_agent(
                run_id, i, maddr, kv_json, steps=60,
                wire_token=wire_token, trace_dir=trace_dir,
            )
            for i in (0, 1)
        ]
        queues = [_drain(a) for a in agents]
        logs = [[], []]

        # discover each worker's TCP ingress and become its producers
        rng = np.random.default_rng(7)
        batch_data = _synthetic_ctr(rng, 256, fields=6, n_dense=4)
        batch = {
            "cat": batch_data[0],
            "dense": batch_data[1],
            "labels": batch_data[2],
        }
        # the port line can interleave with worker logger output on the
        # merged pipe: match the digits explicitly (the script prints
        # the line twice so one clean copy always exists)
        port_re = re.compile(r"\[fullstack\] feed port (\d+)\b")
        for i in (0, 1):
            line = _collect(
                queues[i],
                logs[i],
                until=lambda l: bool(port_re.search(l)),
                deadline=time.time() + 120,
            )
            assert line, (
                f"worker {i} never served its feed port:\n"
                + "".join(logs[i][-40:])
            )
            port = int(port_re.search(line).group(1))
            prod = _Producer(port, batch)
            prod.start()
            producers.append(prod)

        def steps_seen(log):
            out = {}
            for line in log:
                m = _STEP_RE.search(line)
                if m:
                    out[int(m.group(1))] = float(m.group(2))
            return out

        # both workers make progress against the shared sparse tier
        for i in (0, 1):
            assert _collect(
                queues[i],
                logs[i],
                until=lambda l: bool(
                    (m := _STEP_RE.search(l)) and int(m.group(1)) >= 8
                ),
                deadline=time.time() + 180,
            ), f"worker {i} stalled:\n" + "".join(logs[i][-40:])
        first_losses = steps_seen(logs[0])
        first = first_losses[min(first_losses)]

        # ---- failure 1: kill worker 0's PROCESS (agent survives) ------
        # the one failure that exercises the full per-phase recovery
        # chain the flight recorder attributes: the agent's poll detects
        # the exit, persists the staged ckpt, re-rendezvouses (agent 1
        # sees the waiting node and rejoins too), respawns with
        # restart=1, and the new worker's first step closes the timeline
        worker_pid = _find_worker_pid(agents[0].pid)
        assert worker_pid, "could not locate worker 0's process"
        # keep BOTH producers feeding through the kill: starving worker 1
        # here would let it drain its ring and exit CLEANLY — its agent
        # then reports SUCCEEDED and leaves, and the re-rendezvous can
        # never seal. The producer threads exit on their own when the
        # kill/respawn tears down the old ingress sockets.
        old_producers = producers
        producers = []
        t_kill_worker = time.time()
        os.kill(worker_pid, signal.SIGKILL)
        # BOTH workers respawn (coordinated re-rendezvous): re-discover
        # the new ingress ports and become their producers again
        for i in (0, 1):
            line = _collect(
                queues[i],
                logs[i],
                until=lambda l: bool(port_re.search(l)),
                deadline=t_kill_worker + RECOVERY_BUDGET_S,
            )
            assert line, (
                f"worker {i} never re-served its feed port after the "
                "worker kill:\n" + "".join(logs[i][-40:])
            )
            port = int(port_re.search(line).group(1))
            prod = _Producer(port, batch)
            prod.start()
            producers.append(prod)
        for prod in old_producers:
            prod.stop_ev.set()  # hygiene — their sockets are gone
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: bool(_STEP_RE.search(l)),
            deadline=t_kill_worker + RECOVERY_BUDGET_S,
        )
        assert line, (
            "worker 0 made no step within 60s of the worker kill:\n"
            + "".join(logs[0][-40:])
        )
        recovery_worker_s = time.time() - t_kill_worker
        assert recovery_worker_s < RECOVERY_BUDGET_S

        # goodput window opens here: startup (rendezvous + first jit
        # compile) AND the worker-kill recovery above are excluded — the
        # reference's 95% headline is a steady-state number too, not a
        # cold-start one. The stall the kill opened closes only once a
        # respawned worker's report ADVANCES past the pre-kill watermark
        # (restarted workers count from step 0 again), so wait for
        # lost-seconds to stop growing before sampling the baseline.
        deadline = time.time() + 60
        prev_lost = -1.0
        while time.time() < deadline:
            lost_now = _master_metrics(metrics_port)[
                "goodput_lost_seconds"
            ]
            if lost_now == prev_lost:
                break
            prev_lost = lost_now
            time.sleep(1.0)
        else:
            raise AssertionError(
                "worker-kill goodput stall never closed"
            )
        gp0 = _master_metrics(metrics_port)
        t_window_open = time.time()

        # ---- failure 2: kill agent 1 (whole process group) ------------
        t_kill_agent = time.time()
        producers[1].stop_ev.set()
        _kill_tree(agents[1])
        # recovery: the surviving worker keeps stepping (PS-style
        # training has no collective coupling to the dead peer) and the
        # master stays up — within the budget
        base = max(steps_seen(logs[0]))
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: bool(
                (m := _STEP_RE.search(l)) and int(m.group(1)) > base
            ),
            deadline=t_kill_agent + RECOVERY_BUDGET_S,
        )
        assert line, (
            "worker 0 made no progress within 60s of the agent kill:\n"
            + "".join(logs[0][-40:])
        )
        recovery_agent_s = time.time() - t_kill_agent
        assert recovery_agent_s < RECOVERY_BUDGET_S
        assert master.poll() is None, "master died with the agent"

        # ---- failure 3: kill sparse server s0 -------------------------
        t_kill_kv = time.time()
        kv_procs[0].kill()
        kv_procs[0].join(timeout=10)
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: "[fullstack] sparse failover" in l,
            deadline=t_kill_kv + RECOVERY_BUDGET_S,
        )
        assert line and "'s1'" in line, (
            "worker 0 never failed over the sparse ring:\n"
            + "".join(logs[0][-40:])
        )
        base = max(steps_seen(logs[0]))
        line = _collect(
            queues[0],
            logs[0],
            until=lambda l: bool(
                (m := _STEP_RE.search(l)) and int(m.group(1)) > base
            ),
            deadline=t_kill_kv + RECOVERY_BUDGET_S,
        )
        assert line, (
            "worker 0 made no step within 60s of the KvServer kill:\n"
            + "".join(logs[0][-40:])
        )
        recovery_kv_s = time.time() - t_kill_kv
        assert recovery_kv_s < RECOVERY_BUDGET_S

        # the master must have SEEN the agent kill (heartbeat timeout) before
        # the goodput window closes — otherwise the goodput number would
        # be vacuous (no stall ever marked)
        deadline = time.time() + 60
        while time.time() < deadline:
            if _master_metrics(metrics_port)["counters"][
                "node_failures_total"
            ] >= 1:
                break
            time.sleep(2)
        else:
            raise AssertionError(
                "master never detected the killed agent"
            )

        # ---- convergence continues to the end -------------------------
        assert _collect(
            queues[0],
            logs[0],
            until=lambda l: "[fullstack] done" in l,
            deadline=time.time() + 240,
        ), "worker 0 never finished:\n" + "".join(logs[0][-40:])
        losses = steps_seen(logs[0])
        final = losses[max(losses)]
        assert np.isfinite(final)
        # through both failures (incl. re-initialized embedding rows)
        # the loss ends below where it started
        assert final < first, (first, final)

        # ---- goodput across the two failures (VERDICT r4 ask #5) ------
        # windowed: (lost-time delta) / (wall delta) between the sample
        # taken before failure 1 and now, from the LIVE master's
        # GoodputTracker — the measured analog of the reference's
        # 69%→95% headline (reference README.md:57-58)
        gp1 = _master_metrics(metrics_port)
        window_wall = time.time() - t_window_open
        lost = (
            gp1["goodput_lost_seconds"] - gp0["goodput_lost_seconds"]
        )
        goodput = max(0.0, 1.0 - lost / max(window_wall, 1e-9))
        assert goodput >= 0.90, (
            f"goodput {goodput:.3f} across the two failures "
            f"(lost {lost:.1f}s of {window_wall:.1f}s)"
        )

        # ---- flight recorder: merged timeline + phase attribution -----
        # one time-sorted JSONL of every process's spans; the worker-kill
        # failover must decompose into detect → (persist) → rendezvous →
        # restore → first-step, with all three roles on the timeline
        trace_out = os.path.join(REPO, "DRILL_r08_trace.jsonl")
        events = merge_trace_dir(trace_dir, out_path=trace_out)
        phases, win = _failover_phases(
            events, t_kill_worker, t_kill_worker + recovery_worker_s
        )
        roles = {(e.get("args") or {}).get("role", "") for e in win}
        assert {"worker", "agent", "master"} <= roles, (
            f"failover window roles {roles} "
            f"({len(events)} events total, {len(win)} in window)"
        )
        for key in (
            "detect_s", "rendezvous_s", "restore_s", "first_step_s"
        ):
            assert key in phases, (
                phases,
                sorted({e.get("name") for e in win}),
            )

        artifact = {
            "drill": "test_fullstack_elasticity_drill",
            "failures": [
                {
                    "kind": "worker_killed",
                    "recovery_s": round(recovery_worker_s, 2),
                    "phases": phases,
                },
                {"kind": "agent_killed", "recovery_s": round(recovery_agent_s, 2)},
                {"kind": "sparse_server_killed", "recovery_s": round(recovery_kv_s, 2)},
            ],
            "recovery_budget_s": RECOVERY_BUDGET_S,
            "goodput_across_failures": round(goodput, 4),
            "goodput_lost_s": round(lost, 2),
            "goodput_window_s": round(window_wall, 2),
            "goodput_since_master_start": gp1["goodput"],
            "node_failures_seen_by_master": gp1["counters"][
                "node_failures_total"
            ],
            "trace_events": len(events),
            "trace_path": os.path.basename(trace_out),
        }
        out_path = os.environ.get(
            "DLROVER_TPU_DRILL_ARTIFACT",
            os.path.join(REPO, "DRILL_r08.json"),
        )
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"\n[drill] {json.dumps(artifact)}")
    finally:
        dump_dir = os.environ.get("DLROVER_TPU_DRILL_DEBUG_DIR")
        if dump_dir:
            # post-mortem: the failing assert only shows ONE process's
            # tail — dump every captured stream for cross-correlation
            os.makedirs(dump_dir, exist_ok=True)
            try:
                for i, (q, log) in enumerate(zip(queues, logs)):
                    _drain_now(q, log)
                    with open(
                        os.path.join(dump_dir, f"worker{i}.log"), "w"
                    ) as f:
                        f.writelines(log)
                _drain_now(mq, mlines)
                with open(
                    os.path.join(dump_dir, "master.log"), "w"
                ) as f:
                    f.writelines(mlines)
            except Exception:  # noqa: BLE001 — best-effort diagnostics
                pass
        for prod in producers:
            prod.stop_ev.set()
        for a in agents or []:
            _kill_tree(a)
        if master is not None:
            master.kill()
        for p in kv_procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)


@pytest.mark.slow
def test_live_reshard_eviction_drill(tmp_path):
    """Host-eviction stage: a mid-training ``EvictionNotice`` turns into
    a master reshard directive; the worker live-reshards dp 8→4 from
    in-HBM state (survivors donate ZeRO-1 shards over the PackPlan
    wire), the step rebuilds, and training finishes at the new size.
    The happy path must land inside the recovery budget WITHOUT a
    storage-tier restore, and the artifact records per-phase seconds."""
    run_id = f"reshard{os.getpid()}"
    tel_dir = str(tmp_path / "telemetry")
    os.makedirs(tel_dir, exist_ok=True)
    master = agent = None
    lines = []
    try:
        master, mq, mlines, maddr = _start_master(
            run_id,
            env_extra={"DLROVER_TPU_TELEMETRY_DIR": tel_dir},
        )
        agent = _launch_agent(
            run_id,
            0,
            maddr,
            train_args=[
                "--steps", "12", "--batch", "8", "--seq", "16",
                "--zero1", "--evict-at", "6",
                "--ckpt-dir", str(tmp_path / "ckpt"),
            ],
            nnodes="1:1",
            env_extra={
                # the eviction is emulated INSIDE one worker: 8 virtual
                # CPU devices so the mesh can shrink 8 -> 4 in-process
                # (the harness default of one device per worker would
                # leave nothing to reshard)
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "DLROVER_TPU_TELEMETRY_DIR": tel_dir,
                # hermetic compile cache: jaxlib's CPU backend segfaults
                # re-executing a persistent-cache-deserialized executable
                # compiled for a device SUBSET (the dp=4 survivor mesh),
                # so never share the cache across drill runs
                "JAX_COMPILATION_CACHE_DIR": str(tmp_path / "jit_cache"),
            },
        )
        q = _drain(agent)
        done = _collect(
            q,
            lines,
            until=lambda l: "[reshard] done" in l,
            deadline=time.time() + 420,
        )
        assert done, "worker never reported reshard:\n" + "".join(
            lines[-40:]
        )
        summary = json.loads(done.split("[reshard] done", 1)[1])
        assert summary["path"] == "live", summary
        assert summary["dp"] == "8->4", summary
        assert summary["recovery_s"] < RECOVERY_BUDGET_S, summary
        for phase in (
            "detect", "replan", "migrate", "rebuild", "first_step"
        ):
            assert phase in summary["phases"], summary

        # training must CONTINUE at dp=4 to the end — the reshard is a
        # recovery, not a shutdown
        assert _collect(
            q,
            lines,
            until=lambda l: "[worker] done" in l,
            deadline=time.time() + 240,
        ), "worker never finished after reshard:\n" + "".join(lines[-40:])

        # flight recorder: rehydrate the telemetry stream and check the
        # phase events landed and the disk was never read
        from dlrover_tpu.observability import telemetry as tel

        records = []
        for fname in sorted(os.listdir(tel_dir)):
            with open(os.path.join(tel_dir, fname)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(tel.from_json(line))
                    except Exception:  # noqa: BLE001 — torn tail line
                        continue
        elastic = [r for r in records if isinstance(r, tel.ElasticEvent)]
        kinds = [r.kind for r in elastic]
        assert "eviction_notice" in kinds, kinds
        phase_s = {}
        for phase in (
            "detect", "replan", "migrate", "rebuild", "first_step"
        ):
            ev = [r for r in elastic if r.kind == f"reshard_{phase}"]
            assert ev, (phase, kinds)
            assert "ok=True" in ev[-1].detail, ev[-1]
            phase_s[phase] = round(ev[-1].seconds, 3)
        recovery = [r for r in elastic if r.kind == "reshard_recovery"]
        assert recovery, kinds
        assert "path=live" in recovery[-1].detail, recovery[-1]
        assert recovery[-1].seconds < RECOVERY_BUDGET_S, recovery[-1]
        # the defining property of tier 0: NO successful storage-tier
        # restore anywhere in the run (engine only stamps tier="storage"
        # when the disk actually answered)
        disk_restores = [
            r
            for r in records
            if isinstance(r, tel.CheckpointRecord)
            and r.kind == "restore"
            and r.tier == "storage"
        ]
        assert not disk_restores, disk_restores

        # ---- artifact: append the eviction stage ----------------------
        out_path = os.environ.get(
            "DLROVER_TPU_DRILL_ARTIFACT",
            os.path.join(REPO, "DRILL_r08.json"),
        )
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            # test-order independence: a minimal shell when the main
            # drill has not written the artifact yet
            artifact = {
                "drill": "test_fullstack_elasticity_drill",
                "failures": [],
                "recovery_budget_s": RECOVERY_BUDGET_S,
            }
        artifact.setdefault("failures", [])
        artifact["failures"] = [
            f
            for f in artifact["failures"]
            if f.get("kind") != "host_eviction_live_reshard"
        ] + [
            {
                "kind": "host_eviction_live_reshard",
                "recovery_s": round(float(summary["recovery_s"]), 2),
                "phases": phase_s,
                "restore_tier": "live",
            }
        ]
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"\n[drill] {json.dumps(artifact['failures'][-1])}")
    finally:
        _kill_tree(agent)
        if master is not None:
            master.kill()


@pytest.mark.slow
def test_nan_fault_health_drill(monkeypatch, tmp_path):
    """Health-sentinel stage of the drill: a worker whose batch poisons
    the gradients at step 4 must produce — across the REAL wire — an
    AnomalyRecord on the master's flight recorder, a triggered runtime
    capture on the worker, a HealthSummary verdict from the master's
    aggregator, and a healthcheck CLI report (run as the operator
    would, `python -m ...healthcheck`) naming the failing rank and the
    first bad step."""
    import glob as _glob

    import jax
    import jax.numpy as jnp

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.models import decoder, get_config
    from dlrover_tpu.observability import telemetry
    from dlrover_tpu.parallel import MeshConfig, build_mesh
    from dlrover_tpu.train import Trainer, TrainerArgs, make_optimizer

    run_id = f"nandrill{os.getpid()}"
    tel_dir = str(tmp_path / "telemetry")
    monkeypatch.setenv("DLROVER_TPU_RUN_ID", run_id)
    monkeypatch.setenv("DLROVER_TPU_NODE_ID", "1")
    master = None
    telemetry.reset_hub()
    try:
        master, mq, mlines, maddr = _start_master(
            run_id,
            argv_extra=("--num-workers", "2"),
            env_extra={"DLROVER_TPU_TELEMETRY_DIR": tel_dir},
        )
        client = MasterClient(maddr, node_id=1)
        telemetry.configure_hub(sinks=[telemetry.MasterSink(client)])

        cfg = get_config(
            "tiny", n_layer=2, d_model=64, d_ff=128, n_head=4,
            vocab_size=128, max_seq=32,
        )
        mesh = build_mesh(MeshConfig(dp=8))

        def poison_loss(params, batch, **kw):
            clean = {k: v for k, v in batch.items() if k != "poison"}
            loss, metrics = decoder.loss_fn(
                params, clean, cfg=cfg, mesh=mesh
            )
            bad = jnp.max(batch["poison"]) > 0
            return loss * jnp.where(bad, jnp.float32(jnp.nan), 1.0), metrics

        def data():
            rng = np.random.RandomState(0)
            step = 0
            while True:
                step += 1
                base = rng.randint(0, 8, size=(8, 33))
                yield {
                    "tokens": np.asarray(base[:, :-1], np.int32),
                    "targets": np.asarray(base[:, 1:], np.int32),
                    "poison": np.full(
                        (8, 32), 1 if step == 4 else 0, np.int32
                    ),
                }

        args = TrainerArgs(
            output_dir=str(tmp_path / "out"), max_steps=6,
            save_interval=0, log_interval=0, report_to_master=False,
            detect_loss_spikes=False, resume=False,
            health_sentinels=True, sanitize_grads="skip",
        )
        t = Trainer(
            cfg, args, data(), make_optimizer(learning_rate=1e-3),
            mesh=mesh, loss_fn=poison_loss,
        )
        t.train()

        # worker side: classified anomaly with a triggered capture
        (rec,) = [r for r in t.watchdog.anomalies if r.kind == "nan_grads"]
        assert rec.step == 4 and rec.node_id == 1
        assert rec.capture and os.path.exists(rec.capture)
        assert json.load(open(rec.capture))["ops"]

        # master side: the wire-forwarded record and the aggregator's
        # verdict both land on the master's flight recorder
        deadline = time.time() + 30
        jsonl = None
        while time.time() < deadline:
            for path in _glob.glob(
                os.path.join(tel_dir, "telemetry-master-*.jsonl")
            ):
                body = open(path).read()
                if '"AnomalyRecord"' in body and '"HealthSummary"' in body:
                    jsonl = path
                    break
            if jsonl:
                break
            time.sleep(0.5)
        assert jsonl, "master flight recorder never saw the anomaly"

        # operator side: the offline CLI replays the jsonl to the same
        # diagnosis, exit code 1 because anomalies are present
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "dlrover_tpu.observability.healthcheck",
                jsonl,
                "--world",
                "2",
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "nan_grads" in proc.stdout
        assert "failing rank(s) 1" in proc.stdout
        assert "first bad step 4" in proc.stdout
        assert "suspect_data_or_hardware" in proc.stdout
    finally:
        telemetry.reset_hub()
        if master is not None:
            master.kill()
