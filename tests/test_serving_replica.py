"""Elastic serving failover drill (serving/replica.py) — slow tier.

Two live replicas serve a mixed batch; one is killed mid-stream. The
router's poll must re-admit every in-flight request of the dead replica
onto the survivor with NO lost and NO duplicated request, and every
output must still be bitwise equal to per-request greedy (the restart
re-prefills from the prompt — results are path-independent). Also
covers master-plane registration: replicas register as
``NodeType.SERVING`` and publish discovery entries in the master KV
store like sparse servers do.
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.common.constants import NodeType  # noqa: E402
from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402
from dlrover_tpu.serving.replica import (  # noqa: E402
    ReplicaRouter,
    ServingReplica,
    discover_replicas,
)

pytestmark = pytest.mark.slow


def _cfg():
    return get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )


_SERVER_KW = dict(
    n_slots=2, max_len=32, page_size=4, mode="bf16", prefill_chunk=4,
    idle_sleep=0.001,
)


def test_kill_one_of_two_replicas_no_lost_no_duplicated():
    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    prompts = [
        list(rng.integers(1, 32, size=n)) for n in (3, 7, 5, 11, 2, 9, 4, 6)
    ]
    max_new = [6, 4, 8, 5, 7, 3, 6, 5]
    refs = [
        [
            int(t)
            for t in np.asarray(
                generate.greedy(
                    params, cfg, jnp.asarray([p], jnp.int32), m
                )[0]
            )
        ]
        for p, m in zip(prompts, max_new)
    ]

    r0 = ServingReplica("rep-0", params, cfg, **_SERVER_KW).start()
    r1 = ServingReplica("rep-1", params, cfg, **_SERVER_KW).start()
    try:
        router = ReplicaRouter([r0, r1])
        reqs = [router.submit(p, m) for p, m in zip(prompts, max_new)]
        # let work start (both replicas compile + begin decoding), then
        # evict one mid-stream
        time.sleep(1.0)
        r1.kill()
        assert not r1.alive and r0.alive
        in_flight = sum(
            1 for e in router._entries
            if e.replica is r1 and not e.done
        )
        moved = router.poll()
        assert moved == in_flight  # every incomplete request moved, once
        outs = router.wait_all(timeout=600)
    finally:
        r0.stop()
        r1.kill()

    # no lost request: every future resolved with the right sequence
    assert outs == refs
    # no duplicated request: exactly len(refs) completions landed
    # across both schedulers, and the survivor absorbed the re-admits
    assert (
        r0.server.scheduler.completed + r1.server.scheduler.completed
        == len(refs)
    )
    assert r0.server.scheduler.re_admitted == moved
    # each future resolved exactly once (a duplicate would have tried to
    # re-resolve and been dropped by complete(); outputs above prove the
    # first resolution was the correct sequence)
    assert all(r.future.done() for r in reqs)


def test_replica_registers_with_master_as_serving_node():
    from dlrover_tpu.master.master import LocalJobMaster

    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    master = LocalJobMaster(port=0, num_workers=2)
    master.prepare()
    try:
        rep = ServingReplica(
            "rep-m", params, cfg,
            master_addr=master.addr, node_id=7,
            **_SERVER_KW,
        ).start()
        try:
            nodes = master.job_manager.serving_nodes()
            assert len(nodes) == 1
            assert nodes[0].type == NodeType.SERVING
            found = discover_replicas(rep._client, ["rep-m"])
            assert found == {"rep-m": {"name": "rep-m", "node_id": 7}}
            # an unregistered peer defers adoption (partial-set rule)
            assert discover_replicas(rep._client, ["rep-m", "ghost"]) is None
            # the replica still serves while registered
            out = rep.generate([1, 2, 3], 2, timeout=600)
            assert len(out) == 5
        finally:
            rep.stop()
    finally:
        master.stop()
