"""Disaggregated prefill/decode serving (serving/disagg.py + router).

Fast tier: the ``affinity_ok`` dispatch gate is a pure function — unit
coverage lives here. The ``page_start`` wire field rides the migration
codec and is covered in test_serving_migration.py (the wire unit file).

Slow tier — the acceptance drills:

- PARITY MATRIX: the same sampled workload served by a 1-prefill +
  1-decode fleet is bitwise equal to one unified replica at the same
  seeds across {bf16, int8} × {paged, gather} × {spec on, off} — cold
  prompts; the prefix-HIT arm is the affinity drill below — plus a
  one-shot (non-streaming) handoff arm. Both fleets run the same
  ``prefill_chunk`` (chunk width changes the reduction order).
- TORN FRAGMENTS: an injected ``drop_page`` at ``serving.handoff``
  re-exports the same immutable pages and stays bitwise; past the
  retry budget the handoff degrades to re-prefill under the ORIGINAL
  ticket — nothing lost, nothing duplicated, still bitwise.
- MID-STREAM KILLS: killing the prefill donor with fragments in
  flight cancels-or-repoints exactly once (committed handoffs keep
  their decode owner; uncommitted ones re-prefill on the pool);
  killing the only decode replica collapses the fleet to unified and
  every parked prompt re-admits under its original ticket.
- PREFIX AFFINITY: a prompt whose prefix is resident on the decode
  replica skips the prefill fleet entirely (suffix-only local
  prefill); a plan that went stale before admission bounces back to
  the router and re-routes through the prefill pool.
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dlrover_tpu.elastic import faults  # noqa: E402
from dlrover_tpu.serving.prefix import (  # noqa: E402
    AdmissionPlan,
    affinity_ok,
)
from dlrover_tpu.serving.scheduler import SamplingParams  # noqa: E402

# -------------------------------------------------------- affinity gate


def _plan(resume):
    return AdmissionPlan(
        shared=(), cow=(), resume=resume, matched_tokens=resume
    )


def test_affinity_requires_a_resident_prefix():
    assert not affinity_ok(None, 10, 8)          # radix miss
    assert not affinity_ok(_plan(0), 10, 8)      # matched < one chunk


def test_affinity_bounds_the_local_suffix():
    # the decode replica only prefills the divergent suffix locally;
    # past max_suffix it would re-inherit chunked-prefill interference
    assert affinity_ok(_plan(8), 10, 8)          # 2-token suffix
    assert affinity_ok(_plan(8), 16, 8)          # suffix == budget
    assert not affinity_ok(_plan(8), 17, 8)      # one past: bounce
    assert not affinity_ok(_plan(4), 12, 0)      # zero budget, any suffix


# ----------------------------------------------------------- drill rig


_SERVER_KW = dict(
    n_slots=4, max_len=32, page_size=4, prefill_chunk=4,
    idle_sleep=0.001,
)


@pytest.fixture(scope="module")
def drill():
    from dlrover_tpu.models import decoder
    from dlrover_tpu.models.config import get_config

    cfg = get_config(
        "tiny", n_layer=2, d_model=32, d_ff=64, n_head=4,
        vocab_size=32, max_seq=64,
    )
    params = decoder.init(jax.random.key(0), cfg)
    prompts = [[2, 3, 4, 2, 3], [9, 10, 9, 10], [5, 6, 7], [11, 3, 7, 1]]
    max_new = [10, 10, 10, 10]
    sps = [
        SamplingParams(temperature=0.9, top_k=5, top_p=0.9, seed=i + 1)
        for i in range(4)
    ]
    return cfg, params, prompts, max_new, sps


def _serve(drill, roles, *, router_kw=None, server_kw=None,
           before_wait=None):
    """Stand up a role-typed fleet, run the drill workload, tear down.

    Returns everything the assertions need, gathered BEFORE teardown
    (``router.close`` drops the coordinator)."""
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, prompts, max_new, sps = drill
    kw = dict(_SERVER_KW, mode="bf16")
    kw.update(server_kw or {})
    reps = [
        ServingReplica(
            f"dg{i}-{role}", params, cfg, node_id=i, role=role, **kw
        ).start()
        for i, role in enumerate(roles)
    ]
    router = ReplicaRouter(reps, **(router_kw or {}))
    try:
        reqs = [
            router.submit(p, m, sampling=sp)
            for p, m, sp in zip(prompts, max_new, sps)
        ]
        if before_wait is not None:
            before_wait(router, reps, reqs)
        outs = router.wait_all(timeout=600)
        coord = router.coordinator
        return {
            "outs": outs,
            "reqs": reqs,
            "stats": {r.name: r.server.engine.stats() for r in reps},
            "completed": {
                r.name: r.server.scheduler.completed for r in reps
            },
            "degraded": coord.degraded if coord else 0,
            "handoffs_done": coord.completed if coord else 0,
            "disaggregated": router.disaggregated,
            "reserved": {
                r.name: r.server.engine.alloc.reserved_pages
                for r in reps
            },
        }
    finally:
        router.close()
        for r in reps:
            r.stop()


# ------------------------------------------------------- parity matrix


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["bf16", "int8"])
@pytest.mark.parametrize("paged", [True, False])
@pytest.mark.parametrize("spec_k", [0, 3])
def test_disagg_bitwise_parity_matrix(drill, mode, paged, spec_k):
    skw = dict(mode=mode, paged=paged, spec_k=spec_k)
    uni = _serve(drill, ["unified"], server_kw=skw)
    dis = _serve(drill, ["prefill", "decode"], server_kw=skw)
    # the split changed the transport schedule, not the numerics
    assert dis["outs"] == uni["outs"]
    assert dis["disaggregated"]
    pre, dec = dis["stats"]["dg0-prefill"], dis["stats"]["dg1-decode"]
    assert pre["handoffs_out"] == 4 and dec["handoffs_in"] == 4
    assert pre["handoff_bytes"] > 0
    assert dis["degraded"] == 0 and dis["handoffs_done"] == 4
    # every request completed exactly once, on the decode side; the
    # decode engine never ran a cold prefill
    assert dis["completed"] == {"dg0-prefill": 0, "dg1-decode": 4}
    assert dec["prefill_tokens"] == 0
    assert all(v == 0 for v in dis["reserved"].values())


@pytest.mark.slow
def test_one_shot_handoff_parity(drill):
    """streaming=False: the whole snapshot ships as ONE fragment at
    prefill completion — the fallback wire schedule is bitwise too."""
    uni = _serve(drill, ["unified"])
    dis = _serve(
        drill, ["prefill", "decode"],
        router_kw=dict(streaming=False),
    )
    assert dis["outs"] == uni["outs"]
    assert dis["handoffs_done"] == 4 and dis["degraded"] == 0
    assert dis["completed"]["dg1-decode"] == 4


# ------------------------------------------------------ torn fragments


@pytest.mark.slow
def test_torn_fragment_retries_and_stays_bitwise(drill):
    uni = _serve(drill, ["unified"])
    inj = faults.FaultInjector()
    # one transient tear: the retry re-exports the same immutable
    # committed pages from the donor and the stream proceeds
    inj.install(
        faults.FaultSpec("drop_page", point="serving.handoff", times=1)
    )
    dis = _serve(
        drill, ["prefill", "decode"], router_kw=dict(faults=inj),
    )
    assert dis["outs"] == uni["outs"]
    assert dis["degraded"] == 0 and dis["handoffs_done"] == 4
    assert all(v == 0 for v in dis["reserved"].values())


@pytest.mark.slow
def test_torn_beyond_retries_degrades_to_reprefill(drill):
    uni = _serve(drill, ["unified"])
    inj = faults.FaultInjector()
    # retries=1 → two decode attempts per fragment: two tears exhaust
    # exactly one handoff, whose re-dispatch then runs fault-free
    inj.install(
        faults.FaultSpec("drop_page", point="serving.handoff", times=2)
    )
    dis = _serve(
        drill, ["prefill", "decode"], router_kw=dict(faults=inj),
    )
    # degradation is invisible in the output: position-indexed sampling
    # makes the re-prefilled continuation bitwise too
    assert dis["outs"] == uni["outs"]
    assert dis["degraded"] >= 1
    # no lost, no duplicated request
    assert sum(dis["completed"].values()) == 4
    assert all(r.future.done() for r in dis["reqs"])
    assert all(v == 0 for v in dis["reserved"].values())


# ----------------------------------------------------- mid-stream kills


def _wait(cond, timeout=60.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.mark.slow
def test_mid_stream_prefill_kill_cancels_or_repoints_exactly_once(drill):
    """Kill the prefill donor with one handoff committed and one still
    streaming: the committed request keeps its decode owner (repoint,
    no re-prefill); the in-flight one cancels atomically and re-admits
    on the surviving prefill replica under its original ticket."""
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, prompts, max_new, sps = drill
    kw = dict(_SERVER_KW, mode="bf16")
    uni = _serve(drill, ["unified"])
    reps = [
        ServingReplica(
            name, params, cfg, node_id=i, role=role, **kw
        ).start()
        for i, (name, role) in enumerate([
            ("dgk0-prefill", "prefill"),
            ("dgk1-prefill", "prefill"),
            ("dgk2-decode", "decode"),
        ])
    ]
    p0, p1, d = reps
    router = ReplicaRouter(reps)
    try:
        # park BOTH prefill loops so dispatch is deterministic
        # (least-loaded with a stable tie-break alternates) and the
        # victim can be hand-stepped to a pinned mid-stream state
        with p0.server.paused() as eng0, p1.server.paused():
            reqs = [
                router.submit(p, m, sampling=sp)
                for p, m, sp in zip(prompts, max_new, sps)
            ]
            assert [e.replica.name for e in router._entries] == [
                "dgk0-prefill", "dgk1-prefill",
                "dgk0-prefill", "dgk1-prefill",
            ]
            # one hand step: prompt[2] (3 tokens, one chunk) finishes
            # prefill and its handoff commits; prompt[0] (5 tokens,
            # two chunks) ships its first full page and stays mid-prefill
            eng0.step()
            # the commit's donor-side slot release wants OUR held pause
            # lock, so the coordinator's `completed` counter is wedged;
            # the commit point itself is the decode-side import, and the
            # coordinator lock serializes it against the dead-donor
            # resolution below — wait on that
            assert _wait(
                lambda: d.server.engine.stats()["handoffs_in"] >= 1
            ), "first handoff never committed"
            assert router.coordinator.pending() >= 1
            degraded0 = router.coordinator.degraded
            p0.kill()
        coord = router.coordinator
        router.poll()
        outs = router.wait_all(timeout=600)
        assert outs == uni["outs"]
        assert all(r.future.done() for r in reqs)
        # exactly one in-flight handoff cancelled → re-prefilled; the
        # committed one repointed without touching a prefill engine
        assert coord.degraded == degraded0 + 1
        # the survivor re-prefilled the cancelled request
        assert p1.server.engine.stats()["handoffs_out"] == 3
        assert d.server.engine.stats()["handoffs_in"] == 4
        assert d.server.scheduler.completed == 4
        assert d.server.engine.alloc.reserved_pages == 0
    finally:
        router.close()
        for r in reps:
            r.stop()


@pytest.mark.slow
def test_mid_stream_decode_kill_collapses_to_unified(drill):
    """Kill the ONLY decode replica with fragments in flight (the
    coordinator is wedged against the held pause, so nothing has
    committed): the pool empties, the fleet collapses to unified, and
    every parked prompt re-admits on the ex-prefill replica under its
    original ticket — no lost, no duplicated request, still bitwise."""
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, prompts, max_new, sps = drill
    kw = dict(_SERVER_KW, mode="bf16")
    uni = _serve(drill, ["unified"])
    p = ServingReplica(
        "dgc0-prefill", params, cfg, node_id=0, role="prefill", **kw
    ).start()
    d = ServingReplica(
        "dgc1-decode", params, cfg, node_id=1, role="decode", **kw
    ).start()
    router = ReplicaRouter([p, d])
    try:
        # hold the decode pause across submission: fragments stream
        # from the prefill engine but staging blocks on the pause
        # lock, so the kill lands with every handoff mid-flight
        with d.server.paused():
            reqs = [
                router.submit(pr, m, sampling=sp)
                for pr, m, sp in zip(prompts, max_new, sps)
            ]
            assert _wait(
                lambda: p.server.engine.stats()["prefill_tokens"] > 0
            ), "prefill never started"
            d.kill()
        router.poll()
        assert not router.disaggregated  # pool emptied → collapsed
        assert p.server.engine.role == "unified"
        outs = router.wait_all(timeout=600)
        assert outs == uni["outs"]
        assert all(r.future.done() for r in reqs)
        # everything finished on the collapsed survivor; the dead
        # decode replica completed nothing
        assert p.server.scheduler.completed == 4
        assert d.server.scheduler.completed == 0
    finally:
        router.close()
        p.stop()
        d.stop()


# ------------------------------------------------------ prefix affinity


@pytest.mark.slow
def test_prefix_affinity_skips_prefill_and_stale_plan_bounces(drill):
    """A prompt whose prefix is resident on the decode replica
    dispatches there directly — the prefill fleet is never touched and
    only the suffix prefills locally. A plan that goes stale between
    dispatch and admission (resident pages dropped) bounces back to
    the router, which re-routes it through the prefill pool."""
    from dlrover_tpu.serving import prefix as prefix_mod
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    cfg, params, _, _, _ = drill
    kw = dict(
        _SERVER_KW, mode="bf16", prefix_sharing=True, max_len=64,
    )
    hot = [3, 5, 2, 7, 4, 6, 1, 8, 2, 5, 3, 9]  # 3 full pages
    # interned pages die with their LAST SHARER (the trie drops freed
    # pages) — the seeder's long decode keeps the prefix resident while
    # the followers dispatch, exactly the production hot-prefix shape
    jobs = [
        (hot, 40),           # A: cold — prefill fleet, seeds the trie
        (hot + [13], 20),    # B: 1-token suffix — affinity hit
        (hot + [14], 8),     # C: stale plan — bounced, re-routed
    ]
    sps = [
        SamplingParams(temperature=0.9, top_k=5, top_p=0.9, seed=i + 21)
        for i in range(3)
    ]

    def run_unified():
        rep = ServingReplica(
            "aff-uni", params, cfg, node_id=9, role="unified", **kw
        ).start()
        try:
            return [
                rep.server.generate(
                    pr, m, sampling=sp, timeout=600.0
                )
                for (pr, m), sp in zip(jobs, sps)
            ]
        finally:
            rep.stop()

    refs = run_unified()
    p = ServingReplica(
        "aff-prefill", params, cfg, node_id=0, role="prefill", **kw
    ).start()
    d = ServingReplica(
        "aff-decode", params, cfg, node_id=1, role="decode", **kw
    ).start()
    router = ReplicaRouter([p, d])
    try:
        ra = router.submit(jobs[0][0], jobs[0][1], sampling=sps[0])
        # A seeds through the pool: once the handoff lands, the prompt
        # pages are interned on the decode replica and stay resident
        # for as long as A (or any later sharer) holds them
        assert _wait(
            lambda: p.server.engine.stats()["handoffs_out"] == 1
            and d.server.engine.stats()["trie_pages"] >= 3
        ), "seeder handoff never landed on the decode replica"

        # B: dispatched while A is still decoding — the resident prefix
        # routes it straight to the decode replica
        rb = router.submit(jobs[1][0], jobs[1][1], sampling=sps[1])
        assert router._by_rid[rb.rid].replica is d  # skipped the fleet
        assert _wait(
            lambda: d.server.engine.stats()["prefix_hits"] >= 1
        ), "affinity admission never shared the resident pages"
        sp_, sd = p.server.engine.stats(), d.server.engine.stats()
        assert sp_["handoffs_out"] == 1       # prefill fleet untouched
        assert sd["prefill_tokens"] <= len(jobs[1][0])  # suffix only

        # C: dispatch decides on the resident prefix, then the plan
        # goes stale before the engine admits (paused across both)
        with d.server.paused() as eng:
            rc = router.submit(jobs[2][0], jobs[2][1], sampling=sps[2])
            assert router._by_rid[rc.rid].replica is d
            eng.trie = prefix_mod.PrefixIndex(eng.geom.page_size)
            eng.alloc.on_free = eng.trie.drop_pages
        assert _wait(
            lambda: d.server.engine.stats()["affinity_bounced"] >= 1
        ), "stale plan never bounced"
        router.poll()  # drains the bounce lane, re-routes
        assert router.wait_all(timeout=600) == refs
        assert d.server.engine.stats()["affinity_bounced"] == 1
        # the re-route went through the prefill pool this time
        assert p.server.engine.stats()["handoffs_out"] == 2
        assert all(r.future.done() for r in (ra, rb, rc))
    finally:
        router.close()
        p.stop()
        d.stop()
