"""Fused lm-head cross-entropy (ops/fused_ce.py) vs the unfused path.

The fused op must be numerically interchangeable with the full-logits
computation it replaces (same online-statistics argument as the flash
kernel): logz / target-logit / argmax in forward, d(x) and d(w) in
backward, including the muP readout scale and a vocab size that does
not divide the chunk width.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.ops.fused_ce import fused_linear_ce


def _reference_stats(x, w, targets, scale):
    logits = jnp.einsum(
        "bsd,dv->bsv", x, w, preferred_element_type=jnp.float32
    )
    if scale != 1.0:
        logits = logits * scale
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
    return logz, tgt, jnp.argmax(logits, -1)


@pytest.mark.parametrize(
    "v,block_v,scale",
    [(1024, 256, 1.0), (1000, 256, 1.0), (640, 640, 0.25), (130, 512, 1.0)],
)
def test_forward_matches_reference(v, block_v, scale):
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    b, s, d = 2, 16, 32
    x = jax.random.normal(k1, (b, s, d), jnp.float32)
    w = jax.random.normal(k2, (d, v), jnp.float32) * 0.1
    targets = jax.random.randint(k3, (b, s), 0, v)
    logz, tgt, amax = fused_linear_ce(x, w, targets, scale, block_v)
    rlogz, rtgt, ramax = _reference_stats(x, w, targets, scale)
    np.testing.assert_allclose(logz, rlogz, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(tgt, rtgt, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(amax, ramax)


@pytest.mark.parametrize("v,block_v", [(1024, 256), (1000, 384)])
def test_grads_match_reference(v, block_v):
    k1, k2, k3 = jax.random.split(jax.random.key(1), 3)
    b, s, d = 2, 8, 32
    x = jax.random.normal(k1, (b, s, d), jnp.float32)
    w = jax.random.normal(k2, (d, v), jnp.float32) * 0.1
    targets = jax.random.randint(k3, (b, s), 0, v)

    def fused_loss(x, w):
        logz, tgt, _ = fused_linear_ce(x, w, targets, 1.0, block_v)
        # nll mean plus a z-loss term so BOTH cotangents are non-trivial
        return (logz - tgt).mean() + 0.1 * (logz**2).mean()

    def ref_loss(x, w):
        logz, tgt, _ = _reference_stats(x, w, targets, 1.0)
        return (logz - tgt).mean() + 0.1 * (logz**2).mean()

    (fl, (fdx, fdw)) = jax.value_and_grad(fused_loss, argnums=(0, 1))(x, w)
    (rl, (rdx, rdw)) = jax.value_and_grad(ref_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(fl, rl, rtol=1e-5)
    np.testing.assert_allclose(fdx, rdx, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fdw, rdw, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_loss_fn_fused_matches_unfused():
    """End-to-end: decoder.loss_fn with fused_ce on vs off (f32)."""
    import dataclasses

    cfg = get_config("tiny", param_dtype="float32", dtype="float32")
    cfg_fused = dataclasses.replace(cfg, fused_ce=True, ce_block_v=128)
    cfg_plain = dataclasses.replace(cfg, fused_ce=False)
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 100)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    lf, mf = decoder.loss_fn(params, batch, cfg_fused, z_loss=1e-4)
    lp, mp = decoder.loss_fn(params, batch, cfg_plain, z_loss=1e-4)
    np.testing.assert_allclose(lf, lp, rtol=1e-5)
    np.testing.assert_allclose(mf["accuracy"], mp["accuracy"])

    gf = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg_fused)[0])(params)
    gp = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg_plain)[0])(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        gf,
        gp,
    )


@pytest.mark.slow
def test_fused_ce_under_tp_mesh_falls_back():
    """On a tp>1 mesh loss_fn must take the unfused (vocab-parallel)
    path and still produce the same loss as fused on a single device."""
    import dataclasses

    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    cfg = get_config("tiny", param_dtype="float32", dtype="float32")
    cfg = dataclasses.replace(cfg, fused_ce=True)
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 100)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    mesh = build_mesh(MeshConfig(tp=2))
    with mesh:
        loss_tp, _ = jax.jit(
            lambda p, b: decoder.loss_fn(p, b, cfg, mesh=mesh)
        )(params, batch)
    loss_1, _ = decoder.loss_fn(params, batch, cfg)
    np.testing.assert_allclose(loss_tp, loss_1, rtol=1e-4)
