"""Estimator full-stack drill — the reference's §3.5 call stack as one
live composition (SURVEY.md §3.5: trainer entry → EstimatorExecutor →
TF_CONFIG from the master → TensorflowFailover → shard-report hook →
TaskManager shards):

- a real master process,
- two KvServer PROCESSES that register as PS nodes over the wire
  (PsClusterCallback builds the versioned ring),
- an estimator worker process training from master-issued data shards,
- a PS killed mid-run (the platform — this test — reports the node
  FAILED, as the k8s watcher would), a replacement registering,
- the worker riding through via the wire-error → ring-reseal →
  checkpoint-restore path, to completion.

The reference survives this by exiting the worker and restarting it
from the checkpoint; here the worker never exits.
"""

import os
import subprocess
import sys
import time
import uuid

import pytest

from elastic_harness import (
    REPO,
    collect,
    drain,
    drain_now,
    kill_tree,
    make_env,
    start_master,
)

RECOVERY_BUDGET_S = 60.0

PS_CODE = """
import sys, threading
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.sparse import GroupAdam
from dlrover_tpu.sparse.embedding import EmbeddingSpec
from dlrover_tpu.sparse.server import KvServer, register_server

addr, node_id = sys.argv[1], int(sys.argv[2])
server = KvServer(
    [
        EmbeddingSpec("emb", 8, initializer="normal", init_scale=0.01,
                      seed=3),
        EmbeddingSpec("wide", 1, initializer="zeros"),
    ],
    optimizer=GroupAdam(lr=5e-3),
)
c = MasterClient(addr, node_id=node_id)
c.register_node(node_type="ps")
register_server(c, f"ps-{node_id}", server.address)
print(f"[ps] ready ps-{node_id} port {server.address[1]}", flush=True)
threading.Event().wait()
"""


def _spawn_ps(run_id, addr, node_id):
    proc = subprocess.Popen(
        [sys.executable, "-c", PS_CODE, addr, str(node_id)],
        cwd=REPO,
        env=make_env(run_id),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    q = drain(proc)
    lines = []
    ready = collect(
        q, lines, until=lambda l: "[ps] ready" in l,
        deadline=time.time() + 60,
    )
    assert ready, f"ps-{node_id} never became ready:\n" + "".join(lines)
    return proc, q, lines


@pytest.mark.slow
def test_estimator_fullstack_ps_failure(tmp_path):
    run_id = f"estfs_{uuid.uuid4().hex[:8]}"
    master = ps0 = ps1 = ps2 = worker = None
    try:
        master, mq, mlines, addr = start_master(run_id)
        ps0, _, _ = _spawn_ps(run_id, addr, 100)
        ps1, _, _ = _spawn_ps(run_id, addr, 101)

        worker = subprocess.Popen(
            [
                sys.executable,
                "examples/train_estimator_elastic.py",
                "--steps", "40",
                "--batch", "256",
                "--model-dir", str(tmp_path / "model"),
            ],
            cwd=REPO,
            env=make_env(
                run_id,
                {
                    "DLROVER_TPU_MASTER_ADDR": addr,
                    "DLROVER_TPU_NODE_ID": "0",
                },
            ),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        wq = drain(worker)
        wlines = []

        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] cluster" in l,
            deadline=time.time() + 90,
        )
        assert line and '"ps-100"' in line and '"ps-101"' in line, (
            "worker never synthesized the PS cluster spec:\n"
            + "".join(wlines)
        )

        # past the first FULL checkpoint (save_steps=10) so the failure
        # has something to restore from
        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] step 12 " in l,
            deadline=time.time() + 240,
        )
        assert line, "worker never reached step 12:\n" + "".join(wlines)

        # ---- kill ps-100; the platform (this test) reports the node
        # FAILED the way the pod watcher would, and a replacement joins
        t_kill = time.time()
        ps0.kill()
        ps0.wait(timeout=10)
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.common.constants import NodeStatus

        watcher = MasterClient(addr, node_id=100)
        watcher.report_node_status(NodeStatus.FAILED, exit_reason="killed")
        ps2, _, _ = _spawn_ps(run_id, addr, 102)

        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] ps change" in l,
            deadline=t_kill + RECOVERY_BUDGET_S,
        )
        assert line and "ps_failure" in line, (
            "worker never failed over the PS ring:\n"
            + "".join(wlines[-40:])
        )
        recovery_s = time.time() - t_kill
        assert recovery_s < RECOVERY_BUDGET_S, recovery_s

        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] done at step 40" in l,
            deadline=time.time() + 300,
        )
        assert line, (
            "worker never finished after the failover:\n"
            + "".join(wlines[-40:])
        )
        assert worker.wait(timeout=60) == 0
        assert master.poll() is None, "master died during the drill"
        drain_now(mq, mlines)
    finally:
        for p in (worker, ps0, ps1, ps2, master):
            if p is not None and p.poll() is None:
                try:
                    kill_tree(p)
                except Exception:
                    p.kill()
