"""Estimator full-stack drill — the reference's §3.5 call stack as one
live composition (SURVEY.md §3.5: trainer entry → EstimatorExecutor →
TF_CONFIG from the master → TensorflowFailover → shard-report hook →
TaskManager shards):

- a real master process,
- two KvServer PROCESSES that register as PS nodes over the wire
  (PsClusterCallback builds the versioned ring),
- an estimator worker process training from master-issued data shards,
- a PS killed mid-run (the platform — this test — reports the node
  FAILED, as the k8s watcher would), a replacement registering,
- the worker riding through via the wire-error → ring-reseal →
  checkpoint-restore path, to completion.

The reference survives this by exiting the worker and restarting it
from the checkpoint; here the worker never exits.
"""

import os
import subprocess
import sys
import time
import uuid

import pytest

from elastic_harness import (
    REPO,
    collect,
    drain,
    drain_now,
    kill_tree,
    make_env,
    start_master,
)

RECOVERY_BUDGET_S = 60.0

def _spawn_ps(run_id, addr, node_id, drain_grace=30, env_extra=None):
    """Run the first-class PS node process (dlrover-tpu-ps): KvServer +
    registration + heartbeats + graceful drain."""
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.sparse.ps_node",
            "--master-addr", addr,
            "--node-id", str(node_id),
            "--table", "emb:8:normal:0.01:3",
            "--table", "wide:1:zeros",
            "--optimizer", "group_adam", "--lr", "5e-3",
            "--heartbeat-interval", "2",
            "--drain-grace", str(drain_grace),
        ],
        cwd=REPO,
        env=make_env(run_id, env_extra),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    q = drain(proc)
    lines = []
    ready = collect(
        q, lines, until=lambda l: "[ps] ready" in l,
        deadline=time.time() + 60,
    )
    assert ready, f"ps-{node_id} never became ready:\n" + "".join(lines)
    return proc, q, lines


@pytest.mark.slow
def test_estimator_fullstack_ps_failure(tmp_path):
    run_id = f"estfs_{uuid.uuid4().hex[:8]}"
    master = ps0 = ps1 = ps2 = worker = None
    try:
        master, mq, mlines, addr = start_master(run_id)
        ps0, _, _ = _spawn_ps(run_id, addr, 100)
        ps1, _, _ = _spawn_ps(run_id, addr, 101)

        worker = subprocess.Popen(
            [
                sys.executable,
                "examples/train_estimator_elastic.py",
                "--steps", "40",
                "--batch", "256",
                "--model-dir", str(tmp_path / "model"),
            ],
            cwd=REPO,
            env=make_env(
                run_id,
                {
                    "DLROVER_TPU_MASTER_ADDR": addr,
                    "DLROVER_TPU_NODE_ID": "0",
                },
            ),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        wq = drain(worker)
        wlines = []

        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] cluster" in l,
            deadline=time.time() + 90,
        )
        assert line and '"ps-100"' in line and '"ps-101"' in line, (
            "worker never synthesized the PS cluster spec:\n"
            + "".join(wlines)
        )

        # past the first FULL checkpoint (save_steps=10) so the failure
        # has something to restore from
        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] step 12 " in l,
            deadline=time.time() + 240,
        )
        assert line, "worker never reached step 12:\n" + "".join(wlines)

        # ---- kill ps-100; the platform (this test) reports the node
        # FAILED the way the pod watcher would, and a replacement joins
        t_kill = time.time()
        ps0.kill()
        ps0.wait(timeout=10)
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.common.constants import NodeStatus

        watcher = MasterClient(addr, node_id=100)
        watcher.report_node_status(NodeStatus.FAILED, exit_reason="killed")
        ps2, _, _ = _spawn_ps(run_id, addr, 102)

        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] ps change" in l,
            deadline=t_kill + RECOVERY_BUDGET_S,
        )
        assert line and "ps_failure" in line, (
            "worker never failed over the PS ring:\n"
            + "".join(wlines[-40:])
        )
        recovery_s = time.time() - t_kill
        assert recovery_s < RECOVERY_BUDGET_S, recovery_s

        line = collect(
            wq, wlines,
            until=lambda l: "[est-worker] done at step 40" in l,
            deadline=time.time() + 300,
        )
        assert line, (
            "worker never finished after the failover:\n"
            + "".join(wlines[-40:])
        )
        assert worker.wait(timeout=60) == 0
        # the master may legitimately exit SUCCEEDED once every worker
        # reported success (master.run: all_workers_succeeded) — only a
        # non-zero exit is a failure
        assert master.poll() in (None, 0), "master died during the drill"
        drain_now(mq, mlines)
    finally:
        for p in (worker, ps0, ps1, ps2, master):
            if p is not None and p.poll() is None:
                try:
                    kill_tree(p)
                except Exception:
                    p.kill()


@pytest.mark.slow
def test_ps_node_graceful_drain():
    """Planned scale-in loses nothing: SIGTERM a PS node — it reports
    SUCCEEDED (ring drops it, version bumps) but KEEPS SERVING through
    its drain grace, so the trainers' adoption migrates its rows
    (values + optimizer slots + admission state) to the survivors; the
    process exits 0 once its tables are empty.  Only a hard kill needs
    the checkpoint-restore path."""
    import signal as sig

    import numpy as np

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.sparse.embedding import EmbeddingSpec
    from dlrover_tpu.sparse.server import (
        DistributedEmbedding,
        resolve_ring,
        sync_with_master,
    )

    run_id = f"psdrain_{uuid.uuid4().hex[:8]}"
    master = ps0 = ps1 = None
    try:
        master, mq, mlines, addr = start_master(run_id)
        ps0, _, _ = _spawn_ps(run_id, addr, 100)
        ps1, _, _ = _spawn_ps(run_id, addr, 101)

        # the trainer must speak the PS processes' wire token (run id)
        os.environ["DLROVER_TPU_RUN_ID"] = run_id
        try:
            worker = MasterClient(addr, node_id=0)
            worker.register_node()
            addrs = resolve_ring(worker, ["ps-100", "ps-101"])
            assert addrs is not None
            specs = [
                EmbeddingSpec("emb", 8, initializer="normal",
                              init_scale=0.01, seed=3),
                EmbeddingSpec("wide", 1, initializer="zeros"),
            ]
            demb = DistributedEmbedding(specs, addrs)
            demb.version = worker.get_ps_version().version
            keys = np.arange(2000, dtype=np.int64)
            demb.pull({"emb": keys})
            before = np.asarray(demb.pull_frozen({"emb": keys})["emb"][0])
            counts = {k: v["emb"] for k, v in demb.stats().items()}
            assert counts["ps-100"] > 0  # it really holds rows to drain

            # planned scale-in: SIGTERM ps-100
            ps0.send_signal(sig.SIGTERM)
            deadline = time.time() + 30
            rerouted = False
            while time.time() < deadline:
                if sync_with_master(demb, worker):
                    rerouted = True
                    break
                time.sleep(0.5)
            assert rerouted, "ring never re-sealed after the drain signal"
            assert demb.server_names == ["ps-101"]

            # every row survived, byte for byte — migrated, not reborn
            after = np.asarray(demb.pull_frozen({"emb": keys})["emb"][0])
            np.testing.assert_allclose(after, before, atol=1e-6)
            assert demb.stats()["ps-101"]["emb"] == len(keys)

            # the drained process exits 0 once empty (inside its grace)
            assert ps0.wait(timeout=30) == 0
            demb.close()
        finally:
            os.environ.pop("DLROVER_TPU_RUN_ID", None)
    finally:
        for p in (ps0, ps1, master):
            if p is not None and p.poll() is None:
                try:
                    kill_tree(p)
                except Exception:
                    p.kill()


@pytest.mark.slow
def test_estimator_worker_restart_under_agent(tmp_path):
    """§3.5's WORKER-failover leg under the real launcher/agent: the
    estimator worker is SIGKILLed mid-run, the agent restarts it, and
    the restarted process resumes from the latest checkpoint (model +
    ring snapshot + dataset position) and finishes — the reference's
    TF_CONFIG-failover restart, supervised by our agent instead of
    torch elastic."""
    import signal as sig

    from elastic_harness import launch_agent

    run_id = f"estrestart_{uuid.uuid4().hex[:8]}"
    wire_token = f"{run_id}-wire"
    env_extra = {"DLROVER_TPU_WIRE_TOKEN": wire_token}
    master = ps0 = ps1 = agent = None
    try:
        master, mq, mlines, addr = start_master(
            run_id, env_extra=env_extra
        )
        ps0, _, _ = _spawn_ps(run_id, addr, 100, env_extra=env_extra)
        ps1, _, _ = _spawn_ps(run_id, addr, 101, env_extra=env_extra)

        agent = launch_agent(
            run_id, 0, addr,
            train_args=[
                "--steps", "40", "--batch", "256",
                "--model-dir", str(tmp_path / "model"),
            ],
            nnodes="1",
            script="examples/train_estimator_elastic.py",
            env_extra=env_extra,
        )
        aq = drain(agent)
        alines = []

        pid_line = collect(
            aq, alines,
            until=lambda l: "[est-worker] pid " in l,
            deadline=time.time() + 120,
        )
        assert pid_line, (
            "worker never started under the agent:\n" + "".join(alines)
        )
        worker_pid = int(pid_line.split("pid", 1)[1].strip())

        line = collect(
            aq, alines,
            until=lambda l: "[est-worker] step 12 " in l,
            deadline=time.time() + 240,
        )
        assert line, "worker never reached step 12:\n" + "".join(alines)

        # SIGKILL only the worker; the agent must notice, persist
        # nothing extra (estimator checkpoints are its own), and restart
        t_kill = time.time()
        os.kill(worker_pid, sig.SIGKILL)

        line = collect(
            aq, alines,
            until=lambda l: "[est-worker] resumed from step" in l,
            deadline=t_kill + RECOVERY_BUDGET_S,
        )
        assert line, (
            "restarted worker never resumed from the checkpoint:\n"
            + "".join(alines[-40:])
        )
        resumed_step = int(line.rsplit("step", 1)[1].strip())
        assert resumed_step >= 10  # at least the step-10 full save
        recovery_s = time.time() - t_kill
        assert recovery_s < RECOVERY_BUDGET_S, recovery_s

        line = collect(
            aq, alines,
            until=lambda l: "[est-worker] done at step 40" in l,
            deadline=time.time() + 300,
        )
        assert line, (
            "restarted worker never finished:\n" + "".join(alines[-40:])
        )
        assert agent.wait(timeout=120) == 0
        assert master.poll() in (None, 0)
        drain_now(mq, mlines)
    finally:
        for p in (agent, ps0, ps1, master):
            if p is not None and p.poll() is None:
                try:
                    kill_tree(p)
                except Exception:
                    p.kill()


@pytest.mark.slow
def test_two_estimator_workers_share_shards(tmp_path):
    """Two estimator workers under one master train against the SAME
    KvServer ring from master-issued shards (the async-PS data-parallel
    shape of the reference's TF PS jobs): the chief (worker-0)
    checkpoints, worker-1 does not, both finish, and the master stays
    up.  Shard disjointness itself is the TaskManager's property
    (test_master); this is the two-trainers-one-ring composition."""
    run_id = f"est2w_{uuid.uuid4().hex[:8]}"
    master = ps0 = ps1 = w0 = w1 = None
    try:
        master, mq, mlines, addr = start_master(
            run_id, argv_extra=("--num-workers", "2")
        )
        ps0, _, _ = _spawn_ps(run_id, addr, 100)
        ps1, _, _ = _spawn_ps(run_id, addr, 101)

        def spawn_worker(node_id, model_dir):
            return subprocess.Popen(
                [
                    sys.executable,
                    "examples/train_estimator_elastic.py",
                    "--steps", "20",
                    "--batch", "128",
                    "--model-dir", model_dir,
                ],
                cwd=REPO,
                env=make_env(
                    run_id,
                    {
                        "DLROVER_TPU_MASTER_ADDR": addr,
                        "DLROVER_TPU_NODE_ID": str(node_id),
                    },
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )

        d0 = str(tmp_path / "m0")
        d1 = str(tmp_path / "m1")
        w0 = spawn_worker(0, d0)
        q0 = drain(w0)
        l0 = []
        assert collect(
            q0, l0, until=lambda l: "[est-worker] cluster" in l,
            deadline=time.time() + 90,
        ), "worker 0 never started:\n" + "".join(l0)
        w1 = spawn_worker(1, d1)
        q1 = drain(w1)
        l1 = []

        done0 = collect(
            q0, l0, until=lambda l: "[est-worker] done at step 20" in l,
            deadline=time.time() + 300,
        )
        done1 = collect(
            q1, l1, until=lambda l: "[est-worker] done at step 20" in l,
            deadline=time.time() + 300,
        )
        assert done0, "worker 0 never finished:\n" + "".join(l0[-30:])
        assert done1, "worker 1 never finished:\n" + "".join(l1[-30:])
        assert w0.wait(timeout=60) == 0
        assert w1.wait(timeout=60) == 0
        # only the chief checkpointed
        assert os.path.exists(os.path.join(d0, "checkpoint"))
        assert not os.path.exists(os.path.join(d1, "checkpoint"))
        assert master.poll() in (None, 0)
        drain_now(mq, mlines)
    finally:
        for p in (w0, w1, ps0, ps1, master):
            if p is not None and p.poll() is None:
                try:
                    kill_tree(p)
                except Exception:
                    p.kill()
