"""Ulysses SP and ring attention numerics vs the reference attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.ops.attention import mha_reference
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel.sequence import ring_attention, ulysses_attention

# ring-attention compiles are heavy on the CPU mesh; excluded from the tier-1 budget
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(MeshConfig(dp=2, sp=4))


def _qkv(key, b=2, s=128, h=4, d=32):
    ks = jax.random.split(key, 3)
    return (
        jax.random.normal(ks[0], (b, s, h, d)),
        jax.random.normal(ks[1], (b, s, h, d)),
        jax.random.normal(ks[2], (b, s, h, d)),
    )


def _shard_seq(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P(None, "sp", None, None)))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(mesh, causal):
    q, k, v = _qkv(jax.random.key(0))
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention(
        _shard_seq(mesh, q),
        _shard_seq(mesh, k),
        _shard_seq(mesh, v),
        mesh,
        causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(mesh, causal):
    q, k, v = _qkv(jax.random.key(1))
    ref = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention(
        _shard_seq(mesh, q),
        _shard_seq(mesh, k),
        _shard_seq(mesh, v),
        mesh,
        causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ulysses_gqa_on_sp_tp_mesh():
    """Regression: sp=4×tp=2 GQA (Hq=8, Hkv=4). The kv-expansion decision
    must use the tp-LOCAL kv head count (4%4==0 globally, but each tp
    shard holds 2 kv heads, which sp=4 cannot split without expansion)."""
    mesh = build_mesh(MeshConfig(sp=4, tp=2))
    ks = jax.random.split(jax.random.key(3), 3)
    b, s, hq, hkv, d = 2, 64, 8, 4, 16
    q = jax.random.normal(ks[0], (b, s, hq, d))
    k = jax.random.normal(ks[1], (b, s, hkv, d))
    v = jax.random.normal(ks[2], (b, s, hkv, d))
    ref = mha_reference(q, k, v, causal=True)

    def put(x):
        return jax.device_put(
            x, NamedSharding(mesh, P(None, "sp", "tp", None))
        )

    out = ulysses_attention(put(q), put(k), put(v), mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_train_step_matches_dp(mesh):
    """Full train step with ring attention == plain attention numerics."""
    from dlrover_tpu.accelerate import auto_accelerate
    from dlrover_tpu.models import get_config

    cfg = get_config("tiny")
    tokens = jax.random.randint(jax.random.key(5), (8, 64), 0, 1000)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def run(strategy):
        res = auto_accelerate(
            cfg, global_batch=8, seq=64, strategy=strategy
        )
        state = res.init_state(jax.random.key(0))
        b = jax.device_put(batch, res.batch_sharding)
        state, metrics = res.train_step(state, b)
        return float(metrics["loss"])

    loss_dp = run([("mixed_parallel", {"dp": -1})])
    loss_ring = run(
        [
            ("mixed_parallel", {"dp": 2, "sp": 4}),
            ("ring_attention", {"size": 4}),
        ]
    )
    assert loss_dp == pytest.approx(loss_ring, rel=1e-4)


def test_ring_attention_grads(mesh):
    q, k, v = _qkv(jax.random.key(2), s=64)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, causal=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring)(
        _shard_seq(mesh, q), _shard_seq(mesh, k), _shard_seq(mesh, v)
    )
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_ref), rtol=5e-4, atol=5e-4
    )


def test_ulysses_prefix_matches_reference(mesh):
    """Prefix-LM masking through the all-to-all path (GLM + ulysses)."""
    q, k, v = _qkv(jax.random.key(5))
    prefix = jnp.array([17, 90], jnp.int32)
    ref = mha_reference(q, k, v, causal=True, prefix_len=prefix)
    out = ulysses_attention(
        _shard_seq(mesh, q),
        _shard_seq(mesh, k),
        _shard_seq(mesh, v),
        mesh,
        causal=True,
        prefix_len=prefix,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ulysses_window_matches_reference(mesh):
    """Sliding window through the all-to-all path: the inner attention
    sees global positions, so the mask carries over unchanged."""
    q, k, v = _qkv(jax.random.key(11))
    ref = mha_reference(q, k, v, causal=True, window=40)
    out = ulysses_attention(
        _shard_seq(mesh, q),
        _shard_seq(mesh, k),
        _shard_seq(mesh, v),
        mesh,
        causal=True,
        window=40,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("window", [20, 48, 130])
def test_ring_window_matches_reference(mesh, window):
    """Sliding window over the ring (jnp block path): windows smaller
    than, spanning, and exceeding the 32-wide ring blocks."""
    q, k, v = _qkv(jax.random.key(12))  # s=128 over sp=4 → 32-blocks
    ref = mha_reference(q, k, v, causal=True, window=window)
    out = ring_attention(
        _shard_seq(mesh, q),
        _shard_seq(mesh, k),
        _shard_seq(mesh, v),
        mesh,
        causal=True,
        window=window,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_window_flash_path(monkeypatch):
    """Windowed ring over the flash-kernel path: dense, diagonal
    causal+window, boundary-partial, and empty block cases all hit."""
    from dlrover_tpu.ops import pallas_attention as pa

    if pa.pltpu is None:
        pytest.skip("pallas TPU module unavailable")
    monkeypatch.setattr(pa, "INTERPRET", True)
    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    mesh = build_mesh(MeshConfig(sp=4, dp=2))
    b, s, h, d = 2, 1024, 2, 32  # 256-wide ring blocks
    ks = jax.random.split(jax.random.key(13), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    window = 400  # crosses one block boundary, darkens distant blocks
    out = ring_attention(q, k, v, mesh, causal=True, window=window)
    ref = mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-3, atol=3e-3
    )

    def loss(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, causal=True, window=window) ** 2
        )

    def ref_loss(q, k, v):
        return jnp.sum(
            mha_reference(q, k, v, causal=True, window=window) ** 2
        )

    g = jax.grad(loss)(q, k, v)
    rg = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(rg), rtol=5e-3, atol=5e-3
    )


def test_ring_window_flash_path_gqa(monkeypatch):
    """GQA through the windowed flash ring: k/v stay at hkv heads on the
    ring (groups× fewer ppermute bytes) and the offset kernel handles
    the boundary blocks without a head expansion."""
    from dlrover_tpu.ops import pallas_attention as pa

    if pa.pltpu is None:
        pytest.skip("pallas TPU module unavailable")
    monkeypatch.setattr(pa, "INTERPRET", True)
    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    mesh = build_mesh(MeshConfig(sp=4, dp=2))
    b, s, hq, hkv, d = 2, 1024, 4, 2, 32
    ks = jax.random.split(jax.random.key(21), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d))
    k = jax.random.normal(ks[1], (b, s, hkv, d))
    v = jax.random.normal(ks[2], (b, s, hkv, d))
    window = 400
    out = ring_attention(q, k, v, mesh, causal=True, window=window)
    ref = mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-3, atol=3e-3
    )


def test_ring_prefix_matches_reference(mesh):
    """Prefix-LM masking through the ring (jnp block path): prefixes
    crossing ring-block boundaries, incl. one inside an after-block."""
    q, k, v = _qkv(jax.random.key(6))  # s=128 over sp=4 → 32-blocks
    prefix = jnp.array([50, 100], jnp.int32)
    ref = mha_reference(q, k, v, causal=True, prefix_len=prefix)
    out = ring_attention(
        _shard_seq(mesh, q),
        _shard_seq(mesh, k),
        _shard_seq(mesh, v),
        mesh,
        causal=True,
        prefix_len=prefix,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    def loss(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh, causal=True, prefix_len=prefix
            ) ** 2
        )

    def ref_loss(q, k, v):
        return jnp.sum(
            mha_reference(q, k, v, causal=True, prefix_len=prefix) ** 2
        )

    g = jax.grad(loss)(q, k, v)
    rg = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(rg), rtol=5e-4, atol=5e-4
    )


def test_ring_prefix_flash_path(monkeypatch):
    """Prefix ring over the flash-kernel path (interpret): diagonal
    causal+prefix blocks and prefix-reaching after-blocks."""
    from dlrover_tpu.ops import pallas_attention as pa

    if pa.pltpu is None:
        pytest.skip("pallas TPU module unavailable")
    monkeypatch.setattr(pa, "INTERPRET", True)
    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    mesh = build_mesh(MeshConfig(sp=2, dp=4))
    b, s, h, d = 4, 512, 4, 32
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    # one prefix inside the first ring block, one reaching the second
    prefix = jnp.array([100, 300, 0, 511], jnp.int32)
    out = ring_attention(q, k, v, mesh, causal=True, prefix_len=prefix)
    ref = mha_reference(q, k, v, causal=True, prefix_len=prefix)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-3, atol=3e-3
    )

    # gradients: prefix must flow through flash_attention_with_lse's
    # custom_vjp (float0 dprefix) and the g_lse chunked backward
    def loss(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh, causal=True, prefix_len=prefix
            ) ** 2
        )

    def ref_loss(q, k, v):
        return jnp.sum(
            mha_reference(q, k, v, causal=True, prefix_len=prefix) ** 2
        )

    g = jax.grad(loss)(q, k, v)
    rg = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(rg), rtol=5e-3, atol=5e-3
    )


def test_ring_attention_flash_path_matches_reference(monkeypatch):
    """Exercise the flash-kernel ring path (lax.switch over kernel
    variants + lse merge) on the CPU mesh via interpret mode."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.ops import pallas_attention as pa
    from dlrover_tpu.ops.attention import mha_reference
    from dlrover_tpu.parallel import MeshConfig, build_mesh
    from dlrover_tpu.parallel.sequence import ring_attention

    if pa.pltpu is None:
        pytest.skip("pallas TPU module unavailable: flash path untestable")
    monkeypatch.setattr(pa, "INTERPRET", True)
    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    # _fit_block needs 128-multiples: S=512 over sp=2 → 256-local blocks
    mesh = build_mesh(MeshConfig(sp=2, dp=4))
    b, s, h, d = 4, 512, 4, 32
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-3, atol=3e-3
    )

    # gradients flow through the kernel + lse merge
    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g = jax.grad(loss)(q, k, v)
    rg = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(rg), rtol=5e-3, atol=5e-3
    )
