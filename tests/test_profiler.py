"""Observability tier: kernel census, step timer, loss-spike, numerics."""

import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.master.job_metrics import MetricsHTTPServer
from dlrover_tpu.observability import (
    KernelCensus,
    LossSpikeDetector,
    NumericChecker,
    StepTimer,
    WorkerMetrics,
    check_finite,
    profile_compiled,
    sanitize_grads,
)


def _step(w, x):
    h = jnp.tanh(x @ w)
    return jax.lax.psum(h.sum(), None) if False else h.sum()


@pytest.mark.slow
def test_kernel_census_finds_dots_and_collectives():
    mesh = jax.make_mesh((8,), ("dp",))

    def fn(w, x):
        h = x @ w
        return jax.lax.pmean(h.sum(), "dp")

    from jax.sharding import NamedSharding, PartitionSpec as P
    from functools import partial

    shard = jax.shard_map(
        fn, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P()
    )
    w = jnp.ones((16, 32), jnp.float32)
    x = jnp.ones((8, 16), jnp.float32)
    compiled = jax.jit(shard).lower(w, x).compile()
    census = KernelCensus.from_compiled(compiled)
    assert census.matmuls, "dot ops must be censused"
    assert census.collectives, "psum must appear as an all-reduce"
    kinds = {r.kind for r in census.collectives}
    assert "all-reduce" in kinds
    s = census.summary()
    assert s["num_matmul_buckets"] >= 1


# canned HLO exercising exactly the parsing hazards from_compiled must
# handle without a device: TPU async collective pairs (-start/-done),
# fp8 wire dtypes, and bucket clustering by (op, dtype, shape)
_CANNED_HLO = """\
HloModule jit_train_step, entry_computation_layout={...}

ENTRY %main.42 {
  %p0 = f32[8,128]{1,0} parameter(0)
  %p1 = bf16[128,1024]{1,0} parameter(1)
  %dot.1 = bf16[8,1024]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
  %dot.2 = bf16[8,1024]{1,0} dot(%p0, %p1), rhs_contracting_dims={0}
  %q = f8e4m3[8,128]{1,0} convert(%p0)
  %dot.3 = f8e4m3[8,1024]{1,0} dot(%q, %kq)
  %ar-start.1 = bf16[1024]{0} all-reduce-start(%g), replica_groups={{0,1}}
  %ar-done.1 = bf16[1024]{0} all-reduce-done(%ar-start.1)
  %ag.1 = f8e5m2[2048]{0} all-gather(%w8), dimensions={0}
  %rs-start.1 = f32[512]{0} reduce-scatter-start(%acc)
  %rs-done.1 = f32[512]{0} reduce-scatter-done(%rs-start.1)
  ROOT %tuple = (bf16[8,1024]{1,0}) tuple(%dot.1)
}
"""


class _FakeCompiled:
    """Duck-typed stand-in for jax's Compiled (as_text + cost_analysis)."""

    def __init__(self, hlo, cost=None):
        self._hlo = hlo
        self._cost = cost

    def as_text(self):
        return self._hlo

    def cost_analysis(self):
        return self._cost


def test_kernel_census_canned_hlo_async_dedup_and_fp8():
    census = KernelCensus.from_compiled(
        _FakeCompiled(_CANNED_HLO, cost=[{"flops": 123.0,
                                          "bytes accessed": 456.0}])
    )
    # async pairs count once: the -start is censused, the -done skipped
    ar = [r for r in census.collectives if r.kind == "all-reduce"]
    assert len(ar) == 1 and ar[0].count == 1
    assert ar[0].dtype == "bf16" and ar[0].shape == (1024,)
    rs = [r for r in census.collectives if r.kind == "reduce-scatter"]
    assert len(rs) == 1 and rs[0].count == 1
    # fp8 wire dtypes parse (both e4m3 and e5m2 variants)
    ag = [r for r in census.collectives if r.kind == "all-gather"]
    assert ag[0].dtype == "f8e5m2" and ag[0].shape == (2048,)
    fp8_dots = [r for r in census.matmuls if r.dtype == "f8e4m3"]
    assert len(fp8_dots) == 1
    # identical (op, dtype, shape) dots cluster into one bucket
    bf16_dots = [r for r in census.matmuls if r.dtype == "bf16"]
    assert len(bf16_dots) == 1 and bf16_dots[0].count == 2
    s = census.summary()
    assert s["num_collective_buckets"] == 3
    assert s["num_matmul_buckets"] == 2
    # older-jax list-of-dict cost shape unwraps
    assert s["flops"] == 123.0 and s["bytes_accessed"] == 456.0


def test_kernel_census_cost_analysis_failure_is_nonfatal():
    class _Broken(_FakeCompiled):
        def cost_analysis(self):
            raise RuntimeError("unsupported backend")

    census = KernelCensus.from_compiled(_Broken(_CANNED_HLO))
    assert census.matmuls and census.flops == 0.0


def test_profile_compiled_reports_flops():
    w = jnp.ones((64, 64), jnp.float32)
    x = jnp.ones((8, 64), jnp.float32)
    out = profile_compiled(_step, w, x)
    # 2*M*N*K = 2*8*64*64 = 65536 flops for the matmul alone
    assert out["flops"] >= 2 * 8 * 64 * 64
    assert out["census"].matmuls


def test_step_timer_and_worker_metrics_endpoint():
    timer = StepTimer(flops_per_step=1e9, peak_flops=1e12)
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    for _ in range(3):
        timer.start()
        timer.stop(f(x))
    assert timer.mean_s > 0
    assert timer.steps_per_s > 0
    assert 0 < timer.mfu  # 1e9 flops at some measured rate
    assert timer.percentile(99) >= timer.percentile(0)

    wm = WorkerMetrics()
    wm.inc("restarts_total")
    wm.observe_timer(timer)
    srv = MetricsHTTPServer(wm, port=0)  # duck-typed collector
    srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics"
        ).read().decode()
        assert "dlrover_tpu_worker_restarts_total 1.0" in body
        assert "steps_per_second" in body
    finally:
        srv.stop()


def test_loss_spike_detector(tmp_path):
    det = LossSpikeDetector(
        save_dir=str(tmp_path), min_iter=10, min_loss=3.0, zscore=4.0,
        window=50,
    )
    # warmup: high loss before min_iter is not a spike
    assert not det.update(1, 9.0)
    for it in range(10, 60):
        assert not det.update(it, 2.0 + 0.01 * np.random.rand())
    # spike above floor + z-score, with per-sample culprits
    assert det.update(
        60, 7.5, sample_ids=[11, 22, 33, 44],
        per_sample_losses=[1.0, 9.0, 2.0, 8.0],
    )
    # another z-score spike just above the floor (spike at 60 must not
    # have poisoned the rolling baseline)
    assert det.update(61, 4.0)
    # below the absolute floor is never a spike, however anomalous
    assert not det.update(62, 2.9)

    # a plateau above the floor does not flag every step: z-score gate
    det2 = LossSpikeDetector(
        save_dir=None, min_iter=0, min_loss=3.0, zscore=4.0, window=50
    )
    flagged = sum(det2.update(i, 4.5 + 0.01 * (i % 3)) for i in range(100))
    assert flagged == 0
    files = list(tmp_path.iterdir())
    assert files
    records = LossSpikeDetector.decode(str(files[0]))
    assert records[0][1] == 60 and records[0][2] == 7.5
    # worst sample (id 22, loss 9.0) listed first
    assert records[0][3].startswith("22:9.0")


def test_numeric_checker_and_finite():
    a = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)}
    b = {"w": jnp.ones((4, 4)) * (1 + 1e-6), "b": jnp.zeros(4)}
    chk = NumericChecker(rtol=1e-3)
    assert chk.allclose(a, b)
    b["w"] = b["w"].at[0, 0].set(2.0)
    assert not chk.allclose(a, b)
    rep = chk.compare(a, b)
    assert any(r.get("max_abs_err", 0) > 0.5 for r in rep.values())

    bad = {"w": jnp.array([1.0, jnp.nan]), "b": jnp.zeros(2)}
    names = check_finite(bad)
    assert len(names) == 1 and "w" in names[0]


@pytest.mark.parametrize("mode", ["skip", "zero"])
def test_sanitize_grads(mode):
    tx = sanitize_grads(mode)
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    good = {"w": jnp.array([1.0, 2.0, 3.0])}
    upd, state = jax.jit(tx.update)(good, state)
    assert jnp.allclose(upd["w"], good["w"])
    assert int(state.nonfinite_count) == 0

    bad = {"w": jnp.array([1.0, jnp.inf, 3.0])}
    upd, state = jax.jit(tx.update)(bad, state)
    assert int(state.nonfinite_count) == 1
    if mode == "skip":
        assert jnp.allclose(upd["w"], 0.0)
    else:
        assert jnp.allclose(upd["w"], jnp.array([1.0, 0.0, 3.0]))
