"""ZeRO-1 weight-update sharding: HLO guard, parity rollouts, gates.

The contract under test (train/train_step.py resolve_update_sharding +
parallel/sharding.py exchange path):

- Gradients leave the backward as bucketed reduce-scatters (f32 wire)
  or all-to-alls (bf16/int8 wire) — never as a full-gradient
  all-reduce. Small scalar all-reduces (loss psum, denom) are fine.
- The optimizer steps a ``[n_buckets, bucket_elems/dp]`` shard per
  rank, so state bytes per replica drop by ~dp (plus bucket padding).
- On the f32 wire the whole rollout is BITWISE identical to the
  replicated update for the untied-embedding configs: the manual
  apply region pins the ``-lr*y`` mult → ``p+u`` add adjacency the
  XLA:CPU contraction pass otherwise splits across the all-gather.

Known 1-ulp-origin codegen artifacts (pinned by tolerance, not
bitwise — each traced to a fusion-boundary difference, measured over
6 steps on the tiny f32 config):

- tie_embeddings: the replicated baseline inlines the lookup+head
  cotangent add into the embedding's nu (variance) fusion; sharded
  can't. Embedding nu diverges by 1 ulp from step 1 (worst param rel
  ~2.5e-3 by step 6; losses agree to ~1e-6).
- grad_accum > 1: the per-microbatch scatter-add into the embedding
  grad rounds differently under the scan (~1e-3 worst rel).
- grad_clip chains: global_norm sums flat buckets vs per-leaf trees
  in different orders (~6e-3 worst rel, dloss ~5e-7).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models.config import get_config
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh, single_device_mesh
from dlrover_tpu.train.optimizer import make_optimizer, opt_state_bytes_per_replica
from dlrover_tpu.train.train_step import (
    TrainStepBuilder,
    init_train_state,
    resolve_update_sharding,
)

DP = 8


def tiny_cfg(**kw):
    kw.setdefault("dtype", "float32")
    return get_config(
        "tiny",
        n_layer=2,
        d_model=64,
        d_ff=128,
        n_head=4,
        vocab_size=128,
        max_seq=32,
        **kw,
    )


def dp_mesh():
    return build_mesh(MeshConfig(dp=-1))


def comm_cfg(**kw):
    kw.setdefault("bucket_mb", 0.05)
    return shd.CommConfig(update_sharding=True, **kw)


def batches(n, batch=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        base = rng.randint(0, vocab, size=(batch, 33))
        yield {
            "tokens": jnp.asarray(base[:, :-1], jnp.int32),
            "targets": jnp.asarray(base[:, 1:], jnp.int32),
        }


def rollout_pair(cfg, opt_fn, comm, steps=6, batch=16, accum=1):
    """Run replicated and sharded builders in lockstep; return final
    (state_u, state_s, metrics_u, metrics_s)."""
    mesh = dp_mesh()
    bu = TrainStepBuilder(cfg, mesh, opt_fn(), grad_accum=accum)
    bs = TrainStepBuilder(cfg, mesh, opt_fn(), grad_accum=accum, comm=comm)
    assert bs.update_sharding, bs.update_sharding_reason
    su = init_train_state(jax.random.key(0), cfg, mesh, bu.optimizer)
    ss = init_train_state(
        jax.random.key(0), cfg, mesh, bs.optimizer, comm=bs.comm_resolved
    )
    fu = jax.jit(bu.step_fn)
    fs = jax.jit(bs.step_fn)
    mu = ms = None
    for b in batches(steps, batch=batch):
        su, mu = fu(su, b)
        ss, ms = fs(ss, b)
    return su, ss, mu, ms


def params_worst_rel(pu, ps, floor=1e-30):
    """Worst elementwise |x-y|/max(|x|, floor) over the tree. The
    default floor makes this a pure relative error (right for the
    1-ulp-origin artifacts, whose error scales with the value); lossy
    wires pass a floor near the weight scale so near-zero params don't
    dominate the ratio."""
    worst = 0.0
    for x, y in zip(jax.tree.leaves(pu), jax.tree.leaves(ps)):
        x, y = np.asarray(x), np.asarray(y)
        worst = max(
            worst,
            float(np.max(np.abs(x - y) / np.maximum(np.abs(x), floor))),
        )
    return worst


# ---------------------------------------------------------------------------
# Gates: unsupported combinations fall back with a recorded reason
# ---------------------------------------------------------------------------


def test_gate_dp1_falls_back():
    cfg = tiny_cfg()
    active, reason, plan = resolve_update_sharding(
        cfg, single_device_mesh(), optax.adamw(1e-3), comm_cfg()
    )
    assert not active and plan is None
    assert "dp" in reason


def test_gate_non_dp_axes():
    """Axes beyond fsdp/tp (here pp) still refuse — the exchange is
    only defined over dp with fsdp/tp left to the auto partitioner."""
    cfg = tiny_cfg()
    mesh = build_mesh(MeshConfig(dp=-1, pp=2))
    active, reason, _ = resolve_update_sharding(
        cfg, mesh, optax.adamw(1e-3), comm_cfg()
    )
    assert not active
    assert "non-dp" in reason


def test_gate_hybrid_meshes_activate():
    """dp×tp and dp×fsdp are in the zoo now: the resolve must come back
    active with the mesh axes recorded on the plan (the partial-manual
    region and the resharding refusal both key off mesh_axes)."""
    cfg = tiny_cfg()
    for kw, axes in (
        ({"tp": 2}, ("dp", "tp")),
        ({"fsdp": 2}, ("dp", "fsdp")),
    ):
        mesh = build_mesh(MeshConfig(dp=-1, **kw))
        active, reason, plan = resolve_update_sharding(
            cfg, mesh, optax.adamw(1e-3), comm_cfg()
        )
        assert active, reason
        assert plan.mesh_axes == axes
        assert plan.dp == mesh.shape["dp"]


def test_gate_hybrid_mesh_quantized_wire_falls_back():
    """bf16/int8 wires ride all_to_all, which cannot lower inside the
    partial-manual region — hybrid meshes must refuse, pure-dp keeps
    working."""
    cfg = tiny_cfg()
    mesh = build_mesh(MeshConfig(dp=-1, tp=2))
    active, reason, _ = resolve_update_sharding(
        cfg, mesh, optax.adamw(1e-3), comm_cfg(wire_dtype="bfloat16")
    )
    assert not active
    assert "wire" in reason or "pure-dp" in reason
    active, reason, _ = resolve_update_sharding(
        cfg, dp_mesh(), optax.adamw(1e-3), comm_cfg(wire_dtype="bfloat16")
    )
    assert active, reason


def test_gate_hybrid_mesh_fp8_falls_back():
    """fp8 delayed-scaling state threads the pure-dp manual region
    only; on a hybrid mesh the resolve refuses rather than dropping the
    scaling state."""
    cfg = tiny_cfg(fp8=True)
    mesh = build_mesh(MeshConfig(dp=-1, tp=2))
    active, reason, _ = resolve_update_sharding(
        cfg, mesh, optax.adamw(1e-3), comm_cfg()
    )
    assert not active
    assert "fp8" in reason


def test_update_mode_semantics():
    """CommConfig mode strings: False=off, "zero1"=deferred exchange,
    "zero2"=per-microbatch scatter, True=legacy alias for zero2."""
    assert shd.CommConfig().update_mode == ""
    assert shd.CommConfig(update_sharding="zero1").update_mode == "zero1"
    assert shd.CommConfig(update_sharding="zero2").update_mode == "zero2"
    assert shd.CommConfig(update_sharding=True).update_mode == "zero2"
    with pytest.raises(ValueError):
        shd.CommConfig(update_sharding="zero3")


def test_gate_offload_and_custom_loss():
    cfg = tiny_cfg()
    mesh = dp_mesh()
    active, reason, _ = resolve_update_sharding(
        cfg, mesh, optax.adamw(1e-3), comm_cfg(), offload_opt_state=True
    )
    assert not active and "offload" in reason
    active, reason, _ = resolve_update_sharding(
        cfg, mesh, optax.adamw(1e-3), comm_cfg(), loss_fn=lambda *a: 0.0
    )
    assert not active and "loss_fn" in reason


def test_gate_factored_optimizer_rejected():
    """adafactor's state is row/col-factored — a flat-offset shard of
    it is meaningless, so the optimizer probe must refuse."""
    cfg = tiny_cfg()
    active, reason, _ = resolve_update_sharding(
        cfg, dp_mesh(), optax.adafactor(1e-3), comm_cfg()
    )
    assert not active
    assert reason


def test_builder_falls_back_not_fails():
    """An unsupported combo builds a working replicated step."""
    cfg = tiny_cfg(n_experts=2)
    b = TrainStepBuilder(cfg, dp_mesh(), optax.adamw(1e-3), comm=comm_cfg())
    assert not b.update_sharding
    assert b.comm_resolved is None
    assert "MoE" in b.update_sharding_reason


# ---------------------------------------------------------------------------
# Wire format roundtrips
# ---------------------------------------------------------------------------


def test_pack_unpack_roundtrip():
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = dp_mesh()
    b = TrainStepBuilder(cfg, mesh, optax.adamw(1e-3), comm=comm_cfg())
    plan = b._plan
    state = init_train_state(jax.random.key(0), cfg, mesh, optax.adamw(1e-3))
    flat = shd.pack_flat(state["params"], plan)
    assert flat.shape == (plan.n_buckets, plan.bucket_elems)
    back = shd.unpack_flat(flat, state["params"], plan)
    for x, y in zip(jax.tree.leaves(state["params"]), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# HLO guard + state bytes (one compile, several assertions)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def compiled_sharded():
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = dp_mesh()
    comm = comm_cfg()
    b = TrainStepBuilder(cfg, mesh, optax.adamw(1e-3), comm=comm)
    assert b.update_sharding, b.update_sharding_reason
    state = init_train_state(
        jax.random.key(0), cfg, mesh, b.optimizer, comm=b.comm_resolved
    )
    batch = next(batches(1))
    compiled = jax.jit(b.step_fn).lower(state, batch).compile()
    return cfg, comm, b, state, compiled


def test_hlo_has_rs_and_ag(compiled_sharded):
    # function-local: bench is the slow-suite module (see
    # test_marker_lint's bench-import rule)
    from bench import collective_stats

    _, _, _, _, compiled = compiled_sharded
    stats = collective_stats(compiled.as_text())
    counts = stats["counts"]
    assert counts.get("reduce-scatter", 0) > 0, counts
    assert counts.get("all-gather", 0) > 0, counts


def test_hlo_no_full_gradient_all_reduce(compiled_sharded):
    """Every all-reduce left in the program must be a small scalar-ish
    reduction (loss, denom) — the gradient payload rides the
    reduce-scatters. Guard: no f32 all-reduce result within 2x of the
    total parameter count."""
    cfg, _, b, _, compiled = compiled_sharded
    n_params = b._plan.total
    for line in compiled.as_text().splitlines():
        if " = " not in line:
            continue
        rhs = line.split(" = ", 1)[1]
        if "all-reduce(" not in rhs:
            continue
        head = rhs.split("all-reduce(", 1)[0]
        elems = sum(
            int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
            for _, dims in re.findall(r"(f32|bf16)\[([0-9,]*)\]", head)
        )
        assert elems < n_params // 2, (
            f"full-gradient-sized all-reduce survived: {line.strip()[:160]}"
        )


def test_opt_state_bytes_per_replica(compiled_sharded):
    cfg, comm, b, state, _ = compiled_sharded
    mesh = dp_mesh()
    full_state = init_train_state(jax.random.key(0), cfg, mesh, optax.adamw(1e-3))
    full = opt_state_bytes_per_replica(full_state["opt_state"])
    rep = opt_state_bytes_per_replica(state["opt_state"])
    assert rep <= full / DP + 3 * comm.bucket_bytes, (rep, full)


def test_sharded_step_loss_matches_replicated(compiled_sharded):
    cfg, _, b, state, compiled = compiled_sharded
    mesh = dp_mesh()
    bu = TrainStepBuilder(cfg, mesh, optax.adamw(1e-3))
    su = init_train_state(jax.random.key(0), cfg, mesh, bu.optimizer)
    batch = next(batches(1))
    _, mu = jax.jit(bu.step_fn)(su, batch)
    _, ms = compiled(state, batch)
    assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-6


# ---------------------------------------------------------------------------
# Parity rollouts (slow: each compiles two step programs and runs 6 steps)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bitwise_f32_wire_untied():
    """The acceptance bar: f32-wire training is bitwise identical to
    the replicated update over a multi-step rollout."""
    su, ss, mu, ms = rollout_pair(
        tiny_cfg(tie_embeddings=False), lambda: optax.adamw(1e-3), comm_cfg()
    )
    for x, y in zip(jax.tree.leaves(su["params"]), jax.tree.leaves(ss["params"])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert float(mu["loss"]) == float(ms["loss"])


@pytest.mark.slow
def test_fused_adamw_composes_tied():
    """fused_adamw path composes with update sharding; tied embeddings
    carry the usual nu-fusion artifact so this pins a tight tolerance
    rather than bitwise (~5e-5 rel measured on the embedding)."""
    su, ss, mu, ms = rollout_pair(
        tiny_cfg(),
        lambda: make_optimizer(
            learning_rate=1e-3, warmup_steps=2, decay_steps=10,
            grad_clip=0.0, fused=True,
        ),
        comm_cfg(),
    )
    assert params_worst_rel(su["params"], ss["params"]) < 1e-3
    assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-6


@pytest.mark.slow
@pytest.mark.parametrize(
    "name,cfg_kw,accum,batch,tol",
    [
        # tied: baseline inlines the tied-cotangent add into embed's nu
        # fusion; 1 ulp at step 1 compounds to ~2.5e-3 by step 6.
        ("tied", {}, 1, 16, 1e-2),
        # accum: per-microbatch embed scatter-add rounds differently
        # under the scan (~9e-4 measured).
        ("accum4-untied", {"tie_embeddings": False}, 4, 32, 5e-3),
        ("accum2-tied", {}, 2, 32, 1e-2),
    ],
)
def test_tolerance_pinned_adamw(name, cfg_kw, accum, batch, tol):
    su, ss, mu, ms = rollout_pair(
        tiny_cfg(**cfg_kw), lambda: optax.adamw(1e-3), comm_cfg(),
        accum=accum, batch=batch,
    )
    assert params_worst_rel(su["params"], ss["params"]) < tol
    assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-5


@pytest.mark.slow
def test_tolerance_pinned_clip_chain():
    """grad_clip>0: global_norm sums flat buckets vs per-leaf trees in
    different orders (~6e-3 worst rel measured, dloss ~5e-7)."""
    su, ss, mu, ms = rollout_pair(
        tiny_cfg(tie_embeddings=False),
        lambda: make_optimizer(
            learning_rate=1e-3, warmup_steps=2, decay_steps=10, grad_clip=1.0
        ),
        comm_cfg(),
    )
    assert params_worst_rel(su["params"], ss["params"]) < 3e-2
    assert abs(float(mu["loss"]) - float(ms["loss"])) < 1e-5


@pytest.mark.slow
@pytest.mark.parametrize(
    "wire,param_tol,loss_tol",
    [("bfloat16", 0.02, 1e-3), ("int8", 0.05, 5e-3)],
)
def test_tolerance_pinned_quantized_wire(wire, param_tol, loss_tol):
    """Lossy wires trade gradient precision for bytes; the rollout must
    stay close. Drift is pinned as per-leaf relative RMS — individual
    near-zero params wander by the quantization step size (expected),
    but the aggregate divergence from the f32 trajectory stays small
    (blockwise scales bound the per-bucket error)."""
    su, ss, mu, ms = rollout_pair(
        tiny_cfg(tie_embeddings=False),
        lambda: optax.adamw(1e-3),
        comm_cfg(wire_dtype=wire),
    )
    worst = 0.0
    for x, y in zip(
        jax.tree.leaves(su["params"]), jax.tree.leaves(ss["params"])
    ):
        x, y = np.asarray(x), np.asarray(y)
        worst = max(
            worst,
            float(
                np.sqrt(np.mean((x - y) ** 2) / (np.mean(x**2) + 1e-30))
            ),
        )
    assert worst < param_tol
    assert abs(float(mu["loss"]) - float(ms["loss"])) < loss_tol


@pytest.mark.slow
def test_block_fn_composes():
    """block_k>1 scans step_fn; the dispatch to the sharded step must
    survive the scan (state layout is the fixed point)."""
    cfg = tiny_cfg(tie_embeddings=False)
    mesh = dp_mesh()
    b = TrainStepBuilder(cfg, mesh, optax.adamw(1e-3), comm=comm_cfg())
    assert b.update_sharding
    state = init_train_state(
        jax.random.key(0), cfg, mesh, b.optimizer, comm=b.comm_resolved
    )
    bs = list(batches(2))
    block = {
        k: jnp.stack([b2[k] for b2 in bs]) for k in bs[0]
    }
    state, metrics = jax.jit(b.block_fn)(state, block)
    assert np.isfinite(np.asarray(metrics["loss"])).all()
