"""Sparse-tier elasticity: PS cluster versions + HRW key partitioning.

Reference behaviors: elastic_ps.py (ElasticPsService version bookkeeping)
and the PS-migration failover path (tensorflow_failover.py) — here the
re-partition story is rendezvous hashing with bounded key migration.
"""

import numpy as np
import pytest

from dlrover_tpu.master.elastic_ps import ElasticPsService
from dlrover_tpu.sparse.partition import (
    assign_servers,
    migration_plan,
    partition_keys,
)


def test_versions_bump_and_track_nodes():
    svc = ElasticPsService()
    assert svc.get_global_version() == 0
    assert svc.bump_global_version() == 1
    svc.set_node_version(3, 1)
    assert svc.get_node_version(3) == 1
    assert svc.get_node_version(4) == 0


def test_server_set_change_bumps_version():
    svc = ElasticPsService()
    v1 = svc.set_servers(["h0:70", "h1:70"])
    assert v1 == 1
    # same set: no bump
    assert svc.set_servers(["h0:70", "h1:70"]) == 1
    assert svc.set_servers(["h0:70", "h1:70", "h2:70"]) == 2
    assert svc.get_servers() == ["h0:70", "h1:70", "h2:70"]


def test_assignment_deterministic_and_balanced():
    servers = [f"host{i}:7000" for i in range(4)]
    keys = np.arange(40000)
    owner1 = assign_servers(keys, servers)
    owner2 = assign_servers(keys, servers)
    np.testing.assert_array_equal(owner1, owner2)
    counts = np.bincount(owner1, minlength=4)
    # HRW balance: each server within ±20% of fair share
    assert (np.abs(counts - 10000) < 2000).all(), counts


def test_partition_keys_cover_all():
    servers = ["a", "b", "c"]
    keys = np.arange(999)
    parts = partition_keys(keys, servers)
    total = np.concatenate(list(parts.values()))
    assert sorted(total.tolist()) == keys.tolist()


def test_bounded_migration_on_server_removal():
    servers = [f"h{i}" for i in range(5)]
    keys = np.arange(20000)
    owner = assign_servers(keys, servers)
    removed = "h2"
    survivors = [s for s in servers if s != removed]
    moves = migration_plan(keys, servers, survivors)
    # ONLY keys owned by the removed server move (HRW property)
    removed_keys = set(keys[owner == 2].tolist())
    assert {m[0] for m in moves} == removed_keys
    for _, src, dst in moves:
        assert src == removed and dst != removed


def test_bounded_migration_on_server_addition():
    servers = [f"h{i}" for i in range(4)]
    keys = np.arange(20000)
    grown = servers + ["h_new"]
    moves = migration_plan(keys, servers, grown)
    # every move lands on the new server; ~1/5 of keys move
    assert all(dst == "h_new" for _, _, dst in moves)
    assert 0.1 < len(moves) / len(keys) < 0.3


def test_migration_bounded_fuzz():
    """HRW invariant under ARBITRARY membership changes (remove+add in
    one step, weight changes): a key moves only if its old owner left
    or its new owner just arrived/gained weight — never between two
    untouched servers."""
    rng = np.random.RandomState(7)
    keys = np.arange(8000)
    for trial in range(10):
        n = rng.randint(3, 8)
        old = [f"s{i}" for i in range(n)]
        # remove randomly but always keep at least one survivor
        removed = set()
        for s in old[1:]:
            if rng.rand() < 0.3:
                removed.add(s)
        survivors = [s for s in old if s not in removed]
        added = [f"new{trial}_{j}" for j in range(rng.randint(0, 3))]
        new = survivors + added
        moves = migration_plan(keys, old, new)
        old_owner = dict(
            zip(keys.tolist(), np.asarray(assign_servers(keys, old)))
        )
        for key, src, dst in moves:
            assert src != dst
            # every move's source must be the true old owner
            assert old[old_owner[key]] == src
            # and the move must be explained by the membership change
            assert (src in removed) or (dst in added), (
                f"trial {trial}: {key} moved {src}->{dst} though "
                "neither endpoint changed"
            )


def test_weight_change_moves_keys_only_to_or_from_that_server():
    """Weighted HRW: raising one server's weight pulls keys TO it only;
    nothing migrates between other pairs (the Brain's hot-shard
    rebalance relies on this)."""
    servers = [f"h{i}" for i in range(5)]
    keys = np.arange(10000)
    base = assign_servers(keys, servers)
    boosted = assign_servers(
        keys, servers, weights={"h2": 3.0}
    )
    changed = base != boosted
    # every changed key now lands on h2
    assert set(np.asarray(boosted)[changed].tolist()) <= {2}


def test_empty_server_list_raises():
    with pytest.raises(ValueError):
        assign_servers([1, 2], [])


def test_ps_version_rpc_roundtrip():
    """Through the real servicer dispatch (in-process master fixture)."""
    from dlrover_tpu.common import messages as msgs
    from dlrover_tpu.master.servicer import MasterServicer

    svc = ElasticPsService()
    servicer = MasterServicer(ps_service=svc)
    svc.set_servers(["h0", "h1"])
    assert servicer.report(
        msgs.PsVersionReport(node_id=0, version_type="global")
    )
    resp = servicer.get(
        msgs.PsVersionRequest(node_id=0, version_type="global")
    )
    assert resp.version == 2  # set_servers bumped once, report again
    assert resp.servers == ["h0", "h1"]
    # node-level
    servicer.report(
        msgs.PsVersionReport(node_id=7, version_type="node", version=2)
    )
    resp2 = servicer.get(
        msgs.PsVersionRequest(node_id=7, version_type="node")
    )
    assert resp2.version == 2


def test_ps_register_on_precreated_id_refreshes_ring_name():
    """A PS landing on a pre-created worker id must enter the ring under
    its PS name: node.type AND the default-derived node.name refresh, or
    the ring would publish a stale 'worker-N' that never resolves to the
    server's registered address (sync_with_master would defer the whole
    set forever)."""
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.common.messages import NodeMeta
    from dlrover_tpu.master.elastic_ps import PsClusterCallback
    from dlrover_tpu.master.node_manager import JobManager

    jm = JobManager(num_workers=2)
    ps = ElasticPsService()
    jm.event_callbacks.append(PsClusterCallback(ps))
    node = jm.register_node(
        NodeMeta(node_id=1, node_type=NodeType.PS)
    )
    assert node.type == NodeType.PS
    assert node.name == f"{NodeType.PS}-1"
    assert ps.get_servers() == [f"{NodeType.PS}-1"]


def test_ps_cluster_callback_drives_server_set():
    """Node lifecycle -> versioned server set (reference node/ps.py
    scale plans): PS starts join the ring, failures leave it, worker
    nodes are ignored, and each membership change bumps the version."""
    from dataclasses import dataclass

    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.master.elastic_ps import PsClusterCallback

    @dataclass
    class FakeNode:
        id: int
        type: str
        name: str = ""

    ps = ElasticPsService()
    cb = PsClusterCallback(ps)

    cb.on_node_started(FakeNode(0, NodeType.PS, "ps-a"), None)
    cb.on_node_started(FakeNode(1, NodeType.PS, "ps-b"), None)
    v2 = ps.get_global_version()
    assert ps.get_servers() == ["ps-a", "ps-b"] and v2 == 2

    # non-PS nodes never touch the ring
    cb.on_node_started(FakeNode(5, NodeType.WORKER, "w-0"), None)
    cb.on_node_failed(FakeNode(5, NodeType.WORKER, "w-0"), None)
    assert ps.get_global_version() == v2

    # duplicate start is idempotent (no spurious version churn)
    cb.on_node_started(FakeNode(0, NodeType.PS, "ps-a"), None)
    assert ps.get_global_version() == v2

    cb.on_node_failed(FakeNode(0, NodeType.PS, "ps-a"), None)
    assert ps.get_servers() == ["ps-b"]
    assert ps.get_global_version() == v2 + 1
    cb.on_node_deleted(FakeNode(1, NodeType.PS, "ps-b"), None)
    assert ps.get_servers() == []
