"""GPTNeoX parallel-residual and GLM prefix-LM family tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.ops import pallas_attention
from dlrover_tpu.ops.attention import mha_reference


# ---------------------------------------------------------------------------
# parallel residual (GPTNeoX)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_parallel_residual_forward_and_grads():
    cfg = get_config("tiny-neox")
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 1000)
    logits = decoder.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))

    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    grads = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg)[0])(params)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert any(n > 0 for n in norms)


def test_parallel_residual_differs_from_sequential():
    cfg_par = get_config("tiny-neox")
    cfg_seq = get_config("tiny-neox", parallel_residual=False)
    params = decoder.init(jax.random.key(0), cfg_par)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 1000)
    out_par = decoder.forward(params, tokens, cfg_par)
    out_seq = decoder.forward(params, tokens, cfg_seq)
    assert not np.allclose(np.asarray(out_par), np.asarray(out_seq))


# ---------------------------------------------------------------------------
# prefix-LM masking (GLM)
# ---------------------------------------------------------------------------


def _manual_prefix_attention(q, k, v, prefix):
    """O(S^2) dense reference computed straight from the mask rule."""
    b, s, h, d = q.shape
    logits = np.einsum(
        "bqhd,bkhd->bhqk", np.asarray(q, np.float64), np.asarray(k, np.float64)
    ) / np.sqrt(d)
    out = np.zeros((b, s, h, d))
    for bi in range(b):
        mask = np.tril(np.ones((s, s), bool))
        mask[:, : int(prefix[bi])] = True
        lg = np.where(mask[None], logits[bi], -np.inf)
        p = np.exp(lg - lg.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[bi] = np.einsum("hqk,khd->qhd", p, np.asarray(v[bi], np.float64))
    return out


def test_mha_reference_prefix_mask():
    ks = jax.random.split(jax.random.key(0), 3)
    b, s, h, d = 2, 24, 2, 8
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    prefix = jnp.array([7, 13], jnp.int32)
    out = mha_reference(q, k, v, causal=True, prefix_len=prefix)
    ref = _manual_prefix_attention(q, k, v, np.asarray(prefix))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_mha_reference_prefix_zero_equals_causal():
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (2, 16, 2, 8))
    k = jax.random.normal(ks[1], (2, 16, 2, 8))
    v = jax.random.normal(ks[2], (2, 16, 2, 8))
    out = mha_reference(
        q, k, v, causal=True, prefix_len=jnp.zeros((2,), jnp.int32)
    )
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_mha_reference_prefix_requires_causal():
    q = jnp.zeros((1, 8, 1, 4))
    with pytest.raises(ValueError, match="causal"):
        mha_reference(
            q, q, q, causal=False, prefix_len=jnp.ones((1,), jnp.int32)
        )


def test_flash_kernel_prefix_matches_reference(monkeypatch):
    """Pallas kernel (interpret mode) with prefix == masked reference,
    forward AND backward through the custom_vjp."""
    monkeypatch.setattr(pallas_attention, "INTERPRET", True)
    ks = jax.random.split(jax.random.key(2), 3)
    b, s, h, d = 2, 256, 2, 16
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.float32)
    prefix = jnp.array([37, 190], jnp.int32)

    def flash(q, k, v):
        out = pallas_attention._flash_attention(
            q, k, v, prefix, None, True, d**-0.5, 128, 128
        )
        return out

    out = flash(q, k, v)
    ref = mha_reference(q, k, v, causal=True, prefix_len=prefix)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    g = jax.random.normal(jax.random.key(3), out.shape)
    f_flash = lambda q, k, v: jnp.vdot(flash(q, k, v), g)  # noqa: E731
    f_ref = lambda q, k, v: jnp.vdot(  # noqa: E731
        mha_reference(q, k, v, causal=True, prefix_len=prefix), g
    )
    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4
        )


def test_glm_forward_uses_prefix():
    cfg = get_config("tiny-glm")
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 1000)
    prefix = jnp.array([4, 9], jnp.int32)
    out_p = decoder.forward(params, tokens, cfg, prefix_len=prefix)
    # explicit zeros = fully causal
    out_c = decoder.forward(
        params, tokens, cfg, prefix_len=jnp.zeros((2,), jnp.int32)
    )
    # prefix changes attention → logits differ inside the prefix region
    assert not np.allclose(np.asarray(out_p), np.asarray(out_c))
    assert bool(jnp.all(jnp.isfinite(out_p)))
    # omitting prefix_len on a prefix-LM config is a loud error
    with pytest.raises(ValueError, match="prefix_lm"):
        decoder.forward(params, tokens, cfg)


@pytest.mark.slow  # tier-1 budget: grad compile (~13s); the glm
# prefix mask itself is pinned fast by test_glm_forward_uses_prefix
def test_glm_loss_and_grads_with_prefix_batch():
    cfg = get_config("tiny-glm")
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 1000)
    prefix = jnp.array([4, 9], jnp.int32)
    # GLM-style loss: only the causal tail is scored
    mask = (
        jnp.arange(16)[None, :] >= prefix[:, None]
    ).astype(jnp.float32)
    batch = {
        "tokens": tokens,
        "targets": jnp.roll(tokens, -1, 1),
        "mask": mask,
        "prefix_len": prefix,
    }
    loss, metrics = decoder.loss_fn(params, batch, cfg)
    assert np.isfinite(float(loss))
    assert float(metrics["tokens"]) == float(mask.sum())
    grads = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg)[0])(params)
    assert all(
        np.isfinite(float(jnp.linalg.norm(g)))
        for g in jax.tree.leaves(grads)
    )


@pytest.mark.slow
def test_glm_forward_on_sequence_parallel_mesh():
    """GLM + ring/ulysses: prefix-LM logits on an sp mesh match the
    single-device reference path."""
    from dlrover_tpu.parallel import MeshConfig, build_mesh
    from dlrover_tpu.parallel import sharding as shd

    cfg = get_config("tiny-glm", max_seq=64, dtype="float32")
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 64), 0, 1000)
    prefix = jnp.array([10, 40, 0, 63], jnp.int32)
    ref = decoder.forward(
        params, tokens, cfg, prefix_len=prefix, attn_impl="reference"
    )
    mesh = build_mesh(MeshConfig(sp=4, dp=2))
    shardings = shd.shardings_for_tree(mesh, decoder.logical_axes(cfg))
    params_s = jax.device_put(params, shardings)
    for impl in ("ring", "ulysses"):
        out = jax.jit(
            lambda p, t, pf: decoder.forward(
                p, t, cfg, mesh=mesh, prefix_len=pf, attn_impl=impl
            )
        )(params_s, tokens, prefix)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=3e-3, atol=3e-3,
            err_msg=impl,
        )


def test_glm_decode_step_rejected():
    cfg = get_config("tiny-glm")
    params = decoder.init(jax.random.key(0), cfg)
    cache = decoder.init_kv_cache(cfg, 1, 8)
    with pytest.raises(ValueError, match="prefix-LM"):
        decoder.decode_step(
            params, jnp.zeros((1,), jnp.int32), cache,
            jnp.asarray(0), cfg,
        )


def test_neox_cached_decode_matches_forward():
    """decode_step must implement the parallel residual: greedy cached
    sampling == greedy full-prefix sampling on a NeoX config."""
    from dlrover_tpu.models.generate import sample

    # f32: greedy equality must not hinge on bf16 tie-breaking luck
    cfg = get_config("tiny-neox", n_layer=2, d_model=128, dtype="float32")
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (2, 5), 1, 1000)
    out_cached = sample(
        params, cfg, prompts, 6, rng=jax.random.key(2),
        temperature=0.0, use_cache=True,
    )
    out_full = sample(
        params, cfg, prompts, 6, rng=jax.random.key(2),
        temperature=0.0, use_cache=False,
    )
    np.testing.assert_array_equal(
        np.asarray(out_cached), np.asarray(out_full)
    )


# ---------------------------------------------------------------------------
# sliding-window attention (Mistral)
# ---------------------------------------------------------------------------


def _manual_window_attention(q, k, v, window):
    b, s, h, d = q.shape
    logits = np.einsum(
        "bqhd,bkhd->bhqk",
        np.asarray(q, np.float64),
        np.asarray(k, np.float64),
    ) / np.sqrt(d)
    qp = np.arange(s)[:, None]
    kp = np.arange(s)[None, :]
    mask = (qp >= kp) & (qp - kp < window)
    lg = np.where(mask[None, None], logits, -np.inf)
    p = np.exp(lg - lg.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float64))


def test_mha_reference_window_mask():
    ks = jax.random.split(jax.random.key(8), 3)
    b, s, h, d = 2, 32, 2, 8
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    out = mha_reference(q, k, v, causal=True, window=7)
    ref = _manual_window_attention(q, k, v, 7)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)
    # a window >= seq is plain causal
    out_full = mha_reference(q, k, v, causal=True, window=64)
    ref_causal = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_full), np.asarray(ref_causal), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow  # tier-1 budget: kernel-path compile (~9s); the
# window mask keeps fast coverage via test_window_decode_matches_forward
def test_flash_kernel_window_matches_reference(monkeypatch):
    """Pallas kernels (interpret) with a window crossing block
    boundaries: forward and backward against the masked reference."""
    monkeypatch.setattr(pallas_attention, "INTERPRET", True)
    ks = jax.random.split(jax.random.key(9), 3)
    b, s, h, d = 2, 512, 2, 16
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.float32)
    window = 200  # not a block multiple: exercises partial masks

    def flash(q, k, v):
        return pallas_attention._flash_attention(
            q, k, v, None, None, True, d**-0.5, 128, 128, window
        )

    out = flash(q, k, v)
    ref = mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    g = jax.random.normal(jax.random.key(10), out.shape)
    gf = jax.grad(
        lambda q, k, v: jnp.vdot(flash(q, k, v), g), argnums=(0, 1, 2)
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.vdot(
            mha_reference(q, k, v, causal=True, window=window), g
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4
        )


def test_window_config_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        get_config("tiny", attn_window=8, prefix_lm=True)
    with pytest.raises(ValueError, match="causal"):
        get_config("tiny", attn_window=8, causal=False)
    with pytest.raises(ValueError, match=">= 0"):
        get_config("tiny", attn_window=-8)
    cfg = get_config("mistral-7b")
    assert cfg.attn_window == 4096 and cfg.kv_heads == 8
    # windowed attention FLOPs cap at the window span
    assert cfg.flops_per_token(8192) < cfg.flops_per_token(8192 * 2) or (
        cfg.flops_per_token(8192) == cfg.flops_per_token(8192 * 2)
    )
    full = get_config("mistral-7b", attn_window=0)
    assert cfg.flops_per_token(8192) < full.flops_per_token(8192)


def test_window_decode_matches_forward():
    """Cached decode must apply the same sliding window as forward."""
    from dlrover_tpu.models.generate import sample

    # f32 activations: the two paths reduce in different orders, and
    # bf16 noise would cascade through greedy near-ties
    cfg = get_config(
        "tiny", n_layer=2, d_model=128, attn_window=6, max_seq=32,
        dtype="float32",
    )
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (2, 10), 1, 1000)
    out_cached = sample(
        params, cfg, prompts, 6, rng=jax.random.key(2),
        temperature=0.0, use_cache=True,
    )
    out_full = sample(
        params, cfg, prompts, 6, rng=jax.random.key(2),
        temperature=0.0, use_cache=False,
    )
    np.testing.assert_array_equal(
        np.asarray(out_cached), np.asarray(out_full)
    )


@pytest.mark.slow
def test_window_forward_on_sequence_parallel_mesh():
    """Decoder-level window wiring through BOTH sp paths: logits on an
    sp mesh match the single-device reference path (the window crosses
    ring-block boundaries: 64/4 = 16-wide blocks, window 10)."""
    from dlrover_tpu.parallel import MeshConfig, build_mesh
    from dlrover_tpu.parallel import sharding as shd

    cfg = get_config(
        "tiny", max_seq=64, attn_window=10, dtype="float32"
    )
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 64), 0, 1000)
    ref = decoder.forward(params, tokens, cfg, attn_impl="reference")
    mesh = build_mesh(MeshConfig(sp=4, dp=2))
    params_s = jax.device_put(
        params, shd.shardings_for_tree(mesh, decoder.logical_axes(cfg))
    )
    for impl in ("ulysses", "ring"):
        out = jax.jit(
            lambda p, t: decoder.forward(
                p, t, cfg, mesh=mesh, attn_impl=impl
            )
        )(params_s, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=3e-3, atol=3e-3,
            err_msg=impl,
        )


def test_mixtral_style_config():
    """MoE flagship preset: GQA + top-2 routing wired through forward."""
    big = get_config("mixtral-8x7b")
    assert big.n_experts == 8 and big.expert_top_k == 2
    assert big.kv_heads == 8 and big.n_head == 32
    cfg = get_config(
        "mixtral-8x7b", n_layer=2, n_head=4, n_kv_head=2, d_model=128,
        d_ff=256, vocab_size=512, max_seq=64, n_experts=4,
    )
    params = decoder.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 512)
    logits, aux = decoder.forward(params, tokens, cfg, return_aux=True)
    assert logits.shape == (2, 16, 512)
    assert float(aux["moe_lb_loss"]) > 0.0  # router aux losses collected


@pytest.mark.slow
def test_glm_sample_runs_uncached():
    from dlrover_tpu.models.generate import greedy

    cfg = get_config("tiny-glm", n_layer=1, d_model=128)
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (2, 6), 1, 1000)
    out = greedy(params, cfg, prompts, max_new_tokens=4)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(
        np.asarray(out[:, :6]), np.asarray(prompts)
    )
