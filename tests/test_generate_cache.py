"""External KV-cache plumbing in models/generate.py (serving satellite).

``generate.init_kv_cache`` is the ONE allocation site the sampler and
the serving tier share; a rollout decoding into an externally allocated
buffer must be bitwise identical to the inline allocation, and the
helper itself must stay pinned to ``decoder.init_kv_cache``.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models import decoder, generate  # noqa: E402
from dlrover_tpu.models.config import get_config  # noqa: E402


def _cfg(**kw):
    base = dict(
        n_layer=2, d_model=32, d_ff=64, n_head=4, vocab_size=32, max_seq=64
    )
    base.update(kw)
    return get_config("tiny", **base)


def test_init_kv_cache_pinned_to_decoder():
    cfg = _cfg()
    a = generate.init_kv_cache(cfg, 2, 10)
    b = decoder.init_kv_cache(cfg, 2, 10)
    assert set(a) == set(b) == {"k", "v"}
    for key in ("k", "v"):
        assert a[key].shape == b[key].shape
        assert a[key].dtype == b[key].dtype
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))
    # explicit dtype flows through (the serving bf16 reference mode)
    c = generate.init_kv_cache(cfg, 2, 10, dtype=jnp.float32)
    assert c["k"].dtype == jnp.float32


@pytest.mark.slow
def test_external_cache_rollout_bitwise_identical():
    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jnp.asarray(
        np.random.default_rng(0).integers(1, 32, size=(2, 4)), jnp.int32
    )
    inline = generate.greedy(params, cfg, prompts, max_new_tokens=6)
    buf = generate.init_kv_cache(cfg, 2, 10)
    external = generate.sample(
        params, cfg, prompts, 6, rng=jax.random.key(0),
        temperature=0.0, kv_cache=buf,
    )
    np.testing.assert_array_equal(np.asarray(inline), np.asarray(external))


def test_external_cache_shape_mismatch_raises():
    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jnp.ones((2, 4), jnp.int32)
    wrong = generate.init_kv_cache(cfg, 2, 9)  # needs p+max_new = 10
    with pytest.raises(ValueError, match="init_kv_cache"):
        generate.sample(
            params, cfg, prompts, 6, rng=jax.random.key(0),
            temperature=0.0, kv_cache=wrong,
        )


def test_external_cache_rejected_on_cacheless_path():
    cfg = _cfg(n_experts=2)  # MoE always takes the full-prefix path
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jnp.ones((2, 4), jnp.int32)
    buf = generate.init_kv_cache(cfg, 2, 10)
    with pytest.raises(ValueError, match="cacheless"):
        generate.sample(
            params, cfg, prompts, 6, rng=jax.random.key(0),
            temperature=0.0, kv_cache=buf,
        )
