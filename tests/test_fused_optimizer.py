"""fused_adamw vs the optax.chain(clip_by_global_norm, adamw)
reference it replaces.

Parity is asserted BITWISE after multi-step rollouts: the fused update
applies the chain's per-leaf arithmetic verbatim (including optax's
jitted bias-correction region, whose scalar divide XLA rewrites to a
reciprocal multiply — reproducing the formula eagerly lands 1 ulp
off). Both clip regimes run: grads scaled so the global norm
alternates above/below the threshold, exercising both sides of the
clip trigger select.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.train import optimizer as O

_B1, _B2, _WD = 0.9, 0.95, 0.1


def _params():
    return {
        "w": jax.random.normal(jax.random.key(0), (64, 32)),
        "emb": jax.random.normal(jax.random.key(1), (130, 128)),
        "b": jax.random.normal(jax.random.key(2), (32,)),
    }


def _grad(params, i, big):
    # alternate large/small global norm so the clip trigger flips
    scale = 40.0 if big else 0.001
    return jax.tree.map(
        lambda p: jax.random.normal(jax.random.key(100 + i), p.shape)
        * scale,
        params,
    )


def _rollout(opt, params, steps=6, clip_active_on_odd=True):
    state = opt.init(params)
    p = params
    for i in range(steps):
        g = _grad(params, i, big=bool(i % 2) if clip_active_on_odd else True)
        u, state = opt.update(g, state, p)
        p = optax.apply_updates(p, u)
    return p, state


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("sd", [None, "bfloat16"])
@pytest.mark.parametrize("clip", [1.0, 1e-4, 0.0])
def test_fused_matches_chain_bitwise(sd, clip):
    """clip=1.0 alternates active/inactive; 1e-4 is always-active;
    0.0 disables clipping entirely."""
    params = _params()
    sched = O.warmup_cosine(3e-4, warmup_steps=3, decay_steps=50)
    mu_dtype = jnp.bfloat16 if sd == "bfloat16" else None
    links = [optax.clip_by_global_norm(clip)] if clip else []
    links.append(
        optax.adamw(sched, b1=_B1, b2=_B2, weight_decay=_WD,
                    mu_dtype=mu_dtype)
    )
    ref = optax.chain(*links)
    fus = O.fused_adamw(
        sched, b1=_B1, b2=_B2, weight_decay=_WD, grad_clip=clip,
        state_dtype=sd,
    )
    pr, _ = _rollout(ref, params)
    pf, sf = _rollout(fus, params)
    _assert_trees_equal(pr, pf)
    assert int(sf["step"]) == 6


def test_fused_constant_lr_no_decay():
    params = _params()
    ref = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(1e-3, b1=_B1, b2=_B2, weight_decay=0.0),
    )
    fus = O.fused_adamw(1e-3, b1=_B1, b2=_B2, grad_clip=1.0)
    pr, _ = _rollout(ref, params)
    pf, _ = _rollout(fus, params)
    _assert_trees_equal(pr, pf)


def test_fused_factored_matches_chained_factored():
    """state_dtype='factored' delegates to factored_adamw with the
    clip folded into its single traversal — must equal the chained
    clip + factored_adamw composition bitwise."""
    params = _params()  # "emb" is 130x128 -> actually factored
    sched = O.warmup_cosine(3e-4, warmup_steps=3, decay_steps=50)
    ref = optax.chain(
        optax.clip_by_global_norm(1.0),
        O.factored_adamw(sched, b1=_B1, b2=_B2, weight_decay=_WD),
    )
    fus = O.fused_adamw(
        sched, b1=_B1, b2=_B2, weight_decay=_WD, grad_clip=1.0,
        state_dtype="factored",
    )
    pr, _ = _rollout(ref, params)
    pf, sf = _rollout(fus, params)
    _assert_trees_equal(pr, pf)
    # the factored state actually factored the matrix leaf
    assert isinstance(sf["v"]["emb"], dict) and "r" in sf["v"]["emb"]


def test_streamed_offload_grad_clip_fold():
    """streamed_offload_adamw(grad_clip=...) equals the chained
    clip + streamed composition (the fused offload_states recipe)."""
    params = _params()
    ref = optax.chain(
        optax.clip_by_global_norm(1.0),
        O.streamed_offload_adamw(1e-3, b1=_B1, b2=_B2, weight_decay=_WD),
    )
    fus = O.streamed_offload_adamw(
        1e-3, b1=_B1, b2=_B2, weight_decay=_WD, grad_clip=1.0
    )
    pr, _ = _rollout(ref, params)
    pf, _ = _rollout(fus, params)
    _assert_trees_equal(pr, pf)


def test_make_optimizer_fused_variants():
    params = _params()
    g = jax.tree.map(jnp.ones_like, params)
    for kw in (
        dict(fused=True),
        dict(fused=True, state_dtype="bfloat16"),
        dict(fused=True, state_dtype="factored"),
        dict(fused=True, offload_states=True),
    ):
        opt = O.make_optimizer(**kw)
        s = opt.init(params)
        u, s = opt.update(g, s, params)
        for leaf in jax.tree.leaves(u):
            assert np.isfinite(np.asarray(leaf)).all(), kw


def test_make_optimizer_fused_matches_default_recipe():
    """The headline recipe: make_optimizer(fused=True) must train
    bit-identically to make_optimizer() (same defaults, chained)."""
    params = _params()
    ref = O.make_optimizer()
    fus = O.make_optimizer(fused=True)
    # default state_dtype=None -> both keep f32 moments
    pr, _ = _rollout(ref, params)
    pf, _ = _rollout(fus, params)
    _assert_trees_equal(pr, pf)


def test_make_optimizer_fused_rejects_unsupported():
    with pytest.raises(ValueError, match="adamw fast path"):
        O.make_optimizer(name="lion", fused=True)
    with pytest.raises(ValueError, match="composes with state_dtype"):
        O.make_optimizer(fused=True, state_dtype="int8")
    with pytest.raises(ValueError, match="state_dtype"):
        O.fused_adamw(1e-3, state_dtype="mixed8")
