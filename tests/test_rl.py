"""RL (PPO) tier tests.

Reference behaviors: atorch/rl model_engine (4-role engine), replay
buffer, PPO losses/GAE (trlX lineage), actor generation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, generate, get_config
from dlrover_tpu.rl import ModelEngine, PPOConfig, ReplayBuffer, RLTrainer
from dlrover_tpu.rl import ppo


def _cfg(**kw):
    base = dict(
        n_layer=2,
        d_model=32,
        d_ff=64,
        n_head=4,
        vocab_size=32,
        max_seq=32,
    )
    base.update(kw)
    return get_config("tiny", **base)


def test_gae_matches_closed_form():
    # single step episode: advantage = reward − value
    rewards = jnp.array([[1.0, 0.0]])
    values = jnp.array([[0.3, 0.0]])
    mask = jnp.array([[1.0, 0.0]])
    adv, ret = ppo.gae_advantages(rewards, values, mask, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(float(adv[0, 0]), 0.7, rtol=1e-6)
    np.testing.assert_allclose(float(ret[0, 0]), 1.0, rtol=1e-6)


def test_gae_two_step_discounting():
    rewards = jnp.array([[0.0, 1.0]])
    values = jnp.array([[0.5, 0.25]])
    mask = jnp.ones((1, 2))
    gamma, lam = 0.9, 0.8
    adv, _ = ppo.gae_advantages(rewards, values, mask, gamma, lam)
    d1 = 1.0 - 0.25                      # delta_t1 (terminal)
    d0 = 0.0 + gamma * 0.25 - 0.5        # delta_t0
    np.testing.assert_allclose(float(adv[0, 1]), d1, rtol=1e-5)
    np.testing.assert_allclose(
        float(adv[0, 0]), d0 + gamma * lam * d1, rtol=1e-5
    )


def test_policy_loss_clipping():
    old_lp = jnp.zeros((1, 1))
    adv = jnp.ones((1, 1))
    mask = jnp.ones((1, 1))

    def loss_at(new_lp):
        l, _ = ppo.ppo_policy_loss(
            jnp.full((1, 1), new_lp), old_lp, adv, mask, clip_ratio=0.2
        )
        return float(l)

    # within clip: loss = −ratio; beyond clip: saturates at −1.2
    assert abs(loss_at(0.0) + 1.0) < 1e-6
    assert abs(loss_at(np.log(1.1)) + 1.1) < 1e-6
    assert abs(loss_at(np.log(2.0)) + 1.2) < 1e-6


def test_value_loss_clips_large_moves():
    old_v = jnp.zeros((1, 1))
    returns = jnp.ones((1, 1))
    mask = jnp.ones((1, 1))
    # new value jumped +10 beyond the clip window of 0.2: the clipped
    # branch (0.2 − 1)² dominates max(l1, l2)... l1=(10−1)²=81 > l2
    l = ppo.ppo_value_loss(
        jnp.full((1, 1), 10.0), old_v, returns, mask, value_clip=0.2
    )
    np.testing.assert_allclose(float(l), 0.5 * 81.0, rtol=1e-6)


def test_shaped_rewards_places_score_on_last_token():
    score = jnp.array([2.0])
    lp = jnp.zeros((1, 4))
    ref_lp = jnp.zeros((1, 4))
    mask = jnp.array([[1.0, 1.0, 1.0, 0.0]])
    r = ppo.shaped_rewards(score, lp, ref_lp, mask, kl_coef=0.1)
    np.testing.assert_allclose(np.asarray(r[0]), [0.0, 0.0, 2.0, 0.0])


def test_shaped_rewards_suffix_mask():
    """Response (suffix) masks — the shape RLTrainer actually passes —
    must land the score on the LAST response token."""
    score = jnp.array([5.0])
    lp = jnp.zeros((1, 5))
    ref_lp = jnp.zeros((1, 5))
    mask = jnp.array([[0.0, 0.0, 0.0, 1.0, 1.0]])  # prompt 4, response 2
    r = ppo.shaped_rewards(score, lp, ref_lp, mask, kl_coef=0.0)
    np.testing.assert_allclose(np.asarray(r[0]), [0, 0, 0, 0, 5.0])


def test_shaped_rewards_kl_penalty():
    score = jnp.zeros((1,))
    lp = jnp.full((1, 2), -1.0)
    ref_lp = jnp.full((1, 2), -2.0)  # actor more confident than ref
    mask = jnp.ones((1, 2))
    r = ppo.shaped_rewards(score, lp, ref_lp, mask, kl_coef=0.5)
    np.testing.assert_allclose(np.asarray(r[0]), [-0.5, -0.5])


def test_replay_buffer_batches_cover_all():
    buf = ReplayBuffer()
    buf.add({"x": np.arange(6).reshape(6, 1)})
    assert len(buf) == 6
    seen = []
    for b in buf.batches(2, np.random.default_rng(0)):
        assert b["x"].shape == (2, 1)
        seen.extend(b["x"][:, 0].tolist())
    assert sorted(seen) == list(range(6))


def test_generate_shapes_and_greedy_determinism():
    cfg = _cfg()
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jnp.ones((2, 4), jnp.int32)
    out1 = generate.greedy(params, cfg, prompts, max_new_tokens=6)
    out2 = generate.greedy(params, cfg, prompts, max_new_tokens=6)
    assert out1.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :4]), np.asarray(prompts))


@pytest.mark.slow
def test_model_engine_roles_and_update():
    cfg = _cfg()
    eng = ModelEngine(cfg, learning_rate=1e-2)
    toks = jnp.ones((2, 8), jnp.int32)
    assert eng.actor_logits(eng.params["actor"], toks).shape == (
        2, 8, cfg.vocab_size,
    )
    assert eng.critic_values(eng.params["critic"], toks).shape == (2, 8)
    assert eng.score(toks).shape == (2,)
    # hybrid-engine storage sharing (the role ds_hybrid_engine plays in
    # the reference): ref IS the actor's initial arrays — same buffers,
    # zero extra HBM — and functional updates leave it frozen
    for a_leaf, r_leaf in zip(
        jax.tree.leaves(eng.params["actor"]),
        jax.tree.leaves(eng.params["ref"]),
    ):
        assert a_leaf is r_leaf
    # independent host-side snapshot: proves the ref stays frozen even
    # if a future apply_gradients mutated buffers in place (a same-
    # object comparison could not detect that)
    init_vals = np.copy(np.asarray(jax.tree.leaves(eng.params["ref"])[0]))
    grads = jax.tree.map(jnp.ones_like, eng.params["actor"])
    eng.apply_gradients("actor", grads)
    after = jax.tree.leaves(eng.params["actor"])[0]
    assert not np.allclose(init_vals, np.asarray(after))
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(eng.params["ref"])[0]), init_vals
    )
    # state dict roundtrip
    sd = eng.state_dict()
    eng2 = ModelEngine(cfg)
    eng2.load_state_dict(sd)
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(eng2.params["actor"])[0]),
        np.asarray(after),
    )


@pytest.mark.slow
def test_ppo_increases_rewarded_token_probability():
    """Toy RLHF: reward = fraction of response tokens equal to TARGET.
    After a few PPO rounds the actor's probability of TARGET must rise."""
    TARGET = 7
    cfg = _cfg(vocab_size=16, n_layer=1, d_model=32)
    eng = ModelEngine(cfg, learning_rate=2e-2, rng=jax.random.key(1))

    def reward_fn(tokens, mask):
        resp = tokens[:, 1:] == TARGET
        return (resp * mask).sum(-1) / np.maximum(mask.sum(-1), 1.0)

    ppo_cfg = PPOConfig(
        max_new_tokens=8,
        kl_coef=0.0,
        ppo_epochs=2,
        temperature=1.0,
        clip_ratio=0.2,
    )
    trainer = RLTrainer(eng, ppo_cfg, reward_fn=reward_fn)
    prompts = jnp.ones((32, 2), jnp.int32)

    def target_prob(params):
        logits = eng.actor_logits(params, prompts)
        return float(jax.nn.softmax(logits[:, -1, :], -1)[:, TARGET].mean())

    p0 = target_prob(eng.params["actor"])
    scores = []
    for i in range(12):
        stats = trainer.step(prompts, jax.random.key(100 + i))
        scores.append(stats["score_mean"])
    p1 = target_prob(eng.params["actor"])
    assert p1 > p0 * 1.5, (p0, p1, scores)
    # rollout scores trend upward
    assert np.mean(scores[-3:]) > np.mean(scores[:3]), scores


@pytest.mark.slow
def test_cached_generation_matches_uncached_greedy():
    """decode_step + KV cache must reproduce full-prefix greedy decoding
    token for token."""
    # float32: exact token equality between the two attention paths is
    # only guaranteed without bf16 near-tie argmax flips
    cfg = _cfg(n_layer=2, n_head=4, dtype="float32", param_dtype="float32")
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (3, 5), 0, 32)
    cached = generate.sample(
        params, cfg, prompts, 10, rng=jax.random.key(2),
        temperature=0.0, use_cache=True,
    )
    uncached = generate.sample(
        params, cfg, prompts, 10, rng=jax.random.key(2),
        temperature=0.0, use_cache=False,
    )
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(uncached))


@pytest.mark.slow
def test_prefix_lm_cached_matches_full():
    """Prefill builds the prefix-LM cache (bidirectional prompt K/V),
    so cached greedy decode must match the full-recompute path token
    for token — the capability decode_step alone cannot provide."""
    import dataclasses

    cfg = dataclasses.replace(
        _cfg(n_layer=2, n_head=4, dtype="float32", param_dtype="float32"),
        prefix_lm=True,
    )
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (3, 6), 0, 32)
    cached = generate.sample(
        params, cfg, prompts, 8, rng=jax.random.key(2),
        temperature=0.0, use_cache=True,
    )
    uncached = generate.sample(
        params, cfg, prompts, 8, rng=jax.random.key(2),
        temperature=0.0, use_cache=False,
    )
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(uncached))


@pytest.mark.slow
def test_prompt_lens_bound_the_bidirectional_prefix():
    """Ragged prefix-LM batches: per-sequence prompt_lens keep pad
    tokens out of the bidirectional prefix (ADVICE round-1 finding) and
    the cached path agrees with the uncached one under them."""
    import dataclasses

    cfg = dataclasses.replace(
        _cfg(n_layer=2, n_head=4, dtype="float32", param_dtype="float32"),
        prefix_lm=True,
    )
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (2, 6), 1, 32)
    prompts = prompts.at[0, 3:].set(0)  # seq 0: true length 3, pads after
    lens = jnp.array([3, 6], jnp.int32)

    # the mask change is real: bounding the prefix at the true length
    # changes seq 0's logits (pads no longer bidirectionally visible)
    lg_bounded = decoder.forward(
        params, prompts, cfg, prefix_len=lens
    )
    lg_padded = decoder.forward(
        params, prompts, cfg, prefix_len=jnp.array([6, 6], jnp.int32)
    )
    assert (
        float(jnp.max(jnp.abs(lg_bounded[0] - lg_padded[0]))) > 1e-6
    )
    # seq 1's true length IS the padded width: logits identical
    np.testing.assert_allclose(
        np.asarray(lg_bounded[1]), np.asarray(lg_padded[1]), atol=1e-6
    )

    with_lens = generate.sample(
        params, cfg, prompts, 6, rng=jax.random.key(2),
        temperature=0.0, use_cache=False, prompt_lens=lens,
    )
    cached = generate.sample(
        params, cfg, prompts, 6, rng=jax.random.key(2),
        temperature=0.0, use_cache=True, prompt_lens=lens,
    )
    np.testing.assert_array_equal(
        np.asarray(cached), np.asarray(with_lens)
    )


@pytest.mark.slow
def test_cached_rollout_speedup():
    """Prefill+decode must beat full-prefix recompute on rollout
    throughput (VERDICT round-1 item: batched RL rollouts ride the
    cache). Conservative 1.5x bound for CI noise; prints the ratio."""
    import time

    cfg = _cfg(n_layer=2, n_head=4, max_seq=128)
    params = decoder.init(jax.random.key(0), cfg)
    prompts = jax.random.randint(jax.random.key(1), (4, 32), 0, 32)
    new = 64

    def run(use_cache):
        f = jax.jit(
            lambda p, t: generate.sample(
                p, cfg, t, new, rng=jax.random.key(2),
                temperature=1.0, use_cache=use_cache,
            )
        )
        out = f(params, prompts)
        out.block_until_ready()
        t0 = time.perf_counter()
        out = f(params, prompts)
        out.block_until_ready()
        dt = time.perf_counter() - t0
        return 4 * new / dt

    tps_cached = run(True)
    tps_full = run(False)
    print(
        f"\nrollout tokens/s cached={tps_cached:.0f} "
        f"full={tps_full:.0f} ({tps_cached / tps_full:.1f}x)"
    )
    assert tps_cached > 1.5 * tps_full


def test_cached_generation_gqa_and_learned_pos():
    cfg = _cfg(n_layer=2, n_head=4, dtype="float32", param_dtype="float32")
    import dataclasses

    cfg = dataclasses.replace(
        cfg, n_kv_head=2, pos="learned", tie_embeddings=False
    )
    params = decoder.init(jax.random.key(3), cfg)
    prompts = jnp.ones((2, 4), jnp.int32)
    cached = generate.sample(
        params, cfg, prompts, 8, rng=jax.random.key(4),
        temperature=0.0, use_cache=True,
    )
    uncached = generate.sample(
        params, cfg, prompts, 8, rng=jax.random.key(4),
        temperature=0.0, use_cache=False,
    )
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(uncached))


@pytest.mark.slow
def test_decode_step_logits_match_forward():
    cfg = _cfg(n_layer=1)
    params = decoder.init(jax.random.key(5), cfg)
    toks = jax.random.randint(jax.random.key(6), (2, 6), 0, 32)
    full = decoder.forward(params, toks, cfg)
    cache = decoder.init_kv_cache(cfg, 2, 6)
    logits = None
    for i in range(6):
        logits, cache = decoder.decode_step(
            params, toks[:, i], cache, jnp.asarray(i, jnp.int32), cfg
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1]), rtol=2e-2, atol=2e-2
    )


def test_model_engine_weight_sharing_accounting():
    """4 roles, <=2 full weight sets at init (hybrid-engine economy:
    reference ds_hybrid_engine/hybrid_engine.py shares actor storage
    between train and rollout; here ref aliases actor AND — in the
    production setup where a TRAINED reward model is supplied — the
    critic backbone warm-starts from it by alias, TRL-style)."""
    cfg = _cfg()
    from dlrover_tpu.models import decoder as _dec
    from dlrover_tpu.rl.model_engine import init_value_head

    trained_rm = {
        "backbone": _dec.init(jax.random.key(9), cfg),
        "v_head": init_value_head(jax.random.key(10), cfg),
    }
    eng = ModelEngine(cfg, learning_rate=1e-2, reward_params=trained_rm)
    # critic backbone IS the supplied reward backbone at init
    for c_leaf, r_leaf in zip(
        jax.tree.leaves(eng.params["critic"]["backbone"]),
        jax.tree.leaves(eng.params["reward"]["backbone"]),
    ):
        assert c_leaf is r_leaf
    # accounting: distinct bytes across ALL FOUR roles ~= 2 actors
    # (+ two tiny value heads), never 4
    assert eng.weight_sets() < 2.2
    # after an actor update the ref diverges -> one extra weight set,
    # but the critic/reward pair still shares
    grads = jax.tree.map(jnp.ones_like, eng.params["actor"])
    eng.apply_gradients("actor", grads)
    assert eng.weight_sets() < 3.2
    # auto: a fresh-RANDOM reward backbone is NOT aliased into the
    # critic (coupling two random inits measurably hurts toy PPO)
    eng2 = ModelEngine(cfg)
    assert eng2.weight_sets() > 2.8  # actor(+ref alias), critic, reward


@pytest.mark.slow
def test_rollout_reads_training_actor_buffers(tmp_path):
    """The rollout path must consume the SAME actor arrays the train
    step updates — no inference copy (the storage sharing the
    reference's hybrid engine exists to provide)."""
    from dlrover_tpu.models import generate

    cfg = _cfg()
    eng = ModelEngine(cfg, learning_rate=1e-2)
    seen = []
    orig = generate.sample

    def spy(params, *a, **k):
        seen.append(params)
        return orig(params, *a, **k)

    import dlrover_tpu.rl.trainer as rl_trainer_mod

    trainer = rl_trainer_mod.RLTrainer(
        eng,
        rl_trainer_mod.PPOConfig(max_new_tokens=4, ppo_epochs=1),
        reward_fn=lambda tokens, mask: jnp.zeros((tokens.shape[0],)),
    )
    prompts = jnp.ones((2, 4), jnp.int32)
    try:
        rl_trainer_mod.generate.sample = spy
        trainer.make_experience(prompts, jax.random.key(0))
    finally:
        rl_trainer_mod.generate.sample = orig
    assert seen, "rollout never sampled"
    for got, have in zip(
        jax.tree.leaves(seen[0]), jax.tree.leaves(eng.params["actor"])
    ):
        assert got is have


# ---------------------------------------------------------------------------
# GRPO (rl/grpo.py) — exceeds the reference: atorch/rl is PPO-only
# ---------------------------------------------------------------------------


def test_group_advantages_whiten_within_groups():
    from dlrover_tpu.rl import grpo

    scores = jnp.array([1.0, 2.0, 3.0, 4.0, 10.0, 10.0, 10.0, 10.0])
    adv = grpo.group_advantages(scores, group_size=4)
    # group 1: zero-mean, ordered like the raw scores
    g1 = np.asarray(adv[:4])
    assert abs(g1.mean()) < 1e-5
    assert np.all(np.diff(g1) > 0)
    # group 2: zero variance → zero advantage (no preference signal)
    assert np.allclose(np.asarray(adv[4:]), 0.0, atol=1e-5)
    with pytest.raises(ValueError, match="divisible"):
        grpo.group_advantages(scores[:6], group_size=4)


def test_kl_k3_nonnegative_and_zero_at_match():
    from dlrover_tpu.rl import grpo

    lp = jnp.log(jnp.array([[0.5, 0.25, 0.125]]))
    mask = jnp.ones_like(lp)
    assert float(grpo.kl_k3(lp, lp, mask)) == pytest.approx(0.0, abs=1e-7)
    drift = lp + jnp.array([[0.3, -0.2, 0.1]])
    assert float(grpo.kl_k3(drift, lp, mask)) > 0.0


@pytest.mark.slow
def test_grpo_increases_rewarded_token_probability():
    """Same toy task as the PPO test, critic-free: reward = fraction of
    response tokens equal to TARGET; the group baseline alone must be
    enough signal for the actor to shift probability mass."""
    from dlrover_tpu.rl import GRPOConfig, GRPOTrainer

    TARGET = 7
    cfg = _cfg(vocab_size=16, n_layer=1, d_model=32)
    eng = ModelEngine(cfg, learning_rate=2e-2, rng=jax.random.key(2))

    def reward_fn(tokens, mask):
        resp = tokens[:, 1:] == TARGET
        return (resp * mask).sum(-1) / np.maximum(mask.sum(-1), 1.0)

    gcfg = GRPOConfig(
        group_size=4,
        max_new_tokens=8,
        kl_coef=0.0,
        epochs=2,
        temperature=1.0,
    )
    trainer = GRPOTrainer(eng, gcfg, reward_fn=reward_fn)
    prompts = jnp.ones((8, 2), jnp.int32)  # ×4 completions = 32 rollouts

    def target_prob(params):
        logits = eng.actor_logits(params, prompts)
        return float(jax.nn.softmax(logits[:, -1, :], -1)[:, TARGET].mean())

    critic_before = jax.tree.leaves(eng.params["critic"])[0].copy()
    p0 = target_prob(eng.params["actor"])
    scores = []
    for i in range(12):
        stats = trainer.step(prompts, jax.random.key(200 + i))
        scores.append(stats["score_mean"])
    p1 = target_prob(eng.params["actor"])
    assert p1 > p0 * 1.5, (p0, p1, scores)
    assert np.mean(scores[-3:]) > np.mean(scores[:3]), scores
    # critic-free: the critic's weights were never touched
    critic_after = jax.tree.leaves(eng.params["critic"])[0]
    np.testing.assert_array_equal(
        np.asarray(critic_before), np.asarray(critic_after)
    )


def test_grpo_config_validation():
    from dlrover_tpu.rl import GRPOConfig

    with pytest.raises(ValueError, match="group_size"):
        GRPOConfig(group_size=1)
    with pytest.raises(ValueError, match="temperature"):
        GRPOConfig(temperature=0.0)


# ---------------------------------------------------------------------------
# DPO (rl/dpo.py) — exceeds the reference: no offline-preference path
# ---------------------------------------------------------------------------


def test_dpo_loss_prefers_chosen():
    from dlrover_tpu.rl import dpo

    # policy already prefers chosen more than the reference does →
    # positive margin, loss below log(2); flipped pair → above log(2)
    loss_good, stats = dpo.dpo_loss(
        jnp.array([-1.0]), jnp.array([-3.0]),
        jnp.array([-2.0]), jnp.array([-2.0]), beta=1.0,
    )
    loss_bad, _ = dpo.dpo_loss(
        jnp.array([-3.0]), jnp.array([-1.0]),
        jnp.array([-2.0]), jnp.array([-2.0]), beta=1.0,
    )
    assert float(loss_good) < np.log(2.0) < float(loss_bad)
    assert float(stats["reward_accuracy"]) == 1.0
    assert float(stats["reward_margin"]) > 0


@pytest.mark.slow  # tier-1 budget: engine/logit pins keep fast rl coverage
def test_dpo_trainer_shifts_preference():
    """Offline preference pairs: chosen responses are TARGET tokens,
    rejected are OTHER. After DPO steps the actor must assign TARGET a
    higher probability than OTHER (it starts near-uniform), and the
    implicit-reward accuracy must reach 1."""
    from dlrover_tpu.rl import DPOTrainer
    from dlrover_tpu.rl.trainer import _response_mask

    TARGET, OTHER, P, R = 7, 3, 2, 6
    cfg = _cfg(vocab_size=16, n_layer=1, d_model=32)
    eng = ModelEngine(cfg, learning_rate=1e-2, rng=jax.random.key(3))
    trainer = DPOTrainer(eng, beta=0.5)

    b = 16
    prompt = jnp.ones((b, P), jnp.int32)
    chosen = jnp.concatenate(
        [prompt, jnp.full((b, R), TARGET, jnp.int32)], axis=1
    )
    rejected = jnp.concatenate(
        [prompt, jnp.full((b, R), OTHER, jnp.int32)], axis=1
    )
    mask = _response_mask(b, P, P + R)
    batch = {
        "chosen": chosen,
        "rejected": rejected,
        "chosen_mask": mask,
        "rejected_mask": mask,
    }

    def prob(tok):
        logits = eng.actor_logits(eng.params["actor"], prompt)
        return float(
            jax.nn.softmax(logits[:, -1, :], -1)[:, tok].mean()
        )

    p_t0, p_o0 = prob(TARGET), prob(OTHER)
    prepared = trainer.prepare(batch)  # ref logprobs computed ONCE
    stats = {}
    for _ in range(20):
        stats = trainer.step(prepared)
    assert stats["reward_accuracy"] == 1.0
    assert stats["reward_margin"] > 0
    p_t1, p_o1 = prob(TARGET), prob(OTHER)
    assert p_t1 > p_o1, (p_t1, p_o1)
    assert p_t1 > p_t0 and p_o1 < p_o0, (p_t0, p_t1, p_o0, p_o1)
    with pytest.raises(ValueError, match="beta"):
        DPOTrainer(eng, beta=0.0)
