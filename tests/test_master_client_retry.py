"""RPC retry backoff: bounded exponential with jitter, flaky-server
recovery (satellite of the live-resharding PR — a master hiccup during a
rendezvous round must surface as a delayed success, not a failure)."""

import threading
import time

import grpc
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common import messages as msgs
from dlrover_tpu.common.comm import (
    MasterTransportClient,
    MasterTransportServer,
    find_free_port,
)


class _FakeRpcError(grpc.RpcError):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


def test_backoff_delay_bounded_and_growing():
    for attempt in range(12):
        raw = min(comm._BACKOFF_CAP_S, comm._BACKOFF_BASE_S * 2**attempt)
        for _ in range(20):
            d = comm._backoff_delay(attempt)
            assert 0.5 * raw <= d <= raw
            assert d <= comm._BACKOFF_CAP_S
    # jitter: repeated draws are not all identical
    draws = {comm._backoff_delay(3) for _ in range(20)}
    assert len(draws) > 1


def test_call_retries_unavailable_with_backoff(monkeypatch):
    delays = []
    monkeypatch.setattr(
        comm, "_backoff_delay", lambda a: delays.append(a) or 0.0
    )
    client = MasterTransportClient("localhost:1", retries=5)
    calls = {"n": 0}

    def flaky(payload, timeout):
        calls["n"] += 1
        if calls["n"] <= 3:
            raise _FakeRpcError(grpc.StatusCode.UNAVAILABLE)
        return payload

    assert client._call(flaky, b"ping") == b"ping"
    assert calls["n"] == 4
    assert delays == [0, 1, 2]  # attempt index fed to the backoff


def test_call_gives_up_after_retry_budget(monkeypatch):
    monkeypatch.setattr(comm, "_backoff_delay", lambda a: 0.0)
    client = MasterTransportClient("localhost:1", retries=3)

    def always_down(payload, timeout):
        raise _FakeRpcError(grpc.StatusCode.UNAVAILABLE)

    with pytest.raises(grpc.RpcError):
        client._call(always_down, b"ping")


def test_call_non_retryable_raises_immediately(monkeypatch):
    monkeypatch.setattr(
        comm, "_backoff_delay", lambda a: pytest.fail("must not back off")
    )
    client = MasterTransportClient("localhost:1", retries=5)
    calls = {"n": 0}

    def denied(payload, timeout):
        calls["n"] += 1
        raise _FakeRpcError(grpc.StatusCode.PERMISSION_DENIED)

    with pytest.raises(grpc.RpcError):
        client._call(denied, b"ping")
    assert calls["n"] == 1


class _EchoServicer:
    def report(self, msg):
        return True

    def get(self, msg):
        return None


def test_flaky_server_call_survives_late_start(monkeypatch):
    """Nothing listens when the call starts; the server comes up ~0.5s
    later and the retried RPC succeeds instead of surfacing the outage."""
    monkeypatch.setattr(comm, "_BACKOFF_BASE_S", 0.1)
    port = find_free_port()
    holder = {}

    def start_late():
        time.sleep(0.5)
        server = MasterTransportServer(_EchoServicer(), port=port)
        server.start()
        holder["server"] = server

    t = threading.Thread(target=start_late, daemon=True)
    t.start()
    client = MasterTransportClient(
        f"localhost:{port}", timeout_s=5.0, retries=20
    )
    try:
        t0 = time.monotonic()
        assert client.report(msgs.HeartbeatReport(node_id=1))
        assert time.monotonic() - t0 >= 0.3  # it actually waited the outage out
    finally:
        t.join()
        client.close()
        holder["server"].stop()
