"""Shared process harness for the multi-node end-to-end tests.

Spawns the real distributed stack on one machine: a standalone master
process, launcher/agent process groups that rendezvous through it, and
worker processes forming a real jax.distributed cluster over CPU
(SURVEY.md §4's multi-node-without-a-cluster tier). Used by
test_multinode.py and test_slice_elasticity.py.
"""

import os
import queue as queue_mod
import re
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_env(run_id, extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",  # workers: 1 local CPU device each
            "DLROVER_TPU_RUN_ID": run_id,
            "DLROVER_TPU_HOST_ADDR": "localhost",
        }
    )
    if extra:
        env.update(extra)
    return env


def drain(proc):
    """Pump a process's merged stdout into a queue from a daemon thread:
    keeps the ~64KB pipe from backpressure-blocking the producer while
    the test waits on OTHER processes, and lets readers enforce real
    deadlines (a blocking readline would only re-check its deadline
    between lines)."""
    q = queue_mod.Queue()

    def run():
        for line in proc.stdout:
            q.put(line)
        q.put(None)

    threading.Thread(target=run, daemon=True).start()
    return q


def kill_tree(proc):
    """SIGKILL a launched agent AND its worker children (they share the
    process group because we launch with start_new_session=True).

    Safe to call even after the leader was reaped: Linux keeps the pid
    number reserved while it is still the pgid of any live member, so
    killpg either hits OUR group (reaping a crashed leader's orphaned
    workers — the case this exists for) or raises ProcessLookupError
    once the whole group is gone."""
    if proc is None:
        return
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        if proc.poll() is None:
            proc.kill()


def drain_now(q, lines):
    """Pull whatever is already queued, non-blocking (for diagnostics)."""
    while True:
        try:
            line = q.get_nowait()
        except queue_mod.Empty:
            return
        if line is None:
            return
        lines.append(line)


def collect(q, lines, until, deadline, on_line=None):
    """Consume queued lines until ``until(line)`` or EOF/deadline.
    Returns the matching line or None."""
    while time.time() < deadline:
        try:
            line = q.get(timeout=0.2)
        except queue_mod.Empty:
            continue
        if line is None:
            return None
        lines.append(line)
        if on_line:
            on_line(line)
        if until(line):
            return line
    return None


def start_master(run_id, argv_extra=(), env_extra=None):
    """Spawn dlrover_tpu.master.main, return (proc, queue, lines, addr)."""
    master = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--port",
            "0",
            *argv_extra,
        ],
        cwd=REPO,
        env=make_env(run_id, env_extra),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    q = drain(master)
    lines = []
    addr_line = collect(
        q,
        lines,
        until=lambda l: l.startswith("DLROVER_TPU_MASTER_ADDR="),
        deadline=time.time() + 60,
    )
    assert addr_line, "master did not print its address"
    addr = re.match(
        r"DLROVER_TPU_MASTER_ADDR=(.+)", addr_line.strip()
    ).group(1)
    return master, q, lines, addr


def launch_agent(run_id, node_id, addr, train_args, agent_args=(),
                 nnodes="1:2", script="examples/train_gpt_elastic.py",
                 env_extra=None):
    """Spawn a launcher+worker process group for one node."""
    env = {"DLROVER_TPU_COORDINATOR_PORT": "0"}
    if env_extra:
        env.update(env_extra)
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.agent.launcher",
            "--nnodes",
            nnodes,
            "--node-id",
            str(node_id),
            "--nproc",
            "1",
            *agent_args,
            "--master-addr",
            addr,
            "--",
            sys.executable,
            script,
            *train_args,
        ],
        cwd=REPO,
        env=make_env(f"{run_id}_n{node_id}", env),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )
