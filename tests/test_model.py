"""Model + sharded train step tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import decoder, get_config
from dlrover_tpu.parallel import MeshConfig, build_mesh
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.train import (
    TrainStepBuilder,
    batch_sharding,
    init_train_state,
    make_optimizer,
)


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))


def _batch(rng, b=8, s=32, vocab=1000):
    tokens = jax.random.randint(rng, (b, s), 0, vocab)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


def test_forward_shapes():
    cfg = get_config("tiny")
    params = decoder.init(jax.random.key(0), cfg)
    logits = decoder.forward(
        params, jnp.zeros((2, 16), jnp.int32), cfg
    )
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


@pytest.mark.slow  # tier-1 budget: three full model inits (~58s); the
# tree-structure property is exercised fast by every sharded HLO test
# that consumes logical_axes
def test_logical_axes_match_params():
    for name in ("tiny", "gpt2-124m", "tiny-moe"):
        cfg = get_config(name, n_layer=2)
        params = decoder.init(jax.random.key(0), cfg)
        axes = decoder.logical_axes(cfg)
        ps = jax.tree.structure(params)
        ax = jax.tree.structure(
            axes, is_leaf=lambda x: x is None or isinstance(x, tuple)
        )
        assert ps == ax, f"{name}: param/axes tree mismatch"
        # every axes tuple has the same rank as its param
        flat_p = jax.tree.leaves(params)
        flat_a = jax.tree.leaves(
            axes, is_leaf=lambda x: x is None or isinstance(x, tuple)
        )
        for p, a in zip(flat_p, flat_a):
            if a is not None:
                assert len(a) == p.ndim


@pytest.mark.slow  # tier-1 budget: sharded paths pinned fast by HLO tests
def test_sharded_init_and_step(mesh):
    cfg = get_config("tiny")
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2, decay_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    # embedding is sharded: vocab over tp, embed over fsdp
    emb = state["params"]["embed"]["tokens"]
    assert "tp" in str(emb.sharding.spec) or "fsdp" in str(emb.sharding.spec)

    step = TrainStepBuilder(cfg, mesh, opt).build()
    batch = jax.device_put(_batch(jax.random.key(1)), batch_sharding(mesh))
    state, metrics = step(state, batch)
    l1 = float(metrics["loss"])
    for _ in range(3):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < l1, "loss should fall on a repeated batch"
    assert int(state["step"]) == 4


@pytest.mark.slow
def test_offload_attn_remat_matches_no_remat():
    """remat='offload_attn' (selective activation offload to pinned
    host) must not change gradients."""
    cfg0 = get_config("tiny", dtype="float32")
    cfgo = get_config("tiny", dtype="float32", remat="offload_attn")
    params = decoder.init(jax.random.key(0), cfg0)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, 1000)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    g0 = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg0)[0])(params)
    go = jax.grad(lambda p: decoder.loss_fn(p, batch, cfgo)[0])(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(go)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


@pytest.mark.slow  # tier-1 budget: double value_and_grad compile (~35s);
# the offload path keeps fast coverage via the HLO transfer sentinels
def test_save_qkv_offload_matches_save_qkv():
    """remat='save_qkv_offload' pins the SAME residual set as save_qkv —
    only the residency differs — so on CPU (where Host space aliases
    device memory) loss and grads must be bitwise identical."""
    cfgs = get_config("tiny", dtype="float32", remat="save_qkv")
    cfgo = get_config("tiny", dtype="float32", remat="save_qkv_offload")
    params = decoder.init(jax.random.key(0), cfgs)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, 1000)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    ls, gs = jax.value_and_grad(
        lambda p: decoder.loss_fn(p, batch, cfgs)[0]
    )(params)
    lo, go = jax.value_and_grad(
        lambda p: decoder.loss_fn(p, batch, cfgo)[0]
    )(params)
    assert float(ls) == float(lo)
    for a, b in zip(jax.tree.leaves(gs), jax.tree.leaves(go)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_remat_dtype_cast_close_to_full_precision():
    """remat_dtype='bfloat16' narrows only the SAVED residuals; grads
    stay close to the uncast policy (storage round-trip noise only)."""
    cfgs = get_config("tiny", dtype="float32", remat="save_qkv")
    cfgc = get_config(
        "tiny", dtype="float32", remat="save_qkv",
        remat_dtype="bfloat16",
    )
    params = decoder.init(jax.random.key(0), cfgs)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, 1000)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    ls, gs = jax.value_and_grad(
        lambda p: decoder.loss_fn(p, batch, cfgs)[0]
    )(params)
    lc, gc = jax.value_and_grad(
        lambda p: decoder.loss_fn(p, batch, cfgc)[0]
    )(params)
    assert abs(float(ls) - float(lc)) < 5e-2
    for a, b in zip(jax.tree.leaves(gs), jax.tree.leaves(gc)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-2
        )


@pytest.mark.slow
def test_offloaded_opt_state_matches_resident(mesh):
    """Host-offloaded moments (CPU-offload-Adam parity): same numerics
    as HBM-resident state, and the moments actually live in pinned_host."""
    cfg = get_config("tiny")
    opt = make_optimizer(
        learning_rate=1e-3, warmup_steps=2, decay_steps=10
    )
    batch = jax.device_put(_batch(jax.random.key(1)), batch_sharding(mesh))

    state_res = init_train_state(jax.random.key(0), cfg, mesh, opt)
    state_off = init_train_state(
        jax.random.key(0), cfg, mesh, opt, offload_opt_state=True
    )
    if jax.default_backend() != "cpu":  # CPU: offload is a no-op
        kinds = {
            leaf.sharding.memory_kind
            for leaf in jax.tree.leaves(state_off["opt_state"])
            if hasattr(leaf, "sharding")
        }
        assert "pinned_host" in kinds, kinds

    s_res = TrainStepBuilder(cfg, mesh, opt).build()
    s_off = TrainStepBuilder(
        cfg, mesh, opt, offload_opt_state=True
    ).build()
    for _ in range(3):
        state_res, m_res = s_res(state_res, batch)
        state_off, m_off = s_off(state_off, batch)
    np.testing.assert_allclose(
        float(m_res["loss"]), float(m_off["loss"]), rtol=1e-5
    )
    pr = jax.tree.leaves(state_res["params"])[0]
    po = jax.tree.leaves(state_off["params"])[0]
    np.testing.assert_allclose(
        np.asarray(pr), np.asarray(po), rtol=1e-5, atol=1e-6
    )


def test_offload_opt_strategy_method():
    from dlrover_tpu.accelerate.strategy import apply_strategy

    plan = apply_strategy([("fsdp", {}), ("offload_opt", {})])
    assert plan.offload_opt_state is True
    # plan survives the JSON round trip
    from dlrover_tpu.accelerate.strategy import AccelerationPlan

    assert AccelerationPlan.from_json(plan.to_json()).offload_opt_state


@pytest.mark.slow
def test_grad_accum_matches_full_batch(mesh):
    cfg = get_config("tiny")
    opt = make_optimizer(
        learning_rate=1e-3, grad_clip=0, schedule="const", name="sgd"
    )
    batch = _batch(jax.random.key(2), b=8)
    state1 = init_train_state(jax.random.key(0), cfg, mesh, opt)
    state2 = jax.tree.map(jnp.copy, state1)

    s_full = TrainStepBuilder(cfg, mesh, opt, grad_accum=1).build()
    s_acc = TrainStepBuilder(cfg, mesh, opt, grad_accum=4).build()
    out1, _ = s_full(state1, batch)
    out2, _ = s_acc(state2, batch)
    p1 = jax.tree.leaves(out1["params"])[0]
    p2 = jax.tree.leaves(out2["params"])[0]
    # leaf 0 is the embedding table: its grad is a scatter-add of bf16
    # cotangents, and accum=4 vs accum=1 sums them in different orders.
    # The resulting param diff is O(lr · bf16 ulp · counts) ≈ 7e-5 and
    # shifts with XLA's CPU reduction partitioning (thread count), so
    # atol must sit above it; a broken accumulator (wrong scaling,
    # dropped microbatch) is off by O(lr · grad) ≈ 1e-3, far past this.
    np.testing.assert_allclose(
        np.asarray(p1), np.asarray(p2), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow  # tier-1 budget: sharded MoE forward compile
# (~18s); MoE numerics are pinned fast throughout test_moe.py
def test_moe_forward(mesh):
    cfg = get_config("tiny-moe")
    params = decoder.init(jax.random.key(0), cfg)
    logits = decoder.forward(
        params, jnp.zeros((8, 16), jnp.int32), cfg, mesh=mesh
    )
    assert logits.shape == (8, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow  # tier-1 budget: double grad compile (~22s); remat
# parity siblings (offload, dtype-cast) already run on the slow tier
def test_remat_matches_no_remat():
    cfg = get_config("tiny")
    cfg_r = get_config("tiny", remat="full")
    params = decoder.init(jax.random.key(0), cfg)
    toks = jnp.zeros((2, 16), jnp.int32)
    batch = {"tokens": toks, "targets": toks}

    g1 = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg)[0])(params)
    g2 = jax.grad(lambda p: decoder.loss_fn(p, batch, cfg_r)[0])(params)
    a = jax.tree.leaves(g1)[0]
    b = jax.tree.leaves(g2)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


@pytest.mark.slow
def test_streamed_offload_adamw_matches_resident(mesh):
    """Per-leaf streamed host-offload (VERDICT r2 #8): same numerics as
    plain AdamW, no whole-tree device_put — the builder-level offload
    flag stays OFF and the optimizer owns placement."""
    cfg = get_config("tiny")
    opt_res = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                             decay_steps=10)
    opt_str = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                             decay_steps=10, offload_states=True)
    batch = jax.device_put(_batch(jax.random.key(1)), batch_sharding(mesh))

    state_res = init_train_state(jax.random.key(0), cfg, mesh, opt_res)
    state_str = init_train_state(
        jax.random.key(0), cfg, mesh, opt_str, offload_opt_state=True
    )
    s_res = TrainStepBuilder(cfg, mesh, opt_res).build()
    s_str = TrainStepBuilder(cfg, mesh, opt_str).build()
    for _ in range(3):
        state_res, m_res = s_res(state_res, batch)
        state_str, m_str = s_str(state_str, batch)
    # tolerance: the streamed path recomputes the bias-correction
    # powers/f32 chain in a different op order than optax's fused one
    np.testing.assert_allclose(
        float(m_res["loss"]), float(m_str["loss"]), rtol=1e-4
    )
    pr = jax.tree.leaves(state_res["params"])[0]
    ps = jax.tree.leaves(state_str["params"])[0]
    np.testing.assert_allclose(
        np.asarray(pr), np.asarray(ps), rtol=5e-4, atol=1e-6
    )


@pytest.mark.slow
def test_streamed_offload_serializes_leaf_transfers(mesh):
    """Structural proof of the working-set bound: the compiled step's
    HLO chains every moment leaf through opt-barriers, so leaf i+1's
    transfer depends on leaf i's update (XLA cannot batch them)."""
    cfg = get_config("tiny")
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=10, offload_states=True)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    builder = TrainStepBuilder(cfg, mesh, opt)
    batch = jax.device_put(_batch(jax.random.key(1)), batch_sharding(mesh))
    import jax as _jax

    lowered = _jax.jit(builder.step_fn, donate_argnums=(0,)).lower(
        state, batch
    )
    txt = lowered.as_text()  # StableHLO
    n_leaves = len(_jax.tree.leaves(state["params"]))
    n_barriers = txt.count("optimization_barrier")
    assert n_barriers >= n_leaves, (n_barriers, n_leaves)


def test_analyser_offload_bound_is_leaf_sized():
    """analyse() budgets offloaded moments at the largest-leaf bound,
    not a fraction of the tree (closes the 0.5x assumption)."""
    from dlrover_tpu.accelerate.analyser import analyse
    from dlrover_tpu.accelerate.strategy import apply_strategy

    cfg = get_config("gpt2-1.5b")
    axes = {"dp": 1, "fsdp": 8, "tp": 1, "sp": 1, "pp": 1}
    plan_res = apply_strategy([("mixed_parallel", axes)])
    plan_off = apply_strategy(
        [("mixed_parallel", axes), ("offload_opt", {})]
    )
    res = analyse(cfg, plan_res, n_devices=8, batch_per_chip=1, seq=128)
    off = analyse(cfg, plan_off, n_devices=8, batch_per_chip=1, seq=128)
    assert off.opt_bytes_per_chip < res.opt_bytes_per_chip
    # bound = slack * slots * 4B * max(embed, stacked-mlp leaf) / shards
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    max_leaf = max(v * d, cfg.n_layer * d * f)
    assert off.opt_bytes_per_chip == pytest.approx(
        2.0 * 2 * 4 * max_leaf / 8
    )


@pytest.mark.slow
def test_multi_slice_hybrid_mesh_trains():
    """num_slices>1 (the DCN layout: dp split across slices, model axes
    inside each slice) must build and train off multi-slice hardware —
    virtual CPU devices carry no slice_index attribute, so build_mesh
    falls back to contiguous-block slice emulation; the axis SHAPES and
    the collectives they imply are identical to the real hybrid mesh."""
    mesh2 = build_mesh(MeshConfig(dp=4, tp=2, num_slices=2))
    assert mesh2.shape["dp"] == 4 and mesh2.shape["tp"] == 2
    cfg = get_config("tiny")
    opt = make_optimizer(learning_rate=1e-3, warmup_steps=2,
                         decay_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh2, opt)
    step = TrainStepBuilder(cfg, mesh2, opt).build()
    toks = jnp.zeros((8, 32), jnp.int32)
    batch = jax.device_put(
        {"tokens": toks, "targets": toks}, batch_sharding(mesh2)
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # dp must split evenly across slices
    with pytest.raises(ValueError, match="divisible by"):
        build_mesh(MeshConfig(dp=2, tp=4, num_slices=3))


# ---------------------------------------------------------------------------
# pins for the non-matmul rewrites: the strided-reshape rope and the
# single-pass layernorm replaced older formulations in-place, so the old
# formulas live on here as the reference the new code is held to.


def _old_rope(x, positions, theta):
    """The split+concatenate rotate-half this repo shipped before the
    strided-reshape rewrite — kept verbatim as the bitwise reference."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
def test_rope_strided_rewrite_bitwise(dt):
    """The [..., 2, D/2] reshape pairs lane i with i+D/2 exactly like
    split(2, -1), and stack+reshape reproduces the concatenate layout —
    same f32 elementwise ops in the same order, so the rewrite must be
    BITWISE identical, not merely close."""
    b, s, h, d = 2, 16, 4, 64
    x = jax.random.normal(jax.random.key(0), (b, s, h, d)).astype(dt)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    theta = 10000.0
    rope = decoder._rope_tables(positions, d, theta)
    new = decoder._rope(x, rope)
    old = _old_rope(x, positions, theta)
    np.testing.assert_array_equal(np.asarray(new), np.asarray(old))
    # and with non-trivial positions (decode-style offsets)
    positions = positions + 37
    rope = decoder._rope_tables(positions, d, theta)
    np.testing.assert_array_equal(
        np.asarray(decoder._rope(x, rope)),
        np.asarray(_old_rope(x, positions, theta)),
    )


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
def test_layernorm_single_pass_matches_two_pass(dt):
    """_norm's layernorm now computes var = E[x²] − E[x]² in the same
    f32 sweep as the mean (one read of the activation instead of two).
    Against the old mean-then-jnp.var formulation this is a reduction
    reassociation, not a semantics change: equal to f32 tolerance on
    activation scales well past anything the models produce."""
    d = 256
    x = (
        jax.random.normal(jax.random.key(1), (4, 32, d)) * 30.0
    ).astype(dt)
    scale = 1.0 + 0.1 * jax.random.normal(jax.random.key(2), (d,))
    bias = 0.1 * jax.random.normal(jax.random.key(3), (d,))

    def two_pass(x, scale, bias):
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
        out = out * scale.astype(jnp.float32) + bias.astype(jnp.float32)
        return out.astype(x.dtype)

    new = decoder._norm(x, scale, bias, "layernorm")
    old = two_pass(x, scale, bias)
    np.testing.assert_allclose(
        np.asarray(new, np.float32),
        np.asarray(old, np.float32),
        rtol=2e-5 if dt == jnp.float32 else 2e-2,
        atol=2e-5 if dt == jnp.float32 else 2e-2,
    )


def test_layernorm_model_forward_matches_two_pass_family():
    """Model-level version of the layernorm pin: a layernorm-family
    config (neox: layernorm + parallel residual) forward under the
    current _norm agrees with a forward that routes every norm through
    the old two-pass formula, to f32 tolerance."""
    cfg = get_config("tiny-neox", dtype="float32", param_dtype="float32")
    assert cfg.norm == "layernorm"
    params = decoder.init(jax.random.key(0), cfg)
    batch = _batch(jax.random.key(1), b=2, s=16, vocab=cfg.vocab_size)

    loss_new = float(decoder.loss_fn(params, batch, cfg)[0])

    orig = decoder._norm

    def two_pass_norm(x, scale, bias, kind):
        if kind != "layernorm":
            return orig(x, scale, bias, kind)
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
        out = out * scale.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
        return out.astype(x.dtype)

    decoder._norm = two_pass_norm
    try:
        loss_old = float(decoder.loss_fn(params, batch, cfg)[0])
    finally:
        decoder._norm = orig
    np.testing.assert_allclose(loss_new, loss_old, rtol=1e-5)
